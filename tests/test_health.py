"""Resource sentinels and the leak trend detector
(distpow_tpu/runtime/health.py, ISSUE 18): probe registration is
policed against KNOWN_GAUGES, sampling sets the declared gauges, and
the least-squares detector flags a planted linear climb while staying
quiet on noisy-but-flat and oscillating trajectories."""

from __future__ import annotations

import random
import threading

import pytest

from distpow_tpu.obs.timeseries import TimeSeriesStore, Tier
from distpow_tpu.runtime.health import (
    SENTINELS,
    LeakSentinel,
    ResourceSentinels,
    least_squares_slope,
    open_fds,
    rss_bytes,
)
from distpow_tpu.runtime.metrics import KNOWN_GAUGES, REGISTRY as metrics
from distpow_tpu.runtime.telemetry import RECORDER

T0 = 1_000_000.0


def gauge_store(name, values, dt=1.0):
    store = TimeSeriesStore(tiers=(Tier(0.0, 1e9),))
    for i, v in enumerate(values):
        store.append({"ts": T0 + i * dt, "nodes": 1, "counters": {},
                      "gauges": {name: float(v)}, "per_node": {},
                      "per_model": {}, "stale_nodes": []})
    return store


# -- sentinel probes ---------------------------------------------------------

def test_process_probes_return_positive_on_linux():
    assert rss_bytes() and rss_bytes() > 0
    assert open_fds() and open_fds() > 0


def test_sample_sets_every_supported_declared_gauge():
    out = SENTINELS.sample()
    for name in ("proc.rss_bytes", "proc.open_fds", "proc.threads",
                 "ring.spans_depth", "ring.flightrec_depth"):
        assert name in out, f"probe {name} did not sample"
        assert name in KNOWN_GAUGES
    assert out["proc.threads"] >= 1.0
    snap = metrics.snapshot()
    assert snap["gauges"]["proc.rss_bytes"] == out["proc.rss_bytes"]


def test_register_probe_rejects_undeclared_gauge():
    s = ResourceSentinels()
    with pytest.raises(ValueError, match="KNOWN_GAUGES"):
        s.register_probe("proc.typo_bytes", lambda: 1.0)


def test_failing_probe_skips_its_gauge_not_the_sample():
    s = ResourceSentinels()

    def boom():
        raise RuntimeError("probe exploded")

    s.register_probe("ring.repl_queue_depth", boom)
    out = s.sample()
    assert "ring.repl_queue_depth" not in out
    assert "proc.threads" in out


# -- least-squares slope -----------------------------------------------------

def test_slope_exact_on_a_line():
    series = [(T0 + i, 3.0 + 2.5 * i) for i in range(10)]
    assert least_squares_slope(series) == pytest.approx(2.5)


def test_slope_none_on_degenerate_series():
    assert least_squares_slope([]) is None
    assert least_squares_slope([(T0, 1.0)]) is None
    assert least_squares_slope([(T0, 1.0), (T0, 9.0)]) is None


# -- trend detector ----------------------------------------------------------

def test_planted_linear_leak_is_flagged():
    sentinel = LeakSentinel(window_s=1e9, min_points=6, noise_floor=2.0)
    series = [(T0 + i, 10.0 + 0.5 * i) for i in range(30)]  # +14.5 total
    suspect = sentinel.judge_series("proc.threads", series)
    assert suspect is not None
    assert suspect.gauge == "proc.threads"
    assert suspect.slope_per_s == pytest.approx(0.5)
    assert suspect.rise == pytest.approx(14.5)
    assert suspect.points == 30


def test_noisy_but_flat_gauge_stays_quiet():
    rng = random.Random(1810)
    sentinel = LeakSentinel(window_s=1e9, min_points=6, noise_floor=2.0)
    series = [(T0 + i, 40.0 + rng.uniform(-3.0, 3.0)) for i in range(60)]
    assert sentinel.judge_series("proc.threads", series) is None


def test_oscillation_with_rising_endpoints_stays_quiet():
    # a sawtooth whose fitted line technically climbs: the monotone-step
    # fraction gate keeps it quiet
    series = [(T0 + i, 20.0 + (6.0 if i % 2 else 0.0) + 0.05 * i)
              for i in range(40)]
    sentinel = LeakSentinel(window_s=1e9, min_points=6, noise_floor=1.0,
                            min_monotone_frac=0.7)
    assert sentinel.judge_series("proc.threads", series) is None


def test_min_points_and_noise_floor_gates():
    sentinel = LeakSentinel(window_s=1e9, min_points=6, noise_floor=10.0)
    short = [(T0 + i, i * 5.0) for i in range(5)]
    assert sentinel.judge_series("proc.threads", short) is None
    shallow = [(T0 + i, 10.0 + 0.1 * i) for i in range(30)]  # rise 2.9
    assert sentinel.judge_series("proc.threads", shallow) is None


def test_check_sweeps_store_with_side_effects_and_dedup():
    store = gauge_store("proc.threads", [12.0 + 1.5 * i for i in range(20)])
    sentinel = LeakSentinel(window_s=1e9, min_points=6, noise_floor=2.0)
    before = metrics.snapshot()["counters"].get("health.leak_suspects", 0)

    suspects = sentinel.check(store)
    assert [s.gauge for s in suspects] == ["proc.threads"]
    after = metrics.snapshot()["counters"].get("health.leak_suspects", 0)
    assert after == before + 1
    events = [e for e in RECORDER.recent()
              if e["kind"] == "health.leak_suspect"
              and e["gauge"] == "proc.threads"]
    assert events and events[-1]["points"] == 20

    # a leak stays leaking: the suspect is re-reported, the counter and
    # flight-recorder event are not re-fired for the same gauge
    again = sentinel.check(store)
    assert [s.gauge for s in again] == ["proc.threads"]
    assert metrics.snapshot()["counters"]["health.leak_suspects"] == after


def test_check_respects_per_gauge_noise_floors():
    store = gauge_store("proc.open_fds", [50.0 + i for i in range(20)])
    sentinel = LeakSentinel(window_s=1e9, min_points=6, noise_floor=2.0)
    quiet = sentinel.check(store, gauges=["proc.open_fds"],
                           noise_floors={"proc.open_fds": 1000.0})
    assert quiet == []
    loud = sentinel.check(store, gauges=["proc.open_fds"],
                          noise_floors={"proc.open_fds": 5.0})
    assert [s.gauge for s in loud] == ["proc.open_fds"]
    # the floor override must not stick to the sentinel
    assert sentinel.noise_floor == 2.0


def test_check_defaults_to_proc_and_ring_gauges_in_store():
    store = gauge_store("worker.forward_queue_depth",
                        [float(i * 10) for i in range(20)])
    sentinel = LeakSentinel(window_s=1e9, min_points=6, noise_floor=1.0)
    # not proc.* / ring.*: the default sweep ignores it
    assert sentinel.check(store) == []
    assert [s.gauge for s in
            sentinel.check(store, gauges=["worker.forward_queue_depth"])
            ] == ["worker.forward_queue_depth"]


def test_thread_probe_tracks_a_real_thread():
    stop = threading.Event()
    base = SENTINELS.sample()["proc.threads"]
    t = threading.Thread(target=stop.wait, daemon=True)
    t.start()
    try:
        assert SENTINELS.sample()["proc.threads"] >= base + 1
    finally:
        stop.set()
        t.join()
