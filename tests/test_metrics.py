"""Metrics subsystem tests: counters land during a real protocol run and
the Stats RPC / CLI expose them (capability absent in the reference,
SURVEY.md section 5); ISSUE 3 adds the histogram plane — log-bucketed
latency distributions, Stats round-trip preservation, and the
Prometheus text exposition."""

import re
import sys
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent))

from test_nodes import Stack, mine_and_wait  # noqa: E402

from distpow_tpu.cli.stats import (  # noqa: E402
    fetch_stats,
    render_prometheus,
)
from distpow_tpu.runtime.metrics import (  # noqa: E402
    REGISTRY,
    Histogram,
    Metrics,
)


def test_metrics_registry_basics():
    m = Metrics()
    m.inc("a")
    m.inc("a", 5)
    m.gauge("g", 3.5)
    snap = m.snapshot()
    assert snap["counters"]["a"] == 6
    assert snap["gauges"]["g"] == 3.5
    assert snap["uptime_secs"] >= 0
    m.reset()
    assert m.snapshot()["counters"] == {}


# ---------------------------------------------------------------------------
# histograms (ISSUE 3 tentpole)
# ---------------------------------------------------------------------------

def test_histogram_bucket_edges():
    h = Histogram()
    for v in (0.0, 1e-6, 0.001, 1.0, 1.0, 2.0, 1000.0):
        h.observe(v)
    d = h.to_dict()
    assert d["count"] == 7
    assert d["min"] == 0.0 and d["max"] == 1000.0
    assert abs(d["sum"] - (1e-6 + 0.001 + 1.0 + 1.0 + 2.0 + 1000.0)) < 1e-9
    bounds = [b for b, _ in d["buckets"]]
    counts = [c for _, c in d["buckets"]]
    assert bounds == sorted(bounds), "bucket bounds must ascend"
    assert sum(counts) == d["count"]
    # the zero sample lands in the dedicated le=0 bucket
    assert bounds[0] == 0.0 and counts[0] == 1
    # every positive sample sits at or below its bucket's upper bound,
    # and each bound is within one log-step (~19%) above SOME sample:
    # 1.0 was observed twice — both land in the same bucket
    one_bucket = [c for b, c in d["buckets"] if b >= 1.0][0]
    assert one_bucket == 2


def test_histogram_percentile_estimates():
    h = Histogram()
    for v in range(1, 101):  # uniform 1..100
        h.observe(float(v))
    # log-bucket estimates err high by at most one bucket width (~19%)
    p50, p95, p99 = (h.percentile(q) for q in (0.50, 0.95, 0.99))
    assert 45 <= p50 <= 62, p50
    assert 88 <= p95 <= 100, p95
    assert 94 <= p99 <= 100, p99  # clamped to the observed max
    assert h.percentile(1.0) == 100.0
    assert Histogram().percentile(0.5) is None


def test_histogram_concurrent_observe():
    m = Metrics()
    per_thread, n_threads = 1000, 8

    def worker():
        for i in range(per_thread):
            m.observe("h", (i % 10) + 1)

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    d = m.get_histogram("h")
    assert d["count"] == per_thread * n_threads
    assert d["sum"] == n_threads * sum((i % 10) + 1 for i in range(per_thread))
    assert d["min"] == 1 and d["max"] == 10


def test_metrics_time_context_manager():
    m = Metrics()
    with m.time("op_s"):
        time.sleep(0.02)
    d = m.get_histogram("op_s")
    assert d["count"] == 1
    assert 0.01 <= d["sum"] <= 5.0


def test_histogram_snapshot_and_reset():
    m = Metrics()
    m.observe("h", 1.5)
    snap = m.snapshot()
    assert snap["histograms"]["h"]["count"] == 1
    # snapshot is a copy: later observes don't mutate it
    m.observe("h", 2.5)
    assert snap["histograms"]["h"]["count"] == 1
    m.reset()
    assert m.snapshot()["histograms"] == {}
    assert m.get_histogram("h") is None


PROM_SAMPLE_RX = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? -?[0-9.e+\-]+(inf)?$",
    re.IGNORECASE,
)


def assert_valid_prometheus(text: str) -> None:
    """Every non-comment line must be a well-formed sample; every
    histogram family must be cumulative and closed by +Inf == count."""
    families = {}
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("#"):
            m = re.match(r"^# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]*", line)
            assert m, f"malformed comment: {line!r}"
            continue
        assert PROM_SAMPLE_RX.match(line), f"malformed sample: {line!r}"
        name = line.split("{")[0].split(" ")[0]
        families.setdefault(name, []).append(line)
    for name, lines in families.items():
        if not name.endswith("_bucket"):
            continue
        base = name[: -len("_bucket")]
        cum = [float(l.rsplit(" ", 1)[1]) for l in lines]
        assert cum == sorted(cum), f"{name} buckets not cumulative"
        count = float(families[base + "_count"][0].rsplit(" ", 1)[1])
        assert cum[-1] == count, f"{name} +Inf != _count"


def test_render_prometheus_shape():
    m = Metrics()
    m.inc("coord.mine_rpcs", 3)
    m.gauge("worker.active_searches", 2)
    m.observe("coord.mine_s.miss", 0.25)
    m.observe("coord.mine_s.miss", 0.5)
    snap = m.snapshot()
    snap["role"] = "coordinator"
    text = render_prometheus(snap)
    assert_valid_prometheus(text)
    assert 'distpow_node_info{role="coordinator"} 1' in text
    assert "# TYPE distpow_coord_mine_rpcs_total counter" in text
    assert "distpow_coord_mine_rpcs_total 3" in text
    assert "# TYPE distpow_worker_active_searches gauge" in text
    assert "# TYPE distpow_coord_mine_s_miss histogram" in text
    assert "distpow_coord_mine_s_miss_count 2" in text
    assert 'distpow_coord_mine_s_miss_bucket{le="+Inf"} 2' in text


def test_stats_rpc_and_cli_after_protocol_run():
    before = REGISTRY.snapshot()["counters"]
    s = Stack(2)
    try:
        client = s.new_client("client1")
        mine_and_wait(client, b"\x71\x72", 2)
        mine_and_wait(client, b"\x71\x72", 2)  # second hits the cache

        coord_stats = fetch_stats(s.coord_client_addr, role="coordinator")
        assert coord_stats["role"] == "coordinator"
        assert coord_stats["failure_policy"] == "error"
        assert len(coord_stats["workers"]) == 2
        assert all(w["connected"] for w in coord_stats["workers"])
        c = coord_stats["counters"]

        def delta(name):
            return c.get(name, 0) - before.get(name, 0)

        assert delta("coord.mine_rpcs") >= 2
        assert delta("coord.fanouts") >= 1
        assert delta("cache.hit") >= 1
        assert delta("cache.add") >= 1
        assert delta("worker.mine_rpcs") >= 2   # in-process: shared registry
        assert delta("worker.results_sent") >= 4

        # the Stats RPC round-trips full histogram snapshots: one from
        # each node role of the request path (shared in-process
        # registry, so the coordinator snapshot carries all three)
        hists = coord_stats["histograms"]
        assert hists["coord.mine_s.miss"]["count"] >= 1
        assert hists["coord.mine_s.hit"]["count"] >= 1
        assert hists["coord.first_result_s"]["count"] >= 1
        assert hists["coord.cancel_propagation_s"]["count"] >= 1
        assert hists["worker.solve_s"]["count"] >= 1
        assert hists["powlib.mine_s"]["count"] >= 1
        assert hists["rpc.server.dispatch_s.CoordRPCHandler.Mine"][
            "count"] >= 2
        for h in hists.values():
            # JSON round-trip preserved the full estimator state
            assert set(h) >= {"count", "sum", "min", "max",
                              "p50", "p95", "p99", "buckets"}
            if h["count"]:
                assert h["p50"] is not None
                assert h["min"] <= h["p50"] <= h["max"]

        worker_stats = fetch_stats(s.workers[0].bound_addr, role="worker")
        assert worker_stats["role"] == "worker"
        assert worker_stats["backend"] == "PythonBackend"
        assert worker_stats["active_tasks"] == 0

        auto = fetch_stats(s.coord_client_addr, role="auto")
        assert auto["role"] == "coordinator"
    finally:
        s.close()


def test_all_backends_count_hashes():
    """search.hashes must move for every backend family (the jax paths
    via the driver, python via the oracle's progress hook)."""
    from distpow_tpu.backends import PythonBackend

    before = REGISTRY.get("search.hashes")
    found_before = REGISTRY.get("search.found")
    secret = PythonBackend().search(b"\x01\x02", 2, list(range(256)))
    assert secret is not None
    assert REGISTRY.get("search.hashes") > before
    assert REGISTRY.get("search.found") == found_before + 1


def test_cache_replay_does_not_count(tmp_path):
    from distpow_tpu.runtime.cache import ResultCache

    path = str(tmp_path / "c.jsonl")
    c = ResultCache(persist_path=path)
    for i in range(5):
        c.add(bytes([i]), 2, b"\x01", None)
    c.close()
    before = REGISTRY.get("cache.add")
    c2 = ResultCache(persist_path=path)  # replays 5 lines
    c2.close()
    assert REGISTRY.get("cache.add") == before


def test_stats_cli_main(capsys):
    s = Stack(1)
    try:
        from distpow_tpu.cli.stats import main

        assert main(["--addr", s.coord_client_addr]) == 0
        out = capsys.readouterr().out
        assert '"role": "coordinator"' in out
    finally:
        s.close()


def test_stats_cli_prom_exposition(capsys):
    """Acceptance gate (ISSUE 3): --prom emits valid Prometheus text
    exposition including at least one histogram from each node role of
    the request path (coordinator, worker, client/powlib)."""
    s = Stack(1)
    try:
        client = s.new_client("client1")
        mine_and_wait(client, b"\x73\x74", 2)
        from distpow_tpu.cli.stats import main

        assert main(["--addr", s.coord_client_addr, "--prom"]) == 0
        out = capsys.readouterr().out
        assert_valid_prometheus(out)
        for family in ("distpow_coord_mine_s_miss",      # coordinator
                       "distpow_worker_solve_s",          # worker
                       "distpow_powlib_mine_s"):          # client library
            assert f"# TYPE {family} histogram" in out, family
    finally:
        s.close()


def test_stats_cli_watch_delta(capsys):
    s = Stack(1)
    try:
        client = s.new_client("client1")
        mine_and_wait(client, b"\x75\x76", 2)
        from distpow_tpu.cli.stats import main

        assert main(["--addr", s.coord_client_addr,
                     "--watch", "0.05", "--count", "2"]) == 0
        out = capsys.readouterr().out
        assert out.count("--- coordinator @") == 2
        # first frame shows absolute counters as deltas from nothing;
        # the second (quiescent stack) shows no movement
        assert "coord.mine_rpcs" in out
        assert "(no counter movement)" in out
        assert "p50=" in out  # histogram quantiles ride along
    finally:
        s.close()
