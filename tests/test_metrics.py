"""Metrics subsystem tests: counters land during a real protocol run and
the Stats RPC / CLI expose them (capability absent in the reference,
SURVEY.md section 5)."""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent))

from test_nodes import Stack, mine_and_wait  # noqa: E402

from distpow_tpu.cli.stats import fetch_stats  # noqa: E402
from distpow_tpu.runtime.metrics import REGISTRY, Metrics  # noqa: E402


def test_metrics_registry_basics():
    m = Metrics()
    m.inc("a")
    m.inc("a", 5)
    m.gauge("g", 3.5)
    snap = m.snapshot()
    assert snap["counters"]["a"] == 6
    assert snap["gauges"]["g"] == 3.5
    assert snap["uptime_secs"] >= 0
    m.reset()
    assert m.snapshot()["counters"] == {}


def test_stats_rpc_and_cli_after_protocol_run():
    before = REGISTRY.snapshot()["counters"]
    s = Stack(2)
    try:
        client = s.new_client("client1")
        mine_and_wait(client, b"\x71\x72", 2)
        mine_and_wait(client, b"\x71\x72", 2)  # second hits the cache

        coord_stats = fetch_stats(s.coord_client_addr, role="coordinator")
        assert coord_stats["role"] == "coordinator"
        assert coord_stats["failure_policy"] == "error"
        assert len(coord_stats["workers"]) == 2
        assert all(w["connected"] for w in coord_stats["workers"])
        c = coord_stats["counters"]

        def delta(name):
            return c.get(name, 0) - before.get(name, 0)

        assert delta("coord.mine_rpcs") >= 2
        assert delta("coord.fanouts") >= 1
        assert delta("cache.hit") >= 1
        assert delta("cache.add") >= 1
        assert delta("worker.mine_rpcs") >= 2   # in-process: shared registry
        assert delta("worker.results_sent") >= 4

        worker_stats = fetch_stats(s.workers[0].bound_addr, role="worker")
        assert worker_stats["role"] == "worker"
        assert worker_stats["backend"] == "PythonBackend"
        assert worker_stats["active_tasks"] == 0

        auto = fetch_stats(s.coord_client_addr, role="auto")
        assert auto["role"] == "coordinator"
    finally:
        s.close()


def test_all_backends_count_hashes():
    """search.hashes must move for every backend family (the jax paths
    via the driver, python via the oracle's progress hook)."""
    from distpow_tpu.backends import PythonBackend

    before = REGISTRY.get("search.hashes")
    found_before = REGISTRY.get("search.found")
    secret = PythonBackend().search(b"\x01\x02", 2, list(range(256)))
    assert secret is not None
    assert REGISTRY.get("search.hashes") > before
    assert REGISTRY.get("search.found") == found_before + 1


def test_cache_replay_does_not_count(tmp_path):
    from distpow_tpu.runtime.cache import ResultCache

    path = str(tmp_path / "c.jsonl")
    c = ResultCache(persist_path=path)
    for i in range(5):
        c.add(bytes([i]), 2, b"\x01", None)
    c.close()
    before = REGISTRY.get("cache.add")
    c2 = ResultCache(persist_path=path)  # replays 5 lines
    c2.close()
    assert REGISTRY.get("cache.add") == before


def test_stats_cli_main(capsys):
    s = Stack(1)
    try:
        from distpow_tpu.cli.stats import main

        assert main(["--addr", s.coord_client_addr]) == 0
        out = capsys.readouterr().out
        assert '"role": "coordinator"' in out
    finally:
        s.close()
