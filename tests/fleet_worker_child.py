"""Elastic-worker child for the fleet membership tests (test_fleet.py).

Boots a real python-backend Worker with ``FleetRegister`` on, registers
against the given coordinator worker-API address, prints
``WORKER_READY <addr>`` and serves until killed.  The parent SIGKILLs
it mid-round (lease-expiry reassignment) or SIGSTOPs it past its lease
TTL (ride-out + fresh re-registration) — the two membership-chaos
scenarios that need a real process to be honest.

Usage: python tests/fleet_worker_child.py <coord_worker_api_addr>
           [<heartbeat_s>] [<worker_id>]
"""

import os
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distpow_tpu.nodes.worker import Worker  # noqa: E402
from distpow_tpu.runtime.config import WorkerConfig  # noqa: E402

coord_addr = sys.argv[1]
heartbeat_s = float(sys.argv[2]) if len(sys.argv) > 2 else 0.2
worker_id = sys.argv[3] if len(sys.argv) > 3 else "elasticworker"
w = Worker(
    WorkerConfig(
        WorkerID=worker_id,
        ListenAddr="127.0.0.1:0",
        CoordAddr=coord_addr,
        Backend="python",
        WarmupNonceLens=[],
        WarmupWidths=[],
        FleetRegister=True,
        FleetHeartbeatS=heartbeat_s,
        FleetCalibrationS=0.0,  # deterministic boot: no calibration
    )
)
addr = w.initialize_rpcs()
w.start_forwarder()
w.start_fleet_agent()
if not w.fleet_agent.wait_registered(timeout=20.0):
    print("REGISTER_TIMEOUT", flush=True)
    sys.exit(3)
print(f"WORKER_READY {addr}", flush=True)
threading.Event().wait()
