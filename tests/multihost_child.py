"""Child process for the 2-process jax.distributed multi-host test.

Usage: python multihost_child.py <process_id> <coordinator_port>

Each of the two processes owns 4 virtual CPU devices; together they form
one 8-device global mesh.  The mesh solve's ``lax.pmin`` found-index
collective must cross the process boundary for either process to learn
the result (the winning candidate is pinned to the upper thread-byte
half, i.e. process 1's devices).  Run by tests/test_multihost.py.
"""

import os
import sys

pid = int(sys.argv[1])
port = sys.argv[2]
# the container's sitecustomize has already imported jax against the
# axon/TPU backend, so the platform flip must go through jax.config
# (same pattern as tests/conftest.py); XLA_FLAGS is still read lazily
# at backend initialization
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=pid
)
assert len(jax.local_devices()) == 4, jax.local_devices()
assert len(jax.devices()) == 8, jax.devices()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distpow_tpu.models import puzzle  # noqa: E402
from distpow_tpu.models.registry import get_hash_model  # noqa: E402
from distpow_tpu.parallel.mesh_search import (  # noqa: E402
    _pallas_mesh_step_factory,
    make_mesh,
    search_mesh,
)

# nonce chosen so the FIRST solution in enumeration order is
# (tb=214, chunk=empty->width probe) — tb 214 lives on global device
# 214 // 32 = 6, owned by process 1 (tests/test_multihost.py verified
# the oracle offline)
NONCE = bytes.fromhex("045a")
mesh = make_mesh(jax.devices())
res = search_mesh(NONCE, 2, list(range(256)), mesh=mesh, batch_size=1 << 12)
assert res is not None
assert puzzle.check_secret(NONCE, res.secret, 2)
print(f"RESULT pid={pid} secret={res.secret.hex()} tb={res.thread_byte}",
      flush=True)

# a solve through the pallas-mesh kernel factory (interpret mode on the
# CPU mesh).  Different nonce on purpose: NONCE's first solution is
# width-0 (empty chunk), which both factories serve via the shared
# single-device probe — it would never consult the kernel.  0x000c has
# NO width-0 solution and its first width-1 solution is (tb=144,
# chunk=1) — verified against the hashlib oracle — so the result comes
# from the KERNEL's tile grid, tb=144 lives on global device 4 (process
# 1), and only the kernel's pmin-ed global flat index crossing the
# process boundary can deliver it to process 0.
NONCE_P = bytes.fromhex("000c")
pf = _pallas_mesh_step_factory(
    NONCE_P, 2, 0, 256, get_hash_model("md5"), mesh, "workers",
    interpret=True,
)
res_p = search_mesh(NONCE_P, 2, list(range(256)), mesh=mesh,
                    batch_size=1 << 12, step_factory=pf)
assert res_p is not None
assert puzzle.check_secret(NONCE_P, res_p.secret, 2)
assert res_p.secret == bytes([144, 1]), res_p.secret.hex()
print(f"PALLAS pid={pid} secret={res_p.secret.hex()} "
      f"tb={res_p.thread_byte}", flush=True)

# the sponge family through the distributed mesh.  Width-0 first
# solutions are served by the shared single-device probe (same trap the
# PALLAS leg documents above), so the nonce must have NONE: sha3_256
# of 0x000a has no width-0 solution and its first solution in
# reference chunk-major order is (chunk=1, tb=204) — verified against
# the hashlib oracle over iter_candidates — on global device
# 204 // 32 = 6, owned by process 1, so both processes reporting it
# proves the structurally-different model (pad10*1, XOR-absorb,
# 50-limb state) rides the cross-process pmin collective
NONCE_S = bytes.fromhex("000a")
res_s = search_mesh(NONCE_S, 2, list(range(256)), mesh=mesh,
                    model=get_hash_model("sha3_256"), batch_size=1 << 12)
assert res_s is not None
assert puzzle.check_secret(NONCE_S, res_s.secret, 2, "sha3_256")
assert res_s.secret == bytes([204, 1]), res_s.secret.hex()
print(f"SHA3 pid={pid} secret={res_s.secret.hex()} "
      f"tb={res_s.thread_byte}", flush=True)
