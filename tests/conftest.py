"""Test harness configuration.

The test suite runs on a virtual 8-device CPU mesh (mirroring the
reference's everything-on-localhost validation strategy, SURVEY.md
section 4), so sharding/collective behavior is exercised without TPU
hardware.  The container's sitecustomize pre-imports jax against the
axon/TPU backend, so we flip the platform *before the first backend use*
rather than via environment variables.

Set DISTPOW_TEST_TPU=1 to run the suite on the real accelerator instead.
"""

import importlib.util
import os
import sys

import pytest

# -- runtime lock-order audit (docs/CONCURRENCY.md, ISSUE 17) ----------------
# Load lockcheck standalone (stdlib-only) and pre-seed sys.modules under its
# canonical name BEFORE anything imports distpow_tpu: the threading-factory
# patch must be live when module-level singletons (metrics registry, tracer
# sinks) construct their locks, or those locks escape instrumentation.
_LC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "distpow_tpu", "runtime", "lockcheck.py")
_spec = importlib.util.spec_from_file_location(
    "distpow_tpu.runtime.lockcheck", _LC)
lockcheck = importlib.util.module_from_spec(_spec)
sys.modules["distpow_tpu.runtime.lockcheck"] = lockcheck
_spec.loader.exec_module(lockcheck)
if lockcheck.enabled():
    lockcheck.install()


@pytest.fixture(scope="session", autouse=True)
def _race_audit():
    """With DISTPOW_LOCK_CHECK=1, fail the session when the suite
    observed a lock-order inversion at runtime (ci.sh --race-audit)."""
    yield
    if lockcheck.enabled():
        report = lockcheck.check()
        assert not report.cycles, lockcheck.format_report(report)


os.environ.setdefault("XLA_FLAGS", "")
if os.environ.get("DISTPOW_TEST_TPU") != "1":
    os.environ["XLA_FLAGS"] = (
        os.environ["XLA_FLAGS"] + " --xla_force_host_platform_device_count=8"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
