"""Test harness configuration.

The test suite runs on a virtual 8-device CPU mesh (mirroring the
reference's everything-on-localhost validation strategy, SURVEY.md
section 4), so sharding/collective behavior is exercised without TPU
hardware.  The container's sitecustomize pre-imports jax against the
axon/TPU backend, so we flip the platform *before the first backend use*
rather than via environment variables.

Set DISTPOW_TEST_TPU=1 to run the suite on the real accelerator instead.
"""

import os

os.environ.setdefault("XLA_FLAGS", "")
if os.environ.get("DISTPOW_TEST_TPU") != "1":
    os.environ["XLA_FLAGS"] = (
        os.environ["XLA_FLAGS"] + " --xla_force_host_platform_device_count=8"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
