"""Runtime-layer tests: tracing, dominance cache, RPC, config, trace server."""

import json
import os
import threading
import time

import pytest

from distpow_tpu.runtime import (
    MemorySink,
    RPCClient,
    RPCError,
    RPCServer,
    ResultCache,
    Tracer,
    TracingServer,
    TracingServerConfig,
)
from distpow_tpu.runtime.actions import (
    CacheAdd,
    CacheHit,
    CacheMiss,
    CacheRemove,
    CoordinatorMine,
    WorkerResult,
)
from distpow_tpu.runtime.config import (
    ClientConfig,
    CoordinatorConfig,
    WorkerConfig,
    read_json_config,
    write_json_config,
)
from distpow_tpu.runtime.tracing import TCPSink


# --- tracing ----------------------------------------------------------------

def test_trace_actions_and_vector_clocks():
    sink = MemorySink()
    tracer = Tracer("client1", sink)
    trace = tracer.create_trace()
    trace.record_action(CoordinatorMine(nonce=b"\x01\x02", num_trailing_zeros=3))
    trace.record_action(
        WorkerResult(nonce=b"\x01\x02", num_trailing_zeros=3, worker_byte=0, secret=b"\x07")
    )
    acts = sink.actions(identity="client1")
    assert [a[1] for a in acts] == ["CoordinatorMine", "WorkerResult"]
    # trace bodies carry the Go structs' CamelCase field names
    assert acts[0][2]["Nonce"] == [1, 2]
    assert acts[1][2]["Secret"] == [7]
    # vector clock strictly increases on the recording identity
    clocks = [e["vc"]["client1"] for e in sink.events if e["type"] == "action"]
    assert clocks == sorted(clocks) and len(set(clocks)) == len(clocks)


def test_token_passing_stitches_happens_before():
    sink_a, sink_b = MemorySink(), MemorySink()
    a = Tracer("nodeA", sink_a)
    b = Tracer("nodeB", sink_b)
    ta = a.create_trace()
    ta.record_action(CoordinatorMine(nonce=b"\x05", num_trailing_zeros=1))
    token = ta.generate_token()

    tb = b.receive_token(token)
    assert tb.trace_id == ta.trace_id  # same causal trace across nodes
    tb.record_action(WorkerResult(nonce=b"\x05", num_trailing_zeros=1, worker_byte=0, secret=b""))
    # B's clock dominates A's at token-generation time (happens-before)
    b_event = [e for e in sink_b.events if e["type"] == "action"][0]
    a_token_event = [e for e in sink_a.events if e["type"] == "generate_token"][0]
    for ident, clk in a_token_event["vc"].items():
        assert b_event["vc"].get(ident, 0) >= clk
    assert b_event["vc"]["nodeB"] >= 1

    # token round-trips back: A merges B's clock
    token_b = tb.generate_token()
    ta2 = a.receive_token(token_b)
    assert ta2.trace_id == ta.trace_id
    a_after = [e for e in sink_a.events if e["type"] == "receive_token"][0]
    assert a_after["vc"]["nodeB"] >= 1


def test_tracer_thread_safety():
    sink = MemorySink()
    tracer = Tracer("node", sink)
    trace = tracer.create_trace()

    def hammer():
        for _ in range(200):
            trace.record_action(CacheMiss(nonce=b"\x01", num_trailing_zeros=1))

    threads = [threading.Thread(target=hammer) for _ in range(8)]
    [t.start() for t in threads]
    [t.join() for t in threads]
    clocks = [e["vc"]["node"] for e in sink.events]
    assert len(clocks) == 1600
    assert len(set(clocks)) == 1600  # every tick unique under contention


# --- dominance cache (coordinator.go:390-473 / worker.go:423-506) -----------

@pytest.fixture
def traced_cache():
    sink = MemorySink()
    tracer = Tracer("node", sink)
    return ResultCache(), tracer.create_trace(), sink


def names(sink):
    return [a[1] for a in sink.actions()]


def test_cache_miss_then_add_then_hit(traced_cache):
    cache, trace, sink = traced_cache
    assert cache.get(b"\x01", 3, trace) is None
    cache.add(b"\x01", 3, b"\xaa", trace)
    assert cache.get(b"\x01", 3, trace) == b"\xaa"
    assert cache.get(b"\x01", 2, trace) == b"\xaa"  # dominance: 3 >= 2
    assert cache.get(b"\x01", 4, trace) is None     # 3 < 4
    assert names(sink) == ["CacheMiss", "CacheAdd", "CacheHit", "CacheHit", "CacheMiss"]


def test_cache_replace_on_higher_difficulty(traced_cache):
    cache, trace, sink = traced_cache
    cache.add(b"\x01", 3, b"\xaa", trace)
    cache.add(b"\x01", 5, b"\x01", trace)  # higher zeros replaces
    assert cache.get(b"\x01", 5, trace) == b"\x01"
    assert names(sink) == ["CacheAdd", "CacheRemove", "CacheAdd", "CacheHit"]
    # the remove logs the OLD entry (coordinator.go:438-442)
    remove = sink.actions()[1][2]
    assert remove["NumTrailingZeros"] == 3 and remove["Secret"] == [0xAA]


def test_cache_replace_on_lexicographically_greater_secret(traced_cache):
    cache, trace, sink = traced_cache
    cache.add(b"\x01", 3, b"\x10", trace)
    cache.add(b"\x01", 3, b"\x20", trace)      # same zeros, greater secret
    assert cache.get(b"\x01", 3, trace) == b"\x20"
    cache.add(b"\x01", 3, b"\x15", trace)      # dominated: no-op, no actions
    assert cache.get(b"\x01", 3, trace) == b"\x20"
    assert names(sink).count("CacheRemove") == 1


def test_cache_dominated_insert_is_silent(traced_cache):
    cache, trace, sink = traced_cache
    cache.add(b"\x01", 5, b"\xaa", trace)
    before = names(sink)
    assert cache.add(b"\x01", 3, b"\xbb", trace) is False
    assert names(sink) == before


def test_cache_property_convergence():
    """Dominance order makes replicas converge regardless of arrival order."""
    import itertools
    import random

    updates = [(2, b"\x05"), (3, b"\x01"), (3, b"\x07"), (1, b"\xff"), (3, b"\x02")]
    finals = set()
    for perm in itertools.permutations(updates):
        cache = ResultCache()
        for ntz, sec in perm:
            cache.add(b"\x09", ntz, sec, None)
        e = cache.peek(b"\x09")
        finals.add((e.num_trailing_zeros, e.secret))
    assert finals == {(3, b"\x07")}


def test_cache_persist_and_resume(tmp_path):
    """Checkpoint/resume: a cache journal replays to identical converged
    state across restarts (capability the reference lacks; its caches are
    memory-only, coordinator.go:105-108, worker.go:98-101)."""
    path = str(tmp_path / "cache.jsonl")
    c1 = ResultCache(persist_path=path)
    c1.add(b"\x01\x02", 3, b"\xaa", None)
    c1.add(b"\x01\x02", 5, b"\xbb", None)   # supersedes
    c1.add(b"\x03\x04", 2, b"\xcc", None)
    c1.add(b"\x01\x02", 4, b"\xdd", None)   # dominated: not journaled
    c1.close()

    c2 = ResultCache(persist_path=path)
    assert len(c2) == 2
    assert c2.get(b"\x01\x02", 5, None) == b"\xbb"
    assert c2.get(b"\x03\x04", 2, None) == b"\xcc"
    c2.add(b"\x05\x06", 1, b"\xee", None)   # journal keeps appending
    c2.close()

    c3 = ResultCache(persist_path=path)
    assert len(c3) == 3 and c3.get(b"\x05\x06", 1, None) == b"\xee"
    c3.close()


def test_cache_journal_tolerates_torn_tail(tmp_path):
    path = str(tmp_path / "cache.jsonl")
    c1 = ResultCache(persist_path=path)
    c1.add(b"\x01", 3, b"\xaa", None)
    c1.close()
    with open(path, "a") as fh:
        fh.write('{"nonce": "02", "ntz": 4, "sec')  # crash mid-append
    c2 = ResultCache(persist_path=path)
    assert len(c2) == 1 and c2.get(b"\x01", 3, None) == b"\xaa"
    # appending after a torn tail must NOT merge into the partial line —
    # the journal is compacted at open, so the next restart sees all
    # post-crash entries
    c2.add(b"\x03", 2, b"\xbb", None)
    c2.close()
    c3 = ResultCache(persist_path=path)
    assert len(c3) == 2 and c3.get(b"\x03", 2, None) == b"\xbb"
    c3.close()


def test_cache_journal_compaction(tmp_path):
    """A journal full of superseded entries is rewritten at load."""
    path = str(tmp_path / "cache.jsonl")
    c1 = ResultCache(persist_path=path)
    for ntz in range(1, 8):
        c1.add(b"\x01", ntz, bytes([ntz]), None)  # 7 lines, 1 live entry
    c1.close()
    c2 = ResultCache(persist_path=path)
    c2.close()
    with open(path) as fh:
        lines = [ln for ln in fh if ln.strip()]
    assert len(lines) == 1
    c3 = ResultCache(persist_path=path)
    assert c3.get(b"\x01", 7, None) == bytes([7])
    c3.close()


def test_cache_compaction_killed_mid_write_replays_fully(tmp_path,
                                                         monkeypatch):
    """ISSUE 16 satellite: compaction is crash-consistent.  A kill at
    EITHER crash point — mid-temp-file-write, or between write and
    rename — must leave the original journal byte-intact so the next
    open replays every entry (no shortened replay, no torn mix)."""
    import distpow_tpu.runtime.cache as cache_mod

    path = str(tmp_path / "cache.jsonl")
    c1 = ResultCache(persist_path=path)
    for ntz in range(1, 8):
        c1.add(b"\x01", ntz, bytes([ntz]), None)
    c1.add(b"\x02", 2, b"\xbe", None)
    c1.close()
    with open(path, "rb") as fh:
        journal_before = fh.read()

    class Killed(RuntimeError):
        pass

    # crash point 1: the temp-file write dies partway (disk full, kill)
    real_fsync = os.fsync

    def dying_fsync(fd):
        raise Killed("killed mid-compaction-write")

    monkeypatch.setattr(cache_mod.os, "fsync", dying_fsync)
    with pytest.raises(Killed):
        ResultCache(persist_path=path)  # 9 lines / 2 entries: compacts
    monkeypatch.setattr(cache_mod.os, "fsync", real_fsync)
    with open(path, "rb") as fh:
        assert fh.read() == journal_before, \
            "crash mid-temp-write mutated the original journal"

    # crash point 2: the atomic rename itself never happens
    def dying_replace(src, dst):
        raise Killed("killed before rename")

    monkeypatch.setattr(cache_mod.os, "replace", dying_replace)
    with pytest.raises(Killed):
        ResultCache(persist_path=path)
    monkeypatch.undo()
    with open(path, "rb") as fh:
        assert fh.read() == journal_before, \
            "crash before rename mutated the original journal"

    # the uncompacted journal still replays to the FULL converged state
    c2 = ResultCache(persist_path=path)
    assert len(c2) == 2
    assert c2.get(b"\x01", 7, None) == bytes([7])
    assert c2.get(b"\x02", 2, None) == b"\xbe"
    c2.close()
    # and an unimpeded restart compacts + keeps everything
    c3 = ResultCache(persist_path=path)
    assert len(c3) == 2 and c3.get(b"\x01", 7, None) == bytes([7])
    c3.close()


# --- RPC --------------------------------------------------------------------

class EchoService:
    def __init__(self):
        self.slow_started = threading.Event()

    def Echo(self, params):
        return {"echo": params}

    def Add(self, params):
        return {"sum": params["a"] + params["b"]}

    def Boom(self, params):
        raise ValueError("boom")

    def Slow(self, params):
        self.slow_started.set()
        time.sleep(params.get("delay", 0.3))
        return {"done": True}

    def _private(self, params):
        return {"leak": True}


@pytest.fixture
def rpc_pair():
    srv = RPCServer()
    svc = EchoService()
    srv.register("Echo", svc)
    addr = srv.listen("127.0.0.1:0")
    srv.serve_in_background()
    cli = RPCClient(addr)
    yield srv, cli, svc
    cli.close()
    srv.shutdown()


def test_rpc_roundtrip(rpc_pair):
    _, cli, _ = rpc_pair
    assert cli.call("Echo.Add", {"a": 2, "b": 40}) == {"sum": 42}
    assert cli.call("Echo.Echo", {"nonce": [1, 2, 3]}) == {"echo": {"nonce": [1, 2, 3]}}


def test_rpc_error_propagates(rpc_pair):
    _, cli, _ = rpc_pair
    with pytest.raises(RPCError, match="boom"):
        cli.call("Echo.Boom", {})
    with pytest.raises(RPCError, match="unknown method"):
        cli.call("Echo.Nope", {})
    with pytest.raises(RPCError, match="unknown service"):
        cli.call("Nope.Echo", {})
    with pytest.raises(RPCError, match="not exported"):
        cli.call("Echo._private", {})


def test_rpc_async_go_and_concurrency(rpc_pair):
    _, cli, svc = rpc_pair
    # a slow call must not head-of-line-block fast ones on the same conn
    slow = cli.go("Echo.Slow", {"delay": 0.5})
    svc.slow_started.wait(2)
    t0 = time.time()
    assert cli.call("Echo.Add", {"a": 1, "b": 1}) == {"sum": 2}
    assert time.time() - t0 < 0.4
    assert slow.result(2) == {"done": True}


def test_rpc_many_concurrent_calls(rpc_pair):
    _, cli, _ = rpc_pair
    futs = [cli.go("Echo.Add", {"a": i, "b": i}) for i in range(100)]
    assert [f.result(5)["sum"] for f in futs] == [2 * i for i in range(100)]


def test_rpc_shutdown_stops_accepting():
    """shutdown() must actually release the listener: close() alone does
    not wake a thread blocked in accept(), leaving the port serving."""
    srv = RPCServer()
    srv.register("Echo", EchoService())
    addr = srv.listen("127.0.0.1:0")
    srv.serve_in_background()
    RPCClient(addr).call("Echo.Echo", {"x": 1})
    srv.shutdown()
    time.sleep(0.1)
    with pytest.raises((OSError, RPCError)):
        RPCClient(addr, timeout=0.5).call("Echo.Echo", {}, timeout=0.5)


def test_rpc_multiple_listeners():
    # one server on two listeners: the coordinator's segregated
    # client-facing and worker-facing endpoints (coordinator.go:334-351)
    srv = RPCServer()
    srv.register("Echo", EchoService())
    a1 = srv.listen("127.0.0.1:0")
    a2 = srv.listen("127.0.0.1:0")
    assert a1 != a2
    srv.serve_in_background()
    c1, c2 = RPCClient(a1), RPCClient(a2)
    assert c1.call("Echo.Add", {"a": 1, "b": 2}) == {"sum": 3}
    assert c2.call("Echo.Add", {"a": 3, "b": 4}) == {"sum": 7}
    c1.close(); c2.close(); srv.shutdown()


# --- config -----------------------------------------------------------------

def test_config_roundtrip(tmp_path):
    cfg = WorkerConfig(
        WorkerID="worker7",
        ListenAddr="127.0.0.1:1234",
        CoordAddr="127.0.0.1:999",
        TracerServerAddr="127.0.0.1:888",
        Backend="jax-mesh",
        HashModel="sha256",
        BatchSize=1 << 16,
    )
    p = tmp_path / "worker.json"
    write_json_config(p, cfg)
    loaded = read_json_config(p, WorkerConfig)
    assert loaded == cfg


def test_config_reads_reference_format(tmp_path):
    # the reference's exact JSON shape loads unchanged (config/*.json)
    p = tmp_path / "coord.json"
    p.write_text(json.dumps({
        "ClientAPIListenAddr": ":38888",
        "WorkerAPIListenAddr": ":48888",
        "Workers": [":20000", ":20001"],
        "TracerServerAddr": ":58888",
        "TracerSecret": "",
        "SomeUnknownField": 7,
    }))
    cfg = read_json_config(p, CoordinatorConfig)
    assert cfg.Workers == [":20000", ":20001"]
    assert cfg.TracerSecret == b""
    cl = tmp_path / "client.json"
    cl.write_text(json.dumps({"ClientID": "client2", "CoordAddr": ":38888",
                              "TracerServerAddr": ":58888", "TracerSecret": ""}))
    ccfg = read_json_config(cl, ClientConfig)
    assert ccfg.ClientID == "client2" and ccfg.ChCapacity == 10


# --- tracing server ---------------------------------------------------------

def test_tracing_server_end_to_end(tmp_path):
    out = tmp_path / "trace_output.log"
    shiviz = tmp_path / "shiviz_output.log"
    server = TracingServer(TracingServerConfig(
        ServerBind="127.0.0.1:0",
        Secret=b"s3cret",
        OutputFile=str(out),
        ShivizOutputFile=str(shiviz),
    ))
    addr = server.open()
    server.accept_in_background()

    tracer = Tracer("worker1", TCPSink(addr, b"s3cret"))
    trace = tracer.create_trace()
    trace.record_action(CoordinatorMine(nonce=b"\x01\x02", num_trailing_zeros=4))
    trace.generate_token()
    tracer.close()
    time.sleep(0.3)

    human = out.read_text()
    assert "[worker1]" in human and "CoordinatorMine" in human
    assert f"TraceID={trace.trace_id}" in human
    sv = shiviz.read_text()
    assert sv.startswith("(?<host>")
    assert "worker1 {" in sv and "CoordinatorMine" in sv

    # wrong secret: events must NOT land
    bad = Tracer("intruder", TCPSink(addr, b"wrong"))
    t2 = bad.create_trace()
    try:
        t2.record_action(CacheMiss(nonce=b"\x01", num_trailing_zeros=1))
    except OSError:
        pass
    time.sleep(0.3)
    assert "intruder" not in out.read_text()
    server.close()


def test_rpc_server_survives_adversarial_frames():
    """Protocol robustness (round 4): garbage bytes, an oversized
    length prefix, valid-JSON-wrong-shape frames, and truncated frames
    must each cost only the offending CONNECTION — the server keeps
    serving well-formed clients afterward, with no wedged threads."""
    import socket
    import struct

    class Echo:
        def Ping(self, params):
            return {"pong": params.get("n")}

    srv = RPCServer()
    srv.register("Echo", Echo())
    addr = srv.listen("127.0.0.1:0")
    srv.serve_in_background()
    host, _, port = addr.rpartition(":")

    def raw_conn():
        return socket.create_connection((host, int(port)), timeout=5)

    try:
        # (a) garbage bytes where the length prefix should be
        s = raw_conn()
        s.sendall(b"\xde\xad\xbe\xef" + b"\x00" * 64)
        s.close()
        # (b) oversized frame announcement (would be a 1 GiB read)
        s = raw_conn()
        s.sendall(struct.pack(">I", 1 << 30))
        s.close()
        # (c) valid JSON, wrong shape (a bare number)
        s = raw_conn()
        payload = b"5"
        s.sendall(struct.pack(">I", len(payload)) + payload)
        # server must drop this connection, not crash a thread
        assert s.recv(1) == b""  # orderly close from the server side
        s.close()
        # (c2) valid length, invalid UTF-8 payload (UnicodeDecodeError
        # is a ValueError, NOT a json.JSONDecodeError — review r4)
        s = raw_conn()
        s.sendall(struct.pack(">I", 1) + b"\xff")
        assert s.recv(1) == b""
        s.close()
        # (d) truncated frame then hard disconnect
        s = raw_conn()
        s.sendall(struct.pack(">I", 100) + b"partial")
        s.close()
        # (e) a well-formed client still gets served
        cli = RPCClient(addr)
        try:
            assert cli.call("Echo.Ping", {"n": 7}) == {"pong": 7}
        finally:
            cli.close()
    finally:
        srv.shutdown()


def test_rpc_client_fails_fast_after_protocol_violation():
    """A server that sends one malformed frame on a healthy connection
    must not strand LATER calls: the client tears the connection down,
    so subsequent calls raise instead of waiting on a dead reader
    (review r4)."""
    import socket
    import struct
    import threading

    ls = socket.create_server(("127.0.0.1", 0))
    port = ls.getsockname()[1]

    def server():
        conn, _ = ls.accept()
        conn.recv(4096)                       # swallow the request
        conn.sendall(struct.pack(">I", 1) + b"5")  # non-object response
        # keep the TCP connection open: the violation alone must kill it
        threading.Event().wait(3)
        conn.close()

    threading.Thread(target=server, daemon=True).start()
    cli = RPCClient(f"127.0.0.1:{port}", timeout=5)
    try:
        with pytest.raises(RPCError):
            cli.call("Echo.Ping", {})
        # the follow-up call must fail promptly, not hang
        with pytest.raises((RPCError, OSError)):
            cli.call("Echo.Ping", {})
    finally:
        cli.close()
        ls.close()
