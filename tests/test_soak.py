"""Soak plane end to end (distpow_tpu/load/shapes.py + soak.py,
ISSUE 18): seeded shape schedules are deterministic, compression
preserves expected arrivals per phase, Sum names composite phases, and
run_soak turns a real in-process cluster into a typed SoakVerdict —
green on a clean run, nonzero naming proc.threads under a planted
thread-per-request leak."""

from __future__ import annotations

import math
import os
import sys
import threading
import time

import pytest

sys.path.insert(0, os.path.dirname(__file__))

from distpow_tpu.load import (  # noqa: E402
    InProcCluster,
    LoadMix,
    run_soak,
)
from distpow_tpu.load.shapes import (  # noqa: E402
    Compressed,
    Constant,
    Diurnal,
    FlashCrowd,
    Ramp,
    Sum,
    build_shaped_schedule,
    compress,
)
from distpow_tpu.runtime.metrics import REGISTRY as metrics  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SLO_CONFIG = os.path.join(REPO, "config", "slo.json")


def mk_mix(seed, **kw):
    kw.setdefault("n_keys", 24)
    kw.setdefault("zipf_s", 1.1)
    kw.setdefault("difficulties", ((1, 0.7), (2, 0.3)))
    return LoadMix(rate_hz=1.0, duration_s=1.0, seed=seed, **kw)


# -- shape algebra -----------------------------------------------------------

def test_shaped_schedule_is_deterministic_per_seed():
    shape = Sum(parts=(
        Diurnal(base=6.0, amplitude=4.0, period_s=40.0),
        FlashCrowd(extra_hz=10.0, at_s=22.0, width_s=4.0, duration_s=40.0),
    ))
    a = build_shaped_schedule(shape, mk_mix(7))
    b = build_shaped_schedule(shape, mk_mix(7))
    assert a and a == b
    c = build_shaped_schedule(shape, mk_mix(8))
    assert c != a


def test_thinning_respects_the_shape_support():
    crowd = FlashCrowd(extra_hz=30.0, at_s=10.0, width_s=5.0,
                       duration_s=30.0)
    sched = build_shaped_schedule(crowd, mk_mix(11))
    assert sched
    assert all(10.0 <= arr.t < 15.0 for arr in sched)
    assert build_shaped_schedule(Constant(0.0, 10.0), mk_mix(11)) == []


def test_compression_preserves_expected_arrival_count():
    """compress(shape, f) scales time down and rate up by f, so the
    expected arrivals stay put — the 4-sigma Poisson band pins it
    (seeded: deterministic, no flake)."""
    inner = Diurnal(base=5.0, amplitude=3.0, period_s=200.0)
    expected = 5.0 * 200.0  # the sine integrates to zero over a period
    squeezed = compress(inner, 100.0)
    assert squeezed.duration_s == pytest.approx(2.0)
    assert squeezed.peak_hz() == pytest.approx(inner.peak_hz() * 100.0)
    band = 4.0 * math.sqrt(expected)
    for shape, seed in ((inner, 3), (squeezed, 3), (squeezed, 4)):
        n = len(build_shaped_schedule(shape, mk_mix(seed)))
        assert abs(n - expected) < band, (shape, n)


def test_compressed_phases_scale_with_names_intact():
    inner = Diurnal(base=5.0, amplitude=3.0, period_s=200.0)
    squeezed = compress(inner, 100.0)
    assert [(n, s, e) for n, s, e in squeezed.phases()] == [
        (n, s / 100.0, e / 100.0) for n, s, e in inner.phases()]
    with pytest.raises(ValueError):
        Compressed(inner=inner, factor=0.0)


def test_sum_phases_union_boundaries_and_composite_names():
    shape = Sum(parts=(
        Diurnal(base=6.0, amplitude=4.0, period_s=40.0),
        FlashCrowd(extra_hz=10.0, at_s=22.0, width_s=4.0, duration_s=40.0),
    ))
    phases = shape.phases()
    assert [p[0] for p in phases] == [
        "rise+before", "peak+before", "fall+before", "fall+spike",
        "fall+after", "trough+after"]
    # contiguous cover of the whole duration
    assert phases[0][1] == 0.0 and phases[-1][2] == 40.0
    assert all(a[2] == b[1] for a, b in zip(phases, phases[1:]))
    # rates superpose pointwise
    assert shape.rate_hz(23.0) == pytest.approx(
        shape.parts[0].rate_hz(23.0) + 10.0)


def test_ramp_and_diurnal_rate_envelopes():
    ramp = Ramp(start_hz=2.0, end_hz=10.0, duration_s=10.0)
    assert ramp.rate_hz(0.0) == pytest.approx(2.0)
    assert ramp.rate_hz(5.0) == pytest.approx(6.0)
    assert ramp.rate_hz(10.0) == 0.0  # past the end
    assert ramp.peak_hz() == 10.0
    day = Diurnal(base=3.0, amplitude=5.0, period_s=40.0)
    assert day.peak_hz() == pytest.approx(8.0)
    assert day.rate_hz(30.0) == 0.0  # trough clamps at zero
    assert min(day.rate_hz(t / 4.0) for t in range(160)) >= 0.0


def test_multi_day_diurnal_phase_names_number_the_days():
    two_days = Diurnal(base=3.0, amplitude=1.0, period_s=20.0,
                       duration_s=40.0)
    assert [p[0] for p in two_days.phases()] == [
        "day1.rise", "day1.peak", "day1.fall", "day1.trough",
        "day2.rise", "day2.peak", "day2.fall", "day2.trough"]


# -- run_soak end to end -----------------------------------------------------

def test_green_soak_ends_in_a_passing_verdict(tmp_path):
    spool = str(tmp_path / "spool.jsonl")
    report, verdict = run_soak(
        Constant(8.0, 5.0), mk_mix(1811), SLO_CONFIG,
        n_workers=2, scrape_interval_s=0.3, spool_path=spool,
    )
    assert verdict.exit_code() == 0 and verdict.status == "pass"
    assert not verdict.failures and not verdict.leak_suspects
    assert verdict.phases and all(
        p.status in ("pass", "warn", "no_data") for p in verdict.phases)
    assert verdict.lag_p99_s is not None
    assert verdict.lag_p99_s <= verdict.lag_budget_s
    assert report["load"]["issued"] > 20
    assert os.path.exists(spool)
    # the verdict renders for humans and serializes for machines
    assert "Soak verdict: PASS" in verdict.render()
    assert verdict.to_dict()["status"] == "pass"


@pytest.mark.slow
def test_planted_thread_leak_flips_the_verdict_nonzero():
    """The classic slow-executor leak — one parked daemon thread per
    request — must climb proc.threads past the sentinel's noise floor
    and fail the soak BY NAME."""
    cluster = InProcCluster(n_workers=2)
    stop = threading.Event()
    parked = []
    real_mine = cluster.client.mine

    def leaky_mine(*a, **kw):
        t = threading.Thread(target=stop.wait, daemon=True)
        t.start()
        parked.append(t)
        return real_mine(*a, **kw)

    cluster.client.mine = leaky_mine
    before = metrics.snapshot()["counters"].get("health.leak_suspects", 0)
    try:
        report, verdict = run_soak(
            Constant(8.0, 6.0), mk_mix(1812), SLO_CONFIG,
            cluster=cluster, scrape_interval_s=0.25,
        )
    finally:
        stop.set()
        time.sleep(0.05)
        cluster.close()
    assert len(parked) > 20
    assert verdict.exit_code() == 1 and verdict.status == "breach"
    named = [s["gauge"] for s in verdict.leak_suspects]
    assert "proc.threads" in named
    assert any("proc.threads" in f for f in verdict.failures)
    after = metrics.snapshot()["counters"].get("health.leak_suspects", 0)
    assert after >= before + 1
