"""CLI / multi-process tests: config-gen consistency and the full
reference demo scenario as real OS processes on localhost — the closest
analogue of actually deploying the reference's five binaries
(SURVEY.md section 3.5 startup sequence)."""

import os
import subprocess
import sys
import time
from pathlib import Path

import pytest

from distpow_tpu.cli import config_gen
from distpow_tpu.runtime.config import (
    ClientConfig,
    CoordinatorConfig,
    TracingServerConfig,
    WorkerConfig,
    read_json_config,
)

REPO = Path(__file__).resolve().parent.parent


def test_config_gen_consistency(tmp_path):
    config_gen.main(["--config-dir", str(tmp_path), "--workers", "3", "--seed", "7"])
    ts = read_json_config(tmp_path / "tracing_server_config.json", TracingServerConfig)
    coord = read_json_config(tmp_path / "coordinator_config.json", CoordinatorConfig)
    c1 = read_json_config(tmp_path / "client_config.json", ClientConfig)
    c2 = read_json_config(tmp_path / "client2_config.json", ClientConfig)
    w = read_json_config(tmp_path / "worker_config.json", WorkerConfig)

    assert coord.TracerServerAddr == ts.ServerBind
    assert c1.CoordAddr == coord.ClientAPIListenAddr
    assert c2.CoordAddr == coord.ClientAPIListenAddr
    assert c2.ClientID != c1.ClientID
    assert w.CoordAddr == coord.WorkerAPIListenAddr
    assert w.ListenAddr == "PASS VIA COMMAND-LINE"
    assert len(coord.Workers) == 3
    assert len({ts.ServerBind, coord.ClientAPIListenAddr,
                coord.WorkerAPIListenAddr, *coord.Workers}) == 6
    for addr in coord.Workers:
        port = int(addr.rsplit(":", 1)[1])
        assert 1024 <= port < 35535


def test_stock_configs_load():
    assert len(read_json_config(REPO / "config/coordinator_config.json",
                                CoordinatorConfig).Workers) == 4
    assert read_json_config(REPO / "config/worker_config.json",
                            WorkerConfig).Backend == "jax"
    assert read_json_config(REPO / "config/client_config.json",
                            ClientConfig).ClientID == "client1"


def test_difficulty_bits_translation():
    """--difficulty-bits N == --difficulty N/4 (SURVEY.md section 7's
    unit mapping: BASELINE configs speak bits, the protocol's
    numTrailingZeros counts nibbles, worker.go:246-256)."""
    from distpow_tpu.cli.client import difficulty_nibbles

    assert difficulty_nibbles(None, 32) == 8  # --difficulty-bits 32
    assert difficulty_nibbles(8, None) == 8   # == --difficulty 8
    assert difficulty_nibbles(None, None) == 5  # default
    assert difficulty_nibbles(None, 4) == 1
    with pytest.raises(ValueError):
        difficulty_nibbles(None, 30)  # not a whole number of nibbles


@pytest.mark.slow
def test_multiprocess_demo_scenario(tmp_path):
    """Boot tracing server + coordinator + 2 workers + demo client as
    subprocesses, difficulty 2/4 nibbles, python backend (no JAX warmup
    in the workers keeps this fast)."""
    from tests.proc_harness import ProcStack

    stack = ProcStack(tmp_path, workers=2, seed=123)
    try:
        stack.boot_core()
        for i in range(len(stack.coord_cfg["Workers"])):
            stack.boot_worker(i)
        time.sleep(0.5)

        client = stack.spawn(
            "-m", "distpow_tpu.cli.client",
            "--config", stack.config("client_config.json"),
            "--config2", stack.config("client2_config.json"),
            # bits unit: 8 bits = 2 nibbles (exercises the
            # SURVEY §7 difficulty-unit translation end-to-end)
            "--difficulty-bits", "8")
        out, _ = client.communicate(timeout=120)
        assert client.returncode == 0, out
        assert out.count("MineResult") == 4, out

        time.sleep(0.5)
        trace_log = (tmp_path / "trace_output.log").read_text()
        for marker in ("PowlibMiningBegin", "CoordinatorMine", "WorkerMine",
                       "WorkerResult", "CoordinatorSuccess",
                       "PowlibMiningComplete", "[client1]", "[client2]",
                       "[coordinator]", "[worker1]", "[worker2]"):
            assert marker in trace_log, f"missing {marker}"
        shiviz = (tmp_path / "shiviz_output.log").read_text()
        assert shiviz.startswith("(?<host>")
        assert "coordinator {" in shiviz
    finally:
        stack.close()


def test_worker_multihost_bootstrap_subprocess():
    """The --jax-* flags join a jax.distributed cluster before backend
    construction; a 1-process cluster over the virtual CPU mesh proves
    the bootstrap + mesh-search path (multi-host DCN uses the identical
    code with N processes).  Run in a subprocess: jax.distributed state
    is process-global."""
    import socket

    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()
    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "from distpow_tpu.cli.worker import maybe_init_distributed\n"
        f"maybe_init_distributed('127.0.0.1:{port}', 1, 0)\n"
        "assert jax.process_count() == 1\n"
        "from distpow_tpu.parallel import search_mesh, make_mesh\n"
        "from distpow_tpu.models import puzzle\n"
        "r = search_mesh(b'\\x01\\x02', 2, list(range(256)),\n"
        "                mesh=make_mesh(jax.devices()), batch_size=1<<13)\n"
        "assert puzzle.check_secret(b'\\x01\\x02', r.secret, 2)\n"
        "jax.distributed.shutdown()\n"
        "print('MULTIHOST_BOOTSTRAP_OK')\n"
    )
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(REPO)
    out = subprocess.run(
        [sys.executable, "-c", code], env=env, cwd=str(REPO),
        capture_output=True, text=True, timeout=240,
    )
    assert "MULTIHOST_BOOTSTRAP_OK" in out.stdout, out.stderr[-2000:]
