"""End-to-end protocol tests: client -> coordinator -> workers over real RPC.

In-process analogue of the reference's multi-node-on-localhost validation
(SURVEY.md section 4): every node runs with its own MemorySink tracer so
the causal action sequences — the reference's correctness oracle — can be
asserted directly.  Boot order mirrors cmd/* (coordinator, then workers,
then clients; SURVEY.md section 3.5).
"""

import contextlib
import queue
import threading
import time

import pytest

from distpow_tpu.models import puzzle
from distpow_tpu.nodes import Client, Coordinator, Worker
from distpow_tpu.runtime.config import ClientConfig, CoordinatorConfig, WorkerConfig
from distpow_tpu.runtime.tracing import MemorySink


class Stack:
    """coordinator + N workers + client(s), each with a MemorySink.

    Everything binds on ':0' and real addresses are wired afterwards
    (Coordinator.set_worker_addrs) — no probe-then-rebind port races.
    """

    def __init__(self, n_workers: int, backend: str = "python", difficulty_model="md5",
                 coord_cache_file: str = "", failure_policy: str = "error",
                 failure_probe_secs: float = 0.2, sink_factory=None,
                 worker_extra: dict = None, coord_extra: dict = None):
        sink_factory = sink_factory or (lambda name: MemorySink())
        self._sink_factory = sink_factory
        self.sinks = {"coordinator": sink_factory("coordinator")}
        self.coordinator = Coordinator(
            CoordinatorConfig(
                ClientAPIListenAddr="127.0.0.1:0",
                WorkerAPIListenAddr="127.0.0.1:0",
                Workers=["pending:0"] * n_workers,
                CacheFile=coord_cache_file,
                FailurePolicy=failure_policy,
                FailureProbeSecs=failure_probe_secs,
                **(coord_extra or {}),
            ),
            sink=self.sinks["coordinator"],
        )
        client_addr, worker_api_addr = self.coordinator.initialize_rpcs()

        self.workers = []
        worker_addrs = []
        for i in range(n_workers):
            wid = f"worker{i + 1}"
            self.sinks[wid] = self._sink_factory(wid)
            w = Worker(
                WorkerConfig(
                    WorkerID=wid,
                    ListenAddr="127.0.0.1:0",
                    CoordAddr=worker_api_addr,
                    Backend=backend,
                    HashModel=difficulty_model,
                    **(worker_extra or {}),
                ),
                sink=self.sinks[wid],
            )
            worker_addrs.append(w.initialize_rpcs())
            w.start_forwarder()
            self.workers.append(w)
        self.coordinator.set_worker_addrs(worker_addrs)

        self.coord_client_addr = client_addr
        self.clients = []

    def new_client(self, cid: str, **cfg_extra) -> Client:
        """``cfg_extra``: extra ClientConfig fields (e.g. the powlib
        retry knobs the fault-injection tests tune)."""
        self.sinks[cid] = self._sink_factory(cid)
        c = Client(
            ClientConfig(ClientID=cid, CoordAddr=self.coord_client_addr,
                         **cfg_extra),
            sink=self.sinks[cid],
        )
        c.initialize()
        self.clients.append(c)
        return c

    def close(self):
        for c in self.clients:
            c.close()
        for w in self.workers:
            w.shutdown()
        self.coordinator.shutdown()

    def action_names(self, node: str):
        return [a[1] for a in self.sinks[node].actions()]


@pytest.fixture
def stack1():
    s = Stack(1)
    yield s
    s.close()


@pytest.fixture
def stack4():
    s = Stack(4)
    yield s
    s.close()


def mine_and_wait(client: Client, nonce: bytes, ntz: int, timeout=30):
    client.mine(nonce, ntz)
    return client.notify_queue.get(timeout=timeout)


def test_single_worker_end_to_end(stack1):
    client = stack1.new_client("client1")
    res = mine_and_wait(client, b"\x01\x02\x03\x04", 2)
    assert res.nonce == b"\x01\x02\x03\x04"
    assert res.num_trailing_zeros == 2
    assert puzzle.check_secret(res.nonce, res.secret, 2)
    # the result equals the reference-order first match for the full range
    oracle = puzzle.python_search(b"\x01\x02\x03\x04", 2, list(range(256)))
    assert res.secret == oracle

    # client trace ordering (powlib.go:106-176)
    assert stack1.action_names("client1") == [
        "PowlibMiningBegin", "PowlibMine", "PowlibSuccess", "PowlibMiningComplete",
    ]
    # coordinator protocol spine (coordinator.go:139-298)
    coord = stack1.action_names("coordinator")
    assert coord[0] == "CoordinatorMine"
    assert coord[1] == "CacheMiss"
    assert "CoordinatorWorkerMine" in coord
    assert "CoordinatorWorkerResult" in coord
    assert "CoordinatorWorkerCancel" in coord
    assert coord[-1] == "CoordinatorSuccess"
    # CacheAdd happens when the worker result arrives
    assert "CacheAdd" in coord
    # worker: Mine -> (CacheMiss) -> Result -> Cancel last (worker.go:375-387)
    wk = stack1.action_names("worker1")
    assert wk[0] == "WorkerMine"
    assert "WorkerResult" in wk
    assert wk[-1] == "WorkerCancel"
    assert wk.index("WorkerResult") < wk.index("WorkerCancel")


def test_four_workers_partition_and_ledger(stack4):
    client = stack4.new_client("client1")
    res = mine_and_wait(client, b"\x05\x06\x07\x08", 2)
    assert puzzle.check_secret(res.nonce, res.secret, 2)

    coord = stack4.action_names("coordinator")
    # fan-out recorded one CoordinatorWorkerMine per worker
    assert coord.count("CoordinatorWorkerMine") == 4
    # cancel broadcast >= one per worker (more if late results re-broadcast)
    assert coord.count("CoordinatorWorkerCancel") % 4 == 0
    assert coord.count("CoordinatorWorkerCancel") >= 4
    # every worker saw the Mine and recorded a Cancel; a WorkerResult (if
    # any) precedes the first WorkerCancel after it.  (The strict
    # "WorkerCancel last" only holds without late-result re-broadcasts,
    # whose no-task path appends WorkerCancel + CacheAdd, worker.go:215-221.)
    for i in range(4):
        wk = stack4.action_names(f"worker{i + 1}")
        assert wk[0] == "WorkerMine"
        assert "WorkerCancel" in wk
        if "WorkerResult" in wk:
            r = wk.index("WorkerResult")
            assert "WorkerCancel" in wk[r:]
    # the Mine RPC returned (ledger complete) and the system is idle enough
    # for a second request to run cleanly
    res2 = mine_and_wait(client, b"\x09\x0a", 2)
    assert puzzle.check_secret(res2.nonce, res2.secret, 2)


def test_winning_secret_lands_in_all_caches(stack4):
    client = stack4.new_client("client1")
    res = mine_and_wait(client, b"\x11\x12", 2)
    time.sleep(0.3)  # Found broadcast completes before Mine returns; margin
    for i in range(4):
        entry = stack4.workers[i].handler.result_cache.peek(b"\x11\x12")
        assert entry is not None
        # every worker cache converged to a secret >= the winner in the
        # dominance order (late results may dominate the first winner)
        assert entry.num_trailing_zeros >= 2
    coord_entry = stack4.coordinator.handler.result_cache.peek(b"\x11\x12")
    assert coord_entry is not None


def test_cache_hit_skips_fanout(stack1):
    client = stack1.new_client("client1")
    mine_and_wait(client, b"\x21\x22", 2)
    coord_before = stack1.action_names("coordinator")
    n_mines = coord_before.count("CoordinatorWorkerMine")

    res2 = mine_and_wait(client, b"\x21\x22", 2)
    assert puzzle.check_secret(res2.nonce, res2.secret, 2)
    coord_after = stack1.action_names("coordinator")
    # no new fan-out; the hit path records CacheHit then CoordinatorSuccess
    assert coord_after.count("CoordinatorWorkerMine") == n_mines
    assert coord_after[-2:] == ["CacheHit", "CoordinatorSuccess"]


def test_reassign_dead_worker_at_fanout():
    """FailurePolicy="reassign": a worker that is down when the request
    arrives has its shard reassigned to a live worker, and the request
    still completes (the reference would fail the Mine RPC,
    coordinator.go:196-198; divergence documented in config.py)."""
    s = Stack(2, failure_policy="reassign")
    try:
        s.workers[1].shutdown()  # worker2 is gone before the first request
        client = s.new_client("client1")
        res = mine_and_wait(client, b"\x61\x62", 2, timeout=30)
        assert puzzle.check_secret(res.nonce, res.secret, 2)
        coord = s.action_names("coordinator")
        # 2 fan-out attempts + 1 reassignment of the dead worker's shard
        assert coord.count("CoordinatorWorkerMine") == 3
        mines = [a[2]["WorkerByte"] for a in s.sinks["coordinator"].actions()
                 if a[1] == "CoordinatorWorkerMine"]
        assert sorted(mines) == [0, 1, 1]  # shard 1 re-issued
    finally:
        s.close()


def test_reassign_worker_dies_mid_protocol():
    """A worker that dies while mining stops acking; the ledger drops its
    expectations after a failed Found/probe and the Mine still returns."""
    s = Stack(2, failure_policy="reassign")
    try:
        client = s.new_client("client1")
        client.mine(b"\x63\x64", 4)  # ~65K python hashes: slow enough
        time.sleep(0.15)
        s.workers[1].server.shutdown()  # inbound RPCs (Found/Ping) now fail
        res = client.notify_queue.get(timeout=60)
        assert puzzle.check_secret(res.nonce, res.secret, 4)
    finally:
        s.close()


def test_reassign_hung_worker_detected():
    """A hung-but-connected worker (Mine RPC never returns) must be
    detected via the bounded ack timeout and its shard reassigned —
    WITHOUT its timeout ever sitting on the round's critical path: the
    parallel fan-out (ISSUE 5) harvests the hung ack off-path while the
    live worker is already mining (difficulty 5 ~ 1M python candidates
    keeps the round alive well past the 1 s ack deadline, so the
    reassignment is observable)."""
    s = Stack(2, failure_policy="reassign", failure_probe_secs=0.2)
    s.coordinator.handler._call_timeout = 1.0
    try:
        # worker2's Mine handler hangs forever (process alive, TCP open)
        s.workers[1].handler.Mine = lambda params: time.sleep(3600)
        client = s.new_client("client1")
        res = mine_and_wait(client, b"\x67\x68", 5, timeout=120)
        assert puzzle.check_secret(res.nonce, res.secret, 5)
        mines = [a[2]["WorkerByte"] for a in s.sinks["coordinator"].actions()
                 if a[1] == "CoordinatorWorkerMine"]
        assert sorted(mines) == [0, 1, 1]
    finally:
        s.close()


def test_hung_worker_does_not_block_round_start():
    """Head-of-line proof (ISSUE 5 acceptance): with one fully hung
    worker in the fan-out set, the live workers' round must start and
    complete WITHOUT paying the hung worker's ack timeout — the serial
    fan-out used to block `_call_timeout` before the round even began."""
    s = Stack(3, failure_policy="reassign", failure_probe_secs=0.2)
    try:
        hang = lambda params: time.sleep(3600)  # noqa: E731
        s.workers[2].handler.Mine = hang
        s.workers[2].handler.Found = hang
        s.workers[2].handler.Ping = hang
        client = s.new_client("client1")
        t0 = time.monotonic()
        res = mine_and_wait(client, b"\x6c\x6d", 2, timeout=30)
        elapsed = time.monotonic() - t0
        assert puzzle.check_secret(res.nonce, res.secret, 2)
        # default reassign _call_timeout is 10 s; the serial baseline
        # would spend >= one full timeout inside _assign_shards alone.
        # The parallel path's only hung-worker cost is the SHARED Found
        # deadline during the cancel storm, bounded by one timeout — but
        # fanout->first-result must stay flat
        assert elapsed < s.coordinator.handler._call_timeout + 5.0
        evs = [e for e in __import__(
            "distpow_tpu.runtime.telemetry", fromlist=["RECORDER"]
        ).RECORDER.recent() if e["kind"] == "coord.first_result"]
        assert evs, "no first-result event recorded"
        assert evs[-1]["latency_s"] < 2.0, (
            f"fanout->first-result head-of-line blocked: {evs[-1]}"
        )
    finally:
        s.close()


def test_failed_mine_does_not_leak_task_entry():
    """Every exit path out of the miss protocol must release the task
    queue — a flaky cluster must not grow the coordinator task table."""
    s = Stack(1, failure_policy="reassign", failure_probe_secs=0.1)
    try:
        s.workers[0].shutdown()
        client = s.new_client("client1")
        client.mine(b"\x69\x6a", 2)  # all workers dead -> Mine errors
        # the failure surfaces as an error result (VERDICT r1 item 6),
        # not a silent drop that would leave the client blocked forever
        r = client.notify_queue.get(timeout=10.0)
        assert r.secret is None and r.error is not None
        deadline = time.time() + 5
        while s.coordinator.handler._tasks and time.time() < deadline:
            time.sleep(0.05)
        assert s.coordinator.handler._tasks == {}
    finally:
        s.close()


def test_pallas_mesh_worker_serves_through_protocol():
    """A worker with Backend=pallas-mesh (interpret mode off-TPU, the
    PallasInterpret dev knob) serves a full Mine through the real RPC
    protocol — the kernel mesh path integrated at the node layer."""
    s = Stack(1, backend="pallas-mesh",
              worker_extra={"BatchSize": 1 << 13,
                            "PallasInterpret": True,
                            "WarmupNonceLens": [], "WarmupWidths": []})
    try:
        client = s.new_client("client1")
        res = mine_and_wait(client, b"\x6a\x6b", 2, timeout=240)
        assert res.error is None
        assert puzzle.check_secret(res.nonce, res.secret, 2)
    finally:
        s.close()


def test_worker_compilation_cache_dir(tmp_path):
    """CompilationCacheDir persists XLA compiles across boots: after a
    jax-backend solve, the cache directory holds compiled programs."""
    import jax

    cache_dir = str(tmp_path / "xla_cache")
    s = Stack(1, backend="jax",
              worker_extra={"CompilationCacheDir": cache_dir,
                            "BatchSize": 1 << 12,
                            "WarmupNonceLens": [], "WarmupWidths": []})
    try:
        assert jax.config.jax_compilation_cache_dir == cache_dir
        # CPU-mesh compiles are faster than the production 0.5s
        # persistence threshold; persist everything for the assertion
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        # in-suite, earlier tests may have already compiled (and
        # lru-cached) every program this solve needs — the persistent
        # cache only writes on a FRESH compile, so force one
        jax.clear_caches()
        client = s.new_client("client1")
        res = mine_and_wait(client, b"\x5a\x5b", 2)
        assert puzzle.check_secret(res.nonce, res.secret, 2)
        import os
        assert os.path.isdir(cache_dir) and len(os.listdir(cache_dir)) > 0
    finally:
        # the knob is process-global jax config: restore for later tests
        jax.config.update("jax_compilation_cache_dir", None)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        s.close()


def test_orphaned_miner_self_cancels_on_cache_install():
    """A miner whose coordinator abandoned it stops as soon as a
    satisfying secret lands in the worker cache, delivering that secret
    as its result instead of burning the backend forever."""
    import queue as q

    from distpow_tpu.backends import PythonBackend
    from distpow_tpu.nodes.worker import WorkerRPCHandler
    from distpow_tpu.runtime.tracing import MemorySink, Tracer

    tracer = Tracer("workerX", MemorySink())
    rq: "q.Queue" = q.Queue()
    h = WorkerRPCHandler(tracer, rq, PythonBackend())
    trace = tracer.create_trace()
    token = trace.generate_token()
    from distpow_tpu.runtime.tracing import encode_token

    # difficulty 6 on the python backend would take ~hours: the miner
    # must exit via the cache install, not by finding a secret
    h.Mine({"nonce": [9, 9], "num_trailing_zeros": 6, "worker_byte": 0,
            "worker_bits": 0, "token": encode_token(token)})
    time.sleep(0.2)
    secret = b"\x12\x34"  # any value; dominance only needs ntz >= 6
    h.result_cache.add(b"\x09\x09", 6, secret, None)
    res = rq.get(timeout=15)  # result delivered from the cache
    assert bytes(res["secret"]) == secret
    # the finisher is now blocked awaiting Found; deliver it
    h.Found({"nonce": [9, 9], "num_trailing_zeros": 6, "worker_byte": 0,
             "secret": list(secret), "token": encode_token(token)})
    ack = rq.get(timeout=5)
    assert ack["secret"] is None


def test_error_policy_is_reference_parity():
    """Default FailurePolicy="error": worker failure fails the Mine."""
    s = Stack(2)  # default error policy
    try:
        s.workers[1].shutdown()
        client = s.new_client("client1")
        client.mine(b"\x65\x66", 2)
        # powlib surfaces the coordinator-side RPC error; with the
        # busy-retry dial (coordinator.go:169-172) the request never
        # completes — assert no result arrives within a short window
        with pytest.raises(queue.Empty):
            client.notify_queue.get(timeout=1.0)
    finally:
        s.close()


def test_coordinator_cache_resume_across_restart(tmp_path):
    """Checkpoint/resume at the node level: a restarted coordinator
    serves a previously-solved nonce from its journal without re-mining
    (the reference restarts cold, coordinator.go:105-108)."""
    cache_file = str(tmp_path / "coord_cache.jsonl")
    s1 = Stack(1, coord_cache_file=cache_file)
    c1 = s1.new_client("client1")
    r1 = mine_and_wait(c1, b"\x42\x43", 2)
    s1.close()

    s2 = Stack(1, coord_cache_file=cache_file)
    c2 = s2.new_client("client1")
    r2 = mine_and_wait(c2, b"\x42\x43", 2)
    assert r2.secret == r1.secret
    coord = s2.action_names("coordinator")
    # pure cache hit: no fan-out after restart
    assert coord.count("CoordinatorWorkerMine") == 0
    assert "CacheHit" in coord
    s2.close()


def test_dominance_supersede_demo_scenario(stack1):
    # the reference demo's interesting pair: same nonce at difficulty 2
    # then 3 (cmd/client/main.go:46-51 uses 5 then 7) — a cached 2-zeros
    # secret must NOT satisfy the 3-zeros request, whose result then
    # replaces it (coordinator.go:403,436)
    client = stack1.new_client("client1")
    nonce = b"\x02\x02\x02\x02"
    r1 = mine_and_wait(client, nonce, 2)
    r2 = mine_and_wait(client, nonce, 3)
    assert puzzle.check_secret(nonce, r2.secret, 3)
    coord = stack1.action_names("coordinator")
    # second request missed (2 < 3) and re-mined
    assert coord.count("CoordinatorWorkerMine") == 2
    entry = stack1.coordinator.handler.result_cache.peek(nonce)
    assert entry.num_trailing_zeros >= 3
    # the lower-difficulty entry was removed in favor of the higher one
    assert "CacheRemove" in coord


def test_two_clients_concurrent_demo(stack4):
    # the reference's built-in smoke scenario: two clients, four requests,
    # including a repeated nonce at increasing difficulty
    # (cmd/client/main.go:40-60)
    c1 = stack4.new_client("client1")
    c2 = stack4.new_client("client2")
    c1.mine(b"\x01\x02\x03\x04", 3)
    c1.mine(b"\x05\x06\x07\x08", 2)
    c2.mine(b"\x02\x02\x02\x02", 2)
    c2.mine(b"\x02\x02\x02\x02", 3)

    results = []
    for _ in range(4):
        got = None
        for c in (c1, c2):
            try:
                got = c.notify_queue.get(timeout=0.05)
                break
            except queue.Empty:
                continue
        if got is None:
            time.sleep(0.1)
            continue
        results.append(got)
    deadline = time.time() + 60
    while len(results) < 4 and time.time() < deadline:
        for c in (c1, c2):
            try:
                results.append(c.notify_queue.get(timeout=0.2))
            except queue.Empty:
                pass
    assert len(results) == 4
    for r in results:
        assert puzzle.check_secret(r.nonce, r.secret, r.num_trailing_zeros)


def test_late_result_rebroadcast_via_warm_caches(stack4):
    # Warm every worker cache, then issue the same puzzle again: all four
    # workers answer from cache immediately -> one winner + three late
    # results -> the coordinator re-broadcasts Found per late result and
    # drains N acks each (coordinator.go:237-280)
    client = stack4.new_client("client1")
    nonce = b"\x31\x32"
    mine_and_wait(client, nonce, 2)
    time.sleep(0.3)

    # clear the coordinator cache so the request fans out again, but keep
    # worker caches warm
    stack4.coordinator.handler.result_cache._entries.clear()
    res = mine_and_wait(client, nonce, 2)
    assert puzzle.check_secret(nonce, res.secret, 2)
    coord = stack4.action_names("coordinator")
    # at least one late CoordinatorWorkerResult beyond the winner
    assert coord.count("CoordinatorWorkerResult") >= 2
    # re-broadcast rounds: cancels are a multiple of 4 and > 4
    assert coord.count("CoordinatorWorkerCancel") % 4 == 0
    assert coord.count("CoordinatorWorkerCancel") > 4
    # ledger completed: follow-up request still works
    res3 = mine_and_wait(client, b"\x41\x42", 2)
    assert puzzle.check_secret(b"\x41\x42", res3.secret, 2)


def test_duplicate_concurrent_mine_same_key(stack1):
    # documented fix for coordinator.go:376-381: two concurrent Mine
    # requests for the same (nonce, zeros) must both complete
    client = stack1.new_client("client1")
    nonce = b"\x51\x52"
    client.mine(nonce, 3)
    client.mine(nonce, 3)
    r1 = client.notify_queue.get(timeout=60)
    r2 = client.notify_queue.get(timeout=60)
    for r in (r1, r2):
        assert puzzle.check_secret(nonce, r.secret, 3)


def test_worker_cache_hit_path_trace(stack1):
    client = stack1.new_client("client1")
    nonce = b"\x61\x62"
    mine_and_wait(client, nonce, 2)
    time.sleep(0.2)
    # clear coordinator cache; worker cache stays warm -> miner cache-hit
    # path (worker.go:260-299): CacheHit then WorkerResult then WorkerCancel
    stack1.coordinator.handler.result_cache._entries.clear()
    mine_and_wait(client, nonce, 2)
    wk = stack1.action_names("worker1")
    hit = wk.index("CacheHit")
    assert "WorkerResult" in wk[hit:]
    assert wk[-1] == "WorkerCancel"


def test_trace_tokens_cross_all_nodes(stack1):
    # one request's trace id must appear at client, coordinator, and worker
    client = stack1.new_client("client1")
    mine_and_wait(client, b"\x71\x72", 2)
    tid = {e["trace_id"] for e in stack1.sinks["client1"].events
           if e["type"] == "action"}
    assert len(tid) == 1
    tid = tid.pop()
    coord_tids = {e["trace_id"] for e in stack1.sinks["coordinator"].events
                  if e["type"] == "action"}
    worker_tids = {e["trace_id"] for e in stack1.sinks["worker1"].events
                   if e["type"] == "action"}
    assert tid in coord_tids and tid in worker_tids


def test_superseded_miner_exits_silently():
    """A repeat Mine for a key whose previous round is still running must
    cancel the zombie miner WITHOUT it emitting nil ACKs — those would be
    routed into the new round's coordinator queue (keyed (nonce, ntz)) and
    either trip the first-message protocol check or drain its ack ledger
    early (ADVICE r1: worker task-table overwrite)."""
    import queue as q

    from distpow_tpu.backends import PythonBackend
    from distpow_tpu.nodes.worker import WorkerRPCHandler
    from distpow_tpu.runtime.tracing import MemorySink, Tracer, encode_token

    tracer = Tracer("workerY", MemorySink())
    rq: "q.Queue" = q.Queue()
    h = WorkerRPCHandler(tracer, rq, PythonBackend())
    token = encode_token(tracer.create_trace().generate_token())

    # round 1: difficulty 10 on the python backend never finishes on its own
    h.Mine({"nonce": [7, 7], "num_trailing_zeros": 10, "worker_byte": 0,
            "worker_bits": 0, "token": token})
    time.sleep(0.2)
    # round 2: same key replaces round 1; its zombie must exit silently
    h.Mine({"nonce": [7, 7], "num_trailing_zeros": 10, "worker_byte": 0,
            "worker_bits": 0, "token": token})
    time.sleep(0.5)
    assert rq.empty(), "superseded miner leaked a message into the queue"

    # the NEW round still works: a cache install stops it and it delivers
    secret = b"\x12\x34"
    h.result_cache.add(b"\x07\x07", 10, secret, None)
    res = rq.get(timeout=15)
    assert bytes(res["secret"]) == secret
    h.Found({"nonce": [7, 7], "num_trailing_zeros": 10, "worker_byte": 0,
             "secret": list(secret), "token": token})
    ack = rq.get(timeout=5)
    assert ack["secret"] is None
    # and nothing further arrives from either round
    time.sleep(0.3)
    assert rq.empty()


def test_round_ids_survive_backward_clock_restart(tmp_path, monkeypatch):
    """A coordinator restart under a spoofed BACKWARD clock step (larger
    than the downtime) must still order new round ids after old ones —
    the persisted restart epoch, not the wall clock, carries the ordering
    (VERDICT r2 weak #6) — and the worker's zombie-vs-live resolution
    (worker.py _task_take) must therefore pop the zombie, not the live
    round."""
    from distpow_tpu.nodes import coordinator as coord_mod
    from distpow_tpu.nodes.worker import TaskRound, WorkerRPCHandler
    from distpow_tpu.runtime.tracing import Tracer

    epoch_path = str(tmp_path / "cache.jsonl.epoch")

    # boot 1, normal clock: a round goes out and its cancel is lost
    e1 = coord_mod.load_restart_epoch(epoch_path)
    rid_zombie = coord_mod.new_round_id(e1)

    # boot 2: the clock has stepped WAY back (before boot) and the fresh
    # process has no in-memory monotonic floor; the persisted epoch must
    # still strictly increase
    monkeypatch.setattr(coord_mod.time, "time", lambda: 1.0)
    monkeypatch.setattr(coord_mod.time, "time_ns", lambda: 1_000)
    monkeypatch.setattr(coord_mod, "_last_round_ns", [0])
    e2 = coord_mod.load_restart_epoch(epoch_path)
    assert e2 > e1
    rid_live = coord_mod.new_round_id(e2)
    assert rid_live > rid_zombie  # epoch dominates the backward clock

    # worker side: a Found tagged with the NEW round id against a zombie
    # entry from the old round pops + supersedes the zombie...
    handler = WorkerRPCHandler(
        Tracer("worker1", MemorySink()), queue.Queue(), backend=None
    )
    key = (b"\x01", 2)
    zombie = TaskRound(rid_zombie)
    handler._task_set(key, zombie)
    assert handler._task_take(key, rid_live) is None
    assert zombie.superseded and zombie.ev.is_set()
    # ...while a stale Found tagged with the OLD id must not disturb the
    # live round
    live = TaskRound(rid_live)
    handler._task_set(key, live)
    assert handler._task_take(key, rid_zombie) is None
    assert not live.superseded
    assert handler._task_get(key) is live

    # mixed-format window: a pre-epoch 16-char id (bare time_ns hex)
    # held by a long-lived worker must order BELOW any epoch-prefixed id
    # (worker.py _rid_order pads it as epoch 0)
    old_format = f"{123_456_789_000:016x}"
    legacy = TaskRound(old_format)
    handler._task_set(key, legacy)
    assert handler._task_take(key, rid_live) is None
    assert legacy.superseded


@pytest.mark.slow
def test_coordinator_restart_mid_mine(tmp_path):
    """Fault injection (VERDICT r1 items 5+6): the coordinator dies while
    a worker is mining and comes back on the same ports.  The client must
    OBSERVE the failure (error result, not a silent hang), the worker's
    forwarder must re-dial and deliver its result to the restarted
    coordinator (journal-backed cache), and a client retry must complete."""
    from distpow_tpu.nodes import Coordinator
    from distpow_tpu.runtime.config import CoordinatorConfig

    cache_file = str(tmp_path / "coord_cache.jsonl")
    s = Stack(1, coord_cache_file=cache_file)
    try:
        client = s.new_client("client1")
        nonce = b"\x77\x78"
        # difficulty 5 ~= 1M candidates on the python backend: seconds of
        # mining, plenty of window to kill the coordinator mid-search
        client.mine(nonce, 5)
        time.sleep(0.6)  # fan-out done, worker mining

        old_client_addr = s.coordinator.client_addr
        old_worker_addr = s.coordinator.worker_addr
        worker_addrs = [w.bound_addr for w in s.workers]
        s.coordinator.shutdown()

        # the in-flight Mine must surface as an error result
        r = client.notify_queue.get(timeout=30)
        assert r.error is not None and r.secret is None

        # restart on the same ports (create_server sets SO_REUSEADDR);
        # retry briefly — the worker's re-dial loop targeting this very
        # port can transiently occupy it via a Linux self-connect
        for attempt in range(40):
            try:
                s.coordinator = Coordinator(
                    CoordinatorConfig(
                        ClientAPIListenAddr=old_client_addr,
                        WorkerAPIListenAddr=old_worker_addr,
                        Workers=worker_addrs,
                        CacheFile=cache_file,
                    ),
                    sink=s.sinks["coordinator"],
                )
                s.coordinator.initialize_rpcs()
                break
            except OSError:
                # a half-bound server (first listener ok, second raced)
                # must release its port before the retry
                with contextlib.suppress(Exception):
                    s.coordinator.shutdown()
                if attempt == 39:
                    raise
                time.sleep(0.25)

        # the worker finishes its (never-cancelled) search and the
        # forwarder re-delivers to the restarted coordinator, landing the
        # secret in its journal-backed cache; the retried request then
        # completes (usually as a pure cache hit)
        client2 = s.new_client("client1-retry")
        res = mine_and_wait(client2, nonce, 5, timeout=120)
        assert res.error is None
        assert puzzle.check_secret(nonce, res.secret, 5)
    finally:
        s.close()


def test_round_ids_survive_corrupt_epoch_file(tmp_path, monkeypatch):
    """Epoch durability (VERDICT r3 item 9): a corrupt PRIMARY epoch
    file — torn write, bit rot, truncation to a parseable-but-tiny int —
    must be detected (checksum) and recovered from the .bak replica,
    under a spoofed backward clock so any silent wall-clock fallback
    would order wrong; the worker's zombie-vs-live resolution must
    still pop the zombie."""
    from distpow_tpu.nodes import coordinator as coord_mod
    from distpow_tpu.nodes.worker import TaskRound, WorkerRPCHandler
    from distpow_tpu.runtime.tracing import Tracer

    epoch_path = str(tmp_path / "cache.jsonl.epoch")

    e1 = coord_mod.load_restart_epoch(epoch_path)
    rid_zombie = coord_mod.new_round_id(e1)

    # legacy (pre-checksum) bare-int files must still be accepted
    assert coord_mod._read_epoch_file(epoch_path) == e1
    with open(epoch_path, "w") as fh:
        fh.write(str(e1))
    assert coord_mod._read_epoch_file(epoch_path) == e1

    # corrupt the primary four ways; each must be REJECTED, not parsed
    for garbage in ("17 deadbeef",            # checksum mismatch
                    "not-a-number",           # unparseable
                    str(e1)[:2],              # truncated past the separator:
                                              # bare "17" parses as int but
                                              # sits below the wall-clock
                                              # floor every legacy write had
                    str(e1)[:2] + " bogus"):  # truncated value + junk crc
        with open(epoch_path, "w") as fh:
            fh.write(garbage)
        assert coord_mod._read_epoch_file(epoch_path) is None

    # restart under a backward-stepped clock: recovery must come from
    # the .bak replica, not the clock
    monkeypatch.setattr(coord_mod.time, "time", lambda: 1.0)
    monkeypatch.setattr(coord_mod.time, "time_ns", lambda: 1_000)
    monkeypatch.setattr(coord_mod, "_last_round_ns", [0])
    e2 = coord_mod.load_restart_epoch(epoch_path)
    assert e2 > e1
    rid_live = coord_mod.new_round_id(e2)
    assert rid_live > rid_zombie

    # both replicas corrupt -> loud wall-clock fallback, still functional
    for p in (epoch_path, epoch_path + ".bak"):
        with open(p, "w") as fh:
            fh.write("zz zz")
    e3 = coord_mod.load_restart_epoch(epoch_path)
    assert isinstance(e3, int)
    # and the rewrite healed both replicas (checksummed)
    assert coord_mod._read_epoch_file(epoch_path) == e3
    assert coord_mod._read_epoch_file(epoch_path + ".bak") == e3

    # zombie-vs-live at the worker with the recovered ordering
    handler = WorkerRPCHandler(
        Tracer("worker1", MemorySink()), queue.Queue(), backend=None
    )
    key = (b"\x01", 2)
    zombie = TaskRound(rid_zombie)
    handler._task_set(key, zombie)
    assert handler._task_take(key, rid_live) is None
    assert zombie.superseded and zombie.ev.is_set()


def test_worker_restart_rejoins_service():
    """The full worker recovery cycle (round 4): a dead worker's shard
    is reassigned (requests keep completing), and a REPLACEMENT worker
    booted on the same configured address rejoins fan-out with no
    coordinator change — the reference's static worker list + lazy
    redial contract (coordinator.go:169-172,356-368), which reassign
    must not break."""
    import contextlib

    from distpow_tpu.nodes.worker import Worker
    from distpow_tpu.runtime.config import WorkerConfig

    s = Stack(2, failure_policy="reassign", failure_probe_secs=0.2)
    try:
        dead_addr = s.workers[1].bound_addr
        coord_worker_addr = s.workers[1].config.CoordAddr
        s.workers[1].shutdown()

        client = s.new_client("client1")
        res = mine_and_wait(client, b"\x71\x72", 2, timeout=30)
        assert puzzle.check_secret(res.nonce, res.secret, 2)

        # replacement on the SAME address (retry: the coordinator's
        # redial loop can transiently self-connect the freed port)
        s.sinks["worker2b"] = MemorySink()
        for attempt in range(40):
            try:
                w2b = Worker(
                    WorkerConfig(
                        WorkerID="worker2b",
                        ListenAddr=dead_addr,
                        CoordAddr=coord_worker_addr,
                        Backend="python",
                    ),
                    sink=s.sinks["worker2b"],
                )
                w2b.initialize_rpcs()
                break
            except OSError:
                with contextlib.suppress(Exception):
                    w2b.shutdown()
                time.sleep(0.25)
        else:
            raise AssertionError("could not rebind the dead worker's port")
        w2b.start_forwarder()
        s.workers.append(w2b)  # Stack.close() tears it down

        # a FRESH nonce fans out to the replacement and completes
        res2 = mine_and_wait(client, b"\x73\x74", 2, timeout=30)
        assert puzzle.check_secret(res2.nonce, res2.secret, 2)
        deadline = time.time() + 10
        while time.time() < deadline and not any(
            a[1] == "WorkerMine" for a in s.sinks["worker2b"].actions()
        ):
            time.sleep(0.05)
        assert any(a[1] == "WorkerMine"
                   for a in s.sinks["worker2b"].actions()), \
            "replacement worker never participated in fan-out"
    finally:
        s.close()


def test_hung_worker_with_long_round_completes_after_reap():
    """Review PR 5 regression (reproduced pre-fix): with one fully hung
    worker and a round outliving the ~2 s ping timeout, _reap_dead
    kills the worker (closing its client — which fails the pending
    parallel Mine-ack future) and reassigns its shard; when
    _harvest_inflight later resolves that failed future it must NOT
    re-orphan the shard — the duplicate (worker, shard) task entry owed
    the 2N-ack ledger acks the worker could never send, spinning the
    drain loop forever and hanging the client's Mine RPC."""
    from distpow_tpu.models import puzzle as pz

    class SlowFinder:
        """Holds the round open past the reap window, honoring cancel."""

        def search(self, nonce, difficulty, thread_bytes, cancel_check=None):
            deadline = time.monotonic() + 6.0
            while time.monotonic() < deadline:
                if cancel_check and cancel_check():
                    return None
                time.sleep(0.1)
            return pz.python_search(nonce, difficulty, thread_bytes)

    s = Stack(3, failure_policy="reassign", failure_probe_secs=0.2)
    # ack deadline AFTER the ping-based reap (~2.2 s): the reap must win
    # the race so the harvest sees an already-reassigned shard
    s.coordinator.handler._call_timeout = 4.0
    try:
        hang = lambda params: time.sleep(3600)  # noqa: E731
        s.workers[2].handler.Mine = hang
        s.workers[2].handler.Found = hang
        s.workers[2].handler.Ping = hang
        for w in s.workers[:2]:
            w.handler.backend = SlowFinder()
        client = s.new_client("client1")
        res = mine_and_wait(client, b"\x6e\x6f", 2, timeout=60)
        assert puzzle.check_secret(res.nonce, res.secret, 2)
        mines = [a[2]["WorkerByte"] for a in s.sinks["coordinator"].actions()
                 if a[1] == "CoordinatorWorkerMine"]
        # shard 2: the initial issue + exactly ONE reassignment — a
        # second (harvest-driven) reissue is the ledger-corrupting bug
        assert mines.count(2) == 2, mines
    finally:
        s.close()


def test_backend_auto_resolves_from_hardware():
    """``Backend: "auto"`` resolves to the measured-best backend for
    the hardware at boot (backends/get_backend): on this CPU test mesh
    (8 virtual devices, conftest) that is the jax-mesh backend — on a
    TPU it would be the pallas kernels — and the resolved backend must
    actually serve."""
    import jax

    from distpow_tpu.backends import (
        JaxBackend,
        JaxMeshBackend,
        get_backend,
    )

    backend = get_backend("auto", hash_model="md5", batch_size=1 << 13)
    expected = JaxMeshBackend if len(jax.devices()) > 1 else JaxBackend
    assert isinstance(backend, expected), type(backend)
    secret = backend.search(b"\x61\x62", 2, list(range(256)))
    assert secret == puzzle.python_search(b"\x61\x62", 2, list(range(256)))
