"""Shared designated-finder stub for the fleet membership suites
(tests/test_fleet.py, scripts/fleet_smoke.py).

``bench.py --membership`` keeps its own inline twin deliberately — the
bench defines one stub per stage next to the measurement it shapes
(the ``control_plane_stage`` idiom) and must stay importable with no
test-tree dependency.
"""

import time

from distpow_tpu.models import puzzle


class ShardGatedBackend:
    """Solves only when its shard contains first-byte 0 (after an
    optional, cancellation-aware delay); honors cancellation otherwise.
    ``frozen`` wedges the miner — NOT the RPC surface — until released,
    the alive-but-stuck straggler probes cannot see."""

    def __init__(self, solve_delay_s=0.0, frozen=False):
        self.solve_delay_s = solve_delay_s
        self.frozen = frozen

    def search(self, nonce, difficulty, thread_bytes, cancel_check=None):
        while self.frozen and not (cancel_check and cancel_check()):
            time.sleep(0.01)
        if 0 in thread_bytes and not (cancel_check and cancel_check()):
            deadline = time.monotonic() + self.solve_delay_s
            while time.monotonic() < deadline:
                if cancel_check and cancel_check():
                    return None
                time.sleep(0.01)
            return puzzle.python_search(nonce, difficulty, thread_bytes)
        while not (cancel_check and cancel_check()):
            time.sleep(0.01)
        return None
