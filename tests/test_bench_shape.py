"""bench.py's outage-shaping logic: the anomaly screen and stage order.

VERDICT r4 item 1: a degraded-tunnel transient must never silently
replace provenance (the ``sha3_256-serving: 0.9`` case), and the stage
order must put every model's production path ahead of the diagnostic
XLA serving lines so a mid-run tunnel death costs only the tail.

These tests import bench.py as a module — its module level is
deliberately jax-free, so they run anywhere.
"""

from __future__ import annotations

import importlib.util
import os
import sys

import pytest

_BENCH = os.path.join(os.path.dirname(__file__), os.pardir, "bench.py")


@pytest.fixture(scope="module")
def bench():
    spec = importlib.util.spec_from_file_location("bench_module", _BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


LAST = {"rates_mhs": {"serving": 9766.8, "sha3_256-serving": 6.3,
                      "blake2b_256-pallas": 974.9}}


def test_screen_accepts_normal_readings(bench):
    accepted, suspect = bench.screen_rates(
        {"serving": 9900.0, "sha3_256-serving": 6.0}, LAST
    )
    assert suspect == {}
    assert accepted == {"serving": 9900.0, "sha3_256-serving": 6.0}


def test_screen_flags_degraded_low_reading(bench):
    # the bench7 case: sha3 serving measured 0.85 MH/s on a dying
    # tunnel vs 6.3 measured same-day — >3x low is suspect, provenance
    # keeps the previous value, the reading is recorded with context
    accepted, suspect = bench.screen_rates({"sha3_256-serving": 0.85}, LAST)
    assert accepted["sha3_256-serving"] == 6.3
    info = suspect["sha3_256-serving"]
    assert info["measured_mhs"] == 0.85
    assert info["last_measured_mhs"] == 6.3
    assert info["ratio"] < 1 / 3


def test_screen_flags_inflated_high_reading(bench):
    # sync-artifact inflation (the block_until_ready failure mode) is
    # equally suspect in the other direction
    accepted, suspect = bench.screen_rates(
        {"blake2b_256-pallas": 974.9 * 5}, LAST
    )
    assert accepted["blake2b_256-pallas"] == 974.9
    assert suspect["blake2b_256-pallas"]["ratio"] > 3


def test_screen_boundary_is_exactly_3x(bench):
    # 3.0x exactly is NOT suspect (tolerance is strict inequality);
    # just over is
    accepted, suspect = bench.screen_rates({"serving": 9766.8 * 3}, LAST)
    assert suspect == {}
    _, suspect = bench.screen_rates({"serving": 9766.8 * 3.01}, LAST)
    assert "serving" in suspect


def test_screen_new_stage_without_history_is_accepted(bench):
    # a stage with no previous measurement (a new model's first bench
    # line) cannot be screened; it enters provenance as measured
    accepted, suspect = bench.screen_rates({"blake2b_256-serving": 16.0}, LAST)
    assert suspect == {}
    assert accepted["blake2b_256-serving"] == 16.0


def test_screen_without_any_last_measured(bench):
    accepted, suspect = bench.screen_rates({"serving": 123.4}, None)
    assert suspect == {}
    assert accepted == {"serving": 123.4}


def test_screen_override_env(bench, monkeypatch):
    monkeypatch.setenv("BENCH_ACCEPT_ANOMALIES", "1")
    accepted, suspect = bench.screen_rates({"sha3_256-serving": 0.85}, LAST)
    assert suspect == {}
    assert accepted["sha3_256-serving"] == 0.85


LAST_FULL = {
    "value": 10089.2, "vs_baseline": 1830.3,
    "rates_mhs": {"serving": 9766.8, "xla-static": 10089.2,
                  "pallas": 9951.4, "sha1-pallas": 4368.4,
                  "blake2b_256-pallas": 974.9},
}


def test_finalize_headline_selected_on_screened_values(bench):
    """An inflated suspect reading can't steal the headline path: the
    selection runs on screened values, so a healthy serving measurement
    from the same run wins over a 9x-inflated pallas artifact."""
    rates_hs = {"serving": 9800.0e6, "xla-static": 9700.0e6,
                "pallas": 90_000.0e6}
    line, prov = bench.finalize_record(rates_hs, LAST_FULL, 5.35e6)
    assert "serving path" in line["metric"]
    assert line["value"] == 9800.0
    assert "pallas" in line["suspect_readings"]
    # provenance: pallas keeps its previous standing, serving is fresh
    assert prov["rates_mhs"]["pallas"] == 9951.4
    assert prov["rates_mhs"]["serving"] == 9800.0
    assert prov["value"] == 9800.0


def test_finalize_deflated_suspect_cannot_win_selection(bench):
    """Symmetric to the inflation case: a transiently-degraded serving
    reading must not keep the headline via its stale-high screened
    value when another md5 path measured clean in the same run."""
    rates_hs = {"serving": 80.0e6, "xla-static": 9700.0e6}
    line, prov = bench.finalize_record(rates_hs, LAST_FULL, 5.35e6)
    assert "xla-static path" in line["metric"]
    assert line["value"] == 9700.0
    assert "serving" in line["suspect_readings"]
    assert prov["rates_mhs"]["serving"] == 9766.8  # carried standing


def test_finalize_suspect_headline_protects_provenance(bench):
    """All md5 readings degraded (transient window): stdout stays the
    honest measurement, flagged; provenance keeps the previous
    standing for value, vs_baseline, and rates."""
    rates_hs = {"serving": 80.0e6, "xla-static": 82.0e6}
    line, prov = bench.finalize_record(rates_hs, LAST_FULL, 5.35e6)
    assert "suspect" in line["metric"]
    assert line["value"] in (80.0, 82.0)
    # line rates stay the honest measurements (flagged via
    # suspect_readings); the screened standing lives in provenance
    assert line["rates_mhs"]["serving"] == 80.0
    assert prov["rates_mhs"]["serving"] == 9766.8
    assert prov["value"] == prov["rates_mhs"][
        "serving" if "serving path" in line["metric"] else "xla-static"]
    # provenance headline = previous standing, not the degraded reading
    assert prov["value"] > 9000
    assert prov["vs_baseline"] > 1000


def test_finalize_carried_forward_is_explicit(bench):
    """Stages not measured this run are merged from the previous
    provenance under an explicit marker — stale vs fresh stays
    distinguishable under the new date/run_id."""
    rates_hs = {"serving": 9800.0e6, "pallas": 9900.0e6}
    line, prov = bench.finalize_record(rates_hs, LAST_FULL, 5.35e6)
    assert prov["rates_mhs"]["sha1-pallas"] == 4368.4
    assert prov["rates_mhs"]["blake2b_256-pallas"] == 974.9
    assert set(prov["carried_forward"]) == {"xla-static", "sha1-pallas",
                                            "blake2b_256-pallas"}
    # the stdout line never carries stale rates at all: only the stages
    # measured THIS run appear in its rates_mhs (the round artifact the
    # driver records), with their honest values
    assert "carried_forward" not in line
    assert set(line["rates_mhs"]) == {"serving", "pallas"}
    assert line["rates_mhs"]["serving"] == 9800.0


def test_finalize_bailout_note_and_no_baseline(bench):
    """The hang-bailout shape: note lands in metric + provenance, and
    with no baseline measured this run vs_baseline derives from the
    provenance file's own ratio."""
    rates_hs = {"serving": 9766.8e6}
    line, prov = bench.finalize_record(
        rates_hs, LAST_FULL, None, note="device hung during later stages"
    )
    assert "device hung" in line["metric"]
    assert prov["note"] == "device hung during later stages"
    # baseline MH/s from provenance = 10089.2/1830.3 = 5.513; measured
    # 9766.8 / 5.513 = 1771.6
    assert 1700 < line["vs_baseline"] < 1850


def test_finalize_no_history(bench):
    line, prov = bench.finalize_record(
        {"serving": 100.0e6}, None, 5.0e6
    )
    assert line["value"] == 100.0
    assert line["vs_baseline"] == 20.0
    assert "suspect_readings" not in line
    assert "carried_forward" not in prov


def test_stage_order_production_before_diagnostics(bench):
    """Source-order invariant: every production pallas line is emitted
    before any non-md5 serving diagnostic, and the HBM-bound serving
    lines come first within the diagnostics (they are this round's
    reconciliation targets and the cheapest)."""
    src = open(_BENCH).read()
    phase_b = src.index("Phase B")
    phase_e = src.index("Phase E")
    assert phase_b < src.index("Phase C") < src.index("Phase D") < phase_e
    # blake2b is in the production set and in the HBM-bound serving set
    assert "blake2b_256" in bench.OTHER_MODELS
    assert "blake2b_256" in bench.HBM_BOUND_SERVING
    assert "sha3_256" in bench.HBM_BOUND_SERVING
    # sha256d rides Phase E right after the capped HBM lines: its
    # first serving compile is the only unknown-cost one, so it must
    # run while the deadline still admits it (and its grace-expiry
    # path is the salvaging hang bailout, not a lost run)
    assert "sha256d" in bench.OTHER_MODELS
    assert '("sha256d",)' in src[phase_e:]
    # sha512/sha384 serving stays impossible-by-construction
    from distpow_tpu.ops.search_step import XLA_SERVING_COMPILE_IMPRACTICAL

    assert {"sha512", "sha384"} <= set(XLA_SERVING_COMPILE_IMPRACTICAL)


CP = {
    "delay_ms": 40.0, "rounds": 8, "ntz": 1,
    "fanout": {"n8": {"serial": {"p50_ms": 300.0, "p95_ms": 376.6},
                      "parallel": {"p50_ms": 8.0, "p95_ms": 10.4}}},
    "cancel": {"n8": {"serial": {"p50_ms": 700.0, "p95_ms": 744.7},
                      "parallel": {"p50_ms": 60.0, "p95_ms": 67.8}}},
    "speedup": {"cancel_p95_n8": 10.98, "first_p95_n8": 36.12},
    "codec": {"shrink": 4.12},
}


def test_finalize_attaches_control_plane_row(bench):
    """The control-plane stage rides both artifacts of a normal run:
    the stdout line (the driver's BENCH record) and provenance."""
    line, prov = bench.finalize_record(
        {"serving": 9800.0e6}, LAST_FULL, 5.35e6, control_plane=CP
    )
    assert line["control_plane"] == CP
    assert prov["control_plane"] == CP
    assert line["unit"] == "MH/s"  # headline stays the kernel rate


def test_finalize_control_plane_only_run(bench):
    """bench.py --control-plane (or a device-unreachable round): the
    line becomes the tunnel-independent perf row, and kernel provenance
    is NOT re-stamped (prov None)."""
    line, prov = bench.finalize_record({}, LAST_FULL, None, control_plane=CP)
    assert prov is None
    assert line["unit"] == "ms"
    assert line["value"] == 67.8  # cancel p95, 8 workers, parallel
    assert line["vs_baseline"] == 10.98  # serial/parallel speedup
    assert line["control_plane"] == CP


def test_finalize_carries_forward_control_plane(bench):
    """A later kernel-only run must not silently drop the provenance's
    standing control-plane row."""
    lm = dict(LAST_FULL, control_plane=CP)
    line, prov = bench.finalize_record({"serving": 9800.0e6}, lm, 5.35e6)
    assert prov["control_plane"] == CP
    assert "control_plane" not in line  # not measured this run


@pytest.mark.slow
def test_control_plane_stage_meets_acceptance(bench):
    """Live acceptance check (ISSUE 5): cancel fanout->last-ack p95 at
    8 workers improves >= 3x over the serial baseline, binary frames
    shrink the round's payload >= 2x, and a hung worker adds nothing
    like the ack deadline to fanout->first-result."""
    cp = bench.control_plane_stage(ns=(8,), rounds=6)
    assert cp["speedup"]["cancel_p95_n8"] >= 3.0, cp["speedup"]
    assert cp["codec"]["shrink"] >= 2.0, cp["codec"]
    hung = cp["hung_worker"]
    assert hung["first_p95_ms"] < hung["call_timeout_s"] * 1e3 / 2, hung


def test_module_level_is_jax_free(bench):
    """The device-unreachable fast path must not import jax at module
    level (the probe runs in a subprocess; a hung backend would wedge
    the parent import otherwise)."""
    src = open(_BENCH).read()
    head = src[: src.index("def device_rate")]
    assert "import jax" not in head


SL = {
    "ntz": 4, "solves": 4,
    "syncs_per_solve": {"serial": 13.5, "persistent": 0.0},
    "syncs_reduction_x": 54.0,
    "launches_per_solve": {"serial": 14.25, "persistent": 14.25},
    "mixed_hash": {"models": ["md5", "sha1"], "requests": 8,
                   "solo_launches": 35, "batched_launches": 9,
                   "mean_occupancy": 3.89, "mixed_hash_launches": 6},
}


def test_finalize_attaches_serving_loop_row(bench):
    """The serving-loop stage (ISSUE 6) rides both artifacts of a
    normal run, exactly like the control-plane row."""
    line, prov = bench.finalize_record(
        {"serving": 9800.0e6}, LAST_FULL, 5.35e6, serving_loop=SL
    )
    assert line["serving_loop"] == SL
    assert prov["serving_loop"] == SL
    assert line["unit"] == "MH/s"


def test_finalize_serving_loop_only_run(bench):
    """bench.py --serving-loop: the line becomes the syncs-per-solve
    perf row and kernel provenance is NOT re-stamped."""
    line, prov = bench.finalize_record({}, LAST_FULL, None, serving_loop=SL)
    assert prov is None
    assert line["unit"] == "x"
    assert line["value"] == 54.0
    assert line["serving_loop"] == SL


def test_finalize_carries_forward_serving_loop(bench):
    lm = dict(LAST_FULL, serving_loop=SL)
    line, prov = bench.finalize_record({"serving": 9800.0e6}, lm, 5.35e6)
    assert prov["serving_loop"] == SL
    assert "serving_loop" not in line


LAST_SUSPECT = dict(
    LAST_FULL,
    rates_mhs=dict(LAST_FULL["rates_mhs"], **{"sha3_256-serving": 6.3}),
    suspect_readings={"sha3_256-serving": {
        "measured_mhs": 0.85, "last_measured_mhs": 6.3, "ratio": 0.135}},
)


def test_finalize_pending_suspect_rows_stay_annotated(bench):
    """ISSUE 6: a provenance row whose last reading was screened out
    must stay visibly suspect — in suspect_readings AND suspect_rows —
    until a run re-measures it clean, instead of silently carrying the
    previous value forward."""
    line, prov = bench.finalize_record(
        {"serving": 9800.0e6}, LAST_SUSPECT, 5.35e6
    )
    assert prov["rates_mhs"]["sha3_256-serving"] == 6.3  # carried value
    assert "sha3_256-serving" in prov["suspect_readings"]
    assert prov["suspect_rows"] == ["sha3_256-serving"]
    assert line["suspect_rows"] == ["sha3_256-serving"]


def test_finalize_clean_remeasure_clears_suspect_flag(bench):
    """A clean re-measurement of the suspect stage retires the flag:
    the fresh value replaces the standing and no annotation remains."""
    line, prov = bench.finalize_record(
        {"serving": 9800.0e6, "sha3_256-serving": 6.1e6},
        LAST_SUSPECT, 5.35e6,
    )
    assert prov["rates_mhs"]["sha3_256-serving"] == 6.1
    assert "suspect_readings" not in prov
    assert "suspect_rows" not in prov and "suspect_rows" not in line


def test_finalize_re_suspect_remeasure_keeps_flag(bench):
    """A re-measurement that the screen rejects AGAIN keeps the row
    annotated with the fresh context."""
    line, prov = bench.finalize_record(
        {"serving": 9800.0e6, "sha3_256-serving": 0.9e6},
        LAST_SUSPECT, 5.35e6,
    )
    assert prov["rates_mhs"]["sha3_256-serving"] == 6.3
    assert prov["suspect_readings"]["sha3_256-serving"]["measured_mhs"] \
        == 0.9
    assert prov["suspect_rows"] == ["sha3_256-serving"]


# -- empty-md5 pool guard (advisor r5 low #3; regression test ISSUE 8) -------

def test_finalize_empty_rates_returns_device_hung_line(bench):
    """finalize_record with NO md5 label must return the device-hung
    shape instead of raising ValueError on max() over an empty pool —
    main()'s final call must not rely on an earlier stage crashing
    first.  Provenance stays None: a run that measured no md5 stage
    must not re-stamp last_measured.json."""
    line, prov = bench.finalize_record({}, LAST_FULL, None)
    assert prov is None
    assert "device hung" in line["metric"]
    assert line["value"] == 0.0 and line["unit"] == "MH/s"


def test_finalize_non_md5_rates_returns_device_hung_line_with_rates(bench):
    """Diagnostic-only measurements (the device died before any md5
    stage) still ride the hung line's rates_mhs — measured evidence is
    never dropped — but the headline stays the hung shape."""
    line, prov = bench.finalize_record(
        {"sha3_256-serving": 6.1e6}, LAST_FULL, None,
        note="died before phase A",
    )
    assert prov is None
    assert "device hung" in line["metric"]
    assert line["rates_mhs"] == {"sha3_256-serving": 6.1}
    assert line["note"] == "died before phase A"


# -- load-slo row (ISSUE 8) --------------------------------------------------

LS = {
    "slo_config": "config/slo.json", "duration_s": 5.0, "ok": True,
    "rates": {
        "r6": {"target_hz": 6.0, "achieved_solves_per_s": 6.4,
               "merged_miss_p95_ms": 119.2, "verdict": "pass",
               "oracle_within_bucket": True},
        "r12": {"target_hz": 12.0, "achieved_solves_per_s": 11.5,
                "merged_miss_p95_ms": 433.6, "verdict": "pass",
                "oracle_within_bucket": True},
    },
}


def test_finalize_attaches_load_slo_row(bench):
    """The load-slo stage rides both artifacts of a normal run, like
    the control-plane and serving-loop rows."""
    line, prov = bench.finalize_record(
        {"serving": 9800.0e6}, LAST_FULL, 5.35e6, load_slo=LS
    )
    assert line["load_slo"] == LS
    assert prov["load_slo"] == LS
    assert line["unit"] == "MH/s"


def test_finalize_load_slo_only_run(bench):
    """bench.py --load-slo: the headline becomes the highest offered
    rate's achieved solves/s and kernel provenance is NOT re-stamped."""
    line, prov = bench.finalize_record({}, LAST_FULL, None, load_slo=LS)
    assert prov is None
    assert line["unit"] == "solves/s"
    assert line["value"] == 11.5  # the r12 row, selected by target_hz
    assert "12" in line["metric"]
    assert line["load_slo"] == LS


def test_finalize_carries_forward_load_slo(bench):
    lm = dict(LAST_FULL, load_slo=LS)
    line, prov = bench.finalize_record({"serving": 9800.0e6}, lm, 5.35e6)
    assert prov["load_slo"] == LS
    assert "load_slo" not in line


def test_finalize_control_plane_headline_attaches_load_slo(bench):
    """On a device-unreachable run that measured both CPU stages the
    control-plane row stays the headline and the load-slo dict rides
    along."""
    line, prov = bench.finalize_record(
        {}, LAST_FULL, None, control_plane=CP, load_slo=LS
    )
    assert prov is None
    assert line["unit"] == "ms"
    assert line["load_slo"] == LS


# -- membership stage (ISSUE 12) ---------------------------------------------

MB = {
    "solve_delay_s": 1.0,
    "reassignment": {
        "lease_expiry": {"healthy_s": 1.0, "dead_worker_s": 1.7,
                         "detection_overhead_s": 0.7},
        "probe_baseline": {"healthy_s": 1.0, "dead_worker_s": 3.3,
                           "detection_overhead_s": 2.3},
        "lease_vs_probe_x": 3.29,
    },
    "straggler": {"n_workers": 4, "cap_s": 8.0, "healthy_s": 1.0,
                  "hedged_s": 1.3, "hedge_off_s": None,
                  "hedge_off_floor_s": 8.0, "hedged_vs_healthy_x": 1.3},
    "hedge_within_2x_healthy": True,
}


def test_finalize_attaches_membership_row(bench):
    """The membership stage rides both artifacts of a normal run, like
    the other tunnel-independent rows."""
    line, prov = bench.finalize_record(
        {"serving": 9800.0e6}, LAST_FULL, 5.35e6, membership=MB
    )
    assert line["membership"] == MB
    assert prov["membership"] == MB
    assert line["unit"] == "MH/s"


def test_finalize_membership_only_run(bench):
    """bench.py --membership: the headline is the hedged straggler
    round completion and kernel provenance is NOT re-stamped."""
    line, prov = bench.finalize_record({}, LAST_FULL, None, membership=MB)
    assert prov is None
    assert line["unit"] == "s"
    assert line["value"] == 1.3
    assert line["vs_baseline"] == 1.3  # hedged-vs-healthy ratio
    assert "hedging on" in line["metric"]
    assert line["membership"] == MB


def test_finalize_carries_forward_membership(bench):
    lm = dict(LAST_FULL, membership=MB)
    line, prov = bench.finalize_record({"serving": 9800.0e6}, lm, 5.35e6)
    assert prov["membership"] == MB
    assert "membership" not in line


def test_finalize_control_plane_headline_attaches_membership(bench):
    """Device-unreachable runs that measured both CPU stages: the
    control-plane row stays the headline, membership rides along."""
    line, prov = bench.finalize_record(
        {}, LAST_FULL, None, control_plane=CP, membership=MB
    )
    assert prov is None
    assert line["unit"] == "ms"
    assert line["membership"] == MB


# -- forensics-overhead stage (ISSUE 14) --------------------------------------

FO = {
    "rounds_per_arm": 30, "ntz": 1,
    "on": {"median_round_s": 0.0042, "solves_per_s": 238.6},
    "off": {"median_round_s": 0.0041, "solves_per_s": 244.1},
    "on_vs_off_x": 0.9774, "overhead_pct": 2.32,
    "spans_recorded_on_arm": 436, "exemplars_present": True,
    "within_5pct": True,
}


def test_finalize_attaches_forensics_row(bench):
    """The forensics stage rides both artifacts of a normal run, like
    the other tunnel-independent rows."""
    line, prov = bench.finalize_record(
        {"serving": 9800.0e6}, LAST_FULL, 5.35e6, forensics=FO
    )
    assert line["forensics"] == FO
    assert prov["forensics"] == FO
    assert line["unit"] == "MH/s"


def test_finalize_forensics_only_run(bench):
    """bench.py --forensics-overhead: the headline is the on-vs-off
    throughput ratio and kernel provenance is NOT re-stamped."""
    line, prov = bench.finalize_record({}, LAST_FULL, None, forensics=FO)
    assert prov is None
    assert line["unit"] == "x"
    assert line["value"] == 0.9774
    assert "spans+exemplars" in line["metric"]
    assert line["forensics"] == FO


def test_finalize_carries_forward_forensics(bench):
    lm = dict(LAST_FULL, forensics=FO)
    line, prov = bench.finalize_record({"serving": 9800.0e6}, lm, 5.35e6)
    assert prov["forensics"] == FO
    assert "forensics" not in line


def test_finalize_control_plane_headline_attaches_forensics(bench):
    """Device-unreachable runs that measured both CPU stages: the
    control-plane row stays the headline, forensics rides along."""
    line, prov = bench.finalize_record(
        {}, LAST_FULL, None, control_plane=CP, forensics=FO
    )
    assert prov is None
    assert line["unit"] == "ms"
    assert line["forensics"] == FO


def test_finalize_membership_only_attaches_forensics(bench):
    """A membership-headline run still carries the forensics dict."""
    line, prov = bench.finalize_record(
        {}, LAST_FULL, None, membership=MB, forensics=FO
    )
    assert prov is None
    assert line["unit"] == "s"
    assert line["forensics"] == FO


# -- cluster-scale stage (ISSUE 15) -------------------------------------------

CS = {
    "rate_hz": 150.0, "duration_s": 2.0, "max_inflight": 4,
    "solve_delay_s": 0.15,
    "pools": {
        "n1": {"coordinators": 1, "issued": 296, "completed": 296,
               "request_errors": 0, "wall_s": 11.31,
               "solves_per_s": 26.17},
        "n2": {"coordinators": 2, "issued": 285, "completed": 285,
               "request_errors": 0, "wall_s": 6.16,
               "solves_per_s": 46.24},
        "n4": {"coordinators": 4, "issued": 302, "completed": 302,
               "request_errors": 0, "wall_s": 4.24,
               "solves_per_s": 71.19},
    },
    "speedup": {"n2_vs_n1": 1.77, "n4_vs_n1": 2.72},
    "ok": True, "wall_s": 21.9,
}


def test_finalize_attaches_cluster_scale_row(bench):
    """The cluster-scale stage rides both artifacts of a normal run,
    like the other tunnel-independent rows."""
    line, prov = bench.finalize_record(
        {"serving": 9800.0e6}, LAST_FULL, 5.35e6, cluster_scale=CS
    )
    assert line["cluster_scale"] == CS
    assert prov["cluster_scale"] == CS
    assert line["unit"] == "MH/s"


def test_finalize_cluster_scale_only_run(bench):
    """bench.py --cluster-scale: the headline is the largest pool's
    aggregate-solves/s speedup and kernel provenance is NOT
    re-stamped."""
    line, prov = bench.finalize_record({}, LAST_FULL, None,
                                       cluster_scale=CS)
    assert prov is None
    assert line["unit"] == "x"
    assert line["value"] == 2.72
    assert "4-coordinator pool" in line["metric"]
    assert line["cluster_scale"] == CS


def test_finalize_carries_forward_cluster_scale(bench):
    lm = dict(LAST_FULL, cluster_scale=CS)
    line, prov = bench.finalize_record({"serving": 9800.0e6}, lm, 5.35e6)
    assert prov["cluster_scale"] == CS
    assert "cluster_scale" not in line


def test_finalize_control_plane_headline_attaches_cluster_scale(bench):
    """Device-unreachable runs that measured both CPU stages: the
    control-plane row stays the headline, cluster-scale rides along."""
    line, prov = bench.finalize_record(
        {}, LAST_FULL, None, control_plane=CP, cluster_scale=CS
    )
    assert prov is None
    assert line["unit"] == "ms"
    assert line["cluster_scale"] == CS


def test_finalize_forensics_only_attaches_cluster_scale(bench):
    """A forensics-headline run still carries the cluster-scale dict."""
    line, prov = bench.finalize_record(
        {}, LAST_FULL, None, forensics=FO, cluster_scale=CS
    )
    assert prov is None
    assert line["unit"] == "x"
    assert "spans+exemplars" in line["metric"]
    assert line["cluster_scale"] == CS

# -- cache-HA stage (ISSUE 16) -------------------------------------------------

CH = {
    "warm_ntz": 2, "n_keys": 12,
    "arms": {
        "repl_on": {"replicas": 1, "keys": 12, "dead_owned": 6,
                    "warm_completed": 12, "warm_errors": 0,
                    "converged": True, "repeat_completed": 12,
                    "repeat_errors": 0, "repeat_hits": 12,
                    "repeat_fanouts": 0, "repeat_hit_ratio": 1.0},
        "repl_off": {"replicas": 0, "keys": 12, "dead_owned": 6,
                     "warm_completed": 12, "warm_errors": 0,
                     "converged": True, "repeat_completed": 12,
                     "repeat_errors": 0, "repeat_hits": 6,
                     "repeat_fanouts": 6, "repeat_hit_ratio": 0.5},
    },
    "hit_ratio_on": 1.0, "hit_ratio_off": 0.5, "on_vs_off_x": 2.0,
    "ok": True, "wall_s": 2.3,
}


def test_finalize_attaches_cache_ha_row(bench):
    """The cache-HA stage rides both artifacts of a normal run, like
    the other tunnel-independent rows."""
    line, prov = bench.finalize_record(
        {"serving": 9800.0e6}, LAST_FULL, 5.35e6, cache_ha=CH
    )
    assert line["cache_ha"] == CH
    assert prov["cache_ha"] == CH
    assert line["unit"] == "MH/s"


def test_finalize_cache_ha_only_run(bench):
    """bench.py --cache-ha: the headline is the replication-on repeat
    hit ratio (vs_baseline the on/off gap) and kernel provenance is
    NOT re-stamped."""
    line, prov = bench.finalize_record({}, LAST_FULL, None, cache_ha=CH)
    assert prov is None
    assert line["unit"] == "ratio"
    assert line["value"] == 1.0
    assert line["vs_baseline"] == 2.0
    assert "replication on" in line["metric"]
    assert line["cache_ha"] == CH


def test_finalize_carries_forward_cache_ha(bench):
    lm = dict(LAST_FULL, cache_ha=CH)
    line, prov = bench.finalize_record({"serving": 9800.0e6}, lm, 5.35e6)
    assert prov["cache_ha"] == CH
    assert "cache_ha" not in line


def test_finalize_control_plane_headline_attaches_cache_ha(bench):
    """Device-unreachable runs that measured both CPU stages: the
    control-plane row stays the headline, cache-HA rides along."""
    line, prov = bench.finalize_record(
        {}, LAST_FULL, None, control_plane=CP, cache_ha=CH
    )
    assert prov is None
    assert line["unit"] == "ms"
    assert line["cache_ha"] == CH


def test_finalize_cluster_scale_only_attaches_cache_ha(bench):
    """A cluster-scale-headline run still carries the cache-HA dict."""
    line, prov = bench.finalize_record(
        {}, LAST_FULL, None, cluster_scale=CS, cache_ha=CH
    )
    assert prov is None
    assert line["unit"] == "x"
    assert "4-coordinator pool" in line["metric"]
    assert line["cache_ha"] == CH


# -- soak stage (ISSUE 18) ----------------------------------------------------

SK = {
    "slo_config": "config/slo.json", "duration_s": 8.0, "rate_hz": 10.0,
    "sweep_interval_s": 0.25,
    "arms": [
        {"arm": "off", "achieved_solves_per_s": 9.8, "completed": 80,
         "request_errors": 0, "retained_points": 2, "verdict": "pass"},
        {"arm": "on", "achieved_solves_per_s": 9.6, "completed": 78,
         "request_errors": 0, "retained_points": 34, "verdict": "pass"},
    ],
    "on_solves_per_s": 9.6, "off_solves_per_s": 9.8,
    "overhead_pct": 2.04, "overhead_ok": True, "ok": True, "wall_s": 21.0,
}


def test_finalize_attaches_soak_row(bench):
    """The soak stage rides both artifacts of a normal run, like the
    other tunnel-independent rows."""
    line, prov = bench.finalize_record(
        {"serving": 9800.0e6}, LAST_FULL, 5.35e6, soak=SK
    )
    assert line["soak"] == SK
    assert prov["soak"] == SK
    assert line["unit"] == "MH/s"


def test_finalize_soak_only_run(bench):
    """bench.py --soak: the headline is the sweep-overhead percentage
    and kernel provenance is NOT re-stamped."""
    line, prov = bench.finalize_record({}, LAST_FULL, None, soak=SK)
    assert prov is None
    assert line["unit"] == "%"
    assert line["value"] == 2.04
    assert "sweep overhead" in line["metric"]
    assert line["soak"] == SK


def test_finalize_carries_forward_soak(bench):
    lm = dict(LAST_FULL, soak=SK)
    line, prov = bench.finalize_record({"serving": 9800.0e6}, lm, 5.35e6)
    assert prov["soak"] == SK
    assert "soak" not in line


def test_finalize_control_plane_headline_attaches_soak(bench):
    """Device-unreachable runs that measured both CPU stages: the
    control-plane row stays the headline, soak rides along."""
    line, prov = bench.finalize_record(
        {}, LAST_FULL, None, control_plane=CP, soak=SK
    )
    assert prov is None
    assert line["unit"] == "ms"
    assert line["soak"] == SK


def test_finalize_cache_ha_only_attaches_soak(bench):
    """A cache-HA-headline run still carries the soak dict."""
    line, prov = bench.finalize_record(
        {}, LAST_FULL, None, cache_ha=CH, soak=SK
    )
    assert prov is None
    assert line["unit"] == "ratio"
    assert line["soak"] == SK


# -- mesh-serving stage (ISSUE 20) --------------------------------------------

MS = {
    "ntz": 4, "batch": 1024, "solves": 24,
    "arms": [
        {"devices": 1, "requested_devices": 1, "ntz": 4, "batch": 1024,
         "solves": 24, "wall_s": 0.683, "solves_per_s": 35.1,
         "lane_launches": {"xla": 1111}},
        {"devices": 4, "requested_devices": 4, "ntz": 4, "batch": 1024,
         "solves": 24, "wall_s": 0.275, "solves_per_s": 87.2,
         "lane_launches": {"mesh": 80, "xla": 24}},
    ],
    "speedup_x": 2.48, "ok": True,
}


def test_finalize_attaches_mesh_serving_row(bench):
    """The mesh-serving stage rides both artifacts of a normal run,
    like the other tunnel-independent rows."""
    line, prov = bench.finalize_record(
        {"serving": 9800.0e6}, LAST_FULL, 5.35e6, mesh_serving=MS
    )
    assert line["mesh_serving"] == MS
    assert prov["mesh_serving"] == MS
    assert line["unit"] == "MH/s"


def test_finalize_mesh_serving_only_run(bench):
    """bench.py --mesh-serving: the headline is the 4-vs-1-device
    scheduler speedup and kernel provenance is NOT re-stamped."""
    line, prov = bench.finalize_record({}, LAST_FULL, None, mesh_serving=MS)
    assert prov is None
    assert line["unit"] == "x"
    assert line["value"] == 2.48
    assert "mesh-serving" in line["metric"]
    assert line["mesh_serving"] == MS


def test_finalize_carries_forward_mesh_serving(bench):
    lm = dict(LAST_FULL, mesh_serving=MS)
    line, prov = bench.finalize_record({"serving": 9800.0e6}, lm, 5.35e6)
    assert prov["mesh_serving"] == MS
    assert "mesh_serving" not in line


def test_finalize_control_plane_headline_attaches_mesh_serving(bench):
    """Device-unreachable runs that measured both CPU stages: the
    control-plane row stays the headline, mesh-serving rides along."""
    line, prov = bench.finalize_record(
        {}, LAST_FULL, None, control_plane=CP, mesh_serving=MS
    )
    assert prov is None
    assert line["unit"] == "ms"
    assert line["mesh_serving"] == MS


def test_finalize_soak_only_attaches_mesh_serving(bench):
    """A soak-headline run still carries the mesh-serving dict."""
    line, prov = bench.finalize_record(
        {}, LAST_FULL, None, soak=SK, mesh_serving=MS
    )
    assert prov is None
    assert line["unit"] == "%"
    assert line["mesh_serving"] == MS
