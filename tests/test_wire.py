"""Wire codec v2 + negotiation + parallel fan-out data-plane tests
(ISSUE 5).

Four layers:

1. **Golden vectors** — exact bytes of representative frames in BOTH
   directions (encode must reproduce them, decode must invert them),
   including the typed ``retry_after`` response header.  The interning
   tables in runtime/wire.py are append-only wire contract; an
   accidental reorder fails here before it corrupts a mixed-version
   cluster.
2. **Negotiation** — auto clients speak v2 to v2 servers, fall back
   transparently against JSON-only servers, and ``codec="binary"``
   refuses a v1-only peer.
3. **Mixed-version interop** — a JSON-pinned stack and a v2 stack run
   the same Mine scenario and produce IDENTICAL per-node trace shapes;
   payload bytes shrink >= 2x on the binary wire.
4. **Chaos on binary** — the fault plane's truncate/duplicate mutations
   behave on v2 frames exactly as on JSON (the client retry machinery
   rides them out), and a SIGSTOP'd worker process no longer
   head-of-line-blocks round start (slow tier).
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

import pytest

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from test_nodes import Stack, mine_and_wait  # noqa: E402

from distpow_tpu.models import puzzle  # noqa: E402
from distpow_tpu.runtime import faults, rpc, wire  # noqa: E402
from distpow_tpu.runtime.metrics import REGISTRY  # noqa: E402
from distpow_tpu.runtime.telemetry import RECORDER  # noqa: E402


# -- 1. golden vectors -------------------------------------------------------

MINE_REQ = {
    "id": 1, "method": "WorkerRPCHandler.Mine",
    "params": {"nonce": b"\x01\x02\x03\x04", "num_trailing_zeros": 2,
               "worker_byte": 0, "worker_bits": 2,
               "round": "0000000018f2a3b4c5d6e7f0",
               "token": b"\x10\x11\x12\x13"},
}
MINE_REQ_HEX = (
    "01018308068006040102030481030482030083030484051830303030303030303138"
    "663261336234633564366537663085060410111213"
)
FOUND_REQ = {
    "id": 2, "method": "WorkerRPCHandler.Found",
    "params": {"nonce": b"\x01\x02\x03\x04", "num_trailing_zeros": 2,
               "worker_byte": 3, "secret": b"\xaa\xbb",
               "round": "0000000018f2a3b4c5d6e7f0",
               "token": b"\x10\x11\x12\x13"},
}
FOUND_REQ_HEX = (
    "010284080680060401020304810304820306860602aabb84051830303030303030"
    "303138663261336234633564366537663085060410111213"
)
OK_RESP = {"id": 2, "result": {"worker_tasks": 1}, "error": None}
OK_RESP_HEX = "0202000801880302"
ERR_RESP = {"id": 3, "result": None, "error": "RuntimeError: boom"}
ERR_RESP_HEX = "020301051252756e74696d654572726f723a20626f6f6d"
RETRY_RESP = {
    "id": 4, "result": None,
    "error": "retry-after:0.500s coordinator run queue full (2/2)",
    "retry_after": 0.5,
}
RETRY_RESP_HEX = (
    "0204033fe0000000000000053372657472792d61667465723a302e3530307320636f"
    "6f7264696e61746f722072756e2071756575652066756c6c2028322f3229"
)
NEG_REQ = {
    "id": 5, "method": "rpc.custom",
    "params": {"x": -3, "f": 1.5, "b": True, "n": None,
               "l": [1, "s", b"\x00"]},
}
NEG_REQ_HEX = (
    "0105000a7270632e637573746f6d08050001780305000166043ff8000000000000"
    "0001620200016e0000016c07030302050173060100"
)

GOLDENS = [
    ("mine-request", MINE_REQ, MINE_REQ_HEX),
    ("found-request", FOUND_REQ, FOUND_REQ_HEX),
    ("ok-response", OK_RESP, OK_RESP_HEX),
    ("error-response", ERR_RESP, ERR_RESP_HEX),
    ("retry-after-response", RETRY_RESP, RETRY_RESP_HEX),
    ("uninterned-request", NEG_REQ, NEG_REQ_HEX),
]


@pytest.mark.parametrize("name,obj,hexpect", GOLDENS,
                         ids=[g[0] for g in GOLDENS])
def test_golden_vectors_both_directions(name, obj, hexpect):
    encoded = wire.encode_frame(obj)
    assert encoded.hex() == hexpect, (
        f"{name}: encoding drifted — the interning tables are append-only "
        f"wire contract (runtime/wire.py)"
    )
    decoded = wire.decode_frame(bytes.fromhex(hexpect))
    # normalize: decode yields bytes for byte fields, identical otherwise
    assert decoded == obj


def test_retry_after_header_is_typed():
    d = wire.decode_frame(bytes.fromhex(RETRY_RESP_HEX))
    assert isinstance(d["retry_after"], float) and d["retry_after"] == 0.5
    assert d["error"].startswith("retry-after:")
    # and an ok frame never grows the key
    assert "retry_after" not in wire.decode_frame(bytes.fromhex(OK_RESP_HEX))


def test_roundtrip_stats_shaped_payload():
    """Nested snapshot shapes (histogram dicts, None min/max, floats,
    dotted non-interned keys) survive the codec unchanged."""
    snap = {
        "id": 9, "result": {
            "counters": {"coord.mine_rpcs": 3, "rpc.codec.negotiated_v2": 2},
            "gauges": {"search.hashes_per_s": 1.25e9},
            "histograms": {"powlib.mine_s": {
                "count": 2, "sum": 0.5, "min": None, "max": 0.4,
                "buckets": [[0.0, 1], [0.42044820762685725, 1]],
            }},
            "role": "coordinator", "ok": True,
        }, "error": None,
    }
    assert wire.decode_frame(wire.encode_frame(snap)) == snap


def test_decoder_rejects_malformed_frames():
    good = wire.encode_frame(MINE_REQ)
    for bad in (
        b"",                                # empty
        b"\x09",                            # unknown frame kind
        good[:-1],                          # truncated mid-value
        good + b"\x00",                     # trailing garbage
        b"\x01\x01\xff",                    # interned method id out of range
        b"\x02\x01\x80",                    # unknown response flags
        b"\x01\x01" + b"\x80" * 1,          # method ok but params missing
    ):
        with pytest.raises(ValueError):
            wire.decode_frame(bad)


def test_varint_and_int_edges():
    for n in (0, 1, -1, 127, 128, -128, 2**31, -(2**31), 2**63 - 1,
              -(2**63), 300000000000):
        frame = wire.encode_frame({"id": 0, "result": n, "error": None})
        assert wire.decode_frame(frame)["result"] == n


# -- 2. negotiation ----------------------------------------------------------

class _Echo:
    def Ping(self, params):
        return {"got": params}


def _serve(negotiate=True):
    srv = rpc.RPCServer(negotiate=negotiate)
    srv.register("S", _Echo())
    addr = srv.listen("127.0.0.1:0")
    srv.serve_in_background()
    return srv, addr


def test_auto_negotiates_v2_and_roundtrips_bytes():
    srv, addr = _serve()
    try:
        c = rpc.RPCClient(addr)
        assert c.codec_name == "binary"
        out = c.call("S.Ping", {"nonce": b"\xaa\xbb", "n": 5}, timeout=10)
        # binary wire delivers bytes AS bytes, no int-list detour
        assert out["got"]["nonce"] == b"\xaa\xbb" and out["got"]["n"] == 5
        c.close()
    finally:
        srv.shutdown()


def test_auto_falls_back_to_json_against_v1_only_server():
    srv, addr = _serve(negotiate=False)
    try:
        before = REGISTRY.get("rpc.codec.fallback_v1")
        c = rpc.RPCClient(addr)
        assert c.codec_name == "json"
        assert REGISTRY.get("rpc.codec.fallback_v1") == before + 1
        out = c.call("S.Ping", {"nonce": b"\xaa"}, timeout=10)
        # JSON wire renders bytes as the legacy int array
        assert out["got"]["nonce"] == [170]
        c.close()
        with pytest.raises(rpc.RPCError):
            rpc.RPCClient(addr, codec="binary")
    finally:
        srv.shutdown()


def test_json_pinned_client_against_v2_server():
    srv, addr = _serve()
    try:
        c = rpc.RPCClient(addr, codec="json")
        assert c.codec_name == "json"
        assert c.call("S.Ping", {"x": 1}, timeout=10)["got"]["x"] == 1
        c.close()
    finally:
        srv.shutdown()


# -- 3. mixed-version interop over the full protocol -------------------------

def _run_scenario(n_workers=1):
    """One deterministic Mine scenario; returns per-node action-name
    sequences plus the rpc.frame.sent_bytes delta of the PROTOCOL
    frames (the byte window opens after every connection is dialed, so
    the v2 stacks' one-off hello handshakes — which the JSON-pinned
    stack never sends — don't dilute the Mine/Found comparison the
    acceptance criterion is about)."""
    s = Stack(n_workers)
    try:
        c = s.new_client("client1")
        h0 = REGISTRY.get_histogram("rpc.frame.sent_bytes") or \
            {"count": 0, "sum": 0.0}
        r1 = mine_and_wait(c, b"\x77\x01", 2)
        assert puzzle.check_secret(r1.nonce, r1.secret, 2)
        mine_and_wait(c, b"\x77\x02", 2)
        r2 = mine_and_wait(c, b"\x77\x01", 2)  # cache-hit repeat
        assert r2.secret == r1.secret
        h1 = REGISTRY.get_histogram("rpc.frame.sent_bytes")
        shapes = {n: s.action_names(n)
                  for n in ("client1", "coordinator", "worker1")}
    finally:
        s.close()
    return shapes, h1["sum"] - h0["sum"]


def test_mixed_version_trace_parity_and_payload_shrink(monkeypatch):
    """A JSON-only cluster and a v2 cluster run the same rounds with
    IDENTICAL trace shapes (the codec is invisible to the protocol),
    and the binary wire carries the same rounds in <= half the bytes
    (ISSUE 5 acceptance, asserted from rpc.frame.sent_bytes)."""
    monkeypatch.setattr(rpc, "CLIENT_CODEC_DEFAULT", "json")
    monkeypatch.setattr(rpc, "SERVER_NEGOTIATE_DEFAULT", False)
    json_shapes, json_bytes = _run_scenario()

    monkeypatch.setattr(rpc, "CLIENT_CODEC_DEFAULT", "auto")
    monkeypatch.setattr(rpc, "SERVER_NEGOTIATE_DEFAULT", True)
    v2_before = REGISTRY.get("rpc.codec.negotiated_v2")
    bin_shapes, bin_bytes = _run_scenario()
    assert REGISTRY.get("rpc.codec.negotiated_v2") > v2_before

    assert bin_shapes == json_shapes, "codec changed the protocol's traces"
    # aggregate: every frame of the measured rounds, both directions
    # (measured 2.2x — the big raw-vs-base64 tracing tokens dilute the
    # per-frame wins; deterministic for this 1-worker scenario)
    assert json_bytes / bin_bytes >= 2.0, (
        f"binary wire shrank payload only {json_bytes / bin_bytes:.2f}x "
        f"({json_bytes:.0f} -> {bin_bytes:.0f} bytes)"
    )


def test_mine_found_frames_shrink_per_frame():
    """The acceptance criterion's frame classes, compared exactly: a
    representative Mine and Found frame each shrink >= 2.5x against the
    JSON wire (base64 token form — the honest legacy baseline)."""
    tok = bytes(range(40))
    mine = {"id": 3, "method": "WorkerRPCHandler.Mine",
            "params": {"nonce": b"\x01\x02\x03\x04", "num_trailing_zeros": 8,
                       "worker_byte": 0, "worker_bits": 2,
                       "round": "0" * 24, "token": tok}}
    found = {"id": 4, "method": "WorkerRPCHandler.Found",
             "params": {"nonce": b"\x01\x02\x03\x04", "num_trailing_zeros": 8,
                        "worker_byte": 0, "secret": b"\xaa\xbb",
                        "round": "0" * 24, "token": tok}}
    for frame in (mine, found):
        j = len(rpc.JSON_CODEC.encode(frame))
        b = len(wire.encode_frame(frame))
        assert j / b >= 2.5, f"{frame['method']}: {j}/{b} = {j / b:.2f}x"


def test_binary_client_json_server_full_round(monkeypatch):
    """Direction 1 of mixed-version: every CLIENT is v2-capable but
    every SERVER is JSON-only — the hello degrades each connection to
    v1 and a full Mine round completes."""
    monkeypatch.setattr(rpc, "SERVER_NEGOTIATE_DEFAULT", False)
    shapes, _ = _run_scenario()
    assert shapes["coordinator"][-1] == "CoordinatorSuccess"


def test_json_client_binary_server_full_round(monkeypatch):
    """Direction 2: v1-pinned clients against v2-capable servers."""
    monkeypatch.setattr(rpc, "CLIENT_CODEC_DEFAULT", "json")
    shapes, _ = _run_scenario()
    assert shapes["coordinator"][-1] == "CoordinatorSuccess"


# -- 4. chaos on binary frames ----------------------------------------------

@pytest.fixture(autouse=True)
def _no_leftover_faults():
    faults.uninstall()
    yield
    faults.uninstall()


def test_fault_plane_mutations_on_binary_frames():
    """truncate + duplicate on wire-v2 frames: the truncated Mine tears
    the client connection (retry machinery re-dials and re-issues), the
    duplicated Found re-dispatches idempotently — the chaos matrix
    semantics are codec-independent."""
    plan = faults.install_from_spec({"seed": 51, "rules": [
        {"kind": "truncate", "method": "CoordRPCHandler.Mine",
         "side": "client", "calls": "0:1", "max": 1},
        {"kind": "duplicate", "method": "WorkerRPCHandler.Found",
         "side": "client", "max": 1},
    ]})
    s = Stack(1)
    try:
        c = s.new_client("client1", MineRetries=4, MineBackoffS=0.05)
        res = mine_and_wait(c, b"\x77\x42", 2, timeout=60)
        assert res.error is None
        assert puzzle.check_secret(res.nonce, res.secret, 2)
        kinds = {k for _, k, _, _, _ in plan.injected}
        assert "truncate" in kinds, plan.injected
        # the mined round really rode the binary wire
        assert REGISTRY.get("rpc.codec.negotiated_v2") > 0
    finally:
        s.close()


def test_trace_oracle_clean_over_parallel_fanout_golden_run(tmp_path):
    """Trace-oracle pass over a parallel-fan-out run (ISSUE 5
    satellite): a 4-worker stack under concurrent Mines — every fan-out
    and cancel storm issued as parallel futures — must keep the
    reference protocol's ordering invariants byte-for-byte checkable
    (runtime/trace_check.py finds zero violations)."""
    from distpow_tpu.runtime.config import TracingServerConfig
    from distpow_tpu.runtime.trace_check import check_shiviz_log, check_trace_log
    from distpow_tpu.runtime.trace_server import TracingServer
    from distpow_tpu.runtime.tracing import TCPSink

    out = tmp_path / "trace_output.log"
    shiviz = tmp_path / "shiviz_output.log"
    server = TracingServer(TracingServerConfig(
        ServerBind="127.0.0.1:0", Secret=b"",
        OutputFile=str(out), ShivizOutputFile=str(shiviz),
    ))
    addr = server.open()
    server.accept_in_background()
    s = Stack(4, sink_factory=lambda name: TCPSink(addr, b""))
    try:
        c1 = s.new_client("client1")
        c2 = s.new_client("client2")
        # overlapping requests: concurrent fan-outs + cancel storms
        c1.mine(b"\x81\x01", 3)
        c2.mine(b"\x81\x02", 3)
        c1.mine(b"\x81\x03", 2)
        for cl, n in ((c1, 2), (c2, 1)):
            for _ in range(n):
                r = cl.notify_queue.get(timeout=60)
                assert r.error is None
    finally:
        s.close()
        deadline = time.time() + 10
        last = -1
        while time.time() < deadline:
            size = out.stat().st_size if out.exists() else 0
            if size == last:
                break
            last = size
            time.sleep(0.3)
        server.close()
    assert check_trace_log(str(out)) == []
    assert check_shiviz_log(str(shiviz)) == []


@pytest.mark.slow
def test_sigstopped_worker_does_not_head_of_line_block(tmp_path):
    """A worker PROCESS frozen with SIGSTOP (TCP open, nothing answers)
    must not add `_call_timeout` to fanout->first-result for the live
    workers (ISSUE 5 acceptance).  The serial fan-out blocked the whole
    round start on the frozen worker's ack."""
    from distpow_tpu.nodes import Coordinator, Worker
    from distpow_tpu.runtime.config import CoordinatorConfig, WorkerConfig

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    coordinator = Coordinator(CoordinatorConfig(
        ClientAPIListenAddr="127.0.0.1:0",
        WorkerAPIListenAddr="127.0.0.1:0",
        Workers=["pending:0"] * 3,
        FailurePolicy="reassign",
        FailureProbeSecs=0.2,
    ))
    client_addr, worker_api = coordinator.initialize_rpcs()
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    child = subprocess.Popen(
        [sys.executable, os.path.join(repo, "tests", "stopped_worker_child.py"),
         worker_api],
        cwd=repo, env=env, stdout=subprocess.PIPE, text=True,
    )
    workers = []
    try:
        line = child.stdout.readline()
        assert line.startswith("WORKER_READY "), line
        child_addr = line.split()[1]
        for i in range(2):
            w = Worker(WorkerConfig(
                WorkerID=f"live{i}", ListenAddr="127.0.0.1:0",
                CoordAddr=worker_api, Backend="python",
            ))
            w.initialize_rpcs()
            w.start_forwarder()
            workers.append(w)
        # child first: its shard 0 heads the fan-out order, the spot
        # where serial dispatch paid the full head-of-line stall
        coordinator.set_worker_addrs(
            [child_addr] + [w.bound_addr for w in workers])

        from distpow_tpu.nodes import Client
        from distpow_tpu.runtime.config import ClientConfig
        cl = Client(ClientConfig(ClientID="c", CoordAddr=client_addr))
        cl.initialize()
        try:
            # round 1 healthy: establishes the child's connection
            cl.mine(b"\x91\x01", 2)
            assert cl.notify_queue.get(timeout=60).error is None

            os.kill(child.pid, signal.SIGSTOP)
            time.sleep(0.2)
            t0 = time.monotonic()
            cl.mine(b"\x91\x02", 2)
            res = cl.notify_queue.get(timeout=60)
            elapsed = time.monotonic() - t0
            assert res.error is None
            assert puzzle.check_secret(res.nonce, res.secret, 2)
            call_timeout = coordinator.handler._call_timeout
            evs = [e for e in RECORDER.recent()
                   if e["kind"] == "coord.first_result"]
            assert evs and evs[-1]["latency_s"] < 2.0, (
                f"frozen worker head-of-line-blocked round start "
                f"(call_timeout={call_timeout}): {evs[-1:]}"
            )
            # end-to-end bounded by ~one shared Found deadline, never
            # one timeout per worker
            assert elapsed < call_timeout + 5.0
        finally:
            cl.close()
    finally:
        try:
            os.kill(child.pid, signal.SIGCONT)
        except ProcessLookupError:
            pass
        child.terminate()
        try:
            child.wait(timeout=5)
        except subprocess.TimeoutExpired:
            child.kill()
        for w in workers:
            w.shutdown()
        coordinator.shutdown()
