"""Partition algebra tests (coordinator.go:326, worker.go:301-316)."""

import pytest

from distpow_tpu.parallel import partition


def test_worker_bits_matches_go_truncation():
    assert partition.worker_bits(1) == 0
    assert partition.worker_bits(2) == 1
    assert partition.worker_bits(3) == 1  # uint(log2(3)) truncates
    assert partition.worker_bits(4) == 2
    assert partition.worker_bits(8) == 3
    with pytest.raises(ValueError):
        partition.worker_bits(0)


def test_remainder_bits():
    assert partition.remainder_bits(0) == 8
    assert partition.remainder_bits(2) == 6
    assert partition.remainder_bits(8) == 0
    assert partition.remainder_bits(9) == 8  # the % 9 quirk (worker.go:302)


def test_single_worker_owns_all_first_bytes():
    tbs = partition.thread_bytes(0, partition.worker_bits(1))
    assert tbs == list(range(256))


def test_power_of_two_partition_is_disjoint_cover():
    n = 4
    bits = partition.worker_bits(n)
    all_bytes = []
    for wb in range(n):
        tbs = partition.thread_bytes(wb, bits)
        assert len(tbs) == 64
        assert tbs == list(range(wb * 64, (wb + 1) * 64))
        all_bytes.extend(tbs)
    assert sorted(all_bytes) == list(range(256))


def test_non_power_of_two_overlaps_but_covers():
    # reference quirk: floor(log2(3)) = 1, worker 2's prefix wraps onto
    # worker 0's shard — full coverage with duplication, never a gap
    n = 3
    bits = partition.worker_bits(n)
    shards = [partition.thread_bytes(wb, bits) for wb in range(n)]
    assert shards[0] == list(range(0, 128))
    assert shards[1] == list(range(128, 256))
    assert shards[2] == list(range(0, 128))  # wrapped duplicate
    covered = set()
    for s in shards:
        covered.update(s)
    assert covered == set(range(256))


def test_split_thread_bytes():
    tbs = list(range(64, 128))
    shards = partition.split_thread_bytes(tbs, 4)
    assert [len(s) for s in shards] == [16, 16, 16, 16]
    assert sum(shards, []) == tbs
    # uneven split stays contiguous and covers
    shards = partition.split_thread_bytes(list(range(10)), 3)
    assert [len(s) for s in shards] == [4, 3, 3]
    assert sum(shards, []) == list(range(10))
    # more shards than bytes -> empties at the tail
    shards = partition.split_thread_bytes([7], 3)
    assert shards == [[7], [], []]


def test_weighted_ranges_equal_weights_are_reference_algebra():
    """The capability-weighted split's equal-weight special case IS the
    reference split — wrap/overlap quirks included (docs/FLEET.md
    "Weighted partition math")."""
    for n in (1, 2, 3, 4, 5, 8, 9, 16, 100):
        bits = partition.worker_bits(n)
        for wb, (lo, count) in enumerate(partition.weighted_ranges([2.0] * n)):
            tbs = partition.thread_bytes(wb, bits)
            assert lo == tbs[0] and count == len(tbs), (n, wb)


def test_weighted_ranges_unequal_weights_partition_exactly():
    """Unequal weights: disjoint contiguous cover, shares proportional
    (largest remainder), minimum one byte per positive weight."""
    ranges = partition.weighted_ranges([6.0, 2.0, 1.0, 1.0])
    assert sum(c for _, c in ranges) == 256
    lo = 0
    for r_lo, count in ranges:
        assert r_lo == lo and count >= 1  # contiguous, gapless, non-empty
        lo += count
    assert ranges[0][1] > ranges[1][1] > ranges[2][1] >= 1
    # 6/10 of 256 = 153.6: largest-remainder lands within one byte
    assert abs(ranges[0][1] - 153.6) <= 1.0


def test_any_worker_count_covers_byte_space():
    """The invariant the reference preserves THROUGH its quirks
    (truncating log2, uint8 wrap, the %9 regime at >= 512 workers):
    whatever the worker count, the union of all shards is the full
    first-byte space — duplication allowed, gaps never (worker.go:
    301-316; any valid secret is acceptable, a gap could hide the only
    solution)."""
    for n in (1, 2, 3, 5, 7, 8, 9, 15, 16, 31, 100, 255, 256, 257,
              511, 512, 513, 1000, 1024):
        bits = partition.worker_bits(n)
        covered = set()
        for wb in range(n):
            covered.update(partition.thread_bytes(wb, bits))
        assert covered == set(range(256)), n
