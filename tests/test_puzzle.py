"""Behavioral contract tests for the core puzzle semantics.

Pins the framework against the reference's exact semantics
(worker.go:234-256, 301-319, 346-356) using hashlib as the oracle and a
line-for-line-equivalent reimplementation of the chunk counter walk.
"""

import hashlib

import pytest

from distpow_tpu.models import puzzle


def test_trailing_zero_nibbles_matches_hex_string():
    # the raw-digest nibble count must equal counting '0' chars of the hex
    # encoding, the reference's definition (worker.go:246-256, 354-356)
    import random

    rng = random.Random(0)
    for _ in range(2000):
        digest = bytes(rng.randrange(256) for _ in range(16))
        expect = puzzle.count_trailing_zero_chars(digest.hex())
        assert puzzle.count_trailing_zero_nibbles(digest) == expect
    # crafted edges
    assert puzzle.count_trailing_zero_nibbles(b"\x00" * 16) == 32
    assert puzzle.count_trailing_zero_nibbles(b"\x01" + b"\x00" * 15) == 30
    assert puzzle.count_trailing_zero_nibbles(b"\xff" * 15 + b"\x10") == 1
    assert puzzle.count_trailing_zero_nibbles(b"\xff" * 15 + b"\x01") == 0
    assert puzzle.count_trailing_zero_nibbles(b"\xff" * 16) == 0


def test_check_secret_against_hashlib():
    nonce, secret = b"\x01\x02\x03\x04", b"\x2a\x07"
    hexd = hashlib.md5(nonce + secret).hexdigest()
    k = puzzle.count_trailing_zero_chars(hexd)
    assert puzzle.check_secret(nonce, secret, k)
    assert not puzzle.check_secret(nonce, secret, k + 1)
    assert puzzle.check_secret(nonce, secret, 0)


def reference_next_chunk(chunk: bytearray) -> bytearray:
    """Direct transliteration of the counter semantics (worker.go:234-244)
    used as an independent oracle for the int<->chunk bijection."""
    for i in range(len(chunk)):
        if chunk[i] == 0xFF:
            chunk[i] = 0
        else:
            chunk[i] += 1
            return chunk
    chunk.append(1)
    return chunk


def test_chunk_counter_is_minimal_little_endian_integers():
    chunk = bytearray()
    for n in range(1, 70000):
        chunk = reference_next_chunk(chunk)
        assert bytes(chunk) == puzzle.int_to_chunk(n), n
        assert puzzle.chunk_to_int(bytes(chunk)) == n
    # width transitions
    assert puzzle.int_to_chunk(0) == b""
    assert puzzle.int_to_chunk(255) == b"\xff"
    assert puzzle.int_to_chunk(256) == b"\x00\x01"
    assert puzzle.int_to_chunk(65535) == b"\xff\xff"
    assert puzzle.int_to_chunk(65536) == b"\x00\x00\x01"
    assert puzzle.chunk_width(0) == 0
    assert puzzle.chunk_width(255) == 1
    assert puzzle.chunk_width(256) == 2


def test_iter_candidates_reference_order():
    # for each chunk all thread bytes are tried before the chunk advances
    # (worker.go:318-399, chunk starts empty)
    tbs = [4, 5]
    it = puzzle.iter_candidates(tbs)
    got = [next(it) for _ in range(8)]
    assert got == [
        (0, 4, b"\x04"),
        (0, 5, b"\x05"),
        (1, 4, b"\x04\x01"),
        (1, 5, b"\x05\x01"),
        (2, 4, b"\x04\x02"),
        (2, 5, b"\x05\x02"),
        (3, 4, b"\x04\x03"),
        (3, 5, b"\x05\x03"),
    ]


def test_python_search_finds_first_in_reference_order():
    nonce = b"\x01\x02\x03\x04"
    tbs = list(range(256))
    secret = puzzle.python_search(nonce, 2, tbs)
    assert secret is not None
    assert puzzle.check_secret(nonce, secret, 2)
    # verify firstness: no earlier candidate solves it
    for _, _, cand in puzzle.iter_candidates(tbs):
        if cand == secret:
            break
        assert not puzzle.check_secret(nonce, cand, 2)


def test_python_search_cancel_and_budget():
    nonce = b"\x00"
    assert puzzle.python_search(nonce, 30, [0], max_candidates=100) is None
    assert (
        puzzle.python_search(nonce, 30, [0], cancel_check=lambda: True) is None
    )


def test_sha256_pluggable():
    nonce, secret = b"\xaa\xbb", b"\x01"
    hexd = hashlib.sha256(nonce + secret).hexdigest()
    k = puzzle.count_trailing_zero_chars(hexd)
    assert puzzle.check_secret(nonce, secret, k, algo="sha256")
    assert not puzzle.check_secret(nonce, secret, k + 1, algo="sha256")
