"""Search-driver tests: single-device and mesh, vs the python oracle."""

import threading

import jax
import pytest

from distpow_tpu.models import puzzle
from distpow_tpu.models.registry import SHA256
from distpow_tpu.parallel import partition
from distpow_tpu.parallel.mesh_search import make_mesh, search_mesh
from distpow_tpu.parallel.search import search


NONCES = [b"\x01\x02\x03\x04", b"\x02\x02\x02\x02", b"\xfe\xff"]


def test_public_search_name_survives_submodule_import_order():
    """README surface: ``from distpow_tpu.parallel import search`` must
    yield the FUNCTION even after something imports the same-named
    submodule first (backends/__init__ does).  The PEP 562 version
    regressed here — the import system's ``parallel.search = <module>``
    setattr shadowed the lazy getattr (caught by the r4 verify drive);
    the module-class property is order-independent."""
    import subprocess
    import sys as _sys

    code = (
        "import warnings; warnings.simplefilter('error', ImportWarning)\n"
        "import distpow_tpu.backends\n"  # imports parallel.search module
        "import distpow_tpu.parallel.search\n"  # must not ImportWarning
        "from distpow_tpu.parallel import search, search_mesh, make_mesh\n"
        "assert callable(search), type(search)\n"
        "assert callable(search_mesh) and callable(make_mesh)\n"
        "print('SURFACE_OK')\n"
    )
    out = subprocess.run(
        [_sys.executable, "-c", code], capture_output=True, text=True,
        timeout=180,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "SURFACE_OK" in out.stdout


@pytest.mark.parametrize("nonce", NONCES)
@pytest.mark.parametrize("difficulty", [1, 2, 3])
def test_search_matches_python_oracle_full_range(nonce, difficulty):
    tbs = list(range(256))
    oracle = puzzle.python_search(nonce, difficulty, tbs)
    got = search(nonce, difficulty, tbs, batch_size=1 << 14)
    assert got is not None
    assert got.secret == oracle
    assert puzzle.check_secret(nonce, got.secret, difficulty)


def test_search_sub_partition():
    # a single worker's shard in a 4-worker config (worker.go:301-316)
    nonce = b"\x05\x06\x07\x08"
    bits = partition.worker_bits(4)
    tbs = partition.thread_bytes(2, bits)
    oracle = puzzle.python_search(nonce, 2, tbs)
    got = search(nonce, 2, tbs, batch_size=1 << 13)
    assert got is not None and got.secret == oracle
    assert got.secret[0] in tbs


def test_search_difficulty4_deep():
    # difficulty 4 typically needs tens of thousands of candidates; pushes
    # into width >= 2 chunks
    nonce = b"\x11\x22\x33\x44"
    tbs = list(range(256))
    got = search(nonce, 4, tbs, batch_size=1 << 16)
    assert got is not None
    assert puzzle.check_secret(nonce, got.secret, 4)
    oracle = puzzle.python_search(nonce, 4, tbs)
    assert got.secret == oracle


def test_search_single_thread_byte():
    # tb_count == 1 exercises the degenerate lane mapping
    nonce = b"\x09"
    got = search(nonce, 2, [7], batch_size=1 << 12)
    oracle = puzzle.python_search(nonce, 2, [7])
    assert got is not None and got.secret == oracle
    assert got.secret[0] == 7


def test_search_cancellation():
    ev = threading.Event()
    ev.set()
    got = search(b"\x01", 30, list(range(256)), cancel_check=ev.is_set)
    assert got is None


def test_search_max_hashes_budget():
    got = search(
        b"\x01", 30, list(range(256)), batch_size=1 << 12, max_hashes=1 << 14
    )
    assert got is None


def test_search_unsatisfiable_difficulty_returns_on_cancel():
    got = search(b"\x01", 33, list(range(256)), cancel_check=lambda: True)
    assert got is None
    got = search(b"\x01", 33, list(range(256)), max_hashes=100)
    assert got is None


def test_search_unsatisfiable_difficulty_without_gate_raises():
    """Bare library callers get a ValueError instead of an un-endable
    wait (VERDICT r3 item 7); the worker path always passes a
    cancel_check, so serving behavior (block-until-cancel, reference
    parity with worker.go:246-256) is unchanged."""
    with pytest.raises(ValueError, match="unsatisfiable"):
        search(b"\x01", 33, list(range(256)))

    from distpow_tpu.backends import native_miner

    backend = native_miner.NativeBackend()
    with pytest.raises(ValueError, match="unsatisfiable"):
        backend.search(b"\x01", 33, list(range(256)))


def test_search_sha256_model():
    nonce = b"\x0a\x0b"
    tbs = list(range(256))
    oracle = puzzle.python_search(nonce, 2, tbs, algo="sha256")
    got = search(nonce, 2, tbs, model=SHA256, batch_size=1 << 13)
    assert got is not None and got.secret == oracle


def test_search_sha1_model():
    """Third registry model end-to-end through the generic driver — the
    layers below the registry are hash-agnostic, so enumeration-order
    parity with the python oracle must hold for free."""
    from distpow_tpu.models.registry import SHA1

    nonce = b"\x0c\x0d"
    tbs = list(range(256))
    oracle = puzzle.python_search(nonce, 2, tbs, algo="sha1")
    got = search(nonce, 2, tbs, model=SHA1, batch_size=1 << 13)
    assert got is not None and got.secret == oracle


def test_search_ripemd160_model():
    """Fourth registry model (round 4) end-to-end through the generic
    driver, including the long-nonce host-absorption path."""
    from distpow_tpu.models.registry import RIPEMD160

    nonce = b"\x0a\x0b"
    tbs = list(range(256))
    oracle = puzzle.python_search(nonce, 2, tbs, algo="ripemd160")
    got = search(nonce, 2, tbs, model=RIPEMD160, batch_size=1 << 13)
    assert got is not None and got.secret == oracle
    long_nonce = bytes(range(200))  # 3 blocks allows host absorption
    oracle2 = puzzle.python_search(long_nonce, 2, tbs, algo="ripemd160")
    got2 = search(long_nonce, 2, tbs, model=RIPEMD160, batch_size=1 << 13)
    assert got2 is not None and got2.secret == oracle2


def test_search_sha512_model():
    """Fifth registry model (round 4): 128-byte blocks and a 16-byte
    length field through the generic driver — the interface-generality
    case."""
    from distpow_tpu.models.registry import SHA512

    tbs = list(range(256))
    oracle = puzzle.python_search(b"\x0a\x0b", 2, tbs, algo="sha512")
    got = search(b"\x0a\x0b", 2, tbs, model=SHA512, batch_size=1 << 13)
    assert got is not None and got.secret == oracle


@pytest.mark.slow
def test_search_sha512_boundary_layouts():
    """sha512 two-block-tail padding boundary + long-nonce host
    absorption of a full 128-byte block — each length is a fresh layout
    (a fresh loop-form compile), so this lives in the slow set."""
    from distpow_tpu.models.registry import SHA512

    tbs = list(range(256))
    for L in (111, 112, 140):
        nonce = bytes(range(L))
        o = puzzle.python_search(nonce, 1, tbs, algo="sha512")
        g = search(nonce, 1, tbs, model=SHA512, batch_size=1 << 12)
        assert g is not None and g.secret == o, L


def test_search_all_constant_tail_block():
    """Regression (round 4): nonce lengths where the secret fits block 0
    entirely but padding forces a second, ALL-constant tail block (rem +
    1 + width in [56, 63] for 64-byte-block hashes) crashed the sha
    fori_loop forms on CPU."""
    from distpow_tpu.models.registry import SHA1, SHA256

    tbs = list(range(256))
    for model, algo in ((SHA256, "sha256"), (SHA1, "sha1")):
        nonce = bytes(range(59))  # 59 + 1 + 4 = 64 <= 64 < 64 + 9
        o = puzzle.python_search(nonce, 1, tbs, algo=algo)
        g = search(nonce, 1, tbs, model=model, batch_size=1 << 12)
        assert g is not None and g.secret == o, algo


def test_mesh_search_sha1_model():
    """sha1 through the shard_map mesh step (the stacked-window vma fix
    in sha1_jax._compress_loop is only exercised under shard_map)."""
    import jax

    from distpow_tpu.models.registry import SHA1
    from distpow_tpu.parallel.mesh_search import make_mesh, search_mesh

    nonce = b"\x05\x06"
    tbs = list(range(256))
    oracle = puzzle.python_search(nonce, 2, tbs, algo="sha1")
    got = search_mesh(nonce, 2, tbs, model=SHA1,
                      mesh=make_mesh(jax.devices()), batch_size=1 << 13)
    assert got is not None and got.secret == oracle


@pytest.mark.slow
def test_mesh_search_new_models():
    """ripemd160, sha512, and blake2b through the shard_map mesh step:
    the stacked-window sha512 loop form must carry cleanly under
    shard_map's varying-axis types, the two-line ripemd compression
    must shard like any other, and blake2b's fori carry must stay
    vma-uniform although half its initial limbs are replicated IV
    constants (the r5 multichip-dryrun regression — blake2b_jax.py's
    varying-zero promotion)."""
    import jax

    from distpow_tpu.models.registry import RIPEMD160, SHA512, get_hash_model
    from distpow_tpu.parallel.mesh_search import make_mesh, search_mesh

    mesh = make_mesh(jax.devices())
    tbs = list(range(256))
    for model, algo in ((RIPEMD160, "ripemd160"), (SHA512, "sha512"),
                        (get_hash_model("blake2b_256"), "blake2b_256"),
                        # composed finalize under shard_map: the second
                        # compression's constant init/message words are
                        # varying-promoted (sha256d_jax.sha256d_finalize)
                        (get_hash_model("sha256d"), "sha256d")):
        oracle = puzzle.python_search(b"\x0a\x0b", 2, tbs, algo=algo)
        got = search_mesh(b"\x0a\x0b", 2, tbs, model=model, mesh=mesh,
                          batch_size=1 << 13)
        assert got is not None and got.secret == oracle, algo


def test_search_long_nonce_multi_block():
    # nonce longer than one hash block: constant blocks absorb host-side
    nonce = bytes(range(256))[:100]
    tbs = list(range(256))
    oracle = puzzle.python_search(nonce, 2, tbs)
    got = search(nonce, 2, tbs, batch_size=1 << 13)
    assert got is not None and got.secret == oracle


@pytest.mark.parametrize("difficulty", [2, 3])
def test_mesh_search_matches_single_device(difficulty):
    nonce = b"\x01\x02\x03\x04"
    tbs = list(range(256))
    mesh = make_mesh(jax.devices())
    oracle = puzzle.python_search(nonce, difficulty, tbs)
    got = search_mesh(
        nonce, difficulty, tbs, mesh=mesh, batch_size=1 << 14
    )
    assert got is not None
    assert got.secret == oracle


def test_mesh_search_sub_partition_and_chunk_split():
    mesh = make_mesh(jax.devices())
    nonce = b"\x03\x01\x04\x01"
    # tb-split: 64 tbs over 8 devices
    tbs = partition.thread_bytes(1, 2)
    oracle = puzzle.python_search(nonce, 2, tbs)
    got = search_mesh(nonce, 2, tbs, mesh=mesh, batch_size=1 << 13)
    assert got is not None and got.secret == oracle
    # chunk-split: fewer tbs than devices
    tbs = [5, 6, 7]
    oracle = puzzle.python_search(nonce, 2, tbs)
    got = search_mesh(nonce, 2, tbs, mesh=mesh, batch_size=1 << 13)
    assert got is not None and got.secret == oracle


def test_launch_steps_partition_independent():
    """The launch multiplier enters jit compile keys, so for a fixed
    effective batch it must not depend on which pow2 partition a request
    carries — else boot warmup (tbc=256) couldn't cover serving."""
    from distpow_tpu.parallel.search import effective_batch, launch_steps_for

    for batch_size in (1 << 13, 10_000, 1 << 21):
        E = effective_batch(batch_size)
        for vw in (1, 2, 3, 4):
            ks = {launch_steps_for(vw, E // tbc, tbc) for tbc in (256, 64, 8, 1)}
            assert len(ks) == 1, (batch_size, vw, ks)


def test_search_small_launch_budget_matches_oracle():
    """Multi-sub-batch dispatches (launch_steps > 1) preserve reference
    enumeration order across sub-batch boundaries."""
    nonce = b"\x0a\x0b\x0c\x0d"
    for d in (2, 3):
        oracle = puzzle.python_search(nonce, d, list(range(256)))
        got = search(
            nonce, d, list(range(256)), batch_size=1 << 13,
            launch_candidates=1 << 16,
        )
        assert got is not None and got.secret == oracle


def test_warmup_covers_sub_partitions_with_launch_steps():
    """A worker warmed on the full 256-byte partition serves a 4-way
    split (tbc=64) without any new dynamic compiles, launch multiplier
    included."""
    from distpow_tpu.backends import JaxBackend
    from distpow_tpu.ops.search_step import _dyn_search_step

    b = JaxBackend(batch_size=1 << 13)
    b.warmup([4], [0, 1, 2])
    misses = _dyn_search_step.cache_info().misses
    secret = b.search(b"\x01\x01\x02\x03", 2, list(range(64, 128)))
    assert secret is not None
    assert puzzle.check_secret(b"\x01\x01\x02\x03", secret, 2)
    assert _dyn_search_step.cache_info().misses == misses


def test_mesh_warmup_covers_all_pow2_partitions():
    """Boot warmup must pre-compile both mesh regimes, and batch_local
    must be partition-independent even when the configured batch size is
    not divisible by tbc * n_dev (e.g. 10_000 on 8 devices), so every
    pow2 partition's first Mine is pure dispatch."""
    from distpow_tpu.backends import JaxMeshBackend
    from distpow_tpu.parallel.mesh_search import _dyn_mesh_step

    b = JaxMeshBackend(batch_size=10_000)
    b.warmup([4], [0, 1])
    misses = _dyn_mesh_step.cache_info().misses
    n_dev = int(b._get_mesh().devices.size)
    for tbs in (list(range(256)),               # tb-split
                list(range(max(1, n_dev // 2))),  # chunk-split, warmed tbc
                [7],                             # chunk-split, other tbc
                list(range(4, 6))):
        secret = b.search(b"\x00\x01\x02\x03", 2, tbs)
        assert secret is not None
        assert puzzle.check_secret(b"\x00\x01\x02\x03", secret, 2)
    assert _dyn_mesh_step.cache_info().misses == misses, \
        "serving recompiled a program warmup should have covered"


def test_non_pow2_mesh_warns_at_boot_and_request_time(caplog):
    """A non-power-of-two mesh serves through per-request nonce-keyed
    compiles; both the boot warmup skip and the request-time compile must
    SAY so (VERDICT r2 weak #5) — a 6-device dev mesh should never stall
    silently."""
    import logging

    from distpow_tpu.backends import JaxMeshBackend

    b = JaxMeshBackend(batch_size=1 << 13, mesh_devices=6)
    with caplog.at_level(logging.WARNING):
        b.warmup([4], [0, 1])
        assert any("not a power of two" in r.message for r in caplog.records)
        caplog.clear()
        secret = b.search(b"\x09\x08", 2, list(range(256)))
        assert any("nonce-keyed static mesh program" in r.message
                   for r in caplog.records)
    assert secret is not None
    assert puzzle.check_secret(b"\x09\x08", secret, 2)


def test_mesh_search_cancellation():
    mesh = make_mesh(jax.devices())
    got = search_mesh(
        b"\x01", 30, list(range(256)), mesh=mesh, cancel_check=lambda: True
    )
    assert got is None


def _fuzz_configs(rng, n, max_difficulty=3):
    """Random (nonce, difficulty, thread_bytes) mining configs spanning
    the layout space: padding boundaries, multi-block nonces, sub- and
    single-byte partitions."""
    lens = [0, 1, 7, 54, 55, 56, 59, 63, 64, 65, 100, 111, 112, 127, 128,
            140, 200]
    for _ in range(n):
        nonce = bytes(rng.randrange(256) for _ in range(rng.choice(lens)))
        difficulty = rng.randint(1, max_difficulty)
        kind = rng.randrange(3)
        if kind == 0:
            tbs = list(range(256))
        elif kind == 1:
            size = rng.choice([2, 4, 16, 64, 128])
            lo = rng.randrange(0, 256 - size + 1, size)
            tbs = list(range(lo, lo + size))
        else:
            tbs = [rng.randrange(256)]
        yield nonce, difficulty, tbs


def _fuzz_against_oracle(models_algos, seed, n, max_difficulty=3,
                         configs=None):
    import random

    rng = random.Random(seed)
    for model, algo in models_algos:
        for nonce, difficulty, tbs in (
                configs if configs is not None
                else _fuzz_configs(rng, n, max_difficulty)):
            # The oracle generator is infinite, so it gets a candidate
            # budget (an unbounded call could never return None and the
            # exhausted arm would be dead — review r4).  The driver's
            # max_hashes is LAUNCH-QUANTIZED (pipelined in-flight
            # launches all count), so an exact shared budget can give
            # up one launch earlier than the oracle; the contract
            # tested is therefore budget-aware in each direction:
            # - oracle found after p candidates  => the driver, allowed
            #   p plus generous launch slack, finds the SAME secret;
            # - oracle exhausted the budget => the driver at that exact
            #   budget must also return None (its enumerated prefix
            #   never exceeds its counted hashes).
            budget = 1 << 16
            counted = [0]
            oracle = puzzle.python_search(
                nonce, difficulty, tbs, algo=algo, max_candidates=budget,
                on_progress=lambda k: counted.__setitem__(0, counted[0] + k),
            )
            case = (algo, nonce.hex()[:16], difficulty, tbs[0], len(tbs))
            # A segment-overrun launch may return a valid NON-CANONICAL
            # secret (non-minimal chunk encoding, trailing zero byte)
            # the oracle's minimal-encoding enumeration never visits —
            # legitimate per the puzzle contract (search.py module
            # docstring), so both arms accept it when it verifies.
            def wrapped(res):
                return (res is not None and res.chunk
                        and res.chunk[-1] == 0
                        and puzzle.check_secret(nonce, res.secret,
                                                difficulty, algo))

            if oracle is None:
                got = search(nonce, difficulty, tbs, model=model,
                             batch_size=1 << 12, max_hashes=budget)
                # pipelined launches legally overshoot max_hashes, so a
                # find PAST the budget is legitimate; a find the driver
                # claims was within it while the oracle saw none is the
                # only real divergence (review r4)
                assert got is None or wrapped(got) or (
                    got.hashes_tried > budget
                    and puzzle.check_secret(nonce, got.secret, difficulty,
                                            algo)
                ), case
            else:
                slack = (1 << 15) + 4 * (1 << 12)
                got = search(nonce, difficulty, tbs, model=model,
                             batch_size=1 << 12,
                             max_hashes=counted[0] + slack)
                assert got is not None and (
                    got.secret == oracle
                    # a wrapped alias may legitimately pre-empt the
                    # canonical solution, but only from a launch at or
                    # before it — a wrapped find far past the oracle
                    # position would mean a skipped canonical hit
                    # (review r4)
                    or (wrapped(got)
                        and got.hashes_tried <= counted[0] + slack)
                ), case


def test_scaled_launch_budget_tracks_model_cost():
    """Backends' default per-dispatch budget scales inversely with
    HashModel.cost_ops so one launch's wall-clock — the cancellation
    granularity — is roughly model-independent (the fixed 2^30 budget
    quantized sha512/sha3 solves to ~2-4 s steps,
    docs/artifacts/r4c/e2e_models.json).  An explicit max_launch must
    still win."""
    from distpow_tpu.backends import JaxBackend
    from distpow_tpu.models.registry import get_hash_model
    from distpow_tpu.parallel.search import (
        DEFAULT_LAUNCH_CANDIDATES,
        scaled_launch_candidates,
    )

    md5 = get_hash_model("md5")
    assert scaled_launch_candidates(md5.cost_ops) == DEFAULT_LAUNCH_CANDIDATES
    prev = DEFAULT_LAUNCH_CANDIDATES + 1
    for mname in ("md5", "sha1", "ripemd160", "sha256", "sha512"):
        got = scaled_launch_candidates(get_hash_model(mname).cost_ops)
        assert 1 << 24 <= got <= DEFAULT_LAUNCH_CANDIDATES
        assert got < prev, (mname, got)  # strictly costlier -> smaller
        prev = got
    # floor holds even for absurd costs
    assert scaled_launch_candidates(10**9) == 1 << 24
    # backends consume the scale; explicit config bypasses it
    assert JaxBackend(hash_model="sha512").max_launch == \
        scaled_launch_candidates(get_hash_model("sha512").cost_ops)
    assert JaxBackend(hash_model="sha512", max_launch=12345).max_launch \
        == 12345


def test_search_differential_fuzz_fast():
    """Seeded differential fuzz: random layouts/partitions vs the
    hashlib oracle (md5 only here — every novel nonce length is a fresh
    layout compile, so the fast path keeps a small n; the slow twin
    covers the full registry).  This family of bugs is real — the
    all-constant-tail-block crash (round 4) lived exactly in a layout
    combination no systematic parametrization covered."""
    from distpow_tpu.models.registry import MD5

    _fuzz_against_oracle([(MD5, "md5")], seed=0xF00D, n=5)


@pytest.mark.slow
def test_search_differential_fuzz_all_models():
    """The full-registry fuzz, budgeted (VERDICT r4 item 6: the old
    shared-stream version was the full suite's dominant item at
    ~300-470 s).  Every model still fuzzes against the hashlib oracle
    on random layouts every full run — the coverage class is intact —
    but each model draws its OWN fixed per-model seed (crc32 of the
    name) and a per-model config count sized to its measured XLA:CPU
    layout-compile cost (``_fuzz_schedules``); the nightly veryslow
    twin below runs the unshrunk n=3-for-all schedule, and the
    md5-only fast fuzz covers the high-frequency layouts on every
    fast-path run."""
    import zlib

    for model, algo, n, maxd in _fuzz_schedules():
        if n > 0:
            _fuzz_against_oracle(
                [(model, algo)], seed=zlib.crc32(algo.encode()) ^ 0x5EED,
                n=n, max_difficulty=maxd)
    # sha3/blake2b: a RANDOM config routinely lands on a layout whose
    # XLA:CPU loop-form compile alone costs 40-70 s (r5 durations), so
    # the slow tier pins their device-vs-oracle coverage with fixed
    # cheap-layout configs (~7-12 s each: short nonce, full partition,
    # one width segment) and leaves the random draws to the nightly
    # twin.
    from distpow_tpu.models.registry import BLAKE2B_256, SHA3_256

    _fuzz_against_oracle([(SHA3_256, "sha3_256")], seed=0, n=0,
                         configs=[(b"\x0c", 2, list(range(256)))])
    _fuzz_against_oracle([(BLAKE2B_256, "blake2b_256")], seed=0, n=0,
                         configs=[(b"", 2, list(range(256)))])


def _fuzz_schedules():
    """(model, algo, n_slow, max_difficulty) per registry model.

    n is budgeted by measured per-config cost on XLA:CPU (r5: a fresh
    layout of the sha3/blake2b loop forms costs ~40-70 s there, vs
    ~2 s for md5 — those two run fixed cheap configs in the slow tier
    instead, n=0 here) so the slow tier stays inside the suite's
    10-min target; the nightly twin below runs n=3 for every model."""
    from distpow_tpu.models.registry import (
        BLAKE2B_256, MD5, RIPEMD160, SHA1, SHA3_256, SHA256, SHA384,
        SHA512,
    )

    from distpow_tpu.models.registry import SHA256D

    return (
        (MD5, "md5", 3, 3), (SHA1, "sha1", 3, 3),
        (SHA256, "sha256", 3, 3), (RIPEMD160, "ripemd160", 3, 3),
        (SHA512, "sha512", 2, 2), (SHA384, "sha384", 1, 2),
        (SHA3_256, "sha3_256", 0, 2), (BLAKE2B_256, "blake2b_256", 0, 2),
        (SHA256D, "sha256d", 2, 3),
    )


@pytest.mark.veryslow
def test_search_differential_fuzz_registry_nightly():
    """The unshrunk registry fuzz for the nightly veryslow tier — n=3
    random configs for every model from the same fixed per-model
    seeds, PLUS the slow tier's fixed cheap-layout sha3/blake2b
    configs, so the nightly is a strict superset of the slow tier's
    schedule and budgeting the slow tier deleted no coverage class
    (VERDICT r4 item 6)."""
    import zlib

    from distpow_tpu.models.registry import BLAKE2B_256, SHA3_256

    for model, algo, _, maxd in _fuzz_schedules():
        _fuzz_against_oracle(
            [(model, algo)], seed=zlib.crc32(algo.encode()) ^ 0x5EED,
            n=3, max_difficulty=maxd)
    _fuzz_against_oracle([(SHA3_256, "sha3_256")], seed=0, n=0,
                         configs=[(b"\x0c", 2, list(range(256)))])
    _fuzz_against_oracle([(BLAKE2B_256, "blake2b_256")], seed=0, n=0,
                         configs=[(b"", 2, list(range(256)))])


def test_early_exits_account_all_dispatched_work():
    """Every exit path — cancel mid-pipeline, found mid-pipeline — must
    leave search.hashes equal to the TOTAL dispatched candidates,
    including launches still in flight (the device completes them
    either way; round 4).  A fake step factory pins launch sizes so the
    expected totals are exact, independent of the real launch
    multiplier."""
    import jax.numpy as jnp

    from distpow_tpu.ops.search_step import SENTINEL
    from distpow_tpu.runtime.metrics import REGISTRY

    dispatched = [0]

    def make_factory(hit_on_launch=None):
        launches = [0]

        def factory(vw, extra, target_chunks, launch_steps=1):
            # 5 divides every early segment's chunk count exactly
            # (width1: 255, width2: 65280, width3: 16711680), so no
            # launch straddles a segment end and the fake's per-launch
            # count matches the driver's min(chunks, hi - chunk0) clamp
            # on every launch (review r4)
            chunks = 5 if vw else 1

            def step(chunk0):
                launches[0] += 1
                dispatched[0] += chunks * 256
                if hit_on_launch is not None and launches[0] == hit_on_launch:
                    return jnp.uint32(0)  # flat index 0 of this launch
                return jnp.uint32(SENTINEL)

            return step, chunks
        return factory

    # cancel mid-segment with a launch in flight
    calls = [0]

    def cancel_after(n):
        def check():
            calls[0] += 1
            return calls[0] > n
        return check

    dispatched[0] = 0
    before = REGISTRY.get("search.hashes")
    got = search(b"\x01", 30, list(range(256)), batch_size=1 << 10,
                 cancel_check=cancel_after(6),
                 step_factory=make_factory())
    assert got is None
    assert REGISTRY.get("search.hashes") - before == dispatched[0] > 0

    # found mid-pipeline: the undrained trailing launch still counts.
    # hit on launch 4 (width 1, flat index 0 -> chunk 1, tb 0 solves
    # nothing real, so use difficulty 0 where everything solves)
    dispatched[0] = 0
    before = REGISTRY.get("search.hashes")
    got = search(b"\x01", 0, list(range(256)), batch_size=1 << 10,
                 step_factory=make_factory(hit_on_launch=4))
    assert got is not None
    assert REGISTRY.get("search.hashes") - before == dispatched[0] > 0


@pytest.mark.slow
def test_mesh_search_differential_fuzz():
    """Seeded mesh fuzz: random power-of-two partitions (including
    sub-runs, single bytes, and fewer-tbs-than-devices chunk-split
    configs) through shard_map vs the hashlib oracle — the mesh twin of
    test_search_differential_fuzz_*."""
    import random

    mesh = make_mesh(jax.devices())
    rng = random.Random(0xD1CE)
    lens = [0, 2, 55, 63, 64, 100, 112]
    for _ in range(10):
        nonce = bytes(rng.randrange(256) for _ in range(rng.choice(lens)))
        difficulty = rng.randint(1, 2)
        size = rng.choice([1, 2, 4, 8, 64, 256])  # incl. < 8 devices
        lo = rng.randrange(0, 256 - size + 1, size)
        tbs = list(range(lo, lo + size))
        oracle = puzzle.python_search(nonce, difficulty, tbs)
        got = search_mesh(nonce, difficulty, tbs, mesh=mesh,
                          batch_size=1 << 12)
        case = (nonce.hex()[:12], difficulty, lo, size)
        # same wrapped-alias tolerance as _fuzz_against_oracle: a
        # segment-overrun launch may legitimately return a verified
        # non-canonical secret (search.py batch-boundary note), and
        # launch quantization here depends on the device count
        assert got is not None, case
        assert got.secret == oracle or (
            got.chunk and got.chunk[-1] == 0
            and puzzle.check_secret(nonce, got.secret, difficulty)
        ), case
