"""Tiered time-series retention (distpow_tpu/obs/timeseries.py,
ISSUE 18): last-point-per-interval downsampling vs a full-resolution
oracle (bit-identical at retained boundaries, within one log-grid
bucket otherwise), tier eviction, windowed delta/rate queries, gauge
trajectories, and the rotated-JSONL spool round-trip."""

from __future__ import annotations

import math
import random

import pytest

from distpow_tpu.obs.merge import BUCKET_RATIO
from distpow_tpu.obs.timeseries import (
    DEFAULT_TIERS,
    TimeSeriesStore,
    Tier,
    replay_spool,
)
from distpow_tpu.runtime.metrics import Histogram

T0 = 1_000_000.0  # divisible by every tier resolution used below


def snap(ts, hist=None, counters=None, gauges=None, per_node=None):
    """A minimal merged cluster snapshot (obs/merge.py shape)."""
    return {
        "ts": ts,
        "nodes": 1,
        "counters": dict(counters or {}),
        "gauges": dict(gauges or {}),
        "histograms": {"worker.solve_s": hist} if hist else {},
        "per_node": dict(per_node or {}),
        "per_model": {},
        "stale_nodes": [],
    }


# -- tier mechanics ----------------------------------------------------------

def test_finest_tier_keeps_all_coarse_keeps_last_per_interval():
    store = TimeSeriesStore(tiers=(Tier(0.0, 1e9), Tier(10.0, 1e9)))
    for i in range(25):
        store.append(snap(T0 + i, counters={"x": i}))
    assert [t - T0 for t, _ in store.tier_points(0)] == list(range(25))
    # 10 s tier: the LAST cumulative snapshot of each interval wins
    assert [t - T0 for t, _ in store.tier_points(1)] == [9.0, 19.0, 24.0]
    assert store.tier_points(1)[0][1]["counters"]["x"] == 9


def test_retention_evicts_points_older_than_the_tier_window():
    store = TimeSeriesStore(tiers=(Tier(0.0, 30.0),))
    for i in range(61):
        store.append(snap(T0 + i))
    pts = store.tier_points(0)
    assert pts[0][0] >= T0 + 30.0 and pts[-1][0] == T0 + 60.0


def test_len_counts_distinct_points_across_tiers():
    store = TimeSeriesStore(tiers=DEFAULT_TIERS)
    for i in range(12):
        store.append(snap(T0 + i))
    # every point is in the finest tier; coarser tiers hold subsets
    assert len(store) == 12


def test_append_defaults_to_the_snapshot_own_ts():
    store = TimeSeriesStore(tiers=(Tier(0.0, 1e9),))
    store.append(snap(T0 + 5.5))
    assert store.latest()[0] == T0 + 5.5


def test_snapshot_at_resolves_finest_tier_first():
    store = TimeSeriesStore(tiers=(Tier(0.0, 1e9), Tier(10.0, 1e9)))
    for i in range(25):
        store.append(snap(T0 + i, counters={"x": i}))
    t, m = store.snapshot_at(T0 + 17.4)
    assert t == T0 + 17.0 and m["counters"]["x"] == 17


# -- downsampling vs the full-resolution oracle ------------------------------

def _cumulative_stores(n_seconds, seed, per_step=20):
    """One-per-second cumulative snapshots of one latency stream, fed
    to a full-resolution store and a 10 s-downsampled store."""
    rng = random.Random(seed)
    full = TimeSeriesStore(tiers=(Tier(0.0, 1e9),))
    coarse = TimeSeriesStore(tiers=(Tier(10.0, 1e9),))
    h = Histogram()
    for i in range(n_seconds + 1):
        for _ in range(per_step):
            h.observe(rng.lognormvariate(-3.0, 0.6))
        m = snap(T0 + i, hist=h.to_dict(),
                 counters={"coord.requests": (i + 1) * per_step})
        full.append(m)
        coarse.append(m)
    return full, coarse


def test_range_window_bit_identical_at_retained_boundaries():
    """Tier math (timeseries.py docstring): deltas between two RETAINED
    snapshots are exact, so when the query boundaries land on points the
    coarse tier kept, the downsampled answer EQUALS the oracle."""
    full, coarse = _cumulative_stores(240, seed=1807)
    wf = full.range_window(T0 + 19.0, T0 + 239.0)
    wc = coarse.range_window(T0 + 19.0, T0 + 239.0)
    assert wf == wc


@pytest.mark.parametrize("start_s,end_s", [
    (30.5, 235.0),
    (47.3, 180.2),
    (0.0, 240.0),
    (75.9, 120.1),
])
def test_downsampled_percentile_within_one_bucket_of_oracle(start_s, end_s):
    """Off-boundary queries shift the window edge up to one resolution
    step earlier; the percentile estimate must stay within one log-grid
    bucket (~19%) of the full-resolution oracle — the same bound the
    PR 7 merge pins."""
    full, coarse = _cumulative_stores(240, seed=1808)
    wf = full.range_window(T0 + start_s, T0 + end_s)
    wc = coarse.range_window(T0 + start_s, T0 + end_s)
    for q in ("p50", "p95", "p99"):
        pf = wf["histograms"]["worker.solve_s"][q]
        pc = wc["histograms"]["worker.solve_s"][q]
        assert pf is not None and pc is not None
        assert max(pf, pc) / min(pf, pc) <= BUCKET_RATIO * (1 + 1e-9), (
            f"{q}: full {pf} vs downsampled {pc} drifted more than "
            f"one bucket")


def test_downsampled_counter_delta_bounded_by_boundary_shift():
    """Counters grow 20/s here, so a window widened by at most one 10 s
    resolution step can over-count by at most 200."""
    full, coarse = _cumulative_stores(240, seed=1809)
    wf = full.range_window(T0 + 47.3, T0 + 180.2)
    wc = coarse.range_window(T0 + 47.3, T0 + 180.2)
    df = wf["counters"]["coord.requests"]
    dc = wc["counters"]["coord.requests"]
    assert abs(dc - df) <= 200


# -- windowed queries --------------------------------------------------------

def test_window_degrades_to_cumulative_then_oldest():
    store = TimeSeriesStore(tiers=(Tier(0.0, 1e9),))
    assert store.window(60.0) is None
    store.append(snap(T0, counters={"x": 10}))
    # one point: the latest snapshot stands as-is (cumulative)
    assert store.window(60.0)["counters"]["x"] == 10
    store.append(snap(T0 + 5.0, counters={"x": 30}))
    # history shallower than the window: the oldest point stands in
    win = store.window(60.0)
    assert win["counters"]["x"] == 20 and win["window_s"] == 5.0


def test_range_window_none_without_a_point_before_end():
    store = TimeSeriesStore(tiers=(Tier(0.0, 1e9),))
    store.append(snap(T0 + 50.0))
    assert store.range_window(T0, T0 + 10.0) is None


def test_counter_rate_over_window():
    store = TimeSeriesStore(tiers=(Tier(0.0, 1e9),))
    store.append(snap(T0, counters={"coord.requests": 0}))
    store.append(snap(T0 + 10.0, counters={"coord.requests": 50}))
    assert store.counter_rate("coord.requests", 10.0) == pytest.approx(5.0)
    assert store.counter_rate("coord.nope", 10.0) == 0.0


def test_gauge_series_fleet_per_node_and_window():
    store = TimeSeriesStore(tiers=(Tier(0.0, 1e9), Tier(10.0, 1e9)))
    for i in range(30):
        store.append(snap(
            T0 + i, gauges={"proc.threads": 10.0 + i},
            per_node={"w0": {"gauges": {"proc.threads": 4.0 + i}}}))
    series = store.gauge_series("proc.threads")
    # deduped across tiers: one entry per distinct timestamp
    assert len(series) == 30
    assert series[0] == (T0, 10.0) and series[-1] == (T0 + 29, 39.0)
    node = store.gauge_series("proc.threads", node="w0")
    assert node[-1] == (T0 + 29, 33.0)
    recent = store.gauge_series("proc.threads", window_s=5.0)
    assert [t - T0 for t, _ in recent] == [24.0, 25, 26, 27, 28, 29]
    assert store.gauge_series("proc.absent") == []
    assert "proc.threads" in store.gauge_names()


# -- JSONL spool -------------------------------------------------------------

def test_spool_rotates_and_replays_oldest_first(tmp_path):
    path = str(tmp_path / "spool.jsonl")
    store = TimeSeriesStore(tiers=(Tier(0.0, 1e9),), spool_path=path,
                            spool_max_bytes=2048, spool_keep=8)
    for i in range(20):
        store.append(snap(T0 + i, counters={"x": i},
                          gauges={"proc.threads": float(i)}))
    assert (tmp_path / "spool.jsonl.1").exists()  # size cap forced rotation
    replayed = list(replay_spool(path))
    assert [t - T0 for t, _ in replayed] == list(range(20))

    rebuilt = TimeSeriesStore(tiers=(Tier(0.0, 1e9),))
    for ts, merged in replayed:
        rebuilt.append(merged, ts)
    assert rebuilt.latest() == store.latest()
    assert rebuilt.window(10.0) == store.window(10.0)
    assert rebuilt.gauge_series("proc.threads") == \
        store.gauge_series("proc.threads")


def test_replay_skips_corrupt_lines(tmp_path):
    path = str(tmp_path / "spool.jsonl")
    store = TimeSeriesStore(tiers=(Tier(0.0, 1e9),), spool_path=path)
    store.append(snap(T0))
    with open(path, "a") as fh:
        fh.write("not json\n")
        fh.write('{"ts": "oops", "merged": {}}\n')
    store.append(snap(T0 + 1))
    assert [t - T0 for t, _ in replay_spool(path)] == [0.0, 1.0]


# -- construction guards -----------------------------------------------------

def test_bad_tier_configs_rejected():
    with pytest.raises(ValueError):
        TimeSeriesStore(tiers=())
    with pytest.raises(ValueError):
        TimeSeriesStore(tiers=(Tier(10.0, 0.0),))


def test_default_tiers_are_sorted_and_sane():
    assert [t.resolution_s for t in DEFAULT_TIERS] == [0.0, 10.0, 60.0]
    assert all(t.retention_s > 0 for t in DEFAULT_TIERS)
    assert math.isfinite(BUCKET_RATIO) and BUCKET_RATIO > 1.0
