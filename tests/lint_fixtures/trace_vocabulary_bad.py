"""Fixture: trace-vocabulary must flag undeclared actions and
out-of-band Action subclasses."""

from dataclasses import dataclass

from distpow_tpu.runtime import actions as act
from distpow_tpu.runtime.actions import Action


@dataclass(frozen=True)
class WorkerSideChannel(Action):  # line 11: subclass outside actions.py
    nonce: bytes


def record(trace, nonce):
    trace.record_action(
        act.WorkerFrobnicate(nonce=nonce)  # line 17: undeclared action
    )
    trace.record_action(
        act.CoordinatorMinee(nonce=nonce, num_trailing_zeros=4)  # typo'd
    )
