"""Fixture: the clean shapes no-blocking-under-lock must NOT flag."""

import threading
import time

_lock = threading.Lock()


class Node:
    def __init__(self, client, sock, backend, ev):
        self._state_lock = threading.Lock()
        self.client = client
        self.sock = sock
        self.backend = backend
        self.ev = ev
        self.pending = []

    def snapshot_then_send(self):
        # blocking work AFTER the critical section is the sanctioned shape
        with self._state_lock:
            frame = bytes(self.pending.pop())
        self.sock.sendall(frame)
        time.sleep(0.1)
        return self.client.call("Service.Method", {})

    def callback_defined_under_lock(self):
        # a nested def under the lock runs LATER, outside the hold
        with self._state_lock:
            def later():
                return self.backend.search(b"n", 4, [0])
        return later

    def regex_is_not_io(self, pattern):
        import re
        with _lock:
            return re.search(pattern, "haystack")
