"""Fixture: declared config-key reads config-key-sync must accept."""


class Worker:
    def __init__(self, config):
        self.config = config

    def boot(self, args):
        backend = self.config.Backend
        hang = float(getattr(self.config, "DeviceHangTimeoutS", 0.0) or 0.0)
        # lowercase attributes are methods/derived state, not JSON keys
        as_dict = self.config.to_dict() if hasattr(self.config, "to_dict") \
            else None
        # non-config receivers are out of scope (argparse namespaces)
        path = args.config
        return backend, hang, as_dict, path
