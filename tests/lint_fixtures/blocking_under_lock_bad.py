"""Fixture: no-blocking-under-lock must fire on every blocking kind."""

import threading
import time

_lock = threading.Lock()


class Node:
    def __init__(self, client, sock, backend, ev):
        self._state_lock = threading.Lock()
        self.client = client
        self.sock = sock
        self.backend = backend
        self.ev = ev

    def bad_rpc_under_lock(self):
        with self._state_lock:
            return self.client.call("Service.Method", {})  # line 19: call

    def bad_send_and_sleep(self):
        with _lock:
            self.sock.sendall(b"frame")  # line 23: sendall
            time.sleep(0.1)  # line 24: sleep

    def bad_search_and_wait(self):
        with self._state_lock:
            secret = self.backend.search(b"n", 4, [0])  # line 28: search
            self.ev.wait(1.0)  # line 29: wait
            return secret
