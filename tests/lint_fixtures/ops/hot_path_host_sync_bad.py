"""Fixture (scope: ops/): hot-path-host-sync must flag host syncs."""

import numpy as np

import jax


def drain(results, launch):
    first = results[0].item()  # line 9: .item()
    host = np.asarray(results[1])  # line 10: np.asarray
    copied = np.array(results[2])  # line 11: np.array
    fetched = jax.device_get(results[3])  # line 12: device_get
    launch.block_until_ready()  # line 13: block_until_ready
    return first, host, copied, fetched
