"""Fixture (scope: ops/): device-side shapes hot-path-host-sync accepts."""

import jax.numpy as jnp


def step(state, masks):
    # jnp.asarray is device-side — exempt
    init = jnp.asarray(state, jnp.uint32)
    mask = jnp.asarray(masks, jnp.uint32)
    # int() on a drained FIFO result is the sanctioned sync point and
    # deliberately not in the flagged set (the driver owns it)
    return init & mask
