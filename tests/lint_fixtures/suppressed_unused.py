"""Fixture: a suppression matching no finding is reported as
unused-suppression — stale suppressions must not rot in the tree."""


def nothing_wrong_here():
    # distpow: ok no-blocking-under-lock -- stale: the lock is long gone
    return 42
