"""Fixture: config-key-sync must flag undeclared config keys."""


def boot(config):
    backend = config.Backend  # declared: fine
    batch = config.BatchSzie  # line 6: typo of BatchSize
    cache = getattr(config, "CacheFiIe", "")  # line 7: typo of CacheFile
    return backend, batch, cache


def rebind(cfg):
    cfg.ListenAddress = ":0"  # line 12: field is ListenAddr
