"""unclosed-span fixtures: raw begin sites the rule must flag."""

from distpow_tpu.runtime.spans import SPANS


def leaks_on_early_return(items):
    sp = SPANS.begin("sched.slot", seq=1)  # finding: raw begin
    if not items:
        return None  # sp never finishes on this path
    sp.finish()
    return items


def leaks_on_exception(nonce):
    handle = SPANS.begin("worker.solve", shard=0)  # finding: raw begin
    value = int(nonce)  # a raise here loses the span
    handle.finish(outcome="found")
    return value


class Loop:
    def __init__(self, spans):
        self.spans = spans

    def open_one(self):
        # finding: begin through a lowercase alias receiver
        return self.spans.begin("sched.slot", seq=2)
