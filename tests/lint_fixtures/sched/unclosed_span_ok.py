"""unclosed-span fixtures: the sanctioned shapes the rule must pass."""

import time

from distpow_tpu.runtime.spans import SPANS


def context_managed(nonce):
    # the blessed form: cannot leak, error exits record an outcome
    with SPANS.span("worker.solve", shard=0) as sp:
        value = int(nonce)
        sp.annotate(outcome="found")
    return value


def one_shot_record():
    # explicit-timing recorders have no open state to leak
    t0 = time.time()
    SPANS.record("search.launch", t0, 0.01, n_cand=256)
    SPANS.event("coord.reassign", shard=3)


def cross_thread_handle():
    # distpow: ok unclosed-span -- the handle crosses to the device
    # loop, whose _finish() is the single exit point for every slot
    # outcome and finishes it exactly once
    return SPANS.begin("sched.slot", seq=3)
