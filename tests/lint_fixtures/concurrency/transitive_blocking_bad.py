"""Fixture: transitive-blocking-under-lock fires (ISSUE 17).

Expected findings (2):
  * ``Cache.lookup`` calls ``_fetch`` under ``_lock``; the blocking
    ``time.sleep`` sits TWO call hops away (``_fetch`` → ``_pull``) —
    invisible to the lexical rule, caught by the bounded summaries;
  * ``CondHolder.drain`` sleeps directly under a ``Condition`` named
    ``_cond`` — a discovered lock whose name the lexical rule cannot
    recognize, so this rule owns the finding.
"""

import threading
import time


class Cache:
    def __init__(self):
        self._lock = threading.Lock()
        self.data = {}

    def lookup(self, key):
        with self._lock:
            return self._fetch(key)  # BAD: blocks 2 hops down

    def _fetch(self, key):
        if key not in self.data:
            self.data[key] = self._pull(key)
        return self.data[key]

    def _pull(self, key):
        time.sleep(0.1)  # simulated slow origin fetch
        return key


class CondHolder:
    def __init__(self):
        self._cond = threading.Condition()

    def drain(self):
        with self._cond:
            time.sleep(0.01)  # BAD: direct block under a discovered lock
