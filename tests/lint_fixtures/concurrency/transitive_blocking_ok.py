"""Fixture: transitive-blocking-under-lock clean shapes (ISSUE 17).

Blessed: the tree's standard snapshot-under-lock-act-after shape, the
canonical ``Condition.wait`` loop (wait RELEASES the held condition,
so it is exempt), and the justified-suppression protocol.
"""

import threading
import time


class Cache:
    def __init__(self):
        self._lock = threading.Lock()
        self.data = {}

    def lookup(self, key):
        with self._lock:
            hit = self.data.get(key)
        if hit is None:  # slow path runs OUTSIDE the critical section
            hit = self._pull(key)
            with self._lock:
                self.data[key] = hit
        return hit

    def _pull(self, key):
        time.sleep(0.1)
        return key


class CondWaiter:
    def __init__(self):
        self._cond = threading.Condition()
        self.items = []

    def take(self):
        with self._cond:
            while not self.items:
                self._cond.wait(timeout=0.1)  # releases _cond: exempt
            return self.items.pop()

    def put(self, x):
        with self._cond:
            self.items.append(x)
            self._cond.notify_all()


class DeliberateSerializer:
    def __init__(self):
        self._lock = threading.Lock()

    def exclusive_pull(self, key):
        with self._lock:
            # distpow: ok transitive-blocking-under-lock -- the lock IS
            # the serializer: exactly one puller per key by design
            return self._pull(key)

    def _pull(self, key):
        time.sleep(0.1)
        return key
