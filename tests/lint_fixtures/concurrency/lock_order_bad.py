"""Fixture: lock-order-inversion fires on an INDIRECT cycle (ISSUE 17).

``forward`` acquires ``_a`` and then calls ``_grab_b`` — the edge
a → b exists only through the call summary, not lexically.
``backward`` nests ``_a`` under ``_b`` lexically.  Together: one
cycle, one finding (per strongly-connected component, not per edge).
"""

import threading


class Inverted:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self.n = 0

    def forward(self):
        with self._a:
            self._grab_b()  # a -> b via the bounded call summary

    def _grab_b(self):
        with self._b:
            self.n += 1

    def backward(self):
        with self._b:
            with self._a:  # b -> a lexically: the inversion
                self.n -= 1
