"""Fixture: unguarded-shared-write fires on both tiers (ISSUE 17).

Expected findings (3):
  * ``Annotated.state`` — declared ``# guarded-by: self._mu``, written
    bare in ``bad_write``;
  * ``Annotated.count`` — declared guard, READ bare in ``bad_read``
    (the annotation tier flags reads too);
  * ``Heuristic.total`` — written under ``_lock`` in ``locked_add``
    and bare in ``bare_add`` (the discovered tier).
"""

import threading


class Annotated:
    """Declared discipline: annotated attrs demand the lock on every
    access, reads included."""

    def __init__(self):
        self._mu = threading.Lock()
        self.state = "idle"  # guarded-by: self._mu
        self.count = 0  # guarded-by: self._mu

    def advance(self):
        with self._mu:
            self.state = "busy"
            self.count += 1

    def bad_write(self):
        self.state = "done"  # BAD: annotated attr, no lock held

    def bad_read(self):
        return self.count  # BAD: annotated read, no lock held


class Heuristic:
    """Discovered discipline: mixed locked/bare writes, no annotation."""

    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0

    def locked_add(self, n):
        with self._lock:
            self.total += n

    def bare_add(self, n):
        self.total += n  # BAD: the same attr is lock-disciplined above
