"""Fixture: lock-order-inversion clean shapes (ISSUE 17).

Blessed: a single global order (always a before b) — lexically, via a
helper call, and each lock alone; re-entrant single-lock use is not a
cycle.
"""

import threading


class Ordered:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self.n = 0

    def direct(self):
        with self._a:
            with self._b:
                self.n += 1

    def via_helper(self):
        with self._a:
            self._grab_b()  # same a -> b direction as `direct`

    def _grab_b(self):
        with self._b:
            self.n += 1

    def b_alone(self):
        with self._b:
            self.n -= 1
