"""Fixture: unguarded-shared-write clean shapes (ISSUE 17).

Blessed: every access of an annotated attr under the declared lock —
including THROUGH a helper whose only call sites hold it (entry-lock
credit) — plus the justified-suppression protocol for a deliberate
lock-free invariant, and ``__init__`` writes (pre-publication).
"""

import threading


class Annotated:
    def __init__(self):
        self._mu = threading.Lock()
        self.state = "idle"  # guarded-by: self._mu

    def set_state(self, s):
        with self._mu:
            self.state = s

    def read_state(self):
        with self._mu:
            return self.state

    def _advance_locked(self):
        # every visible call site holds _mu -> this write inherits it
        self.state = "advanced"

    def advance(self):
        with self._mu:
            self._advance_locked()


class DeliberateHotPath:
    def __init__(self):
        self._lock = threading.Lock()
        self.beat = 0.0

    def locked_set(self, t):
        with self._lock:
            self.beat = t

    def hot_set(self, t):
        # distpow: ok unguarded-shared-write -- GIL-atomic float store
        # on the hot path; the staleness window tolerates a lost beat
        self.beat = t
