"""Fixture: a package with a real module is not dead."""

VALUE = 1
