"""Fixture: a suppression WITHOUT a justification is itself a finding
(bare-suppression) — the original finding stays silenced but the
policy violation surfaces."""

import threading
import time

_lock = threading.Lock()


def unjustified_hold():
    with _lock:
        time.sleep(0.01)  # distpow: ok no-blocking-under-lock
