"""Fixture: declared series names metrics-registry must accept."""

from distpow_tpu.runtime.metrics import REGISTRY as metrics

TOTAL = "compile_cache.errors"
SOLVE_HIST = "worker.solve_s"


def hot_path(kind, dt, dynamic_name):
    metrics.inc("coord.fanouts")
    metrics.inc("search.hashes", 1024)
    metrics.inc(TOTAL)
    metrics.inc(f"faults.injected.{kind}")
    metrics.observe("coord.first_result_s", dt)
    metrics.observe(SOLVE_HIST, dt)
    metrics.observe(f"rpc.client.call_s.{kind}", dt)
    with metrics.time("powlib.mine_s"):
        pass
    metrics.gauge("proc.rss_bytes", dt)
    metrics.gauge("ring.spans_depth", dt)
    # fully dynamic names are a documented limitation, not a finding
    metrics.inc(dynamic_name)
    metrics.observe(dynamic_name, dt)
    metrics.gauge(dynamic_name, dt)
