"""Fixture: declared counters metrics-registry must accept."""

from distpow_tpu.runtime.metrics import REGISTRY as metrics

TOTAL = "compile_cache.errors"


def hot_path(kind, dynamic_name):
    metrics.inc("coord.fanouts")
    metrics.inc("search.hashes", 1024)
    metrics.inc(TOTAL)
    metrics.inc(f"faults.injected.{kind}")
    # fully dynamic names are a documented limitation, not a finding
    metrics.inc(dynamic_name)
