"""Fixture: the sanctioned scrape shapes must not trip
serial-rpc-fanout in obs/."""

import subprocess
import threading


def concurrent_sweep(targets, deadline):
    # the sanctioned shape: one poll thread per node, all bounded by
    # one shared deadline — the obs/scrape.py structure
    threads = []
    for t in targets:
        def poll(t=t):
            # nested function body: executes on its own thread, outside
            # the loop's dynamic extent
            return t.client.call("CoordRPCHandler.Stats", {},
                                 timeout=deadline)
        th = threading.Thread(target=poll, daemon=True)
        th.start()
        threads.append(th)
    return threads


def futures_then_await(targets):
    futs = [t.client.go("WorkerRPCHandler.Stats", {}) for t in targets]
    for fut in futs:
        fut.result(timeout=5.0)


def not_a_peer_collection(rows):
    for row in rows:
        row.client.call("CoordRPCHandler.Stats", row)


def subprocess_is_not_rpc(node_cmds):
    for cmd in node_cmds:
        subprocess.call(cmd)
