"""Fixture: serial-rpc-fanout must fire in obs/ too — a sequential
Stats scrape loop is the nodes/ fan-out bug one layer up (3 findings)."""


def sweep_serial(self, targets):
    snaps = {}
    for t in targets:
        snaps[t.name] = t.client.call("CoordRPCHandler.Stats", {})  # 1
    return snaps


def poll_states(states):
    for st in {id(s): s for s in states}.values():
        st.client.call("WorkerRPCHandler.Stats", {}, timeout=2.0)  # 2


def nested_node_groups(node_groups):
    for group in node_groups:
        for n in group:
            n.call("X.Stats", {})  # 3 (nested loop, same scope)
