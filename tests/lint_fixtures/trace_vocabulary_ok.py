"""Fixture: declared-vocabulary usage trace-vocabulary must accept."""

from distpow_tpu.runtime import actions as act
from distpow_tpu.runtime.actions import CacheAdd


def record(trace, nonce, secret):
    trace.record_action(
        act.WorkerMine(nonce=nonce, num_trailing_zeros=4, worker_byte=0)
    )
    trace.record_action(
        CacheAdd(nonce=nonce, num_trailing_zeros=4, secret=secret)
    )
    # lowercase attributes on the alias are not action constructions
    return act.Action
