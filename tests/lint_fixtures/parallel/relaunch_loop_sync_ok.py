"""Fixture (scope: parallel/): shapes relaunch-loop-sync accepts."""


def sanctioned_drain(inflight):
    # the drain helper: the conversion lives OUTSIDE any dispatch loop
    # (its caller drains one launch per boundary)
    res = inflight.popleft()
    return int(res)


def dispatch_loop(step, chunks, drain):
    chunk0 = 0
    while chunk0 < chunks:
        res = step(chunk0)
        drain(res)  # draining through the helper, not converting here
        chunk0 += int(bool(res is not None))  # int(Call): host arithmetic
    return chunk0


def host_arithmetic(items):
    total = 0
    for it in items:
        total += int(len(repr(it)))  # int over a host call, not a sync
    return total
