"""Fixture (scope: parallel/): relaunch-loop-sync must flag blocking
result conversions inside dispatch loops."""


def relaunch_loop(step, chunks):
    results = []
    chunk0 = 0
    while chunk0 < chunks:
        res = step(chunk0)
        f = int(res)  # line 10: blocking conversion per launch
        results.append(f)
        chunk0 += 1
    return results


def drain_vector(step, batches):
    out = []
    for res in (step(b) for b in batches):
        out.append(int(res))  # line 19: conversion inside the for loop
    return out


def drain_lanes(res, n):
    lanes = []
    for i in range(n):
        lanes.append(int(res[i]))  # line 26: subscripted conversion
    return lanes


def drain_comprehension(results):
    return [int(r) for r in results]  # line 31: comprehension loop
