"""Fixture: serial-rpc-fanout must fire in cluster/ too (ISSUE 16) —
the replication plane loops over peers with RPCs inside, and a serial
push loop that is NOT the bounded background pusher is the same
head-of-line-blocking bug as a serial round start (3 findings)."""


def push_to_all_peers(self, peers, entries):
    replies = {}
    for p in peers:
        replies[p.member] = p.client.call(
            "Cluster.CacheSync", {"entries": entries})  # 1
    return replies


def digest_walk(successor_targets):
    for t in successor_targets:
        t.call("Cluster.CacheSync", {"digest": 32}, timeout=2.0)  # 2


def nested_handoff(target_groups):
    for group in target_groups:
        for t in group:
            t.call("Cluster.Handoff", {})  # 3 (nested loop, same scope)
