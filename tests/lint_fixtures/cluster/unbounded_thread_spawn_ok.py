"""Fixture: the sanctioned replication-plane shapes must stay clean."""

import threading


def persistent_pusher(queue_):
    def drain():
        while True:
            batch = queue_.get()
            batch.push()

    # ONE pusher thread outside the loop; the loop lives inside it —
    # the real write-behind pusher's shape (cluster/replication.py)
    threading.Thread(target=drain, daemon=True).start()


def sender_defined_in_loop(targets):
    senders = []
    for t in targets:
        # a closure DEFINED (not started) per target is outside the
        # loop's dynamic extent
        def send(t=t):
            threading.Thread(target=t.push).start()

        senders.append(send)
    return senders


def suppressed_handoff_senders(moved, deadline):
    # the warm-handoff sender's shape: one spawn per NEW owner of a
    # remapped range, justified at the spawn site — the suppression
    # protocol the real cluster/replication.py handoff follows
    for target, entries in sorted(moved.items()):
        threading.Thread(target=entries.send, args=(deadline,)).start()  # distpow: ok unbounded-thread-spawn -- fixture: bounded by the pool size (one spawn per new owner) and the shared handoff deadline
