"""Fixture: unbounded-thread-spawn must fire in cluster/ (ISSUE 16) —
the replication plane's tempting shapes all spawn per item: a thread
per pushed entry, a thread per digest exchange, a thread per handoff
chunk (3 findings)."""

import threading
from threading import Thread


def push_each_entry(entries, peers):
    for e in entries:  # one thread per cache entry: scales with cache
        threading.Thread(target=peers.push, args=(e,)).start()


def antientropy_forever(ring):
    while True:  # one thread per sweep: scales with uptime
        Thread(target=ring.sweep).start()


def nested_chunk_senders(target_chunks):
    for chunk in target_chunks:
        for c in chunk:  # anchors to THIS (innermost) loop only
            threading.Thread(target=c.send).start()
