"""Fixture: the sanctioned replication-plane shapes must not trip
serial-rpc-fanout in cluster/."""

import subprocess


def futures_then_await(peers, entries):
    # the sanctioned fan-out: issue every push, then await under one
    # shared deadline
    futs = [p.client.go("Cluster.CacheSync", {"entries": entries})
            for p in peers]
    for fut in futs:
        fut.result(timeout=5.0)


def suppressed_background_pusher(targets, batch):
    # the write-behind pusher's shape: deliberately serial, justified
    # at the call site — the suppression protocol the real
    # cluster/replication.py push loop follows
    for t in sorted(targets):
        t.client.call("Cluster.CacheSync", batch, timeout=5.0)  # distpow: ok serial-rpc-fanout -- fixture: deliberately serial single background pusher, bounded by the replica count and the per-call timeout


def not_a_peer_collection(chunks):
    for chunk in chunks:
        chunk.sink.call("Cluster.Handoff", chunk.entries)


def subprocess_is_not_rpc(node_cmds):
    for cmd in node_cmds:
        subprocess.call(cmd)
