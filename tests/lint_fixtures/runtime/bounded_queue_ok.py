"""Fixture: bounded (or justified) queue constructions the rule passes."""

import queue
from queue import Queue


def build(ch_capacity: int):
    a = queue.Queue(maxsize=10)          # positive literal bound
    b = Queue(32)                        # positional literal bound
    c = queue.Queue(maxsize=ch_capacity)  # configured bound (variable)
    # distpow: ok bounded-queue -- fixture: depth is protocol-bounded
    d = queue.Queue()
    e = dict()  # an unrelated call the rule must ignore
    return a, b, c, d, e
