"""Fixture (scope: runtime/): accounted handlers silent-except accepts."""

import logging

from distpow_tpu.runtime.metrics import REGISTRY as metrics

log = logging.getLogger("fixture")


def logged(op):
    try:
        return op()
    except Exception as exc:
        log.warning("operation failed: %s", exc)
        return None


def counted(op):
    try:
        return op()
    except Exception:
        metrics.inc("search.cancelled")
        return None


def reraised(op):
    try:
        return op()
    except Exception:
        raise RuntimeError("wrapped")


def narrow_is_fine(path):
    try:
        return open(path).read()
    except OSError:
        return ""
