"""Fixture: wall-clock-duration must flag time.time() deltas."""

import time

BOOT_TS = time.time()


def direct(work):
    start = time.time()
    work()
    return time.time() - start  # line 11: direct wall operand


def local_name(work):
    t0 = time.time()
    work()
    t1 = time.time()
    return t1 - t0  # line 18: both operands are tainted locals


class Timer:
    def start(self):
        self._t0 = time.time()

    def elapsed(self):
        return time.time() - self._t0  # line 26: attr carries wall taint


def against_module_anchor():
    return time.time() - BOOT_TS  # line 30: module-level tainted name
