"""Fixture: every unbounded-queue construction form the rule flags."""

import queue
from queue import Queue, SimpleQueue


def build():
    a = queue.Queue()            # no capacity at all
    b = Queue(maxsize=0)         # explicit "unbounded" sentinel
    c = queue.Queue(0)           # positional zero
    d = SimpleQueue()            # can never be bounded
    return a, b, c, d
