"""Fixture (scope: runtime/): silent-except must flag silent handlers."""


def swallow_everything(op):
    try:
        return op()
    except Exception:  # line 7: silent broad catch
        return None


def swallow_bare(op):
    try:
        return op()
    except:  # noqa: E722  # line 13: bare except
        pass


def logs_in_callback_only(op, log):
    try:
        return op()
    except Exception:  # line 19: the nested def runs later, if ever
        def report():
            log.warning("failed")
        return report
