"""Fixture: clocks wall-clock-duration must accept."""

import time


def monotonic_duration(work):
    t0 = time.monotonic()
    work()
    return time.monotonic() - t0


def wall_stamp_only(record):
    # stamping a record with wall time is fine — no delta computed
    record["ts"] = round(time.time(), 6)
    return record


def mixed_discipline(work):
    # the shipped idiom: wall for the stamp, monotonic for the delta
    ts = time.time()
    t0 = time.monotonic()
    work()
    return {"ts": ts, "dt": time.monotonic() - t0}


def cross_node_age(snapshot_ts):
    # judging a remote node's wall stamp: no shared monotonic epoch
    # exists, so wall-vs-wall is the only possible comparison
    return time.time() - snapshot_ts  # distpow: ok wall-clock-duration -- staleness vs a REMOTE wall stamp; no shared monotonic epoch exists across processes


def arithmetic_on_untainted(a, b):
    return a - b
