"""Fixture: unbounded-thread-spawn must fire on each spawn-in-loop."""

import threading
from threading import Thread


def heartbeat_all(members):
    for m in members:  # one thread per member: scales with the fleet
        threading.Thread(target=m.beat, daemon=True).start()


def poll_forever(queue_):
    while True:  # one thread per message: scales with traffic
        msg = queue_.get()
        Thread(target=print, args=(msg,)).start()


def nested_only_reports_once(batches):
    for batch in batches:
        for item in batch:  # anchors to THIS (innermost) loop only
            threading.Thread(target=item.run).start()
