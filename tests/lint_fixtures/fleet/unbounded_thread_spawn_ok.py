"""Fixture: the sanctioned shapes must stay clean."""

import threading
from concurrent.futures import ThreadPoolExecutor


def persistent_loop(members):
    def run():
        for m in members:
            m.beat()

    # ONE thread outside the loop; the loop lives inside it
    threading.Thread(target=run, daemon=True).start()


def pooled(members):
    with ThreadPoolExecutor(max_workers=4) as pool:
        for m in members:  # the pool bounds concurrency, not the loop
            pool.submit(m.beat)


def callback_defined_in_loop(members):
    handlers = []
    for m in members:
        # a thread DEFINED (not started) per item is a closure, and the
        # nested-function body is outside the loop's dynamic extent
        def later(m=m):
            threading.Thread(target=m.beat).start()

        handlers.append(later)
    return handlers


def suppressed_bounded(members):
    for m in members[:4]:
        threading.Thread(target=m.beat).start()  # distpow: ok unbounded-thread-spawn -- bounded: the slice caps this at 4 spawns per call, fixture for the suppression protocol
