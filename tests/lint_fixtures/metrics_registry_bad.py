"""Fixture: metrics-registry must flag undeclared series names."""

from distpow_tpu.runtime.metrics import REGISTRY as metrics
from distpow_tpu.runtime.metrics import REGISTRY

GHOST = "coord.phantom_counter"


def hot_path(kind, dt):
    metrics.inc("coord.fanout")  # line 10: typo of coord.fanouts
    REGISTRY.inc(GHOST)  # line 11: resolvable constant, undeclared
    metrics.inc(f"mystery.{kind}")  # line 12: undeclared prefix
    metrics.observe("worker.solve", dt)  # line 13: typo of worker.solve_s
    with metrics.time(f"rpc.mystery_s.{kind}"):  # line 14: bad prefix
        pass
    metrics.gauge("proc.rss_byte", dt)  # line 16: typo of proc.rss_bytes
    REGISTRY.gauge(f"ring.{kind}_depth", dt)  # line 17: no gauge prefixes
