"""Fixture: metrics-registry must flag undeclared counter names."""

from distpow_tpu.runtime.metrics import REGISTRY as metrics
from distpow_tpu.runtime.metrics import REGISTRY

GHOST = "coord.phantom_counter"


def hot_path(kind):
    metrics.inc("coord.fanout")  # line 10: typo of coord.fanouts
    REGISTRY.inc(GHOST)  # line 11: resolvable constant, undeclared
    metrics.inc(f"mystery.{kind}")  # line 12: undeclared prefix
