"""Fixture: a justified suppression silences the finding and is
counted in the report's suppressed list."""

import threading
import time

_lock = threading.Lock()


def deliberate_hold():
    with _lock:
        # distpow: ok no-blocking-under-lock -- fixture: the hold is the
        # documented design and this justification says why
        time.sleep(0.01)
