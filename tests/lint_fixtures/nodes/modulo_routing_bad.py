"""Fixture: modulo-routing must fire on hash-over-membership modulo."""

import hashlib
import zlib


def route_builtin_hash(nonce, members):
    # finding 1: the builtin hash() reduced modulo the member count
    return members[hash(nonce) % len(members)]


def route_digest(nonce, workers):
    # finding 2: a digest() reduced modulo the worker count
    return workers[
        int.from_bytes(hashlib.md5(nonce).digest()[:4], "big")
        % len(workers)
    ]


def route_crc(nonce, shard_addrs):
    # finding 3: crc32 modulo the shard list
    return zlib.crc32(nonce) % len(shard_addrs)
