"""Fixture: the sanctioned fan-out shapes must not trip
serial-rpc-fanout."""

import subprocess


def parallel_fanout(workers):
    # issue-then-await: the go() futures overlap, replies are collected
    # under one shared deadline
    futs = [w.client.go("WorkerRPCHandler.Mine", {}) for w in workers]
    for fut in futs:
        fut.result(timeout=10.0)


def go_per_peer(workers):
    for w in workers:
        w.client.go("WorkerRPCHandler.Found", {})  # async issue is fine


def call_outside_peer_loop(batches):
    for batch in batches:  # not a peer collection
        batch.client.call("CoordRPCHandler.Result", batch)


def callback_defined_in_loop(workers):
    fns = []
    for w in workers:
        # a nested function BODY is outside the loop's dynamic extent
        def later(w=w):
            return w.client.call("WorkerRPCHandler.Ping", {})
        fns.append(later)
    return fns


def subprocess_is_not_rpc(worker_cmds):
    for cmd in worker_cmds:
        subprocess.call(cmd)
