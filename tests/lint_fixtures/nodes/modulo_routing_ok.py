"""Fixture: sanctioned shapes the modulo-routing rule must NOT flag."""


def rotate_placement(i, candidates):
    # hash-free round-robin index arithmetic: load balancing, not key
    # routing — no cache locality to lose (coordinator _issue_shards)
    return candidates[i % len(candidates)]


def ring_route(ring, nonce):
    # the sanctioned shape: consistent-hash ring lookup (~1/N churn)
    return ring.owner(nonce)


def bucket_stat(value_hash, n_buckets):
    # modulo over a NON-membership count (histogram bucketing): the
    # right side carries no member-collection hint
    return value_hash % n_buckets


def legacy_static_route(nonce, members):
    # distpow: ok modulo-routing -- fixture: membership is a frozen
    # boot-time constant in this (hypothetical) path, so remap churn
    # cannot occur
    return members[hash(nonce) % len(members)]
