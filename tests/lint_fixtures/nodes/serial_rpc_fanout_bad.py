"""Fixture: serial-rpc-fanout must fire on each blocking per-peer call
inside a fan-out loop (3 findings)."""


def broadcast(self, workers):
    for w in workers:
        w.client.call("WorkerRPCHandler.Found", {})  # finding 1


def probe(refs):
    dead = []
    for ref in {id(r): r for r in refs}.values():
        ref.client.call("WorkerRPCHandler.Ping", {}, timeout=2.0)  # finding 2
        dead.append(ref)
    return dead


def nested(peer_groups):
    for group in peer_groups:
        for p in group:
            p.call("X.Y", {})  # finding 3 (nested loop, same scope)
