"""Kernel-lane launch planner suite (sched/lanes.py, docs/SERVING.md).

Three layers:

* planner — the capability-driven selection matrix (pallas on TPU-like
  caps, mesh on any multi-device host, xla otherwise), the ``SchedLane``
  override, and sticky compile-failure demotion, all against injected
  :class:`LaneCaps` so the matrix runs anywhere.
* mesh lanes — byte-identical first-hit parity of the mesh slot step
  and the mesh persistent step against their single-device oracles,
  across widths and across non-power-of-two partitions; the conftest
  boots 8 virtual CPU devices, so these exercise real sharded programs.
* engine integration — a forced-mesh scheduler matches the reference
  oracle while ``sched.lane_launches.mesh`` counts the serving, and a
  mixed-hash launch whose groups land on DIFFERENT lanes still returns
  every slot's oracle answer from one launch.
"""

import sys
import threading
from pathlib import Path

import jax.numpy as jnp
import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).parent))

from distpow_tpu.models import puzzle  # noqa: E402
from distpow_tpu.models.registry import get_hash_model  # noqa: E402
from distpow_tpu.ops.difficulty import nibble_masks  # noqa: E402
from distpow_tpu.ops.packing import build_tail_spec  # noqa: E402
from distpow_tpu.ops.search_step import (  # noqa: E402
    cached_persistent_step,
    slot_search_step,
)
from distpow_tpu.parallel.mesh_search import (  # noqa: E402
    AXIS,
    make_mesh,
    mesh_persistent_factory,
    mesh_slot_search_step,
)
from distpow_tpu.parallel.search import persistent_search  # noqa: E402
from distpow_tpu.runtime.metrics import REGISTRY  # noqa: E402
from distpow_tpu.sched.engine import BatchingScheduler  # noqa: E402
from distpow_tpu.sched.lanes import (  # noqa: E402
    LaneCaps,
    LanePlanner,
    build_pallas_group_step,
    persistent_step_builder,
)

GDEF = ("md5", 1, (0, 1, 2), ((1, 2, 3),), 1)


# -- planner selection matrix ------------------------------------------------

def test_rank_selection_matrix():
    cases = [
        (LaneCaps("tpu", 4), "auto", ("pallas", "mesh", "xla")),
        (LaneCaps("tpu", 1), "auto", ("pallas", "xla")),
        (LaneCaps("cpu", 8), "auto", ("mesh", "xla")),
        (LaneCaps("cpu", 1), "auto", ("xla",)),
        # the interpret dev knob admits pallas off-TPU
        (LaneCaps("cpu", 1, interpret=True), "auto", ("pallas", "xla")),
        # overrides pin the head and drop the other specialized lane
        (LaneCaps("tpu", 4), "mesh", ("mesh", "xla")),
        (LaneCaps("cpu", 8), "xla", ("xla",)),
        (LaneCaps("cpu", 8), "pallas", ("xla",)),  # ineligible override
    ]
    for caps, override, want in cases:
        got = LanePlanner(caps=caps, override=override).rank(GDEF, 4096)
        assert got == want, (caps, override, got)


def test_width0_probe_layout_stays_on_xla():
    """The width-0 probe layout (empty chunk_locs) never rides a
    specialized lane: its whole segment is below one batch, so a
    per-layout compile could not pay for itself."""
    probe = ("md5", 1, (0, 1, 2), (), 1)
    for caps in (LaneCaps("tpu", 4), LaneCaps("cpu", 8),
                 LaneCaps("cpu", 1, interpret=True)):
        assert LanePlanner(caps=caps).rank(probe, 4096) == ("xla",)


def test_unknown_override_rejected():
    with pytest.raises(ValueError, match="unknown scheduler lane"):
        LanePlanner(caps=LaneCaps("cpu", 1), override="warp")


def test_demotion_is_sticky_and_falls_to_xla():
    p = LanePlanner(caps=LaneCaps("cpu", 1, interpret=True),
                    override="pallas")
    # md5 IS pallas-eligible under interpret caps, but an unknown model
    # makes the build itself raise — the demotion path
    gdef = ("nosuch", 1, (0, 1, 2), ((1, 2, 3),), 1)
    lane, step = p.resolve(gdef, 4096)
    assert (lane, step) == ("xla", None)
    assert "pallas" in p._demoted[(gdef, 4096)]
    # sticky: re-resolving never retries the demoted lane
    assert p.resolve(gdef, 4096) == ("xla", None)


def test_pallas_build_guards():
    caps = LaneCaps("cpu", 1, interpret=True)
    spec = build_tail_spec(b"\x01\x02", 2, get_hash_model("md5"), b"")
    ok = ("md5", spec.n_blocks, spec.tb_loc, spec.chunk_locs, 1)
    with pytest.raises(ValueError, match="single-block"):
        build_pallas_group_step(("md5", 2) + ok[2:], 4096, caps)
    with pytest.raises(ValueError, match="tile grid"):
        build_pallas_group_step(ok, 4096 + 128, caps)
    with pytest.raises(ValueError, match="TPU hardware"):
        build_pallas_group_step(ok, 4096, LaneCaps("cpu", 1))
    # the eligible shape builds a real (interpret-mode) group step
    step = build_pallas_group_step(ok, 4096, caps)
    assert step.lane == "pallas" and step.coverage == 4096


def test_pallas_interpret_group_step_parity():
    """The pallas group step (interpret mode, so it runs on CPU) agrees
    byte-for-byte with the XLA slot step over the same lane stack."""
    model = get_hash_model("md5")
    caps = LaneCaps("cpu", 1, interpret=True)
    batch = 2048
    spec = build_tail_spec(b"\x31\x32", 2, model, b"")
    gdef = ("md5", spec.n_blocks, spec.tb_loc, spec.chunk_locs, 2)
    step = build_pallas_group_step(gdef, batch, caps)
    oracle = slot_search_step("md5", spec.n_blocks, spec.tb_loc,
                              spec.chunk_locs, batch, 2)
    ops = (
        jnp.stack([jnp.asarray(spec.init_state, jnp.uint32)] * 2),
        jnp.stack([jnp.asarray(spec.base_words, jnp.uint32)] * 2),
        jnp.stack([jnp.asarray(nibble_masks(d, model), jnp.uint32)
                   for d in (1, 2)]),
        jnp.zeros(2, jnp.uint32),
        jnp.full(2, 8, jnp.uint32),
        jnp.asarray([0, 7], jnp.uint32),
    )
    np.testing.assert_array_equal(
        np.asarray(step(ops, None)), np.asarray(oracle(*ops))
    )


# -- mesh lane parity --------------------------------------------------------

def test_mesh_slot_step_parity_across_widths():
    """Per-slot first-hit indices from the sharded slot step are
    byte-identical to the single-device step over the same global span
    — for real hits and for misses, across tail widths."""
    import jax

    model = get_hash_model("md5")
    mesh = make_mesh(jax.devices()[:4])
    batch = 4096  # global; 1024 per device
    for vw, nonce, ntz in ((1, b"\x41\x42", 1), (2, b"\x43", 2),
                           (3, b"\x44\x45\x46", 2)):
        spec = build_tail_spec(nonce, vw, model, b"")
        args = ("md5", spec.n_blocks, spec.tb_loc, spec.chunk_locs)
        dyn = mesh_slot_search_step(mesh, AXIS, *args, batch // 4, 2)
        oracle = slot_search_step(*args, batch, 2)
        masks = jnp.asarray(nibble_masks(ntz, model), jnp.uint32)
        ops = (
            jnp.stack([jnp.asarray(spec.init_state, jnp.uint32)] * 2),
            jnp.stack([jnp.asarray(spec.base_words, jnp.uint32)] * 2),
            jnp.stack([masks] * 2),
            jnp.zeros(2, jnp.uint32),
            jnp.full(2, 8, jnp.uint32),
            jnp.asarray([0, 3], jnp.uint32),
        )
        for c0 in (0, 16, 64):
            cur = ops[:5] + (ops[5] + jnp.uint32(c0),)
            np.testing.assert_array_equal(
                np.asarray(dyn(*cur)), np.asarray(oracle(*cur)),
                err_msg=f"vw={vw} chunk0={c0}",
            )


def test_mesh_persistent_step_parity_nonpow2_partition():
    """The mesh persistent factory's bound step returns the same
    [first-hit, segments] pair as the single-device persistent step —
    including on a non-power-of-two partition (the // % enumeration)."""
    model = get_hash_model("md5")
    import jax

    mesh = make_mesh(jax.devices()[:4])
    for tbc in (256, 96):
        nonce, ntz, vw, chunks, segs = b"\x51\x52", 1, 2, 32, 4
        factory = mesh_persistent_factory(nonce, ntz, 0, tbc, model,
                                          mesh, AXIS)
        bound, chunks_each, per_step = factory(vw, b"", chunks, segs)
        assert (chunks_each, per_step) == (chunks, chunks * segs)
        oracle = cached_persistent_step(nonce, vw, ntz, 0, tbc, chunks,
                                        "md5", b"", segs)
        for c0 in (0, 64, 1 << 12):
            got = np.asarray(bound(jnp.uint32(c0), jnp.uint32(0)))
            want = np.asarray(oracle(jnp.uint32(c0), jnp.uint32(0)))
            np.testing.assert_array_equal(got, want,
                                          err_msg=f"tbc={tbc} c0={c0}")
    # indivisible global batch refuses cleanly (the demotion signal)
    f6 = mesh_persistent_factory(b"\x51\x52", 1, 0, 6, model, mesh, AXIS)
    with pytest.raises(ValueError, match="divide"):
        f6(2, b"", 1, 2)


def test_persistent_search_mesh_builder_matches_oracle():
    """End to end: persistent_search driving the mesh lane finds the
    oracle's secret (same enumeration order => same first hit)."""
    nonce, ntz = b"\x61\x62\x63", 2
    sb = persistent_step_builder(nonce, ntz, 0, 256,
                                 get_hash_model("md5"))
    assert sb is not None  # 8-device conftest mesh
    res = persistent_search(nonce, ntz, list(range(256)),
                            batch_size=1 << 12, step_builder=sb)
    assert res is not None
    assert res.secret == puzzle.python_search(nonce, ntz,
                                              list(range(256)))


# -- engine integration ------------------------------------------------------

def test_scheduler_mesh_override_parity_and_counters():
    before = REGISTRY.get("sched.lane_launches.mesh")
    eng = BatchingScheduler(hash_model="md5", batch_size=1 << 12,
                            max_slots=4, lane="mesh")
    try:
        for nonce, ntz in ((b"\x71\x72", 2), (b"\x73", 3)):
            got = eng.search(nonce, ntz, list(range(256)))
            assert got == puzzle.python_search(nonce, ntz,
                                              list(range(256)))
    finally:
        eng.close()
    assert REGISTRY.get("sched.lane_launches.mesh") > before


def test_mixed_hash_launch_across_different_lanes():
    """Groups of one launch landing on DIFFERENT lanes (sha1 demoted to
    xla, md5 on mesh) still each return their oracle's answer."""
    before_mesh = REGISTRY.get("sched.lane_launches.mesh")
    before_xla = REGISTRY.get("sched.lane_launches.xla")
    eng = BatchingScheduler(hash_model="md5", batch_size=1 << 12,
                            max_slots=4, extra_models=("sha1",),
                            start=False)
    orig = eng.planner._eligible

    def no_mesh_for_sha1(lane, gdef, batch):
        if lane == "mesh" and gdef[0] == "sha1":
            return False
        return orig(lane, gdef, batch)

    eng.planner._eligible = no_mesh_for_sha1
    results = {}

    def run(name, nonce, model):
        results[name] = eng.search(nonce, 2, list(range(256)),
                                   hash_model=model)

    threads = [
        threading.Thread(target=run, args=("md5", b"\x81\x82", "md5")),
        threading.Thread(target=run, args=("sha1", b"\x83\x84", "sha1")),
    ]
    for t in threads:
        t.start()
    eng.start()
    try:
        for t in threads:
            t.join(timeout=300)
            assert not t.is_alive()
    finally:
        eng.close()
    assert results["md5"] == puzzle.python_search(b"\x81\x82", 2,
                                                  list(range(256)))
    assert results["sha1"] == puzzle.python_search(
        b"\x83\x84", 2, list(range(256)), algo="sha1")
    assert REGISTRY.get("sched.lane_launches.mesh") > before_mesh
    assert REGISTRY.get("sched.lane_launches.xla") > before_xla
