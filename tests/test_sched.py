"""Continuous-batching scheduler suite (docs/SCHEDULER.md).

Three layers, matching the subsystem's three parts:

* engine — slot packing, enumeration parity with the solo oracle, the
  ISSUE-4 acceptance number (8 concurrent searches in measurably fewer
  launches than 8 solos, ``sched.batch_occupancy`` mean > 1),
  deterministic weighted-fairness (a hard puzzle cannot starve cheap
  ones), preemption under oversubscription, and the solo fallback.
* coordinator — in-flight coalescing (N identical Mines -> ONE fan-out
  round, N replies, one trace per request) and bounded-run-queue
  admission control with the typed RETRY_AFTER reply.
* powlib — RETRY_AFTER consumed as a server-paced NON-COUNTING retry
  that never burns the transport budget, including the edge where
  retry-after and the coordinator-reconnect machinery interleave.
"""

import queue
import sys
import threading
import time
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).parent))

from test_nodes import Stack  # noqa: E402

from distpow_tpu.models import puzzle  # noqa: E402
from distpow_tpu.nodes.powlib import POW, MineResult  # noqa: E402
from distpow_tpu.runtime.metrics import REGISTRY  # noqa: E402
from distpow_tpu.runtime.rpc import (  # noqa: E402
    RPCClient,
    RPCRetryAfter,
    RPCServer,
    RPCTransportError,
)
from distpow_tpu.sched.admission import AdmissionReject  # noqa: E402
from distpow_tpu.sched.engine import BatchingScheduler  # noqa: E402


def _hist_delta(before, name="sched.batch_occupancy"):
    after = REGISTRY.get_histogram(name) or {"count": 0, "sum": 0.0}
    b = before or {"count": 0, "sum": 0.0}
    return after["count"] - b["count"], after["sum"] - b["sum"]


def _occupancy_snapshot():
    return REGISTRY.get_histogram("sched.batch_occupancy")


# -- engine ------------------------------------------------------------------

def test_engine_single_search_matches_reference_oracle():
    eng = BatchingScheduler(hash_model="md5", batch_size=1 << 10,
                            max_slots=4)
    try:
        for nonce, ntz in ((b"\x01\x02\x03\x04", 2), (b"\xaa\xbb", 3),
                           (b"\x07", 1)):
            got = eng.search(nonce, ntz, list(range(256)))
            oracle = puzzle.python_search(nonce, ntz, list(range(256)))
            assert got == oracle, (nonce, ntz, got, oracle)
        # a narrow power-of-two partition (a sharded worker's view)
        tbs = list(range(64, 128))
        got = eng.search(b"\x03\x04", 2, tbs)
        assert got is not None
        assert puzzle.check_secret(b"\x03\x04", got, 2)
        assert got[0] in tbs
    finally:
        eng.close()


def test_engine_eight_concurrent_fewer_launches_than_solos():
    """The ISSUE-4 acceptance shape, deterministic at the engine layer:
    the SAME 8 searches run (a) sequentially — occupancy 1, the
    one-launch-per-request baseline — then (b) concurrently on a fresh
    engine whose loop starts only after all 8 slots are queued.  The
    batched run must spend measurably fewer device launches, and the
    occupancy histogram must show real packing (mean > 1)."""
    nonces = [bytes([0x42, i]) for i in range(8)]
    ntz = 3

    seq_eng = BatchingScheduler(hash_model="md5", batch_size=1 << 10,
                                max_slots=8)
    try:
        seq_launch0 = REGISTRY.get("sched.launches")
        for n in nonces:
            assert seq_eng.search(n, ntz, list(range(256))) is not None
        seq_launches = REGISTRY.get("sched.launches") - seq_launch0
    finally:
        seq_eng.close()
    assert seq_launches >= 8  # each solo costs at least one launch

    conc_eng = BatchingScheduler(hash_model="md5", batch_size=1 << 10,
                                 max_slots=8, start=False)
    occ0 = _occupancy_snapshot()
    conc_launch0 = REGISTRY.get("sched.launches")
    slots = [conc_eng.submit(n, ntz, list(range(256))) for n in nonces]
    conc_eng.start()
    try:
        secrets = [s.result(timeout=120) for s in slots]
        for n, secret in zip(nonces, secrets):
            assert secret is not None
            assert puzzle.check_secret(n, secret, ntz)
        conc_launches = REGISTRY.get("sched.launches") - conc_launch0
        count, total = _hist_delta(occ0)
        assert count == conc_launches
        mean_occupancy = total / count
        assert mean_occupancy > 1, mean_occupancy
        assert conc_launches < seq_launches, (conc_launches, seq_launches)
        # batching result parity: same nonce -> same secret either way
        # (the packed lanes advance the same enumeration cursor)
    finally:
        conc_eng.close()


def test_engine_fairness_hard_puzzle_cannot_starve_cheap_ones():
    """Deterministic weighted-fairness: a hard (high-ntz) slot that will
    not finish shares the device with cheap slots submitted AFTER it;
    the cheap ones must complete within a bounded number of their own
    launches while the hard one keeps running."""
    eng = BatchingScheduler(hash_model="md5", batch_size=1 << 10,
                            max_slots=8, start=False)
    try:
        # ~16M expected candidates at ntz 5: never finishes in-test
        hard = eng.submit(b"\xde\xad", 5, list(range(256)))
        cheap = [eng.submit(bytes([0x51, i]), 1, list(range(256)))
                 for i in range(3)]
        eng.start()
        for i, s in enumerate(cheap):
            secret = s.result(timeout=60)
            assert secret is not None
            assert puzzle.check_secret(bytes([0x51, i]), secret, 1)
            # an ntz-1 search hits inside its first one or two quanta;
            # fairness means contention cannot inflate that by more
            # than the shared-launch constant
            assert s.launches <= 4, s.launches
        assert not hard.done.is_set(), "hard slot finished implausibly fast"
        assert hard.launches >= 1  # ...but it IS getting device share
        hard.cancel()
        assert hard.result(timeout=30) is None
    finally:
        eng.close()


def test_engine_preempts_under_oversubscription():
    """More runnable slots than the table holds: the weighted-fair
    allocator must rotate active slots back to the run queue (flight-
    recorder ``sched.slot_preempt``) so every request progresses."""
    before = REGISTRY.get("sched.slots_preempted")
    eng = BatchingScheduler(hash_model="md5", batch_size=1 << 10,
                            max_slots=2, start=False)
    try:
        nonces = [bytes([0x61, i]) for i in range(4)]
        slots = [eng.submit(n, 3, list(range(256))) for n in nonces]
        eng.start()
        for n, s in zip(nonces, slots):
            secret = s.result(timeout=120)
            assert secret is not None
            assert puzzle.check_secret(n, secret, 3)
    finally:
        eng.close()
    assert REGISTRY.get("sched.slots_preempted") > before


def test_engine_falls_back_for_unsupported_shapes():
    calls = []

    class Fallback:
        def search(self, nonce, ntz, tbs, cancel_check=None):
            calls.append((bytes(nonce), ntz, tuple(tbs)))
            return b"\xfa\x11"

    eng = BatchingScheduler(hash_model="md5", batch_size=1 << 10,
                            fallback=Fallback())
    try:
        before = REGISTRY.get("sched.fallback_searches")
        # non-power-of-two partition
        assert eng.search(b"\x01", 1, [3, 4, 5]) == b"\xfa\x11"
        # unsatisfiable difficulty (md5 digest has 32 nibbles)
        assert eng.search(b"\x01", 33, list(range(256))) == b"\xfa\x11"
        assert len(calls) == 2
        assert REGISTRY.get("sched.fallback_searches") - before == 2
        assert not eng.supports(1, [3, 4, 5])
        assert eng.supports(1, list(range(256)))
    finally:
        eng.close()


def test_new_slots_inherit_vtime_floor_no_starvation():
    """A joining slot starts at the most-starved slot's virtual time,
    not zero — otherwise a stream of fresh arrivals would outrank a
    long-running slot forever (review PR 4).  With a 1-wide table the
    late cheap slot must both carry the inherited floor AND complete
    via preemption rotation while the hard slot keeps its share."""
    eng = BatchingScheduler(hash_model="md5", batch_size=1 << 10,
                            max_slots=1, start=False)
    try:
        hard = eng.submit(b"\xde\xad", 5, list(range(256)))
        eng.start()
        deadline = time.time() + 30
        while time.time() < deadline and hard.launches < 2:
            time.sleep(0.01)
        assert hard.launches >= 2
        late = eng.submit(bytes([0x52, 1]), 1, list(range(256)))
        assert late.vtime >= eng.batch, \
            "late slot joined at vtime 0 — starvation floor missing"
        secret = late.result(timeout=60)
        assert secret is not None
        assert puzzle.check_secret(bytes([0x52, 1]), secret, 1)
        # the hard slot regains the device after the rotation
        l0 = hard.launches
        deadline = time.time() + 30
        while time.time() < deadline and hard.launches <= l0:
            time.sleep(0.01)
        assert hard.launches > l0, "hard slot starved after rotation"
        hard.cancel()
        assert hard.result(timeout=30) is None
    finally:
        eng.close()


def test_coordinator_process_stays_jax_free():
    """The coordinator imports sched.admission/coalesce but must NOT
    drag jax (seconds of import, hundreds of MB) into a device-less
    control-plane process — the engine import is lazy (review PR 4)."""
    import subprocess

    out = subprocess.run(
        [sys.executable, "-c",
         "import distpow_tpu.nodes.coordinator, sys; "
         "sys.exit(1 if 'jax' in sys.modules else 0)"],
        capture_output=True, text=True, timeout=120,
        cwd=str(Path(__file__).parent.parent),
    )
    assert out.returncode == 0, (
        f"importing the coordinator pulled jax into the process\n"
        f"{out.stdout}{out.stderr}"
    )


def test_mixed_hash_slots_share_launch_with_parity():
    """ISSUE-6 mixed-hash acceptance at the engine layer: md5 and sha1
    slots submitted together must share launches (occupancy mean > 1
    where single-model-only batching would have been exactly 1 via the
    solo fallback), record ``sched.mixed_hash_launches``, and each
    slot's first hit must equal its OWN model's python oracle."""
    eng = BatchingScheduler(hash_model="md5", batch_size=1 << 10,
                            max_slots=8, extra_models=("sha1",),
                            start=False)
    occ0 = _occupancy_snapshot()
    mh0 = REGISTRY.get("sched.mixed_hash_launches")
    launch0 = REGISTRY.get("sched.launches")
    reqs = [(("sha1" if i % 2 else "md5"), bytes([0x91, i]))
            for i in range(8)]
    slots = [eng.submit(nonce, 3, list(range(256)), hash_model=m)
             for m, nonce in reqs]
    eng.start()
    try:
        for (m, nonce), s in zip(reqs, slots):
            secret = s.result(timeout=180)
            oracle = puzzle.python_search(nonce, 3, list(range(256)),
                                          algo=m)
            assert secret == oracle, (m, nonce, secret, oracle)
            assert puzzle.check_secret(nonce, secret, 3, m)
        conc_launches = REGISTRY.get("sched.launches") - launch0
        count, total = _hist_delta(occ0)
        assert count == conc_launches
        assert total / count > 1, (
            f"mixed-hash traffic did not batch: mean occupancy "
            f"{total / count:.2f}"
        )
        assert REGISTRY.get("sched.mixed_hash_launches") - mh0 >= 1
        assert conc_launches < 8 * 2, (
            "mixed batch spent as many launches as per-model solos"
        )
    finally:
        eng.close()


def test_mixed_hash_unadmitted_model_routes_solo_with_parity():
    """A hash model outside the engine's admitted set must not batch —
    it serves through the solo route with the REQUESTED model (the
    wrapped fallback backend's model would be wrong for it)."""
    eng = BatchingScheduler(hash_model="md5", batch_size=1 << 10,
                            start=False)
    try:
        assert not eng.supports(2, list(range(256)), hash_model="sha1")
        before = REGISTRY.get("sched.fallback_searches")
        got = eng.search(b"\x92\x01", 2, list(range(256)),
                         hash_model="sha1")
        assert got == puzzle.python_search(b"\x92\x01", 2,
                                           list(range(256)), algo="sha1")
        assert REGISTRY.get("sched.fallback_searches") - before == 1
    finally:
        eng.close()


def test_mixed_hash_impractical_model_never_admitted():
    """XLA-serving-impractical models stay on the solo route even when
    configured (on TPU they are served by their Pallas kernels)."""
    eng = BatchingScheduler(hash_model="md5", batch_size=1 << 10,
                            extra_models=("sha512",), start=False)
    try:
        assert "sha512" not in eng.models
        assert not eng.supports(2, list(range(256)), hash_model="sha512")
        # and the solo route refuses it too: the fused XLA step is the
        # thing that is impractical to compile, so a "fallback" that
        # runs it anyway would wedge the caller in that compile
        with pytest.raises(ValueError, match="never admitted"):
            eng.search(b"\x92\x02", 2, list(range(256)),
                       hash_model="sha512")
    finally:
        eng.close()


def test_worker_mine_rpc_honors_hash_model_param():
    """Worker-level mixed-hash plumbing: a Mine carrying ``hash_model``
    mines under that model through the scheduler, skips the
    (single-model) dominance cache, and a worker WITHOUT a scheduler
    rejects the request instead of mining the wrong hash."""
    import queue as queue_mod

    from distpow_tpu.backends import get_backend
    from distpow_tpu.nodes.worker import WorkerRPCHandler
    from distpow_tpu.runtime.tracing import MemorySink, Tracer, wire_token

    tracer = Tracer("mixed-worker", MemorySink())
    result_queue: "queue_mod.Queue" = queue_mod.Queue()
    backend = get_backend("jax", batch_size=1 << 10)
    sched = BatchingScheduler(hash_model="md5", batch_size=1 << 10,
                              extra_models=("sha1",), fallback=backend)
    handler = WorkerRPCHandler(tracer, result_queue, backend,
                               scheduler=sched)
    try:
        def mine(nonce, model):
            trace = tracer.create_trace()
            handler.Mine({
                "nonce": nonce, "num_trailing_zeros": 2,
                "worker_byte": 0, "worker_bits": 0,
                "token": wire_token(trace.generate_token()),
                "round": None, "hash_model": model,
            })

        mine(b"\xa1\x01", "sha1")
        res = result_queue.get(timeout=120)
        assert res["secret"] is not None
        assert puzzle.check_secret(res["nonce"], res["secret"], 2, "sha1")
        # the sha1 secret must NOT have entered the md5 dominance cache
        assert handler.result_cache.satisfies(b"\xa1\x01", 2) is None
        # and the forwarded result is TAGGED off-model so the
        # coordinator's single-model cache skips it too
        assert res["hash_model"] == "sha1"
    finally:
        sched.close()

    no_sched = WorkerRPCHandler(tracer, result_queue, backend)
    trace = tracer.create_trace()
    with pytest.raises(RuntimeError, match="mixed-hash"):
        no_sched.Mine({
            "nonce": b"\xa1\x02", "num_trailing_zeros": 2,
            "worker_byte": 0, "worker_bits": 0,
            "token": wire_token(trace.generate_token()),
            "round": None, "hash_model": "sha1",
        })


def test_coordinator_result_skips_cache_for_off_model_results():
    """A worker-tagged off-model Result must never install into the
    coordinator's single-model dominance cache: a later default-model
    Mine for a dominated (nonce, ntz) would replay a secret that fails
    default-model verification."""
    from distpow_tpu.nodes.coordinator import CoordRPCHandler
    from distpow_tpu.runtime.tracing import MemorySink, Tracer, wire_token

    tracer = Tracer("coord-offmodel", MemorySink())
    coord = CoordRPCHandler(tracer, ["127.0.0.1:1"])  # never dialed
    sha1_secret = puzzle.python_search(b"\xb3\x01", 2, list(range(256)),
                                       algo="sha1")

    def result(nonce, secret, **extra):
        trace = tracer.create_trace()
        coord.Result({
            "nonce": nonce, "num_trailing_zeros": 2, "worker_byte": 0,
            "secret": secret, "round": None,
            "token": wire_token(trace.generate_token()), **extra,
        })

    result(b"\xb3\x01", sha1_secret, hash_model="sha1")
    assert coord.result_cache.satisfies(b"\xb3\x01", 2) is None
    # an untagged (default-model) result still installs
    md5_secret = puzzle.python_search(b"\xb3\x02", 2, list(range(256)))
    result(b"\xb3\x02", md5_secret)
    assert coord.result_cache.satisfies(b"\xb3\x02", 2) is not None


def test_worker_mine_rpc_rejects_unservable_models_at_rpc():
    """An unknown or never-admitted hash model on a SCHEDULER worker
    must fail the Mine RPC itself: raising later inside the daemon
    miner thread would produce no result, no cancel acks and no error
    reply — the caller would wait out its full timeout instead of
    getting the honest refusal a scheduler-less worker already sends."""
    import queue as queue_mod

    from distpow_tpu.backends import get_backend
    from distpow_tpu.nodes.worker import WorkerRPCHandler
    from distpow_tpu.runtime.tracing import MemorySink, Tracer, wire_token

    tracer = Tracer("mixed-worker-reject", MemorySink())
    result_queue: "queue_mod.Queue" = queue_mod.Queue()
    backend = get_backend("jax", batch_size=1 << 10)
    sched = BatchingScheduler(hash_model="md5", batch_size=1 << 10,
                              fallback=backend, start=False)
    handler = WorkerRPCHandler(tracer, result_queue, backend,
                               scheduler=sched)
    try:
        def mine(nonce, model):
            trace = tracer.create_trace()
            handler.Mine({
                "nonce": nonce, "num_trailing_zeros": 2,
                "worker_byte": 0, "worker_bits": 0,
                "token": wire_token(trace.generate_token()),
                "round": None, "hash_model": model,
            })

        with pytest.raises(RuntimeError, match="unknown hash_model"):
            mine(b"\xa2\x01", "sha-1")
        with pytest.raises(RuntimeError, match="never admitted"):
            mine(b"\xa2\x02", "sha512")
        # neither refusal may leave a registered task behind
        assert handler._tasks == {}
    finally:
        sched.close()


def test_engine_close_unblocks_waiters():
    eng = BatchingScheduler(hash_model="md5", batch_size=1 << 10,
                            start=False)
    slot = eng.submit(b"\x99", 5, list(range(256)))
    eng.close()
    assert slot.result(timeout=5) is None


# -- worker integration (the tier-1 acceptance criterion) --------------------

def test_worker_scheduler_eight_concurrent_mines_batch():
    """8 concurrent same-difficulty Mine requests on ONE jax-backend
    worker with Scheduler="batching": all complete with valid secrets
    and the occupancy histogram proves shared launches (mean > 1) —
    the serving win, observed end to end through the real protocol."""
    s = Stack(1, backend="jax",
              worker_extra={"Scheduler": "batching", "BatchSize": 1 << 10,
                            "SchedMaxSlots": 8,
                            "WarmupNonceLens": [], "WarmupWidths": []})
    occ0 = _occupancy_snapshot()
    try:
        client = s.new_client("client1")
        for i in range(8):
            client.mine(bytes([0x71, i]), 3)
        for _ in range(8):
            r = client.notify_queue.get(timeout=180)
            assert r.error is None, r.error
            assert puzzle.check_secret(r.nonce, r.secret,
                                       r.num_trailing_zeros)
        count, total = _hist_delta(occ0)
        assert count >= 1
        assert total / count > 1, (
            f"no batching observed: mean occupancy {total / count:.2f} "
            f"over {count} launches"
        )
        # worker-side protocol state drained
        deadline = time.time() + 10
        while time.time() < deadline and s.workers[0].handler._tasks:
            time.sleep(0.05)
        assert s.workers[0].handler._tasks == {}
    finally:
        s.close()


def test_worker_scheduler_first_result_wins_cancellation_traces():
    """Cancellation through the scheduler keeps the reference trace
    discipline: every worker shard ends on WorkerCancel, results
    precede cancels — the invariants trace_check enforces on the
    golden scenario."""
    s = Stack(2, backend="jax",
              worker_extra={"Scheduler": "batching", "BatchSize": 1 << 10,
                            "WarmupNonceLens": [], "WarmupWidths": []})
    try:
        client = s.new_client("client1")
        client.mine(b"\x82\x83", 3)
        r = client.notify_queue.get(timeout=120)
        assert r.error is None
        assert puzzle.check_secret(r.nonce, r.secret, 3)
        time.sleep(0.3)  # Found broadcast drains before inspection
        for i in (1, 2):
            wk = s.action_names(f"worker{i}")
            assert wk[0] == "WorkerMine"
            assert "WorkerCancel" in wk
            if "WorkerResult" in wk:
                assert wk.index("WorkerResult") < len(wk) - 1 or \
                    wk[-1] == "WorkerCancel"
                assert "WorkerCancel" in wk[wk.index("WorkerResult"):]
    finally:
        s.close()


# -- coordinator: coalescing -------------------------------------------------

class _GatedBackend:
    """Holds every search open until the gate fires (cancel-aware)."""

    def __init__(self, inner, gate):
        self.inner = inner
        self.gate = gate

    def search(self, nonce, ntz, tbs, cancel_check=None):
        while not self.gate.is_set():
            if cancel_check is not None and cancel_check():
                return None
            time.sleep(0.002)
        return self.inner.search(nonce, ntz, tbs, cancel_check=cancel_check)


def test_coalescing_identical_mines_share_one_fanout():
    """N concurrent identical (nonce, ntz) Mines -> ONE fan-out round,
    N replies, N-1 coalesced waiters, and every request's trace keeps
    the duplicate shape the oracle already accepts."""
    s = Stack(2)
    gate = threading.Event()
    for w in s.workers:
        w.handler.backend = _GatedBackend(w.handler.backend, gate)
    try:
        c1 = s.new_client("client1")
        c2 = s.new_client("client2")
        before = REGISTRY.get("sched.coalesced_requests")
        c1.mine(b"\x55\x66", 2)
        c1.mine(b"\x55\x66", 2)
        c1.mine(b"\x55\x66", 2)
        c2.mine(b"\x55\x66", 2)
        deadline = time.time() + 20
        while time.time() < deadline and \
                REGISTRY.get("sched.coalesced_requests") - before < 3:
            time.sleep(0.01)
        assert REGISTRY.get("sched.coalesced_requests") - before == 3
        gate.set()
        results = [c1.notify_queue.get(timeout=60) for _ in range(3)]
        results.append(c2.notify_queue.get(timeout=60))
        for r in results:
            assert r.error is None
            assert puzzle.check_secret(r.nonce, r.secret, 2)
        coord = s.action_names("coordinator")
        # ONE fan-out round: exactly one CoordinatorWorkerMine per worker
        assert coord.count("CoordinatorWorkerMine") == 2
        # ...but four complete request traces
        assert coord.count("CoordinatorMine") == 4
        assert coord.count("CoordinatorSuccess") == 4
        # client traces stay whole per request
        assert s.action_names("client2") == [
            "PowlibMiningBegin", "PowlibMine", "PowlibSuccess",
            "PowlibMiningComplete",
        ]
    finally:
        gate.set()
        s.close()


def test_coalesced_waiters_share_leader_failure():
    """A failing leader round must release every waiter with the same
    typed error — never strand them."""
    s = Stack(1, failure_policy="reassign", failure_probe_secs=0.1)
    try:
        s.workers[0].shutdown()  # every fan-out will fail
        # the all-dead leader round fails in well under a millisecond
        # (one refused localhost dial), so whether the second Mine
        # joins as a waiter was pure scheduler luck — hold the leader
        # inside its round long enough for the duplicate to coalesce
        # deterministically (flaked ~50% on loaded 2-core CI)
        handler = s.coordinator.handler
        orig_init = handler._initialize_workers

        def slow_init():
            time.sleep(0.4)
            orig_init()

        handler._initialize_workers = slow_init
        client = s.new_client("client1")
        before = REGISTRY.get("sched.coalesced_requests")
        client.mine(b"\x77\x01", 2)
        client.mine(b"\x77\x01", 2)
        r1 = client.notify_queue.get(timeout=30)
        r2 = client.notify_queue.get(timeout=30)
        assert r1.secret is None and r1.error is not None
        assert r2.secret is None and r2.error is not None
        assert REGISTRY.get("sched.coalesced_requests") - before >= 1
    finally:
        s.close()


# -- coordinator: admission control ------------------------------------------

def test_admission_control_sheds_with_typed_retry_after():
    """SchedMaxInflight=1 + a gated worker: a second distinct-key Mine
    is shed with RETRY_AFTER; powlib paces itself off the server hint
    (non-counting — zero transport retries burned) and completes once
    the round drains."""
    s = Stack(1, coord_extra={"SchedMaxInflight": 1,
                              "SchedRetryAfterS": 0.05})
    gate = threading.Event()
    s.workers[0].handler.backend = _GatedBackend(
        s.workers[0].handler.backend, gate)
    try:
        client = s.new_client("client1")
        before = {k: REGISTRY.get(k) for k in (
            "powlib.retries", "powlib.retry_after", "powlib.degraded",
            "sched.admission_rejected")}
        client.mine(b"\x81\x01", 2)  # occupies the single in-flight slot
        deadline = time.time() + 10
        while time.time() < deadline and not s.coordinator.handler._tasks:
            time.sleep(0.01)
        client.mine(b"\x81\x02", 2)  # must be shed until the gate opens
        deadline = time.time() + 20
        while time.time() < deadline and \
                REGISTRY.get("sched.admission_rejected") \
                - before["sched.admission_rejected"] < 2:
            time.sleep(0.01)
        gate.set()
        for _ in range(2):
            r = client.notify_queue.get(timeout=60)
            assert r.error is None, r.error
            assert puzzle.check_secret(r.nonce, r.secret, 2)
        delta = {k: REGISTRY.get(k) - v for k, v in before.items()}
        assert delta["sched.admission_rejected"] >= 2
        assert delta["powlib.retry_after"] >= 2
        assert delta["powlib.retries"] == 0, \
            "backpressure burned the transport retry budget"
        assert delta["powlib.degraded"] == 0
    finally:
        gate.set()
        s.close()


def test_rpc_retry_after_frame_roundtrip():
    """The typed hint survives the wire: a handler raising
    AdmissionReject surfaces client-side as RPCRetryAfter with the
    delay, not as a plain string error."""

    class Svc:
        def Busy(self, params):
            raise AdmissionReject(1.25, "run queue full (tests)")

        def Fine(self, params):
            return {"ok": True}

    server = RPCServer()
    server.register("Svc", Svc())
    addr = server.listen("127.0.0.1:0")
    server.serve_in_background()
    client = RPCClient(addr)
    try:
        with pytest.raises(RPCRetryAfter) as ei:
            client.call("Svc.Busy", {}, timeout=10)
        assert ei.value.delay_s == pytest.approx(1.25)
        assert "retry-after:1.250s" in str(ei.value)
        assert client.call("Svc.Fine", {}, timeout=10) == {"ok": True}
    finally:
        client.close()
        server.shutdown()


# -- powlib: retry-after semantics -------------------------------------------

def _stub_pow(retries=2, script=None):
    """A POW whose attempt/reconnect machinery is scripted."""
    p = POW()
    p.coord_addr = "stub:0"
    p.retries = retries
    p.backoff_s = 0.001
    p.backoff_max_s = 0.002
    p.coordinator = object()  # _conn() only needs non-None
    events = []
    script = list(script or [])

    def issue(client, trace, nonce, ntz):
        step = script.pop(0)
        events.append(step[0])
        if step[0] == "ok":
            return step[1]
        raise step[1]

    p._issue_attempt = issue
    p._reconnect = lambda gen, attempt: (events.append("reconnect")
                                         or True)
    return p, events


def test_retry_after_is_non_counting_and_interleaves_with_reconnect():
    """The ISSUE-4 edge: RETRY_AFTER replies interleaved with a real
    transport outage + reconnect.  Backpressure attempts must not touch
    the budget; the transport failure consumes one unit and the
    (stubbed, successful) reconnect restores it; the mine completes
    without ever approaching 'degraded'."""
    reply = {"nonce": [1], "num_trailing_zeros": 2, "secret": [9],
             "token": "x"}
    p, events = _stub_pow(retries=1, script=[
        ("retry_after", RPCRetryAfter("retry-after:0.010s full", 0.01)),
        ("retry_after", RPCRetryAfter("retry-after:0.010s full", 0.01)),
        ("transport", RPCTransportError("conn reset")),
        ("retry_after", RPCRetryAfter("retry-after:0.010s full", 0.01)),
        ("ok", reply),
    ])
    before = {k: REGISTRY.get(k) for k in
              ("powlib.retries", "powlib.retry_after", "powlib.degraded")}
    out = p._mine_with_retry(None, b"\x01", 2)
    assert out == reply
    assert events == ["retry_after", "retry_after", "transport",
                      "reconnect", "retry_after", "ok"]
    delta = {k: REGISTRY.get(k) - v for k, v in before.items()}
    assert delta["powlib.retry_after"] == 3
    assert delta["powlib.retries"] == 1  # only the transport failure
    assert delta["powlib.degraded"] == 0


def test_retry_after_alone_never_burns_budget_but_ceiling_terminates():
    """A permanently saturated coordinator: every attempt is shed.  The
    budget stays untouched (no reconnect churn), yet the overall
    attempts ceiling still converts the loop into a terminal degraded
    error — the 'never hangs' contract."""
    from distpow_tpu.nodes.powlib import _MineFailed

    cap = max(8, 1 * 10)
    p, events = _stub_pow(retries=1, script=[
        ("retry_after", RPCRetryAfter("retry-after:0.001s full", 0.001))
    ] * (cap + 1))
    before = REGISTRY.get("powlib.retries")
    with pytest.raises(_MineFailed) as ei:
        p._mine_with_retry(None, b"\x02", 2)
    assert str(ei.value).startswith("degraded:")
    assert "reconnect" not in events
    assert REGISTRY.get("powlib.retries") - before == 0


def test_retry_after_wait_is_close_interruptible():
    """close() during a server-paced wait abandons the mine promptly
    instead of sleeping out the hint."""
    p, _ = _stub_pow(retries=1, script=[
        ("retry_after", RPCRetryAfter("retry-after:30.000s full", 30.0)),
        ("ok", {}),
    ])
    out = {}

    def run():
        out["res"] = p._mine_with_retry(None, b"\x03", 2)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    time.sleep(0.1)
    p._close_ev.set()
    t.join(timeout=5)
    assert not t.is_alive(), "close did not interrupt the retry-after wait"
    assert out["res"] is None


def test_degraded_backpressure_surfaces_as_error_result():
    """End to end through _call_mine: an exhausted backpressure loop
    delivers a MineResult with a degraded error, never a hang."""
    p, _ = _stub_pow(retries=0, script=[
        ("retry_after", RPCRetryAfter("retry-after:0.001s full", 0.001))
    ] * 20)
    p.notify_queue = queue.Queue(maxsize=10)

    from distpow_tpu.runtime.tracing import MemorySink, Tracer

    tracer = Tracer("clientX", MemorySink())
    trace = tracer.create_trace()
    p._call_mine(tracer, b"\x04", 2, trace)
    res: MineResult = p.notify_queue.get(timeout=5)
    assert res.secret is None
    assert res.error and res.error.startswith("degraded:")
