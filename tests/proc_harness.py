"""Shared multi-process stack harness for process-surface tests.

Boots the reference deployment shape (SURVEY.md §3.5) — tracing server,
coordinator, workers, client — as real subprocesses on random localhost
ports, with the config tweaks and teardown discipline every such test
needs.  Used by tests/test_cli.py (demo scenario) and
tests/test_watchdog.py (hung-worker recovery); keep fixes here so the
copies cannot drift.
"""

import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class ProcStack:
    """Config generation + subprocess lifecycle for one test stack."""

    def __init__(self, tmp_path, workers=2, seed=123,
                 coord_overrides=None, worker_overrides=None):
        from distpow_tpu.cli import config_gen

        self.tmp = tmp_path
        config_gen.main(["--config-dir", str(tmp_path),
                         "--workers", str(workers), "--seed", str(seed)])
        self.coord_cfg = self._edit("coordinator_config.json",
                                    coord_overrides or {})
        # python backend by default: subprocess workers should not pay
        # JAX warmup unless a test opts in
        self.worker_cfg = self._edit(
            "worker_config.json", {"Backend": "python",
                                   **(worker_overrides or {})})
        self._edit("tracing_server_config.json", {
            "OutputFile": str(tmp_path / "trace_output.log"),
            "ShivizOutputFile": str(tmp_path / "shiviz_output.log"),
        })
        self.env = dict(os.environ)
        self.env["PALLAS_AXON_POOL_IPS"] = ""  # no TPU in subprocesses
        self.env["JAX_PLATFORMS"] = "cpu"
        self.procs = []

    def _edit(self, name, overrides):
        path = self.tmp / name
        cfg = json.loads(path.read_text())
        cfg.update(overrides)
        path.write_text(json.dumps(cfg))
        return cfg

    def config(self, name):
        return str(self.tmp / name)

    def spawn(self, *argv, track=True):
        p = subprocess.Popen(
            [sys.executable, *argv], cwd=REPO, env=self.env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        if track:
            self.procs.append(p)
        return p

    def boot_core(self):
        """Tracing server then coordinator (order matters, SURVEY §3.5)."""
        self.spawn("-m", "distpow_tpu.cli.tracing_server",
                   "--config", self.config("tracing_server_config.json"))
        time.sleep(0.5)
        self.spawn("-m", "distpow_tpu.cli.coordinator",
                   "--config", self.config("coordinator_config.json"))
        time.sleep(0.5)

    def boot_worker(self, index, wait_ready=True):
        """CLI worker ``index`` (0-based) on its configured address.
        ``wait_ready`` blocks on the worker's own "serving ... RPCs"
        log line — a fixed sleep races the bind on loaded machines."""
        p = self.spawn(
            "-m", "distpow_tpu.cli.worker",
            "--config", self.config("worker_config.json"),
            "--id", f"worker{index + 1}",
            "--listen", self.coord_cfg["Workers"][index],
        )
        if wait_ready:
            self.wait_for_line(p, f"serving worker{index + 1} RPCs")
        return p

    def wait_for_line(self, proc, marker, timeout=30.0):
        """Consume ``proc`` stdout until ``marker`` appears (readiness
        handshake — fixed sleeps race on loaded machines).

        The blocking readline runs in a helper thread so the deadline
        preempts a silent-but-alive child; everything read so far rides
        in the failure message (a silent flake is undiagnosable).  The
        reader keeps draining after the match — a child that keeps
        logging must not block on a full 64KB pipe — and stdout EOF
        fails fast with the exit code instead of burning the timeout."""
        import threading

        lines = []
        found_line = []
        found = threading.Event()
        eof = threading.Event()

        def reader():
            for line in proc.stdout:
                lines.append(line)
                if marker in line and not found.is_set():
                    found_line.append(line)
                    found.set()
                # no early return: keep draining the pipe for the
                # child's lifetime (daemon thread)
            eof.set()

        threading.Thread(target=reader, daemon=True).start()
        deadline = time.time() + timeout
        while time.time() < deadline:
            if found.wait(0.05):
                return found_line[0]
            if eof.is_set():
                raise AssertionError(
                    f"child exited (rc={proc.poll()}) before {marker!r} "
                    f"appeared; output:\n{''.join(lines)[-2000:]}"
                )
        raise AssertionError(
            f"{marker!r} never appeared on stdout within {timeout}s; "
            f"output so far:\n{''.join(lines)[-2000:]}"
        )

    def close(self):
        for p in self.procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in self.procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
