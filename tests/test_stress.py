"""Concurrency stress harness — the framework's race-detection story.

The reference has no race detection (SURVEY.md section 5: shared state
behind mutexes, nothing runs Go's -race).  Here the equivalent is
adversarial load + the trace oracle: many concurrent clients hammer
overlapping (nonce, difficulty) requests through the full RPC stack, and
afterwards we assert (a) every result is a valid solving secret, (b) all
per-task state drained (no leaked queues/events), and (c) the recorded
trace still satisfies every protocol ordering invariant
(runtime/trace_check.py — this combination already caught a real
emit-order race in the tracer).
"""

import queue
import sys
import threading
import time
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).parent))

from test_nodes import Stack  # noqa: E402

from distpow_tpu.models import puzzle  # noqa: E402
from distpow_tpu.runtime.config import TracingServerConfig  # noqa: E402
from distpow_tpu.runtime.trace_check import (  # noqa: E402
    check_shiviz_log,
    check_trace_log,
)
from distpow_tpu.runtime.trace_server import TracingServer  # noqa: E402
from distpow_tpu.runtime.tracing import TCPSink  # noqa: E402


def hammer(stack, n_clients: int, requests_per_client: int, seed: int):
    """Concurrent clients issuing overlapping nonces/difficulties."""
    errors: "queue.Queue" = queue.Queue()

    def run_client(ci: int):
        try:
            client = stack.new_client(f"client{ci + 1}")
            got = []
            for r in range(requests_per_client):
                # overlap nonces across clients on purpose: repeats, the
                # dominance supersede path, and concurrent identical keys
                nonce = bytes([seed, (ci + r) % 3])
                ntz = 1 + (r % 2)
                client.mine(nonce, ntz)
                got.append((nonce, ntz))
            for nonce, ntz in got:
                res = client.notify_queue.get(timeout=60)
                assert puzzle.check_secret(res.nonce, res.secret,
                                           res.num_trailing_zeros), \
                    (res.nonce, res.secret)
        except Exception as exc:  # surfaced in the main thread
            errors.put((ci, repr(exc)))

    threads = [
        threading.Thread(target=run_client, args=(i,), daemon=True)
        for i in range(n_clients)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
        assert not t.is_alive(), "stress client wedged"
    assert errors.empty(), list(errors.queue)


def test_stress_concurrent_clients_memory_sinks():
    s = Stack(2)
    try:
        hammer(s, n_clients=6, requests_per_client=4, seed=0x30)
        # all per-task state drained
        deadline = time.time() + 10
        while time.time() < deadline and (
            s.coordinator.handler._tasks
            or any(w.handler._tasks for w in s.workers)
        ):
            time.sleep(0.05)
        assert s.coordinator.handler._tasks == {}
        for w in s.workers:
            assert w.handler._tasks == {}
        assert s.coordinator.handler._key_locks == {}
    finally:
        s.close()


def test_stress_trace_invariants_hold(tmp_path):
    """Same load against a real tracing server; the trace oracle must be
    violation-free afterwards."""
    out = tmp_path / "trace_output.log"
    shiviz = tmp_path / "shiviz_output.log"
    server = TracingServer(TracingServerConfig(
        ServerBind="127.0.0.1:0", Secret=b"",
        OutputFile=str(out), ShivizOutputFile=str(shiviz),
    ))
    addr = server.open()
    server.accept_in_background()
    s = Stack(2, sink_factory=lambda name: TCPSink(addr, b""))
    try:
        hammer(s, n_clients=4, requests_per_client=3, seed=0x40)
    finally:
        s.close()
        time.sleep(0.5)
        server.close()
    assert check_trace_log(str(out)) == []
    assert check_shiviz_log(str(shiviz)) == []


@pytest.mark.slow
def test_mesh_worker_death_mid_solve_reassigned(tmp_path):
    """Failure recovery composed with the MESH backends at the process
    level (VERDICT r4 item 4): a pallas-mesh worker is SIGKILLed while
    its first Mine is in flight (its interpret-mode launch is slow by
    construction, so the kill deterministically lands mid-solve and its
    cancel-acks are still outstanding); FailurePolicy="reassign" must
    prune it, re-solve its shard through the surviving jax-mesh worker,
    complete all four demo requests, and leave the trace oracle clean.
    The reference errors out of the whole Mine in this situation
    (/root/reference/coordinator.go:196-229)."""
    import signal
    import subprocess

    from proc_harness import ProcStack

    from distpow_tpu.cli.stats import fetch_stats

    stack = ProcStack(
        tmp_path, workers=2, seed=905,
        coord_overrides={"FailurePolicy": "reassign",
                         "FailureProbeSecs": 0.5},
        # worker_config.json = the DOOMED pallas-mesh worker: interpret
        # mode (no TPU in subprocesses) over a 4-device virtual CPU
        # mesh; no warmup, so its first Mine pays the slow interpret
        # launch and is guaranteed still in flight when we kill it
        worker_overrides={"Backend": "pallas-mesh", "MeshDevices": 4,
                          "PallasInterpret": True, "BatchSize": 1 << 14,
                          "WarmupNonceLens": [], "WarmupWidths": []},
    )
    stack.env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    # second config file for the SURVIVOR: the XLA mesh step over the
    # same virtual mesh (fast on CPU) — "re-solved through a second
    # mesh worker" needs both sides of the kill to be mesh backends
    survivor_cfg = dict(stack.worker_cfg)
    survivor_cfg.update({"Backend": "jax-mesh", "PallasInterpret": False})
    (tmp_path / "worker_mesh2_config.json").write_text(
        __import__("json").dumps(survivor_cfg))
    try:
        stack.boot_core()
        doomed = stack.spawn(
            "-m", "distpow_tpu.cli.worker",
            "--config", stack.config("worker_config.json"),
            "--id", "worker1", "--listen", stack.coord_cfg["Workers"][0],
        )
        stack.wait_for_line(doomed, "serving worker1 RPCs")
        survivor = stack.spawn(
            "-m", "distpow_tpu.cli.worker",
            "--config", stack.config("worker_mesh2_config.json"),
            "--id", "worker2", "--listen", stack.coord_cfg["Workers"][1],
        )
        stack.wait_for_line(survivor, "serving worker2 RPCs")

        # difficulty 4 sizes the kill window: the doomed worker's
        # interpret launches run ~4 s each (measured ~1 s per 4096
        # candidates), so its first Mine is still mid-launch — acks
        # outstanding — when the SIGKILL lands
        client = stack.spawn(
            "-m", "distpow_tpu.cli.client",
            "--config", stack.config("client_config.json"),
            "--config2", stack.config("client2_config.json"),
            "--difficulty", "4",
        )

        # kill trigger: the doomed worker's own Stats counters prove a
        # Mine is in flight on it (no fixed sleeps)
        deadline = time.time() + 60
        while time.time() < deadline:
            try:
                snap = fetch_stats(stack.coord_cfg["Workers"][0],
                                   role="worker", timeout=2.0)
                if (snap["counters"].get("worker.mine_rpcs", 0) >= 1
                        and snap["active_tasks"] >= 1):
                    break
            except Exception:
                pass
            time.sleep(0.05)
        else:
            raise AssertionError("doomed worker never received a Mine")
        doomed.send_signal(signal.SIGKILL)

        # every storm request still completes: the reap path prunes the
        # dead mesh worker mid-flight and the survivor covers its shard
        out, _ = client.communicate(timeout=180)
        assert client.returncode == 0, out
        assert out.count("MineResult") == 4, out
        assert doomed.wait(timeout=10) == -signal.SIGKILL

        # a FRESH post-kill nonce exercises the fan-out-into-the-corpse
        # path deterministically: the dead worker's Mine send fails, is
        # counted, and its shard is placed on (and re-solved by) the
        # surviving mesh worker
        from distpow_tpu.nodes.client import Client
        from distpow_tpu.runtime.config import ClientConfig, read_json_config

        late = Client(read_json_config(
            stack.config("client_config.json"), ClientConfig))
        late.config.ClientID = "client_late"
        try:
            late.initialize()
            late.mine(bytes([0x91, 0x05]), 2)
            res = late.notify_queue.get(timeout=120)
            assert puzzle.check_secret(res.nonce, res.secret, 2)
        finally:
            late.close()

        coord_snap = fetch_stats(
            stack.coord_cfg["ClientAPIListenAddr"], role="coordinator",
            timeout=5.0)
        assert coord_snap["counters"].get("coord.worker_failures", 0) >= 1
        assert coord_snap["counters"].get("coord.reassigned_shards", 0) >= 1
    finally:
        stack.close()
        time.sleep(0.5)

    assert check_trace_log(str(tmp_path / "trace_output.log")) == []
    assert check_shiviz_log(str(tmp_path / "shiviz_output.log")) == []


def test_stress_chaos_worker_death_reassign_journal(tmp_path):
    """Three subsystems under one adversarial load (round 4): concurrent
    overlapping clients + a worker killed MID-load with
    FailurePolicy="reassign" + a live cache journal.  Afterwards: every
    request completed with a valid secret (hammer asserts), the trace
    oracle is violation-free, per-task state drained, and a FRESH cache
    replayed from the journal satisfies every requested nonce —
    i.e. failure recovery, checkpoint/resume, and tracing compose."""
    cache_file = str(tmp_path / "cache.jsonl")
    out = tmp_path / "trace_output.log"
    shiviz = tmp_path / "shiviz_output.log"
    server = TracingServer(TracingServerConfig(
        ServerBind="127.0.0.1:0", Secret=b"",
        OutputFile=str(out), ShivizOutputFile=str(shiviz),
    ))
    addr = server.open()
    server.accept_in_background()
    s = Stack(3, failure_policy="reassign", failure_probe_secs=0.2,
              coord_cache_file=cache_file,
              sink_factory=lambda name: TCPSink(addr, b""))
    killed = threading.Event()

    def killer():
        # deterministically land inside the storm: wait for a LIVE task
        # (a Mine in flight), then kill — not a fixed sleep, which can
        # fire after the low-difficulty storm has already drained
        deadline = time.time() + 10
        while time.time() < deadline and not s.coordinator.handler._tasks:
            time.sleep(0.002)
        s.workers[2].server.shutdown()  # inbound RPCs now fail
        killed.set()

    threading.Thread(target=killer, daemon=True).start()
    try:
        hammer(s, n_clients=5, requests_per_client=3, seed=0x50)
        assert killed.wait(10)
        # a FRESH post-kill nonce must fan out into the dead worker and
        # come back anyway — the reassignment path, exercised
        # unconditionally (the storm may or may not have covered it)
        late = s.new_client("client_late")
        late.mine(bytes([0x51, 9]), 2)
        res = late.notify_queue.get(timeout=60)
        assert puzzle.check_secret(res.nonce, res.secret, 2)
        deadline = time.time() + 10
        while time.time() < deadline and (
            s.coordinator.handler._tasks
            or any(w.handler._tasks for w in s.workers[:2])
        ):
            time.sleep(0.05)
        assert s.coordinator.handler._tasks == {}
        assert s.coordinator.handler._key_locks == {}
        for w in s.workers[:2]:
            assert w.handler._tasks == {}
    finally:
        s.close()
        time.sleep(0.5)
        server.close()

    # The trace oracle binds REACHABLE workers.  The killed worker's
    # miner threads outlive its server: one of them can legitimately
    # win the low-difficulty race and report a Result over its (still
    # healthy) outbound forwarder — but the cancellation that would
    # complete its trace is undeliverable to a node whose listener is
    # gone, so its local trace honestly ends at WorkerResult (the
    # reference has the same shape: the killChan receive blocks
    # forever, worker.go:375-379).  The drain assertions above already
    # scope to workers[:2] for the same reason; whether the killed
    # worker's find lands before or after the shutdown is a pure
    # scheduler race (observed flipping with machine load), so the
    # oracle check must not hang the verdict on it.
    killed_dangling = (
        "worker3 shard 2: WorkerResult without a following WorkerCancel",
        "worker3 shard 2: WorkerCancel is not the final worker action",
    )
    viol = [v for v in check_trace_log(str(out))
            if not any(k in v for k in killed_dangling)]
    assert viol == []
    assert check_shiviz_log(str(shiviz)) == []

    # checkpoint/resume: a coordinator restarted on this journal serves
    # every nonce the storm mined straight from cache (dominance covers
    # the lower difficulty of each overlapped pair)
    from distpow_tpu.runtime.cache import ResultCache

    replay = ResultCache(persist_path=cache_file)
    for k in range(3):
        nonce = bytes([0x50, k])
        secret = replay.satisfies(nonce, 1)
        assert secret is not None, f"journal lost nonce {nonce.hex()}"
        assert puzzle.check_secret(nonce, secret, 1)


def test_scheduler_bounds_contention_pile_up():
    """ISSUE-4 upgrade of the measure-don't-fix contention test below:
    with the continuous-batching scheduler enabled, N concurrent Mine
    requests no longer pile N miner threads into backend.search — the
    ``worker.active_searches`` gauge the PR-3 test used to RECORD the
    pile-up must now stay at zero (one engine loop owns the device)
    while the batch-occupancy histogram shows the requests sharing
    launches, and every request still completes with a valid secret."""
    from distpow_tpu.runtime.metrics import REGISTRY

    N = 6
    s = Stack(1, backend="jax",
              worker_extra={"Scheduler": "batching", "BatchSize": 1 << 10,
                            "SchedMaxSlots": N,
                            "WarmupNonceLens": [], "WarmupWidths": []})
    occ0 = REGISTRY.get_histogram("sched.batch_occupancy") or \
        {"count": 0, "sum": 0.0}
    peak = {"active_searches": 0, "active_slots": 0}
    stop = threading.Event()

    def sample():
        while not stop.is_set():
            peak["active_searches"] = max(
                peak["active_searches"],
                REGISTRY.get("worker.active_searches"))
            peak["active_slots"] = max(
                peak["active_slots"], REGISTRY.get("sched.active_slots"))
            time.sleep(0.001)

    sampler = threading.Thread(target=sample, daemon=True)
    sampler.start()
    try:
        client = s.new_client("client1")
        for i in range(N):
            client.mine(bytes([0xA0, i]), 3)
        for _ in range(N):
            res = client.notify_queue.get(timeout=120)
            assert res.error is None, res.error
            assert puzzle.check_secret(res.nonce, res.secret,
                                       res.num_trailing_zeros)
    finally:
        stop.set()
        sampler.join(timeout=5)
        s.close()
    # the pile-up is gone: no miner thread ever entered backend.search
    assert peak["active_searches"] == 0, peak
    # ...and the slot table is the bounded replacement signal
    assert peak["active_slots"] <= N
    occ1 = REGISTRY.get_histogram("sched.batch_occupancy")
    count = occ1["count"] - occ0["count"]
    mean = (occ1["sum"] - occ0["sum"]) / count
    assert count >= 1 and mean > 1, (count, mean)
    # drained afterwards: gauges fall back to zero with the load gone
    deadline = time.time() + 10
    while time.time() < deadline and (
            REGISTRY.get("sched.active_slots") != 0
            or REGISTRY.get("sched.run_queue_depth") != 0):
        time.sleep(0.01)
    assert REGISTRY.get("sched.active_slots") == 0
    assert REGISTRY.get("sched.run_queue_depth") == 0


def test_multi_request_contention_on_one_backend_recorded():
    """VERDICT r5 weak #4, measure-don't-fix: N concurrent Mine requests
    pile onto ONE worker's single backend.  The new gauges must record
    the pile-up — ``worker.active_searches`` (miner threads inside
    backend.search) and ``worker.mine_queue_depth`` (task-table depth) —
    so the admission-control gap has numbers before anyone designs the
    fix.  The backend is gated so the contention window is deterministic,
    not a race against trivial-difficulty solve times."""
    from distpow_tpu.runtime.metrics import REGISTRY
    from distpow_tpu.runtime.telemetry import RECORDER

    N = 3
    s = Stack(1)
    handler = s.workers[0].handler
    gate = threading.Event()
    inner = handler.backend

    class GatedBackend:
        """Blocks every search until the gate opens (cancel-aware)."""

        def search(self, nonce, ntz, tbs, cancel_check=None):
            while not gate.is_set():
                if cancel_check is not None and cancel_check():
                    return None
                time.sleep(0.002)
            return inner.search(nonce, ntz, tbs, cancel_check=cancel_check)

    handler.backend = GatedBackend()
    try:
        client = s.new_client("client1")
        for i in range(N):
            client.mine(bytes([0x90, i]), 2)
        # all N searches must be IN the backend concurrently before the
        # gate opens — the gauges sample the actual pile-up
        deadline = time.time() + 20
        while time.time() < deadline and \
                REGISTRY.get("worker.active_searches") < N:
            time.sleep(0.01)
        peak_active = REGISTRY.get("worker.active_searches")
        peak_queue = REGISTRY.get("worker.mine_queue_depth")
        assert peak_active == N, \
            f"contention never recorded: active_searches={peak_active}"
        assert peak_queue >= N, \
            f"task table depth not recorded: mine_queue_depth={peak_queue}"
        # leave the measurement in the flight recorder: the artifact the
        # future admission-control design starts from
        RECORDER.record("stress.contention", backend="python",
                        requests=N, active_searches=peak_active,
                        mine_queue_depth=peak_queue)
        gate.set()
        for _ in range(N):
            res = client.notify_queue.get(timeout=60)
            assert puzzle.check_secret(res.nonce, res.secret,
                                       res.num_trailing_zeros)
        # drained: the gauges fall back to zero with the load gone —
        # BOTH of them (a queue-depth gauge stuck at the high-water
        # mark would fake a permanent backlog; review PR 3)
        deadline = time.time() + 10
        while time.time() < deadline and (
                REGISTRY.get("worker.active_searches") != 0
                or REGISTRY.get("worker.mine_queue_depth") != 0):
            time.sleep(0.01)
        assert REGISTRY.get("worker.active_searches") == 0
        assert REGISTRY.get("worker.mine_queue_depth") == 0
    finally:
        gate.set()
        s.close()
