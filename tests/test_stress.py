"""Concurrency stress harness — the framework's race-detection story.

The reference has no race detection (SURVEY.md section 5: shared state
behind mutexes, nothing runs Go's -race).  Here the equivalent is
adversarial load + the trace oracle: many concurrent clients hammer
overlapping (nonce, difficulty) requests through the full RPC stack, and
afterwards we assert (a) every result is a valid solving secret, (b) all
per-task state drained (no leaked queues/events), and (c) the recorded
trace still satisfies every protocol ordering invariant
(runtime/trace_check.py — this combination already caught a real
emit-order race in the tracer).
"""

import queue
import sys
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent))

from test_nodes import Stack  # noqa: E402

from distpow_tpu.models import puzzle  # noqa: E402
from distpow_tpu.runtime.config import TracingServerConfig  # noqa: E402
from distpow_tpu.runtime.trace_check import (  # noqa: E402
    check_shiviz_log,
    check_trace_log,
)
from distpow_tpu.runtime.trace_server import TracingServer  # noqa: E402
from distpow_tpu.runtime.tracing import TCPSink  # noqa: E402


def hammer(stack, n_clients: int, requests_per_client: int, seed: int):
    """Concurrent clients issuing overlapping nonces/difficulties."""
    errors: "queue.Queue" = queue.Queue()

    def run_client(ci: int):
        try:
            client = stack.new_client(f"client{ci + 1}")
            got = []
            for r in range(requests_per_client):
                # overlap nonces across clients on purpose: repeats, the
                # dominance supersede path, and concurrent identical keys
                nonce = bytes([seed, (ci + r) % 3])
                ntz = 1 + (r % 2)
                client.mine(nonce, ntz)
                got.append((nonce, ntz))
            for nonce, ntz in got:
                res = client.notify_queue.get(timeout=60)
                assert puzzle.check_secret(res.nonce, res.secret,
                                           res.num_trailing_zeros), \
                    (res.nonce, res.secret)
        except Exception as exc:  # surfaced in the main thread
            errors.put((ci, repr(exc)))

    threads = [
        threading.Thread(target=run_client, args=(i,), daemon=True)
        for i in range(n_clients)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
        assert not t.is_alive(), "stress client wedged"
    assert errors.empty(), list(errors.queue)


def test_stress_concurrent_clients_memory_sinks():
    s = Stack(2)
    try:
        hammer(s, n_clients=6, requests_per_client=4, seed=0x30)
        # all per-task state drained
        deadline = time.time() + 10
        while time.time() < deadline and (
            s.coordinator.handler._tasks
            or any(w.handler._tasks for w in s.workers)
        ):
            time.sleep(0.05)
        assert s.coordinator.handler._tasks == {}
        for w in s.workers:
            assert w.handler._tasks == {}
        assert s.coordinator.handler._key_locks == {}
    finally:
        s.close()


def test_stress_trace_invariants_hold(tmp_path):
    """Same load against a real tracing server; the trace oracle must be
    violation-free afterwards."""
    out = tmp_path / "trace_output.log"
    shiviz = tmp_path / "shiviz_output.log"
    server = TracingServer(TracingServerConfig(
        ServerBind="127.0.0.1:0", Secret=b"",
        OutputFile=str(out), ShivizOutputFile=str(shiviz),
    ))
    addr = server.open()
    server.accept_in_background()
    s = Stack(2, sink_factory=lambda name: TCPSink(addr, b""))
    try:
        hammer(s, n_clients=4, requests_per_client=3, seed=0x40)
    finally:
        s.close()
        time.sleep(0.5)
        server.close()
    assert check_trace_log(str(out)) == []
    assert check_shiviz_log(str(shiviz)) == []


def test_stress_chaos_worker_death_reassign_journal(tmp_path):
    """Three subsystems under one adversarial load (round 4): concurrent
    overlapping clients + a worker killed MID-load with
    FailurePolicy="reassign" + a live cache journal.  Afterwards: every
    request completed with a valid secret (hammer asserts), the trace
    oracle is violation-free, per-task state drained, and a FRESH cache
    replayed from the journal satisfies every requested nonce —
    i.e. failure recovery, checkpoint/resume, and tracing compose."""
    cache_file = str(tmp_path / "cache.jsonl")
    out = tmp_path / "trace_output.log"
    shiviz = tmp_path / "shiviz_output.log"
    server = TracingServer(TracingServerConfig(
        ServerBind="127.0.0.1:0", Secret=b"",
        OutputFile=str(out), ShivizOutputFile=str(shiviz),
    ))
    addr = server.open()
    server.accept_in_background()
    s = Stack(3, failure_policy="reassign", failure_probe_secs=0.2,
              coord_cache_file=cache_file,
              sink_factory=lambda name: TCPSink(addr, b""))
    killed = threading.Event()

    def killer():
        # deterministically land inside the storm: wait for a LIVE task
        # (a Mine in flight), then kill — not a fixed sleep, which can
        # fire after the low-difficulty storm has already drained
        deadline = time.time() + 10
        while time.time() < deadline and not s.coordinator.handler._tasks:
            time.sleep(0.002)
        s.workers[2].server.shutdown()  # inbound RPCs now fail
        killed.set()

    threading.Thread(target=killer, daemon=True).start()
    try:
        hammer(s, n_clients=5, requests_per_client=3, seed=0x50)
        assert killed.wait(10)
        # a FRESH post-kill nonce must fan out into the dead worker and
        # come back anyway — the reassignment path, exercised
        # unconditionally (the storm may or may not have covered it)
        late = s.new_client("client_late")
        late.mine(bytes([0x51, 9]), 2)
        res = late.notify_queue.get(timeout=60)
        assert puzzle.check_secret(res.nonce, res.secret, 2)
        deadline = time.time() + 10
        while time.time() < deadline and (
            s.coordinator.handler._tasks
            or any(w.handler._tasks for w in s.workers[:2])
        ):
            time.sleep(0.05)
        assert s.coordinator.handler._tasks == {}
        assert s.coordinator.handler._key_locks == {}
        for w in s.workers[:2]:
            assert w.handler._tasks == {}
    finally:
        s.close()
        time.sleep(0.5)
        server.close()

    assert check_trace_log(str(out)) == []
    assert check_shiviz_log(str(shiviz)) == []

    # checkpoint/resume: a coordinator restarted on this journal serves
    # every nonce the storm mined straight from cache (dominance covers
    # the lower difficulty of each overlapped pair)
    from distpow_tpu.runtime.cache import ResultCache

    replay = ResultCache(persist_path=cache_file)
    for k in range(3):
        nonce = bytes([0x50, k])
        secret = replay.satisfies(nonce, 1)
        assert secret is not None, f"journal lost nonce {nonce.hex()}"
        assert puzzle.check_secret(nonce, secret, 1)
