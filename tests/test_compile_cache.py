"""Persistent compile-cache error accounting (VERDICT r4 item 2).

bench7 (r4) logged a persistent-cache read error (``UNAVAILABLE: TPU
backend setup/compile error``) that nothing surfaced or counted — the
run silently lost its warm start.  These tests pin the two interception
channels: jax's ``warnings.warn`` read/write-entry failures and the
``jax._src.compiler`` logger's cache-key failures, both counted into
the process metrics registry that the Stats RPC ships.

The warnings channel is exercised in a SUBPROCESS: pytest's own
warnings plugin replaces ``warnings.showwarning`` around every test
(``catch_warnings(record=True)``), which would bypass the chained
production wrapper and test pytest instead of the repo.
"""

from __future__ import annotations

import logging
import subprocess
import sys

import pytest

from distpow_tpu.runtime import compile_cache
from distpow_tpu.runtime.metrics import REGISTRY


@pytest.fixture(autouse=True)
def _fresh_registry():
    REGISTRY.reset()
    compile_cache._install_error_counters()
    yield
    REGISTRY.reset()


def test_read_error_classified_and_counted():
    assert compile_cache._count(
        "Error reading persistent compilation cache entry for "
        "'jit_search_step': UNAVAILABLE: TPU backend setup/compile error",
        "warning",
    )
    assert compile_cache.error_count() == 1
    assert REGISTRY.get(compile_cache.ERRORS_READ) == 1
    assert REGISTRY.get(compile_cache.ERRORS_WRITE) == 0


def test_write_error_classified_and_counted():
    assert compile_cache._count(
        "Error writing persistent compilation cache entry for "
        "'jit_run': PERMISSION_DENIED: /tmp/xla_cache",
        "warning",
    )
    assert REGISTRY.get(compile_cache.ERRORS_WRITE) == 1
    assert compile_cache.error_count() == 1


def test_keygen_log_error_is_counted():
    # the logger channel is NOT touched by pytest's warning capture, so
    # this exercises the real production handler end to end
    logging.getLogger("jax._src.compiler").error(
        "compile_or_get_cached: unable to generate cache key, "
        "skipping the cache: boom"
    )
    assert REGISTRY.get(compile_cache.ERRORS_KEYGEN) == 1
    assert compile_cache.error_count() == 1


def test_classify_anchors_on_literal_jax_phrasings():
    """Regression for the advisor-r5 substring heuristic (ISSUE 8
    satellite): the old ``"read" in m.split("cache")[0]`` matched the
    'read' inside words like 'thread', misclassifying unrelated cache
    warnings as read errors.  _classify must anchor on jax's LITERAL
    'error reading'/'error writing' phrasings and let any other
    cache-related message degrade to the total counter only."""
    # 'thread' before 'compilation cache', no literal 'error reading':
    # cache-related, so counted — but ONLY in the total
    assert compile_cache._classify(
        "a worker thread hit a persistent compilation cache problem"
    ) == compile_cache.ERRORS_TOTAL
    # 'spread'/'already' style words must not trip 'read' either
    assert compile_cache._classify(
        "cache key spread warning touching the compilation cache"
    ) == compile_cache.ERRORS_KEYGEN  # 'cache key' IS a literal anchor
    assert compile_cache._classify(
        "compilation cache entry already present, skipping"
    ) == compile_cache.ERRORS_TOTAL
    # the literal phrasings still classify into their breakdowns
    assert compile_cache._classify(
        "Error reading persistent compilation cache entry for 'jit_x'"
    ) == compile_cache.ERRORS_READ
    assert compile_cache._classify(
        "Error writing persistent compilation cache entry for 'jit_x'"
    ) == compile_cache.ERRORS_WRITE
    # non-cache messages stay out entirely
    assert compile_cache._classify("error reading some config file") is None


def test_classify_total_only_message_counts_once():
    """A cache message with no breakdown anchor increments the total
    counter exactly once and no breakdown counter at all."""
    assert compile_cache._count(
        "persistent compilation cache hiccup in a worker thread", "warning"
    )
    assert compile_cache.error_count() == 1
    assert REGISTRY.get(compile_cache.ERRORS_READ) == 0
    assert REGISTRY.get(compile_cache.ERRORS_WRITE) == 0
    assert REGISTRY.get(compile_cache.ERRORS_KEYGEN) == 0


def test_unrelated_messages_not_counted():
    assert not compile_cache._count("Some unrelated deprecation", "warning")
    logging.getLogger("jax._src.compiler").error("unrelated error")
    # non-ERROR cache chatter (the "Not writing ... since cache is
    # disabled" info lines) must not count either
    logging.getLogger("jax._src.compiler").info(
        "Not writing persistent cache entry with key 'k' since cache "
        "is disabled/not initialized"
    )
    assert compile_cache.error_count() == 0


def test_warnings_channel_intercepts_in_fresh_process():
    """End-to-end: in a pristine process (no pytest warning capture),
    a jax-shaped cache read warning increments the counter AND still
    reaches the normal warning display (the chain forwards)."""
    code = (
        "import warnings, sys\n"
        "from distpow_tpu.runtime import compile_cache\n"
        "compile_cache._install_error_counters()\n"
        # deliberately NO simplefilter: the production install must
        # count REPEAT identical failures too (Python's 'default'
        # action would dedupe the second warn from the same site, and
        # an ongoing cache outage would look like one transient)
        "for _ in range(2):\n"
        "    warnings.warn('Error reading persistent compilation cache "
        "entry for jit_x: UNAVAILABLE: boom')\n"
        "warnings.warn('unrelated warning')\n"
        "print('COUNT', compile_cache.error_count())\n"
    )
    import os

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=120, cwd=repo_root,
    )
    assert out.returncode == 0, out.stderr
    assert "COUNT 2" in out.stdout
    # the original warning still printed (stderr) — interception is a
    # chain, not a replacement
    assert "UNAVAILABLE: boom" in out.stderr


def test_install_is_idempotent():
    import warnings as w

    before = w.showwarning
    compile_cache._install_error_counters()
    compile_cache._install_error_counters()
    assert w.showwarning is before
    # double-install must not stack log handlers either
    handlers = [
        h for h in logging.getLogger("jax._src.compiler").handlers
        if isinstance(h, compile_cache._CacheErrorLogHandler)
    ]
    assert len(handlers) == 1


def test_enable_installs_counters_and_sets_config():
    import jax

    prev_dir = jax.config.jax_compilation_cache_dir
    prev_secs = jax.config.jax_persistent_cache_min_compile_time_secs
    try:
        assert compile_cache.enable("/tmp/xla_cache_test_dir") is True
        assert (jax.config.jax_compilation_cache_dir
                == "/tmp/xla_cache_test_dir")
        # re-pointing the dir must take effect even though jax binds its
        # cache object lazily and ignores later config edits: enable()
        # resets the cache object on a dir change (the in-process
        # worker-reboot scenario test_nodes exercises end to end)
        assert compile_cache.enable("/tmp/xla_cache_test_dir2") is True
        assert (jax.config.jax_compilation_cache_dir
                == "/tmp/xla_cache_test_dir2")
    finally:
        # restore: leaving the persistent cache globally enabled would
        # couple every later test's compiles to /tmp state
        jax.config.update("jax_compilation_cache_dir", prev_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", prev_secs
        )
