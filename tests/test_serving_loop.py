"""Persistent serving-loop suite (docs/SERVING.md, ISSUE 6).

Three layers:

* **golden parity** — the persistent driver must return the SAME
  first-hit secret (reference enumeration order, byte-identical) as the
  solo serial driver and the python oracle, across chunk widths,
  partitions (full / sub / single-byte / non-power-of-two) and hash
  models.  This is the acceptance bar that lets the persistent loop be
  the serving default.
* **flag protocol** — the host-writable stop flag: dispatches issued
  after ``set()`` exit at their first on-device loop check, cancel
  latency is bounded, and the polling drain never issues a blocking
  result conversion (``search.blocking_syncs`` stays flat while the
  serial driver's counter moves).
* **backend plumbing** — ``JaxBackend(loop=...)`` selects the driver,
  warmup compiles the persistent programs, and the config default
  serves persistent.
"""

import threading
import time

import pytest

from distpow_tpu.models import puzzle
from distpow_tpu.parallel import partition
from distpow_tpu.parallel.search import (
    StopFlag,
    persistent_search,
    search,
)
from distpow_tpu.runtime.metrics import REGISTRY


NONCES = [b"\x01\x02\x03\x04", b"\x02\x02\x02\x02", b"\xfe\xff"]


# -- golden parity -----------------------------------------------------------

@pytest.mark.parametrize("nonce", NONCES)
@pytest.mark.parametrize("difficulty", [1, 2, 3])
def test_persistent_matches_serial_and_oracle_full_range(nonce, difficulty):
    tbs = list(range(256))
    oracle = puzzle.python_search(nonce, difficulty, tbs)
    serial = search(nonce, difficulty, tbs, batch_size=1 << 14)
    persistent = persistent_search(nonce, difficulty, tbs,
                                   batch_size=1 << 14)
    assert persistent is not None and serial is not None
    assert persistent.secret == serial.secret == oracle


def test_persistent_parity_deep_widths():
    # difficulty 4 pushes into width >= 2 chunks — the multi-segment
    # while_loop must preserve enumeration order across segment
    # boundaries and across the width cursor
    nonce = b"\x11\x22\x33\x44"
    tbs = list(range(256))
    got = persistent_search(nonce, 4, tbs, batch_size=1 << 16)
    assert got is not None
    assert got.secret == puzzle.python_search(nonce, 4, tbs)


@pytest.mark.parametrize("tbs", [
    list(range(64, 128)),            # pow2 sub-partition (sharded worker)
    [7],                             # single thread byte
    [3, 4, 5],                       # contiguous non-pow2 (static regime)
], ids=["pow2-sub", "single", "non-pow2"])
def test_persistent_parity_partitions(tbs):
    nonce = b"\x05\x06\x07\x08"
    oracle = puzzle.python_search(nonce, 2, tbs)
    got = persistent_search(nonce, 2, tbs, batch_size=1 << 13)
    assert got is not None and got.secret == oracle
    assert got.secret[0] in tbs


def test_persistent_parity_worker_shard():
    nonce = b"\x21\x22\x23"
    bits = partition.worker_bits(4)
    tbs = partition.thread_bytes(2, bits)
    oracle = puzzle.python_search(nonce, 2, tbs)
    got = persistent_search(nonce, 2, tbs, batch_size=1 << 13)
    assert got is not None and got.secret == oracle


@pytest.mark.parametrize("model_name", ["sha1", "sha256", "blake2b_256"])
def test_persistent_parity_models(model_name):
    from distpow_tpu.models.registry import get_hash_model

    model = get_hash_model(model_name)
    nonce = b"\x31\x32\x33\x34"
    tbs = list(range(256))
    oracle = puzzle.python_search(nonce, 2, tbs, algo=model_name)
    serial = search(nonce, 2, tbs, model=model, batch_size=1 << 13)
    got = persistent_search(nonce, 2, tbs, model=model,
                            batch_size=1 << 13)
    assert got is not None and serial is not None
    assert got.secret == serial.secret == oracle


def test_persistent_small_launch_budget_matches_oracle():
    # a tiny per-dispatch budget forces MANY multi-segment dispatches
    # through the pipeline — the FIFO drain must still hand back the
    # enumeration-order first hit
    nonce = b"\x41\x42"
    tbs = list(range(256))
    got = persistent_search(nonce, 3, tbs, batch_size=1 << 10,
                            launch_candidates=1 << 12)
    assert got is not None
    assert got.secret == puzzle.python_search(nonce, 3, tbs)
    assert REGISTRY.get("search.persistent_steps") > 0


# -- budget / unsatisfiable gates (contract parity with search()) ------------

def test_persistent_max_hashes_budget():
    got = persistent_search(b"\x01", 30, list(range(256)),
                            batch_size=1 << 12, max_hashes=1 << 14)
    assert got is None


def test_persistent_unsatisfiable_gates():
    assert persistent_search(b"\x01", 33, list(range(256)),
                             cancel_check=lambda: True) is None
    assert persistent_search(b"\x01", 33, list(range(256)),
                             max_hashes=100) is None
    with pytest.raises(ValueError, match="unsatisfiable"):
        persistent_search(b"\x01", 33, list(range(256)))


# -- flag protocol / polling drain -------------------------------------------

def test_stop_flag_short_circuits_dispatch():
    import jax.numpy as jnp

    from distpow_tpu.ops.search_step import (
        SENTINEL,
        cached_persistent_step,
    )

    step = cached_persistent_step(b"\x51\x52", 1, 2, 0, 256, 4, "md5",
                                  b"", 8)
    flag = StopFlag()
    assert not flag.is_set()
    live = step(jnp.uint32(1), flag.operand())
    flag.set()
    assert flag.is_set()
    stopped = step(jnp.uint32(1), flag.operand())
    f, segs = (int(live[0]), int(live[1]))
    sf, ssegs = (int(stopped[0]), int(stopped[1]))
    assert segs >= 1  # the live dispatch did real work
    assert sf == SENTINEL and ssegs == 0, \
        "a dispatch carrying a set stop flag must exit at segment 0"


def test_persistent_cancel_latency_bounded():
    """Cancel mid-search: the driver must return promptly — it stops
    issuing, flips the stop flag, and never blocks on a result fetch
    while waiting (the poll loop checks the cancel between polls)."""
    ev = threading.Event()
    out = {}

    def run():
        out["res"] = persistent_search(
            b"\xde\xad\xbe", 6, list(range(256)), batch_size=1 << 12,
            launch_candidates=1 << 14, cancel_check=ev.is_set,
        )

    t = threading.Thread(target=run, daemon=True)
    t.start()
    time.sleep(0.5)  # let the pipeline fill
    t0 = time.monotonic()
    ev.set()
    t.join(timeout=30)
    assert not t.is_alive(), "cancel did not stop the persistent search"
    latency = time.monotonic() - t0
    assert out["res"] is None
    # generous CPU bound: the in-flight window is pipeline_depth tiny
    # launches; anything near the full enumeration means the flag or
    # the issue-loop check is broken
    assert latency < 10.0, f"cancel took {latency:.1f}s"


def test_persistent_never_blocks_serial_does():
    nonce, tbs = b"\x61\x62", list(range(256))
    b0 = REGISTRY.get("search.blocking_syncs")
    serial = search(nonce, 3, tbs, batch_size=1 << 10,
                    launch_candidates=1 << 12)
    b1 = REGISTRY.get("search.blocking_syncs")
    persistent = persistent_search(nonce, 3, tbs, batch_size=1 << 10,
                                   launch_candidates=1 << 12)
    b2 = REGISTRY.get("search.blocking_syncs")
    assert serial.secret == persistent.secret
    assert b1 - b0 >= 1, "serial drain stopped counting blocking syncs"
    assert b2 == b1, "persistent drain issued a blocking conversion"


# -- backend plumbing --------------------------------------------------------

def test_jax_backend_loop_selection_and_default():
    from distpow_tpu.backends import JaxBackend, get_backend

    assert JaxBackend().loop == "persistent"  # the serving default
    assert get_backend("jax", loop="serial").loop == "serial"
    with pytest.raises(ValueError, match="unknown search loop"):
        JaxBackend(loop="bogus")
    nonce, tbs = b"\x71\x72", list(range(256))
    per = JaxBackend(batch_size=1 << 13).search(nonce, 2, tbs)
    ser = JaxBackend(batch_size=1 << 13, loop="serial").search(
        nonce, 2, tbs)
    assert per == ser == puzzle.python_search(nonce, 2, tbs)


def test_jax_backend_persistent_warmup_compiles_and_serves():
    from distpow_tpu.backends import JaxBackend

    backend = JaxBackend(batch_size=1 << 12)
    backend.warmup([2], [0, 1, 2])  # must not dispatch real segment work
    got = backend.search(b"\x81\x82", 2, list(range(256)))
    assert got == puzzle.python_search(b"\x81\x82", 2, list(range(256)))


def test_worker_config_search_loop_plumbs_to_backend():
    from distpow_tpu.runtime.config import WorkerConfig

    assert WorkerConfig().SearchLoop == "persistent"
    assert WorkerConfig(SearchLoop="serial").SearchLoop == "serial"
