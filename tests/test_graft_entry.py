"""Driver entry-point contract tests.

``dryrun_multichip`` is the driver's multichip-correctness artifact
(MULTICHIP_r0N.json) and must be outage-proof: it is a pure CPU check
and may never block on the tunneled TPU backend's liveness
(MULTICHIP_r03.json recorded rc=124 because the old ordering called
``jax.devices()`` against a dead tunnel before flipping to the CPU
mesh).  The test runs the real dryrun body in a fresh subprocess with
the DRIVER'S environment — no JAX_PLATFORMS / XLA_FLAGS CPU forcing —
under a hard timeout, so it passes only if the function itself flips
platforms before any backend touch.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_dryrun_multichip_is_outage_proof():
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS", "JAX_NUM_CPU_DEVICES")}
    out = subprocess.run(
        [sys.executable, "-c",
         "from __graft_entry__ import dryrun_multichip; dryrun_multichip(8)"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=420,
    )
    assert out.returncode == 0, (out.stdout, out.stderr[-2000:])
    assert "dryrun_multichip(8)" in out.stdout
    assert "pallas-mesh bit-identical" in out.stdout


def test_dryrun_body_under_forced_cpu():
    """Fast guard: the dryrun body under an explicitly forced-CPU env.

    Subprocess rather than in-process because the body calls
    clear_backends, which would tear down the conftest 8-device mesh
    under every later test in the session.
    """
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    out = subprocess.run(
        [sys.executable, "-c",
         "from __graft_entry__ import dryrun_multichip; dryrun_multichip(4)"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 0, (out.stdout, out.stderr[-2000:])
    assert "dryrun_multichip(4)" in out.stdout
