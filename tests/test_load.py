"""Open-loop load harness (distpow_tpu/load/, ISSUE 8): seeded
schedule determinism, Zipf skew, genuine open-loop dispatch, the
end-to-end harness against a real in-process cluster (cache/coalesce
evidence, SLO green-vs-tightened), chaos-under-load, and the
coordinator's hash-model pass-through."""

from __future__ import annotations

import os
import sys
import time

import pytest

sys.path.insert(0, os.path.dirname(__file__))

from distpow_tpu.load import (  # noqa: E402
    InProcCluster,
    LoadMix,
    OpenLoopRunner,
    build_schedule,
    exact_percentile,
    run_load_slo,
)
from distpow_tpu.load.loadgen import key_nonce  # noqa: E402
from distpow_tpu.obs import load_slo_config  # noqa: E402
from distpow_tpu.models import puzzle  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SLO_GREEN = os.path.join(REPO, "config", "slo.json")


# -- seeded schedule determinism ---------------------------------------------

def test_schedule_is_deterministic_per_seed():
    mix = LoadMix(rate_hz=50.0, duration_s=2.0, seed=905, n_keys=32,
                  zipf_s=1.1, difficulties=((1, 0.5), (2, 0.5)))
    assert build_schedule(mix) == build_schedule(mix)
    other = build_schedule(LoadMix(rate_hz=50.0, duration_s=2.0, seed=906,
                                   n_keys=32, zipf_s=1.1,
                                   difficulties=((1, 0.5), (2, 0.5))))
    assert build_schedule(mix) != other


def test_schedule_arrivals_are_poisson_shaped():
    mix = LoadMix(rate_hz=100.0, duration_s=10.0, seed=1)
    sched = build_schedule(mix)
    # ~rate*duration arrivals, monotonic offsets inside the window
    assert 800 <= len(sched) <= 1200
    ts = [a.t for a in sched]
    assert ts == sorted(ts)
    assert 0.0 < ts[0] and ts[-1] < 10.0


def test_zipf_skew_concentrates_keys():
    flat = build_schedule(LoadMix(rate_hz=200.0, duration_s=5.0, seed=3,
                                  n_keys=64, zipf_s=0.0))
    skew = build_schedule(LoadMix(rate_hz=200.0, duration_s=5.0, seed=3,
                                  n_keys=64, zipf_s=1.3))

    def hot_share(sched):
        counts = {}
        for a in sched:
            counts[a.key] = counts.get(a.key, 0) + 1
        return max(counts.values()) / len(sched)

    assert hot_share(skew) > 3 * hot_share(flat)
    # repeats of one key genuinely repeat the nonce (the cache point)
    by_key = {}
    for a in skew:
        by_key.setdefault(a.key, set()).add(a.nonce)
    assert all(len(nonces) == 1 for nonces in by_key.values())


def test_nonces_disjoint_across_seeds():
    """Two mixes must not cross-hit each other's dominance-cache
    entries — bench.py --load-slo runs one seed per rate."""
    a = {key_nonce(41, k, 4) for k in range(64)}
    b = {key_nonce(42, k, 4) for k in range(64)}
    assert not (a & b)


def test_difficulty_and_model_blends_sampled():
    mix = LoadMix(rate_hz=200.0, duration_s=3.0, seed=5,
                  difficulties=((1, 0.5), (3, 0.5)),
                  hash_models=((None, 0.7), ("sha1", 0.3)))
    sched = build_schedule(mix)
    ntzs = {a.ntz for a in sched}
    models = {a.hash_model for a in sched}
    assert ntzs == {1, 3}
    assert models == {None, "sha1"}
    share = sum(1 for a in sched if a.hash_model == "sha1") / len(sched)
    assert 0.15 < share < 0.45


def test_mix_validation():
    with pytest.raises(ValueError):
        LoadMix(rate_hz=0.0, duration_s=1.0)
    with pytest.raises(ValueError):
        LoadMix(rate_hz=1.0, duration_s=1.0, difficulties=())
    with pytest.raises(ValueError):
        LoadMix(rate_hz=1.0, duration_s=1.0, n_keys=0)


# -- the open-loop runner ----------------------------------------------------

def test_runner_is_open_loop_under_slow_completions():
    """Arrivals fire on schedule even though nothing ever completes —
    the defining property: a slow server faces the offered rate."""
    fired = []
    runner = OpenLoopRunner(lambda a: fired.append(
        (time.monotonic(), a.t)))
    mix = LoadMix(rate_hz=40.0, duration_s=1.0, seed=9)
    rep = runner.run(build_schedule(mix))
    assert rep.issued == len(fired) > 20
    assert rep.submit_errors == 0
    # dispatch stayed on schedule (no completion ever unblocked it)
    assert rep.max_lag_s < 0.5
    t0 = fired[0][0] - fired[0][1]
    for fire_t, sched_t in fired:
        assert fire_t - t0 >= sched_t - 0.05


def test_runner_counts_submit_errors_and_continues():
    calls = []

    def submit(a):
        calls.append(a)
        if len(calls) % 2 == 0:
            raise RuntimeError("boom")

    rep = OpenLoopRunner(submit).run(
        build_schedule(LoadMix(rate_hz=50.0, duration_s=0.5, seed=2)))
    assert rep.issued == len(calls)
    assert rep.submit_errors == len(calls) // 2


def test_runner_stop_aborts_schedule():
    runner = OpenLoopRunner(lambda a: None)
    sched = build_schedule(LoadMix(rate_hz=5.0, duration_s=30.0, seed=4))
    import threading

    threading.Timer(0.3, runner.stop).start()
    t0 = time.monotonic()
    rep = runner.run(sched)
    assert time.monotonic() - t0 < 5.0
    assert rep.issued < len(sched)


def test_exact_percentile():
    assert exact_percentile([], 0.95) is None
    assert exact_percentile([3.0, 1.0, 2.0], 0.5) == 2.0
    assert exact_percentile([1.0], 0.99) == 1.0


# -- end-to-end harness ------------------------------------------------------

def test_harness_green_run_with_cache_and_coalesce_evidence():
    """A skewed open-loop burst against a real cluster: everything
    completes, repeats hit the dominance cache, and the checked-in
    green SLO config passes over the merged run window."""
    mix = LoadMix(rate_hz=12.0, duration_s=2.5, seed=905, n_keys=8,
                  zipf_s=1.2, difficulties=((1, 0.7), (2, 0.3)))
    report, verdict = run_load_slo(mix, SLO_GREEN, n_workers=2,
                                   scrape_interval_s=0.5)
    assert report["request_errors"] == 0
    assert report["completed"] == report["load"]["issued"] > 10
    assert report["merged"]["cache_hits"] > 0  # the Zipf point
    assert report["merged"]["stale_nodes"] == []
    assert report["achieved_solves_per_s"] > mix.rate_hz / 3
    assert verdict.status in ("pass", "warn")
    assert verdict.exit_code() == 0


def test_harness_tightened_config_breaches():
    tight = load_slo_config({
        "objectives": [{"name": "mine_e2e_p95_s",
                        "histogram": "coord.mine_s.miss",
                        "stat": "p95", "max": 1e-6}]})
    mix = LoadMix(rate_hz=10.0, duration_s=1.5, seed=907, n_keys=6,
                  difficulties=((1, 1.0),))
    report, verdict = run_load_slo(mix, tight, n_workers=1,
                                   breach_hooks=False)
    assert report["completed"] > 0
    assert verdict.status == "breach"
    assert verdict.exit_code() == 1


@pytest.mark.faults
def test_harness_chaos_under_load_still_completes():
    """PR 1 fault plane under open-loop traffic: seeded server-side
    delays on the worker Mine path slow rounds down but every request
    still completes and the harness reports it faithfully."""
    mix = LoadMix(rate_hz=6.0, duration_s=2.0, seed=911, n_keys=6,
                  difficulties=((1, 1.0),))
    report, verdict = run_load_slo(
        mix, SLO_GREEN, n_workers=2, scrape_interval_s=0.5,
        fault_spec={"seed": 905, "rules": [
            {"kind": "delay", "side": "server",
             "method": "WorkerRPCHandler.Mine", "delay_s": 0.05},
        ]},
    )
    assert report["mix"]["chaos"] is True
    assert report["request_errors"] == 0
    assert report["completed"] == report["load"]["issued"]
    assert verdict.exit_code() == 0


@pytest.mark.slow
def test_coordinator_hash_model_pass_through_end_to_end():
    """The coordinator seam (ISSUE 8): a client Mine carrying
    ``hash_model`` routes through the coordinator to a model-capable
    scheduler worker, solves under THAT hash, skips the single-model
    dominance cache, and lands in the per-model solve histogram."""
    from distpow_tpu.runtime.metrics import REGISTRY

    cluster = InProcCluster(
        n_workers=1, backend="jax",
        worker_extra={"Scheduler": "batching", "SchedMaxSlots": 4,
                      "SchedHashModels": ["sha1"], "BatchSize": 1 << 10},
    )
    try:
        h0 = REGISTRY.get_histogram("worker.solve_s.sha1") or {"count": 0}
        cluster.client.mine(b"\xa7\x01", 2, hash_model="sha1")
        res = cluster.client.notify_queue.get(timeout=120)
        assert res.error is None, res.error
        assert puzzle.check_secret(res.nonce, res.secret, 2, "sha1")
        # the sha1 secret must NOT be servable from the coordinator's
        # (md5) dominance cache
        coord = cluster.coordinator.handler
        assert coord.result_cache.satisfies(b"\xa7\x01", 2) is None
        # a default-model mine for the same nonce leads its own round
        # and returns an md5-valid secret
        cluster.client.mine(b"\xa7\x01", 2)
        res2 = cluster.client.notify_queue.get(timeout=120)
        assert res2.error is None, res2.error
        assert puzzle.check_secret(res2.nonce, res2.secret, 2)
        # per-model breakdown observed the off-default solve
        h1 = REGISTRY.get_histogram("worker.solve_s.sha1")
        assert h1 and h1["count"] > h0["count"]
    finally:
        cluster.close()
