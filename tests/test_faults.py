"""Deterministic fault-injection chaos matrix (runtime/faults.py).

Three claims are pinned here:

1. **Determinism** — a fault plan is a seeded pure function of the call
   sequence: identical seeds reproduce identical injected-fault
   sequences, both at the plan level and through a real sequential
   protocol run.
2. **Survival** — every fault kind (refuse / delay / truncate /
   duplicate / drop) is ridden out on BOTH control-plane links: the
   client↔coordinator link via powlib's retry/backoff/reconnect
   machinery, and the coordinator↔worker link via
   ``FailurePolicy="reassign"``'s failure detection + shard
   reassignment.  Every chaos run must still produce a valid secret.
3. **Outage recovery** — a coordinator restart mid-mine completes the
   mine through powlib's automatic reconnect with no client-visible
   error, and the retry budget's edge cases (exhaustion => terminal
   "degraded" error, not a hang; jittered backoff within bounds;
   successful reconnect restores the budget) hold.
"""

import contextlib
import queue
import sys
import time
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).parent))

from test_nodes import Stack, mine_and_wait  # noqa: E402

from distpow_tpu.models import puzzle  # noqa: E402
from distpow_tpu.nodes.powlib import (  # noqa: E402
    POW,
    backoff_delay,
)
from distpow_tpu.runtime import faults  # noqa: E402
from distpow_tpu.runtime.faults import FaultPlan  # noqa: E402
from distpow_tpu.runtime.metrics import REGISTRY as metrics  # noqa: E402
from distpow_tpu.runtime.rpc import RPCTransportError  # noqa: E402
from distpow_tpu.runtime.tracing import MemorySink, Tracer, encode_token  # noqa: E402

pytestmark = pytest.mark.faults

FAULT_KINDS = ("refuse", "delay", "truncate", "duplicate", "drop")


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    """A fault plan is process-global state: never leak one across
    tests (or into the rest of the suite)."""
    faults.uninstall()
    yield
    faults.uninstall()


# ---------------------------------------------------------------------------
# 1. determinism
# ---------------------------------------------------------------------------

MIXED_SPEC = {
    "seed": 1234,
    "rules": [
        {"kind": "delay", "method": "CoordRPCHandler.Mine", "side": "client",
         "prob": 0.5, "delay_s": 0.0},
        {"kind": "drop", "method": "WorkerRPCHandler.*", "side": "client",
         "prob": 0.3},
        {"kind": "truncate", "method": "*.Result", "calls": "2:5",
         "prob": 0.8},
    ],
}

SYNTHETIC_CALLS = [
    ("client", "CoordRPCHandler.Mine", "127.0.0.1:1"),
    ("client", "WorkerRPCHandler.Mine", "127.0.0.1:2"),
    ("server", "CoordRPCHandler.Result", "127.0.0.1:3"),
    ("client", "WorkerRPCHandler.Found", "127.0.0.1:2"),
] * 25


def _drive(plan):
    for side, method, peer in SYNTHETIC_CALLS:
        plan.on_frame(side, method, peer)
    return plan.injected


def test_same_seed_same_injected_sequence():
    a = _drive(FaultPlan.from_spec(MIXED_SPEC))
    b = _drive(FaultPlan.from_spec(MIXED_SPEC))
    assert a, "plan never fired — the matrix is vacuous"
    assert a == b
    # and the probabilistic rules actually declined sometimes (a plan
    # that fires on every call proves nothing about seeded decisions)
    assert len(a) < len(SYNTHETIC_CALLS)


def test_different_seed_different_sequence():
    other = dict(MIXED_SPEC, seed=999)
    a = _drive(FaultPlan.from_spec(MIXED_SPEC))
    b = _drive(FaultPlan.from_spec(other))
    assert a != b


def test_call_window_and_max_cap():
    plan = FaultPlan(seed=7, rules=[
        {"kind": "delay", "method": "M.x", "calls": "2:4", "delay_s": 0.0},
        {"kind": "drop", "method": "M.y", "max": 1},
    ])
    hits = []
    for i in range(6):
        hits.append(plan.on_frame("client", "M.x", ""))
    # fires exactly on matching-call indexes 2 and 3
    assert [h is not None for h in hits] == [
        False, False, True, True, False, False]
    assert plan.on_frame("client", "M.y", "") is not None
    assert plan.on_frame("client", "M.y", "") is None  # max=1 spent


def test_env_and_file_install(tmp_path, monkeypatch):
    spec = '{"seed": 5, "rules": [{"kind": "drop", "method": "A.b"}]}'
    # inline JSON via the environment
    monkeypatch.setenv("DISTPOW_FAULTS", spec)
    faults._env_install()
    assert faults.PLAN is not None and faults.PLAN.seed == 5
    faults.uninstall()
    # file path via install_from_spec (the --faults / FaultPlanFile route)
    p = tmp_path / "plan.json"
    p.write_text(spec)
    plan = faults.install_from_spec(str(p))
    assert faults.PLAN is plan and plan.rules[0].kind == "drop"


def test_real_stack_sequential_run_is_deterministic():
    """Six sequential mines through the full RPC stack: the injected
    sequence (delay-only, so control flow never forks) replays exactly
    under the same seed."""
    spec = {
        "seed": 42,
        "rules": [
            {"kind": "delay", "method": "CoordRPCHandler.Mine",
             "side": "client", "prob": 0.5, "delay_s": 0.01},
            {"kind": "delay", "method": "WorkerRPCHandler.Mine",
             "side": "client", "prob": 0.5, "delay_s": 0.01},
        ],
    }

    def run():
        plan = faults.install_from_spec(spec)
        s = Stack(1)
        try:
            client = s.new_client("client1")
            for i in range(6):
                res = mine_and_wait(client, bytes([0x70, i]), 2)
                assert res.error is None
                assert puzzle.check_secret(res.nonce, res.secret, 2)
        finally:
            s.close()
            faults.uninstall()
        return list(plan.injected)

    first, second = run(), run()
    assert first, "no faults injected — determinism claim is vacuous"
    assert first == second


# ---------------------------------------------------------------------------
# 2. survival matrix: client <-> coordinator link
# ---------------------------------------------------------------------------

# client-side plans targeting the Mine RPC; installed AFTER the client
# dialed, so the initial connect is clean and recovery is what's tested.
CLIENT_LINK_PLANS = {
    # truncate forces a re-dial; the refuse rule then rejects the next
    # two reconnect dials before letting one through
    "refuse": {"seed": 11, "rules": [
        {"kind": "truncate", "method": "CoordRPCHandler.Mine",
         "side": "client", "max": 1},
        {"kind": "refuse", "max": 2},
    ]},
    "delay": {"seed": 12, "rules": [
        {"kind": "delay", "method": "CoordRPCHandler.Mine",
         "side": "client", "delay_s": 0.2, "max": 3},
    ]},
    "truncate": {"seed": 13, "rules": [
        {"kind": "truncate", "method": "CoordRPCHandler.Mine",
         "side": "client", "max": 1},
    ]},
    "duplicate": {"seed": 14, "rules": [
        {"kind": "duplicate", "method": "CoordRPCHandler.Mine",
         "side": "client", "max": 2},
    ]},
    # a dropped Mine frame is invisible on a healthy connection: only
    # the per-attempt timeout can observe it (and then re-issue)
    "drop": {"seed": 15, "rules": [
        {"kind": "drop", "method": "CoordRPCHandler.Mine",
         "side": "client", "max": 1},
    ]},
}


@pytest.mark.parametrize("kind", FAULT_KINDS)
def test_client_coordinator_link_survives(kind):
    s = Stack(1)
    try:
        client = s.new_client(
            "client1",
            MineRetries=6, MineBackoffS=0.05, MineBackoffMaxS=0.3,
            MineAttemptTimeoutS=2.0,
        )
        plan = faults.install_from_spec(CLIENT_LINK_PLANS[kind])
        res = mine_and_wait(client, bytes([0x80, ord(kind[0])]), 2,
                            timeout=60)
        assert res.error is None, res.error
        assert puzzle.check_secret(res.nonce, res.secret, 2)
        assert any(inj[1] == kind for inj in plan.injected), \
            f"{kind} fault never injected — survival claim is vacuous"
    finally:
        s.close()


def test_duplicate_mine_delivers_exactly_one_result():
    """A duplicated Mine request is dispatched twice by the coordinator;
    the client must still see exactly one result per mine() call."""
    s = Stack(1)
    try:
        client = s.new_client("client1")
        faults.install_from_spec({"seed": 3, "rules": [
            {"kind": "duplicate", "method": "CoordRPCHandler.Mine",
             "side": "client"},
        ]})
        res = mine_and_wait(client, b"\x81\x01", 2)
        assert puzzle.check_secret(res.nonce, res.secret, 2)
        time.sleep(0.5)
        assert client.notify_queue.empty(), \
            "duplicated request leaked a second result"
    finally:
        s.close()


# ---------------------------------------------------------------------------
# 2b. survival matrix: coordinator <-> worker link
# ---------------------------------------------------------------------------

WORKER_LINK_PLANS = {
    # the coordinator's first dial of a worker is refused once; reassign
    # proceeds with the live subset and re-issues the orphaned shard
    "refuse": {"seed": 21, "rules": [
        {"kind": "refuse", "max": 1},
    ]},
    "delay": {"seed": 22, "rules": [
        {"kind": "delay", "method": "WorkerRPCHandler.*", "side": "client",
         "delay_s": 0.2, "max": 4},
    ]},
    # the worker's Mine RESPONSE is truncated: the coordinator sees a
    # mid-frame reset, marks the worker dead, reassigns its shard
    "truncate": {"seed": 23, "rules": [
        {"kind": "truncate", "method": "WorkerRPCHandler.Mine",
         "side": "server", "max": 1},
    ]},
    # the coordinator's Mine call to a worker is written twice: the
    # worker's round supersede logic must absorb the repeat silently
    "duplicate": {"seed": 24, "rules": [
        {"kind": "duplicate", "method": "WorkerRPCHandler.Mine",
         "side": "client", "max": 1},
    ]},
    # a dropped Mine call blocks until the bounded reassign-mode call
    # timeout declares the worker dead and reassigns
    "drop": {"seed": 25, "rules": [
        {"kind": "drop", "method": "WorkerRPCHandler.Mine",
         "side": "client", "max": 1},
    ]},
}


@pytest.mark.parametrize("kind", FAULT_KINDS)
def test_coordinator_worker_link_survives(kind):
    s = Stack(2, failure_policy="reassign", failure_probe_secs=0.2)
    s.coordinator.handler._call_timeout = 1.5
    try:
        client = s.new_client("client1")
        plan = faults.install_from_spec(WORKER_LINK_PLANS[kind])
        res = mine_and_wait(client, bytes([0x90, ord(kind[0])]), 2,
                            timeout=60)
        assert res.error is None, res.error
        assert puzzle.check_secret(res.nonce, res.secret, 2)
        assert any(inj[1] == kind for inj in plan.injected), \
            f"{kind} fault never injected — survival claim is vacuous"
        # a second, fault-free request proves the stack healed
        faults.uninstall()
        res2 = mine_and_wait(client, bytes([0x91, ord(kind[0])]), 2,
                             timeout=60)
        assert puzzle.check_secret(res2.nonce, res2.secret, 2)
    finally:
        s.close()


# ---------------------------------------------------------------------------
# 3. coordinator outage recovery + retry edge cases
# ---------------------------------------------------------------------------

def test_powlib_rides_out_coordinator_restart(tmp_path):
    """The acceptance scenario: the coordinator dies mid-mine and comes
    back on the same ports; powlib's automatic reconnect re-issues the
    (idempotent) Mine and the client sees a normal result — NO
    client-visible error (contrast tests/test_nodes.py
    test_coordinator_restart_mid_mine, which pins the pre-retry
    surface-the-error behavior once the budget is exhausted)."""
    from distpow_tpu.nodes import Coordinator
    from distpow_tpu.runtime.config import CoordinatorConfig

    cache_file = str(tmp_path / "coord_cache.jsonl")
    s = Stack(1, coord_cache_file=cache_file)
    try:
        client = s.new_client(
            "client1",
            MineRetries=10, MineBackoffS=0.1, MineBackoffMaxS=0.5,
        )
        nonce = b"\x79\x7a"
        # difficulty 5 ~= 1M python-backend candidates: seconds of
        # mining, plenty of window to restart the coordinator mid-search
        client.mine(nonce, 5)
        time.sleep(0.6)  # fan-out done, worker mining

        old_client_addr = s.coordinator.client_addr
        old_worker_addr = s.coordinator.worker_addr
        worker_addrs = [w.bound_addr for w in s.workers]
        s.coordinator.shutdown()

        # restart on the same ports (create_server sets SO_REUSEADDR);
        # retry briefly — re-dial loops targeting this very port can
        # transiently occupy it via a Linux self-connect
        for attempt in range(40):
            try:
                s.coordinator = Coordinator(
                    CoordinatorConfig(
                        ClientAPIListenAddr=old_client_addr,
                        WorkerAPIListenAddr=old_worker_addr,
                        Workers=worker_addrs,
                        CacheFile=cache_file,
                    ),
                    sink=s.sinks["coordinator"],
                )
                s.coordinator.initialize_rpcs()
                break
            except OSError:
                with contextlib.suppress(Exception):
                    s.coordinator.shutdown()
                if attempt == 39:
                    raise
                time.sleep(0.25)

        # the ORIGINAL mine() call must complete: powlib reconnects and
        # re-issues; the restarted coordinator serves it (from the
        # journal-backed cache once the worker's forwarder re-delivers,
        # or by re-fanning out)
        res = client.notify_queue.get(timeout=120)
        assert res.error is None, f"client saw the outage: {res.error}"
        assert puzzle.check_secret(nonce, res.secret, 5)
        assert metrics.get("powlib.reconnects") >= 1
        assert metrics.get("powlib.retries") >= 1
    finally:
        s.close()


def _wired_pow(retries: int) -> "POW":
    """A POW with the retry loop wired but no real coordinator."""
    pow_ = POW()
    pow_.notify_queue = queue.Queue()
    pow_.coordinator = object()  # non-None sentinel; attempts are stubbed
    pow_.retries = retries
    pow_.backoff_s = 0.01
    pow_.backoff_max_s = 0.02
    return pow_


def test_retry_budget_exhaustion_is_terminal_error_not_hang():
    pow_ = _wired_pow(retries=2)
    pow_._issue_attempt = lambda client, trace, nonce, ntz: (
        (_ for _ in ()).throw(RPCTransportError("boom")))
    pow_._reconnect = lambda gen, attempt: False  # outage never heals
    tracer = Tracer("clientX", MemorySink())
    pow_.mine(tracer, b"\x01", 2)
    res = pow_.notify_queue.get(timeout=10)  # a hang fails here
    assert res.secret is None
    assert res.error is not None and res.error.startswith("degraded:")
    assert "2-retry budget" in res.error


def test_flapping_coordinator_terminates_at_attempts_ceiling():
    """Budget resets on every successful re-dial, so a coordinator that
    accepts dials but kills every call could loop forever — the overall
    attempts ceiling must convert that into a terminal degraded error."""
    pow_ = _wired_pow(retries=2)
    calls = {"n": 0}

    def always_fails(client, trace, nonce, ntz):
        calls["n"] += 1
        raise RPCTransportError("flap")

    pow_._issue_attempt = always_fails
    pow_._reconnect = lambda gen, attempt: True  # every re-dial "succeeds"
    tracer = Tracer("clientZ", MemorySink())
    pow_.mine(tracer, b"\x03", 2)
    res = pow_.notify_queue.get(timeout=20)
    assert res.error is not None and res.error.startswith("degraded:")
    assert calls["n"] == max(8, pow_.retries * 10)


def test_successful_reconnect_resets_budget():
    """Two separate one-failure outages must both be survivable on a
    budget of 1: each failed attempt consumes the budget, each
    successful reconnect restores it."""
    pow_ = _wired_pow(retries=1)
    tracer = Tracer("clientY", MemorySink())
    calls = {"n": 0}

    def scripted_attempt(client, trace, nonce, ntz):
        calls["n"] += 1
        if calls["n"] <= 2:  # outage 1 and outage 2
            raise RPCTransportError(f"outage {calls['n']}")
        return {
            "nonce": list(nonce),
            "num_trailing_zeros": ntz,
            "secret": [0x42],
            "token": encode_token(tracer.create_trace().generate_token()),
        }

    pow_._issue_attempt = scripted_attempt
    pow_._reconnect = lambda gen, attempt: True  # re-dial always succeeds
    pow_.mine(tracer, b"\x02", 2)
    res = pow_.notify_queue.get(timeout=10)
    assert res.error is None, res.error
    assert res.secret == b"\x42"
    assert calls["n"] == 3


def test_backoff_stays_within_configured_bounds():
    import random

    rng = random.Random(123)
    base, cap = 0.1, 1.5
    for attempt in range(10):
        upper = min(cap, base * 2 ** attempt)
        for _ in range(200):
            d = backoff_delay(attempt, base, cap, rng)
            assert 0 < d <= cap
            assert upper / 2 <= d <= upper


def test_app_level_error_is_not_retried():
    """An error RESPONSE from the coordinator (handler raised — e.g.
    'no live workers') must surface immediately, not burn the retry
    budget re-earning it."""
    s = Stack(1, failure_policy="reassign", failure_probe_secs=0.1)
    try:
        s.workers[0].shutdown()
        client = s.new_client("client1", MineRetries=50,
                              MineBackoffS=0.5, MineBackoffMaxS=5.0)
        t0 = time.time()
        client.mine(b"\x6b\x6c", 2)
        r = client.notify_queue.get(timeout=10.0)
        # retrying 50x at 0.5s+ backoff would blow the 10s window; an
        # immediate surface proves the app-error path skipped the budget
        assert r.secret is None and r.error is not None
        assert not r.error.startswith("degraded:")
        assert time.time() - t0 < 8.0
    finally:
        s.close()


# ---------------------------------------------------------------------------
# 4. flight recorder (runtime/telemetry.py): chaos evidence by construction
# ---------------------------------------------------------------------------

def test_flight_recorder_dump_on_fault(tmp_path):
    """Injected faults land in the flight-recorder ring, and a watchdog
    hang verdict dumps ring + metrics snapshot to disk BEFORE any exit
    path — the outage narrative exists as an artifact whether or not
    anyone was watching (ISSUE 3 tentpole part 3)."""
    import glob
    import json
    import threading

    from distpow_tpu.runtime.telemetry import RECORDER
    from distpow_tpu.runtime.watchdog import DeviceWatchdog

    RECORDER.reset()
    plan = faults.install_from_spec({
        "seed": 77,
        "rules": [{"kind": "drop", "method": "M.x", "max": 2}],
    })
    plan.on_frame("client", "M.x", "127.0.0.1:9")
    plan.on_frame("client", "M.x", "127.0.0.1:9")
    plan.on_frame("client", "M.x", "127.0.0.1:9")  # max=2: not injected
    injected = [e for e in RECORDER.recent() if e["kind"] == "fault.injected"]
    assert len(injected) == 2
    assert all(e["method"] == "M.x" and e["side"] == "client"
               for e in injected)
    # ring events carry ordering + wall-clock annotations
    assert injected[0]["seq"] < injected[1]["seq"]
    assert all("ts" in e for e in injected)

    saved_dir = RECORDER._dump_dir
    wd = DeviceWatchdog()
    hung = threading.Event()
    try:
        RECORDER.configure(dump_dir=str(tmp_path))
        wd.start(0.3, on_hang=lambda stale: hung.set())
        with wd.active():  # no beats: a "hung dispatch"
            assert hung.wait(10), "watchdog never fired"
        wd.stop()
        dumps = glob.glob(str(tmp_path / "flightrec-device-hang-*.json"))
        assert len(dumps) == 1, dumps
        payload = json.load(open(dumps[0]))
        assert payload["reason"] == "device-hang"
        kinds = [e["kind"] for e in payload["events"]]
        assert kinds.count("fault.injected") == 2
        assert "watchdog.hang" in kinds
        # the dump carries the full metrics state alongside the ring
        assert payload["metrics"]["counters"].get(
            "faults.injected.drop", 0) >= 2
        assert "histograms" in payload["metrics"]
        assert metrics.get("telemetry.dumps") >= 1
    finally:
        wd.stop()
        RECORDER._dump_dir = saved_dir
        RECORDER.reset()


def test_flight_recorder_journal_appends_jsonl(tmp_path):
    """The periodic journal is append-only JSONL with monotonically
    increasing seq — and flushes are incremental (no duplicates)."""
    import json

    from distpow_tpu.runtime.telemetry import FlightRecorder

    rec = FlightRecorder(capacity=16)
    journal = tmp_path / "node.telemetry.jsonl"
    rec.configure(journal_path=str(journal), journal_interval_s=30.0)
    try:
        rec.record("coord.fanout", round="r1", nonce="0102", ntz=2)
        rec.record("coord.first_result", round="r1", latency_s=0.1)
        rec.flush_journal()
        rec.record("coord.cancel_complete", round="r1", latency_s=0.2)
        rec.flush_journal()
        rec.flush_journal()  # idempotent: nothing new to write
        lines = [json.loads(l) for l in journal.read_text().splitlines()]
        assert [e["kind"] for e in lines] == [
            "coord.fanout", "coord.first_result", "coord.cancel_complete"]
        assert [e["seq"] for e in lines] == [1, 2, 3]
    finally:
        rec.stop()


def test_chaos_run_leaves_evidence_in_recorder():
    """End-to-end: a real chaos mine (worker-link truncate) leaves its
    fault injections AND the round's coord.* milestones in one ring —
    the correlated record a post-mortem needs."""
    from distpow_tpu.runtime.telemetry import RECORDER

    RECORDER.reset()
    faults.install_from_spec({
        "seed": 11,
        "rules": [{"kind": "truncate", "method": "WorkerRPCHandler.Mine",
                   "side": "client", "max": 1}],
    })
    s = Stack(2, failure_policy="reassign", failure_probe_secs=0.2)
    try:
        client = s.new_client("client1")
        res = mine_and_wait(client, b"\x7a\x01", 2)
        assert puzzle.check_secret(res.nonce, res.secret, 2)
    finally:
        s.close()
        faults.uninstall()
    kinds = [e["kind"] for e in RECORDER.recent()]
    assert "fault.injected" in kinds
    assert "coord.fanout" in kinds
    assert "coord.first_result" in kinds
    assert "coord.cancel_complete" in kinds
    RECORDER.reset()
