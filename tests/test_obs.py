"""Cluster observability plane (distpow_tpu/obs/, ISSUE 8): histogram
merging vs a combined-stream oracle, the shared-deadline fleet scraper
(including a real SIGSTOP'd worker process), and the SLO engine's
verdict edges, burn-rate windows, unknown-metric rejection, and breach
evidence."""

from __future__ import annotations

import json
import os
import random
import signal
import subprocess
import sys
import time

import pytest

sys.path.insert(0, os.path.dirname(__file__))

from distpow_tpu.obs import (  # noqa: E402
    FleetScraper,
    NodeTarget,
    SLOConfigError,
    SLOEngine,
    load_slo_config,
    merge_histograms,
    merge_snapshots,
)
from distpow_tpu.obs.merge import BUCKET_RATIO, delta_histogram  # noqa: E402
from distpow_tpu.runtime.metrics import Histogram, Metrics  # noqa: E402
from distpow_tpu.runtime.rpc import RPCServer  # noqa: E402
from distpow_tpu.runtime.telemetry import RECORDER  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def hist_dict(samples):
    h = Histogram()
    for v in samples:
        h.observe(v)
    return h.to_dict()


# -- bucket-wise merging vs the combined-stream oracle -----------------------

def test_merge_matches_combined_stream_exactly():
    """Bucketing is deterministic per value, so merging N node
    histograms bucket-wise must EQUAL the histogram one node observing
    the union stream would have built — not just approximate it."""
    rng = random.Random(905)
    a = [rng.lognormvariate(-3.0, 1.5) for _ in range(400)]
    b = [rng.lognormvariate(-1.0, 1.0) for _ in range(300)]
    c = [rng.uniform(0.0, 2.0) for _ in range(100)]  # includes zeros path
    merged = merge_histograms([hist_dict(a), hist_dict(b), hist_dict(c)])
    oracle = hist_dict(a + b + c)
    assert merged == oracle


def test_merge_percentile_within_one_bucket_of_true_value():
    """The merged estimate inherits the single-node error bound: each
    reported percentile sits within one log bucket (~19%) of the true
    sample percentile."""
    rng = random.Random(17)
    a = [rng.lognormvariate(-4.0, 1.0) for _ in range(500)]
    b = [rng.lognormvariate(-2.0, 0.5) for _ in range(500)]
    merged = merge_histograms([hist_dict(a), hist_dict(b)])
    both = sorted(a + b)
    for q, key in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
        true = both[min(len(both) - 1, int(q * len(both)))]
        est = merged[key]
        assert est / true <= BUCKET_RATIO + 1e-9, (key, est, true)
        assert true / est <= BUCKET_RATIO + 1e-9, (key, est, true)


def test_merge_single_snapshot_is_identity():
    h = hist_dict([0.01, 0.5, 2.0, 0.0])
    assert merge_histograms([h]) == h


def test_merge_handles_empty_and_none():
    h = hist_dict([1.0])
    assert merge_histograms([h, {}, None]) == h
    empty = merge_histograms([])
    assert empty["count"] == 0 and empty["p95"] is None


def test_delta_histogram_is_the_between_window():
    first = [0.01, 0.02, 0.4]
    later = [0.8, 0.9, 1.7, 3.2]
    old = hist_dict(first)
    new = hist_dict(first + later)
    delta = delta_histogram(new, old)
    assert delta["count"] == len(later)
    assert abs(delta["sum"] - sum(later)) < 1e-6
    # the window's percentile reflects only the later samples
    assert delta["p50"] >= 0.8 / BUCKET_RATIO


def test_delta_histogram_clamps_counter_resets():
    """A restarted node's snapshot shrinks; the delta must clamp at
    zero instead of poisoning the percentile walk with negatives."""
    old = hist_dict([0.1] * 10)
    new = hist_dict([0.2])  # fresh registry after restart
    delta = delta_histogram(new, old)
    assert delta["count"] == 0
    assert all(c >= 0 for _, c in delta["buckets"])


def test_merge_snapshots_sums_and_breaks_down():
    m1, m2 = Metrics(), Metrics()
    m1.inc("coord.mine_rpcs", 5)
    m2.inc("coord.mine_rpcs", 7)
    m1.observe("worker.solve_s.md5", 0.01)
    m2.observe("worker.solve_s.sha1", 0.5)
    s1, s2 = m1.snapshot(), m2.snapshot()
    s1["role"], s2["role"] = "coordinator", "worker"
    merged = merge_snapshots({"c": s1, "w": s2})
    assert merged["counters"]["coord.mine_rpcs"] == 12
    assert set(merged["per_model"]) == {"md5", "sha1"}
    assert merged["per_node"]["c"]["role"] == "coordinator"
    assert merged["per_node"]["w"]["counters"]["coord.mine_rpcs"] == 7
    assert merged["stale_nodes"] == []


# -- the fleet scraper -------------------------------------------------------

class _StatsNode:
    """A real RPCServer whose Stats serves a private Metrics registry —
    genuinely distinct per-node registries, unlike in-process nodes."""

    def __init__(self, role="worker", freeze=None):
        self.metrics = Metrics()
        self.role = role
        self.freeze = freeze  # threading.Event-like; when set, hang
        node = self

        class Handler:
            def Stats(self, params):
                if node.freeze is not None and node.freeze.is_set():
                    time.sleep(60)
                snap = node.metrics.snapshot()
                snap["role"] = node.role
                return snap

        self.server = RPCServer()
        service = ("CoordRPCHandler" if role == "coordinator"
                   else "WorkerRPCHandler")
        self.server.register(service, Handler())
        self.addr = self.server.listen("127.0.0.1:0")
        self.server.serve_in_background()

    def close(self):
        self.server.shutdown()


@pytest.fixture
def three_nodes():
    import threading

    freeze = threading.Event()
    coord = _StatsNode("coordinator")
    w1 = _StatsNode("worker")
    w2 = _StatsNode("worker", freeze=freeze)
    yield coord, w1, w2, freeze
    for n in (coord, w1, w2):
        n.close()


def test_scraper_merges_distinct_registries(three_nodes):
    coord, w1, w2, _ = three_nodes
    coord.metrics.inc("coord.mine_rpcs", 3)
    w1.metrics.observe("worker.solve_s.md5", 0.1)
    w2.metrics.observe("worker.solve_s.md5", 0.4)
    scraper = FleetScraper([
        NodeTarget(coord.addr, "coord", "coordinator"),
        NodeTarget(w1.addr, "w1", "worker"),
        NodeTarget(w2.addr, "w2", "worker"),
    ], deadline_s=5.0)
    try:
        snap = scraper.sweep()
    finally:
        scraper.close()
    assert snap["stale_nodes"] == []
    assert snap["counters"]["coord.mine_rpcs"] == 3
    md5 = snap["per_model"]["md5"]["solve_s"]
    assert md5["count"] == 2  # one sample from each worker registry
    oracle = merge_histograms([hist_dict([0.1]), hist_dict([0.4])])
    assert snap["histograms"]["worker.solve_s.md5"] == oracle


def test_scraper_marks_frozen_node_stale_within_deadline(three_nodes):
    """The SIGSTOP-shaped contract at the RPC level: a node whose Stats
    never answers costs the sweep its shared deadline, not a hang — it
    is reported stale with its last-seen age and its LAST snapshot
    keeps contributing, flagged."""
    coord, w1, w2, freeze = three_nodes
    w2.metrics.inc("worker.mine_rpcs", 9)
    scraper = FleetScraper([
        NodeTarget(coord.addr, "coord", "coordinator"),
        NodeTarget(w1.addr, "w1", "worker"),
        NodeTarget(w2.addr, "w2", "worker"),
    ], deadline_s=5.0)
    try:
        first = scraper.sweep()
        assert first["stale_nodes"] == []
        freeze.set()
        t0 = time.monotonic()
        snap = scraper.sweep(deadline_s=1.0)
        wall = time.monotonic() - t0
        assert wall < 3.0, f"sweep did not respect its deadline: {wall}"
        assert snap["stale_nodes"] == ["w2"]
        meta = snap["per_node"]["w2"]
        assert meta["status"] == "stale"
        assert meta["age_s"] is not None and meta["age_s"] >= 0.9
        # last-seen data still contributes, flagged
        assert snap["counters"]["worker.mine_rpcs"] == 9
        # and the others answered normally
        assert snap["per_node"]["coord"]["status"] == "ok"
        # recovery: unfreeze -> next sweep is clean again
        freeze.clear()
        # the abandoned poll thread still owns w2's poll lock for up to
        # 60s of its frozen call; a RECOVERING scrape may need a fresh
        # connection — give it a couple of sweeps
        deadline = time.time() + 10
        while time.time() < deadline:
            snap = scraper.sweep(deadline_s=1.0)
            if not snap["stale_nodes"]:
                break
            time.sleep(0.2)
    finally:
        scraper.close()


def test_scraper_never_seen_node_is_stale_with_null_age():
    scraper = FleetScraper([
        NodeTarget("127.0.0.1:1", "ghost", "worker"),  # nothing listens
    ], deadline_s=1.0)
    try:
        snap = scraper.sweep()
    finally:
        scraper.close()
    assert snap["stale_nodes"] == ["ghost"]
    assert snap["per_node"]["ghost"]["age_s"] is None
    assert snap["per_node"]["ghost"]["error"]


def test_scraper_rejects_duplicate_names_and_empty():
    with pytest.raises(ValueError):
        FleetScraper([])
    with pytest.raises(ValueError):
        FleetScraper([NodeTarget("a:1", "x"), NodeTarget("b:2", "x")])


@pytest.mark.slow
def test_scraper_survives_sigstopped_worker_process():
    """ISSUE 8 acceptance: a worker PROCESS frozen with SIGSTOP (TCP
    accepted by the kernel, nothing answers) must not stall the sweep —
    it completes within its shared deadline, the node reports stale,
    and the SLO verdict still renders."""
    coord = _StatsNode("coordinator")
    coord.metrics.observe("coord.mine_s.miss", 0.05)
    child = subprocess.Popen(
        [sys.executable,
         os.path.join(REPO, "tests", "stopped_worker_child.py"),
         coord.addr],
        stdout=subprocess.PIPE, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    try:
        line = child.stdout.readline()
        assert line.startswith("WORKER_READY"), line
        worker_addr = line.split()[1]
        scraper = FleetScraper([
            NodeTarget(coord.addr, "coord", "coordinator"),
            NodeTarget(worker_addr, "stopworker", "worker"),
        ], deadline_s=5.0)
        try:
            first = scraper.sweep()
            assert first["stale_nodes"] == []
            os.kill(child.pid, signal.SIGSTOP)
            time.sleep(0.2)
            t0 = time.monotonic()
            snap = scraper.sweep(deadline_s=1.5)
            wall = time.monotonic() - t0
            assert wall < 4.0, f"sweep stalled on the frozen worker: {wall}"
            assert snap["stale_nodes"] == ["stopworker"]
            # the SLO verdict still renders over the degraded view
            engine = SLOEngine(load_slo_config(
                os.path.join(REPO, "config", "slo.json")))
            verdict = engine.evaluate(snap, breach_hooks=False)
            assert verdict.status in ("pass", "warn")
            assert verdict.stale_nodes == ["stopworker"]
            assert "stopworker" in verdict.render()
        finally:
            scraper.close()
    finally:
        try:
            os.kill(child.pid, signal.SIGCONT)
        except ProcessLookupError:
            pass
        child.kill()
        child.wait(timeout=10)
        coord.close()


# -- SLO config validation ---------------------------------------------------

def _cfg(objectives, **windows):
    cfg = {"objectives": objectives}
    if windows:
        cfg["windows"] = windows
    return load_slo_config(cfg)


def test_slo_config_unknown_histogram_rejected():
    with pytest.raises(SLOConfigError, match="unknown histogram"):
        _cfg([{"name": "x", "histogram": "coord.mine_s.typo", "max": 1}])


def test_slo_config_unknown_counter_rejected():
    with pytest.raises(SLOConfigError, match="unknown counter"):
        _cfg([{"name": "x", "max": 1,
               "ratio": {"num": "rpc.handler_errorz",
                         "den": "coord.mine_rpcs"}}])


def test_slo_config_prefix_families_accepted():
    cfg = _cfg([
        {"name": "m", "histogram": "worker.solve_s.sha1", "max": 1},
        {"name": "r", "histogram": "rpc.server.dispatch_s.C.Mine",
         "max": 1},
    ])
    assert len(cfg.objectives) == 2


def test_slo_config_shape_errors():
    with pytest.raises(SLOConfigError, match="duplicate"):
        _cfg([{"name": "x", "histogram": "powlib.mine_s", "max": 1},
              {"name": "x", "histogram": "powlib.mine_s", "max": 2}])
    with pytest.raises(SLOConfigError, match="exactly one"):
        _cfg([{"name": "x", "max": 1}])
    with pytest.raises(SLOConfigError, match="unknown stat"):
        _cfg([{"name": "x", "histogram": "powlib.mine_s", "stat": "p42",
               "max": 1}])
    with pytest.raises(SLOConfigError, match="must be positive"):
        _cfg([{"name": "x", "histogram": "powlib.mine_s", "max": 0}])
    with pytest.raises(SLOConfigError, match="per_model"):
        _cfg([{"name": "x", "histogram": "powlib.mine_s", "max": 1,
               "per_model": True}])
    with pytest.raises(SLOConfigError, match="fast_s"):
        _cfg([{"name": "x", "histogram": "powlib.mine_s", "max": 1}],
             fast_s=100.0, slow_s=10.0)
    with pytest.raises(SLOConfigError, match="non-empty"):
        load_slo_config({"objectives": []})


def test_checked_in_slo_config_loads():
    cfg = load_slo_config(os.path.join(REPO, "config", "slo.json"))
    names = [o.name for o in cfg.objectives]
    assert "mine_e2e_p95_s" in names and "rpc_error_rate" in names


# -- SLO verdict edges and burn-rate windows ---------------------------------

def _merged(ts, miss_samples=(), errors=0, mines=0):
    return {
        "ts": ts,
        "counters": {"rpc.handler_errors": errors,
                     "coord.mine_rpcs": mines},
        "histograms": {"coord.mine_s.miss": hist_dict(list(miss_samples))},
        "stale_nodes": [],
    }


LAT_CFG = {"windows": {"fast_s": 60, "slow_s": 300},
           "objectives": [{"name": "p95", "histogram": "coord.mine_s.miss",
                           "stat": "p95", "max": 1.0}]}
ERR_CFG = {"windows": {"fast_s": 60, "slow_s": 300},
           "objectives": [{"name": "err", "max": 0.1,
                           "ratio": {"num": "rpc.handler_errors",
                                     "den": "coord.mine_rpcs"}}]}


def test_verdict_pass_and_exit_zero():
    engine = SLOEngine(load_slo_config(LAT_CFG))
    v = engine.evaluate(_merged(1000.0, [0.1, 0.2]), breach_hooks=False)
    assert v.status == "pass" and v.exit_code() == 0


def test_verdict_cumulative_breach_on_single_snapshot():
    """One-shot CI evaluation: both windows degrade to cumulative, so a
    single over-threshold snapshot is a sustained breach."""
    engine = SLOEngine(load_slo_config(LAT_CFG))
    v = engine.evaluate(_merged(1000.0, [5.0] * 20), breach_hooks=False)
    assert v.status == "breach" and v.exit_code() == 1


def test_verdict_fast_spike_is_warn_not_breach():
    """Burn-rate windows: a spike inside the fast window with a healthy
    slow window warns — paging on every blip is how pages get ignored."""
    engine = SLOEngine(load_slo_config(ERR_CFG))
    t0 = 10_000.0
    # deep history: 400s of healthy traffic (slow window looks good)
    engine.observe(_merged(t0 - 400, errors=0, mines=1000), ts=t0 - 400)
    engine.observe(_merged(t0 - 90, errors=5, mines=5000), ts=t0 - 90)
    # the last 60s: 30% errors — fast window over budget
    v = engine.evaluate(_merged(t0, errors=5 + 150, mines=5500), ts=t0,
                        breach_hooks=False)
    assert v.objectives[0].status == "warn"
    assert "spike" in v.objectives[0].detail
    assert v.exit_code() == 0


def test_verdict_sustained_burn_is_breach():
    engine = SLOEngine(load_slo_config(ERR_CFG))
    t0 = 10_000.0
    engine.observe(_merged(t0 - 400, errors=0, mines=1000), ts=t0 - 400)
    engine.observe(_merged(t0 - 90, errors=800, mines=3000), ts=t0 - 90)
    v = engine.evaluate(_merged(t0, errors=1400, mines=5000), ts=t0,
                        breach_hooks=False)
    assert v.objectives[0].status == "breach"
    assert v.exit_code() == 1
    assert v.objectives[0].burn is not None and v.objectives[0].burn > 1


def test_verdict_recovering_slow_window_is_warn():
    """Errors stopped recently: slow window still over, fast clean."""
    engine = SLOEngine(load_slo_config(ERR_CFG))
    t0 = 10_000.0
    engine.observe(_merged(t0 - 400, errors=0, mines=1000), ts=t0 - 400)
    engine.observe(_merged(t0 - 90, errors=900, mines=3000), ts=t0 - 90)
    v = engine.evaluate(_merged(t0, errors=900, mines=5000), ts=t0,
                        breach_hooks=False)
    assert v.objectives[0].status == "warn"
    assert "recovering" in v.objectives[0].detail


def test_verdict_no_data_passes():
    engine = SLOEngine(load_slo_config(ERR_CFG))
    v = engine.evaluate(_merged(1000.0, mines=0), breach_hooks=False)
    assert v.objectives[0].status == "no_data"
    assert v.exit_code() == 0


def test_verdict_per_model_thresholds():
    cfg = load_slo_config({"objectives": [
        {"name": "serving", "histogram": "worker.solve_s", "stat": "p95",
         "max": 1.0, "per_model": True, "models": {"sha3_256": 30.0}}]})
    engine = SLOEngine(cfg)
    merged = {
        "ts": 1.0,
        "counters": {},
        "histograms": {
            "worker.solve_s.md5": hist_dict([5.0] * 10),     # over default
            "worker.solve_s.sha3_256": hist_dict([5.0] * 10),  # under its own
        },
        "stale_nodes": [],
    }
    v = engine.evaluate(merged, breach_hooks=False)
    by_model = {o.model: o for o in v.objectives}
    assert by_model["md5"].status == "breach"
    assert by_model["md5"].threshold == 1.0
    assert by_model["sha3_256"].status == "pass"
    assert by_model["sha3_256"].threshold == 30.0


def test_breach_records_event_and_dumps(tmp_path):
    RECORDER.reset()
    RECORDER.configure(dump_dir=str(tmp_path))
    engine = SLOEngine(load_slo_config(LAT_CFG))
    v = engine.evaluate(_merged(1000.0, [5.0] * 20))
    assert v.status == "breach"
    events = [e for e in RECORDER.recent() if e["kind"] == "slo.breach"]
    assert len(events) == 1
    assert events[0]["objective"] == "p95"
    assert events[0]["threshold"] == 1.0
    assert v.dump_path and os.path.exists(v.dump_path)
    payload = json.loads(open(v.dump_path).read())
    assert payload["extra"]["verdict"]["status"] == "breach"


def test_breach_dump_carries_trace_profile_critical_path(tmp_path):
    """With a telemetry journal available, the breach dump includes the
    trace_profile per-round critical-path breakdown (slowest first)."""
    journal = tmp_path / "coordinator.telemetry.jsonl"
    events = [
        {"seq": 1, "ts": 100.0, "kind": "coord.fanout", "round": "r1",
         "nonce": "aa", "ntz": 2},
        {"seq": 2, "ts": 100.1, "kind": "coord.first_result", "round": "r1",
         "nonce": "aa", "ntz": 2, "worker_byte": 0, "latency_s": 0.1},
        {"seq": 3, "ts": 100.4, "kind": "coord.cancel_complete",
         "round": "r1", "nonce": "aa", "ntz": 2, "late_results": 0,
         "latency_s": 0.4},
        {"seq": 4, "ts": 101.0, "kind": "coord.fanout", "round": "r2",
         "nonce": "bb", "ntz": 2},
        {"seq": 5, "ts": 103.0, "kind": "coord.cancel_complete",
         "round": "r2", "nonce": "bb", "ntz": 2, "late_results": 1,
         "latency_s": 2.0},
    ]
    journal.write_text("".join(json.dumps(e) + "\n" for e in events))
    RECORDER.reset()
    RECORDER.configure(dump_dir=str(tmp_path))
    engine = SLOEngine(load_slo_config(LAT_CFG),
                       journal_path=str(journal))
    v = engine.evaluate(_merged(1000.0, [5.0] * 20))
    assert v.status == "breach" and v.dump_path
    payload = json.loads(open(v.dump_path).read())
    cp = payload["extra"]["critical_path"]
    assert [r["round"] for r in cp] == ["r2", "r1"]  # slowest first
    assert cp[0]["cancel_propagation_s"] == 2.0


def test_verdict_render_and_dict_roundtrip():
    engine = SLOEngine(load_slo_config(LAT_CFG))
    v = engine.evaluate(_merged(1000.0, [0.1]), breach_hooks=False)
    text = v.render()
    assert "SLO verdict: PASS" in text and "p95" in text
    d = v.to_dict()
    assert d["status"] == "pass" and d["objectives"][0]["name"] == "p95"
    json.dumps(d)  # JSON-able end to end


# -- cluster Prometheus exposition -------------------------------------------

def test_cluster_prometheus_rendering_is_valid():
    from distpow_tpu.cli.stats import render_cluster_prometheus
    from test_metrics import assert_valid_prometheus

    m1, m2 = Metrics(), Metrics()
    m1.inc("coord.mine_rpcs", 2)
    m1.observe("coord.mine_s.miss", 0.2)
    m2.observe("worker.solve_s.md5", 0.01)
    s1, s2 = m1.snapshot(), m2.snapshot()
    s1["role"], s2["role"] = "coordinator", "worker"
    cluster = merge_snapshots(
        {"c": s1, "w": s2},
        {"c": {"status": "ok", "age_s": 0.0},
         "w": {"status": "stale", "age_s": 12.5}},
    )
    text = render_cluster_prometheus(cluster)
    assert_valid_prometheus(text)
    assert 'distpow_node_info{role="cluster"} 1' in text
    assert 'distpow_node_stale{node="w"} 1' in text
    assert 'distpow_node_stale{node="c"} 0' in text
    assert 'distpow_node_age_seconds{node="w"} 12.5' in text


def test_auto_role_discovery_is_error_free_on_current_nodes():
    """The Node.Stats alias (found live by the verify drive of this
    PR): auto-role discovery against current nodes must NOT mint
    rpc.handler_errors on the observed node — with a light-traffic
    denominator those probe errors breached the green error-rate SLO
    on a perfectly healthy cluster."""
    from distpow_tpu.nodes import Coordinator, Worker
    from distpow_tpu.runtime.config import CoordinatorConfig, WorkerConfig
    from distpow_tpu.runtime.metrics import REGISTRY

    coordinator = Coordinator(CoordinatorConfig(
        ClientAPIListenAddr="127.0.0.1:0",
        WorkerAPIListenAddr="127.0.0.1:0",
        Workers=["pending:0"],
    ))
    client_addr, worker_api = coordinator.initialize_rpcs()
    worker = Worker(WorkerConfig(
        WorkerID="aliasw", ListenAddr="127.0.0.1:0", CoordAddr=worker_api,
        Backend="python", WarmupNonceLens=[], WarmupWidths=[],
    ))
    worker_addr = worker.initialize_rpcs()
    scraper = FleetScraper([
        NodeTarget(client_addr, "coord"),   # role defaults to auto
        NodeTarget(worker_addr, "worker"),
    ], deadline_s=5.0)
    try:
        errs0 = REGISTRY.get("rpc.handler_errors")
        snap = scraper.sweep()
        assert snap["stale_nodes"] == []
        assert snap["per_node"]["coord"]["role"] == "coordinator"
        assert snap["per_node"]["worker"]["role"] == "worker"
        assert REGISTRY.get("rpc.handler_errors") == errs0
    finally:
        scraper.close()
        worker.shutdown()
        coordinator.shutdown()
