"""Device-hang watchdog tests (runtime/watchdog.py).

The watchdog exists for a failure mode the Go reference cannot have: an
accelerator dispatch that never returns leaves a worker answering
liveness probes while its Mine task never completes (BASELINE.md
round-3 provenance documents the real outages that motivated it).
These tests cover the monitor itself, the search-driver
instrumentation, and the WorkerConfig plumbing.
"""

import threading
import time

import pytest

from distpow_tpu.runtime.watchdog import EXIT_CODE, WATCHDOG, DeviceWatchdog


@pytest.fixture
def dog():
    d = DeviceWatchdog()
    yield d
    d.stop()


def test_fires_on_stale_active_section(dog):
    fired = []
    dog.start(0.2, on_hang=fired.append)
    with dog.active():
        assert dog.fired.wait(2.0), "no fire despite stale active section"
    assert fired and fired[0] >= 0.2


def test_beats_keep_active_section_alive(dog):
    dog.start(0.3, on_hang=lambda s: None)
    with dog.active():
        for _ in range(6):
            time.sleep(0.1)
            dog.beat()
    assert not dog.fired.is_set()


def test_idle_never_fires(dog):
    # active == 0: nothing drives the device, staleness is meaningless
    dog.start(0.2, on_hang=lambda s: None)
    time.sleep(0.7)
    assert not dog.fired.is_set()


def test_noop_when_not_started(dog):
    dog.beat()
    with dog.active():
        pass
    assert not dog.running


def test_rejects_bad_timeout(dog):
    with pytest.raises(ValueError):
        dog.start(0)


def test_grace_widens_window_for_one_operation(dog):
    # a single long op (a first XLA compile cannot beat) inside grace()
    # must not fire even though it exceeds the base timeout...
    dog.start(0.2, on_hang=lambda s: None)
    with dog.active():
        with dog.grace(5.0):
            time.sleep(0.6)  # 3x the base timeout, under the grace
        assert not dog.fired.is_set()
        # ...and leaving the block restores the normal window
        assert dog.fired.wait(2.0), "base timeout not restored after grace"


def test_grace_still_fires_when_exceeded(dog):
    fired = []
    dog.start(0.1, on_hang=fired.append)
    with dog.active():
        with dog.grace(0.3):
            assert dog.fired.wait(2.0), "hang under grace never detected"
    assert fired and fired[0] >= 0.3


def test_nested_grace_widest_wins(dog):
    dog.start(0.1, on_hang=lambda s: None)
    with dog.active():
        with dog.grace(5.0):
            with dog.grace(0.2):
                # inner narrower grace must not shrink the outer window
                time.sleep(0.5)
            assert not dog.fired.is_set()


def test_inner_grace_does_not_leak_into_outer_block(dog):
    # review r4: a depth-counter implementation kept the inner 900s
    # window active for the rest of the outer block, delaying genuine
    # hang detection 15x
    dog.start(0.1, on_hang=lambda s: None)
    with dog.active():
        with dog.grace(0.3):
            with dog.grace(30.0):
                pass  # wide inner grace exits immediately
            # hang here must be caught by the outer 0.3s grace, not 30s
            assert dog.fired.wait(2.0), "inner grace leaked into outer block"


def test_search_driver_hang_detected():
    """A device fetch that never returns must trip the watchdog through
    parallel.search's own instrumentation (the beat in drain_one)."""
    from distpow_tpu.ops.search_step import SENTINEL
    from distpow_tpu.parallel.search import search

    unblock = threading.Event()

    def factory(vw, extra, target_chunks, launch_steps=1):
        def step(chunk0):
            class HungResult:
                def __int__(self):  # a device_get that never completes
                    unblock.wait()
                    return SENTINEL  # a miss, so the released thread
                    # drains cleanly instead of fabricating a hit

            return HungResult()

        return step, max(1, target_chunks)

    WATCHDOG.start(0.3, on_hang=lambda s: None)
    try:
        t = threading.Thread(
            target=lambda: search(
                b"\x01", 2, list(range(256)), step_factory=factory,
                pipeline_depth=1, batch_size=1 << 10,
                cancel_check=unblock.is_set,
            ),
            daemon=True,
        )
        t.start()
        assert WATCHDOG.fired.wait(3.0), \
            "watchdog did not detect the hung drain"
    finally:
        unblock.set()  # release the blocked thread before stopping
        t.join(timeout=5.0)
        WATCHDOG.stop()


def test_slow_first_launch_compile_not_killed(monkeypatch):
    """A cold layout's FIRST launch pays the XLA compile — one gap that
    can far exceed the hang timeout (sha512 unrolled: >22 min on the
    tunnel).  The driver wraps that launch in a grace window, so an
    armed watchdog must ride out a slow first compile and still serve
    the result."""
    import importlib

    search_mod = importlib.import_module("distpow_tpu.parallel.search")
    search = search_mod.search

    # shrink the grace so the test can also prove it expires (below)
    monkeypatch.setattr(search_mod, "FIRST_COMPILE_GRACE_S", 5.0)

    calls = {"n": 0}

    def factory(vw, extra, target_chunks, launch_steps=1):
        from distpow_tpu.ops.search_step import SENTINEL

        def step(chunk0):
            calls["n"] += 1
            if calls["n"] == 1:
                time.sleep(0.8)  # "compile": 4x the base timeout

            class Result:
                def __int__(self):
                    return 0 if chunk0 == 0 else SENTINEL

            return Result()

        return step, max(1, target_chunks)

    WATCHDOG.start(0.2, on_hang=lambda s: None)
    try:
        res = search(b"\x01", 0, list(range(256)), step_factory=factory,
                     pipeline_depth=1, batch_size=1 << 10)
        assert res is not None  # difficulty 0: first candidate wins
        assert not WATCHDOG.fired.is_set(), \
            "watchdog killed a healthy slow first compile"
    finally:
        WATCHDOG.stop()


def test_acquire_release_refcount(dog):
    dog.acquire(5.0)
    dog.acquire(9.0)  # shared; first timeout wins
    assert dog.running and dog._timeout == 5.0
    dog.release()
    assert dog.running, "watchdog stopped while a co-owner remains"
    dog.release()
    assert not dog.running


def test_stop_with_hung_section_does_not_blind_rearm(dog):
    """A section still stuck inside active() across a stop/start cycle
    must not skew the counter and disable a re-armed watchdog."""
    entered, unblock = threading.Event(), threading.Event()

    def hung_section():
        with dog.active():
            entered.set()
            unblock.wait()

    dog.start(5.0, on_hang=lambda s: None)
    t = threading.Thread(target=hung_section, daemon=True)
    t.start()
    assert entered.wait(2.0)
    dog.stop()          # section still inside active()
    unblock.set()       # now it unwinds (paired decrement)
    t.join(timeout=5.0)
    dog.start(0.2, on_hang=lambda s: None)
    with dog.active():
        assert dog.fired.wait(2.0), "re-armed watchdog is blind"


def test_worker_config_arms_watchdog():
    """DeviceHangTimeoutS > 0 on WorkerConfig starts the process
    watchdog at worker boot, and the owning worker's shutdown stops it;
    0 (the default) leaves it off."""
    from tests.test_nodes import Stack

    assert not WATCHDOG.running
    stack = Stack(2, worker_extra={"DeviceHangTimeoutS": 300.0})
    try:
        assert WATCHDOG.running
        assert WATCHDOG._timeout == 300.0
        assert stack.workers[0].handler.Stats({})["watchdog_armed"] is True
        # one armed worker down, the other keeps its protection
        stack.workers[0].shutdown()
        assert WATCHDOG.running
    finally:
        stack.close()
    assert not WATCHDOG.running, "last armed worker's shutdown must disarm"
    # default config: off (reference parity)
    stack = Stack(1)
    try:
        assert not WATCHDOG.running
    finally:
        stack.close()


@pytest.mark.slow
def test_hung_worker_process_dies_and_request_completes(tmp_path):
    """The full recovery chain at the process level: a worker whose
    backend wedges (tests/hang_worker_child.py — the stand-in for a TPU
    dispatch that never returns) still answers Ping, so ONLY the
    watchdog can unblock the protocol: it kills the worker with
    EXIT_CODE, the coordinator's FailurePolicy="reassign" prunes it,
    and the healthy worker completes every client request."""
    from tests.proc_harness import ProcStack

    stack = ProcStack(
        tmp_path, workers=2, seed=777,
        coord_overrides={"FailurePolicy": "reassign",
                         "FailureProbeSecs": 0.5},
    )
    try:
        stack.boot_core()
        hang_child = stack.spawn(
            "tests/hang_worker_child.py", stack.coord_cfg["Workers"][0],
            stack.coord_cfg["WorkerAPIListenAddr"],
        )
        stack.boot_worker(1)  # blocks on its "serving ... RPCs" line
        stack.wait_for_line(hang_child, "HANG_WORKER_READY")

        client = stack.spawn(
            "-m", "distpow_tpu.cli.client",
            "--config", stack.config("client_config.json"),
            "--config2", stack.config("client2_config.json"),
            "--difficulty", "2",
        )
        out, _ = client.communicate(timeout=120)
        assert client.returncode == 0, out
        assert out.count("MineResult") == 4, out

        # the zombie died by watchdog (exit 43), not by our teardown
        rc = hang_child.wait(timeout=30)
        assert rc == EXIT_CODE, (rc, hang_child.stdout.read())
    finally:
        stack.close()


def test_section_in_flight_before_arming_is_covered(dog):
    """A device section entered while the watchdog is STOPPED must still
    count once a later start()/acquire() arms the monitor (advisor r3:
    the old early-return in active() left such sections permanently
    invisible — e.g. a search already dispatching when a worker boots
    and arms, or when bench/sweep call start())."""
    entered = threading.Event()
    release = threading.Event()

    def hung_section():
        with dog.active():          # watchdog not running yet
            entered.set()
            release.wait(5.0)       # simulates a dispatch that never beats

    t = threading.Thread(target=hung_section, daemon=True)
    t.start()
    assert entered.wait(2.0)
    assert dog._active == 1         # counted even while stopped
    dog.start(0.2, on_hang=lambda s: None)
    assert dog.fired.wait(2.0), \
        "pre-armed in-flight section never detected as hung"
    release.set()
    t.join(2.0)
