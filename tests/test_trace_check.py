"""Trace-log validator tests: a real multi-node scenario must produce
logs with zero ordering violations, and corrupted logs must be caught.

This is the executable form of the reference's de-facto acceptance test
(SURVEY.md section 4: trace parity / ordering invariants graded via the
tracing server output).
"""

import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent))

from test_nodes import Stack, mine_and_wait  # noqa: E402

from distpow_tpu.runtime.config import TracingServerConfig  # noqa: E402
from distpow_tpu.runtime.trace_check import (  # noqa: E402
    check_shiviz_log,
    check_trace_log,
    parse_trace_log,
)
from distpow_tpu.runtime.trace_server import TracingServer  # noqa: E402
from distpow_tpu.runtime.tracing import TCPSink  # noqa: E402


def run_demo_scenario(tmp_path, n_workers=2):
    """The reference demo (cmd/client/main.go:40-51) against a real
    tracing server: two clients, four requests including the repeat
    nonce at higher difficulty."""
    out = tmp_path / "trace_output.log"
    shiviz = tmp_path / "shiviz_output.log"
    server = TracingServer(TracingServerConfig(
        ServerBind="127.0.0.1:0",
        Secret=b"",
        OutputFile=str(out),
        ShivizOutputFile=str(shiviz),
    ))
    addr = server.open()
    server.accept_in_background()

    stack = Stack(n_workers, sink_factory=lambda name: TCPSink(addr, b""))
    try:
        c1 = stack.new_client("client1")
        c2 = stack.new_client("client2")
        mine_and_wait(c1, b"\x01\x02\x03\x04", 3)
        mine_and_wait(c1, b"\x05\x06\x07\x08", 2)
        mine_and_wait(c2, b"\x02\x02\x02\x02", 2)
        mine_and_wait(c2, b"\x02\x02\x02\x02", 3)  # dominance supersede
    finally:
        stack.close()
        # drain deterministically: wait until the log stops growing (a
        # fixed sleep flakes on a loaded machine)
        deadline = time.time() + 10
        last = -1
        while time.time() < deadline:
            size = out.stat().st_size if out.exists() else 0
            if size == last:
                break
            last = size
            time.sleep(0.3)
        server.close()
    return out, shiviz


def test_demo_scenario_trace_has_no_violations(tmp_path):
    out, shiviz = run_demo_scenario(tmp_path)
    events = parse_trace_log(str(out))
    assert len(events) > 20, "expected a substantial trace"
    assert check_trace_log(str(out)) == []
    assert check_shiviz_log(str(shiviz)) == []


def test_checker_flags_missing_cancel(tmp_path):
    log = tmp_path / "bad.log"
    log.write_text(
        "[worker1] TraceID=7 WorkerMine Nonce=[1], NumTrailingZeros=2, WorkerByte=0\n"
        "[worker1] TraceID=7 WorkerResult Nonce=[1], NumTrailingZeros=2, "
        "WorkerByte=0, Secret=[170]\n"
    )
    violations = check_trace_log(str(log))
    assert any("WorkerResult without a following WorkerCancel" in v
               for v in violations)


def test_checker_flags_cancel_before_result(tmp_path):
    log = tmp_path / "bad.log"
    log.write_text(
        "[worker1] TraceID=7 WorkerMine Nonce=[1], NumTrailingZeros=2, WorkerByte=0\n"
        "[worker1] TraceID=7 WorkerCancel Nonce=[1], NumTrailingZeros=2, WorkerByte=0\n"
        "[worker1] TraceID=7 WorkerResult Nonce=[1], NumTrailingZeros=2, "
        "WorkerByte=0, Secret=[170]\n"
    )
    violations = check_trace_log(str(log))
    assert any("WorkerCancel before WorkerResult" in v for v in violations)
    assert any("not the final worker action" in v for v in violations)


def test_checker_flags_fanout_after_hit(tmp_path):
    log = tmp_path / "bad.log"
    log.write_text(
        "[coordinator] TraceID=9 CoordinatorMine Nonce=[1], NumTrailingZeros=2\n"
        "[coordinator] TraceID=9 CacheHit Nonce=[1], NumTrailingZeros=2, Secret=[170]\n"
        "[coordinator] TraceID=9 CoordinatorWorkerMine Nonce=[1], "
        "NumTrailingZeros=2, WorkerByte=0\n"
        "[coordinator] TraceID=9 CoordinatorSuccess Nonce=[1], "
        "NumTrailingZeros=2, Secret=[170]\n"
    )
    violations = check_trace_log(str(log))
    assert any("fan-out after CacheHit" in v for v in violations)


def test_checker_flags_unpaired_cache_remove(tmp_path):
    log = tmp_path / "bad.log"
    log.write_text(
        "[coordinator] TraceID=5 CoordinatorMine Nonce=[1], NumTrailingZeros=2\n"
        "[coordinator] TraceID=5 CacheRemove Nonce=[1], NumTrailingZeros=1, Secret=[9]\n"
        "[coordinator] TraceID=5 CoordinatorSuccess Nonce=[1], "
        "NumTrailingZeros=2, Secret=[170]\n"
    )
    violations = check_trace_log(str(log))
    assert any("CacheRemove" in v and "CacheAdd" in v for v in violations)


def test_checker_flags_bad_vector_clock(tmp_path):
    log = tmp_path / "bad_shiviz.log"
    log.write_text(
        "(?<host>\\S*) (?<clock>{.*})\\n(?<event>.*)\n"
        "\n"
        'client1 {"client1":1}\n'
        "PowlibMiningBegin {}\n"
        'client1 {"client1":3}\n'
        "PowlibMine {}\n"
    )
    violations = check_shiviz_log(str(log))
    assert any("jumped 1 -> 3" in v for v in violations)


def test_cli_trace_check(tmp_path, capsys):
    from distpow_tpu.cli.trace_check import main

    out, shiviz = run_demo_scenario(tmp_path, n_workers=1)
    assert main([str(out), str(shiviz)]) == 0
    bad = tmp_path / "bad.log"
    bad.write_text(
        "[worker1] TraceID=7 WorkerMine Nonce=[1], NumTrailingZeros=2, WorkerByte=0\n"
        "[worker1] TraceID=7 WorkerResult Nonce=[1], NumTrailingZeros=2, "
        "WorkerByte=0, Secret=[170]\n"
    )
    assert main([str(bad)]) == 1
