"""Worker child whose backend hangs forever — the zombie the watchdog
exists to kill.

Run by tests/test_watchdog.py::test_hung_worker_process_dies_and_request_completes:
this process boots a real Worker (RPC server, forwarder, tracer) whose
``search`` blocks inside a never-beating ``WATCHDOG.active()`` section —
the process-level stand-in for a TPU dispatch that never returns
(BASELINE.md round-3 provenance).  With ``DeviceHangTimeoutS`` set, the
watchdog must end this process with ``os._exit(43)``; the parent test
asserts the exit code and that the coordinator's
``FailurePolicy="reassign"`` then completes the client's request via
the healthy worker.

Usage: python tests/hang_worker_child.py <listen_addr> <coord_addr>
"""

import os
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import distpow_tpu.nodes.worker as worker_mod  # noqa: E402
from distpow_tpu.nodes.worker import Worker  # noqa: E402
from distpow_tpu.runtime.config import WorkerConfig  # noqa: E402
from distpow_tpu.runtime.watchdog import WATCHDOG  # noqa: E402


class HangBackend:
    """A dispatch that never returns and never beats."""

    def __init__(self, **_):
        pass

    def search(self, nonce, difficulty, thread_bytes, cancel_check=None):
        with WATCHDOG.active():
            threading.Event().wait()


# swap the backend factory BEFORE Worker construction (the module-level
# symbol nodes.worker resolved at import time)
worker_mod.get_backend = lambda name, **kw: HangBackend()

listen_addr, coord_addr = sys.argv[1], sys.argv[2]
w = Worker(
    WorkerConfig(
        WorkerID="hangworker",
        ListenAddr=listen_addr,
        CoordAddr=coord_addr,
        DeviceHangTimeoutS=2.0,
        WarmupNonceLens=[],  # no warmup: the hang must come from Mine
    )
)
w.initialize_rpcs()
w.start_forwarder()
print("HANG_WORKER_READY", flush=True)
threading.Event().wait()  # serve until the watchdog kills the process
