"""Native C++ miner tests (builds the shared library on first use)."""

import hashlib
import threading

import pytest

from distpow_tpu.models import puzzle

native = pytest.importorskip("distpow_tpu.backends.native_miner")

try:
    native.load_library()
    HAVE_NATIVE = True
except native.NativeUnavailable:
    HAVE_NATIVE = False

pytestmark = pytest.mark.skipif(
    not HAVE_NATIVE, reason="native miner could not be built"
)


@pytest.mark.parametrize("length", [0, 1, 8, 55, 56, 63, 64, 65, 130])
def test_native_md5_vs_hashlib(length):
    import random

    rng = random.Random(length)
    data = bytes(rng.randrange(256) for _ in range(length))
    assert native.native_md5(data) == hashlib.md5(data).digest()


@pytest.mark.parametrize("length", [0, 1, 55, 56, 64, 130])
def test_native_sha256_vs_hashlib(length):
    import random

    rng = random.Random(1000 + length)
    data = bytes(rng.randrange(256) for _ in range(length))
    assert native.native_sha256(data) == hashlib.sha256(data).digest()


def test_native_backend_sha256_matches_oracle():
    """The traits-templated scan loop must give reference enumeration
    order for the SHA-256 model too (models/registry.py pluggability,
    completing the model x backend matrix on the CPU perf path)."""
    backend = native.NativeBackend(hash_model="sha256", n_threads=1)
    for nonce in (b"\x01\x02\x03\x04", b"\xaa\xbb"):
        for difficulty in (1, 2, 3):
            tbs = list(range(256))
            secret = backend.search(nonce, difficulty, tbs)
            assert secret == puzzle.python_search(
                nonce, difficulty, tbs, algo="sha256")


def test_native_backend_sha256_long_nonce_multiblock():
    backend = native.NativeBackend(hash_model="sha256", n_threads=1)
    nonce = bytes(range(150))
    secret = backend.search(nonce, 2, list(range(256)))
    assert secret == puzzle.python_search(nonce, 2, list(range(256)),
                                          algo="sha256")


@pytest.mark.parametrize("length", [0, 1, 55, 56, 64, 130])
def test_native_sha1_vs_hashlib(length):
    import random

    rng = random.Random(2000 + length)
    data = bytes(rng.randrange(256) for _ in range(length))
    assert native.native_sha1(data) == hashlib.sha1(data).digest()


@pytest.mark.parametrize("length", [0, 1, 55, 64, 130])
def test_native_ripemd160_vs_hashlib(length):
    import random

    rng = random.Random(3000 + length)
    data = bytes(rng.randrange(256) for _ in range(length))
    want = hashlib.new("ripemd160", data).digest()
    assert native.native_ripemd160(data) == want


def test_native_backend_ripemd160_matches_oracle():
    """Ripemd160Traits through the same templated scan loop (round 4,
    fourth model): reference enumeration order preserved."""
    from distpow_tpu.models import puzzle

    backend = native.NativeBackend("ripemd160", n_threads=1)
    nonce = b"\x0a\x0b"
    oracle = puzzle.python_search(nonce, 2, list(range(256)),
                                  algo="ripemd160")
    assert backend.search(nonce, 2, list(range(256))) == oracle


@pytest.mark.parametrize("length", [0, 1, 111, 112, 128, 260])
def test_native_sha512_vs_hashlib(length):
    import random

    rng = random.Random(4000 + length)
    data = bytes(rng.randrange(256) for _ in range(length))
    assert native.native_sha512(data) == hashlib.sha512(data).digest()


def test_native_backend_sha512_matches_oracle():
    """Sha512Traits: the first 128-byte-block / 16-byte-length trait
    through the generalized scan loop (round 4)."""
    from distpow_tpu.models import puzzle

    backend = native.NativeBackend("sha512", n_threads=1)
    nonce = b"\x0a\x0b"
    oracle = puzzle.python_search(nonce, 2, list(range(256)), algo="sha512")
    assert backend.search(nonce, 2, list(range(256))) == oracle
    long_nonce = bytes(range(140))  # host-absorbs one full 128B block
    o2 = puzzle.python_search(long_nonce, 1, list(range(256)), algo="sha512")
    assert backend.search(long_nonce, 1, list(range(256))) == o2


@pytest.mark.parametrize("length", [0, 111, 112, 260])
def test_native_sha384_vs_hashlib(length):
    import random

    rng = random.Random(5000 + length)
    data = bytes(rng.randrange(256) for _ in range(length))
    assert native.native_sha384(data) == hashlib.sha384(data).digest()


def test_native_backend_sha384_matches_oracle():
    """Sha384Traits: truncated digest through the generic scan loop —
    MeetsDifficulty must read the 48-byte digest, not the 64-byte
    state."""
    from distpow_tpu.models import puzzle

    backend = native.NativeBackend("sha384", n_threads=1)
    oracle = puzzle.python_search(b"\x31\x41", 2, list(range(256)),
                                  algo="sha384")
    assert backend.search(b"\x31\x41", 2, list(range(256))) == oracle


@pytest.mark.parametrize("length", [0, 135, 136, 137, 300])
def test_native_sha3_vs_hashlib(length):
    """Sha3_256Traits digest hook: the lengths bracket the 136-byte
    rate boundary where the merged 0x86 pad byte appears."""
    import random

    rng = random.Random(7000 + length)
    data = bytes(rng.randrange(256) for _ in range(length))
    assert native.native_sha3_256(data) == hashlib.sha3_256(data).digest()


def test_native_backend_sha3_matches_oracle():
    """The sponge trait through the generic scan loop: kSpongePadding
    exercises the pad10*1 branch of the tail writer, including a
    long-nonce host absorption of one full 136-byte rate block."""
    from distpow_tpu.models import puzzle

    backend = native.NativeBackend("sha3_256", n_threads=1)
    oracle = puzzle.python_search(b"\x21\x43", 2, list(range(256)),
                                  algo="sha3_256")
    assert backend.search(b"\x21\x43", 2, list(range(256))) == oracle
    long_nonce = bytes(range(150))  # host-absorbs one full rate block
    o2 = puzzle.python_search(long_nonce, 1, list(range(256)),
                              algo="sha3_256")
    assert backend.search(long_nonce, 1, list(range(256))) == o2


@pytest.mark.parametrize("length", [0, 127, 128, 129, 300])
def test_native_blake2b_vs_hashlib(length):
    """Blake2b256Traits digest hook: lengths bracket the full-final-
    block edge (len % 128 == 0), where blake2 compresses the LAST full
    block with last=true instead of absorbing it early."""
    import random

    rng = random.Random(8000 + length)
    data = bytes(rng.randrange(256) for _ in range(length))
    assert native.native_blake2b_256(data) == hashlib.blake2b(
        data, digest_size=32).digest()


def test_native_backend_blake2b_matches_oracle():
    """The per-block-parameter trait through the generic scan loop:
    kNeedsBlockParams routes (t, last) into CompressWithParams, with a
    host-absorbed full prefix block carrying the counter across."""
    from distpow_tpu.models import puzzle

    backend = native.NativeBackend("blake2b_256", n_threads=1)
    for nonce in (b"\x61\x43", bytes(range(130))):
        oracle = puzzle.python_search(nonce, 2, list(range(256)),
                                      algo="blake2b_256")
        assert backend.search(nonce, 2, list(range(256))) == oracle


@pytest.mark.parametrize("length", [0, 5, 55, 56, 63, 64, 70, 128])
def test_native_sha256d_vs_hashlib(length):
    """Sha256dTraits digest hook (r5 ninth model): the composition
    lives entirely in StoreDigest, so the fixed second-block layout
    (0x80 at byte 32, zeros, BE bit-length 256 at bytes 56-63) is the
    hand-written part to pin against hashlib's double digest."""
    import random

    rng = random.Random(9000 + length)
    data = bytes(rng.randrange(256) for _ in range(length))
    assert native.native_sha256d(data) == hashlib.sha256(
        hashlib.sha256(data).digest()).digest()


def test_native_backend_sha256d_matches_oracle():
    """The composed trait through the generic scan loop: absorption is
    plain SHA-256, the second compression happens at digest time."""
    backend = native.NativeBackend(hash_model="sha256d", n_threads=1)
    for nonce in (b"\x01\x02\x03\x04", bytes(range(70))):
        for difficulty in (1, 2, 3):
            tbs = list(range(256))
            secret = backend.search(nonce, difficulty, tbs)
            assert secret == puzzle.python_search(
                nonce, difficulty, tbs, algo="sha256d")


def test_native_backend_sha1_matches_oracle():
    """Sha1Traits through the same templated scan loop: reference
    enumeration order for the third registry model too."""
    backend = native.NativeBackend(hash_model="sha1", n_threads=1)
    for nonce in (b"\x01\x02\x03\x04", b"\xcc\xdd"):
        for difficulty in (1, 2, 3):
            tbs = list(range(256))
            secret = backend.search(nonce, difficulty, tbs)
            assert secret == puzzle.python_search(
                nonce, difficulty, tbs, algo="sha1")


def test_native_backend_rejects_unknown_model():
    with pytest.raises(ValueError, match="native backend implements"):
        native.NativeBackend(hash_model="blake3")


def test_native_backend_unsatisfiable_difficulty_blocks_until_cancel():
    """difficulty > digest nibbles must block on the cancel gate (the
    reference parity contract, parallel/search.py) — never raise, never
    over-read the digest buffer in the C scan loop."""
    backend = native.NativeBackend(hash_model="md5", n_threads=1)
    ev = threading.Event()
    threading.Timer(0.1, ev.set).start()
    assert backend.search(b"\x01", 33, list(range(256)),
                          cancel_check=ev.is_set) is None


def test_native_backend_matches_oracle_single_thread():
    backend = native.NativeBackend(n_threads=1)
    for nonce in (b"\x01\x02\x03\x04", b"\xaa\xbb"):
        for difficulty in (1, 2, 3):
            tbs = list(range(256))
            secret = backend.search(nonce, difficulty, tbs)
            assert secret == puzzle.python_search(nonce, difficulty, tbs)


def test_native_backend_subpartition():
    backend = native.NativeBackend(n_threads=1)
    tbs = list(range(192, 256))
    secret = backend.search(b"\x05\x06", 2, tbs)
    assert secret is not None and secret[0] in tbs
    assert secret == puzzle.python_search(b"\x05\x06", 2, tbs)


def test_native_backend_multithreaded_valid():
    backend = native.NativeBackend(n_threads=4)
    secret = backend.search(b"\x31\x41\x59", 3, list(range(256)))
    assert secret is not None
    assert puzzle.check_secret(b"\x31\x41\x59", secret, 3)


def test_native_backend_long_nonce_multiblock():
    backend = native.NativeBackend(n_threads=1)
    nonce = bytes(range(150))
    secret = backend.search(nonce, 2, list(range(256)))
    assert secret == puzzle.python_search(nonce, 2, list(range(256)))


def test_native_backend_cancellation():
    backend = native.NativeBackend(n_threads=2, range_size=1 << 18)
    ev = threading.Event()
    threading.Timer(0.2, ev.set).start()
    secret = backend.search(b"\x01", 30, list(range(256)), cancel_check=ev.is_set)
    assert secret is None


def test_native_backend_hash_accounting():
    """search.hashes must total across range calls — the native library
    OVERWRITES its out-param per call, so multi-call searches (small
    range_size) previously recorded only deltas."""
    from distpow_tpu.models import puzzle
    from distpow_tpu.runtime.metrics import REGISTRY

    backend = native.NativeBackend(n_threads=1, range_size=1 << 8)
    before = REGISTRY.get("search.hashes")
    secret = backend.search(b"\x01\x02", 3, list(range(256)))
    assert secret is not None
    counted = REGISTRY.get("search.hashes") - before
    # exact total: replay the same search with the oracle and count
    oracle_count = 0

    def on_progress(n):
        nonlocal oracle_count
        oracle_count += n

    assert puzzle.python_search(b"\x01\x02", 3, list(range(256)),
                                on_progress=on_progress) == secret
    assert counted == oracle_count


def test_native_digest_bytes_agree_with_registry():
    """The local DIGEST_BYTES table (which keeps jax out of the native
    import graph, advisor r3) must never drift from the registry."""
    from distpow_tpu.models.registry import get_hash_model

    for name, nbytes in native.DIGEST_BYTES.items():
        model = get_hash_model(name)
        assert model.digest_bytes == nbytes
        assert model.max_difficulty == 2 * nbytes
    assert set(native.DIGEST_BYTES) == set(native.ALGO_IDS)


def test_native_backend_importable_without_jax():
    """Native-only deployments (jax absent) must be able to import and
    run the C++ backend: the whole import graph of
    backends.native_miner is jax-free (advisor r3; models/__init__ and
    parallel/__init__ expose their jax halves lazily via PEP 562)."""
    import subprocess
    import sys as _sys

    code = """
import sys
for m in [m for m in sys.modules if m == "jax" or m.startswith(("jax.", "jaxlib"))]:
    del sys.modules[m]
class Block:
    def find_spec(self, name, path=None, target=None):
        if name == "jax" or name.startswith(("jax.", "jaxlib")):
            raise ImportError("jax blocked: " + name)
sys.meta_path.insert(0, Block())
from distpow_tpu.backends import native_miner
for algo in ("md5", "sha256", "sha1"):
    s = native_miner.NativeBackend(algo).search(
        b"\\x01\\x02\\x03\\x04", 3, list(range(256)))
    assert s is not None, algo
print("JAXFREE_OK")
"""
    out = subprocess.run([_sys.executable, "-c", code],
                         capture_output=True, text=True, timeout=180)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "JAXFREE_OK" in out.stdout
