"""distpow-lint: the suite enforces a clean tree, and the fixture
corpus proves every rule both fires and passes (ISSUE 2).

Tier-1 (un-slow, ``lint`` marker): the engine is stdlib-only AST work —
the whole file runs in well under a second — so the fast suite gates on
it exactly like ``scripts/ci.sh --lint`` does.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from distpow_tpu.analysis import build_context, run_analysis  # noqa: E402
from distpow_tpu.analysis.engine import (  # noqa: E402
    BARE_SUPPRESSION,
    UNUSED_SUPPRESSION,
)
from distpow_tpu.analysis.rules import ALL_RULES  # noqa: E402

PKG = os.path.join(REPO, "distpow_tpu")
FIXTURES = os.path.join(REPO, "tests", "lint_fixtures")
CTX = build_context(PKG)

pytestmark = pytest.mark.lint


def lint(path, rule=None):
    return run_analysis(
        [os.path.join(FIXTURES, path)],
        context=CTX,
        rule_ids=[rule] if rule else None,
        rel_to=REPO,
    )


# -- the gate: the shipped tree is clean -------------------------------------

def test_package_tree_has_zero_unsuppressed_findings():
    report = run_analysis([PKG], context=CTX, rel_to=REPO)
    assert report.findings == [], (
        "distpow-lint findings in the shipped tree:\n"
        + "\n".join(f.format() for f in report.findings)
    )
    # the tree exercises the suppression protocol for real (the
    # deliberate emit-under-lock / silent-hook holds), and every one of
    # those suppressions carries a justification by construction
    assert len(report.suppressed) >= 10
    assert all(s.justification for _, s in report.suppressed)


def test_context_parsed_from_real_declarations():
    # 16 reference-parity action types, the full counter AND histogram
    # registries, and the config dataclass fields all parse out of the
    # package source
    assert len(CTX.action_names) == 16
    assert "CoordinatorWorkerResult" in CTX.action_names
    assert "coord.stale_results_dropped" in CTX.counters
    assert "faults.injected." in CTX.counter_prefixes
    assert "coord.mine_s.miss" in CTX.histograms
    assert "worker.solve_s" in CTX.histograms
    assert "rpc.client.call_s." in CTX.histogram_prefixes
    assert "rpc.server.dispatch_s." in CTX.histogram_prefixes
    assert "proc.rss_bytes" in CTX.gauges
    assert "ring.repl_queue_depth" in CTX.gauges
    assert CTX.gauge_prefixes == ()
    assert {"Backend", "CacheFile", "MineRetries",
            "TelemetryDir"} <= CTX.config_fields


def test_known_series_documented():
    """Every declared counter, histogram, and gauge appears in the
    metrics.py docstring — the human registry and the machine registry
    must not drift."""
    import distpow_tpu.runtime.metrics as m

    doc = m.__doc__ or ""
    for declared in (m.KNOWN_COUNTERS, m.KNOWN_HISTOGRAMS, m.KNOWN_GAUGES):
        missing = sorted(
            c for c in declared
            if c not in doc and f"``.{c.split('.', 1)[1]}" not in doc
            and c.split(".", 1)[1] not in doc
        )
        assert not missing, f"series undeclared in docstring: {missing}"


# -- every rule fires on its bad fixture and passes its clean one ------------

CASES = [
    ("no-blocking-under-lock", "blocking_under_lock_bad.py",
     "blocking_under_lock_ok.py", 5),
    ("trace-vocabulary", "trace_vocabulary_bad.py",
     "trace_vocabulary_ok.py", 3),
    ("metrics-registry", "metrics_registry_bad.py",
     "metrics_registry_ok.py", 7),
    ("config-key-sync", "config_key_sync_bad.py",
     "config_key_sync_ok.py", 3),
    ("hot-path-host-sync", os.path.join("ops", "hot_path_host_sync_bad.py"),
     os.path.join("ops", "hot_path_host_sync_ok.py"), 5),
    ("relaunch-loop-sync",
     os.path.join("parallel", "relaunch_loop_sync_bad.py"),
     os.path.join("parallel", "relaunch_loop_sync_ok.py"), 4),
    ("silent-except", os.path.join("runtime", "silent_except_bad.py"),
     os.path.join("runtime", "silent_except_ok.py"), 3),
    ("bounded-queue", os.path.join("runtime", "bounded_queue_bad.py"),
     os.path.join("runtime", "bounded_queue_ok.py"), 4),
    ("serial-rpc-fanout", os.path.join("nodes", "serial_rpc_fanout_bad.py"),
     os.path.join("nodes", "serial_rpc_fanout_ok.py"), 3),
    # fleet membership (ISSUE 12): a per-member thread spawn in a loop
    # scales thread count with the fleet; the ok fixture blesses the
    # persistent-thread / bounded-pool shapes + the suppression protocol
    ("unbounded-thread-spawn",
     os.path.join("fleet", "unbounded_thread_spawn_bad.py"),
     os.path.join("fleet", "unbounded_thread_spawn_ok.py"), 3),
    # the same rule's obs/ scope (ISSUE 8): a serial Stats scrape loop
    # is the fan-out bug one layer up — the fixture pair proves the
    # rule fires there and blesses the shared-deadline thread shape
    ("serial-rpc-fanout", os.path.join("obs", "serial_rpc_fanout_bad.py"),
     os.path.join("obs", "serial_rpc_fanout_ok.py"), 3),
    # request forensics (ISSUE 14): a raw SPANS.begin leaks its span on
    # any missed exit path — a silent hole in the request timeline; the
    # ok fixture blesses the context-manager form, the one-shot
    # recorders, and the justified cross-thread suppression
    ("unclosed-span", os.path.join("sched", "unclosed_span_bad.py"),
     os.path.join("sched", "unclosed_span_ok.py"), 3),
    # coordinator scale-out (ISSUE 15): hash % len(members) routing
    # remaps ~every key on membership change — the consistent-hash
    # ring (cluster/ring.py) is the sanctioned shape; the ok fixture
    # blesses hash-free rotation, ring lookups, non-membership modulo,
    # and the suppression protocol
    ("modulo-routing", os.path.join("nodes", "modulo_routing_bad.py"),
     os.path.join("nodes", "modulo_routing_ok.py"), 3),
    # cache replication (ISSUE 16): both rules now cover cluster/ — the
    # replication plane loops over peer collections with RPCs and
    # per-target sender spawns inside, exactly the shapes these rules
    # police; the ok fixtures bless issue-then-await, the persistent
    # pusher, and the justified-suppression protocol the real
    # cluster/replication.py loops follow
    ("serial-rpc-fanout",
     os.path.join("cluster", "serial_rpc_fanout_bad.py"),
     os.path.join("cluster", "serial_rpc_fanout_ok.py"), 3),
    ("unbounded-thread-spawn",
     os.path.join("cluster", "unbounded_thread_spawn_bad.py"),
     os.path.join("cluster", "unbounded_thread_spawn_ok.py"), 3),
    # concurrency-discipline plane (ISSUE 17): guarded-by covers both
    # tiers (annotation violations incl. a bare READ, discovered
    # mixed locked/bare writes); the ok fixture proves entry-lock
    # credit through a helper and the suppression protocol
    ("unguarded-shared-write",
     os.path.join("concurrency", "guarded_by_bad.py"),
     os.path.join("concurrency", "guarded_by_ok.py"), 3),
    # the inversion's a->b edge exists only through the call summary
    # (indirect), b->a lexically; one finding per cycle, not per edge
    ("lock-order-inversion",
     os.path.join("concurrency", "lock_order_bad.py"),
     os.path.join("concurrency", "lock_order_ok.py"), 1),
    # blocking two call hops below the critical section + a direct
    # block under a discovered Condition the lexical rule cannot name;
    # the ok fixture blesses snapshot-then-act and the cond-wait loop
    ("transitive-blocking-under-lock",
     os.path.join("concurrency", "transitive_blocking_bad.py"),
     os.path.join("concurrency", "transitive_blocking_ok.py"), 2),
    # long-haul soak plane (ISSUE 18): a wall-clock delta in a duration
    # position silently corrupts every latency/lag series under NTP
    # slew; the ok fixture blesses the wall-stamp/monotonic-delta
    # idiom and the justified cross-process-staleness suppression
    ("wall-clock-duration",
     os.path.join("runtime", "wall_clock_duration_bad.py"),
     os.path.join("runtime", "wall_clock_duration_ok.py"), 4),
]


@pytest.mark.parametrize("rule,bad,ok,n_expected",
                         CASES, ids=[c[0] for c in CASES])
def test_rule_fires_and_passes(rule, bad, ok, n_expected):
    bad_report = lint(bad, rule)
    assert len(bad_report.findings) == n_expected, (
        f"{rule} on {bad}: expected {n_expected} findings, got:\n"
        + "\n".join(f.format() for f in bad_report.findings)
    )
    assert all(f.rule == rule for f in bad_report.findings)
    ok_report = lint(ok, rule)
    assert ok_report.findings == [], (
        f"{rule} false positives on {ok}:\n"
        + "\n".join(f.format() for f in ok_report.findings)
    )


def test_blocking_under_lock_flags_each_blocking_kind():
    lines = {f.line for f in lint("blocking_under_lock_bad.py",
                                  "no-blocking-under-lock").findings}
    assert lines == {19, 23, 24, 28, 29}


def test_dead_package_rule():
    bad = run_analysis([os.path.join(FIXTURES, "dead_pkg_bad")],
                       context=CTX, rel_to=REPO)
    assert [f.rule for f in bad.findings] == ["dead-package"]
    ok = run_analysis([os.path.join(FIXTURES, "dead_pkg_ok")],
                      context=CTX, rel_to=REPO)
    assert ok.findings == []


# -- suppression protocol ----------------------------------------------------

def test_justified_suppression_is_honored_and_counted():
    report = lint("suppressed_ok.py")
    assert report.findings == []
    assert len(report.suppressed) == 1
    finding, sup = report.suppressed[0]
    assert finding.rule == "no-blocking-under-lock"
    assert "documented design" in sup.justification


def test_bare_suppression_is_itself_a_finding():
    report = lint("suppressed_bare.py")
    assert [f.rule for f in report.findings] == [BARE_SUPPRESSION]
    assert report.suppressed == []  # silenced, but not counted as clean


def test_unused_suppression_is_flagged():
    report = lint("suppressed_unused.py")
    assert [f.rule for f in report.findings] == [UNUSED_SUPPRESSION]


def test_single_rule_run_does_not_flag_foreign_suppressions():
    """--rule subset runs must not report other rules' justified holds
    as unused (review: `--rule silent-except distpow_tpu/nodes/` failed
    the clean tree on powlib's no-blocking-under-lock suppressions)."""
    report = run_analysis(
        [os.path.join(REPO, "distpow_tpu", "nodes")],
        context=CTX, rule_ids=["silent-except"], rel_to=REPO,
    )
    assert report.findings == [], [f.format() for f in report.findings]


def test_trailing_suppression_covers_wrapped_call(tmp_path):
    """A trailing suppression on the continuation line of a wrapped
    call covers the finding anchored at the statement's first line."""
    p = tmp_path / "wrapped.py"
    p.write_text(
        "import threading, time\n"
        "_lock = threading.Lock()\n"
        "def f(x):\n"
        "    with _lock:\n"
        "        time.sleep(\n"
        "            x)  # distpow: ok no-blocking-under-lock -- "
        "deliberate hold, fixture\n"
    )
    report = run_analysis([str(p)], context=CTX)
    assert report.findings == [], [f.format() for f in report.findings]
    assert len(report.suppressed) == 1


# -- CLI contract ------------------------------------------------------------

def _cli(*args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "lint.py"), *args],
        capture_output=True, text=True, timeout=120, cwd=REPO,
    )


def test_cli_clean_tree_exits_zero():
    out = _cli("distpow_tpu", "--json",
               "--baseline", os.path.join("scripts", "lint_baseline.json"))
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["ok"] is True
    assert payload["findings"] == []
    assert payload["checked_files"] > 50


def test_cli_findings_exit_one():
    out = _cli(os.path.join("tests", "lint_fixtures",
                            "blocking_under_lock_bad.py"))
    assert out.returncode == 1
    assert "no-blocking-under-lock" in out.stdout


def test_cli_unknown_rule_exits_two():
    out = _cli("distpow_tpu", "--rule", "no-such-rule")
    assert out.returncode == 2


def test_cli_list_rules_names_every_rule():
    out = _cli("--list-rules")
    assert out.returncode == 0
    for rule in ALL_RULES:
        assert rule.RULE_ID in out.stdout
    assert len(ALL_RULES) >= 7


# -- baseline hygiene (ISSUE 17) ---------------------------------------------
# A baseline entry that no longer matches any finding is itself a
# ``stale-baseline`` finding: grandfathered debt must shrink, never rot.

_BAD = os.path.join("tests", "lint_fixtures", "blocking_under_lock_bad.py")


def _live_entries():
    payload = json.loads(_cli(_BAD, "--json").stdout)
    assert payload["findings"], "fixture must still produce findings"
    return [{"rule": f["rule"], "path": f["path"], "message": f["message"]}
            for f in payload["findings"]]


def test_stale_baseline_entry_is_a_finding(tmp_path):
    base = tmp_path / "baseline.json"
    base.write_text(json.dumps({"findings": _live_entries() + [
        {"rule": "silent-except", "path": "distpow_tpu/gone.py",
         "message": "fixed long ago"}]}))
    out = _cli(_BAD, "--baseline", str(base))
    assert out.returncode == 1
    assert "stale-baseline" in out.stdout
    assert "gone.py" in out.stdout
    # the live entries still grandfather their findings
    assert "no-blocking-under-lock" not in out.stdout


def test_live_baseline_still_grandfathers_cleanly(tmp_path):
    base = tmp_path / "baseline.json"
    base.write_text(json.dumps({"findings": _live_entries()}))
    out = _cli(_BAD, "--baseline", str(base))
    assert out.returncode == 0, out.stdout + out.stderr


def test_rewrite_baseline_prunes_only_stale_entries(tmp_path):
    live = _live_entries()
    base = tmp_path / "baseline.json"
    base.write_text(json.dumps({
        "_comment": "kept",
        "findings": live + [{"rule": "silent-except",
                             "path": "distpow_tpu/gone.py",
                             "message": "fixed long ago"}]}))
    out = _cli(_BAD, "--baseline", str(base), "--rewrite-baseline")
    assert out.returncode == 0, out.stdout + out.stderr
    assert "pruned 1 stale" in out.stderr
    data = json.loads(base.read_text())
    assert data["_comment"] == "kept"
    assert data["findings"] == live
    # idempotent: a second rewrite changes nothing and stays clean
    out2 = _cli(_BAD, "--baseline", str(base), "--rewrite-baseline")
    assert out2.returncode == 0
    assert "pruned" not in out2.stderr


def test_rewrite_baseline_requires_baseline():
    out = _cli(_BAD, "--rewrite-baseline")
    assert out.returncode == 2
