"""Golden-trace parity: run-to-run deterministic ids and action order.

The reference's de-facto acceptance test is trace parity (SURVEY.md
section 4: BASELINE config 5 demands "identical traces").  That requires
(a) trace ids that are stable across runs — the reference's
DistributedClocks ids are the client identity + a counter, deterministic
by construction — and (b) per-node action sequences that do not reorder
between runs of the same scenario.
"""

import zlib

from distpow_tpu.runtime.tracing import MemorySink, Tracer


def test_trace_ids_deterministic_across_tracers():
    """Two tracers with the same identity produce the same trace-id
    sequence — the property PYTHONHASHSEED randomization used to break
    (VERDICT r1 weak #7)."""
    a = Tracer("clientA", MemorySink())
    b = Tracer("clientA", MemorySink())
    ids_a = [a.create_trace().trace_id for _ in range(5)]
    ids_b = [b.create_trace().trace_id for _ in range(5)]
    assert ids_a == ids_b
    # and the construction is the documented stable one
    tag = zlib.crc32(b"clientA") & 0xFFFFFFFF
    assert ids_a[0] == (tag << 32 | 1)


def test_trace_ids_distinct_across_identities():
    ids = set()
    for ident in ("client1", "client2", "worker1", "coordinator"):
        t = Tracer(ident, MemorySink())
        for _ in range(3):
            ids.add(t.create_trace().trace_id)
    assert len(ids) == 12


def _node_sequence(sink):
    seq = []
    for e in sink.events:
        if e["type"] != "action":
            continue
        b = e["body"]
        seq.append([e["trace_id"], e["action"],
                    bytes(b["Nonce"]).hex() if "Nonce" in b else None,
                    b.get("NumTrailingZeros")])
    return seq


def test_golden_trace_demo_replay():
    """Replay the cmd/client demo scenario (cmd/client/main.go:40-51)
    SEQUENTIALLY and diff every node's ordered action sequence against
    the checked-in golden file — any action reorder, drop, duplicate, or
    trace-id drift fails.  (Sequential replay pins the orderings that the
    concurrent demo leaves racy; the concurrent variant is covered by the
    trace_check invariants and tests/test_stress.py.)  Regenerate the
    golden after an INTENTIONAL protocol change by running this scenario
    and dumping `_node_sequence` per node to tests/golden_trace.json."""
    import json
    import os
    import sys

    sys.path.insert(0, os.path.dirname(__file__))
    from test_nodes import Stack, mine_and_wait

    s = Stack(1)
    try:
        c1 = s.new_client("client1")
        c2 = s.new_client("client2")
        mine_and_wait(c1, bytes([1, 2, 3, 4]), 4)
        mine_and_wait(c1, bytes([5, 6, 7, 8]), 2)
        mine_and_wait(c2, bytes([2, 2, 2, 2]), 2)
        mine_and_wait(c2, bytes([2, 2, 2, 2]), 4)  # dominance supersede

        golden = json.load(open(
            os.path.join(os.path.dirname(__file__), "golden_trace.json")))
        for node in ("client1", "client2", "coordinator", "worker1"):
            assert _node_sequence(s.sinks[node]) == golden[node], \
                f"{node} action sequence diverged from golden"
    finally:
        s.close()


def test_shiviz_output_matches_published_parser_spec():
    """Validate the ShiViz log against ShiViz's own parser contract —
    the regex `(?<host>\\S*) (?<clock>{.*})\\n(?<event>.*)` published in
    the GoVector/ShiViz docs (the reference's tracing server writes this
    format, config/tracing_server_config.json:4-5) — NOT against this
    repo's own parser.  Also checks the GoVector clock discipline: each
    host's own component is present and strictly increases by 1 per
    emitted event."""
    import json
    import re

    from distpow_tpu.runtime.config import TracingServerConfig
    from distpow_tpu.runtime.trace_server import TracingServer

    import tempfile, os
    d = tempfile.mkdtemp()
    cfg = TracingServerConfig(
        ServerBind="127.0.0.1:0",
        Secret=b"",
        OutputFile=os.path.join(d, "trace_output.log"),
        ShivizOutputFile=os.path.join(d, "shiviz_output.log"),
    )
    server = TracingServer(cfg)
    # generate real tracer events through a sink that feeds the server
    class DirectSink:
        def emit(self, event):
            server._handle_event(event)
        def close(self):
            pass

    a = Tracer("alpha", DirectSink())
    b = Tracer("beta", DirectSink())
    t = a.create_trace()
    from distpow_tpu.runtime.actions import CacheMiss
    t.record_action(CacheMiss(nonce=b"\x01", num_trailing_zeros=3))
    tok = t.generate_token()
    t2 = b.receive_token(tok)
    t2.record_action(CacheMiss(nonce=b"\x01", num_trailing_zeros=3))
    server.close()

    lines = open(cfg.ShivizOutputFile).read().split("\n")
    # header first: the multi-line parser regex ShiViz is configured
    # with, written on one line (literal backslash-n), then a blank line
    assert lines[0] == "(?<host>\\S*) (?<clock>{.*})\\n(?<event>.*)"
    assert lines[1] == ""
    pair_rx = re.compile(r"^(\S+) (\{.*\})$")
    pairs = [ln for ln in lines[2:] if ln]
    assert len(pairs) % 2 == 0 and pairs
    last_clock = {}
    for i in range(0, len(pairs), 2):
        m = pair_rx.match(pairs[i])
        assert m, f"event line {pairs[i]!r} does not match the ShiViz regex"
        host, clock = m.group(1), json.loads(m.group(2))
        assert host in clock and isinstance(clock[host], int)
        # GoVector discipline: the emitter ticks its own component by
        # exactly 1 per emitted event
        assert clock[host] == last_clock.get(host, 0) + 1
        last_clock[host] = clock[host]
        assert pairs[i + 1].strip(), "empty description line"


def test_shiviz_clock_lines_byte_match_govector_golden():
    """Byte-for-byte golden-shape diff against the published GoVector
    format (VERDICT r3 item 6).

    The golden below is hand-derived from GoVector's documented log
    entry shape — ``pid vcstring\\nmessage\\n`` where vcstring is
    ``vclock.ReturnVCString()``: ids sorted lexicographically,
    ``"id":count`` pairs joined by ", " inside braces (e.g.
    ``{"alpha":2, "beta":1}``) — the format the reference's tracing
    server writes into shiviz_output.log via govec
    (cmd/tracing-server/main.go:10-17,
    config/tracing_server_config.json:4-5).  The full ``pid vcstring``
    clock line must diff CLEAN against a GoVector log.

    Irreducible divergences, documented: (a) this server writes the
    ShiViz parser regex as a 2-line file header — GoVector raw logs
    carry no header (strip 2 lines to compare whole files); (b) the
    event-description line renders the action body as JSON
    (``CacheMiss {"Nonce": [1], ...}``) where Go's fmt "%+v" renders
    ``{Nonce:[1] ...}`` — ShiViz treats the description as opaque text,
    and the Go rendering is unreproducible without fixing every
    downstream type's String(); (c) GoVector logs open with an
    "Initialization Complete" entry at clock {pid:1} — the tracing-layer
    equivalent is the first real event, since the reference tracing lib
    (not raw govec) also skips a dedicated init line per its
    trace_output.log samples."""
    import os
    import tempfile

    from distpow_tpu.runtime.actions import CacheMiss
    from distpow_tpu.runtime.config import TracingServerConfig
    from distpow_tpu.runtime.trace_server import TracingServer, govector_vc_string

    d = tempfile.mkdtemp()
    cfg = TracingServerConfig(
        ServerBind="127.0.0.1:0",
        Secret=b"",
        OutputFile=os.path.join(d, "trace_output.log"),
        ShivizOutputFile=os.path.join(d, "shiviz_output.log"),
    )
    server = TracingServer(cfg)

    class DirectSink:
        def emit(self, event):
            server._handle_event(event)

        def close(self):
            pass

    # two-host token exchange: alpha acts, hands causality to beta
    alpha = Tracer("alpha", DirectSink())
    beta = Tracer("beta", DirectSink())
    t = alpha.create_trace()
    t.record_action(CacheMiss(nonce=b"\x01", num_trailing_zeros=3))
    tok = t.generate_token()
    t2 = beta.receive_token(tok)
    t2.record_action(CacheMiss(nonce=b"\x01", num_trailing_zeros=3))
    tok2 = t2.generate_token()
    alpha.receive_token(tok2)
    server.close()

    lines = open(cfg.ShivizOutputFile).read().split("\n")
    clock_lines = [ln for ln in lines[2:] if ln][0::2]  # skip header; evens
    golden = [
        'alpha {"alpha":1}',                       # CacheMiss
        'alpha {"alpha":2}',                       # generate_token
        'beta {"alpha":2, "beta":1}',              # receive_token (merge)
        'beta {"alpha":2, "beta":2}',              # CacheMiss
        'beta {"alpha":2, "beta":3}',              # generate_token
        'alpha {"alpha":3, "beta":3}',             # receive_token (merge)
    ]
    assert clock_lines == golden

    # and the formatter alone round-trips a published GoVector sample
    assert govector_vc_string({"beta": 1, "alpha": 2}) == \
        '{"alpha":2, "beta":1}'
    assert govector_vc_string({"solo": 7}) == '{"solo":7}'
