"""Request-forensics plane tests (ISSUE 14, docs/FORENSICS.md).

Covers the span recorder and slow-request trigger units, histogram
exemplars (capture, snapshot, cluster-merge survival, OpenMetrics
rendering), flight-recorder journal rotation, the ``Node.Spans`` RPC +
cross-node stitch, span-tree completeness on the hard paths (coalesced
waiters, mid-round reassignment, hedged duplicate shards, scheduler
slots), the coordinator's slow-request auto-capture, SLO breach dumps
attaching slow-request timelines, and ``trace_profile``'s span-ring
input format.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from test_nodes import Stack, mine_and_wait  # noqa: E402

from distpow_tpu.models import puzzle  # noqa: E402
from distpow_tpu.obs.forensics import (  # noqa: E402
    fetch_spans,
    render_timeline,
    slowest_trace_id,
    stitch_timeline,
)
from distpow_tpu.obs.merge import (  # noqa: E402
    delta_histogram,
    merge_histograms,
)
from distpow_tpu.runtime.metrics import REGISTRY as metrics  # noqa: E402
from distpow_tpu.runtime.metrics import Histogram  # noqa: E402
from distpow_tpu.runtime.spans import (  # noqa: E402
    SPANS,
    SlowRequestTrigger,
    SpanRecorder,
)
from distpow_tpu.runtime.telemetry import RECORDER  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _trace_id(res) -> int:
    """Trace id from a MineResult's self-contained token."""
    return int(json.loads(bytes(res.token).decode())["trace_id"])


def _names(spans):
    return {s["name"] for s in spans}


# -- recorder unit ------------------------------------------------------------

def test_span_context_manager_records_once():
    rec = SpanRecorder(capacity=16)
    with rec.span("worker.solve", trace_id=7, node="w", shard=3) as sp:
        sp.annotate(outcome="found")
    spans = rec.spans_for(7)
    assert len(spans) == 1
    s = spans[0]
    assert s["name"] == "worker.solve" and s["node"] == "w"
    assert s["attrs"] == {"shard": 3, "outcome": "found"}
    assert s["dur_s"] >= 0.0


def test_span_error_exit_tags_outcome():
    rec = SpanRecorder(capacity=16)
    with pytest.raises(ValueError):
        with rec.span("worker.solve", trace_id=9, node="w"):
            raise ValueError("boom")
    (s,) = rec.spans_for(9)
    assert s["attrs"]["outcome"] == "error:ValueError"


def test_begin_finish_is_idempotent():
    rec = SpanRecorder(capacity=16)
    h = rec.begin("sched.slot", trace_id=5, node="w")
    h.finish(launches=2)
    h.finish(launches=99)  # second finish must not double-record
    spans = rec.spans_for(5)
    assert len(spans) == 1 and spans[0]["attrs"]["launches"] == 2


def test_bind_nesting_and_inheritance():
    rec = SpanRecorder(capacity=16)
    assert rec.current_trace_id() == 0
    with rec.bind(11, "node-a"):
        with rec.span("search.launch") as sp:
            assert sp.trace_id == 11 and sp.node == "node-a"
        with rec.bind(22, "node-b"):
            assert rec.current_trace_id() == 22
        # inner bind restored
        assert rec.current_trace_id() == 11
    assert rec.current_trace_id() == 0


def test_ring_bound_counts_drops():
    rec = SpanRecorder(capacity=4)
    d0 = metrics.get("spans.dropped")
    for i in range(10):
        rec.record("search.launch", time.time(), 0.001, trace_id=i)
    assert len(rec.recent()) == 4
    assert metrics.get("spans.dropped") - d0 == 6


def test_disabled_recorder_is_noop():
    rec = SpanRecorder(capacity=16)
    rec.configure(enabled=False)
    with rec.span("worker.solve", trace_id=3) as sp:
        sp.annotate(x=1)  # null span: must not raise
    rec.record("search.launch", time.time(), 0.1, trace_id=3)
    rec.event("coord.reassign", trace_id=3)
    assert rec.recent() == []
    rec.configure(enabled=True)
    rec.event("coord.reassign", trace_id=3)
    assert len(rec.recent()) == 1


def test_trace_summaries_rank_by_root_span():
    rec = SpanRecorder(capacity=64)
    rec.record("coord.mine", time.time(), 0.5, trace_id=1, node="c")
    rec.record("worker.solve", time.time(), 9.0, trace_id=1, node="w")
    rec.record("coord.mine", time.time(), 2.0, trace_id=2, node="c")
    rec.record("search.launch", time.time(), 0.1, trace_id=3, node="w")
    summaries = {t["trace_id"]: t for t in rec.trace_summaries()}
    assert summaries[1]["root"] == "coord.mine"
    assert summaries[1]["dur_s"] == 0.5  # root span, not slowest member
    assert summaries[3]["root"] is None
    assert summaries[3]["dur_s"] == 0.1  # rootless: slowest member
    slowest = rec.slowest_traces(k=2)
    assert [t["trace_id"] for t in slowest] == [2, 1]
    assert all(t["spans"] for t in slowest)  # full trees attached


# -- slow-request trigger -----------------------------------------------------

def test_trigger_threshold_arm():
    t = SlowRequestTrigger(threshold_s=0.5)
    assert t.armed
    assert t.observe(0.4) is None
    assert t.observe(0.6) == "threshold"


def test_trigger_disarmed_by_default():
    t = SlowRequestTrigger()
    assert not t.armed
    assert t.observe(100.0) is None


def test_trigger_p99_arm_quiet_until_min_samples():
    t = SlowRequestTrigger(p99_factor=3.0, min_samples=10)
    for _ in range(9):
        assert t.observe(0.01) is None  # warming: even a 100x outlier
    assert t.observe(10.0) is None      # ...9 samples < min: still quiet
    # window now holds the 10.0 outlier; p99 ~ 10.0, so only > 30 fires
    assert t.observe(0.02) is None
    assert t.observe(40.0) == "p99"


def test_trigger_sample_does_not_lift_its_own_bar():
    t = SlowRequestTrigger(p99_factor=2.0, min_samples=5)
    for _ in range(20):
        t.observe(0.01)
    # 1.0 is judged against the PRE-observation window (p99 ~ 0.01)
    assert t.observe(1.0) == "p99"


# -- histogram exemplars ------------------------------------------------------

def test_exemplar_capture_and_snapshot_shape():
    h = Histogram()
    h.observe(0.5, trace_id=42)
    h.observe(0.5)            # no trace: exemplar kept
    h.observe(0.5, trace_id=43)  # same bucket: last trace wins
    h.observe(0.0, trace_id=7)   # zero bucket
    d = h.to_dict()
    ex = {b: (tid, v) for b, tid, v, _ts in d["exemplars"]}
    assert ex[0.0] == (7, 0.0)
    (bucket_bound,) = [b for b in ex if b > 0.0]
    assert ex[bucket_bound] == (43, 0.5)
    # exemplars ride only when present
    assert "exemplars" not in Histogram().to_dict()


def test_registry_exemplar_toggle():
    m = metrics.__class__()
    m.observe("coord.mine_s.miss", 0.5, trace_id=1)
    m.exemplars_enabled = False
    m.observe("coord.mine_s.miss", 0.5, trace_id=2)
    ex = m.get_histogram("coord.mine_s.miss")["exemplars"]
    assert ex[0][1] == 1  # the disabled observation left no exemplar


def test_exemplar_survives_cluster_merge_freshest_wins():
    a, b = Histogram(), Histogram()
    a.observe(0.5, trace_id=1)
    time.sleep(0.002)
    b.observe(0.5, trace_id=2)  # fresher observation of the same bucket
    b.observe(8.0, trace_id=3)
    merged = merge_histograms([a.to_dict(), b.to_dict()])
    ex = {b_: tid for b_, tid, _v, _ts in merged["exemplars"]}
    assert len(ex) == 2
    assert 2 in ex.values()  # freshest won the shared bucket
    assert 3 in ex.values()
    # the merged counts are unchanged by exemplar merging
    assert merged["count"] == 3
    # and the windowed view keeps the new snapshot's exemplars
    delta = delta_histogram(b.to_dict(), a.to_dict())
    assert {e[1] for e in delta["exemplars"]} == {2, 3}


def test_openmetrics_rendering_carries_exemplars():
    from distpow_tpu.cli.stats import render_prometheus

    h = Histogram()
    h.observe(0.5, trace_id=77)
    snap = {"role": "worker",
            "histograms": {"worker.solve_s": h.to_dict()}}
    plain = render_prometheus(snap)
    assert "trace_id" not in plain and "# EOF" not in plain
    om = render_prometheus(snap, openmetrics=True)
    assert '# {trace_id="77"} 0.5' in om
    assert om.rstrip().endswith("# EOF")


# -- journal rotation ---------------------------------------------------------

def test_journal_rotation_bounds_disk(tmp_path):
    from distpow_tpu.runtime.telemetry import FlightRecorder

    rec = FlightRecorder(capacity=64)
    path = str(tmp_path / "soak.telemetry.jsonl")
    rec.configure(journal_path=path, journal_interval_s=3600.0,
                  journal_max_bytes=2048, journal_keep=2)
    try:
        for i in range(400):
            rec.record("soak.event", i=i, pad="x" * 64)
            if i % 10 == 9:
                rec.flush_journal()
    finally:
        rec.stop()
    segments = sorted(p for p in os.listdir(tmp_path)
                      if p.startswith("soak.telemetry.jsonl"))
    # rotation happened, the keep cap held, and no segment beyond .2
    assert f"{os.path.basename(path)}.1" in segments
    assert all(not p.endswith(".3") for p in segments)
    assert len(segments) <= 3  # live + keep(2)
    total = sum(os.path.getsize(tmp_path / p) for p in segments)
    # bounded at ~(keep + 1) x cap plus one flush of slack
    assert total < 3 * 2048 + 4096
    # rotated + live lines are valid JSONL and strictly seq-ordered
    seqs = []
    for p in (f"{path}.2", f"{path}.1", path):
        if os.path.exists(p):
            with open(p) as fh:
                seqs.extend(json.loads(ln)["seq"] for ln in fh
                            if ln.strip())
    assert seqs == sorted(seqs)
    # the newest events survived rotation (only the oldest were dropped)
    assert seqs[-1] == 400


# -- e2e: spans over a real in-process cluster --------------------------------

def test_mine_records_cross_node_span_tree_and_stitches():
    SPANS.reset()
    s = Stack(2, failure_policy="reassign")
    try:
        client = s.new_client("client1")
        res = mine_and_wait(client, b"\x60\x02", 2)
        assert res.error is None
        tid = _trace_id(res)
        spans = SPANS.spans_for(tid)
        names = _names(spans)
        assert {"powlib.mine", "coord.mine", "coord.fanout",
                "coord.first_result", "coord.cancel_storm",
                "worker.solve", "worker.result_forward"} <= names
        mine_span = [x for x in spans if x["name"] == "coord.mine"][0]
        assert mine_span["attrs"]["path"] == "miss"
        # solve spans carry the shard attribution the forensics verdict
        # ranks on
        shards = {x["attrs"]["shard"] for x in spans
                  if x["name"] == "worker.solve"}
        assert shards == {0, 1}

        # fetch over the REAL RPC surface and stitch
        addrs = [s.coord_client_addr] + [w.bound_addr for w in s.workers]
        fetched = fetch_spans(addrs, trace_id=tid, deadline_s=5.0)
        assert not fetched["unreachable"]
        tl = stitch_timeline(fetched, tid)
        # every node answered with the (shared in-process) ring's union:
        # the stitch must dedup to distinct spans — never 4x copies.
        # (Late forwarder acks may legally land between the local read
        # and the fetch, so compare against uniqueness, not the earlier
        # snapshot's count.)
        keys = {(x["node"], x["seq"]) for x in tl["spans"]}
        assert len(tl["spans"]) == len(keys)
        assert len(tl["spans"]) >= len(spans)
        assert tl["slow_shard"] in (0, 1)
        assert tl["slowest"]["name"] not in ("powlib.mine", "coord.mine")
        text = render_timeline(tl)
        assert "slow shard" in text and "coord.first_result" in text

        # summaries sweep finds this trace as the slowest recent one
        summary = fetch_spans(addrs, deadline_s=5.0)
        assert slowest_trace_id(summary) == tid

        # exemplars landed on the request histograms with this trace id
        ex = metrics.get_histogram("coord.mine_s.miss")["exemplars"]
        assert any(e[1] == tid for e in ex)
    finally:
        s.close()


def test_forensics_fetch_reports_unreachable_nodes():
    fetched = fetch_spans(["127.0.0.1:1"], trace_id=1, deadline_s=1.0)
    assert fetched["nodes"] == {}
    assert "127.0.0.1:1" in fetched["unreachable"]
    tl = stitch_timeline(fetched, 1)
    assert tl["spans"] == [] and tl["unreachable"]


class _GatedFinder:
    """Blocks every search on a release event, then solves (or honors
    cancellation) — holds a round open so a duplicate can coalesce."""

    def __init__(self):
        self.release = threading.Event()
        self.entered = threading.Event()

    def search(self, nonce, difficulty, thread_bytes, cancel_check=None):
        self.entered.set()
        while not self.release.wait(0.01):
            if cancel_check and cancel_check():
                return None
        if cancel_check and cancel_check():
            return None
        return puzzle.python_search(nonce, difficulty, thread_bytes)


def test_coalesced_waiter_span_completeness():
    """PR 4 hard path: the waiter's trace must still carry a complete
    ``coord.mine`` span — tagged coalesced — even though it never led a
    fan-out; the leader's trace carries the round spans."""
    SPANS.reset()
    s = Stack(1, failure_policy="reassign")
    gate = _GatedFinder()
    try:
        s.workers[0].handler.backend = gate
        client = s.new_client("client1")
        c0 = metrics.get("sched.coalesced_requests")
        client.mine(b"\x61\x01", 1)
        assert gate.entered.wait(10.0)
        client.mine(b"\x61\x01", 1)  # identical: coalesces as a waiter
        deadline = time.monotonic() + 10.0
        while metrics.get("sched.coalesced_requests") == c0:
            assert time.monotonic() < deadline, "duplicate never coalesced"
            time.sleep(0.01)
        gate.release.set()
        r1 = client.notify_queue.get(timeout=30)
        r2 = client.notify_queue.get(timeout=30)
        assert r1.error is None and r2.error is None
        tids = {_trace_id(r1), _trace_id(r2)}
        assert len(tids) == 2
        waiter = leader = None
        for tid in tids:
            spans = SPANS.spans_for(tid)
            mine = [x for x in spans if x["name"] == "coord.mine"]
            assert len(mine) == 1, f"trace {tid} missing its mine span"
            if mine[0]["attrs"].get("coalesced"):
                waiter = (tid, spans, mine[0])
            else:
                leader = (tid, spans, mine[0])
        assert waiter is not None and leader is not None
        assert waiter[2]["attrs"]["path"] == "hit"
        assert "coord.fanout" not in _names(waiter[1])
        assert {"coord.fanout", "coord.first_result",
                "coord.cancel_storm"} <= _names(leader[1])
    finally:
        s.close()


def test_mid_round_reassignment_records_span():
    """PR 8 hard path: a dead worker's shard moving to a live one must
    leave a ``coord.reassign`` marker on the request's timeline."""
    SPANS.reset()
    s = Stack(2, failure_policy="reassign", failure_probe_secs=0.2)
    try:
        s.workers[1].shutdown()  # shard 1's owner is gone before fan-out
        client = s.new_client("client1")
        res = mine_and_wait(client, b"\x62\x03", 1, timeout=30)
        assert res.error is None
        tid = _trace_id(res)
        spans = SPANS.spans_for(tid)
        re_spans = [x for x in spans if x["name"] == "coord.reassign"]
        assert re_spans, f"no reassign span in {_names(spans)}"
        assert re_spans[0]["attrs"]["shard"] == 1
        assert re_spans[0]["attrs"]["to_byte"] == 0
    finally:
        s.close()


def test_hedged_duplicate_shard_records_span():
    """PR 8 hard path: a straggler's hedged duplicate shard must leave
    a ``fleet.hedge`` marker on the request timeline naming owner and
    target, and the round's solve span comes from the hedge target."""
    from fleet_helpers import ShardGatedBackend
    from test_fleet import _elastic_worker

    SPANS.reset()
    owner = helper = None
    s = Stack(0, failure_policy="reassign", failure_probe_secs=0.2,
              coord_extra={"FleetLeaseTTLS": 30.0,
                           "FleetHedgeMultiple": 2.0})
    try:
        owner = _elastic_worker(s, "owner", heartbeat_s=0.1)
        helper = _elastic_worker(s, "helper", heartbeat_s=0.1)
        # n=2 split: owner (registered first) owns 0..127 — the only
        # shard ShardGatedBackend can solve
        owner.handler.backend = ShardGatedBackend(frozen=True)
        helper.handler.backend = ShardGatedBackend()
        owner.fleet_agent.pause()  # beats stop: hedge-stale soon
        time.sleep(0.3)
        client = s.new_client("client1")
        res = mine_and_wait(client, b"\x66\x03", 2, timeout=20)
        assert res.error is None
        tid = _trace_id(res)
        spans = SPANS.spans_for(tid)
        hedges = [x for x in spans if x["name"] == "fleet.hedge"]
        assert hedges, f"no hedge span in {_names(spans)}"
        assert hedges[0]["attrs"]["shard"] == 0
        assert hedges[0]["attrs"]["owner_byte"] != \
            hedges[0]["attrs"]["target_byte"]
        # the hedge target's solve span carries the duplicated shard
        solves = [x for x in spans if x["name"] == "worker.solve"
                  and x["attrs"].get("outcome") == "found"]
        assert any(x["attrs"]["shard"] == 0 and x["node"] == "helper"
                   for x in solves)
        owner.fleet_agent.resume()
    finally:
        for w in (owner, helper):
            if w is not None:
                w.shutdown()
        s.close()


def test_slow_request_auto_capture_e2e():
    """A Mine slower than ForensicsSlowS lands a forensics.slow_request
    flight-recorder event carrying the span tree."""
    SPANS.reset()
    s = Stack(1, failure_policy="reassign",
              coord_extra={"ForensicsSlowS": 0.05})
    gate = _GatedFinder()
    try:
        s.workers[0].handler.backend = gate
        client = s.new_client("client1")
        cap0 = metrics.get("forensics.slow_captures")
        client.mine(b"\x63\x01", 1)
        assert gate.entered.wait(10.0)
        time.sleep(0.1)  # hold the round past the 50 ms budget
        gate.release.set()
        res = client.notify_queue.get(timeout=30)
        assert res.error is None
        tid = _trace_id(res)
        assert metrics.get("forensics.slow_captures") == cap0 + 1
        evs = [e for e in RECORDER.recent()
               if e["kind"] == "forensics.slow_request"
               and e["trace_id"] == tid]
        assert len(evs) == 1
        ev = evs[0]
        assert ev["reason"] == "threshold" and ev["dur_s"] >= 0.05
        assert {"coord.fanout", "worker.solve"} <= _names(ev["spans"])
        json.dumps(ev)  # the capture must be journal/dump-able
    finally:
        s.close()


def test_sched_slot_span_records_residency():
    """The scheduler's cross-thread slot span (the tree's one justified
    SPANS.begin) finishes with launches/preemptions/outcome."""
    from distpow_tpu.sched.engine import BatchingScheduler

    SPANS.reset()
    sched = BatchingScheduler(batch_size=1 << 14, max_slots=2)
    try:
        with SPANS.bind(424242, "w-test"):
            secret = sched.search(b"\x64\x01", 1, list(range(256)))
        assert secret is not None
        (slot_span,) = [x for x in SPANS.spans_for(424242)
                        if x["name"] == "sched.slot"]
        assert slot_span["node"] == "w-test"
        assert slot_span["attrs"]["outcome"] == "found"
        assert slot_span["attrs"]["launches"] >= 1
        assert slot_span["attrs"]["preemptions"] == 0
    finally:
        sched.close()


def test_spans_rpc_summaries_over_rpc():
    SPANS.reset()
    s = Stack(1)
    try:
        client = s.new_client("client1")
        res = mine_and_wait(client, b"\x65\x02", 1)
        tid = _trace_id(res)
        from distpow_tpu.runtime.rpc import RPCClient

        c = RPCClient(s.coord_client_addr)
        try:
            reply = c.call("Node.Spans", {}, timeout=5.0)
            assert reply["node"] == "coordinator"
            assert any(t["trace_id"] == tid for t in reply["traces"])
            reply = c.call("Node.Spans", {"trace_id": tid, "limit": 4},
                           timeout=5.0)
            assert 0 < len(reply["spans"]) <= 4
        finally:
            c.close()
    finally:
        s.close()


def test_slo_breach_dump_attaches_slow_request_timelines(tmp_path):
    """ISSUE 14: breach evidence carries the top-k slowest request
    span trees, not just round milestones."""
    from distpow_tpu.obs.slo import SLOEngine, load_slo_config

    SPANS.reset()
    SPANS.record("coord.mine", time.time(), 3.0, trace_id=91, node="c",
                 path="miss")
    SPANS.record("worker.solve", time.time(), 2.5, trace_id=91, node="w",
                 shard=2)
    SPANS.record("coord.mine", time.time(), 0.5, trace_id=92, node="c",
                 path="miss")
    RECORDER.reset()
    RECORDER.configure(dump_dir=str(tmp_path))
    h = Histogram()
    for _ in range(20):
        h.observe(5.0)
    merged = {"ts": 1.0, "counters": {},
              "histograms": {"coord.mine_s.miss": h.to_dict()},
              "stale_nodes": []}
    cfg = load_slo_config({"objectives": [
        {"name": "p95", "histogram": "coord.mine_s.miss",
         "stat": "p95", "max": 1.0}]})
    v = SLOEngine(cfg).evaluate(merged)
    assert v.status == "breach" and v.dump_path
    payload = json.loads(open(v.dump_path).read())
    slow = payload["extra"]["slow_requests"]
    assert slow[0]["trace_id"] == 91  # slowest first
    assert any(sp["name"] == "worker.solve" for sp in slow[0]["spans"])


def test_slowest_request_timelines_over_rpc():
    """The cross-process twin of SPANS.slowest_traces: rank remote
    traces from a summaries sweep, then fetch each tree."""
    from distpow_tpu.obs.forensics import slowest_request_timelines

    SPANS.reset()
    s = Stack(1)
    try:
        client = s.new_client("client1")
        res = mine_and_wait(client, b"\x67\x04", 1)
        tid = _trace_id(res)
        out = slowest_request_timelines([s.coord_client_addr], k=3,
                                        deadline_s=5.0)
        assert out and out[0]["trace_id"] == tid
        assert any(sp["name"] == "coord.mine" for sp in out[0]["spans"])
    finally:
        s.close()


def test_slo_breach_sweeps_remote_spans_when_local_ring_empty(
        tmp_path, monkeypatch):
    """The production gate process (cli/slo.py) has no local span ring:
    on breach the engine must sweep the scraped fleet's Node.Spans for
    the slow-request evidence instead of silently attaching nothing
    (review PR 9)."""
    import distpow_tpu.obs.forensics as forensics
    from distpow_tpu.obs.slo import SLOEngine, load_slo_config

    canned = [{"trace_id": 7, "dur_s": 2.0,
               "spans": [{"name": "coord.mine", "trace_id": 7}]}]
    swept = {}

    def fake_sweep(addrs, k=5, deadline_s=5.0):
        swept["addrs"] = list(addrs)
        return canned

    monkeypatch.setattr(forensics, "slowest_request_timelines",
                        fake_sweep)
    SPANS.reset()  # the gate process's empty local ring
    RECORDER.reset()
    RECORDER.configure(dump_dir=str(tmp_path))
    h = Histogram()
    for _ in range(20):
        h.observe(5.0)
    merged = {"ts": 1.0, "counters": {},
              "histograms": {"coord.mine_s.miss": h.to_dict()},
              "stale_nodes": []}
    cfg = load_slo_config({"objectives": [
        {"name": "p95", "histogram": "coord.mine_s.miss",
         "stat": "p95", "max": 1.0}]})
    engine = SLOEngine(cfg, span_addrs=["127.0.0.1:9"])
    v = engine.evaluate(merged)
    assert v.status == "breach" and v.dump_path
    assert swept["addrs"] == ["127.0.0.1:9"]
    payload = json.loads(open(v.dump_path).read())
    assert payload["extra"]["slow_requests"] == canned


# -- trace_profile span-ring input format -------------------------------------

def _spans_payload():
    return {
        "format": "spans",
        "trace_id": 5,
        "spans": [
            {"seq": 1, "trace_id": 5, "name": "coord.fanout", "node": "c",
             "ts": 100.0, "dur_s": 0.01,
             "attrs": {"round": "r9", "nonce": "aa", "ntz": 2}},
            {"seq": 2, "trace_id": 5, "name": "coord.first_result",
             "node": "c", "ts": 100.0, "dur_s": 0.2,
             "attrs": {"round": "r9", "nonce": "aa", "ntz": 2,
                       "winner_byte": 1}},
            {"seq": 3, "trace_id": 5, "name": "coord.cancel_storm",
             "node": "c", "ts": 100.2, "dur_s": 0.3,
             "attrs": {"round": "r9", "nonce": "aa", "ntz": 2,
                       "late_results": 1}},
            {"seq": 4, "trace_id": 5, "name": "worker.solve", "node": "w",
             "ts": 100.05, "dur_s": 0.1, "attrs": {"shard": 1}},
        ],
    }


def test_trace_profile_reads_span_ring_json(tmp_path):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "_tp", os.path.join(REPO, "scripts", "trace_profile.py"))
    tp = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tp)

    rounds = tp.profile_spans(_spans_payload())
    assert len(rounds) == 1
    r = rounds[0]
    assert r["round"] == "r9" and r["winner_byte"] == 1
    assert r["first_result_s"] == 0.2
    # cancel_propagation re-assembled: first_result + storm (they tile)
    assert r["cancel_propagation_s"] == 0.5
    assert r["late_results"] == 1

    # and through the CLI: the shared wall-clock renderer
    path = tmp_path / "timeline.json"
    path.write_text(json.dumps(_spans_payload()))
    assert tp.main([str(path)]) == 0
    out = json.loads(
        _capture_main(tp, [str(path), "--json"]))
    assert out["format"] == "spans"
    assert out["rounds"][0]["cancel_propagation_s"] == 0.5


def _capture_main(mod, argv):
    import contextlib
    import io

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        assert mod.main(argv) == 0
    return buf.getvalue()
