"""Elastic fleet membership tests (ISSUE 12, docs/FLEET.md).

Covers the lease registry state machine, the capability-weighted
partition plan, join-under-load, drain-mid-round, straggler hedging
(duplicate-secret parity included), and the real-process membership
chaos: a SIGKILLed elastic worker whose shard is reassigned without
failing the Mine, and a SIGSTOP'd worker riding out its lease then
recovering with a fresh registration (no zombie double-assignment).
"""

import os
import signal
import subprocess
import sys
import threading
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from fleet_helpers import ShardGatedBackend as _ShardGatedBackend  # noqa: E402
from test_nodes import Stack, mine_and_wait  # noqa: E402

from distpow_tpu.backends import PythonBackend  # noqa: E402
from distpow_tpu.fleet import (  # noqa: E402
    Capability,
    FleetRegistry,
    WorkerLease,
)
from distpow_tpu.models import puzzle  # noqa: E402
from distpow_tpu.nodes import Worker  # noqa: E402
from distpow_tpu.nodes.coordinator import WorkerRef  # noqa: E402
from distpow_tpu.parallel import partition  # noqa: E402
from distpow_tpu.runtime.config import (  # noqa: E402
    WorkerConfig,
    read_json_config,
)
from distpow_tpu.runtime.metrics import REGISTRY as metrics  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- registry state machine (no RPC) -----------------------------------------

def _registry(n_static=0, **kw):
    refs = [WorkerRef(f"127.0.0.1:{9000 + i}", i) for i in range(n_static)]
    kw.setdefault("make_ref", WorkerRef)
    return FleetRegistry(refs, **kw), refs


def test_static_workers_are_permanent_leases():
    reg, refs = _registry(3, lease_ttl_s=0.05)
    assert all(r.lease is not None and r.lease.permanent for r in refs)
    time.sleep(0.1)
    assert reg.expire_stale() == []  # permanent leases never expire
    plan = reg.round_plan()
    assert [s for _, s in plan.entries] == [0, 1, 2]
    assert plan.ranges is None  # reference algebra, wire-identical
    assert metrics.get("fleet.live_workers") >= 3


def test_register_heartbeat_expire_cycle():
    reg, refs = _registry(1, lease_ttl_s=0.3)
    before = metrics.get("fleet.lease_expiries")
    grant = reg.register("w-elastic", "127.0.0.1:9100",
                         Capability(backend="python", mhs=2.0))
    assert grant["ttl_s"] == 0.3 and grant["heartbeat_s"] == 0.1
    assert len(reg.refs) == 2
    for _ in range(3):
        time.sleep(0.1)
        assert reg.heartbeat(grant["lease_id"])["ok"]
    assert reg.expire_stale() == []  # beats kept it alive past one TTL
    time.sleep(0.45)
    # the registry's own reaper thread may beat this manual sweep to
    # the expiry — assert the OUTCOME, not which sweep got there first
    reg.expire_stale()
    assert len(reg.refs) == 1  # back to the static member
    assert metrics.get("fleet.lease_expiries") == before + 1
    with pytest.raises(KeyError):
        reg.heartbeat(grant["lease_id"])
    reg.close()


def test_is_stale_reads_beat_clock_under_registry_lock(monkeypatch):
    """``is_stale`` must hold the registry lock while it reads the
    beat clock: ``heartbeat()`` writes ``last_beat`` on RPC handler
    threads, and the original bare read raced it (caught by
    distpow-lint's unguarded-shared-write sweep, ISSUE 17)."""
    reg, _ = _registry(0, lease_ttl_s=30.0)
    reg.register("w1", "127.0.0.1:9300", Capability())
    ref = reg.refs[0]
    seen = []
    real = WorkerLease.beat_age

    def spying_beat_age(self, now):
        seen.append(reg._lock.locked())
        return real(self, now)

    monkeypatch.setattr(WorkerLease, "beat_age", spying_beat_age)
    assert reg.is_stale(ref, threshold_s=1e9) is False
    assert seen and all(seen), "beat clock read without the registry lock"
    reg.close()


def test_reregistration_retires_the_stale_twin():
    reg, _ = _registry(0, lease_ttl_s=30.0)
    g1 = reg.register("w1", "127.0.0.1:9200", Capability())
    g2 = reg.register("w1", "127.0.0.1:9201", Capability())
    assert g1["lease_id"] != g2["lease_id"]
    members = reg.members()
    assert len(members) == 1  # no zombie double-assignment
    assert members[0]["addr"] == "127.0.0.1:9201"
    with pytest.raises(KeyError):
        reg.heartbeat(g1["lease_id"])  # the old lease is gone
    reg.close()


def test_drain_waits_for_inflight_rounds():
    reg, _ = _registry(0, lease_ttl_s=30.0)
    grant = reg.register("w1", "127.0.0.1:9300", Capability())
    ref = reg.refs[0]
    reg.track_round([ref], +1)
    t = threading.Thread(
        target=lambda: time.sleep(0.3) or reg.track_round([ref], -1))
    t.start()
    t0 = time.monotonic()
    out = reg.drain(grant["lease_id"], timeout_s=5.0)
    assert out["drained"] is True
    assert time.monotonic() - t0 >= 0.25  # waited the round out
    assert reg.refs == []
    t.join()
    reg.close()


def test_drain_outlasting_the_ttl_is_not_expired_mid_drain():
    """The agent stops heartbeating BEFORE it calls Fleet.Drain, so a
    drain that outlasts the lease TTL must not be expired mid-drain —
    that would crash out the exact worker the graceful path is
    finishing, and double-count the departure (review PR 8)."""
    reg, _ = _registry(0, lease_ttl_s=0.2)
    grant = reg.register("w1", "127.0.0.1:9350", Capability())
    ref = reg.refs[0]
    reg.track_round([ref], +1)
    expiries0 = metrics.get("fleet.lease_expiries")
    t = threading.Thread(
        target=lambda: time.sleep(0.6) or reg.track_round([ref], -1))
    t.start()
    out = reg.drain(grant["lease_id"], timeout_s=5.0)  # 3x the TTL
    assert out["drained"] is True
    assert metrics.get("fleet.lease_expiries") == expiries0
    t.join()
    reg.close()


def test_drain_rejects_static_and_bounds_the_wait():
    reg, refs = _registry(1, lease_ttl_s=30.0)
    with pytest.raises(ValueError):
        reg.drain(refs[0].lease.lease_id)
    grant = reg.register("w1", "127.0.0.1:9400", Capability())
    reg.track_round([reg.refs[1]], +1)  # never released
    out = reg.drain(grant["lease_id"], timeout_s=0.2)
    assert out["drained"] is False and out["pending_rounds"] == 1
    assert len(reg.refs) == 1  # released anyway, bounded
    reg.close()


# -- weighted partition plan -------------------------------------------------

def test_equal_weights_reproduce_reference_split_exactly():
    for n in (1, 2, 3, 4, 5, 7, 8, 9, 16):
        ranges = partition.weighted_ranges([3.5] * n)
        bits = partition.worker_bits(n)
        for wb, (lo, count) in enumerate(ranges):
            tbs = partition.thread_bytes(wb, bits)
            assert (lo, count) == (tbs[0], len(tbs)), (n, wb)


def test_skewed_weights_give_fast_worker_proportional_space():
    ranges = partition.weighted_ranges([4.0, 1.0])
    (lo_f, n_f), (lo_s, n_s) = ranges
    assert n_f >= 3 * n_s  # the 4:1 acceptance floor
    covered = set(range(lo_f, lo_f + n_f)) | set(range(lo_s, lo_s + n_s))
    assert covered == set(range(256))  # full disjoint cover
    assert n_f + n_s == 256
    # 4-way skew: every positive weight keeps at least one byte
    r4 = partition.weighted_ranges([100.0, 0.001, 0.001, 0.001])
    assert sum(c for _, c in r4) == 256
    assert all(c >= 1 for _, c in r4)
    assert r4[0][1] >= 3 * max(c for _, c in r4[1:])


def test_weighted_ranges_rejects_bad_inputs():
    with pytest.raises(ValueError):
        partition.weighted_ranges([])
    with pytest.raises(ValueError):
        partition.weighted_ranges([1.0, 0.0])
    with pytest.raises(ValueError):
        partition.weighted_ranges([1.0, -2.0])
    with pytest.raises(ValueError):
        partition.weighted_ranges([1.0, float("nan")])
    with pytest.raises(ValueError):
        # unequal weights across > 256 workers cannot each own a byte
        partition.weighted_ranges([1.0] * 256 + [2.0])


def test_round_plan_weighted_only_when_all_rates_known():
    reg, _ = _registry(1, lease_ttl_s=30.0)  # static member: unknown rate
    reg.register("w1", "127.0.0.1:9500", Capability(mhs=8.0))
    plan = reg.round_plan()
    assert plan.ranges is None  # any unknown rate -> reference split
    reg2, _ = _registry(0, lease_ttl_s=30.0)
    reg2.register("fast", "127.0.0.1:9501", Capability(mhs=8.0))
    reg2.register("slow", "127.0.0.1:9502", Capability(mhs=2.0))
    plan2 = reg2.round_plan()
    assert plan2.ranges is not None
    assert plan2.ranges[0][1] >= 3 * plan2.ranges[1][1]
    assert plan2.mine_extra(0) == {"tb_lo": plan2.ranges[0][0],
                                   "tb_count": plan2.ranges[0][1]}
    # draining members leave the next plan
    reg2.register("third", "127.0.0.1:9503", Capability(mhs=2.0))
    lease = reg2.refs[-1].lease
    lease.state = "draining"
    assert len(reg2.round_plan().entries) == 2
    reg.close()
    reg2.close()


# -- in-process e2e ----------------------------------------------------------

def _elastic_worker(stack, wid, mhs=0.0, heartbeat_s=0.2, **extra):
    """Boot one FleetRegister worker against the stack's coordinator."""
    from distpow_tpu.runtime.tracing import MemorySink

    w = Worker(
        WorkerConfig(
            WorkerID=wid,
            ListenAddr="127.0.0.1:0",
            CoordAddr=stack.coordinator.worker_addr,
            Backend="python",
            FleetRegister=True,
            FleetHeartbeatS=heartbeat_s,
            FleetCalibrationS=0.0,
            FleetMHS=mhs,
            **extra,
        ),
        sink=MemorySink(),
    )
    w.initialize_rpcs()
    w.start_forwarder()
    w.start_fleet_agent()
    assert w.fleet_agent.wait_registered(timeout=10.0), "registration hung"
    return w


def _count_mines(worker):
    """Wrap a worker's Mine handler with a call recorder."""
    calls = []
    orig = worker.handler.Mine

    def wrapped(params):
        calls.append(dict(params))
        return orig(params)

    worker.handler.Mine = wrapped
    return calls


def test_join_under_load_elastic_worker_serves():
    """A worker started AFTER the cluster is up joins via
    Fleet.Register, receives shards in subsequent rounds, and the
    rounds keep succeeding throughout (join-under-load)."""
    s = Stack(2, failure_policy="reassign", failure_probe_secs=0.2)
    extra = None
    try:
        client = s.new_client("client1")
        joins0 = metrics.get("fleet.joins")
        # traffic before, during and after the join; distinct nonces so
        # every request is a real fan-out round
        res = mine_and_wait(client, b"\x31\x01", 2)
        assert res.error is None
        extra = _elastic_worker(s, "elastic1")
        calls = _count_mines(extra)
        for i in range(6):
            res = mine_and_wait(client, bytes([0x32, i]), 2)
            assert res.error is None
            assert puzzle.check_secret(res.nonce, res.secret, 2)
            if calls:
                break
        assert calls, "elastic worker never received a shard"
        assert metrics.get("fleet.joins") == joins0 + 1
        members = s.coordinator.handler.fleet.members()
        assert len(members) == 3
        assert any(m.get("worker_id") == "elastic1" for m in members)
        # the agent observed heartbeat round trips (the first beat
        # lands one full interval after registration by design — the
        # cadence EMA must never see a near-zero first gap)
        deadline = time.time() + 5
        while time.time() < deadline:
            snap = metrics.snapshot()
            if snap["histograms"].get("fleet.heartbeat_rtt_s", {}) \
                    .get("count", 0) >= 1:
                break
            time.sleep(0.05)
        assert snap["histograms"].get("fleet.heartbeat_rtt_s", {}) \
            .get("count", 0) >= 1
    finally:
        if extra is not None:
            extra.shutdown()
        s.close()


def test_weighted_rounds_carry_explicit_ranges_end_to_end():
    """A pure-elastic fleet with a 4:1 advertised-rate skew fans out
    explicit (tb_lo, tb_count) ranges: the fast worker owns >= 3x the
    first-byte space, coverage is exact, and the mined secret still
    verifies."""
    s = Stack(0, failure_policy="reassign", failure_probe_secs=0.2)
    fast = slow = None
    try:
        fast = _elastic_worker(s, "fast", mhs=8.0)
        slow = _elastic_worker(s, "slow", mhs=2.0)
        fast_calls = _count_mines(fast)
        slow_calls = _count_mines(slow)
        client = s.new_client("client1")
        res = mine_and_wait(client, b"\x41\x02", 2)
        assert res.error is None
        assert puzzle.check_secret(res.nonce, res.secret, 2)
        assert fast_calls and slow_calls
        f, sl = fast_calls[0], slow_calls[0]
        assert f["tb_count"] >= 3 * sl["tb_count"]
        covered = set(range(f["tb_lo"], f["tb_lo"] + f["tb_count"]))
        covered |= set(range(sl["tb_lo"], sl["tb_lo"] + sl["tb_count"]))
        assert covered == set(range(256))
    finally:
        for w in (fast, slow):
            if w is not None:
                w.shutdown()
        s.close()


def test_straggler_shard_is_hedged_and_duplicate_secret_verifies():
    """One silent straggler out of two elastic workers: its heartbeats
    stop (agent.pause) and its backend is frozen, so only a hedged
    duplicate of its shard can finish the round.  The duplicate's
    secret must pass the exact verification the original shard's owner
    would have produced (hedged-shard parity)."""
    owner = helper = None
    s = Stack(0, failure_policy="reassign", failure_probe_secs=0.2,
              coord_extra={"FleetLeaseTTLS": 30.0,
                           "FleetHedgeMultiple": 2.0})
    try:
        owner = _elastic_worker(s, "owner", heartbeat_s=0.1)
        helper = _elastic_worker(s, "helper", heartbeat_s=0.1)
        # n=2 split: owner (registered first) owns 0..127 — the only
        # shard _ShardGatedBackend can solve
        owner.handler.backend = _ShardGatedBackend(frozen=True)
        helper.handler.backend = _ShardGatedBackend()
        hedged0 = metrics.get("fleet.hedged_shards")
        owner.fleet_agent.pause()  # beats stop: hedge-stale soon
        time.sleep(0.3)
        client = s.new_client("client1")
        t0 = time.monotonic()
        res = mine_and_wait(client, b"\x51\x03", 2, timeout=20)
        wall = time.monotonic() - t0
        assert res.error is None
        assert puzzle.check_secret(res.nonce, res.secret, 2)
        assert metrics.get("fleet.hedged_shards") >= hedged0 + 1
        assert wall < 10.0, f"hedged round took {wall:.1f}s"
        owner.fleet_agent.resume()
    finally:
        for w in (owner, helper):
            if w is not None:
                w.shutdown()
        s.close()


def test_drain_mid_round_completes_the_shard():
    """Fleet.Drain during an in-flight round blocks until the draining
    worker's shard completes, the round succeeds with its secret, and
    the member then leaves cleanly."""
    finder = waiter = None
    s = Stack(0, failure_policy="reassign", failure_probe_secs=0.2,
              coord_extra={"FleetLeaseTTLS": 30.0})
    try:
        finder = _elastic_worker(s, "finder")
        waiter = _elastic_worker(s, "waiter")
        finder.handler.backend = _ShardGatedBackend(solve_delay_s=0.8)
        waiter.handler.backend = _ShardGatedBackend()
        drains0 = metrics.get("fleet.drains")
        client = s.new_client("client1")
        client.mine(b"\x61\x04", 2)
        time.sleep(0.3)  # fan-out is in flight; finder is mid-solve
        out = finder.fleet_agent.stop(drain=True)
        res = client.notify_queue.get(timeout=20)
        assert res.error is None
        assert puzzle.check_secret(res.nonce, res.secret, 2)
        assert out.get("skipped") is False
        assert out.get("drained") is True, out
        assert metrics.get("fleet.drains") == drains0 + 1
        members = s.coordinator.handler.fleet.members()
        assert all(m.get("worker_id") != "finder" for m in members)
        finder.fleet_agent = None  # already stopped; skip shutdown drain
    finally:
        for w in (finder, waiter):
            if w is not None:
                w.shutdown()
        s.close()


# -- real-process membership chaos -------------------------------------------

def _spawn_child(coord_addr, heartbeat_s=0.2, worker_id="elasticworker"):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    child = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tests", "fleet_worker_child.py"),
         coord_addr, str(heartbeat_s), worker_id],
        cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    deadline = time.time() + 30
    lines = []
    while time.time() < deadline:
        line = child.stdout.readline()
        if not line:
            raise AssertionError(
                f"child exited rc={child.poll()}: {''.join(lines)[-1500:]}")
        lines.append(line)
        if line.startswith("WORKER_READY"):
            return child
    child.kill()
    raise AssertionError(f"child never became ready: {''.join(lines)[-1500:]}")


def test_sigkill_mid_round_lease_expiry_reassigns_without_failing_mine():
    """Acceptance e2e: a worker started after the cluster is up joins,
    receives shards and contributes the winning secret; SIGKILLing it
    mid-round is detected and its shard reassigned — the Mine still
    succeeds — and its lease expires out of the membership table with
    no coordinator restart."""
    s = Stack(1, failure_policy="reassign", failure_probe_secs=0.2,
              coord_extra={"FleetLeaseTTLS": 1.0})
    child = None
    try:
        client = s.new_client("client1")
        # the static worker cannot solve: only the elastic child can
        s.workers[0].handler.backend = _ShardGatedBackend(frozen=True)
        child = _spawn_child(s.coordinator.worker_addr, heartbeat_s=0.2)
        expiries0 = metrics.get("fleet.lease_expiries")
        res = mine_and_wait(client, b"\x71\x05", 2, timeout=30)
        assert res.error is None
        assert puzzle.check_secret(res.nonce, res.secret, 2)
        # round 2: both may solve again (static worker restored), the
        # child is killed right after fan-out starts
        s.workers[0].handler.backend = PythonBackend()
        client.mine(b"\x72\x05", 4)
        time.sleep(0.05)
        os.kill(child.pid, signal.SIGKILL)
        res = client.notify_queue.get(timeout=60)
        assert res.error is None, f"Mine failed after SIGKILL: {res.error}"
        assert puzzle.check_secret(res.nonce, res.secret, 4)
        # lease expiry retires the vanished worker from membership
        deadline = time.time() + 10
        while time.time() < deadline:
            if metrics.get("fleet.lease_expiries") > expiries0:
                break
            time.sleep(0.1)
        assert metrics.get("fleet.lease_expiries") > expiries0
        members = s.coordinator.handler.fleet.members()
        assert all(m.get("worker_id") != "elasticworker" for m in members)
    finally:
        if child is not None and child.poll() is None:
            child.kill()
        s.close()


@pytest.mark.slow
def test_sigstop_rides_out_lease_and_reregisters_fresh():
    """SIGSTOP a registered worker past its TTL: the lease expires (it
    leaves membership); on SIGCONT the agent's heartbeat earns an
    unknown-lease error and re-registers FRESH — exactly one membership
    entry, no zombie double-assignment — and the fleet serves again."""
    s = Stack(1, failure_policy="reassign", failure_probe_secs=0.2,
              coord_extra={"FleetLeaseTTLS": 1.0})
    child = None
    try:
        client = s.new_client("client1")
        child = _spawn_child(s.coordinator.worker_addr, heartbeat_s=0.2,
                             worker_id="stopper")
        joins0 = metrics.get("fleet.joins")
        expiries0 = metrics.get("fleet.lease_expiries")
        os.kill(child.pid, signal.SIGSTOP)
        try:
            deadline = time.time() + 10
            while time.time() < deadline:
                if metrics.get("fleet.lease_expiries") > expiries0:
                    break
                time.sleep(0.1)
            assert metrics.get("fleet.lease_expiries") > expiries0
            assert all(
                m.get("worker_id") != "stopper"
                for m in s.coordinator.handler.fleet.members())
        finally:
            os.kill(child.pid, signal.SIGCONT)
        deadline = time.time() + 15
        while time.time() < deadline:
            members = [m for m in s.coordinator.handler.fleet.members()
                       if m.get("worker_id") == "stopper"]
            if members:
                break
            time.sleep(0.1)
        assert len(members) == 1, members  # fresh lease, no zombie twin
        assert metrics.get("fleet.joins") >= joins0 + 1
        res = mine_and_wait(client, b"\x81\x06", 2, timeout=30)
        assert res.error is None
        assert puzzle.check_secret(res.nonce, res.secret, 2)
    finally:
        if child is not None and child.poll() is None:
            import contextlib

            with contextlib.suppress(OSError):
                os.kill(child.pid, signal.SIGCONT)
            child.kill()
        s.close()


# -- config + discovery satellites -------------------------------------------

def test_fleet_config_fields_round_trip(tmp_path):
    from distpow_tpu.cli import config_gen
    from distpow_tpu.runtime.config import CoordinatorConfig

    config_gen.main(["--config-dir", str(tmp_path), "--workers", "2",
                     "--seed", "7", "--elastic"])
    import json

    raw = json.loads((tmp_path / "worker_config.json").read_text())
    for key in ("FleetRegister", "FleetHeartbeatS", "FleetCalibrationS",
                "FleetMHS", "FleetDrainTimeoutS"):
        assert key in raw, f"config_gen did not emit {key}"
    assert raw["FleetRegister"] is True
    craw = json.loads((tmp_path / "coordinator_config.json").read_text())
    for key in ("FleetLeaseTTLS", "FleetHedge", "FleetHedgeMultiple",
                "FleetDrainTimeoutS"):
        assert key in craw, f"config_gen did not emit {key}"
    wc = read_json_config(str(tmp_path / "worker_config.json"), WorkerConfig)
    assert wc.FleetRegister is True and wc.FleetHeartbeatS == 0.0
    cc = read_json_config(str(tmp_path / "coordinator_config.json"),
                          CoordinatorConfig)
    assert cc.FleetLeaseTTLS == 10.0 and cc.FleetHedge is True


def test_stats_discover_scrapes_live_membership(capsys):
    """`stats --cluster --discover <coordinator>` pulls the membership
    table instead of needing a hand-maintained --addr list, and the
    sweep covers coordinator + every member."""
    import json

    from distpow_tpu.cli import stats as stats_cli

    s = Stack(1, failure_policy="reassign", failure_probe_secs=0.2)
    extra = None
    try:
        extra = _elastic_worker(s, "disco")
        rc = stats_cli.main(["--cluster", "--discover",
                             s.coord_client_addr, "--deadline", "5"])
        out = capsys.readouterr().out
        cluster = json.loads(out)
        assert rc == 0, cluster.get("stale_nodes")
        per_node = cluster["per_node"]
        assert s.coord_client_addr in per_node
        assert len(per_node) == 3  # coordinator + static + elastic
        roles = sorted(m.get("role") for m in per_node.values())
        assert roles.count("worker") == 2
    finally:
        if extra is not None:
            extra.shutdown()
        s.close()
