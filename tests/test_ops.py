"""Ops-layer tests: packing, difficulty masks, fused search step."""

import hashlib
import random

import jax.numpy as jnp
import numpy as np
import pytest

from distpow_tpu.models import puzzle
from distpow_tpu.models.registry import (
    BLAKE2B_256,
    MD5,
    RIPEMD160,
    SHA1,
    SHA3_256,
    SHA256,
    SHA512,
)
from distpow_tpu.ops.difficulty import meets_difficulty, nibble_masks
from distpow_tpu.ops.packing import build_tail_spec, make_words, pack_reference_bytes
from distpow_tpu.ops.search_step import (
    SENTINEL,
    build_search_step,
    flat_to_candidate,
)


def digest_of(spec, model, tb, chunk):
    state = spec.init_state
    for b in range(spec.n_blocks):
        words = make_words(spec, jnp.uint32(tb), jnp.uint32(chunk))[b]
        state = model.compress(state, words)
    return model.state_to_digest(state)


@pytest.mark.parametrize("model", [
    MD5, SHA256, SHA1,
    # 36 eager loop-form compiles; the fast path keeps sha512 packing
    # covered via test_sha512_jax_vs_hashlib + the search-layer tests
    pytest.param(SHA512, marks=pytest.mark.slow),
])
@pytest.mark.parametrize("nonce_len", [0, 4, 20, 54, 55, 63, 64, 65, 130])
@pytest.mark.parametrize("width", [0, 1, 3, 4])
def test_packing_matches_hashlib(model, nonce_len, width):
    rng = random.Random(nonce_len * 7 + width)
    nonce = bytes(rng.randrange(256) for _ in range(nonce_len))
    spec = build_tail_spec(nonce, width, model)
    for _ in range(3):
        tb = rng.randrange(256)
        chunk = rng.randrange(256 ** width) if width else 0
        msg = pack_reference_bytes(nonce, tb, chunk, width)
        expect = model.hashlib_new()
        expect.update(msg)
        assert digest_of(spec, model, tb, chunk) == expect.digest()


def test_sha256d_tail_spec_identical_to_sha256():
    """Composition must not leak into packing: sha256d's tail spec is
    byte-identical to sha256's at every layout (same padding family,
    block geometry, byte orders, init state) — the finalize stage is
    the ONLY difference between the two models' device programs."""
    from distpow_tpu.models.registry import get_hash_model

    sha256d = get_hash_model("sha256d")
    rng = random.Random(0xD0)
    for nonce_len in (0, 4, 55, 64, 70, 130):
        nonce = bytes(rng.randrange(256) for _ in range(nonce_len))
        for width in (0, 2, 4):
            a = build_tail_spec(nonce, width, SHA256)
            b = build_tail_spec(nonce, width, sha256d)
            assert a.init_state == b.init_state
            assert a.n_blocks == b.n_blocks
            assert a.base_words == b.base_words
            assert a.tb_loc == b.tb_loc and a.chunk_locs == b.chunk_locs
    # ...and the composed digest check itself is pinned elsewhere
    # (test_hash_models.test_sha256d_registry_and_finalize, the fuzz)


def test_packing_extra_const_chunk():
    # width > 4 support: high chunk bytes folded into the constant template
    nonce = b"\x01\x02\x03\x04"
    extra = b"\x09\x02"
    spec = build_tail_spec(nonce, 4, MD5, extra_const_chunk=extra)
    msg = pack_reference_bytes(nonce, 7, 0xDEADBEEF, 4, extra)
    assert digest_of(spec, MD5, 7, 0xDEADBEEF) == hashlib.md5(msg).digest()
    assert len(msg) == 4 + 1 + 4 + 2


@pytest.mark.parametrize("model", [SHA3_256, BLAKE2B_256])
@pytest.mark.parametrize("nonce_len", [0, 4, 130, 135, 260])
@pytest.mark.parametrize("width", [0, 2, 4])
def test_packing_matches_hashlib_sponge_and_params(model, nonce_len, width):
    """The two non-Merkle-Damgard packing families through the full
    template path: sha3's pad10*1 and blake2's zero-fill + baked (t, f)
    parameter words, across host-absorption boundaries (nonce lengths
    bracket the 128/136-byte blocks) and chunk widths — including the
    width>4 extra-const-chunk mechanism, whose bytes must count into
    blake2's byte counter."""
    rng = random.Random(nonce_len * 13 + width)
    nonce = bytes(rng.randrange(256) for _ in range(nonce_len))
    for extra in (b"", b"\x09\x02"):
        spec = build_tail_spec(nonce, width, model, extra_const_chunk=extra)
        for _ in range(2):
            tb = rng.randrange(256)
            chunk = rng.randrange(256 ** width) if width else 0
            msg = pack_reference_bytes(nonce, tb, chunk, width, extra)
            expect = model.hashlib_new()
            expect.update(msg)
            assert digest_of(spec, model, tb, chunk) == expect.digest(), (
                model.name, nonce_len, width, extra
            )


@pytest.mark.parametrize("model", [MD5, SHA256, SHA1, SHA512])
def test_nibble_masks_vs_oracle(model):
    rng = random.Random(42)
    for _ in range(300):
        digest = bytes(
            rng.choice([0, 0, rng.randrange(256)])
            for _ in range(model.digest_bytes)
        )
        words = tuple(
            jnp.uint32(
                int.from_bytes(digest[4 * i : 4 * i + 4], model.word_byteorder)
            )
            for i in range(model.digest_words)
        )
        true_k = puzzle.count_trailing_zero_nibbles(digest)
        for k in (0, 1, true_k, true_k + 1, model.max_difficulty):
            if k > model.max_difficulty:
                with pytest.raises(ValueError):
                    nibble_masks(k, model)
                continue
            ok = bool(meets_difficulty(words, nibble_masks(k, model)))
            assert ok == (true_k >= k), (digest.hex(), k, true_k)


def test_search_step_finds_reference_first_match():
    nonce = b"\x01\x02\x03\x04"
    difficulty = 2
    tbs = list(range(256))
    # oracle: first match in reference enumeration order within width<=2
    oracle = puzzle.python_search(nonce, difficulty, tbs)
    assert oracle is not None

    # width-0 step
    step0 = build_search_step(nonce, 0, difficulty, 0, 256, 1, MD5)
    f0 = int(step0(jnp.uint32(0)))
    # width-1 step covering chunks [1, 256)
    step1 = build_search_step(nonce, 1, difficulty, 0, 256, 255, MD5)
    f1 = int(step1(jnp.uint32(1)))

    if f0 != SENTINEL:
        chunk, tb = flat_to_candidate(f0, 0, 0, 256)
        secret = bytes([tb])
    else:
        assert f1 != SENTINEL
        chunk, tb = flat_to_candidate(f1, 1, 0, 256)
        secret = bytes([tb]) + puzzle.int_to_chunk(chunk)
    assert secret == oracle


def test_search_step_no_false_positives_at_high_difficulty():
    step = build_search_step(b"\x05\x06", 1, 30, 0, 256, 16, MD5)
    assert int(step(jnp.uint32(1))) == SENTINEL


def test_search_step_sha256():
    nonce = b"\xaa"
    tbs = list(range(256))
    oracle = puzzle.python_search(nonce, 2, tbs, algo="sha256")
    found = None
    step0 = build_search_step(nonce, 0, 2, 0, 256, 1, SHA256)
    f = int(step0(jnp.uint32(0)))
    if f != SENTINEL:
        found = bytes([f % 256])
    else:
        step1 = build_search_step(nonce, 1, 2, 0, 256, 255, SHA256)
        f = int(step1(jnp.uint32(1)))
        assert f != SENTINEL
        chunk, tb = flat_to_candidate(f, 1, 0, 256)
        found = bytes([tb]) + puzzle.int_to_chunk(chunk)
    assert found == oracle


# ---------------------------------------------------------------------------
# Dynamic (serving-path) regime: cached_search_step binds nonce/difficulty/
# partition as runtime operands onto layout-keyed compiled programs.
# ---------------------------------------------------------------------------

from distpow_tpu.ops.search_step import _dyn_search_step, cached_search_step


# Non-md5 parametrizations are `slow` (VERDICT r3 item 8: XLA:CPU
# compiles of their compress forms dominate the default suite); md5
# keeps dyn-vs-static parity in the fast path.  The LONG-nonce cells
# of the two costliest compilers (ripemd160, sha512 — 15-20 s of
# XLA:CPU compile each, r5 durations) sit in the nightly veryslow
# tier: their short-nonce parity still gates every full run, and the
# long-nonce layout class stays covered per full run by the other
# models' (63,1)/(70,2) cells (VERDICT r4 item 6 suite budget).
def _dyn_static_cells():
    cells = []
    for nl, w in ((2, 1), (4, 2), (63, 1), (70, 2)):
        for model in (MD5, SHA256, SHA1, RIPEMD160, SHA512):
            if model is MD5:
                marks = ()
            elif model in (RIPEMD160, SHA512) and nl > 8:
                marks = (pytest.mark.veryslow,)
            else:
                marks = (pytest.mark.slow,)
            cells.append(pytest.param(
                model, nl, w, marks=marks,
                id=f"{model.name}-{nl}-{w}"))
    return cells


@pytest.mark.parametrize("model,nonce_len,width", _dyn_static_cells())
def test_dyn_step_matches_static(model, nonce_len, width):
    rng = random.Random(nonce_len * 31 + width)
    nonce = bytes(rng.randrange(256) for _ in range(nonce_len))
    for difficulty, tb_lo, tbc in ((1, 0, 256), (2, 64, 64)):
        dyn = cached_search_step(
            nonce, width, difficulty, tb_lo, tbc, 8, model.name
        )
        static = build_search_step(
            nonce, width, difficulty, tb_lo, tbc, 8, model
        )
        for c0 in (1, 77, 255):
            assert int(dyn(jnp.uint32(c0))) == int(static(jnp.uint32(c0)))


def test_dyn_step_compile_reuse_across_requests():
    """Different nonces, difficulties, and power-of-two partitions of the
    same (length, width, batch) must share one compiled program."""
    _dyn_search_step.cache_clear()
    cached_search_step.cache_clear()
    cached_search_step(b"\x01\x02\x03\x04", 2, 3, 0, 256, 16, "md5")
    before = _dyn_search_step.cache_info()
    # same length/width/batch, different content/difficulty/partition:
    cached_search_step(b"\xaa\xbb\xcc\xdd", 2, 7, 0, 256, 16, "md5")
    cached_search_step(b"\x01\x02\x03\x04", 2, 5, 64, 64, 64, "md5")  # batch 4096 == 16*256
    after = _dyn_search_step.cache_info()
    assert after.misses == before.misses, "unexpected recompile"
    assert after.hits > before.hits
    # different length => new layout => one new compile
    cached_search_step(b"\x01\x02\x03", 2, 3, 0, 256, 16, "md5")
    assert _dyn_search_step.cache_info().misses == before.misses + 1


def test_dyn_step_difficulty_bucket_sharing():
    """Difficulties 1..8 share one compiled program (one significant mask
    word); difficulty 9+ selects a second bucket and still matches the
    static program."""
    _dyn_search_step.cache_clear()
    cached_search_step.cache_clear()
    cached_search_step(b"\x31\x32\x33\x34", 2, 1, 0, 256, 16, "md5")
    before = _dyn_search_step.cache_info()
    for d in (2, 5, 8):
        cached_search_step(b"\x31\x32\x33\x34", 2, d, 0, 256, 16, "md5")
    assert _dyn_search_step.cache_info().misses == before.misses
    nine = cached_search_step(b"\x31\x32\x33\x34", 2, 9, 0, 256, 16, "md5")
    assert _dyn_search_step.cache_info().misses == before.misses + 1
    static9 = build_search_step(b"\x31\x32\x33\x34", 2, 9, 0, 256, 16, MD5)
    for c0 in (256, 5000):
        assert int(nine(jnp.uint32(c0))) == int(static9(jnp.uint32(c0)))


def test_dyn_step_non_pow2_partition_falls_back():
    nonce = b"\x0e\x0f"
    dyn = cached_search_step(nonce, 1, 1, 10, 96, 4, "md5")
    static = build_search_step(nonce, 1, 1, 10, 96, 4, MD5)
    for c0 in (1, 100):
        assert int(dyn(jnp.uint32(c0))) == int(static(jnp.uint32(c0)))


def test_backend_warmup_smoke():
    from distpow_tpu.backends import JaxBackend

    b = JaxBackend(batch_size=1 << 12)
    b.warmup([3], [0, 1])
    # warmed layouts serve a real request without new dyn compiles
    before = _dyn_search_step.cache_info().misses
    secret = b.search(b"\x09\x08\x07", 2, list(range(256)))
    assert secret is not None
    assert puzzle.check_secret(b"\x09\x08\x07", secret, 2)
    assert _dyn_search_step.cache_info().misses == before


def test_w0_program_partition_independent():
    """Width-0 probes share one layout-keyed program across partitions
    (the first Mine on any worker split is pure dispatch after warmup)."""
    from distpow_tpu.ops.search_step import _dyn_search_step_w0

    _dyn_search_step_w0.cache_clear()
    cached_search_step.cache_clear()
    nonce = b"\x0c\x0d"
    full = cached_search_step(nonce, 0, 1, 0, 256, 1, "md5")
    misses = _dyn_search_step_w0.cache_info().misses
    quarter = cached_search_step(nonce, 0, 1, 64, 64, 1, "md5")
    assert _dyn_search_step_w0.cache_info().misses == misses
    # results agree with the static program on both partitions
    for dyn, (lo, cnt) in ((full, (0, 256)), (quarter, (64, 64))):
        static = build_search_step(nonce, 0, 1, lo, cnt, 1, MD5)
        assert int(dyn(jnp.uint32(0))) == int(static(jnp.uint32(0)))
