"""Ops-layer tests: packing, difficulty masks, fused search step."""

import hashlib
import random

import jax.numpy as jnp
import numpy as np
import pytest

from distpow_tpu.models import puzzle
from distpow_tpu.models.registry import MD5, SHA256
from distpow_tpu.ops.difficulty import meets_difficulty, nibble_masks
from distpow_tpu.ops.packing import build_tail_spec, make_words, pack_reference_bytes
from distpow_tpu.ops.search_step import (
    SENTINEL,
    build_search_step,
    flat_to_candidate,
)


def digest_of(spec, model, tb, chunk):
    state = spec.init_state
    for b in range(spec.n_blocks):
        words = make_words(spec, jnp.uint32(tb), jnp.uint32(chunk))[b]
        state = model.compress(state, words)
    return b"".join(int(w).to_bytes(4, model.word_byteorder) for w in state)


@pytest.mark.parametrize("model", [MD5, SHA256])
@pytest.mark.parametrize("nonce_len", [0, 4, 20, 54, 55, 63, 64, 65, 130])
@pytest.mark.parametrize("width", [0, 1, 3, 4])
def test_packing_matches_hashlib(model, nonce_len, width):
    rng = random.Random(nonce_len * 7 + width)
    nonce = bytes(rng.randrange(256) for _ in range(nonce_len))
    spec = build_tail_spec(nonce, width, model)
    for _ in range(3):
        tb = rng.randrange(256)
        chunk = rng.randrange(256 ** width) if width else 0
        msg = pack_reference_bytes(nonce, tb, chunk, width)
        expect = model.hashlib_new()
        expect.update(msg)
        assert digest_of(spec, model, tb, chunk) == expect.digest()


def test_packing_extra_const_chunk():
    # width > 4 support: high chunk bytes folded into the constant template
    nonce = b"\x01\x02\x03\x04"
    extra = b"\x09\x02"
    spec = build_tail_spec(nonce, 4, MD5, extra_const_chunk=extra)
    msg = pack_reference_bytes(nonce, 7, 0xDEADBEEF, 4, extra)
    assert digest_of(spec, MD5, 7, 0xDEADBEEF) == hashlib.md5(msg).digest()
    assert len(msg) == 4 + 1 + 4 + 2


@pytest.mark.parametrize("model", [MD5, SHA256])
def test_nibble_masks_vs_oracle(model):
    rng = random.Random(42)
    for _ in range(300):
        digest = bytes(
            rng.choice([0, 0, rng.randrange(256)])
            for _ in range(model.digest_bytes)
        )
        words = tuple(
            jnp.uint32(
                int.from_bytes(digest[4 * i : 4 * i + 4], model.word_byteorder)
            )
            for i in range(model.digest_words)
        )
        true_k = puzzle.count_trailing_zero_nibbles(digest)
        for k in (0, 1, true_k, true_k + 1, model.max_difficulty):
            if k > model.max_difficulty:
                with pytest.raises(ValueError):
                    nibble_masks(k, model)
                continue
            ok = bool(meets_difficulty(words, nibble_masks(k, model)))
            assert ok == (true_k >= k), (digest.hex(), k, true_k)


def test_search_step_finds_reference_first_match():
    nonce = b"\x01\x02\x03\x04"
    difficulty = 2
    tbs = list(range(256))
    # oracle: first match in reference enumeration order within width<=2
    oracle = puzzle.python_search(nonce, difficulty, tbs)
    assert oracle is not None

    # width-0 step
    step0 = build_search_step(nonce, 0, difficulty, 0, 256, 1, MD5)
    f0 = int(step0(jnp.uint32(0)))
    # width-1 step covering chunks [1, 256)
    step1 = build_search_step(nonce, 1, difficulty, 0, 256, 255, MD5)
    f1 = int(step1(jnp.uint32(1)))

    if f0 != SENTINEL:
        chunk, tb = flat_to_candidate(f0, 0, 0, 256)
        secret = bytes([tb])
    else:
        assert f1 != SENTINEL
        chunk, tb = flat_to_candidate(f1, 1, 0, 256)
        secret = bytes([tb]) + puzzle.int_to_chunk(chunk)
    assert secret == oracle


def test_search_step_no_false_positives_at_high_difficulty():
    step = build_search_step(b"\x05\x06", 1, 30, 0, 256, 16, MD5)
    assert int(step(jnp.uint32(1))) == SENTINEL


def test_search_step_sha256():
    nonce = b"\xaa"
    tbs = list(range(256))
    oracle = puzzle.python_search(nonce, 2, tbs, algo="sha256")
    found = None
    step0 = build_search_step(nonce, 0, 2, 0, 256, 1, SHA256)
    f = int(step0(jnp.uint32(0)))
    if f != SENTINEL:
        found = bytes([f % 256])
    else:
        step1 = build_search_step(nonce, 1, 2, 0, 256, 255, SHA256)
        f = int(step1(jnp.uint32(1)))
        assert f != SENTINEL
        chunk, tb = flat_to_candidate(f, 1, 0, 256)
        found = bytes([tb]) + puzzle.int_to_chunk(chunk)
    assert found == oracle
