"""Generated-docs sync: the registry-standing table must match
last_measured.json (VERDICT r4 item 3 — the README-vs-KERNELS number
drift class dies by construction: prose no longer carries the numbers,
and this test fails when the generated copies go stale)."""

from __future__ import annotations

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_registry_standing_tables_in_sync():
    out = subprocess.run(
        [sys.executable, "scripts/gen_registry_table.py", "--check"],
        capture_output=True, text=True, timeout=120, cwd=REPO,
    )
    assert out.returncode == 0, (
        f"stale generated table — run scripts/gen_registry_table.py\n"
        f"{out.stdout}{out.stderr}"
    )


def test_readme_documents_wire_parity_boundary():
    """The one redrawn boundary (framed-JSON RPC vs net/rpc+gob) and
    the three GoVector divergences must stay stated in README — a
    reader must not mistake behavioral parity for wire interop."""
    text = open(os.path.join(REPO, "README.md")).read()
    assert "Wire-level parity boundary" in text
    assert "net/rpc" in text and "gob" in text
    assert "framed JSON" in text
    for marker in ("parser regex", "%+v", "Initialization Complete"):
        assert marker in text, f"divergence {marker!r} undocumented"
