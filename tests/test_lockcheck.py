"""Runtime lock-order audit tests (distpow_tpu/runtime/lockcheck.py,
docs/CONCURRENCY.md, ISSUE 17).

The audit is exercised directly — ``install()`` / ``uninstall()`` in a
fixture — rather than via DISTPOW_LOCK_CHECK, so these tests behave the
same under ``ci.sh --race-audit`` (where the env flag is live for the
whole session) and in a plain run.  Locks are constructed inside this
file, which is under the repository root, so they are instrumented.
"""

import threading
import time

import pytest

from distpow_tpu.runtime import lockcheck


@pytest.fixture
def audit():
    """Fresh instrumented window: patch, hand control to the test,
    unpatch and clear.  Restores a prior install (ci.sh --race-audit
    keeps the patch live for the whole session)."""
    was_installed = lockcheck._installed
    lockcheck.install()
    before = lockcheck.check().edges
    yield lockcheck
    # drop edges this test minted so the session-wide audit (conftest)
    # does not inherit the deliberately-inverted fixtures below
    lockcheck.reset()
    with lockcheck._state_lock:
        lockcheck._edges.update(before)
    if not was_installed:
        lockcheck.uninstall()


def _ordered(a, b):
    with a:
        with b:
            pass


def test_observed_inversion_is_reported(audit):
    a = threading.Lock()
    b = threading.Lock()
    t1 = threading.Thread(target=_ordered, args=(a, b))
    t2 = threading.Thread(target=_ordered, args=(b, a))
    for t in (t1, t2):
        t.start()
        t.join()
    report = audit.check()
    assert len(report.cycles) == 1
    text = audit.format_report(report)
    assert "inversion" in text and "test_lockcheck.py" in text


def test_consistent_order_is_clean(audit):
    a = threading.Lock()
    b = threading.Lock()
    for _ in range(3):
        _ordered(a, b)
    report = audit.check()
    assert report.cycles == []
    assert any(k for k in report.edges), "ordered pair should be recorded"
    assert "clean" in audit.format_report(report)


def test_rlock_reentry_records_no_self_edge(audit):
    r = threading.RLock()

    def reenter():
        with r:
            with r:
                pass

    reenter()
    assert all(a != b for a, b in audit.check().edges)


def test_condition_wait_is_not_an_inversion(audit):
    cond = threading.Condition()
    hits = []

    def waiter():
        with cond:
            while not hits:
                cond.wait(timeout=1.0)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    with cond:
        hits.append(1)
        cond.notify_all()
    t.join()
    assert audit.check().cycles == []


def test_instrumentation_is_site_filtered(audit):
    import queue

    q = queue.Queue()  # stdlib constructs its own mutex internally
    q.put(1)
    assert q.get() == 1
    assert not isinstance(q.mutex, lockcheck._LockProxy)
    lk = threading.Lock()  # constructed HERE -> instrumented
    assert isinstance(lk, lockcheck._LockProxy)


def test_hold_stats_accumulate(audit):
    lk = threading.Lock()
    with lk:
        time.sleep(0.01)
    stats = audit.stats()
    site = next(s for s in stats if "test_lockcheck.py" in s)
    assert stats[site]["n"] == 1
    assert stats[site]["max_s"] >= 0.01


def test_overhead_smoke(audit):
    """The proxy costs an attribute hop and a list append per
    acquisition — budget: 200k uncontended acquire/release cycles in
    well under five seconds even on a loaded CI box."""
    lk = threading.Lock()
    t0 = time.monotonic()
    for _ in range(200_000):
        with lk:
            pass
    assert time.monotonic() - t0 < 5.0


def test_uninstall_restores_real_factories():
    lockcheck.install()
    lockcheck.uninstall()
    try:
        lk = threading.Lock()
        assert not isinstance(lk, lockcheck._LockProxy)
    finally:
        if lockcheck.enabled():
            lockcheck.install()  # restore the session-wide audit
