"""Hash-model correctness: JAX and pure-Python twins vs hashlib."""

import hashlib
import random
import struct

import jax.numpy as jnp
import numpy as np
import pytest

from distpow_tpu.models import md5_jax, sha1_jax, sha256_jax
from distpow_tpu.models.registry import MD5, SHA1, SHA256, get_hash_model


def pad_md5(message: bytes) -> bytes:
    tail = message + b"\x80"
    tail += b"\x00" * ((-len(tail) - 8) % 64)
    tail += struct.pack("<Q", len(message) * 8)
    return tail


def pad_sha256(message: bytes) -> bytes:
    tail = message + b"\x80"
    tail += b"\x00" * ((-len(tail) - 8) % 64)
    tail += struct.pack(">Q", len(message) * 8)
    return tail


def blocks_to_words(padded: bytes, order: str):
    fmt = "<16I" if order == "little" else ">16I"
    return [
        list(struct.unpack(fmt, padded[i : i + 64]))
        for i in range(0, len(padded), 64)
    ]


@pytest.mark.parametrize("length", [0, 1, 3, 8, 55, 56, 63, 64, 65, 120, 200])
def test_md5_jax_vs_hashlib(length):
    rng = random.Random(length)
    msg = bytes(rng.randrange(256) for _ in range(length))
    words = blocks_to_words(pad_md5(msg), "little")
    state = md5_jax.md5_digest_words(words)
    digest = b"".join(int(w).to_bytes(4, "little") for w in state)
    assert digest == hashlib.md5(msg).digest()


@pytest.mark.parametrize("length", [0, 1, 8, 55, 56, 64, 65, 130])
def test_sha256_jax_vs_hashlib(length):
    rng = random.Random(1000 + length)
    msg = bytes(rng.randrange(256) for _ in range(length))
    words = blocks_to_words(pad_sha256(msg), "big")
    state = sha256_jax.sha256_digest_words(words)
    digest = b"".join(int(w).to_bytes(4, "big") for w in state)
    assert digest == hashlib.sha256(msg).digest()


@pytest.mark.parametrize("length", [0, 1, 8, 55, 56, 64, 65, 130])
def test_sha1_jax_vs_hashlib(length):
    rng = random.Random(2000 + length)
    msg = bytes(rng.randrange(256) for _ in range(length))
    # same big-endian single-padding scheme as sha256 (FIPS 180-4)
    words = blocks_to_words(pad_sha256(msg), "big")
    state = sha1_jax.sha1_digest_words(words)
    digest = b"".join(int(w).to_bytes(4, "big") for w in state)
    assert digest == hashlib.sha1(msg).digest()


def test_md5_jax_vectorized_batch():
    # the compression must vectorize over batch-shaped message words
    rng = random.Random(7)
    msgs = [bytes(rng.randrange(256) for _ in range(10)) for _ in range(33)]
    word_batches = []
    for m in msgs:
        word_batches.append(blocks_to_words(pad_md5(m), "little")[0])
    arr = np.array(word_batches, dtype=np.uint32)  # (33, 16)
    words = [jnp.asarray(arr[:, i]) for i in range(16)]
    state = md5_jax.md5_digest_words([words])
    for j, m in enumerate(msgs):
        digest = b"".join(int(w[j]).to_bytes(4, "little") for w in state)
        assert digest == hashlib.md5(m).digest()


@pytest.mark.parametrize("model,href", [(MD5, hashlib.md5),
                                        (SHA256, hashlib.sha256),
                                        (SHA1, hashlib.sha1)])
@pytest.mark.parametrize("length", [0, 5, 63, 64, 70, 128, 129])
def test_py_twins_vs_hashlib(model, href, length):
    rng = random.Random(length * 31)
    msg = bytes(rng.randrange(256) for _ in range(length))
    mod = {MD5: md5_jax, SHA256: sha256_jax, SHA1: sha1_jax}[model]
    assert mod.py_digest(msg) == href(msg).digest()


def test_py_absorb_prefix_state():
    # absorbing N full blocks then continuing must equal hashing the whole
    # message — this is what lets long constant nonces run host-side
    msg = bytes(range(256)) * 2  # 512 bytes = 8 blocks
    state, rem, absorbed = md5_jax.py_absorb(msg[:130])
    assert absorbed == 128 and rem == msg[128:130]
    # continue: tail = rem + suffix and padding with total length
    suffix = b"hello"
    total = msg[:130] + suffix
    tail = rem + suffix + b"\x80"
    tail += b"\x00" * ((-len(tail) - 8) % 64)
    tail += struct.pack("<Q", len(total) * 8)
    for i in range(0, len(tail), 64):
        state = md5_jax.py_compress(state, tail[i : i + 64])
    digest = b"".join(w.to_bytes(4, "little") for w in state)
    assert digest == hashlib.md5(total).digest()


def test_registry():
    assert get_hash_model("md5") is MD5
    assert get_hash_model("SHA256") is SHA256
    assert get_hash_model("sha1") is SHA1
    assert MD5.max_difficulty == 32
    assert SHA256.max_difficulty == 64
    assert SHA1.max_difficulty == 40
    with pytest.raises(ValueError):
        get_hash_model("sha1024")
