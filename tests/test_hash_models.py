"""Hash-model correctness: JAX and pure-Python twins vs hashlib."""

import hashlib
import random
import struct

import jax.numpy as jnp
import numpy as np
import pytest

from distpow_tpu.models import (
    blake2b_jax,
    md5_jax,
    ripemd160_jax,
    sha1_jax,
    sha3_jax,
    sha256_jax,
    sha256d_jax,
    sha384_jax,
    sha512_jax,
)
from distpow_tpu.models.registry import (
    BLAKE2B_256,
    MD5,
    RIPEMD160,
    SHA1,
    SHA3_256,
    SHA256,
    SHA256D,
    SHA384,
    SHA512,
    get_hash_model,
)


def pad_md5(message: bytes) -> bytes:
    tail = message + b"\x80"
    tail += b"\x00" * ((-len(tail) - 8) % 64)
    tail += struct.pack("<Q", len(message) * 8)
    return tail


def pad_sha256(message: bytes) -> bytes:
    tail = message + b"\x80"
    tail += b"\x00" * ((-len(tail) - 8) % 64)
    tail += struct.pack(">Q", len(message) * 8)
    return tail


def blocks_to_words(padded: bytes, order: str):
    fmt = "<16I" if order == "little" else ">16I"
    return [
        list(struct.unpack(fmt, padded[i : i + 64]))
        for i in range(0, len(padded), 64)
    ]


@pytest.mark.parametrize("length", [0, 1, 3, 8, 55, 56, 63, 64, 65, 120, 200])
def test_md5_jax_vs_hashlib(length):
    rng = random.Random(length)
    msg = bytes(rng.randrange(256) for _ in range(length))
    words = blocks_to_words(pad_md5(msg), "little")
    state = md5_jax.md5_digest_words(words)
    digest = b"".join(int(w).to_bytes(4, "little") for w in state)
    assert digest == hashlib.md5(msg).digest()


@pytest.mark.parametrize("length", [0, 1, 8, 55, 56, 64, 65, 130])
def test_sha256_jax_vs_hashlib(length):
    rng = random.Random(1000 + length)
    msg = bytes(rng.randrange(256) for _ in range(length))
    words = blocks_to_words(pad_sha256(msg), "big")
    state = sha256_jax.sha256_digest_words(words)
    digest = b"".join(int(w).to_bytes(4, "big") for w in state)
    assert digest == hashlib.sha256(msg).digest()


@pytest.mark.parametrize("length", [0, 1, 8, 55, 56, 64, 65, 130])
def test_sha1_jax_vs_hashlib(length):
    rng = random.Random(2000 + length)
    msg = bytes(rng.randrange(256) for _ in range(length))
    # same big-endian single-padding scheme as sha256 (FIPS 180-4)
    words = blocks_to_words(pad_sha256(msg), "big")
    state = sha1_jax.sha1_digest_words(words)
    digest = b"".join(int(w).to_bytes(4, "big") for w in state)
    assert digest == hashlib.sha1(msg).digest()


def test_md5_jax_vectorized_batch():
    # the compression must vectorize over batch-shaped message words
    rng = random.Random(7)
    msgs = [bytes(rng.randrange(256) for _ in range(10)) for _ in range(33)]
    word_batches = []
    for m in msgs:
        word_batches.append(blocks_to_words(pad_md5(m), "little")[0])
    arr = np.array(word_batches, dtype=np.uint32)  # (33, 16)
    words = [jnp.asarray(arr[:, i]) for i in range(16)]
    state = md5_jax.md5_digest_words([words])
    for j, m in enumerate(msgs):
        digest = b"".join(int(w[j]).to_bytes(4, "little") for w in state)
        assert digest == hashlib.md5(m).digest()


@pytest.mark.parametrize("model,href", [
    (MD5, hashlib.md5),
    (SHA256, hashlib.sha256),
    (SHA1, hashlib.sha1),
    (RIPEMD160, lambda m: hashlib.new("ripemd160", m)),
    (SHA512, hashlib.sha512),
    (SHA384, hashlib.sha384),
    (SHA3_256, hashlib.sha3_256),
    (BLAKE2B_256, lambda m: hashlib.blake2b(m, digest_size=32)),
    (SHA256D,
     lambda m: hashlib.sha256(hashlib.sha256(m).digest())),
])
@pytest.mark.parametrize("length", [0, 5, 63, 64, 70, 128, 129, 135, 136, 137])
def test_py_twins_vs_hashlib(model, href, length):
    rng = random.Random(length * 31)
    msg = bytes(rng.randrange(256) for _ in range(length))
    mod = {MD5: md5_jax, SHA256: sha256_jax, SHA1: sha1_jax,
           RIPEMD160: ripemd160_jax, SHA512: sha512_jax,
           SHA384: sha384_jax, SHA3_256: sha3_jax,
           BLAKE2B_256: blake2b_jax, SHA256D: sha256d_jax}[model]
    assert mod.py_digest(msg) == href(msg).digest()


@pytest.mark.parametrize("length", [0, 1, 8, 55, 56, 64, 65, 130])
def test_ripemd160_jax_vs_hashlib(length):
    rng = random.Random(3000 + length)
    msg = bytes(rng.randrange(256) for _ in range(length))
    # MD5's little-endian padding scheme (ISO 10118-3)
    words = blocks_to_words(pad_md5(msg), "little")
    state = RIPEMD160.init_state
    for block in words:
        state = ripemd160_jax.ripemd160_compress(
            state, [jnp.uint32(w) for w in block])
    digest = b"".join(int(w).to_bytes(4, "little") for w in state)
    assert digest == hashlib.new("ripemd160", msg).digest()


def test_ripemd160_spec_vectors():
    """Published vectors from the RIPEMD-160 paper (Dobbertin,
    Bosselaers, Preneel — Appendix B), independent of this machine's
    hashlib/OpenSSL build."""
    vectors = {
        b"": "9c1185a5c5e9fc54612808977ee8f548b2258d31",
        b"a": "0bdc9d2d256b3ee9daae347be6f4dc835a467ffe",
        b"abc": "8eb208f7e05d987a9b044a8e98c6b087f15a0bfc",
        b"message digest": "5d0689ef49d2fae572b881b123a85ffa21595f36",
        b"abcdefghijklmnopqrstuvwxyz":
            "f71c27109c692c1b56bbdceb5b9d2865b3708dbc",
        b"1234567890" * 8: "9b752e45573d4b39f4dbd3323cab82bf63326bfb",
    }
    for msg, want in vectors.items():
        assert ripemd160_jax.py_digest(msg).hex() == want, msg


def test_py_absorb_prefix_state():
    # absorbing N full blocks then continuing must equal hashing the whole
    # message — this is what lets long constant nonces run host-side
    msg = bytes(range(256)) * 2  # 512 bytes = 8 blocks
    state, rem, absorbed = md5_jax.py_absorb(msg[:130])
    assert absorbed == 128 and rem == msg[128:130]
    # continue: tail = rem + suffix and padding with total length
    suffix = b"hello"
    total = msg[:130] + suffix
    tail = rem + suffix + b"\x80"
    tail += b"\x00" * ((-len(tail) - 8) % 64)
    tail += struct.pack("<Q", len(total) * 8)
    for i in range(0, len(tail), 64):
        state = md5_jax.py_compress(state, tail[i : i + 64])
    digest = b"".join(w.to_bytes(4, "little") for w in state)
    assert digest == hashlib.md5(total).digest()


def test_registry():
    assert get_hash_model("md5") is MD5
    assert get_hash_model("SHA256") is SHA256
    assert get_hash_model("sha1") is SHA1
    assert get_hash_model("ripemd160") is RIPEMD160
    assert get_hash_model("sha512") is SHA512
    assert SHA512.max_difficulty == 128
    assert SHA512.words_per_block == 32 and SHA512.length_bytes == 16
    assert get_hash_model("sha384") is SHA384
    # the truncating model: digest narrower than the carried state
    assert SHA384.max_difficulty == 96 and SHA384.digest_words == 12
    assert len(SHA384.init_state) == 16
    assert SHA384.state_to_digest(SHA384.init_state) == b"".join(
        w.to_bytes(4, "big") for w in SHA384.init_state[:12])
    assert MD5.max_difficulty == 32
    assert SHA256.max_difficulty == 64
    assert SHA1.max_difficulty == 40
    assert RIPEMD160.max_difficulty == 40
    with pytest.raises(ValueError):
        get_hash_model("sha1024")


def test_ripemd160_fallback_without_openssl_support(monkeypatch):
    """ripemd160 is the only registry model outside hashlib's guaranteed
    set (stock OpenSSL 3 without the legacy provider refuses it); every
    puzzle verification path must fall back to the spec-vector-pinned
    pure-Python implementation (models/ripemd160_py.py) on such hosts."""
    from distpow_tpu.models import puzzle

    real_new = hashlib.new

    def deny(name, *a, **k):
        if name == "ripemd160":
            raise ValueError("unsupported hash type ripemd160")
        return real_new(name, *a, **k)

    monkeypatch.setattr(hashlib, "new", deny)
    h = puzzle.new_hash("ripemd160")
    h.update(b"abc")
    assert h.hexdigest() == "8eb208f7e05d987a9b044a8e98c6b087f15a0bfc"
    assert RIPEMD160.hashlib_new().name == "ripemd160"
    oracle = puzzle.python_search(b"\x0a\x0b", 2, list(range(256)),
                                  algo="ripemd160")
    assert puzzle.check_secret(b"\x0a\x0b", oracle, 2, algo="ripemd160")
    # non-ripemd algos still reject unknown names
    with pytest.raises(ValueError):
        puzzle.new_hash("sha1024")


@pytest.mark.parametrize("length", [0, 1, 8, 111, 112, 127, 128, 129, 260])
def test_sha512_jax_vs_hashlib(length):
    """Fifth model (round 4): 128-byte blocks, 16-byte length field,
    64-bit words emulated as (hi, lo) uint32 pairs.  Lengths straddle
    the 112-mod-128 two-block-padding boundary and the 128-byte block
    boundary."""
    rng = random.Random(4000 + length)
    msg = bytes(rng.randrange(256) for _ in range(length))
    tail = msg + b"\x80"
    tail += b"\x00" * ((-len(tail) - 16) % 128)
    tail += (len(msg) * 8).to_bytes(16, "big")
    state = SHA512.init_state
    for i in range(0, len(tail), 128):
        words = struct.unpack(">32I", tail[i:i + 128])
        state = sha512_jax.sha512_compress(state, [jnp.uint32(w) for w in words])
    digest = b"".join(int(w).to_bytes(4, "big") for w in state)
    assert digest == hashlib.sha512(msg).digest()


def test_sha512_spec_vector():
    """FIPS 180-4 / NIST example vector, independent of hashlib."""
    assert sha512_jax.py_digest(b"abc").hex() == (
        "ddaf35a193617abacc417349ae20413112e6fa4e89a97ea20a9eeee64b55d39a"
        "2192992a274fc1a836ba3c23a3feebbd454d4423643ce80e2a9ac94fa54ca49f")


def test_loop_compress_all_constant_block_with_batched_state():
    """Regression (round 4): a tail block can be ALL-constant (every
    message word a scalar) while the incoming state is batch-shaped —
    the fori_loop forms derived their broadcast shape from the words
    alone and crashed in broadcast_to.  Exercises sha256, sha1, and
    sha512 loop forms directly."""
    for model in (SHA256, SHA1, SHA512):
        n = model.words_per_block
        batch_state = tuple(
            jnp.full((7,), s, jnp.uint32) for s in model.init_state)
        out = model.compress(batch_state, [int(i + 1) for i in range(n)])
        # must equal the scalar-state result broadcast
        ref = model.compress(model.init_state,
                             [int(i + 1) for i in range(n)])
        for o, r in zip(out, ref):
            assert o.shape == (7,)
            assert int(o[3]) == int(r)


def test_sha384_spec_vector_and_truncation():
    """FIPS 180-4 vector; the digest is the first 48 bytes of the
    (differently-initialized) sha512 state — the truncating-model case
    (digest_words < state words) no layer may conflate."""
    assert sha384_jax.py_digest(b"abc").hex() == (
        "cb00753f45a35e8bb5a03d699ac65007272c32ab0eded1631a8b605a43ff5bed"
        "8086072ba1e7cc2358baeca134c825a7")
    # mining parity at a difficulty whose masks live in the truncated
    # digest's trailing words
    from distpow_tpu.models import puzzle
    from distpow_tpu.parallel.search import search

    tbs = list(range(256))
    oracle = puzzle.python_search(b"\x31\x41", 2, tbs, algo="sha384")
    got = search(b"\x31\x41", 2, tbs, model=SHA384, batch_size=1 << 13)
    assert got is not None and got.secret == oracle


def test_sha3_registry_and_spec_vectors():
    """The sponge model's registry shape + FIPS 202 vectors (the empty
    string and 'abc' are the published SHA3-256 examples)."""
    assert get_hash_model("sha3_256") is SHA3_256
    assert SHA3_256.padding == "sha3" and MD5.padding == "md"
    assert SHA3_256.block_bytes == 136 and SHA3_256.words_per_block == 34
    assert SHA3_256.digest_words == 8 and SHA3_256.max_difficulty == 64
    assert len(SHA3_256.init_state) == 50  # 25 lanes x 2 limbs
    assert sha3_jax.py_digest(b"").hex() == (
        "a7ffc6f8bf1ed76651c14756a061d662f580ff4de43b49fa82d80a4b80f8434a")
    assert sha3_jax.py_digest(b"abc").hex() == (
        "3a985da74fe225b2045c172d6bd390bd855f086e3e9d525b46bfe24511431532")


def test_sha3_jax_compress_batch_vs_hashlib():
    """The limb-pair keccak on batch-shaped words (the serving operand
    shape) matches hashlib lane-for-lane, one-block and two-block."""
    rng = random.Random(99)
    N = 9
    msgs = [bytes(rng.randrange(256) for _ in range(rng.randrange(1, 135)))
            for _ in range(N)]
    blocks = []
    for m in msgs:
        t = bytearray(136)
        t[: len(m)] = m
        t[len(m)] ^= 0x06
        t[-1] ^= 0x80
        blocks.append(struct.unpack("<34I", bytes(t)))
    arr = np.array(blocks, np.uint32)  # (N, 34)
    words = [jnp.asarray(arr[:, j]) for j in range(34)]
    state = sha3_jax.sha3_256_compress(sha3_jax.SHA3_INIT, words)
    for i, m in enumerate(msgs):
        digest = b"".join(
            int(np.asarray(state[w])[i]).to_bytes(4, "little")
            for w in range(8)
        )
        assert digest == hashlib.sha3_256(m).digest(), i
    # two-block path: absorbed prefix -> device continuation
    long_msg = bytes(range(200))
    st, rem, absorbed = sha3_jax.py_absorb(long_msg)
    assert absorbed == 136 and len(rem) == 64
    t = bytearray(136)
    t[: len(rem)] = rem
    t[len(rem)] ^= 0x06
    t[-1] ^= 0x80
    st = sha3_jax.sha3_256_compress(st, struct.unpack("<34I", bytes(t)))
    digest = b"".join(int(w).to_bytes(4, "little") for w in st[:8])
    assert digest == hashlib.sha3_256(long_msg).digest()


def test_sha256d_registry_and_finalize():
    """The composed model's registry shape (r5 ninth model): sha256d
    is plain SHA-256 absorption plus a ``finalize`` composition stage
    — the structural axis no other model exercises.  The vectorized
    finalize and its python twin must agree with hashlib's double
    digest, and the serving path must apply it (a cached step at
    difficulty 1 agrees with the double-hash oracle)."""
    import hashlib

    m = get_hash_model("sha256d")
    assert m is SHA256D
    assert m.finalize is sha256d_jax.sha256d_finalize
    assert m.py_finalize is sha256d_jax.py_finalize
    assert m.compress is sha256_jax.sha256_compress
    assert m.max_difficulty == 64 and m.digest_words == 8

    # vectorized finalize == python twin == hashlib, over a small batch
    msgs = [bytes([i]) * 11 for i in range(4)]
    states = [sha256_jax.py_absorb(b"")[0] for _ in msgs]
    firsts = []
    for msg, st in zip(msgs, states):
        padded = (msg + b"\x80" + bytes((55 - len(msg)) % 64)
                  + (8 * len(msg)).to_bytes(8, "big"))
        for i in range(0, len(padded), 64):
            st = sha256_jax.py_compress(st, padded[i:i + 64])
        firsts.append(st)
    batch = tuple(
        jnp.asarray(np.array([f[w] for f in firsts], np.uint32))
        for w in range(8)
    )
    out = sha256d_jax.sha256d_finalize(batch)
    for i, msg in enumerate(msgs):
        want = hashlib.sha256(hashlib.sha256(msg).digest()).digest()
        got = b"".join(int(w[i]).to_bytes(4, "big") for w in out)
        assert got == want
        assert m.state_to_digest(sha256d_jax.py_finalize(firsts[i])) == want

    # the serving (dyn) path applies finalize: first hit at difficulty
    # 1 matches the double-hash oracle exactly
    from distpow_tpu.models import puzzle
    from distpow_tpu.ops.search_step import SENTINEL, cached_search_step

    nonce = b"\x09\x08\x07"
    step = cached_search_step(nonce, 1, 1, 0, 256, 64, "sha256d")
    got_f = int(step(jnp.uint32(0)))
    assert got_f != SENTINEL
    # brute oracle over the same window
    want_f = None
    for f in range(64 * 256):
        chunk, tb = f // 256, f % 256
        secret = bytes([tb, chunk & 0xFF])
        h = puzzle.new_hash("sha256d")
        h.update(nonce + secret)
        if h.hexdigest().endswith("0"):
            want_f = f
            break
    assert got_f == want_f


def test_blake2b_py_compress_accepts_plain_block():
    """Generic-consumer contract (advisor r4 + review r5): a plain
    BLOCK_BYTES block with an EXPLICIT byte counter must compress
    identically to the template-shaped block carrying the same baked
    parameters; omitting t raises a guiding TypeError instead of
    silently chaining multi-block inputs into a wrong digest (blake2's
    compression is not a pure function of (state, block))."""
    import pytest as _pytest

    from distpow_tpu.models import blake2b_py as b

    state, rem, absorbed = b.py_absorb(b"")
    assert rem == b"" and absorbed == 0
    block = bytes(range(100)) + bytes(28)
    params = (128).to_bytes(8, "little") + (0).to_bytes(8, "little")
    assert (b.py_compress(state, block, t=128)
            == b.py_compress(state, block + params))
    # a final block via explicit kwargs
    final_params = (100).to_bytes(8, "little") + \
        (0xFFFFFFFFFFFFFFFF).to_bytes(8, "little")
    assert (b.py_compress(state, block, t=100, last=True)
            == b.py_compress(state, block + final_params))
    # the plain non-final form agrees with py_absorb on real data
    state2, _, absorbed2 = b.py_absorb(block + b"\x99")
    assert absorbed2 == 128
    assert tuple(b.py_compress(state, block, t=128)) == tuple(state2)
    # ...and CHAINING with correct counters matches a 2-block absorb
    block2 = bytes(range(50, 178))
    state3, _, absorbed3 = b.py_absorb(block + block2 + b"\x77")
    assert absorbed3 == 256
    chained = b.py_compress(
        b.py_compress(state, block, t=128), block2, t=256)
    assert tuple(chained) == tuple(state3)
    # omitted counter on a plain block: guided error, not wrong math
    with _pytest.raises(TypeError, match="bytes absorbed"):
        b.py_compress(state, block)
    # the template form still rejects doubled parameters (TypeError,
    # not assert: must survive python -O)
    with _pytest.raises(TypeError, match="baked"):
        b.py_compress(state, block + params, t=1)


def test_blake2b_registry_and_params():
    """The per-block-parameter model's registry shape: blake2's byte
    counter and finalization flag are compression inputs the packing
    layer bakes as extra template words (HashModel.block_param_words) —
    the structural axis no other model exercises."""
    from distpow_tpu.models import blake2b_jax
    from distpow_tpu.models.registry import BLAKE2B_256

    assert get_hash_model("blake2b_256") is BLAKE2B_256
    assert BLAKE2B_256.padding == "blake2"
    assert BLAKE2B_256.param_words == 4
    assert BLAKE2B_256.block_param_words is blake2b_jax.block_param_words
    assert BLAKE2B_256.digest_words == 8 and BLAKE2B_256.max_difficulty == 64
    # param derivation: non-final blocks count full message bytes,
    # the final block the true length, finality all-ones
    assert blake2b_jax.block_param_words(0, 200, 0, 2) == (128, 0, 0, 0)
    assert blake2b_jax.block_param_words(0, 200, 1, 2) == (
        200, 0, 0xFFFFFFFF, 0xFFFFFFFF)
    assert blake2b_jax.block_param_words(256, 10, 0, 1) == (
        266, 0, 0xFFFFFFFF, 0xFFFFFFFF)
    # the template rows carry the params (packing layer)
    from distpow_tpu.ops.packing import build_tail_spec

    spec = build_tail_spec(b"\x01\x02", 2, BLAKE2B_256)
    assert spec.n_blocks == 1
    assert len(spec.base_words[0]) == 32 + 4
    # t = 2 (nonce rem) + 1 (tb) + 2 (width) = 5; final
    assert spec.base_words[0][32:] == (5, 0, 0xFFFFFFFF, 0xFFFFFFFF)


def test_blake2b_search_matches_oracle():
    """Mining parity end-to-end: zero-fill padding, baked per-block
    params, including a host-absorbed full prefix block (the t counter
    must carry across the absorb boundary)."""
    from distpow_tpu.models import puzzle
    from distpow_tpu.models.registry import BLAKE2B_256
    from distpow_tpu.parallel.search import search

    tbs = list(range(256))
    for nonce in (b"\x27\x18", bytes(range(130))):
        oracle = puzzle.python_search(nonce, 2, tbs, algo="blake2b_256")
        got = search(nonce, 2, tbs, model=BLAKE2B_256, batch_size=1 << 13)
        assert got is not None and got.secret == oracle
        assert hashlib.blake2b(nonce + got.secret,
                               digest_size=32).hexdigest().endswith("00")


def test_sha3_search_matches_oracle():
    """Mining parity end-to-end through the generic driver — the sponge
    padding hook (ops/packing.py) in its serving configuration."""
    from distpow_tpu.models import puzzle
    from distpow_tpu.parallel.search import search

    tbs = list(range(256))
    for nonce in (b"\x27\x18", b"\x01\x02\x03\x04"):
        oracle = puzzle.python_search(nonce, 2, tbs, algo="sha3_256")
        got = search(nonce, 2, tbs, model=SHA3_256, batch_size=1 << 13)
        assert got is not None and got.secret == oracle
        assert hashlib.sha3_256(nonce + got.secret).hexdigest().endswith("00")
