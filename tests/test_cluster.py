"""Coordinator scale-out plane (ISSUE 15, docs/CLUSTER.md): ring
properties, the NOT_OWNER redirect protocol, hedged sibling retry,
shard-death failover, epoch-namespaced round-id fencing, shared-worker
reply-to routing, pool discovery and config generation.

Everything here is CPU-only and jax-free (python backends over
localhost RPC), so the whole file rides tier-1.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time

import pytest

from distpow_tpu.cluster import (
    ClusterState,
    HashRing,
    NotOwnerError,
    ring_from_peers,
)
from distpow_tpu.load.harness import InProcCluster
from distpow_tpu.models import puzzle
from distpow_tpu.nodes.coordinator import new_round_id
from distpow_tpu.nodes.worker import TaskRound, _rid_order, _rid_split
from distpow_tpu.runtime import rpc, wire
from distpow_tpu.runtime.metrics import REGISTRY as metrics
from distpow_tpu.sched.admission import AdmissionReject


def _sample_nonces(n: int = 2000):
    # deterministic keyspace sample: 2-byte nonces are enough to cover
    # every ring arc at 64 vnodes
    return [bytes([i % 256, i // 256]) for i in range(n)]


# -- ring math (the routing contract) ---------------------------------------

def test_ring_is_deterministic_and_wire_roundtrips():
    peers = ["h0:1", "h1:2", "h2:3"]
    a, b = ring_from_peers(peers), ring_from_peers(peers)
    nonces = _sample_nonces(512)
    assert [a.owner(x) for x in nonces] == [b.owner(x) for x in nonces]
    c = HashRing.from_wire(a.to_wire())
    assert c == a
    assert [c.owner(x) for x in nonces] == [a.owner(x) for x in nonces]
    assert a.addr_of("c1") == "h1:2"
    assert a.addr_of("nope") is None


def test_ring_routes_on_nonce_alone_dominance_preserving():
    """The dominance contract (docs/CLUSTER.md): every difficulty of
    one nonce maps to ONE shard — the ring key is the nonce alone, so
    a shard's cache entry at ntz=k dominates every ntz<=k request for
    that nonce.  Pinned against the coordinator-side ownership check,
    which is the code that would break it."""
    ring = ring_from_peers(["h0:1", "h1:2", "h2:3", "h3:4"])
    state = ClusterState(ring, "c0")
    for nonce in _sample_nonces(256):
        owner = ring.owner(nonce)
        # owns() consults nothing but the nonce; exercising it across
        # the ntz range documents the contract at the checking site
        for _ntz in (1, 2, 7, 16):
            assert ring.owner(nonce) == owner
            assert state.owns(nonce) == (owner == "c0")


def test_ring_walk_orders_distinct_members_owner_first():
    ring = ring_from_peers(["h0:1", "h1:2", "h2:3"])
    for nonce in _sample_nonces(64):
        walk = ring.ordered(nonce)
        assert walk[0] == ring.owner(nonce)
        assert sorted(walk) == ["c0", "c1", "c2"]  # distinct, complete


def test_adding_a_shard_remaps_bounded_fraction():
    """Consistent hashing's whole point: N -> N+1 moves ~1/(N+1) of
    the keyspace, not ~all of it (the modulo-routing failure mode the
    lint rule freezes out)."""
    peers4 = [f"h{i}:{i}" for i in range(4)]
    r4 = ring_from_peers(peers4)
    r5 = ring_from_peers(peers4 + ["h4:4"])
    nonces = _sample_nonces(2000)
    moved = sum(1 for x in nonces if r4.owner(x) != r5.owner(x))
    frac = moved / len(nonces)
    assert frac <= 0.35, f"adding 1 of 5 shards remapped {frac:.0%}"
    # and every key that moved, moved TO the new member — an old
    # member must never steal keys from another old member
    for x in nonces:
        if r4.owner(x) != r5.owner(x):
            assert r5.owner(x) == "c4"


def test_ring_rejects_duplicates_and_empty():
    with pytest.raises(ValueError):
        HashRing([])
    with pytest.raises(ValueError):
        HashRing([("c0", "a:1"), ("c0", "b:2")])
    with pytest.raises(ValueError):
        ClusterState(ring_from_peers(["a:1"]), "c9")


# -- wire + rpc plumbing -----------------------------------------------------

def test_not_owner_ring_rides_binary_frame():
    ring = ring_from_peers(["h0:1", "h1:2"]).to_wire()
    frame = {"id": 7, "result": None,
             "error": "NotOwnerError: NOT_OWNER: key is owned by 'c1'",
             "ring": ring}
    enc = wire.encode_frame(frame)
    dec = wire.decode_frame(enc)
    assert dec == frame
    # frames WITHOUT a ring stay exactly the pre-cluster encoding
    plain = {"id": 7, "result": None, "error": "x"}
    assert wire.decode_frame(wire.encode_frame(plain)) == plain
    assert wire.FLAG_RING == 0x04


def test_rpc_surfaces_not_owner_and_hello_ring():
    """A handler raising a ring_wire-carrying exception reaches the
    caller as typed RPCNotOwner on BOTH codecs, and the extended
    rpc.hello ack carries the advertised ring."""
    ring_wire = ring_from_peers(["h0:1", "h1:2"]).to_wire()

    class Svc:
        def Boom(self, params):
            raise NotOwnerError("c1", ring_wire)

        def Ok(self, params):
            return {"ok": True}

    server = rpc.RPCServer()
    server.register("Svc", Svc())
    server.hello_extra = lambda: {"ring": ring_wire}
    addr = server.listen("127.0.0.1:0")
    server.serve_in_background()
    try:
        for codec in ("auto", "json"):
            client = rpc.RPCClient(addr, codec=codec)
            try:
                if codec == "auto":
                    assert client.codec_name == "binary"
                    assert client.hello_info.get("ring") == ring_wire
                else:
                    assert client.hello_info == {}
                with pytest.raises(rpc.RPCNotOwner) as exc_info:
                    client.call("Svc.Boom", {}, timeout=5.0)
                assert exc_info.value.ring == ring_wire
                assert "NOT_OWNER" in str(exc_info.value)
                assert client.call("Svc.Ok", {}, timeout=5.0) == {"ok": True}
            finally:
                client.close()
    finally:
        server.shutdown()


# -- round-id namespacing (zombie fencing across pool members) ---------------

def test_round_id_namespace_format_and_split():
    plain = new_round_id(5)
    namespaced = new_round_id(5, "c3")
    assert "." not in plain and len(plain) == 24
    assert namespaced.startswith("c3.") and len(namespaced) == 27
    assert _rid_split(plain) == ("", plain)
    assert _rid_split(namespaced) == ("c3", namespaced[3:])
    # ordering stays meaningful within one namespace
    a, b = new_round_id(5, "c3"), new_round_id(5, "c3")
    assert _rid_order(a) < _rid_order(b)
    # and pre-epoch bare ids still order below epoch-prefixed ones
    assert _rid_order("00ff" + "0" * 12) < _rid_order(plain)


def test_worker_fencing_ignores_cross_namespace_founds():
    """Two pool members fanning to one shared worker: a Found tagged
    with ANOTHER member's namespace must neither cancel nor supersede
    the live round (their id streams are unordered against each
    other); same-namespace newer Founds keep the zombie-popping
    behavior."""
    import distpow_tpu.nodes.worker as worker_mod

    handler = worker_mod.WorkerRPCHandler.__new__(
        worker_mod.WorkerRPCHandler)
    handler._tasks = {}
    handler._tasks_lock = threading.Lock()
    key = (b"\x01\x02", 3, 0)

    live = TaskRound(new_round_id(1, "c0"))
    handler._task_set(key, live)
    foreign = new_round_id(9, "c1")  # later epoch, DIFFERENT member
    assert handler._task_take(key, foreign) is None
    assert handler._task_get(key) is live  # untouched
    assert not live.superseded and not live.ev.is_set()

    newer_same_ns = new_round_id(9, "c0")
    assert handler._task_take(key, newer_same_ns) is None
    assert handler._task_get(key) is None  # zombie popped...
    assert live.superseded and live.ev.is_set()  # ...and woken silent


# -- end-to-end pool ---------------------------------------------------------

def _pool(n_coordinators=2, n_workers=2, **kw):
    return InProcCluster(n_workers=n_workers, backend="python",
                         n_coordinators=n_coordinators, **kw)


def _mine_ok(cluster, nonce: bytes, ntz: int, timeout: float = 30.0):
    cluster.client.mine(nonce, ntz)
    res = cluster.client.notify_queue.get(timeout=timeout)
    assert res.error is None, f"client-visible error: {res.error}"
    assert res.nonce == nonce and res.secret is not None
    assert puzzle.check_secret(nonce, bytes(res.secret), ntz)
    return res


def _nonce_owned_by(ring, member: str, tag: int = 0):
    for i in range(4096):
        nonce = bytes([i % 256, (i // 256) % 256, tag])
        if ring.owner(nonce) == member:
            return nonce
    raise AssertionError(f"no nonce owned by {member}")


def test_pool_serves_both_shards_with_owner_routing():
    cluster = _pool()
    try:
        ring = cluster.client.pow._ring
        before_foreign = metrics.get("cluster.foreign_mines")
        before_redirect = metrics.get("cluster.not_owner_redirects")
        for member in ("c0", "c1"):
            _mine_ok(cluster, _nonce_owned_by(ring, member, tag=1), 1)
        # a correctly-routed pool serves everything at its owner:
        # no redirects, no foreign serves
        assert metrics.get("cluster.foreign_mines") == before_foreign
        assert metrics.get("cluster.not_owner_redirects") == before_redirect
    finally:
        cluster.close()


def test_pool_same_nonce_all_difficulties_hit_one_dominance_cache():
    """The reason the ring keys on the nonce alone: a harder solve for
    a nonce must serve the easier difficulties of the SAME nonce from
    the owner's dominance cache."""
    cluster = _pool()
    try:
        ring = cluster.client.pow._ring
        nonce = _nonce_owned_by(ring, "c1", tag=2)
        _mine_ok(cluster, nonce, 2)
        before_hits = metrics.get("cache.hit")
        t0 = time.monotonic()
        _mine_ok(cluster, nonce, 1)  # dominated by the ntz=2 secret
        assert metrics.get("cache.hit") > before_hits
        assert time.monotonic() - t0 < 5.0
    finally:
        cluster.close()


def test_stale_client_ring_earns_not_owner_and_reroutes():
    """A client routing by a WRONG ring over WARM links (no fresh dial
    — the hello refresh channel cannot teach it) gets the typed
    redirect, adopts the carried snapshot, and completes at the true
    owner — one extra round trip, no retry-budget burn."""
    cluster = _pool()
    try:
        pow_ = cluster.client.pow
        true_ring = pow_._ring
        a0 = true_ring.addr_of("c0")
        # warm the c0 link so the misroute below reuses it (hello
        # extras are consumed at dial time, never re-taught)
        _mine_ok(cluster, _nonce_owned_by(true_ring, "c0", tag=3), 1)
        nonce = _nonce_owned_by(true_ring, "c1", tag=3)
        # a ring that maps EVERY key to c0: the c1-owned key misroutes
        with pow_._ring_lock:
            pow_._ring = HashRing([("c0", a0)])
        before = {k: metrics.get(k) for k in
                  ("cluster.reroutes", "cluster.not_owner_redirects",
                   "powlib.retries")}
        _mine_ok(cluster, nonce, 1)
        assert metrics.get("cluster.not_owner_redirects") > \
            before["cluster.not_owner_redirects"]
        assert metrics.get("cluster.reroutes") > before["cluster.reroutes"]
        # a redirect is the server working as designed, not an outage
        assert metrics.get("powlib.retries") == before["powlib.retries"]
        # the adopted snapshot is the pool's true ring
        assert pow_._ring == true_ring
    finally:
        cluster.close()


def test_retry_after_hedges_to_sibling_without_burning_budget():
    """ISSUE 15 satellite: RETRY_AFTER on the owner routes the request
    to a sibling WITHOUT consuming the retry budget, and the winning
    reply's trace shape is pinned (identical to a plain mine)."""
    from distpow_tpu.runtime.tracing import MemorySink

    sink = MemorySink()
    cluster = _pool(client_extra={})
    try:
        # rebuild the client with a sink so the trace shape is visible
        cluster.client.close()
        from distpow_tpu.nodes import Client
        from distpow_tpu.runtime.config import ClientConfig

        cluster.client = Client(ClientConfig(
            ClientID="hedger", CoordAddr=cluster.client_addr,
            CoordAddrs=cluster.client_addrs, ChCapacity=100,
        ), sink=sink)
        cluster.client.initialize()
        ring = cluster.client.pow._ring
        nonce = _nonce_owned_by(ring, "c0", tag=4)
        # saturate the OWNER's admission plane: every Mine it receives
        # is shed with the typed RETRY_AFTER
        owner_handler = cluster.coordinators[0].handler
        owner_handler._sched_max_inflight = 1
        owner_handler._sched_inflight = 1
        before = {k: metrics.get(k) for k in
                  ("powlib.retries", "powlib.retry_after",
                   "cluster.sibling_hedges", "cluster.foreign_mines",
                   "sched.admission_rejected")}
        t0 = time.monotonic()
        _mine_ok(cluster, nonce, 1)
        wall = time.monotonic() - t0
        assert metrics.get("sched.admission_rejected") > \
            before["sched.admission_rejected"]
        assert metrics.get("cluster.sibling_hedges") > \
            before["cluster.sibling_hedges"]
        assert metrics.get("cluster.foreign_mines") > \
            before["cluster.foreign_mines"]
        assert metrics.get("powlib.retry_after") > \
            before["powlib.retry_after"]
        # NON-COUNTING: the transport retry budget is untouched
        assert metrics.get("powlib.retries") == before["powlib.retries"]
        # hedged, not parked: the sibling absorbed the mine immediately
        # instead of the client waiting out the owner's pacing hint
        assert wall < 10.0
        # the winning reply's trace shape is the plain-mine shape
        names = [a[1] for a in sink.actions()]
        assert names == ["PowlibMiningBegin", "PowlibMine",
                         "PowlibSuccess", "PowlibMiningComplete"]
    finally:
        cluster.close()


def test_dead_owner_with_saturated_sibling_stays_non_counting():
    """Review PR 10 regression: owner shard dead AND the failover
    sibling shedding load — every server-paced retry must stay on the
    live (merely busy) sibling instead of bouncing to the dead owner,
    which would burn one transport-budget unit per pacing hint and
    degrade the mine."""
    cluster = _pool(client_extra={"MineBackoffS": 0.05,
                                  "MineBackoffMaxS": 0.2})
    try:
        ring = cluster.client.pow._ring
        nonce = _nonce_owned_by(ring, "c0", tag=11)
        cluster.coordinators[0].shutdown()  # the OWNER dies
        sib = cluster.coordinators[1].handler
        sib._sched_retry_after_s = 0.05
        sib._sched_max_inflight = 1
        sib._sched_inflight = 1  # saturated: every Mine is shed
        releases = threading.Timer(
            1.0, lambda: setattr(sib, "_sched_inflight", 0))
        releases.start()
        before = {k: metrics.get(k) for k in
                  ("powlib.retries", "powlib.retry_after",
                   "powlib.degraded")}
        _mine_ok(cluster, nonce, 1, timeout=30.0)
        releases.join()
        d_retries = metrics.get("powlib.retries") - before["powlib.retries"]
        d_after = (metrics.get("powlib.retry_after")
                   - before["powlib.retry_after"])
        assert metrics.get("powlib.degraded") == before["powlib.degraded"]
        # ~1s of 0.05s pacing hints: many server-paced retries...
        assert d_after >= 3
        # ...but the transport budget was charged ONLY for the initial
        # dead-owner failure(s), never once per pacing hint
        assert d_retries <= 3, \
            f"{d_retries} budget units burned across {d_after} pacing hints"
    finally:
        cluster.close()


def test_attempt_timeout_on_healthy_shard_does_not_fail_over():
    """Review PR 10 regression: a transport-class failure on a HEALTHY
    connection (attempt timeout — the response frame is merely slow)
    must re-issue on the same shard like single-coordinator mode, not
    mis-report a shard death and sacrifice the owner's cache locality
    with a foreign failover."""
    from distpow_tpu.runtime import faults

    prev_plan = faults.PLAN
    cluster = _pool(client_extra={"MineAttemptTimeoutS": 0.4,
                                  "MineBackoffS": 0.05,
                                  "MineBackoffMaxS": 0.2})
    try:
        ring = cluster.client.pow._ring
        nonce = _nonce_owned_by(ring, "c1", tag=12)
        _mine_ok(cluster, nonce, 1)  # warm: links dialed, pool healthy
        # delay exactly ONE Mine dispatch past the attempt timeout —
        # the connection stays healthy throughout
        faults.install_from_spec({"seed": 151, "rules": [
            {"kind": "delay", "side": "server",
             "method": "CoordRPCHandler.Mine", "delay_s": 1.2, "max": 1},
        ]})
        before = {k: metrics.get(k) for k in
                  ("cluster.failovers", "cluster.foreign_mines",
                   "powlib.retries")}
        nonce2 = _nonce_owned_by(ring, "c1", tag=13)
        _mine_ok(cluster, nonce2, 1, timeout=30.0)
        assert metrics.get("powlib.retries") > before["powlib.retries"]
        assert metrics.get("cluster.failovers") == \
            before["cluster.failovers"]
        assert metrics.get("cluster.foreign_mines") == \
            before["cluster.foreign_mines"]
    finally:
        faults.install(prev_plan)
        cluster.close()


def test_fresh_dial_hello_ack_refreshes_stale_ring():
    """The extended rpc.hello's ring advertisement is a live refresh
    channel: a client whose stale ring routes a fresh dial at the
    wrong member adopts the advertised ring BEFORE issuing — no
    NOT_OWNER round trip needed."""
    cluster = _pool()
    try:
        pow_ = cluster.client.pow
        true_ring = pow_._ring
        nonce = _nonce_owned_by(true_ring, "c1", tag=14)
        a0, a1 = (true_ring.addr_of("c0"), true_ring.addr_of("c1"))
        with pow_._ring_lock:
            pow_._ring = HashRing([("c0", a1), ("c1", a0)])
            pow_._links = {}  # force fresh dials, whose hellos advertise
        before = {k: metrics.get(k) for k in
                  ("cluster.reroutes", "cluster.not_owner_redirects")}
        _mine_ok(cluster, nonce, 1)
        assert pow_._ring == true_ring
        # the hello taught the client before any misroute reached a
        # coordinator: no redirect was minted anywhere
        assert metrics.get("cluster.not_owner_redirects") == \
            before["cluster.not_owner_redirects"]
        assert metrics.get("cluster.reroutes") == \
            before["cluster.reroutes"]
    finally:
        cluster.close()


def test_client_single_entry_coord_addrs_is_honored():
    """Review PR 10 regression: CoordAddrs=[one-addr] with an empty
    CoordAddr must dial that one address (plain single mode), not the
    empty default."""
    from distpow_tpu.nodes import Client
    from distpow_tpu.runtime.config import ClientConfig

    cluster = _pool(n_coordinators=1)
    try:
        c = Client(ClientConfig(
            ClientID="solo", CoordAddr="",
            CoordAddrs=[cluster.client_addr], ChCapacity=10,
        ))
        c.initialize()
        try:
            assert c.pow._ring is None  # one member = plain single mode
            assert c.pow.coord_addr == cluster.client_addr
            c.mine(b"\x0f\x01", 1)
            assert c.notify_queue.get(timeout=30).error is None
        finally:
            c.close()
    finally:
        cluster.close()


def test_shard_death_fails_over_with_zero_client_errors():
    """Chaos acceptance (in-process half; scripts/cluster_smoke.py does
    the real-SIGKILL version): kill one of two coordinators while keys
    it owns are mined — every mine completes via ring failover, no
    client-visible errors."""
    cluster = _pool(client_extra={"MineBackoffS": 0.05,
                                  "MineBackoffMaxS": 0.3})
    try:
        ring = cluster.client.pow._ring
        victim = "c1"
        nonces = [_nonce_owned_by(ring, m, tag=5 + i)
                  for i, m in enumerate(("c0", "c1", "c1", "c0"))]
        _mine_ok(cluster, nonces[0], 1)  # warm: the pool serves
        before = metrics.get("cluster.failovers")
        cluster.coordinators[1].shutdown()
        for nonce in nonces[1:]:
            _mine_ok(cluster, nonce, 1)
        assert metrics.get("cluster.failovers") > before
        snap = metrics.snapshot()["histograms"].get("cluster.failover_s")
        assert snap and snap["count"] >= 1
        assert ring.owner(nonces[1]) == victim  # the dead shard's key
    finally:
        cluster.close()


def test_pool_under_open_loop_load_with_mid_run_shard_kill():
    """The PR 7 harness drives a 2-member pool while one member dies
    mid-load: zero client-visible Mine errors (acceptance criterion)."""
    from distpow_tpu.load.loadgen import LoadMix, OpenLoopRunner, \
        build_schedule

    cluster = _pool(client_extra={"MineBackoffS": 0.05,
                                  "MineBackoffMaxS": 0.3,
                                  "MineRetries": 8})
    try:
        mix = LoadMix(rate_hz=20.0, duration_s=1.5, seed=7,
                      n_keys=64, zipf_s=0.0, difficulties=((1, 1.0),))
        schedule = build_schedule(mix)
        done, errors = [0], []
        stop = threading.Event()

        def drain():
            q = cluster.client.notify_queue
            while not stop.is_set():
                try:
                    res = q.get(timeout=0.05)
                except queue.Empty:
                    continue
                done[0] += 1
                if res.error:
                    errors.append(str(res.error))

        drainer = threading.Thread(target=drain, daemon=True)
        drainer.start()
        killer = threading.Timer(0.5, cluster.coordinators[1].shutdown)
        killer.start()
        report = OpenLoopRunner(
            lambda arr: cluster.client.mine(arr.nonce, arr.ntz)
        ).run(schedule)
        killer.join()
        deadline = time.monotonic() + 60.0
        expected = report.issued - report.submit_errors
        while done[0] < expected and time.monotonic() < deadline:
            time.sleep(0.02)
        stop.set()
        drainer.join(timeout=2.0)
        assert report.submit_errors == 0
        assert done[0] == expected, \
            f"only {done[0]}/{expected} completions after shard kill"
        assert errors == [], f"client-visible errors: {errors[:3]}"
    finally:
        cluster.close()


# -- shared-worker reply-to routing ------------------------------------------

def test_pooled_rounds_stamp_reply_to_and_workers_route_home():
    """Each member's rounds carry its own worker-facing address, and
    the shared workers' forwarder delivers Results there — the config
    default (member 0) must not receive member 1's results."""
    cluster = _pool()
    try:
        ring = cluster.client.pow._ring
        h0, h1 = (c.handler for c in cluster.coordinators)
        assert h0.reply_addr and h1.reply_addr
        assert h0.reply_addr != h1.reply_addr
        params = h1._mine_params(
            _FakeTrace(), b"\x01", 1, 0, "c1.deadbeef")
        assert params["coord_addr"] == h1.reply_addr
        before = metrics.get("coord.mine_rpcs")
        # e2e: a c1-owned mine completes => its Results reached c1
        # (c0 would drop them as unknown-task noise and c1's round
        # would hang past this timeout)
        _mine_ok(cluster, _nonce_owned_by(ring, "c1", tag=9), 1,
                 timeout=20.0)
        assert metrics.get("coord.mine_rpcs") > before
    finally:
        cluster.close()


class _FakeTrace:
    trace_id = 1

    def record_action(self, *a, **k):
        pass

    def generate_token(self):
        return json.dumps({"trace_id": 1}).encode()


# -- discovery + config generation -------------------------------------------

def test_discover_expands_pool_and_dedup_merges_members():
    from distpow_tpu.cli.stats import discover_cluster_addrs

    cluster = _pool(n_workers=2)
    try:
        # ONE seed expands to the whole pool (the Stats snapshot's
        # ring) and merges both members' Fleet.Members tables
        addrs = discover_cluster_addrs(cluster.client_addrs[0])
        for coord_addr in cluster.client_addrs:
            assert coord_addr in addrs
        for worker_addr in cluster.worker_addrs:
            assert worker_addr in addrs
        assert len(addrs) == len(set(addrs))  # dedup
        # multiple seeds (the repeatable --discover flag) dedup too
        addrs2 = discover_cluster_addrs(list(cluster.client_addrs))
        assert sorted(addrs2) == sorted(addrs)
    finally:
        cluster.close()


def test_config_gen_coordinators_emits_round_tripping_pool(tmp_path):
    from distpow_tpu.cli import config_gen
    from distpow_tpu.runtime.config import (
        ClientConfig,
        CoordinatorConfig,
        read_json_config,
    )

    d = str(tmp_path)
    config_gen.main(["--config-dir", d, "--workers", "2",
                     "--coordinators", "3", "--seed", "11"])
    paths = [os.path.join(d, "coordinator_config.json"),
             os.path.join(d, "coordinator1_config.json"),
             os.path.join(d, "coordinator2_config.json")]
    coords = [read_json_config(p, CoordinatorConfig) for p in paths]
    peers = coords[0].ClusterPeers
    assert len(peers) == 3 and len(set(peers)) == 3
    for i, c in enumerate(coords):
        assert c.ClusterPeers == peers
        assert c.ClusterSelf == i
        assert c.ClientAPIListenAddr == peers[i]
        assert c.Workers == coords[0].Workers  # ONE shared fleet
        assert len(c.Workers) == 2
    listen_addrs = {c.WorkerAPIListenAddr for c in coords}
    assert len(listen_addrs) == 3
    client = read_json_config(os.path.join(d, "client_config.json"),
                              ClientConfig)
    assert client.CoordAddrs == peers
    assert client.CoordAddr == peers[0]
    # the ring both sides derive from those configs is identical
    assert ring_from_peers(peers) == ring_from_peers(client.CoordAddrs)

    # inherited per-process paths get per-shard suffixes (two shards
    # sharing one cache journal would corrupt both)
    d3 = str(tmp_path / "paths")
    os.makedirs(d3)
    from distpow_tpu.runtime.config import write_json_config
    write_json_config(os.path.join(d3, "coordinator_config.json"),
                      CoordinatorConfig(CacheFile="/var/x.journal",
                                        TelemetryDir="/var/tel"))
    config_gen.main(["--config-dir", d3, "--workers", "2",
                     "--coordinators", "2", "--seed", "13"])
    c0 = read_json_config(os.path.join(d3, "coordinator_config.json"),
                          CoordinatorConfig)
    c1 = read_json_config(os.path.join(d3, "coordinator1_config.json"),
                          CoordinatorConfig)
    assert c0.CacheFile == "/var/x.journal"
    assert c1.CacheFile == "/var/x.journal.c1"
    assert c0.TelemetryDir != c1.TelemetryDir

    # --coordinators 1 (the default) emits the historical single shape
    d2 = str(tmp_path / "single")
    config_gen.main(["--config-dir", d2, "--workers", "2", "--seed", "12"])
    single = read_json_config(
        os.path.join(d2, "coordinator_config.json"), CoordinatorConfig)
    assert single.ClusterPeers == [] and single.ClusterSelf == -1
    sclient = read_json_config(os.path.join(d2, "client_config.json"),
                               ClientConfig)
    assert sclient.CoordAddrs == []
    assert not os.path.exists(os.path.join(d2, "coordinator1_config.json"))


def test_cluster_ring_rpc_and_invalid_self_rejected():
    cluster = _pool()
    try:
        client = rpc.RPCClient(cluster.client_addrs[1], codec="json")
        try:
            reply = client.call("Cluster.Ring", {}, timeout=5.0)
        finally:
            client.close()
        assert reply["self"] == "c1"
        assert HashRing.from_wire(reply["ring"]) == \
            cluster.client.pow._ring
    finally:
        cluster.close()
    from distpow_tpu.nodes import Coordinator
    from distpow_tpu.runtime.config import CoordinatorConfig

    with pytest.raises(ValueError):
        Coordinator(CoordinatorConfig(
            ClientAPIListenAddr="127.0.0.1:0",
            WorkerAPIListenAddr="127.0.0.1:0",
            Workers=["pending:0"],
            ClusterPeers=["a:1", "b:2"], ClusterSelf=7,
        ))


# -- cache replication / HA (ISSUE 16, docs/CLUSTER.md "Replication & HA") ---

def _wait_for(pred, timeout_s=10.0, what="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


def test_write_behind_replicates_entry_to_ring_successor():
    """The tentpole's core promise: a round completed at the owner
    lands on the key's ring successor via the write-behind push —
    observable as the SIBLING's dominance cache holding the entry."""
    cluster = _pool()
    try:
        ring = cluster.client.pow._ring
        nonce = _nonce_owned_by(ring, "c1", tag=20)
        sibling = cluster.coordinators[0].handler.result_cache
        assert sibling.peek(nonce) is None
        before = metrics.get("repl.installs")
        _mine_ok(cluster, nonce, 2)
        # the push is write-BEHIND: off the Mine path, so the entry
        # arrives shortly after, not synchronously with, the reply
        _wait_for(lambda: sibling.peek(nonce) is not None,
                  what="replica install on the sibling")
        entry = sibling.peek(nonce)
        assert entry.num_trailing_zeros >= 2
        assert puzzle.check_secret(nonce, entry.secret, 2)
        assert metrics.get("repl.installs") > before
        snap = metrics.snapshot()["histograms"].get("repl.push_lag_s")
        assert snap and snap["count"] >= 1
    finally:
        cluster.close()


def test_survivor_serves_dead_members_repeat_key_from_replica():
    """The HA acceptance gate's in-process half (scripts/ha_smoke.py
    does the real-SIGKILL version): kill the owner AFTER its entry
    replicated — the repeat key rides ring-walk failover to the
    survivor and is served from the REPLICATED dominance cache (a
    CacheHit, not a re-mine)."""
    cluster = _pool(client_extra={"MineBackoffS": 0.05,
                                  "MineBackoffMaxS": 0.3})
    try:
        ring = cluster.client.pow._ring
        nonce = _nonce_owned_by(ring, "c1", tag=21)
        survivor = cluster.coordinators[0].handler.result_cache
        _mine_ok(cluster, nonce, 2)
        _wait_for(lambda: survivor.peek(nonce) is not None,
                  what="replica install on the survivor")
        cluster.kill_coordinator(1)  # the OWNER dies
        before_hits = metrics.get("cache.hit")
        before_fanouts = metrics.get("coord.fanouts")
        t0 = time.monotonic()
        _mine_ok(cluster, nonce, 1)  # dominated by the replicated ntz=2
        wall = time.monotonic() - t0
        assert metrics.get("cache.hit") > before_hits
        # served warm: the survivor never fanned a mining round out
        assert metrics.get("coord.fanouts") == before_fanouts
        assert wall < 10.0
    finally:
        cluster.close()


def test_stale_push_is_dropped_not_regressed():
    """Dominance under replication: a push carrying FEWER trailing
    zeros than the replica already holds is rejected by the same
    order every install rides — counted as repl.stale_drops, and the
    replica's entry is untouched."""
    from distpow_tpu.cluster import Replicator, entry_wire
    from distpow_tpu.runtime.cache import ResultCache

    cache = ResultCache()
    cache.add(b"\xaa\x01", 5, b"high-secret", trace=None)
    repl = Replicator(cache, replicas=0)  # install path needs no threads
    before_stale = metrics.get("repl.stale_drops")
    before_inst = metrics.get("repl.installs")
    installed, stale = repl.install([
        entry_wire(b"\xaa\x01", 3, b"late-low"),   # stale: lower ntz
        entry_wire(b"\xaa\x02", 4, b"fresh"),      # new key: installs
    ])
    assert (installed, stale) == (1, 1)
    assert cache.peek(b"\xaa\x01").secret == b"high-secret"
    assert cache.peek(b"\xaa\x01").num_trailing_zeros == 5
    assert cache.peek(b"\xaa\x02").num_trailing_zeros == 4
    assert metrics.get("repl.stale_drops") == before_stale + 1
    assert metrics.get("repl.installs") == before_inst + 1
    repl.close()


def test_push_queue_overflow_drops_and_counts():
    """The write-behind queue is BOUNDED: overflow is a counted drop
    (anti-entropy heals it later), never backpressure into the Result
    handler."""
    from distpow_tpu.cluster import Replicator
    from distpow_tpu.runtime.cache import ResultCache

    repl = Replicator(ResultCache(), replicas=1, queue_depth=1)
    # state installed directly so no pusher thread drains the queue
    repl._state = ClusterState(ring_from_peers(["a:1", "b:2"]), "c0")
    before = metrics.get("repl.push_failures")
    assert repl.offer(b"\x01", 1, b"s1") is True
    assert repl.offer(b"\x02", 1, b"s2") is False  # queue full: dropped
    assert metrics.get("repl.push_failures") == before + 1
    repl.close()


def test_antientropy_heals_entry_missed_by_write_behind():
    """A replica that was down (or a dropped push) misses write-behind
    traffic; the digest exchange finds the diverged range and heals
    exactly it.  The sweep is invoked directly — deterministic, no
    interval sleeps — with the pool's timer loop disabled."""
    cluster = _pool(coord_extra={"ClusterAntiEntropyS": 0.0})
    try:
        ring = cluster.client.pow._ring
        owner = cluster.coordinators[1]
        sibling_cache = cluster.coordinators[0].handler.result_cache
        # install at the owner BEHIND the replication plane's back —
        # the stand-in for an entry whose push was lost
        nonce = _nonce_owned_by(ring, "c1", tag=22)
        owner.handler.result_cache.add(nonce, 3, b"healed-secret",
                                       trace=None)
        assert sibling_cache.peek(nonce) is None
        before_rounds = metrics.get("repl.antientropy_rounds")
        healed = owner._replicator.antientropy_sweep()
        assert healed >= 1
        entry = sibling_cache.peek(nonce)
        assert entry is not None and entry.secret == b"healed-secret"
        assert metrics.get("repl.antientropy_rounds") == before_rounds + 1
        # convergence: the next sweep finds nothing to heal
        assert owner._replicator.antientropy_sweep() == 0
    finally:
        cluster.close()


def _handoff_rig(peers_old, peers_new, sender_id, receiver_ids):
    """Real-RPC handoff rig: one listening receiver per new owner,
    each with its own cache + install-path Replicator; the sender is a
    thread-less Replicator over a pre-populated cache."""
    from distpow_tpu.cluster import ClusterService, Replicator
    from distpow_tpu.runtime.cache import ResultCache

    receivers = {}
    addr_by_id = dict(peers_new)
    for rid in receiver_ids:
        server = rpc.RPCServer()
        cache = ResultCache()
        repl = Replicator(cache, replicas=0)
        addr = server.listen("127.0.0.1:0")
        addr_by_id[rid] = addr
        server.serve_in_background()
        receivers[rid] = (server, cache, repl)
    old_ring = HashRing([(m, addr_by_id.get(m, a))
                         for m, a in peers_old])
    new_ring = HashRing([(m, addr_by_id.get(m, a)) for m, a in peers_new],
                        version=1)
    for rid, (server, cache, repl) in receivers.items():
        state = ClusterState(new_ring, rid)
        repl._state = state
        server.register("Cluster", ClusterService(state, replicator=repl))
    sender_cache = ResultCache()
    sender = Replicator(sender_cache, replicas=0)
    sender._state = ClusterState(old_ring, sender_id)
    return old_ring, new_ring, sender, sender_cache, receivers


def test_handoff_grow_moves_exactly_the_remapped_keys():
    """Warm handoff property, N -> N+1: exactly the keys whose owner
    changed from the sender to the NEW member arrive there — every one
    of them, and nothing else."""
    peers_old = [("c0", "o0:1"), ("c1", "o1:1")]
    peers_new = [("c0", "o0:1"), ("c1", "o1:1"), ("c2", None)]
    old_ring, new_ring, sender, sender_cache, receivers = _handoff_rig(
        peers_old, peers_new, "c0", ["c2"])
    try:
        nonces = _sample_nonces(600)
        for i, n in enumerate(nonces):
            if old_ring.owner(n) == "c0":
                sender_cache.add(n, 1 + i % 3, b"s%d" % i, trace=None)
        moved = {n for n, _z, _s in sender_cache.entries_snapshot()
                 if new_ring.owner(n) == "c2"}
        assert moved, "fixture must remap at least one key"
        result = sender.handoff(old_ring, new_ring, deadline_s=20.0)
        assert result["complete"] is True
        assert result["keys"] == result["expected"] == len(moved)
        _server, recv_cache, _repl = receivers["c2"]
        arrived = {n for n, _z, _s in recv_cache.entries_snapshot()}
        assert arrived == moved  # every remapped key, nothing else
        # and each arrived entry carries the sender's exact payload
        for n in moved:
            assert recv_cache.peek(n).secret == sender_cache.peek(n).secret
    finally:
        sender.close()
        for server, _c, repl in receivers.values():
            repl.close()
            server.shutdown()


def test_handoff_shrink_moves_all_leaving_members_keys_to_survivors():
    """Warm handoff property, N+1 -> N: the LEAVING member's whole key
    range lands on the survivors the new ring assigns — partitioned
    exactly, nothing misdelivered, dominance preserved when a survivor
    already holds a better entry (counted as repl.stale_drops)."""
    peers_old = [("c0", None), ("c1", None), ("c2", "gone:1")]
    peers_new = [("c0", None), ("c1", None)]
    old_ring, new_ring, sender, sender_cache, receivers = _handoff_rig(
        peers_old, peers_new, "c2", ["c0", "c1"])
    try:
        nonces = _sample_nonces(600)
        for i, n in enumerate(nonces):
            if old_ring.owner(n) == "c2":
                sender_cache.add(n, 2, b"from-c2-%d" % i, trace=None)
        owned = {n for n, _z, _s in sender_cache.entries_snapshot()}
        assert owned
        # one survivor already DOMINATES one moved key: the handoff
        # push for it must be a stale drop, not a regression
        pinned = next(n for n in owned if new_ring.owner(n) == "c0")
        receivers["c0"][1].add(pinned, 9, b"better", trace=None)
        before_stale = metrics.get("repl.stale_drops")
        result = sender.handoff(old_ring, new_ring, deadline_s=20.0)
        assert result["complete"] is True
        assert result["keys"] == len(owned)
        for rid in ("c0", "c1"):
            expect = {n for n in owned if new_ring.owner(n) == rid}
            got = {n for n, _z, _s in
                   receivers[rid][1].entries_snapshot()}
            assert got == expect, f"misdelivered handoff range for {rid}"
        assert receivers["c0"][1].peek(pinned).secret == b"better"
        assert metrics.get("repl.stale_drops") > before_stale
    finally:
        sender.close()
        for server, _c, repl in receivers.values():
            repl.close()
            server.shutdown()


def test_handoff_deadline_bounds_a_frozen_recipient():
    """A recipient that never answers costs the sender at most the
    handoff deadline — the ring change is delayed, never wedged; the
    result reports the incompleteness anti-entropy will heal."""
    from distpow_tpu.cluster import Replicator
    from distpow_tpu.runtime.cache import ResultCache

    # a listening socket that accepts and then says NOTHING
    import socket

    frozen = socket.socket()
    frozen.bind(("127.0.0.1", 0))
    frozen.listen(1)
    addr = "127.0.0.1:%d" % frozen.getsockname()[1]
    old_ring = HashRing([("c0", "o0:1"), ("c1", addr)])
    new_ring = HashRing([("c0", "o0:1"), ("c1", addr)], version=1)
    # force a remap by building the new ring with an extra member and
    # sending to the frozen one: simplest is old=solo-owner, new=pair
    old_ring = HashRing([("c0", "o0:1")])
    sender_cache = ResultCache()
    sender = Replicator(sender_cache, replicas=0)
    sender._state = ClusterState(old_ring, "c0")
    for n in _sample_nonces(64):
        sender_cache.add(n, 1, b"x", trace=None)
    moved = [n for n, _z, _s in sender_cache.entries_snapshot()
             if new_ring.owner(n) == "c1"]
    assert moved
    try:
        t0 = time.monotonic()
        result = sender.handoff(old_ring, new_ring, deadline_s=1.0)
        wall = time.monotonic() - t0
        assert wall < 8.0, f"frozen recipient held the handoff {wall:.1f}s"
        assert result["complete"] is False
        assert result["keys"] < result["expected"]
    finally:
        sender.close()
        frozen.close()


def test_membership_change_hands_off_before_installing_new_ring():
    """Coordinator-level wiring: re-invoking set_cluster_peers with a
    grown pool runs the warm handoff BEFORE the new ring is installed,
    bumps the ring version (so clients adopt), and the new member
    starts WARM for the ranges it inherited."""
    cluster = _pool()
    extra = None
    try:
        ring0 = cluster.coordinators[0].handler.cluster.ring
        assert ring0.version == 0
        # pre-warm member 0 with entries across its range
        for i, n in enumerate(_sample_nonces(400)):
            if ring0.owner(n) == "c0":
                cluster.coordinators[0].handler.result_cache.add(
                    n, 2, b"warm%d" % i, trace=None)
        # boot the joining third member and rewire the whole pool
        from distpow_tpu.nodes import Coordinator
        from distpow_tpu.runtime.config import CoordinatorConfig

        extra = Coordinator(CoordinatorConfig(
            ClientAPIListenAddr="127.0.0.1:0",
            WorkerAPIListenAddr="127.0.0.1:0",
            Workers=["pending:0"] * len(cluster.worker_addrs),
        ))
        extra_client_addr, _w = extra.initialize_rpcs()
        extra.set_worker_addrs(cluster.worker_addrs)
        peers = cluster.client_addrs + [extra_client_addr]
        # the JOINING member adopts the grown ring first, so it can
        # receive handoff pushes the moment the losers start sending
        extra.set_cluster_peers(peers, 2)
        before_keys = metrics.get("repl.handoff_keys")
        for i, c in enumerate(cluster.coordinators):
            c.set_cluster_peers(peers, i)
        new_ring = cluster.coordinators[0].handler.cluster.ring
        assert new_ring.version == 1
        moved = [n for n, _z, _s in
                 cluster.coordinators[0].handler.result_cache
                 .entries_snapshot()
                 if ring0.owner(n) == "c0" and new_ring.owner(n) == "c2"]
        assert moved, "growing the pool must remap some warmed keys"
        assert metrics.get("repl.handoff_keys") >= before_keys + len(moved)
        recv = extra.handler.result_cache
        for n in moved:
            assert recv.peek(n) is not None, \
                "new member is cold for a handed-off key"
    finally:
        if extra is not None:
            extra.shutdown()
        cluster.close()


def test_single_coordinator_mode_carries_no_replication_plane():
    """Byte-identity pin (acceptance criterion): a single-coordinator
    deployment constructs NO replicator, registers NO Cluster service,
    and mints NO repl.* traffic — every pre-cluster code path runs
    exactly as before."""
    cluster = _pool(n_coordinators=1)
    try:
        coord = cluster.coordinators[0]
        assert coord._replicator is None
        assert coord.handler.replicator is None
        assert coord.handler.cluster is None
        assert "Cluster" not in coord.server._services
        before = {k: metrics.get(k) for k in
                  ("repl.pushes", "repl.installs", "repl.push_failures",
                   "repl.handoff_keys", "repl.antientropy_rounds")}
        _mine_ok(cluster, b"\x77\x01", 1)
        for k, v in before.items():
            assert metrics.get(k) == v, f"{k} moved in single mode"
        snap = coord.handler.Stats({})
        assert "replication" not in snap and "cluster" not in snap
    finally:
        cluster.close()


def test_replication_wire_vocabulary_is_append_only():
    """The CacheSync/Handoff methods and their params extend the
    wire-v2 intern tables at the END — existing frames keep their
    byte encodings (the golden vectors in test_wire.py pin them)."""
    assert wire.METHODS[-2:] == ("Cluster.CacheSync", "Cluster.Handoff")
    assert wire.KEYS[-4:] == ("entries", "digest", "installed", "stale")
    # and a CacheSync frame round-trips on the binary codec
    entries = [{"nonce": b"\x01\x02", "num_trailing_zeros": 3,
                "secret": b"\xaa"}]
    frame = {"id": 1, "method": "Cluster.CacheSync",
             "params": {"entries": entries, "self": "c0"}}
    assert wire.decode_frame(wire.encode_frame(frame)) == frame


def test_cache_sync_rpc_serves_digests_and_rejects_without_replicator():
    """The digest half of Cluster.CacheSync over a real pool: a peer
    asks for this member's view of the requester's replicated range."""
    cluster = _pool(coord_extra={"ClusterAntiEntropyS": 0.0})
    try:
        ring = cluster.client.pow._ring
        nonce = _nonce_owned_by(ring, "c1", tag=23)
        _mine_ok(cluster, nonce, 1)
        sibling = cluster.coordinators[0].handler.result_cache
        _wait_for(lambda: sibling.peek(nonce) is not None,
                  what="replica install before digest probe")
        client = rpc.RPCClient(cluster.client_addrs[0], codec="json")
        try:
            reply = client.call("Cluster.CacheSync",
                                {"digest": 8, "self": "c1"}, timeout=5.0)
            digests = reply["digest"]
            assert len(digests) == 8
            assert sum(d[0] for d in digests) >= 1  # the replica counts
        finally:
            client.close()
    finally:
        cluster.close()


def test_admission_reject_still_typed_for_single_coordinator():
    """Guard: the cluster exception plumbing must not perturb the
    existing RETRY_AFTER typing (both carry extra response fields)."""
    assert issubclass(rpc.RPCNotOwner, rpc.RPCError)
    reject = AdmissionReject(0.25, "full")
    assert reject.retry_after_s == 0.25
    err = NotOwnerError("c2", {"version": 0, "vnodes": 64,
                              "members": [["c2", "x:1"]]})
    assert err.ring_wire["members"] == [["c2", "x:1"]]
