"""Pallas kernel tests (interpret mode on CPU; the driver/bench exercise
the compiled kernel on real TPU hardware)."""

import jax
import jax.numpy as jnp
import pytest

from distpow_tpu.backends.pallas_backend import PallasBackend
from distpow_tpu.models import puzzle
from distpow_tpu.models.registry import MD5
from distpow_tpu.ops.md5_pallas import build_pallas_search_step
from distpow_tpu.ops.search_step import SENTINEL, build_search_step


def test_pallas_matches_xla_step():
    nonce = b"\x01\x02\x03\x04"
    step_p = build_pallas_search_step(nonce, 1, 2, 0, 256, 128, interpret=True)
    step_x = build_search_step(nonce, 1, 2, 0, 256, 128, MD5)
    for c0 in (1, 129, 200):
        assert int(step_p(jnp.uint32(c0))) == int(step_x(jnp.uint32(c0)))


def test_pallas_width2_and_subpartition():
    nonce = b"\x05\x06"
    # 64-thread-byte shard (4-worker partition), width 2
    step_p = build_pallas_search_step(
        nonce, 2, 2, 64, 64, 512, sublanes=8, interpret=True
    )
    step_x = build_search_step(nonce, 2, 2, 64, 64, 512, MD5)
    for c0 in (256, 256 + 512):
        assert int(step_p(jnp.uint32(c0))) == int(step_x(jnp.uint32(c0)))


def test_pallas_no_hit_returns_sentinel():
    step = build_pallas_search_step(b"\x07", 1, 30, 0, 256, 128, interpret=True)
    assert int(step(jnp.uint32(1))) == SENTINEL


def test_pallas_rejects_unsupported_configs():
    with pytest.raises(ValueError, match="power-of-two"):
        build_pallas_search_step(b"\x01", 1, 2, 0, 96, 128, interpret=True)
    with pytest.raises(ValueError, match="md5"):
        build_pallas_search_step(
            b"\x01", 1, 2, 0, 256, 128, model_name="sha256", interpret=True
        )
    with pytest.raises(ValueError, match="single-block"):
        build_pallas_search_step(bytes(60), 4, 2, 0, 256, 128, interpret=True)


def test_pallas_backend_end_to_end():
    backend = PallasBackend(batch_size=1 << 15, sublanes=8, interpret=True)
    nonce = b"\x0a\x0b\x0c"
    tbs = list(range(256))
    secret = backend.search(nonce, 2, tbs)
    assert secret is not None
    assert secret == puzzle.python_search(nonce, 2, tbs)


def test_pallas_backend_falls_back_for_long_nonce():
    # two-block tail -> transparent XLA fallback inside the factory
    backend = PallasBackend(batch_size=1 << 14, sublanes=8, interpret=True)
    nonce = bytes(range(60))
    secret = backend.search(nonce, 1, list(range(256)))
    assert secret is not None
    assert puzzle.check_secret(nonce, secret, 1)
