"""Pallas kernel tests (interpret mode on CPU; the driver/bench exercise
the compiled kernel on real TPU hardware)."""

import jax
import jax.numpy as jnp
import pytest

from distpow_tpu.backends.pallas_backend import PallasBackend
from distpow_tpu.models import puzzle
from distpow_tpu.models.registry import MD5
from distpow_tpu.ops.md5_pallas import build_pallas_search_step
from distpow_tpu.ops.search_step import SENTINEL, build_search_step


def test_pallas_matches_xla_step():
    nonce = b"\x01\x02\x03\x04"
    step_p = build_pallas_search_step(nonce, 1, 2, 0, 256, 128, interpret=True)
    step_x = build_search_step(nonce, 1, 2, 0, 256, 128, MD5)
    for c0 in (1, 129, 200):
        assert int(step_p(jnp.uint32(c0))) == int(step_x(jnp.uint32(c0)))


def test_pallas_width2_and_subpartition():
    nonce = b"\x05\x06"
    # 64-thread-byte shard (4-worker partition), width 2
    step_p = build_pallas_search_step(
        nonce, 2, 2, 64, 64, 512, sublanes=8, interpret=True
    )
    step_x = build_search_step(nonce, 2, 2, 64, 64, 512, MD5)
    for c0 in (256, 256 + 512):
        assert int(step_p(jnp.uint32(c0))) == int(step_x(jnp.uint32(c0)))


def test_pallas_no_hit_returns_sentinel():
    step = build_pallas_search_step(b"\x07", 1, 30, 0, 256, 128, interpret=True)
    assert int(step(jnp.uint32(1))) == SENTINEL


def test_pallas_rejects_unsupported_configs():
    with pytest.raises(ValueError, match="power-of-two"):
        build_pallas_search_step(b"\x01", 1, 2, 0, 96, 128, interpret=True)
    with pytest.raises(ValueError, match="single-block"):
        build_pallas_search_step(bytes(60), 4, 2, 0, 256, 128, interpret=True)


def test_default_geometry_resolution_at_every_site():
    """The interpret-mode sublanes cap must hold at every resolution
    site (ops/md5_pallas.py default_geometry): serving gets the swept
    MODEL_GEOMETRY entry, interpret mode is capped at 8 (the serving
    geometry's interpret compile is pathological on XLA:CPU), and an
    explicit override always wins."""
    from distpow_tpu.ops.md5_pallas import MODEL_GEOMETRY, default_geometry

    assert default_geometry("sha256") == MODEL_GEOMETRY["sha256"]
    assert default_geometry("sha256", interpret=True)[0] == 8
    assert default_geometry("md5", interpret=True)[0] == 8
    # PallasBackend resolves through the same helper
    assert PallasBackend(hash_model="sha256").sublanes == \
        MODEL_GEOMETRY["sha256"][0]
    assert PallasBackend(hash_model="sha256", interpret=True).sublanes == 8
    assert PallasBackend(hash_model="sha256", interpret=True,
                         sublanes=16).sublanes == 16
    # ...and so does the pallas-mesh step factory (the third site)
    import jax
    from distpow_tpu.models.registry import SHA256
    from distpow_tpu.parallel.mesh_search import (
        AXIS,
        _pallas_mesh_step_factory,
        make_mesh,
    )

    mesh = make_mesh(jax.devices()[:8])
    f_serve = _pallas_mesh_step_factory(
        b"\x01", 8, 0, 256, SHA256, mesh, AXIS)
    f_interp = _pallas_mesh_step_factory(
        b"\x01", 8, 0, 256, SHA256, mesh, AXIS, interpret=True)
    assert f_serve.sublanes == MODEL_GEOMETRY["sha256"][0]
    assert f_interp.sublanes == 8


def test_pallas_backend_end_to_end():
    backend = PallasBackend(batch_size=1 << 15, sublanes=8, interpret=True)
    nonce = b"\x0a\x0b\x0c"
    tbs = list(range(256))
    secret = backend.search(nonce, 2, tbs)
    assert secret is not None
    assert secret == puzzle.python_search(nonce, 2, tbs)


def test_pallas_backend_serves_sha1_with_kernel():
    # sha1 has a _TILE_FNS entry since round 3 — served by the kernel,
    # reference enumeration order
    backend = PallasBackend(hash_model="sha1", batch_size=1 << 14,
                            interpret=True)
    nonce = b"\x11\x22"
    secret = backend.search(nonce, 2, list(range(256)))
    assert secret == puzzle.python_search(nonce, 2, list(range(256)),
                                          algo="sha1")


def test_pallas_backend_serves_ripemd160_with_kernel():
    # fourth model (round 4): the two-line tile serves through the
    # kernel path in reference enumeration order
    backend = PallasBackend(hash_model="ripemd160", batch_size=1 << 14,
                            interpret=True)
    nonce = b"\x33\x44"
    secret = backend.search(nonce, 2, list(range(256)))
    assert secret == puzzle.python_search(nonce, 2, list(range(256)),
                                          algo="ripemd160")


def test_pallas_backend_falls_back_for_sha512():
    # sha512 HAS a kernel tile since round 4, but it is TPU-only
    # (INTERPRET_XLA_FALLBACK: the interpret-mode XLA:CPU compile of
    # the unrolled limb-pair graph is pathological) — under
    # interpret=True the backend must still serve through the
    # transparent XLA fallback
    backend = PallasBackend(hash_model="sha512", batch_size=1 << 13,
                            interpret=True)
    nonce = b"\x55\x66"
    secret = backend.search(nonce, 2, list(range(256)))
    assert secret == puzzle.python_search(nonce, 2, list(range(256)),
                                          algo="sha512")


def test_pallas_backend_falls_back_for_model_without_kernel(monkeypatch):
    # a registry model WITHOUT a kernel entry -> transparent XLA
    # fallback (all three shipped models have kernels now, so the
    # branch is exercised by deleting one)
    from distpow_tpu.ops import md5_pallas

    monkeypatch.delitem(md5_pallas._TILE_FNS, "sha1")
    backend = PallasBackend(hash_model="sha1", batch_size=1 << 14,
                            interpret=True)
    # different nonce from the kernel test above: the layout-keyed
    # program cache would otherwise return the already-built kernel
    # step without ever consulting the patched _TILE_FNS
    nonce = b"\x33\x44"
    secret = backend.search(nonce, 2, list(range(256)))
    assert secret == puzzle.python_search(nonce, 2, list(range(256)),
                                          algo="sha1")


def test_pallas_backend_falls_back_for_long_nonce():
    # two-block tail -> transparent XLA fallback inside the factory
    backend = PallasBackend(batch_size=1 << 14, sublanes=8, interpret=True)
    nonce = bytes(range(60))
    secret = backend.search(nonce, 1, list(range(256)))
    assert secret is not None
    assert puzzle.check_secret(nonce, secret, 1)


def test_pallas_launch_steps_extends_grid():
    # k sub-batches in one dispatch == k sequential dispatches' minimum
    nonce = b"\x11\x12\x13"
    step_k = build_pallas_search_step(
        nonce, 1, 2, 0, 256, 4, sublanes=8, interpret=True, launch_steps=3
    )
    step_1 = build_search_step(nonce, 1, 2, 0, 256, 4, MD5)
    for c0 in (1, 64):
        got = int(step_k(jnp.uint32(c0)))
        best = SENTINEL
        for i in range(3):
            f = int(step_1(jnp.uint32(c0 + 4 * i)))
            if f != SENTINEL:
                best = min(best, f + i * 4 * 256)
        assert got == best


def test_pallas_launch_bound_enforced():
    with pytest.raises(ValueError, match="2\\^31"):
        build_pallas_search_step(
            b"\x01", 4, 2, 0, 256, 1 << 16, sublanes=8, interpret=True,
            launch_steps=1 << 8,
        )


def test_sha256_tile_matches_hashlib_all_buckets():
    """The DCE'd functional-form SHA-256 tile (ops/md5_pallas.py
    _sha256_tile) must reproduce hashlib's digest words for every
    mask-word bucket, with exactly the dead words elided.  Eager mode:
    the unrolled 64-round graph is too slow for XLA:CPU to compile per
    bucket, but op-by-op eager dispatch is instant."""
    import hashlib
    import struct

    from distpow_tpu.models.sha256_jax import SHA256_INIT
    from distpow_tpu.ops.md5_pallas import _sha256_tile

    msg = b"\x01\x02\x03\x04" + b"\x99\x11\x22\x33\x44"
    tail = (msg + b"\x80" + b"\x00" * (64 - len(msg) - 9)
            + struct.pack(">Q", len(msg) * 8))
    words = [jnp.uint32(w) for w in struct.unpack(">16I", tail)]
    init = [jnp.uint32(s) for s in SHA256_INIT]
    ref_words = struct.unpack(">8I", hashlib.sha256(msg).digest())
    for mw in range(1, 9):
        out = _sha256_tile(words, init, mw)
        for j in range(8):
            if j < 8 - mw:
                assert out[j] is None
            else:
                assert int(out[j]) == ref_words[j], (mw, j)


def test_sha256_tile_randomized_batch_words():
    """Property test: the tile function on BATCH-SHAPED message words
    (the kernel's real operand shape, exercising the non-scalar branch
    of the K+w fold) matches hashlib lane-for-lane across random
    messages and every DCE bucket."""
    import hashlib
    import random
    import struct

    import numpy as np

    from distpow_tpu.models.sha256_jax import SHA256_INIT
    from distpow_tpu.ops.md5_pallas import _sha256_tile

    rng = random.Random(42)
    LANES_N = 16
    msgs = [bytes(rng.randrange(256) for _ in range(rng.randrange(1, 56)))
            for _ in range(LANES_N)]
    # pad each to one block; words[j] becomes a (LANES_N,) array
    blocks = []
    for m in msgs:
        tail = (m + b"\x80" + b"\x00" * (64 - len(m) - 9)
                + struct.pack(">Q", len(m) * 8))
        blocks.append(struct.unpack(">16I", tail))
    words = [jnp.asarray(np.array([b[j] for b in blocks], np.uint32))
             for j in range(16)]
    init = [jnp.uint32(s) for s in SHA256_INIT]
    refs = [struct.unpack(">8I", hashlib.sha256(m).digest()) for m in msgs]
    for mw in (1, 3, 8):
        out = _sha256_tile(words, init, mw)
        for j in range(8 - mw, 8):
            got = np.asarray(out[j])
            for lane in range(LANES_N):
                assert int(got[lane]) == refs[lane][j], (mw, j, lane)


def _one_block_tail_512(msg: bytes) -> tuple:
    """Pad ``msg`` to one 128-byte SHA-512/384 block; 32 uint32 words."""
    import struct

    assert len(msg) <= 128 - 17
    tail = (msg + b"\x80" + b"\x00" * (128 - len(msg) - 17)
            + struct.pack(">QQ", 0, len(msg) * 8))
    return struct.unpack(">32I", tail)


def test_sha512_tile_matches_hashlib_all_buckets():
    """The limb-pair SHA-512 tile (ops/md5_pallas.py _sha512_tile) must
    reproduce hashlib's digest words for every mask-word bucket with
    exactly the dead words elided.  Eager mode, same rationale as the
    sha256 tile test — and doubly so here: the unrolled limb graph is
    the very thing interpret mode refuses to compile
    (INTERPRET_XLA_FALLBACK)."""
    import hashlib
    import struct

    from distpow_tpu.models.sha512_py import SHA512_INIT
    from distpow_tpu.ops.md5_pallas import _sha512_tile

    msg = b"\x01\x02\x03\x04" + b"\x99\x11\x22\x33\x44"
    words = [jnp.uint32(w) for w in _one_block_tail_512(msg)]
    init = [jnp.uint32(s) for s in SHA512_INIT]
    ref_words = struct.unpack(">16I", hashlib.sha512(msg).digest())
    for mw in range(1, 17):
        out = _sha512_tile(words, init, mw)
        for j in range(16):
            if out[j] is None:
                assert j < 16 - mw, (mw, j)
            else:
                assert int(out[j]) == ref_words[j], (mw, j)
        # every masked word must be present (the kernel consumes them)
        for j in range(16 - mw, 16):
            assert out[j] is not None, (mw, j)


def test_sha384_tile_matches_hashlib_all_buckets():
    """SHA-384 shares the compression; digest = first 12 uint32 words
    (6 of 8 64-bit state words) with its own init constants — the
    truncation must hold per bucket."""
    import hashlib
    import struct

    from distpow_tpu.models.sha384_jax import SHA384_INIT
    from distpow_tpu.ops.md5_pallas import _sha384_tile

    msg = b"\xaa\xbb\xcc" + bytes(range(40))
    words = [jnp.uint32(w) for w in _one_block_tail_512(msg)]
    init = [jnp.uint32(s) for s in SHA384_INIT]
    ref_words = struct.unpack(">12I", hashlib.sha384(msg).digest())
    for mw in (1, 2, 3, 7, 12):
        out = _sha384_tile(words, init, mw)
        for j in range(12):
            if out[j] is None:
                assert j < 12 - mw, (mw, j)
            else:
                assert int(out[j]) == ref_words[j], (mw, j)
        for j in range(12 - mw, 12):
            assert out[j] is not None, (mw, j)


def test_sha512_tile_randomized_batch_words():
    """Batch-shaped message words (the kernel's real operand shape)
    match hashlib lane-for-lane across random one-block messages."""
    import hashlib
    import random
    import struct

    import numpy as np

    from distpow_tpu.models.sha512_py import SHA512_INIT
    from distpow_tpu.ops.md5_pallas import _sha512_tile

    rng = random.Random(7)
    LANES_N = 8
    msgs = [bytes(rng.randrange(256) for _ in range(rng.randrange(1, 100)))
            for _ in range(LANES_N)]
    blocks = [_one_block_tail_512(m) for m in msgs]
    words = [jnp.asarray(np.array([b[j] for b in blocks], np.uint32))
             for j in range(32)]
    init = [jnp.uint32(s) for s in SHA512_INIT]
    refs = [struct.unpack(">16I", hashlib.sha512(m).digest()) for m in msgs]
    for mw in (1, 5, 16):
        out = _sha512_tile(words, init, mw)
        for j in range(16 - mw, 16):
            got = np.asarray(out[j])
            for lane in range(LANES_N):
                assert int(got[lane]) == refs[lane][j], (mw, j, lane)


def test_model_geometry_divides_serving_batches():
    """Every shipped MODEL_GEOMETRY tile must divide the power-of-two
    batches serving and the bench dispatch (2^21 and every smaller
    pow2 a backend would round to).  This class of mistake has now been
    caught twice in review — a sweep's absolute best at sublanes=24
    gives a 3072-candidate tile that the kernel builder rejects
    outright at bench shapes and that collapses the swept `inner` to
    unswept territory under the backend's tile rounding — so the
    constraint is pinned here, next to the data it guards."""
    from distpow_tpu.ops.md5_pallas import LANES, MODEL_GEOMETRY

    for mname, (sublanes, inner) in MODEL_GEOMETRY.items():
        tile = sublanes * LANES
        assert (1 << 21) % tile == 0, (
            f"{mname}: tile {tile} (sublanes={sublanes}) does not divide "
            f"the 2^21 serving batch — ship the best power-of-two-"
            f"compatible sweep point instead"
        )
        assert inner & (inner - 1) == 0, (
            f"{mname}: inner {inner} must be a power of two (the "
            f"inner-shrink loop halves it to fit tile counts)"
        )


def test_sha3_tile_matches_hashlib_all_buckets():
    """The unrolled keccak tile (round 4, seventh model — the sponge)
    must reproduce hashlib's digest words for every mask bucket, with
    the final-round chi DCE eliding exactly the dead words.  Eager
    mode, like every limb tile."""
    import hashlib
    import struct

    from distpow_tpu.models.sha3_py import SHA3_INIT
    from distpow_tpu.ops.md5_pallas import _sha3_tile

    msg = b"\x42\x24" + bytes(range(50))
    t = bytearray(136)
    t[: len(msg)] = msg
    t[len(msg)] ^= 0x06
    t[-1] ^= 0x80
    words = [jnp.uint32(w) for w in struct.unpack("<34I", bytes(t))]
    init = [jnp.uint32(s) for s in SHA3_INIT]
    ref_words = struct.unpack("<8I", hashlib.sha3_256(msg).digest())
    for mw in range(1, 9):
        out = _sha3_tile(words, init, mw)
        for j in range(8):
            if out[j] is None:
                assert j < 8 - mw, (mw, j)
            else:
                assert int(out[j]) == ref_words[j], (mw, j)
        for j in range(8 - mw, 8):
            assert out[j] is not None, (mw, j)


def test_sha3_tile_nonzero_absorbed_state():
    """A long nonce host-absorbs a full rate block: the tile's XOR
    absorb must continue from the NONZERO sponge state."""
    import hashlib
    import struct

    from distpow_tpu.models.sha3_py import py_absorb
    from distpow_tpu.ops.md5_pallas import _sha3_tile

    long_msg = bytes(range(170))
    st, rem, absorbed = py_absorb(long_msg)
    assert absorbed == 136
    t = bytearray(136)
    t[: len(rem)] = rem
    t[len(rem)] ^= 0x06
    t[-1] ^= 0x80
    words = [jnp.uint32(w) for w in struct.unpack("<34I", bytes(t))]
    init = [jnp.uint32(s) for s in st]
    ref_words = struct.unpack("<8I", hashlib.sha3_256(long_msg).digest())
    out = _sha3_tile(words, init, 8)
    for j in range(8):
        assert int(out[j]) == ref_words[j], j


def test_blake2b_tile_matches_hashlib_all_buckets():
    """The per-block-parameter tile (round 4, eighth model): the baked
    t/f limbs ride at the end of the 36-word template row, and the
    final-round diagonal DCE elides exactly the dead digest words."""
    import hashlib
    import struct

    from distpow_tpu.models.blake2b_py import BLAKE2B_INIT
    from distpow_tpu.ops.md5_pallas import _blake2b_tile

    msg = b"\x42\x24" + bytes(range(60))
    t = bytearray(128)
    t[: len(msg)] = msg
    words = list(struct.unpack("<32I", bytes(t)))
    words += [len(msg), 0, 0xFFFFFFFF, 0xFFFFFFFF]
    wj = [jnp.uint32(w) for w in words]
    init = [jnp.uint32(s) for s in BLAKE2B_INIT]
    ref = struct.unpack(
        "<8I", hashlib.blake2b(msg, digest_size=32).digest())
    for mw in range(1, 9):
        out = _blake2b_tile(wj, init, mw)
        for j in range(8):
            if out[j] is None:
                assert j < 8 - mw, (mw, j)
            else:
                assert int(out[j]) == ref[j], (mw, j)
        for j in range(8 - mw, 8):
            assert out[j] is not None, (mw, j)


def test_sha512_interpret_mode_falls_back():
    """Both kernel constructors — the single-device builder AND the
    mesh step factory (review r4: it bypassed the first guard) — must
    refuse the limb-pair tiles under interpret=True (ValueError = the
    transparent-fallback signal every caller maps to the XLA step)."""
    import jax

    from distpow_tpu.models.registry import get_hash_model
    from distpow_tpu.ops.md5_pallas import build_pallas_search_step
    from distpow_tpu.parallel.mesh_search import (
        _pallas_mesh_step_factory,
        make_mesh,
    )

    mesh = make_mesh(jax.devices())
    for mname in ("sha512", "sha384", "sha3_256", "blake2b_256"):
        with pytest.raises(ValueError, match="TPU-only"):
            build_pallas_search_step(
                b"\x01\x02", 1, 3, 0, 256, 8, mname,
                sublanes=8, interpret=True,
            )
        with pytest.raises(ValueError, match="TPU-only"):
            _pallas_mesh_step_factory(
                b"\x01\x02", 3, 0, 256, get_hash_model(mname), mesh,
                "devices", sublanes=8, interpret=True,
            )


def test_sha256d_tile_matches_hashlib_all_buckets():
    """Composed double-sha256 tile (r5 ninth model): eager tile math
    vs hashlib's double digest at every mask-word bucket; the None-DCE
    contract holds on the SECOND stage's dead words while stage 1 runs
    full-width underneath."""
    import hashlib
    import struct

    import numpy as np

    from distpow_tpu.models.sha256_jax import SHA256_INIT
    from distpow_tpu.ops.md5_pallas import _sha256d_tile

    msgs = [bytes([i, (7 * i) & 0xFF, 3]) + b"abc" for i in range(8)]

    def block_words(m):
        block = (m + b"\x80" + bytes(64 - len(m) - 1 - 8)
                 + (8 * len(m)).to_bytes(8, "big"))
        return struct.unpack(">16I", block)

    cols = [
        jnp.asarray(np.array([block_words(m)[g] for m in msgs], np.uint32))
        for g in range(16)
    ]
    init = tuple(jnp.uint32(c) for c in SHA256_INIT)
    refs = [
        struct.unpack(
            ">8I", hashlib.sha256(hashlib.sha256(m).digest()).digest())
        for m in msgs
    ]
    for mw in (1, 2, 4, 5, 8):
        out = _sha256d_tile(cols, init, mw)
        for j in range(8):
            if j < 8 - mw:
                assert out[j] is None
            else:
                for i, r in enumerate(refs):
                    assert int(out[j][i]) == r[j], (mw, j, i)


def test_sha256d_interpret_falls_back():
    """Off-TPU the composed tile is kernel-unavailable by design (the
    doubled unrolled graph is pathological for XLA:CPU codegen): the
    builder refuses interpret mode and the backend transparently serves
    the fused XLA step instead."""
    from distpow_tpu.backends.pallas_backend import PallasBackend
    from distpow_tpu.models import puzzle

    with pytest.raises(ValueError, match="TPU-only"):
        build_pallas_search_step(
            b"\x01", 1, 2, 0, 256, 128, model_name="sha256d",
            interpret=True)
    b = PallasBackend(hash_model="sha256d", interpret=True,
                      batch_size=1 << 12)
    secret = b.search(b"\x05\x06\x07", 2, list(range(256)))
    assert secret is not None
    assert puzzle.check_secret(b"\x05\x06\x07", secret, 2, "sha256d")


def test_backend_batch_rounding_keeps_inner_for_24_sublane_tiles(monkeypatch):
    """Serving-side support for the sweep-best sublanes=24 geometries
    (VERDICT r4 item 8 / ROUND4 open edge): a 2^21 batch at tile 3072
    is 683 tiles — prime — which would collapse the tuned inner to
    unswept territory.  The factory must grow the batch by whole tiles
    until the per-dispatch tile count divides inner, keeping chunk
    accounting exact and the growth marginal."""
    import math

    from distpow_tpu.backends.pallas_backend import PallasBackend

    captured = {}

    def fake_step(nonce, vw, difficulty, tb_lo, tbc, chunks, mname,
                  extra, sublanes, interpret, k, inner):
        captured.update(chunks=chunks, k=k, tbc=tbc, sublanes=sublanes,
                        inner=inner)
        return lambda c0: 0

    monkeypatch.setattr(
        "distpow_tpu.backends.pallas_backend.cached_pallas_search_step",
        fake_step)
    b = PallasBackend(hash_model="ripemd160", batch_size=1 << 21,
                      sublanes=24, inner=1024)
    factory = b._factory(b"\x01\x02\x03\x04", 8, 0, 256)
    step, covered = factory(4, b"", (1 << 21) // 256, launch_steps=128)

    tile = 24 * 128
    batch = captured["chunks"] * 256
    assert batch % tile == 0, "not a whole tile grid"
    n_tiles = batch // tile
    k = captured["k"]
    assert (n_tiles * k) % 1024 == 0, \
        f"inner would shrink: {n_tiles} tiles x k={k} vs inner=1024"
    # growth stays marginal (<= inner extra tiles; here well under 2%)
    assert batch < (1 << 21) * 1.02
    assert covered == captured["chunks"] * k
    # power-of-two geometries are untouched by the rounding
    captured.clear()
    b2 = PallasBackend(hash_model="md5", batch_size=1 << 21)
    f2 = b2._factory(b"\x01\x02\x03\x04", 8, 0, 256)
    f2(4, b"", (1 << 21) // 256, launch_steps=8)
    assert captured["chunks"] == (1 << 21) // 256
    # and the no-op claim holds structurally: gcd math keeps pow2 counts
    assert ((1 << 21) // (64 * 128) * 8) % b2.inner == 0 or \
        math.gcd(8, b2.inner) == 8
    # overgrowth is REJECTED (review r5: an uncapped version grew small
    # segments 4x): a tiny k=1 launch at need=1024 would have to grow
    # to 1024 tiles — far past the 2% cap — so the batch keeps the
    # plain tile rounding and the kernel shrinks inner instead
    captured.clear()
    small = factory(4, b"", 1024, launch_steps=1)
    batch_small = captured["chunks"] * 256
    assert batch_small % tile == 0
    assert batch_small <= 2 * 1024 * 256, \
        f"small segment overgrown to {batch_small}"


@pytest.mark.veryslow
def test_sha256_pallas_kernel_matches_xla_step():
    """Full sha256 kernel in interpret mode (one compile ~80-160s on
    XLA:CPU — the single biggest test in the suite, so it carries the
    nightly-style ``veryslow`` marker, VERDICT r4 item 6; per-bucket
    hash correctness is covered by the eager tile test above, the
    scaffold by the md5 tests, and the compiled kernel by the hardware
    parity artifacts under docs/artifacts/).  Run with
    ``pytest -m veryslow`` before shipping kernel-scaffold changes.
    sublanes is pinned to 8: the serving default (16, MODEL_GEOMETRY)
    multiplies the interpret-mode compile severalfold, and tile
    correctness is geometry-independent."""
    from distpow_tpu.models.registry import SHA256

    nonce = b"\x01\x02\x03\x04"
    step_p = build_pallas_search_step(
        nonce, 1, 2, 0, 256, 8, model_name="sha256", sublanes=8,
        interpret=True
    )
    step_x = build_search_step(nonce, 1, 2, 0, 256, 8, SHA256)
    for c0 in (1, 17):
        assert int(step_p(jnp.uint32(c0))) == int(step_x(jnp.uint32(c0)))


def test_sha1_tile_matches_hashlib_all_buckets():
    """The SHA-1 tile's single-chain form and its seam handling (rounds
    0-4 draw from raw init words) must reproduce hashlib's digest words
    for every mask-word DCE bucket (1-5)."""
    import hashlib
    import struct

    import numpy as np

    from distpow_tpu.models.sha1_jax import SHA1_INIT
    from distpow_tpu.ops.md5_pallas import _sha1_tile

    rng = np.random.default_rng(11)
    SL, LN = 8, 16
    msgs = [rng.integers(0, 256, 9, dtype=np.uint8).tobytes()
            for _ in range(SL * LN)]
    words = []
    for g in range(16):
        arr = np.zeros((SL, LN), np.uint32)
        for i, m in enumerate(msgs):
            blk = bytearray(64)
            blk[:9] = m
            blk[9] = 0x80
            blk[56:64] = (72).to_bytes(8, "big")
            arr[i // LN, i % LN] = struct.unpack(">16I", bytes(blk))[g]
        words.append(jnp.asarray(arr))
    init = [jnp.uint32(x) for x in SHA1_INIT]
    refs = [struct.unpack(">5I", hashlib.sha1(m).digest()) for m in msgs]
    for mw in range(1, 6):
        out = _sha1_tile(words, init, mw)
        assert sum(o is None for o in out) == 5 - mw
        for j, o in enumerate(out):
            if o is None:
                continue
            o = np.asarray(o)
            for i, r in enumerate(refs):
                assert int(o[i // LN, i % LN]) == r[j], (mw, j, i)


def test_sha1_pallas_kernel_matches_xla_step():
    """Full sha1 kernel in interpret mode vs the XLA step.  Unlike the
    sha256 tile (80-160s interpret compile), the single-chain form
    compiles in seconds, so this is not a slow test."""
    from distpow_tpu.models.registry import SHA1

    nonce = b"\x01\x02\x03\x04"
    step_p = build_pallas_search_step(
        nonce, 1, 2, 0, 256, 8, model_name="sha1", sublanes=8,
        interpret=True
    )
    step_x = build_search_step(nonce, 1, 2, 0, 256, 8, SHA1)
    for c0 in (1, 17):
        assert int(step_p(jnp.uint32(c0))) == int(step_x(jnp.uint32(c0)))


def test_ripemd160_pallas_kernel_matches_xla_step():
    """Full ripemd160 kernel in interpret mode vs the XLA step.  Both
    lines in the SHA-1-style functional form compile in seconds (no
    sha256-style schedule expansion), so this is not a slow test."""
    from distpow_tpu.models.registry import RIPEMD160

    nonce = b"\x01\x02\x03\x04"
    step_p = build_pallas_search_step(
        nonce, 1, 2, 0, 256, 8, model_name="ripemd160", sublanes=8,
        interpret=True
    )
    step_x = build_search_step(nonce, 1, 2, 0, 256, 8, RIPEMD160)
    for c0 in (1, 17):
        assert int(step_p(jnp.uint32(c0))) == int(step_x(jnp.uint32(c0)))


@pytest.mark.slow
def test_pallas_mesh_matches_jax_mesh_all_partitions():
    """pallas-mesh must be bit-identical to jax-mesh in both sharding
    regimes (tb-split, chunk-split) and on sub-partitions — both return
    the minimal TRUE global flat index, so the decoded secrets match
    exactly (parallel/mesh_search.py _dyn_pallas_mesh_step)."""
    from distpow_tpu.backends import JaxMeshBackend, PallasMeshBackend

    b = PallasMeshBackend(batch_size=1 << 14, interpret=True)
    ref = JaxMeshBackend(batch_size=1 << 14)
    for tbs in (list(range(256)),        # tb-split
                list(range(64, 128)),    # tb-split, sub-partition
                list(range(4))):         # chunk-split (tbc < n_dev)
        got = b.search(b"\x01\x02\x03", 2, tbs)
        want = ref.search(b"\x01\x02\x03", 2, tbs)
        assert got == want
        assert puzzle.check_secret(b"\x01\x02\x03", got, 2)


def test_pallas_mesh_falls_back_for_long_nonce():
    from distpow_tpu.backends import PallasMeshBackend

    b = PallasMeshBackend(batch_size=1 << 13, interpret=True)
    nonce = bytes(range(60))  # two-block tail -> XLA mesh fallback
    secret = b.search(nonce, 1, list(range(256)))
    assert secret is not None
    assert puzzle.check_secret(nonce, secret, 1)


def test_pallas_mesh_warmup_covers_serving_compile_keys():
    """After boot warmup, serving any pow2 partition must not compile a
    new mesh-kernel program (the same layout-keyed discipline the XLA
    mesh path proves in test_search.py)."""
    from distpow_tpu.backends import PallasMeshBackend
    from distpow_tpu.parallel.mesh_search import _dyn_pallas_mesh_step

    b = PallasMeshBackend(batch_size=1 << 14, interpret=True)
    b.warmup([3], [0, 1])
    misses = _dyn_pallas_mesh_step.cache_info().misses
    for tbs in (list(range(256)), list(range(128, 192))):
        secret = b.search(b"\x07\x08\x09", 2, tbs)
        assert secret is not None
    assert _dyn_pallas_mesh_step.cache_info().misses == misses


@pytest.mark.slow
def test_pallas_mask_word_buckets_match_xla():
    # difficulties spanning all four trailing-word buckets exercise the
    # skipped-final-rounds DCE (mw=1 skips rounds 62-63, mw=2 skips 63)
    nonce = b"\x21\x22\x23"
    for d in (1, 2, 8, 9, 16, 17, 25):
        step_p = build_pallas_search_step(
            nonce, 2, d, 0, 256, 64, sublanes=8, interpret=True, inner=4
        )
        step_x = build_search_step(nonce, 2, d, 0, 256, 64, MD5)
        for c0 in (256, 4096):
            assert int(step_p(jnp.uint32(c0))) == int(step_x(jnp.uint32(c0))), \
                f"divergence at difficulty {d} chunk0 {c0}"


# -- launch-geometry k selection (pure CPU math; ISSUE 8 satellite) ----------

class TestPlanLaunchGeometry:
    """Unit tests for the extracted k-selection logic — in particular
    the advisor-r5 pow2-k fix: the power-of-two rounding only COMMITS
    together with a batch that makes the inner loop effective, and the
    original k is kept otherwise."""

    def test_pow2_tile_is_untouched(self):
        from distpow_tpu.backends.pallas_backend import plan_launch_geometry

        # tile 8x128=1024 is a power of two: the inner-loop fixup never
        # runs and k keeps the driver's requested (odd) multiplier
        batch, chunks, k = plan_launch_geometry(
            2048, 256, 1024, 4, 5, 1 << 24)
        assert (batch, chunks, k) == (2048 * 256, 2048, 5)

    def test_batch_rounds_up_to_whole_tiles(self):
        from distpow_tpu.backends.pallas_backend import plan_launch_geometry

        # 2^21 candidates at a 24-sublane (3072) tile: 682.67 tiles
        # rounds up to a whole grid and chunks re-derive from it
        batch, chunks, k = plan_launch_geometry(
            8192, 256, 3072, 1, 1, 1 << 26)
        assert batch % 3072 == 0
        assert batch >= 8192 * 256
        assert chunks == batch // 256

    def test_pow2_rounding_commits_with_marginal_growth(self):
        from distpow_tpu.backends.pallas_backend import plan_launch_geometry

        # the sweep-best serving shape: 683 (prime) tiles, inner=4 —
        # a whole-tile growth of ~0.15% makes the pow2 k effective
        batch, chunks, k = plan_launch_geometry(
            8192, 256, 3072, 4, 5, 1 << 26)
        assert k & (k - 1) == 0, f"k={k} not a power of two"
        assert batch % 3072 == 0 and batch % 256 == 0
        assert batch <= 8192 * 256 * 1.03  # growth stayed marginal

    def test_growth_rejected_keeps_original_k(self):
        from distpow_tpu.backends.pallas_backend import plan_launch_geometry

        # 53 tiles at tbc=160: the next inner-compatible whole-tile
        # batch is not tbc-aligned within the <=2% cap, so the growth
        # conditions FAIL — the original k=3 must survive (the advisor
        # r5 regression: unconditional rounding silently halved it)
        batch, chunks, k = plan_launch_geometry(
            1000, 160, 3072, 4, 3, 1 << 22)
        assert k == 3
        assert batch == 53 * 3072  # and the batch stayed unrounded

    def test_budget_clamp_holds_through_every_path(self):
        from distpow_tpu.backends.pallas_backend import plan_launch_geometry

        for target_chunks in (1000, 2000, 8192):
            for tbc in (96, 160, 256):
                for launch_steps in (1, 3, 5, 8):
                    for max_launch in (1 << 22, 1 << 24):
                        batch, chunks, k = plan_launch_geometry(
                            target_chunks, tbc, 3072, 4, launch_steps,
                            max_launch)
                        assert batch * k <= max_launch, (
                            target_chunks, tbc, launch_steps, max_launch,
                            batch, k)
                        assert k >= 1 and chunks >= 1
