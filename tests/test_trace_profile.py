"""Critical-path profiler (scripts/trace_profile.py) — the ISSUE 3
acceptance gate: the per-Mine-request breakdown over the checked-in
golden trace must exist and its stage ordering must hold
(queue <= fanout <= first-result <= cancel-complete), plus the human
trace-log and flight-recorder-journal input formats parse to the same
structure."""

from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN = os.path.join(REPO, "tests", "golden_trace.json")
SCRIPT = os.path.join(REPO, "scripts", "trace_profile.py")


def _run(*args):
    return subprocess.run(
        [sys.executable, SCRIPT, *args],
        capture_output=True, text=True, timeout=60, cwd=REPO,
    )


def test_golden_trace_stage_ordering():
    out = _run(GOLDEN, "--json")
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["ordering_ok"] is True
    assert payload["violations"] == []
    assert payload["truncated"] == []
    requests = payload["requests"]
    # the demo scenario: four Mine requests, all misses (the dominance
    # supersede request re-fans out at the higher difficulty)
    assert len(requests) == 4
    for req in requests:
        assert req["path"] == "miss"
        assert req["queue"] is not None
        assert (req["queue"] <= req["fanout"] <= req["first_result"]
                <= req["cancel_complete"] <= req["done"]), req
        assert req["workers"] >= 1
        assert req["results"] >= 1


def test_golden_trace_human_output_reports_ordering_ok():
    out = _run(GOLDEN)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "4 Mine request(s)" in out.stdout
    assert "stage ordering OK" in out.stdout
    assert "queue <= fanout <= first_result <= cancel_complete" \
        in out.stdout


def test_ordering_violation_fails_both_output_modes(tmp_path):
    """A trace violating the stage ordering (a miss with no fanout ever
    recorded) must exit 1 in BOTH the human and --json modes — a CI
    consumer of the machine-readable output must not silently pass."""
    bad = tmp_path / "bad_trace.json"
    bad.write_text(json.dumps({
        "coordinator": [
            [5, "CoordinatorMine", "0102", 2],
            [5, "CacheMiss", "0102", 2],
            # no CoordinatorWorkerMine: fanout stage missing entirely
            [5, "CoordinatorWorkerResult", "0102", 2],
            [5, "CoordinatorSuccess", "0102", 2],
        ],
    }))
    human = _run(str(bad))
    assert human.returncode == 1, human.stdout + human.stderr
    assert "ORDERING VIOLATION" in human.stderr
    machine = _run(str(bad), "--json")
    assert machine.returncode == 1, machine.stdout + machine.stderr
    payload = json.loads(machine.stdout)
    assert payload["ordering_ok"] is False
    assert payload["violations"] == [5]


def test_truncated_round_is_not_an_ordering_violation(tmp_path):
    """A log captured mid-round (no CoordinatorSuccess — node killed
    while mining, the crash-forensics case) is reported as truncated,
    NOT as a protocol ordering violation: exit 0 in both modes."""
    trunc = tmp_path / "truncated_trace.json"
    trunc.write_text(json.dumps({
        "coordinator": [
            [9, "CoordinatorMine", "0304", 2],
            [9, "CacheMiss", "0304", 2],
            [9, "CoordinatorWorkerMine", "0304", 2],
            # killed here: no result, no cancel, no success
        ],
    }))
    human = _run(str(trunc))
    assert human.returncode == 0, human.stdout + human.stderr
    assert "truncated mid-round" in human.stdout
    machine = _run(str(trunc), "--json")
    assert machine.returncode == 0, machine.stdout + machine.stderr
    payload = json.loads(machine.stdout)
    assert payload["ordering_ok"] is True
    assert payload["truncated"] == [9]


def test_human_trace_log_format_parses(tmp_path):
    """FileSink/tracing-server lines profile identically to the golden
    JSON of the same scenario."""
    log = tmp_path / "trace_output.log"
    log.write_text(
        "[client1] TraceID=7 PowlibMiningBegin Nonce=[1, 2], "
        "NumTrailingZeros=3\n"
        "[coordinator] TraceID=7 CoordinatorMine Nonce=[1, 2], "
        "NumTrailingZeros=3\n"
        "[coordinator] TraceID=7 CacheMiss Nonce=[1, 2], "
        "NumTrailingZeros=3\n"
        "[coordinator] TraceID=7 CoordinatorWorkerMine Nonce=[1, 2], "
        "NumTrailingZeros=3, WorkerByte=0\n"
        "[coordinator] TraceID=7 CoordinatorWorkerResult Nonce=[1, 2], "
        "NumTrailingZeros=3, WorkerByte=0, Secret=[9]\n"
        "[coordinator] TraceID=7 CoordinatorWorkerCancel Nonce=[1, 2], "
        "NumTrailingZeros=3, WorkerByte=0\n"
        "[coordinator] TraceID=7 CoordinatorSuccess Nonce=[1, 2], "
        "NumTrailingZeros=3, Secret=[9]\n"
    )
    out = _run(str(log), "--json")
    assert out.returncode == 0, out.stdout + out.stderr
    (req,) = json.loads(out.stdout)["requests"]
    assert req["trace_id"] == 7
    assert req["nonce"] == "0102"
    assert req["path"] == "miss"
    assert (req["queue"] < req["fanout"] < req["first_result"]
            < req["cancel_complete"] < req["done"])


def test_flight_recorder_journal_format(tmp_path):
    """A telemetry JSONL journal (runtime/telemetry.py) yields per-round
    wall-clock stage timings."""
    journal = tmp_path / "coordinator.telemetry.jsonl"
    rid = "00000000deadbeef00000001"
    events = [
        {"seq": 1, "ts": 100.0, "kind": "coord.fanout", "round": rid,
         "nonce": "0102", "ntz": 3},
        {"seq": 2, "ts": 100.2, "kind": "coord.first_result",
         "round": rid, "nonce": "0102", "ntz": 3, "worker_byte": 1,
         "latency_s": 0.2},
        {"seq": 3, "ts": 100.3, "kind": "coord.cancel_complete",
         "round": rid, "nonce": "0102", "ntz": 3, "late_results": 1,
         "latency_s": 0.3},
        {"seq": 4, "ts": 101.0, "kind": "fault.injected",
         "kind2": "ignored-non-coord-event"},
    ]
    journal.write_text("".join(json.dumps(e) + "\n" for e in events))
    out = _run(str(journal), "--json")
    assert out.returncode == 0, out.stdout + out.stderr
    (r,) = json.loads(out.stdout)["rounds"]
    assert r["round"] == rid
    assert r["first_result_s"] == 0.2
    assert r["cancel_propagation_s"] == 0.3
    assert r["first_result_s"] <= r["cancel_propagation_s"]
    assert r["late_results"] == 1
    assert r["winner_byte"] == 1


def test_live_stack_trace_profiles_clean(tmp_path):
    """End-to-end: profile a REAL run's memory-sink trace — not just the
    checked-in golden — so the profiler tracks the live action
    vocabulary, not a snapshot of it."""
    sys.path.insert(0, os.path.dirname(__file__))
    from test_nodes import Stack, mine_and_wait
    from test_trace_parity import _node_sequence

    s = Stack(2)
    try:
        c = s.new_client("client1")
        mine_and_wait(c, b"\x61\x62", 2)
        mine_and_wait(c, b"\x61\x62", 2)  # cache hit
        dump = {ident: _node_sequence(sink)
                for ident, sink in s.sinks.items()}
    finally:
        s.close()
    trace = tmp_path / "live_trace.json"
    trace.write_text(json.dumps(dump))
    out = _run(str(trace), "--json")
    assert out.returncode == 0, out.stdout + out.stderr
    requests = json.loads(out.stdout)["requests"]
    assert len(requests) == 2
    paths = sorted(r["path"] for r in requests)
    assert paths == ["hit", "miss"]
    miss = next(r for r in requests if r["path"] == "miss")
    assert (miss["queue"] <= miss["fanout"] <= miss["first_result"]
            <= miss["cancel_complete"] <= miss["done"])
    assert miss["workers"] == 2
