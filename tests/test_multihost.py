"""A REAL 2-process ``jax.distributed`` mesh solve (VERDICT r1 item 8).

Round 1 validated the multi-host bootstrap with a 1-process "cluster";
this spawns two OS processes, each owning 4 virtual CPU devices of one
8-device global mesh, and runs one mesh solve spanning both.  The
winning candidate's thread byte (214) maps to global device 6 — owned
by process 1 — so process 0 can only report the correct result if the
``lax.pmin`` found-index collective actually crossed the process
boundary (ICI/DCN in production, the distributed service's transport
here).
"""

import os
import socket
import subprocess
import sys

import pytest

CHILD = os.path.join(os.path.dirname(__file__), "multihost_child.py")


@pytest.mark.slow
def test_two_process_mesh_solve_crosses_processes():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    env = dict(os.environ)
    # the children configure their own platform/device-count settings
    # (multihost_child.py overwrites XLA_FLAGS and flips the platform via
    # jax.config); scrub the parent suite's values anyway so nothing else
    # jax reads from the environment leaks through
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, CHILD, str(pid), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env,
            text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=220)
            assert p.returncode == 0, f"child failed:\n{err[-2000:]}"
            outs.append(out)
    finally:
        # child 0 is the jax.distributed coordinator: if it died, child 1
        # would otherwise block in initialize() forever and leak
        for q in procs:
            if q.poll() is None:
                q.kill()
                q.communicate()

    results = []
    for out in outs:
        lines = [ln for ln in out.splitlines() if ln.startswith("RESULT")]
        assert len(lines) == 1, out
        results.append(lines[0].split(" ", 1)[1])
    # both processes observed the SAME winning secret...
    assert results[0].split("secret=")[1] == results[1].split("secret=")[1]
    # ...and it was found on process 1's devices (tb=214 -> device 6),
    # proving the pmin collective crossed the process boundary
    assert "tb=214" in results[0] and "tb=214" in results[1]
    # the pallas-mesh kernel leg: nonce 0x000c's first solution (tb=144,
    # chunk=1) comes from the kernel's tile grid on process 1's device 4
    # — both processes reporting it proves the KERNEL's pmin-ed global
    # flat index crossed the process boundary (the child also asserts
    # the exact secret bytes against the oracle)
    for out in outs:
        pallas = [ln for ln in out.splitlines() if ln.startswith("PALLAS")]
        assert len(pallas) == 1 and "tb=144" in pallas[0], out
    # the sponge leg: sha3_256's first solution for 0x000a is
    # (chunk=1, tb=204) -> device 6, process 1; the nonce has no
    # width-0 solution, so the single-device probe cannot serve it —
    # BOTH processes observing it means the non-Merkle-Damgard model
    # rode the same distributed pmin collective
    for out in outs:
        sponge = [ln for ln in out.splitlines() if ln.startswith("SHA3")]
        assert len(sponge) == 1 and "tb=204" in sponge[0], out
