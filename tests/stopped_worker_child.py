"""Worker child for the SIGSTOP head-of-line test (tests/test_wire.py).

Boots a real python-backend Worker on an ephemeral port, prints
``WORKER_READY <addr>`` and serves until killed.  The parent freezes
this whole process with SIGSTOP — TCP stays open, nothing answers — to
prove the parallel fan-out (ISSUE 5) no longer lets one frozen worker
add ``_call_timeout`` to fanout->first-result for the live workers.

Usage: python tests/stopped_worker_child.py <coord_worker_api_addr>
"""

import os
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distpow_tpu.nodes.worker import Worker  # noqa: E402
from distpow_tpu.runtime.config import WorkerConfig  # noqa: E402

coord_addr = sys.argv[1]
w = Worker(
    WorkerConfig(
        WorkerID="stopworker",
        ListenAddr="127.0.0.1:0",
        CoordAddr=coord_addr,
        Backend="python",
        WarmupNonceLens=[],
        WarmupWidths=[],
    )
)
addr = w.initialize_rpcs()
w.start_forwarder()
print(f"WORKER_READY {addr}", flush=True)
threading.Event().wait()
