"""Open-loop load harness (ISSUE 8; ROADMAP open item 5b; docs/SLO.md).

The "millions of users" north star needs a traffic source that behaves
like users, not like a benchmark loop: OPEN-LOOP arrivals (requests
fire on a seeded Poisson schedule regardless of how many are still in
flight — a slow server faces a growing backlog, exactly like
production) with Zipf-skewed keys (so the dominance cache and the PR 4
coalescer see the repeat traffic they were built for), blended
difficulties, and optional PR 1 fault-plane chaos.

* :mod:`.loadgen`  — the seeded schedule builder + open-loop runner
  (deterministic: one seed, one schedule — replayable in CI);
* :mod:`.harness`  — an in-process cluster wired to the fleet scraper
  and SLO engine (distpow_tpu/obs/): run a mix, scrape the nodes,
  assert the objectives.  ``bench.py --load-slo`` and
  ``scripts/ci.sh --slo-smoke`` are thin wrappers over this;
* :mod:`.shapes`   — seeded, pure time-varying rate schedules (ISSUE
  18, docs/SOAK.md): diurnal sinusoid, flash crowd, linear ramp,
  composable sums, and a wall-clock compression knob so an 8-hour
  diurnal replays in CI minutes;
* :mod:`.soak`     — the long-haul soak harness: shaped load + chaos +
  time-series retention + leak sentinels, ending in a typed
  :class:`~.soak.SoakVerdict` with the 0/1/2 exit-code contract.
  ``python -m distpow_tpu.cli.soak``, ``bench.py --soak`` and
  ``scripts/ci.sh --soak-smoke`` are thin wrappers over this.
"""

from .loadgen import Arrival, LoadMix, OpenLoopRunner, build_schedule
from .harness import (
    InProcCluster,
    exact_percentile,
    percentile_within_one_bucket,
    run_load_slo,
)
from .shapes import (
    Compressed,
    Constant,
    Diurnal,
    FlashCrowd,
    Ramp,
    RateShape,
    Sum,
    build_shaped_schedule,
    compress,
)
from .soak import PhaseVerdict, SoakVerdict, run_soak

__all__ = [
    "Arrival",
    "LoadMix",
    "OpenLoopRunner",
    "build_schedule",
    "InProcCluster",
    "exact_percentile",
    "percentile_within_one_bucket",
    "run_load_slo",
    "RateShape",
    "Constant",
    "Diurnal",
    "FlashCrowd",
    "Ramp",
    "Sum",
    "Compressed",
    "compress",
    "build_shaped_schedule",
    "PhaseVerdict",
    "SoakVerdict",
    "run_soak",
]
