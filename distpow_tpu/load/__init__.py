"""Open-loop load harness (ISSUE 8; ROADMAP open item 5b; docs/SLO.md).

The "millions of users" north star needs a traffic source that behaves
like users, not like a benchmark loop: OPEN-LOOP arrivals (requests
fire on a seeded Poisson schedule regardless of how many are still in
flight — a slow server faces a growing backlog, exactly like
production) with Zipf-skewed keys (so the dominance cache and the PR 4
coalescer see the repeat traffic they were built for), blended
difficulties, and optional PR 1 fault-plane chaos.

* :mod:`.loadgen`  — the seeded schedule builder + open-loop runner
  (deterministic: one seed, one schedule — replayable in CI);
* :mod:`.harness`  — an in-process cluster wired to the fleet scraper
  and SLO engine (distpow_tpu/obs/): run a mix, scrape the nodes,
  assert the objectives.  ``bench.py --load-slo`` and
  ``scripts/ci.sh --slo-smoke`` are thin wrappers over this.
"""

from .loadgen import Arrival, LoadMix, OpenLoopRunner, build_schedule
from .harness import (
    InProcCluster,
    exact_percentile,
    percentile_within_one_bucket,
    run_load_slo,
)

__all__ = [
    "Arrival",
    "LoadMix",
    "OpenLoopRunner",
    "build_schedule",
    "InProcCluster",
    "exact_percentile",
    "percentile_within_one_bucket",
    "run_load_slo",
]
