"""Seeded, pure time-varying rate schedules for the Poisson generator
(docs/SOAK.md "Shape catalog").

The PR 7 generator speaks one dialect: constant-rate Poisson.  Real
fleets don't — the failure modes a soak must surface (queue growth
under a diurnal peak, cache-warmth collapse after a flash crowd, leak
slopes that only matter over hours) are properties of the rate's SHAPE
over time.  This module adds shapes without touching the generator's
contract:

* a :class:`RateShape` is a PURE function ``rate_hz(t) -> float`` over
  schedule-relative time, plus ``phases(duration_s)`` naming the
  windows a soak verdict judges separately;
* :func:`build_shaped_schedule` turns (shape, mix) into the same
  ``List[Arrival]`` the :class:`~.loadgen.OpenLoopRunner` already
  replays, via Lewis–Shedler thinning of a homogeneous Poisson process
  at the shape's peak rate — seeded through the mix's ``random.Random``
  exactly like :func:`~.loadgen.build_schedule`, so one seed gives one
  schedule byte for byte (test-pinned), and key/difficulty/model
  sampling reuses the generator's own helpers;
* :func:`compress` is the wall-clock knob: ``compress(shape, 320)``
  squeezes an 8-hour diurnal into 90 s by scaling time down and rate up
  by the same factor — expected arrivals per phase are preserved, so a
  CI soak exercises the same cache/coalesce regimes as the real thing,
  just faster.

Shapes compose by :class:`Sum` (diurnal + flash crowd is the canonical
soak), and every shape is immutable and stateless — determinism lives
entirely in the thinning RNG.
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass
from typing import List, Optional, Tuple

from .loadgen import (
    Arrival,
    LoadMix,
    _cum_weights,
    _pick,
    _zipf_cdf,
    key_nonce,
)

#: one named judgment window: (name, start_s, end_s)
Phase = Tuple[str, float, float]


class RateShape:
    """Base: a pure instantaneous-rate function over schedule time."""

    #: duration the shape naturally describes (seconds); schedules and
    #: phase lists default to it
    duration_s: float = 0.0

    def rate_hz(self, t: float) -> float:
        raise NotImplementedError

    def peak_hz(self) -> float:
        """A tight upper bound on ``rate_hz`` over the duration — the
        thinning envelope.  Subclasses with closed forms override;
        this fallback samples."""
        n = 1024
        return max(self.rate_hz(i * self.duration_s / n)
                   for i in range(n + 1))

    def phases(self, duration_s: Optional[float] = None) -> List[Phase]:
        """Named windows the soak verdict judges separately.  Default:
        the whole run as one phase."""
        d = self.duration_s if duration_s is None else duration_s
        return [("all", 0.0, d)]


@dataclass(frozen=True)
class Constant(RateShape):
    """The PR 7 regime, as a shape."""

    rate: float
    duration_s: float = 60.0

    def rate_hz(self, t: float) -> float:
        return self.rate if 0.0 <= t < self.duration_s else 0.0

    def peak_hz(self) -> float:
        return self.rate


@dataclass(frozen=True)
class Diurnal(RateShape):
    """Sinusoidal day: ``base + amplitude * sin(2*pi*t/period)``,
    clamped at zero.  One period is one "day"; the default phases
    split it into rise / peak / fall / trough quarters."""

    base: float
    amplitude: float
    period_s: float
    duration_s: float = 0.0

    def __post_init__(self) -> None:
        if self.duration_s <= 0:
            object.__setattr__(self, "duration_s", self.period_s)

    def rate_hz(self, t: float) -> float:
        if not 0.0 <= t < self.duration_s:
            return 0.0
        return max(0.0, self.base + self.amplitude
                   * math.sin(2.0 * math.pi * t / self.period_s))

    def peak_hz(self) -> float:
        return max(0.0, self.base + max(0.0, self.amplitude))

    def phases(self, duration_s: Optional[float] = None) -> List[Phase]:
        d = self.duration_s if duration_s is None else duration_s
        names = ("rise", "peak", "fall", "trough")
        out: List[Phase] = []
        q = self.period_s / 4.0
        start, i = 0.0, 0
        while start < d:
            end = min(d, start + q)
            day, quarter = divmod(i, 4)
            tag = names[quarter] if d <= self.period_s else \
                f"day{day + 1}.{names[quarter]}"
            out.append((tag, start, end))
            start, i = end, i + 1
        return out


@dataclass(frozen=True)
class FlashCrowd(RateShape):
    """A spike: ``extra_hz`` added over ``[at_s, at_s + width_s)`` —
    zero elsewhere (sum it onto a baseline shape)."""

    extra_hz: float
    at_s: float
    width_s: float
    duration_s: float = 0.0

    def __post_init__(self) -> None:
        if self.duration_s <= 0:
            object.__setattr__(self, "duration_s", self.at_s + self.width_s)

    def rate_hz(self, t: float) -> float:
        return self.extra_hz if self.at_s <= t < self.at_s + self.width_s \
            else 0.0

    def peak_hz(self) -> float:
        return self.extra_hz

    def phases(self, duration_s: Optional[float] = None) -> List[Phase]:
        d = self.duration_s if duration_s is None else duration_s
        out: List[Phase] = []
        if self.at_s > 0:
            out.append(("before", 0.0, min(d, self.at_s)))
        if self.at_s < d:
            out.append(("spike", self.at_s, min(d, self.at_s + self.width_s)))
        if self.at_s + self.width_s < d:
            out.append(("after", self.at_s + self.width_s, d))
        return out


@dataclass(frozen=True)
class Ramp(RateShape):
    """Linear sweep from ``start_hz`` to ``end_hz`` across the
    duration — the capacity-probe shape."""

    start_hz: float
    end_hz: float
    duration_s: float

    def rate_hz(self, t: float) -> float:
        if not 0.0 <= t < self.duration_s:
            return 0.0
        frac = t / self.duration_s
        return max(0.0, self.start_hz + (self.end_hz - self.start_hz) * frac)

    def peak_hz(self) -> float:
        return max(self.start_hz, self.end_hz, 0.0)


@dataclass(frozen=True)
class Sum(RateShape):
    """Pointwise sum of shapes (superposed Poisson processes sum rates
    exactly).  Phases: the union of the parts' phase boundaries, so a
    flash crowd riding a diurnal is judged before/during/after the
    spike within each diurnal quarter it touches."""

    parts: Tuple[RateShape, ...]

    def __post_init__(self) -> None:
        if not self.parts:
            raise ValueError("Sum needs at least one part")
        object.__setattr__(self, "duration_s",
                           max(p.duration_s for p in self.parts))

    def rate_hz(self, t: float) -> float:
        return sum(p.rate_hz(t) for p in self.parts)

    def peak_hz(self) -> float:
        # conservative (rates are non-negative): a valid envelope even
        # when the parts peak at different instants
        return sum(p.peak_hz() for p in self.parts)

    def phases(self, duration_s: Optional[float] = None) -> List[Phase]:
        d = self.duration_s if duration_s is None else duration_s
        cuts = {0.0, d}
        for p in self.parts:
            for _, s, e in p.phases(d):
                cuts.update((min(s, d), min(e, d)))
        edges = sorted(cuts)
        out: List[Phase] = []
        for s, e in zip(edges, edges[1:]):
            if e <= s:
                continue
            mid = (s + e) / 2.0
            names = []
            for p in self.parts:
                for tag, ps, pe in p.phases(d):
                    if ps <= mid < pe:
                        names.append(tag)
                        break
            out.append(("+".join(names) or "all", s, e))
        return out


@dataclass(frozen=True)
class Compressed(RateShape):
    """The wall-clock knob: replay ``inner`` ``factor``-times faster.
    Time scales down, rate scales up by the same factor, so the
    EXPECTED ARRIVAL COUNT of every phase is preserved — an 8-hour
    diurnal compressed 320x runs in 90 s and still pushes the same
    number of requests through each quarter."""

    inner: RateShape
    factor: float

    def __post_init__(self) -> None:
        if self.factor <= 0:
            raise ValueError("compression factor must be positive")
        object.__setattr__(self, "duration_s",
                           self.inner.duration_s / self.factor)

    def rate_hz(self, t: float) -> float:
        return self.inner.rate_hz(t * self.factor) * self.factor

    def peak_hz(self) -> float:
        return self.inner.peak_hz() * self.factor

    def phases(self, duration_s: Optional[float] = None) -> List[Phase]:
        d = (self.inner.duration_s if duration_s is None
             else duration_s * self.factor)
        return [(name, s / self.factor, e / self.factor)
                for name, s, e in self.inner.phases(d)]


def compress(shape: RateShape, factor: float) -> RateShape:
    return Compressed(inner=shape, factor=factor)


def build_shaped_schedule(shape: RateShape, mix: LoadMix) -> List[Arrival]:
    """Arrivals for a time-varying rate, by Lewis–Shedler thinning:
    draw a homogeneous Poisson stream at the envelope ``peak_hz`` and
    keep each candidate ``t`` with probability ``rate_hz(t)/peak``.
    Pure and seeded — the mix's ``seed`` drives candidate times,
    thinning, and the key/difficulty/model draws (the generator's own
    samplers), so one (shape, mix) pair yields one schedule byte for
    byte.  The mix's ``rate_hz``/``duration_s`` are ignored in favor of
    the shape (the LoadMix validator requires them positive; pass any
    placeholder)."""
    peak = shape.peak_hz()
    if peak <= 0:
        return []
    rng = random.Random(mix.seed)
    zipf = _zipf_cdf(mix.n_keys, mix.zipf_s)
    diff_cum = _cum_weights(mix.difficulties)
    model_cum = _cum_weights(mix.hash_models)
    out: List[Arrival] = []
    t = rng.expovariate(peak)
    while t < shape.duration_s:
        if rng.random() * peak < shape.rate_hz(t):
            key = _pick(zipf, rng)
            ntz = mix.difficulties[_pick(diff_cum, rng)][0]
            model = mix.hash_models[_pick(model_cum, rng)][0]
            out.append(Arrival(
                t=round(t, 9), key=key,
                nonce=key_nonce(mix.seed, key, mix.nonce_len),
                ntz=int(ntz), hash_model=model,
            ))
        t += rng.expovariate(peak)
    return out
