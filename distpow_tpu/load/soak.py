"""Long-haul soak harness: shaped load + chaos + retention + a typed
verdict (docs/SOAK.md).

``run_load_slo`` (harness.py) answers "does a 60-second constant-rate
burst meet the SLO?".  A soak answers the question ROADMAP item 4
actually asks — *does the system hold for hours without an operator
watching?* — which needs four things the short harness lacks, all
built in this plane and assembled here:

1. **shaped load** — a :class:`~.shapes.RateShape` replayed through
   the open-loop runner (compressed diurnal + flash crowd is the
   canonical CI soak);
2. **retention** — every fleet sweep lands in a
   :class:`~distpow_tpu.obs.timeseries.TimeSeriesStore` (optionally
   spooled to rotated JSONL for post-mortem replay), shared with the
   SLO engine so burn windows and phase judgments read the same
   points;
3. **sentinels** — the ``proc.*`` gauges the node Stats handlers now
   export are trended by a :class:`~distpow_tpu.runtime.health
   .LeakSentinel` over the whole run;
4. **a typed verdict** — :class:`SoakVerdict` fails when ANY of: some
   shape phase breaches its windowed SLO judgment, a leak suspect is
   flagged, ring-drop counters exceed their per-request budget, or the
   generator could not hold its schedule (``load.lag_s`` p99 over
   budget — a lagging generator silently converts open-loop into
   closed-loop and invalidates everything else).  ``exit_code()``
   follows the SLO CLI contract: 0 green, 1 failed; config errors
   raise :class:`~distpow_tpu.obs.slo.SLOConfigError` and exit 2 at
   the CLI (cli/soak.py).

The registry caveat from harness.py applies unchanged: the judged view
scrapes the first coordinator only.
"""

from __future__ import annotations

import queue as _queue
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..obs.scrape import FleetScraper
from ..obs.slo import SLOEngine, load_slo_config
from ..obs.timeseries import DEFAULT_TIERS, TimeSeriesStore
from ..runtime import faults
from ..runtime.health import LeakSentinel
from ..runtime.metrics import REGISTRY as metrics
from ..runtime.telemetry import RECORDER
from .harness import InProcCluster, _CompletionTracker, exact_percentile
from .loadgen import Arrival, LoadMix, OpenLoopRunner
from .shapes import RateShape, build_shaped_schedule

#: ring-drop budgets, per issued request (plus a flat allowance): a
#: bounded ring dropping its oldest under sustained load is the design
#: working, an EXPLOSION is evidence loss worth failing on.
DEFAULT_RING_DROP_PER_REQUEST: Dict[str, float] = {
    "telemetry.dropped_events": 20.0,
    "spans.dropped": 200.0,
}
DEFAULT_RING_DROP_FLAT = 2000.0

#: leak-sentinel noise floors, in each gauge's own units (total rise
#: over the run below which a climb is noise, not a leak)
DEFAULT_LEAK_FLOORS: Dict[str, float] = {
    "proc.threads": 8.0,
    "proc.open_fds": 32.0,
    "proc.rss_bytes": 256.0 * 1024 * 1024,
}

DEFAULT_LAG_BUDGET_S = 1.0


@dataclass
class PhaseVerdict:
    """One shape phase's windowed SLO judgment."""

    name: str
    start_s: float
    end_s: float
    status: str
    objectives: List[dict] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "start_s": round(self.start_s, 3),
            "end_s": round(self.end_s, 3),
            "status": self.status,
            "objectives": self.objectives,
        }


@dataclass
class SoakVerdict:
    """The soak contract (module docstring): green needs every phase
    SLO-clean, zero leak suspects, bounded ring drops, bounded
    generator lag."""

    status: str  # pass | warn | breach
    phases: List[PhaseVerdict]
    leak_suspects: List[dict]
    ring_drops: Dict[str, float]
    ring_drop_budgets: Dict[str, float]
    lag_p99_s: Optional[float]
    lag_budget_s: float
    failures: List[str] = field(default_factory=list)
    ts: float = 0.0

    def exit_code(self) -> int:
        return 1 if self.status == "breach" else 0

    def to_dict(self) -> dict:
        return {
            "status": self.status,
            "ts": self.ts,
            "failures": list(self.failures),
            "phases": [p.to_dict() for p in self.phases],
            "leak_suspects": list(self.leak_suspects),
            "ring_drops": dict(self.ring_drops),
            "ring_drop_budgets": {k: round(v, 1) for k, v
                                  in self.ring_drop_budgets.items()},
            "lag_p99_s": self.lag_p99_s,
            "lag_budget_s": self.lag_budget_s,
        }

    def render(self) -> str:
        out = [f"Soak verdict: {self.status.upper()}"]
        for p in self.phases:
            out.append(f"  phase {p.name:24s} "
                       f"[{p.start_s:7.1f}s..{p.end_s:7.1f}s]  "
                       f"{p.status.upper()}")
        for s in self.leak_suspects:
            out.append(f"  LEAK SUSPECT {s.get('gauge')}: "
                       f"+{s.get('rise'):.3g} over "
                       f"{s.get('window_s'):.1f}s "
                       f"({s.get('slope_per_s'):.3g}/s)")
        for name, n in sorted(self.ring_drops.items()):
            budget = self.ring_drop_budgets.get(name, 0.0)
            tag = "OVER" if n > budget else "ok"
            out.append(f"  ring drops {name}: {n:.0f} "
                       f"(budget {budget:.0f}) {tag}")
        lag = "-" if self.lag_p99_s is None else f"{self.lag_p99_s:.4f}s"
        out.append(f"  generator lag p99: {lag} "
                   f"(budget {self.lag_budget_s:.3f}s)")
        for f in self.failures:
            out.append(f"  FAIL: {f}")
        return "\n".join(out)


def run_soak(
    shape: RateShape,
    mix: LoadMix,
    slo_config,
    cluster: Optional[InProcCluster] = None,
    n_workers: int = 2,
    coord_extra: Optional[dict] = None,
    worker_extra: Optional[dict] = None,
    scrape_interval_s: float = 1.0,
    scrape_deadline_s: float = 2.0,
    drain_timeout_s: float = 60.0,
    fault_spec: Optional[dict] = None,
    store: Optional[TimeSeriesStore] = None,
    spool_path: Optional[str] = None,
    leak_window_s: Optional[float] = None,
    leak_floors: Optional[Dict[str, float]] = None,
    leak_gauges: Tuple[str, ...] = ("proc.threads", "proc.open_fds",
                                    "proc.rss_bytes"),
    ring_drop_per_request: Optional[Dict[str, float]] = None,
    lag_budget_s: float = DEFAULT_LAG_BUDGET_S,
) -> Tuple[dict, SoakVerdict]:
    """Replay ``shape`` against a cluster with retention + sentinels on;
    returns ``(report, verdict)`` (module docstring).

    The mix supplies seed/keys/difficulties; its ``rate_hz`` /
    ``duration_s`` are placeholders (the shape rules).  ``cluster=None``
    boots an :class:`~.harness.InProcCluster`; pass an attached cluster
    object (``.client``, ``.scrape_targets()``) to soak real processes
    (cli/soak.py).  ``fault_spec`` installs a PR 1 chaos plan for the
    duration."""
    config = slo_config if hasattr(slo_config, "objectives") \
        else load_slo_config(slo_config)
    own_cluster = cluster is None
    if own_cluster:
        cluster = InProcCluster(n_workers=n_workers,
                                coord_extra=coord_extra,
                                worker_extra=worker_extra)
    if store is None:
        store = TimeSeriesStore(tiers=DEFAULT_TIERS, spool_path=spool_path)
    engine = SLOEngine(config, store=store)
    scraper = FleetScraper(
        # judged view: first coordinator only (module docstring)
        cluster.scrape_targets(include_workers=False)[:1],
        deadline_s=scrape_deadline_s,
    )
    tracker = _CompletionTracker()
    stop_drain = threading.Event()
    stop_sweeps = threading.Event()
    prev_plan = faults.PLAN

    def drain() -> None:
        q = cluster.client.notify_queue
        while not stop_drain.is_set():
            try:
                res = q.get(timeout=0.05)
            except _queue.Empty:
                continue
            tracker.completed_one(res)

    def submit(arr: Arrival) -> None:
        tracker.issued(arr)
        cluster.client.mine(arr.nonce, arr.ntz, hash_model=arr.hash_model)

    def sweep_once() -> Optional[dict]:
        try:
            merged = scraper.sweep()
        except Exception:
            # one lost point, never the run — the final sweep gates
            return None
        store.append(merged)
        metrics.inc("soak.sweeps")
        return merged

    def sweep_loop() -> None:
        while not stop_sweeps.wait(scrape_interval_s):
            sweep_once()

    try:
        if fault_spec:
            faults.install_from_spec(fault_spec)
        schedule = build_shaped_schedule(shape, mix)
        baseline = sweep_once()
        drainer = threading.Thread(target=drain, daemon=True,
                                   name="soak-drain")
        drainer.start()
        sweeper = threading.Thread(target=sweep_loop, daemon=True,
                                   name="soak-sweeps")
        sweeper.start()
        runner = OpenLoopRunner(submit)
        # phase boundaries are schedule offsets; the store is keyed by
        # the scraper's wall-clock stamps, so anchor offsets at the
        # wall clock once (an instant, not a duration — durations below
        # ride the monotonic clock)
        t0_wall = time.time()
        t0 = time.monotonic()
        load_report = runner.run(schedule)
        deadline = time.monotonic() + drain_timeout_s
        expected = load_report.issued - load_report.submit_errors
        while (tracker.completed < expected
               and time.monotonic() < deadline):
            time.sleep(0.02)
        wall_total_s = time.monotonic() - t0
        stop_sweeps.set()
        sweeper.join(timeout=scrape_deadline_s + 1.0)
        final = sweep_once()
        stop_drain.set()
        drainer.join(timeout=2.0)
        end_wall = t0_wall + wall_total_s

        verdict = _judge(
            engine, store, shape, t0_wall, end_wall,
            issued=load_report.issued,
            leak_window_s=leak_window_s or wall_total_s + 1.0,
            leak_floors={**DEFAULT_LEAK_FLOORS, **(leak_floors or {})},
            leak_gauges=leak_gauges,
            ring_drop_per_request={**DEFAULT_RING_DROP_PER_REQUEST,
                                   **(ring_drop_per_request or {})},
            lag_budget_s=lag_budget_s,
        )
        solved = list(tracker.latencies_s)
        report = {
            "shape": repr(shape),
            "phases": [{"name": n, "start_s": round(s, 3),
                        "end_s": round(e, 3),
                        "arrivals": sum(1 for a in schedule
                                        if s <= a.t < e)}
                       for n, s, e in shape.phases()],
            "mix": {"seed": mix.seed, "n_keys": mix.n_keys,
                    "zipf_s": mix.zipf_s, "chaos": bool(fault_spec)},
            "load": load_report.to_dict(),
            "completed": tracker.completed,
            "request_errors": len(tracker.errors),
            "error_samples": tracker.errors[:3],
            "wall_total_s": round(wall_total_s, 3),
            "achieved_solves_per_s": round(
                tracker.completed / max(wall_total_s, 1e-9), 3),
            "client_latency_ms": {
                "n": len(solved),
                "p50": _ms(exact_percentile(solved, 0.50)),
                "p95": _ms(exact_percentile(solved, 0.95)),
            },
            "retention": {
                "points": len(store),
                "tiers": [{"resolution_s": t.resolution_s,
                           "retention_s": t.retention_s,
                           "points": len(store.tier_points(i))}
                          for i, t in enumerate(store.tiers)],
                "spool": spool_path,
            },
            "sweeps_ok": baseline is not None and final is not None,
            "verdict": verdict.to_dict(),
        }
        return report, verdict
    finally:
        if fault_spec:
            faults.install(prev_plan)
        stop_sweeps.set()
        stop_drain.set()
        scraper.close()
        if own_cluster:
            cluster.close()


def _judge(engine: SLOEngine, store: TimeSeriesStore, shape: RateShape,
           t0_wall: float, end_wall: float, issued: int,
           leak_window_s: float, leak_floors: Dict[str, float],
           leak_gauges: Tuple[str, ...],
           ring_drop_per_request: Dict[str, float],
           lag_budget_s: float) -> SoakVerdict:
    failures: List[str] = []

    # 1. every shape phase must hold the SLO over ITS window
    phases: List[PhaseVerdict] = []
    worst = "pass"
    for name, s, e in shape.phases():
        try:
            pv = engine.judge_range(t0_wall + s, min(t0_wall + e, end_wall))
            objectives = [o.to_dict() for o in pv.objectives]
            # phase status prefers the informative tie-break: a warm
            # dominance cache legitimately starves miss-series
            # objectives of samples mid-soak, and "no_data" must not
            # mask the objectives that DID judge the phase green
            statuses = {o.status for o in pv.objectives}
            for status in ("breach", "warn", "pass", "no_data"):
                if status in statuses:
                    break
            else:
                status = "no_data"
        except ValueError:
            status, objectives = "no_data", []
        phases.append(PhaseVerdict(name=name, start_s=s, end_s=e,
                                   status=status, objectives=objectives))
        if status == "breach":
            metrics.inc("soak.phase_breaches")
            failures.append(f"phase {name!r} breached its SLO window")
            worst = "breach"
        elif status == "warn" and worst == "pass":
            worst = "warn"

    # 2. zero leak suspects (runtime/health.py; the event/counter side
    # effects fire inside check())
    sentinel = LeakSentinel(window_s=leak_window_s)
    suspects = sentinel.check(store, gauges=list(leak_gauges),
                              noise_floors=leak_floors)
    for s in suspects:
        failures.append(
            f"leak suspect: gauge {s.gauge!r} climbed {s.rise:.3g} "
            f"({s.slope_per_s:.3g}/s over {s.window_s:.1f}s)")

    # 3. ring-drop counters bounded (per-request budgets + flat slack)
    run_window = store.range_window(t0_wall, end_wall) or {}
    counters = run_window.get("counters") or {}
    drops: Dict[str, float] = {}
    budgets: Dict[str, float] = {}
    for name, per_req in ring_drop_per_request.items():
        n = float(counters.get(name, 0))
        budget = per_req * max(0, issued) + DEFAULT_RING_DROP_FLAT
        drops[name] = n
        budgets[name] = budget
        if n > budget:
            failures.append(f"ring drops {name}: {n:.0f} over "
                            f"budget {budget:.0f}")

    # 4. the generator held its schedule (load.lag_s over the run)
    lag_hist = (run_window.get("histograms") or {}).get("load.lag_s")
    lag_p99 = (lag_hist or {}).get("p99")
    if lag_p99 is not None and lag_p99 > lag_budget_s:
        failures.append(f"open-loop lag p99 {lag_p99:.3f}s over "
                        f"budget {lag_budget_s:.3f}s — the generator "
                        f"could not hold its schedule")

    status = "breach" if failures else worst
    verdict = SoakVerdict(
        status=status, phases=phases,
        leak_suspects=[s.to_dict() for s in suspects],
        ring_drops=drops, ring_drop_budgets=budgets,
        lag_p99_s=lag_p99, lag_budget_s=lag_budget_s,
        failures=failures, ts=end_wall,
    )
    RECORDER.record("soak.verdict", status=status,
                    failures=list(failures),
                    phases=[(p.name, p.status) for p in phases])
    return verdict


def _ms(v: Optional[float]) -> Optional[float]:
    return None if v is None else round(v * 1e3, 3)
