"""In-process cluster + loadgen + fleet scrape + SLO assertion — the
observe-assert-generate triad in one callable (package docstring).

``run_load_slo`` is the engine under ``bench.py --load-slo`` and
``scripts/ci.sh --slo-smoke``: boot a cluster, replay a
:class:`..load.loadgen.LoadMix` open-loop against it, sweep the nodes'
Stats RPCs through the fleet scraper while traffic runs, and judge the
merged run-window snapshot against a declarative SLO config
(docs/SLO.md).  Everything is CPU-only and tunnel-independent by
construction: python-backend workers by default, localhost RPC, seeded
arrivals.

Registry caveat (runtime/metrics.py): in-process nodes share ONE
process-wide registry, so scraping the coordinator *and* its workers
returns near-identical snapshots — counter sums over them would
multiply by the node count.  The harness therefore scrapes the
COORDINATOR alone for the judged view (its snapshot already covers the
whole in-process cluster) and uses the worker targets only where
multiplicity is harmless by construction: the merge-vs-single-node
percentile cross-check (percentile estimates are invariant under
uniform count scaling), and the stale-node machinery.  Real multi-
registry merging is exercised by the subprocess tests in
tests/test_obs.py.
"""

from __future__ import annotations

import queue as _queue
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from ..nodes import Client, Coordinator, Worker
from ..obs.merge import BUCKET_RATIO, delta_merged
from ..obs.scrape import FleetScraper, NodeTarget
from ..obs.slo import SLOEngine, SLOVerdict, load_slo_config
from ..runtime import faults
from ..runtime.config import ClientConfig, CoordinatorConfig, WorkerConfig
from .loadgen import Arrival, LoadMix, OpenLoopRunner, build_schedule


def exact_percentile(samples: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile over raw samples — the combined-stream
    oracle the merged log-bucket estimates are cross-checked against."""
    if not samples:
        return None
    s = sorted(samples)
    idx = min(len(s) - 1, max(0, round(q * (len(s) - 1))))
    return s[idx]


class InProcCluster:
    """coordinator pool + N shared workers + one client, all in this
    process.

    The production shape of tests/test_nodes.py's Stack, packaged as
    product code so bench.py and the CI smoke need no test imports.
    Binds on ':0' and wires real addresses afterwards — no port races.

    ``n_coordinators > 1`` boots the scale-out shape (docs/CLUSTER.md):
    a pool of coordinators over ONE shared worker fleet, each member's
    ring installed via ``set_cluster_peers`` once the real client
    addresses exist, and the client in powlib cluster mode (consistent-
    hash routing, sibling hedging, failover).  ``n_coordinators=1``
    keeps the historical single-coordinator cluster byte-identical.
    """

    def __init__(self, n_workers: int = 2, backend: str = "python",
                 coord_extra: Optional[dict] = None,
                 worker_extra: Optional[dict] = None,
                 client_extra: Optional[dict] = None,
                 n_coordinators: int = 1):
        self.coordinators: List[Coordinator] = [
            Coordinator(CoordinatorConfig(
                ClientAPIListenAddr="127.0.0.1:0",
                WorkerAPIListenAddr="127.0.0.1:0",
                Workers=["pending:0"] * n_workers,
                **(coord_extra or {}),
            ))
            for _ in range(n_coordinators)
        ]
        self.coordinator = self.coordinators[0]  # back-compat alias
        bound = [c.initialize_rpcs() for c in self.coordinators]
        self.client_addrs = [client for client, _worker in bound]
        self.client_addr = self.client_addrs[0]
        if n_coordinators > 1:
            for i, c in enumerate(self.coordinators):
                c.set_cluster_peers(self.client_addrs, i)
        self.workers: List[Worker] = []
        addrs = []
        for i in range(n_workers):
            w = Worker(WorkerConfig(
                WorkerID=f"loadw{i}",
                ListenAddr="127.0.0.1:0",
                # the config default delivery target; pooled rounds
                # stamp their own reply-to, so every member receives
                # its rounds' Results regardless of this choice
                CoordAddr=bound[0][1],
                Backend=backend,
                WarmupNonceLens=[],
                WarmupWidths=[],
                **(worker_extra or {}),
            ))
            addrs.append(w.initialize_rpcs())
            w.start_forwarder()
            self.workers.append(w)
        self.worker_addrs = addrs
        for c in self.coordinators:
            c.set_worker_addrs(addrs)
        # the open-loop client: a deep notify queue — the drain runs on
        # one harness thread and a bounded default (10) would make
        # powlib's delivery the closed-loop throttle the generator
        # exists to avoid.  A pool rides CoordAddrs (powlib cluster
        # mode); a single coordinator keeps the plain CoordAddr shape.
        self.client = Client(ClientConfig(
            ClientID="loadgen", CoordAddr=self.client_addr,
            CoordAddrs=self.client_addrs if n_coordinators > 1 else [],
            ChCapacity=100_000, **(client_extra or {}),
        ))
        self.client.initialize()

    def scrape_targets(self, include_workers: bool = False) -> List[NodeTarget]:
        targets = [
            NodeTarget(addr=a, name=(f"coordinator{i}" if i else
                                     "coordinator"),
                       role="coordinator")
            for i, a in enumerate(self.client_addrs)
        ]
        if include_workers:
            targets.extend(
                NodeTarget(addr=a, name=w.config.WorkerID, role="worker")
                for a, w in zip(self.worker_addrs, self.workers)
            )
        return targets

    def kill_coordinator(self, i: int) -> None:
        """Hard-stop pool member ``i`` without draining — the in-proc
        stand-in for SIGKILL (bench.py ``--cache-ha``; the real-process
        version lives in scripts/ha_smoke.py).  The member's listeners
        close and its worker links drop; the client's next Mine on a key
        it owned rides powlib's ring-walk failover to the survivor.
        Idempotent; ``close()`` skips already-killed members."""
        c = self.coordinators[i]
        if c is None:
            return
        self.coordinators[i] = None
        c.shutdown()

    def close(self) -> None:
        self.client.close()
        for w in self.workers:
            w.shutdown()
        for c in self.coordinators:
            if c is not None:
                c.shutdown()


class _CompletionTracker:
    """Match notify-queue completions back to their issue times.

    Keyed by (nonce, ntz): Zipf repeats make keys non-unique, so each
    key holds a FIFO of issue times — completions of coalesced/cached
    repeats drain oldest-first, which at worst attributes one repeat's
    latency to its sibling (same key, same round: the error is bounded
    by the round itself)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._issued: Dict[Tuple[bytes, int], deque] = {}
        self.latencies_s: List[float] = []
        self.completed = 0
        self.errors: List[str] = []

    def issued(self, arr: Arrival) -> None:
        with self._lock:
            self._issued.setdefault((arr.nonce, arr.ntz),
                                    deque()).append(time.monotonic())

    def completed_one(self, res) -> None:
        now = time.monotonic()
        with self._lock:
            self.completed += 1
            if getattr(res, "error", None):
                self.errors.append(str(res.error))
            dq = self._issued.get((bytes(res.nonce),
                                   int(res.num_trailing_zeros)))
            if dq:
                self.latencies_s.append(now - dq.popleft())


def run_load_slo(
    mix: LoadMix,
    slo_config,
    cluster: Optional[InProcCluster] = None,
    n_workers: int = 2,
    coord_extra: Optional[dict] = None,
    worker_extra: Optional[dict] = None,
    scrape_interval_s: float = 1.0,
    scrape_deadline_s: float = 2.0,
    include_worker_targets: bool = False,
    drain_timeout_s: float = 60.0,
    breach_hooks: bool = True,
    fault_spec: Optional[dict] = None,
) -> Tuple[dict, SLOVerdict]:
    """Replay ``mix`` against a cluster, scraping + judging as it runs.

    Returns ``(report, verdict)``: the report is a JSON-able summary
    (offered/achieved rates, client-observed exact latencies, merged
    run-window views, coalesce/cache evidence); the verdict is the
    typed SLO outcome whose ``exit_code()`` gates CI.  ``fault_spec``
    optionally installs a PR 1 fault plan for the duration of the run
    (chaos under load), restored afterwards.
    """
    config = slo_config if hasattr(slo_config, "objectives") \
        else load_slo_config(slo_config)
    own_cluster = cluster is None
    if own_cluster:
        cluster = InProcCluster(n_workers=n_workers,
                                coord_extra=coord_extra,
                                worker_extra=worker_extra)
    # the JUDGED view scrapes the coordinator alone (module docstring:
    # in-process nodes share one registry, so summing coordinator AND
    # worker snapshots would multiply every counter by the node count);
    # include_worker_targets only adds the multi-node sweep used for
    # the scale-invariant merge-vs-single-node cross-check below
    scraper = FleetScraper(
        # first coordinator only: under an in-process pool every member
        # shares the one registry, so sweeping them all would multiply
        # the judged counters by the pool size (module docstring)
        cluster.scrape_targets(include_workers=False)[:1],
        deadline_s=scrape_deadline_s,
    )
    engine = SLOEngine(config)
    tracker = _CompletionTracker()
    stop_drain = threading.Event()
    prev_plan = faults.PLAN

    def drain() -> None:
        q = cluster.client.notify_queue
        while not stop_drain.is_set():
            try:
                res = q.get(timeout=0.05)
            except _queue.Empty:
                continue
            tracker.completed_one(res)

    def submit(arr: Arrival) -> None:
        tracker.issued(arr)
        cluster.client.mine(arr.nonce, arr.ntz, hash_model=arr.hash_model)

    stop_sweeps = threading.Event()

    def sweep_loop() -> None:
        while not stop_sweeps.wait(scrape_interval_s):
            try:
                engine.observe(scraper.sweep())
            except Exception:
                # a failed mid-run sweep costs one history point, never
                # the run; the final sweep below is the one that gates
                pass

    try:
        if fault_spec:
            faults.install_from_spec(fault_spec)
        baseline = scraper.sweep()
        engine.observe(baseline)
        drainer = threading.Thread(target=drain, daemon=True,
                                   name="loadgen-drain")
        drainer.start()
        sweeper = threading.Thread(target=sweep_loop, daemon=True,
                                   name="loadgen-sweeps")
        sweeper.start()
        schedule = build_schedule(mix)
        runner = OpenLoopRunner(submit)
        t_start = time.monotonic()
        load_report = runner.run(schedule)
        # drain the tail: open-loop means arrivals never waited for
        # completions, so the backlog finishes after the last arrival
        deadline = time.monotonic() + drain_timeout_s
        # a submit that RAISED never reaches powlib, so no completion
        # (not even an error MineResult) will ever arrive for it —
        # waiting for those would stall every such run for the full
        # drain timeout (review of this PR)
        expected = load_report.issued - load_report.submit_errors
        while (tracker.completed < expected
               and time.monotonic() < deadline):
            time.sleep(0.02)
        wall_total_s = time.monotonic() - t_start
        stop_sweeps.set()
        sweeper.join(timeout=scrape_deadline_s + 1.0)
        final = scraper.sweep()
        verdict = engine.evaluate(final, breach_hooks=breach_hooks)
        stop_drain.set()
        drainer.join(timeout=2.0)
        run_window = delta_merged(final, baseline)
        hists = run_window.get("histograms") or {}
        counters = run_window.get("counters") or {}
        solved = [s for s in tracker.latencies_s]
        report = {
            "mix": {
                "rate_hz": mix.rate_hz, "duration_s": mix.duration_s,
                "seed": mix.seed, "n_keys": mix.n_keys,
                "zipf_s": mix.zipf_s,
                "difficulties": [list(d) for d in mix.difficulties],
                "hash_models": [[m or "default", w]
                                for m, w in mix.hash_models],
                "chaos": bool(fault_spec),
            },
            "load": load_report.to_dict(),
            "completed": tracker.completed,
            "request_errors": len(tracker.errors),
            "error_samples": tracker.errors[:3],
            # completions over the FULL wall (arrival window + backlog
            # drain): open-loop lets the backlog outlive the schedule,
            # and dividing by the arrival window alone would overstate
            # a server that is merely queueing
            "wall_total_s": round(wall_total_s, 3),
            "achieved_solves_per_s": round(
                tracker.completed / max(wall_total_s, 1e-9), 3),
            "client_latency_ms": {
                "n": len(solved),
                "p50": _ms(exact_percentile(solved, 0.50)),
                "p95": _ms(exact_percentile(solved, 0.95)),
                "max": _ms(max(solved) if solved else None),
            },
            "merged": {
                "window_s": run_window.get("window_s"),
                "mine_miss_p95_ms": _ms(
                    (hists.get("coord.mine_s.miss") or {}).get("p95")),
                "mine_hit_p95_ms": _ms(
                    (hists.get("coord.mine_s.hit") or {}).get("p95")),
                "cache_hits": counters.get("cache.hit", 0),
                "coalesced_requests": counters.get(
                    "sched.coalesced_requests", 0),
                "admission_rejected": counters.get(
                    "sched.admission_rejected", 0),
                "stale_nodes": final.get("stale_nodes") or [],
            },
            "verdict": verdict.to_dict(),
        }
        if include_worker_targets:
            # merged-vs-single-node oracle (bench.py --load-slo
            # acceptance): one multi-node sweep, used ONLY here — the
            # cluster-merged percentile must sit within one log bucket
            # of the coordinator's own estimate (the merge may
            # re-bucket, never relocate).  Percentiles are invariant
            # under the shared-registry count multiplication that keeps
            # these worker targets out of the judged view above.
            xcheck = FleetScraper(
                cluster.scrape_targets(include_workers=True),
                deadline_s=scrape_deadline_s,
            )
            try:
                xsnap = xcheck.sweep()
                coord_hists = (xcheck.last_snapshots().get("coordinator")
                               or {}).get("histograms") or {}
                report["oracle_check"] = percentile_within_one_bucket(
                    (xsnap.get("histograms") or {}).get("coord.mine_s.miss"),
                    coord_hists.get("coord.mine_s.miss"),
                )
                report["oracle_check"]["nodes"] = int(xsnap.get("nodes", 0))
            finally:
                xcheck.close()
        return report, verdict
    finally:
        if fault_spec:
            faults.install(prev_plan)
        stop_sweeps.set()
        stop_drain.set()
        scraper.close()
        if own_cluster:
            cluster.close()


def _ms(v: Optional[float]) -> Optional[float]:
    return None if v is None else round(v * 1e3, 3)


def percentile_within_one_bucket(merged_hist: Optional[dict],
                                 oracle_hist: Optional[dict],
                                 stat: str = "p95") -> dict:
    """Cross-check for bench.py --load-slo: a cluster-merged percentile
    must sit within ONE log bucket (``BUCKET_RATIO``) of a single-node
    oracle's estimate for the same stream — merging may re-bucket, it
    must never move a percentile beyond the representation's own error
    bound (docs/SLO.md "Aggregation")."""
    m = (merged_hist or {}).get(stat)
    o = (oracle_hist or {}).get(stat)
    if not m or not o:
        return {"ok": m == o, "merged": m, "oracle": o, "stat": stat}
    ratio = m / o if m >= o else o / m
    return {
        "ok": ratio <= BUCKET_RATIO + 1e-9,
        "merged": round(m, 6),
        "oracle": round(o, 6),
        "ratio": round(ratio, 4),
        "bound": round(BUCKET_RATIO, 4),
        "stat": stat,
    }
