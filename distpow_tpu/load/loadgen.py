"""Seeded Poisson open-loop generator (package docstring; docs/SLO.md).

Two halves, split so tests can pin determinism without wall clocks:

* :func:`build_schedule` — pure: ``LoadMix`` -> the full arrival list
  (offsets, keys, nonces, difficulties, hash models), derived entirely
  from the mix's seed.  Same mix, same schedule, byte for byte.
* :class:`OpenLoopRunner` — executes a schedule against a submit
  callable on the wall clock WITHOUT waiting for completions: an
  arrival whose predecessors are still in flight fires anyway (that is
  what "open loop" means — a server falling behind faces the full
  offered rate, not a politely self-throttling client).  The runner
  never skips arrivals; when the submit path itself lags it fires late
  and records the lag, so a wedged cluster shows up as lag + missing
  completions, never as silently reduced load.

Key skew is Zipf (``P(key=k) ∝ 1/(k+1)^s``) over a bounded key
universe: with s ≈ 1 a handful of hot keys dominate — repeat Mines for
a hot key coalesce while in flight (PR 4) and hit the dominance cache
after — which is exactly the cache/coalesce regime the ROADMAP's heavy
-traffic story depends on.  ``zipf_s=0`` degrades to uniform.
"""

from __future__ import annotations

import hashlib
import random
import threading
import time
from bisect import bisect_left
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Tuple

from ..runtime.metrics import REGISTRY as metrics


@dataclass(frozen=True)
class LoadMix:
    """One traffic mix: rate, duration, skew, and blends.

    ``difficulties`` / ``hash_models`` are ``(value, weight)`` blends;
    weights need not sum to 1.  ``hash_model=None`` entries mean the
    cluster default model (requests then carry no ``hash_model`` param
    and stay wire-identical to plain traffic)."""

    rate_hz: float
    duration_s: float
    seed: int = 1
    n_keys: int = 64
    zipf_s: float = 1.1
    nonce_len: int = 4
    difficulties: Tuple[Tuple[int, float], ...] = ((2, 1.0),)
    hash_models: Tuple[Tuple[Optional[str], float], ...] = ((None, 1.0),)

    def __post_init__(self) -> None:
        if self.rate_hz <= 0 or self.duration_s <= 0:
            raise ValueError("rate_hz and duration_s must be positive")
        if self.n_keys < 1 or self.nonce_len < 1:
            raise ValueError("n_keys and nonce_len must be >= 1")
        for blend, what in ((self.difficulties, "difficulties"),
                            (self.hash_models, "hash_models")):
            if not blend or any(w <= 0 for _, w in blend):
                raise ValueError(f"{what} needs positive-weight entries")


@dataclass(frozen=True)
class Arrival:
    """One scheduled request."""

    t: float  # offset from schedule start, seconds
    key: int  # key-universe index (before skew, for diagnostics)
    nonce: bytes
    ntz: int
    hash_model: Optional[str] = None


def _cum_weights(blend: Sequence[Tuple[object, float]]) -> List[float]:
    total = 0.0
    out = []
    for _, w in blend:
        total += float(w)
        out.append(total)
    return out


def _zipf_cdf(n_keys: int, s: float) -> List[float]:
    total = 0.0
    out = []
    for k in range(n_keys):
        total += 1.0 / ((k + 1) ** s) if s > 0 else 1.0
        out.append(total)
    return out


def _pick(cdf: List[float], rng: random.Random) -> int:
    return bisect_left(cdf, rng.random() * cdf[-1])


def key_nonce(seed: int, key: int, nonce_len: int) -> bytes:
    """Deterministic per-key nonce: stable across runs of one seed (so
    repeat keys genuinely repeat — the cache/coalesce point) and
    disjoint across seeds (so two mixes cannot cross-hit each other's
    dominance-cache entries)."""
    digest = hashlib.sha256(f"loadgen:{seed}:{key}".encode()).digest()
    return digest[:nonce_len]


def build_schedule(mix: LoadMix) -> List[Arrival]:
    """The full, deterministic arrival list for ``mix`` (module
    docstring).  Inter-arrival gaps are exponential(rate) — a Poisson
    process — starting from the first gap, so the schedule models a
    steady stream joined mid-flow, not a thundering herd at t=0."""
    rng = random.Random(mix.seed)
    zipf = _zipf_cdf(mix.n_keys, mix.zipf_s)
    diff_cum = _cum_weights(mix.difficulties)
    model_cum = _cum_weights(mix.hash_models)
    out: List[Arrival] = []
    t = rng.expovariate(mix.rate_hz)
    while t < mix.duration_s:
        key = _pick(zipf, rng)
        ntz = mix.difficulties[_pick(diff_cum, rng)][0]
        model = mix.hash_models[_pick(model_cum, rng)][0]
        out.append(Arrival(
            t=round(t, 9), key=key,
            nonce=key_nonce(mix.seed, key, mix.nonce_len),
            ntz=int(ntz), hash_model=model,
        ))
        t += rng.expovariate(mix.rate_hz)
    return out


@dataclass
class LoadReport:
    """What the runner observed about its own dispatch (completions are
    the harness's side — see distpow_tpu/load/harness.py)."""

    issued: int = 0
    submit_errors: int = 0
    wall_s: float = 0.0
    offered_rate_hz: float = 0.0
    max_lag_s: float = 0.0  # worst (fire time - scheduled time)
    lag_sum_s: float = 0.0
    issued_by_key: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "issued": self.issued,
            "submit_errors": self.submit_errors,
            "wall_s": round(self.wall_s, 3),
            "offered_rate_hz": round(self.offered_rate_hz, 3),
            "max_lag_s": round(self.max_lag_s, 4),
            "mean_lag_s": round(
                self.lag_sum_s / max(1, self.issued), 4),
            "hot_key_share": round(
                max(self.issued_by_key.values(), default=0)
                / max(1, self.issued), 4),
        }


class OpenLoopRunner:
    """Fire a schedule at the wall clock, open-loop (module docstring).

    ``submit(arrival)`` must be non-blocking-cheap (powlib's
    ``client.mine`` enqueues and returns); a submit that raises is
    counted, logged into the report, and the schedule continues — load
    generation never dies mid-mix, or the SLO assertion would judge a
    cluster that only saw half the offered traffic."""

    def __init__(self, submit: Callable[[Arrival], None]):
        self._submit = submit
        self._stop = threading.Event()

    def stop(self) -> None:
        self._stop.set()

    def run(self, schedule: Sequence[Arrival]) -> LoadReport:
        rep = LoadReport()
        t0 = time.monotonic()
        for arr in schedule:
            if self._stop.is_set():
                break
            delay = arr.t - (time.monotonic() - t0)
            if delay > 0 and self._stop.wait(delay):
                break
            lag = (time.monotonic() - t0) - arr.t
            try:
                self._submit(arr)
            except Exception:
                rep.submit_errors += 1
            rep.issued += 1
            rep.issued_by_key[arr.key] = rep.issued_by_key.get(arr.key, 0) + 1
            if lag > rep.max_lag_s:
                rep.max_lag_s = lag
            rep.lag_sum_s += max(0.0, lag)
            # declared histogram, not just the report (ISSUE 18): a
            # lagging generator silently converts open-loop into
            # closed-loop, so the lag distribution must be visible to
            # the scraper/soak verdict like any other series
            metrics.observe("load.lag_s", max(0.0, lag))
        rep.wall_s = time.monotonic() - t0
        rep.offered_rate_hz = rep.issued / rep.wall_s if rep.wall_s else 0.0
        return rep
