"""Request-forensics span layer — per-trace_id timing observers
(ISSUE 14; docs/FORENSICS.md).

The tracing plane (runtime/tracing.py) proves *ordering* and the
metrics plane (runtime/metrics.py) proves *aggregates*; neither can
answer "which shard/slot/launch made THIS Mine slow".  This module
closes that gap with spans: lock-cheap in-process records of
``(trace_id, name, node, start_ts, dur_s, attrs)`` hung off the seams
the tracer and flight recorder already pass through.  Spans are
DERIVED observers — they never mint trace actions, never touch the
16-action wire vocabulary, and golden traces stay byte-identical
whether spans are on or off.

Mechanics:

* One process-global :data:`SPANS` ring (the ``REGISTRY``/``RECORDER``
  pattern): recording is a dict build plus a deque append under one
  lock — the same cost class as a counter increment.  In-process
  multi-node harnesses share the ring; every span carries its ``node``
  so attribution survives the sharing.
* Spans are keyed by the EXISTING trace ids (runtime/tracing.py): the
  id a client's token carries is the id the coordinator's and workers'
  spans record, so one fetch per node stitches the cross-node
  timeline with no new protocol state.  Layers below the RPC surface
  (parallel/search.py, sched/engine.py) have no Trace in scope; the
  owning request thread binds its id — :meth:`SpanRecorder.bind` —
  and those layers read it back through the thread-local.
* The sanctioned begin-site form is the context manager
  ``with SPANS.span("worker.solve", ...) as sp: ...`` — it cannot
  leak an unfinished span.  :meth:`SpanRecorder.begin` exists for
  spans that genuinely cross a thread boundary (a scheduler slot is
  submitted on the miner thread and finished on the device loop);
  distpow-lint's ``unclosed-span`` rule (docs/LINT.md) requires every
  ``begin`` call site to carry a justified suppression naming its
  single finish point.
* Fleet-scoped events with no request in scope (a lease expiry) record
  under ``trace_id=0`` — visible in the ring and in dumps, never in a
  per-trace fetch.

Export: every node answers the ``Node.Spans`` RPC (runtime/rpc.py
``StatsOnly``) with its ring's spans for a trace id, or summaries of
its recent traces; ``distpow_tpu/obs/forensics.py`` sweeps the fleet
concurrently and stitches the timeline.  ``DISTPOW_SPANS=0`` disables
recording process-wide (``bench.py --forensics-overhead`` measures the
on-vs-off serving cost and asserts it stays within 5%).

Span-name vocabulary (kept small and documented — docs/FORENSICS.md):
``powlib.mine``, ``coord.mine``, ``coord.fanout``,
``coord.first_result``, ``coord.cancel_storm``, ``coord.reassign``,
``fleet.hedge``, ``fleet.lease_expiry``, ``worker.solve``,
``worker.result_forward``, ``sched.slot``, ``search.launch``,
``search.poll``.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from .metrics import REGISTRY as metrics

DEFAULT_CAPACITY = 4096

#: span names that anchor a whole request (the per-trace "root"):
#: trace summaries and slowest-trace ranking prefer these durations.
ROOT_SPANS = ("coord.mine", "powlib.mine")

_tls = threading.local()


class _NullSpan:
    """Returned when recording is disabled: every operation is a no-op,
    so call sites never branch on the enabled flag themselves."""

    __slots__ = ()

    def annotate(self, **attrs) -> None:
        pass

    def finish(self, **attrs) -> None:
        pass

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass


_NULL = _NullSpan()


class SpanHandle:
    """One open span.  ``finish()`` records it exactly once; the
    context-manager form finishes at block exit (and tags an
    ``outcome`` on exceptions so an error path is visible in the
    timeline, not just absent)."""

    __slots__ = ("_rec", "trace_id", "name", "node", "attrs", "ts",
                 "_t0", "_done")

    def __init__(self, rec: "SpanRecorder", trace_id: int, name: str,
                 node: str, attrs: dict):
        self._rec = rec
        self.trace_id = trace_id
        self.name = name
        self.node = node
        self.attrs = attrs
        self.ts = time.time()
        self._t0 = time.monotonic()
        self._done = False

    def annotate(self, **attrs) -> None:
        self.attrs.update(attrs)

    def finish(self, **attrs) -> None:
        if self._done:
            return
        self._done = True
        if attrs:
            self.attrs.update(attrs)
        self._rec._append(self.trace_id, self.name, self.node, self.ts,
                          time.monotonic() - self._t0, self.attrs)

    def __enter__(self) -> "SpanHandle":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        # a handle the block already finished must not be touched: its
        # attrs dict is aliased into the recorded span
        if self._done:
            return
        if exc_type is not None and "outcome" not in self.attrs:
            self.attrs["outcome"] = f"error:{exc_type.__name__}"
        self.finish()


class _Bind:
    """Context manager installing (trace_id, node) on the current
    thread; nests correctly (restores the previous binding)."""

    __slots__ = ("_tid", "_node", "_prev")

    def __init__(self, trace_id: int, node: str):
        self._tid = int(trace_id)
        self._node = node

    def __enter__(self) -> "_Bind":
        self._prev = getattr(_tls, "ctx", None)
        _tls.ctx = (self._tid, self._node)
        return self

    def __exit__(self, *exc) -> None:
        _tls.ctx = self._prev


class SpanRecorder:
    """Bounded ring of finished spans (module docstring)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._spans: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._seq = 0
        self._enabled = os.environ.get("DISTPOW_SPANS", "1") != "0"

    # -- configuration ------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._enabled

    def configure(self, enabled: Optional[bool] = None,
                  capacity: Optional[int] = None) -> None:
        with self._lock:
            if enabled is not None:
                self._enabled = bool(enabled)
            if capacity is not None and capacity != self._spans.maxlen:
                self._spans = deque(self._spans, maxlen=int(capacity))

    # -- thread-local request binding ---------------------------------------
    @staticmethod
    def bind(trace_id: int, node: str = "") -> _Bind:
        """Bind the current thread to a request: spans recorded below
        the RPC surface (search drivers, scheduler submit) inherit the
        trace id and node without plumbing them through every call."""
        return _Bind(trace_id, node)

    @staticmethod
    def current_trace_id() -> int:
        ctx = getattr(_tls, "ctx", None)
        return ctx[0] if ctx else 0

    @staticmethod
    def current_node() -> str:
        ctx = getattr(_tls, "ctx", None)
        return ctx[1] if ctx else ""

    # -- recording ----------------------------------------------------------
    def _resolve(self, trace_id, node):
        tid = self.current_trace_id() if trace_id is None else int(trace_id)
        nd = self.current_node() if node is None else node
        return tid, nd

    def span(self, name: str, trace_id: Optional[int] = None,
             node: Optional[str] = None, **attrs):
        """The sanctioned begin-site form: ``with SPANS.span(...)``."""
        if not self._enabled:
            return _NULL
        tid, nd = self._resolve(trace_id, node)
        return SpanHandle(self, tid, name, nd, attrs)

    def begin(self, name: str, trace_id: Optional[int] = None,
              node: Optional[str] = None, **attrs):
        """Open a span that a DIFFERENT scope will ``finish()`` — for
        work crossing a thread boundary.  Lint-gated (``unclosed-span``,
        docs/LINT.md): every call site must justify where the single
        finish point is, because a leaked handle is a span that never
        happened."""
        if not self._enabled:
            return _NULL
        tid, nd = self._resolve(trace_id, node)
        return SpanHandle(self, tid, name, nd, attrs)

    def record(self, name: str, start_ts: float, dur_s: float,
               trace_id: Optional[int] = None, node: Optional[str] = None,
               **attrs) -> None:
        """Record a span whose timing the caller already measured
        (explicit start/duration — the coordinator's fanout stages are
        carved out of timestamps it takes anyway)."""
        if not self._enabled:
            return
        tid, nd = self._resolve(trace_id, node)
        self._append(tid, name, nd, start_ts, dur_s, attrs)

    def event(self, name: str, trace_id: Optional[int] = None,
              node: Optional[str] = None, **attrs) -> None:
        """Zero-duration marker span (a hedge, a reassignment)."""
        self.record(name, time.time(), 0.0, trace_id, node, **attrs)

    def _append(self, trace_id: int, name: str, node: str, ts: float,
                dur_s: float, attrs: dict) -> None:
        with self._lock:
            self._seq += 1
            if len(self._spans) == self._spans.maxlen:
                # ring overwrite: per-trace fetches lose the oldest
                # span — counted so a truncated timeline is attributable
                # to capacity, not a bug
                metrics.inc("spans.dropped")
            self._spans.append({
                "seq": self._seq,
                "trace_id": int(trace_id),
                "name": name,
                "node": node,
                "ts": round(ts, 6),
                "dur_s": round(float(dur_s), 6),
                "attrs": attrs,
            })

    # -- reading ------------------------------------------------------------
    def depth(self) -> int:
        """Current ring occupancy — the ``ring.spans_depth`` gauge the
        resource sentinels export (runtime/health.py)."""
        with self._lock:
            return len(self._spans)

    @property
    def total_recorded(self) -> int:
        """Monotonic count of spans ever recorded — the delta source
        for "did anything record?" checks (ring LENGTH saturates at
        capacity and reads as a zero delta forever after)."""
        with self._lock:
            return self._seq

    def recent(self, n: Optional[int] = None) -> List[dict]:
        with self._lock:
            out = list(self._spans)
        return out if n is None else out[-n:]

    def spans_for(self, trace_id: int,
                  limit: Optional[int] = None) -> List[dict]:
        out = [s for s in self.recent() if s["trace_id"] == int(trace_id)]
        return out if limit is None else out[-limit:]

    def trace_summaries(self, limit: int = 50) -> List[dict]:
        """Newest-first per-trace summaries: root span (when captured),
        span count, and the trace's slowest span — the ``Spans`` RPC's
        no-trace_id reply, which is how a caller finds the trace worth
        fetching in full."""
        by_tid: Dict[int, dict] = {}
        for s in self.recent():
            tid = s["trace_id"]
            if tid == 0:
                continue
            cur = by_tid.setdefault(tid, {
                "trace_id": tid, "spans": 0, "ts": s["ts"],
                "root": None, "dur_s": 0.0, "slowest": None,
                "slowest_dur_s": 0.0,
            })
            cur["spans"] += 1
            cur["ts"] = min(cur["ts"], s["ts"])
            if s["name"] in ROOT_SPANS and s["dur_s"] >= cur["dur_s"]:
                cur["root"] = s["name"]
                cur["dur_s"] = s["dur_s"]
            if s["dur_s"] >= cur["slowest_dur_s"]:
                cur["slowest"] = s["name"]
                cur["slowest_dur_s"] = s["dur_s"]
        out = sorted(by_tid.values(), key=lambda r: -r["ts"])[:limit]
        for r in out:
            if r["root"] is None:
                # no root captured (ring overwrote it, or a partial
                # trace): rank by the slowest member instead
                r["dur_s"] = r["slowest_dur_s"]
        return out

    def slowest_traces(self, k: int = 5) -> List[dict]:
        """Top-k slowest recent traces WITH their span trees — what an
        SLO breach dump attaches (distpow_tpu/obs/slo.py)."""
        summaries = sorted(self.trace_summaries(limit=256),
                           key=lambda r: -r["dur_s"])[:k]
        return [dict(s, spans=self.spans_for(s["trace_id"]))
                for s in summaries]

    def reset(self) -> None:
        """Testing hook (configuration is kept)."""
        with self._lock:
            self._spans.clear()
            self._seq = 0


SPANS = SpanRecorder()


class SlowRequestTrigger:
    """Slow-request auto-capture policy (docs/FORENSICS.md).

    Two independent arms, either of which fires the capture:

    * a FIXED threshold (``threshold_s`` > 0): any request slower than
      the budget is evidence by definition;
    * a ROLLING p99 exceedance (``p99_factor`` > 0): a request slower
      than ``p99_factor x`` the p99 of the last ``window`` requests is
      a tail outlier even when the absolute budget is generous.  The
      rolling arm stays quiet until ``min_samples`` requests have been
      observed, so boot-time compiles cannot spray captures.

    ``observe`` judges the sample against the PRE-observation window —
    a slow request must not lift its own bar — then folds it in.
    Thread-safe; the coordinator calls it once per completed miss.
    """

    def __init__(self, threshold_s: float = 0.0, p99_factor: float = 0.0,
                 window: int = 256, min_samples: int = 20):
        self.threshold_s = float(threshold_s or 0.0)
        self.p99_factor = float(p99_factor or 0.0)
        self.min_samples = int(min_samples)
        self._durs: deque = deque(maxlen=int(window))
        self._lock = threading.Lock()

    @property
    def armed(self) -> bool:
        return self.threshold_s > 0.0 or self.p99_factor > 0.0

    def observe(self, dur_s: float) -> Optional[str]:
        """Returns the trigger reason ("threshold" / "p99") when the
        sample should be captured, else None."""
        dur_s = float(dur_s)
        reason = None
        with self._lock:
            if self.threshold_s > 0.0 and dur_s > self.threshold_s:
                reason = "threshold"
            elif self.p99_factor > 0.0 and \
                    len(self._durs) >= self.min_samples:
                ordered = sorted(self._durs)
                p99 = ordered[min(len(ordered) - 1,
                                  int(0.99 * (len(ordered) - 1)))]
                if dur_s > self.p99_factor * p99:
                    reason = "p99"
            self._durs.append(dur_s)
        return reason
