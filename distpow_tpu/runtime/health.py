"""Resource sentinels: per-node self-telemetry gauges and a leak trend
detector (docs/SOAK.md "Sentinels").

The gap (ROADMAP item 4): every observability plane so far watches the
WORKLOAD — latency, fan-outs, cache hits.  Nothing watches the process
itself, so the classic long-haul failures (a thread leaked per request,
an fd leaked per reconnect, a ring quietly pinned at capacity) are
invisible to every 60-second test and every SLO objective.  Two pieces
close it:

* :class:`ResourceSentinels` — samples process self-telemetry (RSS via
  ``/proc/self/statm`` with a ``resource.getrusage`` fallback, fd count
  via ``/proc/self/fd``, thread count) plus the occupancy of every
  bounded ring the repo owns (span ring, flight-recorder ring, and any
  registered probe such as the replication push queue) into DECLARED
  gauges (``proc.*`` / ``ring.*`` — runtime/metrics.py KNOWN_GAUGES).
  The node ``Stats`` handlers call :meth:`ResourceSentinels.sample`
  before snapshotting, so the gauges ride the existing Stats RPC,
  ``--prom`` exposition, fleet scraper, and time-series retention with
  zero new plumbing.  Forwarder backlog and sched run queue already
  ship as ``worker.forward_queue_depth`` / ``sched.run_queue_depth``.

* :class:`LeakSentinel` — a trend detector over a gauge's retained
  trajectory (obs/timeseries.py ``gauge_series``): least-squares slope
  over a configurable window, judged against a noise floor (the total
  rise across the window must clear an absolute floor AND the series
  must actually climb, not wobble — a noisy-but-flat gauge fits a
  near-zero slope and stays quiet; tests/test_health.py pins both
  directions).  A suspect becomes a typed ``health.leak_suspect``
  flight-recorder event + ``health.leak_suspects`` counter increment,
  deduplicated per gauge per detector instance, and a
  :class:`LeakSuspect` entry in the soak verdict (load/soak.py).

Sampling is read-only and bounded (two /proc reads, one directory
listing, a couple of ring locks) — cheap enough for every Stats call.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .metrics import REGISTRY as metrics
from .spans import SPANS
from .telemetry import RECORDER

log = logging.getLogger("distpow.health")

_PAGE_SIZE = 4096
try:
    _PAGE_SIZE = os.sysconf("SC_PAGE_SIZE")
except (ValueError, OSError, AttributeError):  # non-POSIX
    pass


def rss_bytes() -> Optional[float]:
    """Resident set size.  ``/proc/self/statm`` (current RSS) when the
    platform has it; ``resource.getrusage`` (peak RSS — still monotone
    under a leak, which is what the sentinel needs) otherwise."""
    try:
        with open("/proc/self/statm") as fh:
            return float(int(fh.read().split()[1]) * _PAGE_SIZE)
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource

        ru = resource.getrusage(resource.RUSAGE_SELF)
        # ru_maxrss is KiB on Linux, bytes on macOS; Linux took the
        # /proc path above, so scale for the common fallback
        return float(ru.ru_maxrss) * (1.0 if ru.ru_maxrss > 1 << 30
                                      else 1024.0)
    except (ImportError, OSError, ValueError):
        return None


def open_fds() -> Optional[float]:
    try:
        return float(len(os.listdir("/proc/self/fd")))
    except OSError:
        return None


class ResourceSentinels:
    """Gauge sampler for process self-telemetry and ring depths.

    Probes are ``name -> callable() -> Optional[float]``; a probe
    returning None (unsupported platform, ring not wired yet) simply
    skips its gauge that round.  Probe names must be DECLARED gauges
    (KNOWN_GAUGES) — :meth:`register_probe` enforces it so a typo'd
    sentinel cannot hide from the trend detector."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._probes: Dict[str, Callable[[], Optional[float]]] = {}
        self.register_probe("proc.rss_bytes", rss_bytes)
        self.register_probe("proc.open_fds", open_fds)
        self.register_probe("proc.threads",
                            lambda: float(threading.active_count()))
        self.register_probe("ring.spans_depth",
                            lambda: float(SPANS.depth()))
        self.register_probe("ring.flightrec_depth",
                            lambda: float(RECORDER.depth()))

    def register_probe(self, name: str,
                       fn: Callable[[], Optional[float]]) -> None:
        from .metrics import KNOWN_GAUGES

        if name not in KNOWN_GAUGES:
            raise ValueError(
                f"sentinel probe {name!r} is not a declared gauge — add "
                f"it to runtime/metrics.py KNOWN_GAUGES")
        with self._lock:
            self._probes[name] = fn

    def sample(self) -> Dict[str, float]:
        """Run every probe and set its gauge; returns what was set.
        Best-effort per probe: one failing probe must not cost the
        Stats snapshot it rides on."""
        with self._lock:
            probes = list(self._probes.items())
        out: Dict[str, float] = {}
        for name, fn in probes:
            try:
                v = fn()
            except Exception as exc:
                log.debug("sentinel probe %s failed: %s", name, exc)
                continue
            if v is None:
                continue
            metrics.gauge(name, v)
            out[name] = v
        return out


#: process-global sampler, the REGISTRY/RECORDER pattern — the node
#: Stats handlers call ``SENTINELS.sample()`` before snapshotting.
SENTINELS = ResourceSentinels()


def least_squares_slope(
        series: Sequence[Tuple[float, float]]) -> Optional[float]:
    """Ordinary least-squares slope (units/second) of ``(ts, value)``
    points; None with fewer than two distinct timestamps."""
    n = len(series)
    if n < 2:
        return None
    mean_t = sum(t for t, _ in series) / n
    mean_v = sum(v for _, v in series) / n
    sxx = sum((t - mean_t) ** 2 for t, _ in series)
    if sxx <= 0.0:
        return None
    sxy = sum((t - mean_t) * (v - mean_v) for t, v in series)
    return sxy / sxx


@dataclass(frozen=True)
class LeakSuspect:
    """One gauge the trend detector judged monotone-climbing."""

    gauge: str
    slope_per_s: float
    rise: float         # slope * observed span: total climb judged
    window_s: float     # observed span of the judged series
    points: int
    first: float
    last: float

    def to_dict(self) -> dict:
        return dict(self.__dict__)


class LeakSentinel:
    """Trend detector over gauge trajectories (module docstring).

    ``noise_floor`` is the absolute rise (gauge units over the whole
    window) below which a climb is noise: 1.5 means "flag only if the
    fitted line climbs more than 1.5 threads/fds", while RSS callers
    pass bytes.  ``min_monotone_frac`` additionally requires that
    fraction of consecutive steps to be non-decreasing, so an
    oscillating gauge whose endpoints happen to rise stays quiet."""

    def __init__(self, window_s: float = 120.0, min_points: int = 6,
                 noise_floor: float = 2.0,
                 min_monotone_frac: float = 0.7):
        self.window_s = float(window_s)
        self.min_points = int(min_points)
        self.noise_floor = float(noise_floor)
        self.min_monotone_frac = float(min_monotone_frac)
        self._flagged: set = set()

    def judge_series(
            self, gauge: str,
            series: Sequence[Tuple[float, float]]) -> Optional[LeakSuspect]:
        """Judge one gauge trajectory; no side effects (unit tests call
        this directly)."""
        if len(series) < self.min_points:
            return None
        slope = least_squares_slope(series)
        if slope is None or slope <= 0.0:
            return None
        span = series[-1][0] - series[0][0]
        rise = slope * span
        if rise <= self.noise_floor:
            return None
        steps = [series[i + 1][1] - series[i][1]
                 for i in range(len(series) - 1)]
        up = sum(1 for d in steps if d >= 0)
        if up < self.min_monotone_frac * len(steps):
            return None
        return LeakSuspect(gauge=gauge, slope_per_s=slope, rise=rise,
                           window_s=span, points=len(series),
                           first=series[0][1], last=series[-1][1])

    def check(self, store, gauges: Optional[Sequence[str]] = None,
              now: Optional[float] = None,
              noise_floors: Optional[Dict[str, float]] = None
              ) -> List[LeakSuspect]:
        """Sweep gauge trajectories retained in a
        :class:`~distpow_tpu.obs.timeseries.TimeSeriesStore`; each NEW
        suspect (per-gauge dedup — a leak stays leaking, one verdict
        entry is enough) increments ``health.leak_suspects`` and
        records a ``health.leak_suspect`` flight-recorder event."""
        names = list(gauges) if gauges is not None else [
            g for g in store.gauge_names()
            if g.startswith(("proc.", "ring."))
        ]
        floors = noise_floors or {}
        out: List[LeakSuspect] = []
        for name in names:
            series = store.gauge_series(name, window_s=self.window_s,
                                        now=now)
            floor = floors.get(name)
            if floor is None:
                suspect = self.judge_series(name, series)
            else:
                saved, self.noise_floor = self.noise_floor, float(floor)
                try:
                    suspect = self.judge_series(name, series)
                finally:
                    self.noise_floor = saved
            if suspect is None:
                continue
            out.append(suspect)
            if name in self._flagged:
                continue
            self._flagged.add(name)
            metrics.inc("health.leak_suspects")
            RECORDER.record(
                "health.leak_suspect", gauge=name,
                slope_per_s=round(suspect.slope_per_s, 6),
                rise=round(suspect.rise, 3),
                window_s=round(suspect.window_s, 3),
                points=suspect.points,
                first=suspect.first, last=suspect.last,
            )
            log.warning(
                "leak suspect: %s climbed %.3g over %.1fs "
                "(slope %.3g/s across %d points)",
                name, suspect.rise, suspect.window_s,
                suspect.slope_per_s, suspect.points)
        return out
