"""Token-passing distributed tracing with vector clocks.

Re-implements the role the DistributedClocks/tracing library plays in the
reference (SURVEY.md section 5): every node owns a ``Tracer`` with an
identity; a request's life is one ``Trace`` created at the client
(powlib/powlib.go:104); causality crosses process boundaries by embedding
``trace.generate_token()`` in RPC payloads and calling
``tracer.receive_token(token)`` at the receiver (every reference RPC
struct carries a Token field, e.g. worker.go:58,72, coordinator.go:72,87).

Mechanics:

* Each tracer maintains a vector clock over tracer identities.  Recording
  an action and generating/receiving a token all tick the local component;
  receiving merges the sender's clock (element-wise max) before ticking —
  the standard happens-before stitch.
* Tokens are self-contained JSON: ``{trace_id, vc}``.
* Events stream to a pluggable sink: ``TCPSink`` talks to the standalone
  tracing server process (cmd/tracing-server equivalent,
  cli/tracing_server_main.py), ``FileSink`` writes directly,
  ``MemorySink`` captures for tests (the trace-parity oracle).

Thread safety: a tracer may be used from many request threads (the
reference records from RPC handler goroutines); the clock and sink are
mutex-guarded.
"""

from __future__ import annotations

import base64
import json
import os
import socket
import struct
import threading
import zlib
from typing import Dict, List, Optional

from .actions import Action

Token = bytes


class MemorySink:
    """Captures events in memory; the unit-test trace oracle."""

    def __init__(self):
        self.events: List[dict] = []
        self._lock = threading.Lock()

    def emit(self, event: dict) -> None:
        with self._lock:
            self.events.append(event)

    def close(self) -> None:
        pass

    # -- test helpers ------------------------------------------------------
    def actions(self, identity: Optional[str] = None, trace_id: Optional[int] = None):
        with self._lock:
            evs = list(self.events)
        out = []
        for e in evs:
            if e["type"] != "action":
                continue
            if identity is not None and e["identity"] != identity:
                continue
            if trace_id is not None and e["trace_id"] != trace_id:
                continue
            out.append((e["identity"], e["action"], e["body"]))
        return out


class FileSink:
    """Appends human-readable trace lines to a local file."""

    def __init__(self, path: str):
        self._f = open(path, "a", buffering=1)
        self._lock = threading.Lock()

    def emit(self, event: dict) -> None:
        with self._lock:
            self._f.write(format_trace_line(event) + "\n")

    def close(self) -> None:
        with self._lock:
            self._f.close()


class TCPSink:
    """Ships events to the tracing server over a framed-JSON TCP stream."""

    def __init__(self, addr: str, secret: bytes = b""):
        self._addr = addr
        self._secret = bytes(secret)
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()

    def _connect(self) -> socket.socket:
        host, _, port = self._addr.rpartition(":")
        sock = socket.create_connection((host or "127.0.0.1", int(port)))
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        hello = json.dumps(
            {"type": "hello", "secret": base64.b64encode(self._secret).decode()}
        ).encode()
        sock.sendall(struct.pack(">I", len(hello)) + hello)
        return sock

    def emit(self, event: dict) -> None:
        """Ship one event; a broken connection is retried once and then the
        event is dropped.  Tracing must never poison the protocol path: a
        tracing-server restart or hiccup costs trace records, not mining
        requests."""
        payload = json.dumps(event).encode()
        with self._lock:
            for attempt in (0, 1):
                try:
                    if self._sock is None:
                        # distpow: ok transitive-blocking-under-lock -- the
                        # sink lock doubles as the exclusive-redialer
                        # guard: exactly one tracer thread dials after a
                        # drop while the rest queue behind it, and the
                        # dial is bounded by the connect timeout
                        self._sock = self._connect()
                    # distpow: ok no-blocking-under-lock -- the sink lock
                    # is the per-connection frame serializer (same
                    # invariant as rpc._write_frame); only tracer threads
                    # of this process contend here, and a wedged tracing
                    # server costs trace records, never protocol progress
                    self._sock.sendall(
                        struct.pack(">I", len(payload)) + payload
                    )
                    return
                except OSError:
                    if self._sock is not None:
                        try:
                            self._sock.close()
                        except OSError:
                            pass
                        self._sock = None
                    if attempt == 1:
                        return  # drop the event

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                finally:
                    self._sock = None


def format_trace_line(event: dict) -> str:
    """Human trace format: [identity] TraceID=… Action field=value, …"""
    if event["type"] == "action":
        body = ", ".join(f"{k}={v}" for k, v in event["body"].items())
        return (
            f"[{event['identity']}] TraceID={event['trace_id']} "
            f"{event['action']} {body}"
        )
    return f"[{event['identity']}] {event['type']} TraceID={event.get('trace_id')}"


class Trace:
    """One causal trace (a single request's life across nodes)."""

    def __init__(self, tracer: "Tracer", trace_id: int):
        self.tracer = tracer
        self.trace_id = trace_id

    def record_action(self, action: Action) -> None:
        self.tracer._record(self.trace_id, action)

    def record_actions(self, *actions: Action) -> None:
        """Record several actions under ONE tracer-lock critical section.

        Needed wherever an invariant spans a multi-action sequence — e.g.
        the cache replacement pair CacheRemove→CacheAdd (coordinator.go:
        436-454 emits them back-to-back from inside the cache mutex, so no
        other action of the same node can interleave).  With per-action
        locking a concurrent handler thread could slot an event between
        them and the trace checker's adjacency invariant would (correctly)
        flag the emitted order even though cache state was consistent.
        """
        self.tracer._record_many(self.trace_id, actions)

    def generate_token(self) -> Token:
        return self.tracer._generate_token(self.trace_id)


class Tracer:
    """Per-node tracing endpoint (DistributedClocks tracing.Tracer role)."""

    def __init__(self, identity: str, sink, secret: bytes = b""):
        self.identity = identity
        self.sink = sink
        self.secret = bytes(secret)
        self._vc: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._next_trace = [0]

    # -- trace lifecycle ---------------------------------------------------
    def create_trace(self) -> Trace:
        with self._lock:
            self._next_trace[0] += 1
            # trace ids are unique per (identity, counter); fold a STABLE
            # identity hash in so ids from different clients don't collide
            # yet two runs of the same scenario yield the same ids — the
            # golden-trace diff harness (tests/test_trace_parity.py)
            # depends on run-to-run determinism, which Python's built-in
            # hash() breaks via PYTHONHASHSEED randomization
            ident_tag = zlib.crc32(self.identity.encode()) & 0xFFFFFFFF
            tid = ident_tag << 32 | self._next_trace[0]
        return Trace(self, tid)

    def receive_token(self, token: Token) -> Trace:
        data = json.loads(bytes(token).decode())
        with self._lock:
            for ident, clock in data["vc"].items():
                self._vc[ident] = max(self._vc.get(ident, 0), clock)
            self._tick_locked()
            vc = dict(self._vc)
            # emit INSIDE the lock: clock tick and wire order must agree,
            # or concurrent threads ship events out of clock order and the
            # ShiViz happens-before stream is corrupt
            # distpow: ok no-blocking-under-lock -- that ordering invariant
            # REQUIRES the emit under the clock lock; the TCP sink degrades
            # to dropping events rather than blocking indefinitely
            self._emit(
                {
                    "type": "receive_token",
                    "identity": self.identity,
                    "trace_id": data["trace_id"],
                    "vc": vc,
                }
            )
        return Trace(self, data["trace_id"])

    def close(self) -> None:
        self.sink.close()

    # -- internals ---------------------------------------------------------
    def _tick_locked(self) -> None:
        self._vc[self.identity] = self._vc.get(self.identity, 0) + 1

    def _record(self, trace_id: int, action: Action) -> None:
        self._record_many(trace_id, (action,))

    def _record_many(self, trace_id: int, actions) -> None:
        with self._lock:
            for action in actions:
                self._tick_locked()
                vc = dict(self._vc)
                # distpow: ok no-blocking-under-lock -- clock tick and
                # wire order must agree (see receive_token); emitting
                # outside the lock lets concurrent recorders invert the
                # happens-before stream
                self._emit(
                    {
                        "type": "action",
                        "identity": self.identity,
                        "trace_id": trace_id,
                        "action": action.name,
                        "body": action.to_fields(),
                        "vc": vc,
                    }
                )

    def _generate_token(self, trace_id: int) -> Token:
        with self._lock:
            self._tick_locked()
            vc = dict(self._vc)
            # distpow: ok no-blocking-under-lock -- clock tick and wire
            # order must agree (see receive_token)
            self._emit(
                {
                    "type": "generate_token",
                    "identity": self.identity,
                    "trace_id": trace_id,
                    "vc": vc,
                }
            )
        return json.dumps({"trace_id": trace_id, "vc": vc}).encode()

    def _emit(self, event: dict) -> None:
        self.sink.emit(event)


def make_tracer(
    identity: str,
    server_addr: str = "",
    secret: bytes = b"",
    sink=None,
) -> Tracer:
    """Build a tracer for a node config: TCP to the tracing server when an
    address is configured, else a local memory sink (tracing effectively
    off, but the API stays live)."""
    if sink is None:
        sink = TCPSink(server_addr, secret) if server_addr else MemorySink()
    return Tracer(identity, sink, secret)


def encode_token(token: Optional[Token]) -> Optional[str]:
    """Legacy (pre-wire-v2) form: tokens as base64 strings inside JSON
    RPC payloads.  Kept because ``decode_token`` must keep accepting
    frames from peers that still send this form."""
    if token is None:
        return None
    return base64.b64encode(bytes(token)).decode()


def wire_token(token: Optional[Token]) -> Optional[bytes]:
    """Tokens ride RPC payloads as raw bytes: wire v2 ships them
    verbatim; the JSON codec renders bytes as arrays of ints
    (runtime/rpc.py ``_json_default``) — both of which
    ``decode_token`` accepts alongside the legacy base64 string."""
    return None if token is None else bytes(token)


def decode_token(s) -> Optional[Token]:
    """Accept every wire form a peer may send: ``None``, the legacy
    base64 string (pre-v2 senders), a list of ints (wire v1 from a v2
    sender), or raw bytes (wire v2)."""
    if s is None:
        return None
    if isinstance(s, str):
        return base64.b64decode(s)
    return bytes(s)
