"""Framed-JSON TCP RPC — the framework's DCN-level communication backend.

Plays the role Go ``net/rpc`` plays in the reference (SURVEY.md section 2
component 11): blocking unary calls (rpc.Client.Call,
coordinator.go:195,226), async calls returning a completion handle
(rpc.Client.Go, powlib/powlib.go:156, cmd/worker/main.go:35), one server
servicing multiple listeners (the coordinator's segregated client/worker
listeners, coordinator.go:334-351), and concurrent dispatch of requests.

Wire framing: 4-byte big-endian length prefix + payload.  Two payload
codecs exist (docs/RPC.md):

* **v1 (JSON)** — UTF-8 JSON, the format every version of this repo has
  spoken.  Request ``{"id": n, "method": "Service.Method", "params":
  {...}}``; response ``{"id": n, "result": ..., "error": null | str}``.
  Byte fields travel as arrays of ints (the natural JSON form of the
  reference's ``[]uint8``) and tracing tokens as base64 strings —
  senders pass ``bytes`` and the codec renders both legacy forms
  (``_json_default`` / ``_jsonify_tokens``), keeping JSON-mode frames
  byte-identical to pre-v2 versions of this repo.
* **v2 (binary)** — the struct-packed codec in runtime/wire.py: raw
  ``bytes`` for nonce/secret/token, interned method and key ids, a
  dedicated ``retry_after`` header field.  Negotiated PER CONNECTION at
  dial time: the client sends a plain-JSON ``rpc.hello`` request; a
  v2-capable server acks it and both sides switch, while any other
  server answers it like any unknown method — an error frame — and the
  client transparently stays on JSON.  Mixed-version clusters therefore
  interoperate with no configuration; ``DISTPOW_RPC_CODEC=json`` pins
  the process to v1 for A/B measurement (bench.py --control-plane).

The fault-injection plane (runtime/faults.py) mutates the *encoded
frame* — delay/drop/duplicate/truncate behave identically on both
codecs — and the ``rpc.frame.{sent,recv}_bytes`` histograms measure the
payload shrink directly.  Within a TPU pod the hot path never touches
this transport — device fan-out rides ICI collectives
(parallel/mesh_search.py); this backend carries only control-plane
traffic between hosts, as the north-star design prescribes
(BASELINE.json: "the coordinator/worker RPC boundary stays intact").
"""

from __future__ import annotations

import base64
import json
import os
import socket
import struct
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Tuple

from . import faults, wire
from .metrics import REGISTRY as metrics


class RPCError(Exception):
    pass


class RPCTransportError(RPCError):
    """A connection-level failure — refused dial, failed/partial send,
    reader death — as opposed to an error *returned by* the remote
    handler (which stays a plain :class:`RPCError`).  The distinction is
    what makes client-side retry safe: a transport failure means the
    peer may never have seen (or finished) the call, so re-issuing an
    idempotent RPC (``Mine`` — the dominance cache absorbs repeats) is
    correct, while a handler error would just be re-earned."""


class RPCRetryAfter(RPCError):
    """The remote handler REJECTED the call with server-paced
    backpressure (the scheduler's admission control,
    sched/admission.py): the ``retry_after`` field of the response
    frame says when to try again.  A third retry class beside the two
    above: unlike a handler error it IS worth re-issuing — the server
    itself asked for the retry — and unlike a transport failure the
    retry is paced by the server's hint and must not burn the client's
    transport-failure budget (nodes/powlib.py)."""

    def __init__(self, message: str, delay_s: float):
        super().__init__(message)
        self.delay_s = float(delay_s)


class RPCNotOwner(RPCError):
    """The remote coordinator REJECTED the call because the cluster
    ring maps the key to a different shard (the scale-out plane's
    typed redirect — distpow_tpu/cluster/, docs/CLUSTER.md).  The
    ``ring`` attribute is the coordinator's fresh ring snapshot
    (``HashRing.to_wire`` dict), carried in the response frame's
    dedicated ``ring`` field: the client adopts it and re-routes in one
    round trip, with no separate discovery call.  A fourth retry class:
    like RETRY_AFTER it is worth re-issuing (elsewhere) and must not
    burn the transport retry budget — the connection is healthy and the
    server did exactly its job."""

    def __init__(self, message: str, ring: dict):
        super().__init__(message)
        self.ring = dict(ring or {})


#: pseudo-method of the per-connection codec negotiation exchange.  The
#: hello rides an ordinary v1 frame so a JSON-only peer handles it as a
#: normal (unknown-method) request; it is NOT passed through the fault
#: plane's per-frame hooks — dial-time faults already model the
#: negotiation window via the ``@connect`` pseudo-method.
HELLO_METHOD = "rpc.hello"
HELLO_TIMEOUT_S = 5.0

#: process defaults, overridable per client/server: "auto" negotiates
#: v2 with transparent JSON fallback; "json" pins v1; "binary" requires
#: v2 and fails the dial when the peer can't speak it.
CLIENT_CODEC_DEFAULT = os.environ.get("DISTPOW_RPC_CODEC") or "auto"
SERVER_NEGOTIATE_DEFAULT = os.environ.get("DISTPOW_RPC_CODEC") != "json"


def _json_default(o):
    """``bytes`` params render as arrays of ints on the JSON wire — the
    exact frames pre-v2 versions of this repo sent, so a v2 process
    pinned (or negotiated down) to JSON stays wire-identical."""
    if isinstance(o, (bytes, bytearray, memoryview)):
        return list(bytes(o))
    raise TypeError(f"{type(o).__name__} is not JSON-encodable")


def _jsonify_tokens(obj: dict) -> dict:
    """Tracing tokens travel as base64 strings on the JSON wire — the
    exact pre-v2 form, which a genuinely old peer's ``decode_token``
    (base64-only) can parse.  Every OTHER byte field was an int array
    before v2 and stays one via ``_json_default``; the token is the one
    field whose legacy form differed, so it alone needs this rewrite
    (review PR 5: rendering it as an int array would have broken real
    mixed-version clusters while the in-repo interop tests — both ends
    current code — stayed green)."""
    for key in ("params", "result"):
        inner = obj.get(key)
        if isinstance(inner, dict) and \
                isinstance(inner.get("token"), (bytes, bytearray, memoryview)):
            obj = dict(obj)
            obj[key] = dict(inner, token=base64.b64encode(
                bytes(inner["token"])).decode())
    return obj


class _JsonCodec:
    """Wire v1: UTF-8 JSON payloads."""

    name = "json"
    version = 1

    @staticmethod
    def encode(obj: dict) -> bytes:
        return json.dumps(_jsonify_tokens(obj), default=_json_default).encode()

    @staticmethod
    def decode(data: bytes):
        return json.loads(data.decode())


class _BinaryCodec:
    """Wire v2: the struct-packed codec (runtime/wire.py)."""

    name = "binary"
    version = wire.WIRE_VERSION

    @staticmethod
    def encode(obj: dict) -> bytes:
        return wire.encode_frame(obj)

    @staticmethod
    def decode(data: bytes) -> dict:
        return wire.decode_frame(data)


JSON_CODEC = _JsonCodec()
BINARY_CODEC = _BinaryCodec()


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        part = sock.recv(n - len(buf))
        if not part:
            raise ConnectionError("connection closed")
        buf += part
    return buf


def _read_frame(sock: socket.socket, codec=JSON_CODEC) -> dict:
    (length,) = struct.unpack(">I", _read_exact(sock, 4))
    if length > 64 * 1024 * 1024:
        raise RPCError(f"oversized frame: {length} bytes")
    metrics.observe("rpc.frame.recv_bytes", length)
    return codec.decode(_read_exact(sock, length))


def _write_frame(sock: socket.socket, obj: dict, lock: threading.Lock,
                 codec=JSON_CODEC) -> None:
    payload = codec.encode(obj)
    metrics.observe("rpc.frame.sent_bytes", len(payload))
    with lock:
        # distpow: ok no-blocking-under-lock -- this lock IS the frame
        # serializer: interleaved sendall from two threads would corrupt
        # the length-prefixed stream; the send is bounded by SO_SNDTIMEO
        sock.sendall(struct.pack(">I", len(payload)) + payload)


def _write_truncated(sock: socket.socket, obj: dict,
                     lock: threading.Lock, codec=JSON_CODEC) -> None:
    """Fault-plane helper (faults.py kind="truncate"): write a partial
    frame — length prefix plus roughly half the payload — so the peer's
    ``_read_exact`` sees a mid-frame connection reset when the caller
    tears the socket down right after.  Codec-agnostic: the tear is at
    the byte level, exactly like a real mid-frame reset."""
    payload = codec.encode(obj)
    frame = struct.pack(">I", len(payload)) + payload
    try:
        with lock:
            # distpow: ok no-blocking-under-lock -- same frame-serializer
            # lock as _write_frame; the deliberately-torn fault frame must
            # not interleave with a concurrent healthy write either
            sock.sendall(frame[: max(5, len(frame) // 2)])
    except OSError:
        pass


def split_addr(addr: str) -> Tuple[str, int]:
    """Connect-side parse: a host-less ':port' targets the local host."""
    host, _, port = addr.rpartition(":")
    return host or "127.0.0.1", int(port)


def split_bind_addr(addr: str) -> Tuple[str, int]:
    """Listen-side parse: a host-less ':port' binds all interfaces, like
    Go's net.Listen — reference configs use bare ':port' addresses
    (config/coordinator_config.json) and must stay multi-host capable."""
    host, _, port = addr.rpartition(":")
    return host, int(port)


class StatsOnly:
    """Observability-only view of a node handler, for registration
    under the role-agnostic ``Node`` service name (nodes/coordinator.py,
    nodes/worker.py): callers resolve any node's Stats — and, since the
    forensics plane (docs/FORENSICS.md), its span ring via ``Spans`` —
    without knowing or mis-probing its role, so auto-role discovery
    never mints ``rpc.handler_errors`` on the node being observed
    (distpow_tpu/obs/scrape.py, docs/SLO.md).  The protocol surface
    stays single-named; this view never exposes protocol methods."""

    def __init__(self, handler):
        self._handler = handler

    def Stats(self, params) -> dict:
        return self._handler.Stats(params)

    def Spans(self, params) -> dict:
        """Span-ring export (runtime/spans.py, docs/FORENSICS.md).

        ``{"trace_id": N}`` returns every retained span of that trace;
        without a trace_id the reply carries per-trace SUMMARIES of the
        recent ring (how a forensics caller finds the trace worth
        fetching in full).  ``limit`` bounds either list.  The ring is
        process-global, so an in-process multi-node harness answers
        with the union — each span's ``node`` field keeps attribution
        honest (the stitcher dedups by (node, seq))."""
        from .spans import SPANS

        limit = int(params.get("limit") or 512)
        tracer = getattr(self._handler, "tracer", None)
        out = {"node": getattr(tracer, "identity", "")}
        tid = params.get("trace_id")
        if tid is None:
            out["traces"] = SPANS.trace_summaries(limit=limit)
        else:
            out["spans"] = SPANS.spans_for(int(tid), limit=limit)
        return out


class RPCServer:
    """Multi-listener RPC server dispatching ``Service.Method`` requests.

    Each connection gets a reader thread; each request is dispatched on its
    own worker thread so slow handlers (the coordinator's blocking ``Mine``)
    never head-of-line-block other requests on the same connection —
    matching Go net/rpc's goroutine-per-request semantics.

    ``negotiate`` (default: module ``SERVER_NEGOTIATE_DEFAULT``) governs
    wire-v2 negotiation: when False the server is JSON-only and an
    incoming ``rpc.hello`` falls through to normal dispatch — the
    unknown-service error a pre-v2 server would return, which is
    exactly the reply that makes v2 clients fall back transparently.
    """

    def __init__(self, negotiate: Optional[bool] = None):
        self._negotiate = (SERVER_NEGOTIATE_DEFAULT
                           if negotiate is None else bool(negotiate))
        #: optional callable returning extra keys merged into the
        #: ``rpc.hello`` ack result beside ``codec`` (the cluster
        #: plane's ring advertisement — docs/CLUSTER.md).  The ack is
        #: always plain JSON, so the payload must be JSON-encodable;
        #: pre-cluster clients ignore keys they don't know.
        self.hello_extra = None
        self._services: Dict[str, object] = {}
        self._listeners = []
        self._threads = []
        self._conns = set()
        self._lock = threading.Lock()
        self._shutdown = threading.Event()

    def register(self, name: str, handler: object) -> None:
        self._services[name] = handler

    def listen(self, addr: str) -> str:
        """Bind a listener; returns the bound address (resolves ':0')."""
        host, port = split_bind_addr(addr)
        ls = socket.create_server((host, port), reuse_port=False)
        self._listeners.append(ls)
        bound = ls.getsockname()
        return f"{host or '127.0.0.1'}:{bound[1]}"

    def serve_in_background(self) -> None:
        for ls in self._listeners:
            # distpow: ok unbounded-thread-spawn -- bounded: one
            # acceptor per listener, and listeners are a small fixed
            # set wired at boot (the coordinator's two)
            t = threading.Thread(target=self._accept_loop, args=(ls,), daemon=True)
            t.start()
            self._threads.append(t)

    def _accept_loop(self, ls: socket.socket) -> None:
        while not self._shutdown.is_set():
            try:
                conn, _ = ls.accept()
            except OSError:
                return
            if self._shutdown.is_set():
                # the wake-up connection from shutdown(), or a late dial
                try:
                    conn.close()
                except OSError:
                    pass
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                self._conns.add(conn)
            # distpow: ok unbounded-thread-spawn -- deliberate
            # thread-per-connection: Go net/rpc parity (the reference's
            # accept loop spawns a goroutine per conn), and the peer set
            # is the cluster's node count, not open traffic
            threading.Thread(
                target=self._conn_loop, args=(conn,), daemon=True
            ).start()

    def _conn_loop(self, conn: socket.socket) -> None:
        wlock = threading.Lock()
        # per-connection codec, shared with this connection's dispatch
        # threads via a one-slot holder; flipped only by the hello
        # exchange below, which the client sends before any other frame
        codec: List[object] = [JSON_CODEC]
        try:
            peer = "%s:%s" % conn.getpeername()[:2]
        except OSError:
            peer = ""
        try:
            while True:
                req = _read_frame(conn, codec[0])
                if not isinstance(req, dict):
                    # valid JSON, wrong shape (e.g. a bare number):
                    # drop the connection rather than crash the
                    # dispatch thread on req.get (adversarial-input
                    # hardening, round 4)
                    raise RPCError(f"non-object frame: {type(req).__name__}")
                if self._negotiate and req.get("method") == HELLO_METHOD:
                    # answered INLINE on the reader thread: the ack must
                    # hit the wire before any frame of the new codec is
                    # read, and the handshake is the connection's first
                    # exchange so nothing else can be in flight
                    self._handle_hello(conn, wlock, req, codec)
                    continue
                # distpow: ok unbounded-thread-spawn -- deliberate
                # goroutine-per-request parity (class docstring): a slow
                # handler (the blocking Mine) must not head-of-line-block
                # the connection; depth is bounded by the caller's own
                # in-flight window, and admission control (PR 4) sheds
                # load before this layer sees it
                threading.Thread(
                    target=self._dispatch,
                    args=(conn, wlock, req, peer, codec),
                    daemon=True,
                ).start()
        except (ConnectionError, OSError, ValueError, RPCError):
            # ValueError covers json.JSONDecodeError AND the
            # UnicodeDecodeError a non-UTF-8 payload raises; RPCError
            # covers protocol violations from _read_frame (oversized
            # frame) and the shape check above — close the offending
            # connection quietly; other clients are unaffected
            pass
        finally:
            with self._lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _handle_hello(self, conn, wlock, req: dict, codec: List[object]) -> None:
        """Codec negotiation (docs/RPC.md): ack a supported version and
        flip this connection to the binary codec; anything else gets an
        error frame and the connection stays on JSON.  The hello itself
        always travels as v1 in both directions."""
        want = req.get("params") or {}
        version = want.get("codec") if isinstance(want, dict) else None
        if version == wire.WIRE_VERSION:
            result = {"codec": wire.WIRE_VERSION}
            if self.hello_extra is not None:
                try:
                    result.update(self.hello_extra() or {})
                # distpow: ok silent-except -- the hello extra is an
                # ADVISORY advertisement (the cluster ring): a broken
                # provider must not take codec negotiation down with
                # it, and clients refresh the same payload via
                # Cluster.Ring where a failure IS surfaced
                except Exception:
                    pass
            resp = {"id": req.get("id"), "result": result, "error": None}
        else:
            resp = {"id": req.get("id"), "result": None,
                    "error": f"RPCError: unsupported wire codec {version!r}"}
        try:
            _write_frame(conn, resp, wlock, JSON_CODEC)
        except OSError:
            return
        if resp["error"] is None:
            codec[0] = BINARY_CODEC
            metrics.inc("rpc.codec.negotiated_v2")

    def _dispatch(self, conn, wlock, req: dict, peer: str = "",
                  codec: Optional[List[object]] = None) -> None:
        codec = codec or [JSON_CODEC]
        rid = req.get("id")
        try:
            service_name, _, method_name = req["method"].partition(".")
            service = self._services.get(service_name)
            if service is None:
                raise RPCError(f"unknown service {service_name!r}")
            if method_name.startswith("_"):
                raise RPCError(f"method {method_name!r} is not exported")
            method = getattr(service, method_name, None)
            if method is None or not callable(method):
                raise RPCError(f"unknown method {req['method']!r}")
            # per-method handler latency: the distribution the ISSUE-3
            # telemetry plane exists for — a slow Mine is invisible in
            # counters alone.  Timed only once the method resolved, so
            # adversarial method strings cannot mint histogram families.
            t0 = time.monotonic()
            try:
                result = method(req.get("params") or {})
            finally:
                metrics.observe(
                    f"rpc.server.dispatch_s.{service_name}.{method_name}",
                    time.monotonic() - t0,
                )
            resp = {"id": rid, "result": result, "error": None}
        except Exception as exc:  # handler errors travel to the caller
            metrics.inc("rpc.handler_errors")
            resp = {"id": rid, "result": None, "error": f"{type(exc).__name__}: {exc}"}
            # typed backpressure: an exception carrying retry_after_s
            # (duck-typed — the runtime layer must not import sched)
            # ships the hint as a dedicated frame field so clients get
            # a machine-readable RETRY_AFTER, not a string to parse
            retry_after = getattr(exc, "retry_after_s", None)
            if retry_after is not None:
                try:
                    resp["retry_after"] = float(retry_after)
                except (TypeError, ValueError):
                    pass
            # typed NOT_OWNER redirect: an exception carrying a
            # ``ring_wire`` snapshot (duck-typed — this layer must not
            # import cluster, mirroring the retry_after discipline)
            # ships it as the response frame's dedicated ``ring``
            # field, so misrouted clients re-route in one round trip
            ring = getattr(exc, "ring_wire", None)
            if isinstance(ring, dict):
                resp["ring"] = ring
        if faults.PLAN is not None:
            hit = faults.PLAN.on_frame(
                "server", str(req.get("method") or ""), peer
            )
            if hit is not None:
                kind, delay = hit
                if kind == "delay":
                    time.sleep(delay)
                elif kind == "drop":
                    return  # response silently never sent
                elif kind == "duplicate":
                    try:
                        _write_frame(conn, resp, wlock, codec[0])
                        _write_frame(conn, resp, wlock, codec[0])
                    except OSError:
                        pass
                    return
                elif kind == "truncate":
                    # partial response, then reset: the peer's pending
                    # calls on this connection all fail fast
                    _write_truncated(conn, resp, wlock, codec[0])
                    for op in (lambda: conn.shutdown(socket.SHUT_RDWR),
                               conn.close):
                        try:
                            op()
                        except OSError:
                            pass
                    return
        try:
            _write_frame(conn, resp, wlock, codec[0])
        except OSError:
            pass

    def shutdown(self) -> None:
        self._shutdown.set()
        for ls in self._listeners:
            # close() alone does NOT interrupt a thread parked in
            # accept() on Linux — the listening description stays alive
            # and the port keeps accepting.  Wake the acceptor with a
            # throwaway connection first; it sees _shutdown and exits.
            try:
                host, port = ls.getsockname()[:2]
                if host == "0.0.0.0":
                    host = "127.0.0.1"
                elif host == "::":
                    # V6ONLY listener (create_server default): the wake
                    # connection must itself be IPv6
                    host = "::1"
                with socket.create_connection((host, port), timeout=0.5):
                    pass
            except OSError:
                pass
            try:
                ls.close()
            except OSError:
                pass
        # join the acceptors: a thread still inside accept() keeps the
        # listening description (and the PORT) alive past ls.close(), so
        # an immediate restart on the same address would hit EADDRINUSE
        for t in self._threads:
            t.join(timeout=2.0)
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            # SHUT_RDWR first: close() alone neither wakes this server's
            # own reader thread blocked in recv on the fd nor (therefore)
            # sends the FIN that tells peers the server is gone — clients
            # would never see their in-flight calls fail
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass


class RPCClient:
    """Connection to one RPC server: blocking ``call`` and async ``go``.

    The send path is BOUNDED (``send_timeout``): a peer that stops
    reading fills the TCP buffer and ``sendall`` would otherwise block
    forever while holding the write lock — wedging every other caller on
    this client, including the failure detector's probes, before their
    own future timeouts could apply (VERDICT r1 weak #4).  The bound is
    the kernel-level ``SO_SNDTIMEO`` — NOT ``settimeout``, which flips
    the shared fd to non-blocking and would poison the reader thread's
    blocking recv.  A send that trips the bound (or fails at all) tears
    the connection down rather than reusing it, because a partially
    written frame has corrupted the stream; pending callers all fail
    fast and can re-dial.
    """

    def __init__(self, addr: str, timeout: Optional[float] = 10.0,
                 send_timeout: float = 20.0, codec: Optional[str] = None):
        self._addr = addr
        self._dial_timeout = timeout
        self._send_timeout = send_timeout
        if faults.PLAN is not None:
            faults.PLAN.on_connect(addr)  # may delay or refuse the dial
        self._sock = self._dial()
        self._wlock = threading.Lock()
        self._pending: Dict[int, Future] = {}
        self._plock = threading.Lock()
        self._next_id = 0
        self._closed = False
        self._dead: Optional[RPCError] = None  # set by the reader on death
        #: extra keys a v2 server's hello ack carried beside ``codec``
        #: (the cluster ring advertisement — docs/CLUSTER.md); empty on
        #: JSON-pinned clients and against pre-extension servers
        self.hello_info: Dict[str, Any] = {}
        # wire codec (module docstring): negotiated synchronously BEFORE
        # the reader thread exists, so reader and senders always agree
        mode = codec or CLIENT_CODEC_DEFAULT
        if mode not in ("auto", "json", "binary"):
            raise ValueError(f"unknown rpc codec mode {mode!r}")
        self._codec = JSON_CODEC if mode == "json" else \
            self._negotiate_codec(mode)
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()

    def _dial(self) -> socket.socket:
        sock = socket.create_connection(split_addr(self._addr),
                                        timeout=self._dial_timeout)
        sock.settimeout(None)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        if self._send_timeout:
            sec = int(self._send_timeout)
            usec = int((self._send_timeout - sec) * 1e6)
            sock.setsockopt(
                socket.SOL_SOCKET, socket.SO_SNDTIMEO,
                struct.pack("ll", sec, usec),
            )
        return sock

    def _negotiate_codec(self, mode: str):
        """One v1 round trip: ``rpc.hello`` → ack means wire v2; a
        pre-v2 server's unknown-method error means stay on JSON.  A
        TIMED-OUT or garbled handshake tears this socket down and
        re-dials a fresh one with no hello: a slow v2 server may still
        ack (and flip ITS side to binary) after we give up, so reusing
        the socket could split-brain the codec — or leave a
        partially-read ack desynchronizing the length-prefixed stream —
        while the fresh hello-less connection is v1 on both sides by
        construction.  Connection-level failures propagate like any
        other dial failure.  ``mode == "binary"`` turns any fallback
        into an error instead."""
        hello = {"id": 0, "method": HELLO_METHOD,
                 "params": {"codec": wire.WIRE_VERSION}}
        resp = None
        redial = False
        try:
            self._sock.settimeout(HELLO_TIMEOUT_S)
            try:
                _write_frame(self._sock, hello, self._wlock, JSON_CODEC)
                resp = _read_frame(self._sock, JSON_CODEC)
            except (TimeoutError, socket.timeout):
                redial = True  # silent peer: see docstring
            except (ValueError, RPCError):
                redial = True  # garbled/oversized reply: same hazard
        finally:
            try:
                self._sock.settimeout(None)
            except OSError:
                pass
        ok = (isinstance(resp, dict) and isinstance(resp.get("result"), dict)
              and resp["result"].get("codec") == wire.WIRE_VERSION)
        if ok:
            metrics.inc("rpc.codec.negotiated_v2")
            self.hello_info = {k: v for k, v in resp["result"].items()
                               if k != "codec"}
            return BINARY_CODEC
        metrics.inc("rpc.codec.fallback_v1")
        if mode == "binary":
            try:
                self._sock.close()
            except OSError:
                pass
            raise RPCError(f"peer {self._addr} does not speak wire v2")
        if redial:
            # one logical dial: the fault plane's @connect hook already
            # ran for it, so the replacement socket is not re-hooked
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = self._dial()
        return JSON_CODEC

    @property
    def codec_name(self) -> str:
        """"json" (wire v1) or "binary" (wire v2) for this connection."""
        return self._codec.name

    def _read_loop(self) -> None:
        try:
            while True:
                resp = _read_frame(self._sock, self._codec)
                if not isinstance(resp, dict):
                    raise RPCError(f"non-object frame: {type(resp).__name__}")
                with self._plock:
                    fut = self._pending.pop(resp.get("id"), None)
                if fut is None:
                    continue
                if resp.get("error"):
                    # a malformed hint must NOT kill the reader thread
                    # (a TypeError here would skip the fail-all
                    # teardown below and strand every pending future):
                    # degrade to a plain RPCError instead
                    try:
                        retry_after = float(resp["retry_after"])
                    except (KeyError, TypeError, ValueError):
                        retry_after = None
                    ring = resp.get("ring")
                    if isinstance(ring, dict):
                        # NOT_OWNER redirect (cluster plane): the ring
                        # snapshot outranks a retry_after hint — a
                        # misrouted key must move, not wait
                        fut.set_exception(RPCNotOwner(
                            resp["error"], ring
                        ))
                    elif retry_after is not None:
                        fut.set_exception(RPCRetryAfter(
                            resp["error"], retry_after
                        ))
                    else:
                        fut.set_exception(RPCError(resp["error"]))
                else:
                    fut.set_result(resp.get("result"))
        except (ConnectionError, OSError, ValueError, RPCError) as exc:
            # same coverage as the server reader (review r4): an
            # oversized/undecodable/non-object response must FAIL the
            # pending futures, not strand them behind a dead reader
            err = exc if self._closed is False else ConnectionError("client closed")
            with self._plock:
                pending, self._pending = self._pending, {}
                # the dead flag and the swap share one critical
                # section: a concurrent go() either registered before
                # (its future is in `pending`, failed below) or
                # registers after (it sees _dead and fails fast) — no
                # window where a future lands in the fresh dict with no
                # reader to resolve it (review r4)
                self._dead = RPCTransportError(str(err))
            for fut in pending.values():
                if not fut.done():
                    fut.set_exception(RPCTransportError(str(err)))
            # and tear the CONNECTION down: on a protocol violation the
            # socket is still healthy, so without this a later go()/
            # call() would send fine and then wait forever on a reader
            # that no longer exists (review r4); closing makes the next
            # send fail fast like the ConnectionError path
            try:
                self._sock.close()
            except OSError:
                pass

    def go(self, method: str, params: Optional[dict] = None) -> Future:
        """Async call; resolves with the result (rpc.Client.Go role)."""
        fut: Future = Future()
        with self._plock:
            if self._dead is not None:
                # a FRESH instance per future: raising a shared
                # exception object from concurrent .result() callers
                # would interleave their __traceback__s (review r4)
                fut.set_exception(RPCTransportError(str(self._dead)))
                return fut
            self._next_id += 1
            rid = self._next_id
            self._pending[rid] = fut
        req = {"id": rid, "method": method, "params": params or {}}
        # round-trip latency per method, observed when the reader (or a
        # teardown path) RESOLVES the future — success and error alike.
        # A frame silently lost on a healthy connection (drop fault, or
        # a peer that just never answers) has no completion to time and
        # leaves no sample here; that outage surfaces in the caller-
        # level histograms instead (powlib.mine_s spans its retries)
        t0 = time.monotonic()
        fut.add_done_callback(
            lambda _f, _m=method, _t0=t0: metrics.observe(
                f"rpc.client.call_s.{_m}", time.monotonic() - _t0
            )
        )
        duplicate = False
        if faults.PLAN is not None:
            hit = faults.PLAN.on_frame("client", method, self._addr)
            if hit is not None:
                kind, delay = hit
                if kind == "delay":
                    time.sleep(delay)
                elif kind == "drop":
                    # silently never sent; the connection stays healthy,
                    # so only the caller's own timeout observes this
                    return fut
                elif kind == "duplicate":
                    duplicate = True
                elif kind == "truncate":
                    # partial frame + teardown: the reader fails every
                    # pending future (this one included) with a
                    # transport error, like a real mid-frame reset
                    _write_truncated(self._sock, req, self._wlock,
                                     self._codec)
                    self.close()
                    return fut
        try:
            _write_frame(self._sock, req, self._wlock, self._codec)
            if duplicate:
                _write_frame(self._sock, req, self._wlock, self._codec)
        except OSError as exc:
            with self._plock:
                self._pending.pop(rid, None)
            fut.set_exception(RPCTransportError(str(exc)))
            # a failed sendall may have written a PARTIAL frame (SNDTIMEO
            # surfaces as BlockingIOError mid-write); the stream is
            # unusable — tear it down so the reader fails every pending
            # future and callers re-dial
            self.close()
        return fut

    @property
    def dead(self) -> bool:
        """True once the transport is unusable (reader died or close()
        was called).  False means the connection is healthy as far as
        anyone can tell — a frame lost to a drop fault or an unanswered
        call does NOT flip this; callers deciding whether to re-dial vs
        re-issue on the same connection use exactly that distinction
        (nodes/powlib.py _reconnect)."""
        return self._dead is not None or self._closed

    def call(
        self, method: str, params: Optional[dict] = None, timeout: Optional[float] = None
    ) -> Any:
        """Blocking call (rpc.Client.Call role)."""
        return self.go(method, params).result(timeout=timeout)

    def close(self) -> None:
        self._closed = True
        try:
            # wake the reader thread if it is blocked in recv
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
