"""Framed-JSON TCP RPC — the framework's DCN-level communication backend.

Plays the role Go ``net/rpc`` plays in the reference (SURVEY.md section 2
component 11): blocking unary calls (rpc.Client.Call,
coordinator.go:195,226), async calls returning a completion handle
(rpc.Client.Go, powlib/powlib.go:156, cmd/worker/main.go:35), one server
servicing multiple listeners (the coordinator's segregated client/worker
listeners, coordinator.go:334-351), and concurrent dispatch of requests.

Wire format: 4-byte big-endian length prefix + UTF-8 JSON.
Request  ``{"id": n, "method": "Service.Method", "params": {...}}``
Response ``{"id": n, "result": ..., "error": null | str}``

Byte fields travel as arrays of ints (the natural JSON form of the
reference's ``[]uint8``); tracing tokens as base64 strings (see
runtime/tracing.py).  Within a TPU pod the hot path never touches this
transport — device fan-out rides ICI collectives (parallel/mesh_search.py);
this backend carries only control-plane traffic between hosts, as the
north-star design prescribes (BASELINE.json: "the coordinator/worker RPC
boundary stays intact").
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, Optional, Tuple

from . import faults
from .metrics import REGISTRY as metrics


class RPCError(Exception):
    pass


class RPCTransportError(RPCError):
    """A connection-level failure — refused dial, failed/partial send,
    reader death — as opposed to an error *returned by* the remote
    handler (which stays a plain :class:`RPCError`).  The distinction is
    what makes client-side retry safe: a transport failure means the
    peer may never have seen (or finished) the call, so re-issuing an
    idempotent RPC (``Mine`` — the dominance cache absorbs repeats) is
    correct, while a handler error would just be re-earned."""


class RPCRetryAfter(RPCError):
    """The remote handler REJECTED the call with server-paced
    backpressure (the scheduler's admission control,
    sched/admission.py): the ``retry_after`` field of the response
    frame says when to try again.  A third retry class beside the two
    above: unlike a handler error it IS worth re-issuing — the server
    itself asked for the retry — and unlike a transport failure the
    retry is paced by the server's hint and must not burn the client's
    transport-failure budget (nodes/powlib.py)."""

    def __init__(self, message: str, delay_s: float):
        super().__init__(message)
        self.delay_s = float(delay_s)


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        part = sock.recv(n - len(buf))
        if not part:
            raise ConnectionError("connection closed")
        buf += part
    return buf


def _read_frame(sock: socket.socket) -> dict:
    (length,) = struct.unpack(">I", _read_exact(sock, 4))
    if length > 64 * 1024 * 1024:
        raise RPCError(f"oversized frame: {length} bytes")
    metrics.observe("rpc.frame.recv_bytes", length)
    return json.loads(_read_exact(sock, length).decode())


def _write_frame(sock: socket.socket, obj: dict, lock: threading.Lock) -> None:
    payload = json.dumps(obj).encode()
    metrics.observe("rpc.frame.sent_bytes", len(payload))
    with lock:
        # distpow: ok no-blocking-under-lock -- this lock IS the frame
        # serializer: interleaved sendall from two threads would corrupt
        # the length-prefixed stream; the send is bounded by SO_SNDTIMEO
        sock.sendall(struct.pack(">I", len(payload)) + payload)


def _write_truncated(sock: socket.socket, obj: dict,
                     lock: threading.Lock) -> None:
    """Fault-plane helper (faults.py kind="truncate"): write a partial
    frame — length prefix plus roughly half the payload — so the peer's
    ``_read_exact`` sees a mid-frame connection reset when the caller
    tears the socket down right after."""
    payload = json.dumps(obj).encode()
    frame = struct.pack(">I", len(payload)) + payload
    try:
        with lock:
            # distpow: ok no-blocking-under-lock -- same frame-serializer
            # lock as _write_frame; the deliberately-torn fault frame must
            # not interleave with a concurrent healthy write either
            sock.sendall(frame[: max(5, len(frame) // 2)])
    except OSError:
        pass


def split_addr(addr: str) -> Tuple[str, int]:
    """Connect-side parse: a host-less ':port' targets the local host."""
    host, _, port = addr.rpartition(":")
    return host or "127.0.0.1", int(port)


def split_bind_addr(addr: str) -> Tuple[str, int]:
    """Listen-side parse: a host-less ':port' binds all interfaces, like
    Go's net.Listen — reference configs use bare ':port' addresses
    (config/coordinator_config.json) and must stay multi-host capable."""
    host, _, port = addr.rpartition(":")
    return host, int(port)


class RPCServer:
    """Multi-listener RPC server dispatching ``Service.Method`` requests.

    Each connection gets a reader thread; each request is dispatched on its
    own worker thread so slow handlers (the coordinator's blocking ``Mine``)
    never head-of-line-block other requests on the same connection —
    matching Go net/rpc's goroutine-per-request semantics.
    """

    def __init__(self):
        self._services: Dict[str, object] = {}
        self._listeners = []
        self._threads = []
        self._conns = set()
        self._lock = threading.Lock()
        self._shutdown = threading.Event()

    def register(self, name: str, handler: object) -> None:
        self._services[name] = handler

    def listen(self, addr: str) -> str:
        """Bind a listener; returns the bound address (resolves ':0')."""
        host, port = split_bind_addr(addr)
        ls = socket.create_server((host, port), reuse_port=False)
        self._listeners.append(ls)
        bound = ls.getsockname()
        return f"{host or '127.0.0.1'}:{bound[1]}"

    def serve_in_background(self) -> None:
        for ls in self._listeners:
            t = threading.Thread(target=self._accept_loop, args=(ls,), daemon=True)
            t.start()
            self._threads.append(t)

    def _accept_loop(self, ls: socket.socket) -> None:
        while not self._shutdown.is_set():
            try:
                conn, _ = ls.accept()
            except OSError:
                return
            if self._shutdown.is_set():
                # the wake-up connection from shutdown(), or a late dial
                try:
                    conn.close()
                except OSError:
                    pass
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                self._conns.add(conn)
            threading.Thread(
                target=self._conn_loop, args=(conn,), daemon=True
            ).start()

    def _conn_loop(self, conn: socket.socket) -> None:
        wlock = threading.Lock()
        try:
            peer = "%s:%s" % conn.getpeername()[:2]
        except OSError:
            peer = ""
        try:
            while True:
                req = _read_frame(conn)
                if not isinstance(req, dict):
                    # valid JSON, wrong shape (e.g. a bare number):
                    # drop the connection rather than crash the
                    # dispatch thread on req.get (adversarial-input
                    # hardening, round 4)
                    raise RPCError(f"non-object frame: {type(req).__name__}")
                threading.Thread(
                    target=self._dispatch,
                    args=(conn, wlock, req, peer),
                    daemon=True,
                ).start()
        except (ConnectionError, OSError, ValueError, RPCError):
            # ValueError covers json.JSONDecodeError AND the
            # UnicodeDecodeError a non-UTF-8 payload raises; RPCError
            # covers protocol violations from _read_frame (oversized
            # frame) and the shape check above — close the offending
            # connection quietly; other clients are unaffected
            pass
        finally:
            with self._lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, conn, wlock, req: dict, peer: str = "") -> None:
        rid = req.get("id")
        try:
            service_name, _, method_name = req["method"].partition(".")
            service = self._services.get(service_name)
            if service is None:
                raise RPCError(f"unknown service {service_name!r}")
            if method_name.startswith("_"):
                raise RPCError(f"method {method_name!r} is not exported")
            method = getattr(service, method_name, None)
            if method is None or not callable(method):
                raise RPCError(f"unknown method {req['method']!r}")
            # per-method handler latency: the distribution the ISSUE-3
            # telemetry plane exists for — a slow Mine is invisible in
            # counters alone.  Timed only once the method resolved, so
            # adversarial method strings cannot mint histogram families.
            t0 = time.monotonic()
            try:
                result = method(req.get("params") or {})
            finally:
                metrics.observe(
                    f"rpc.server.dispatch_s.{service_name}.{method_name}",
                    time.monotonic() - t0,
                )
            resp = {"id": rid, "result": result, "error": None}
        except Exception as exc:  # handler errors travel to the caller
            metrics.inc("rpc.handler_errors")
            resp = {"id": rid, "result": None, "error": f"{type(exc).__name__}: {exc}"}
            # typed backpressure: an exception carrying retry_after_s
            # (duck-typed — the runtime layer must not import sched)
            # ships the hint as a dedicated frame field so clients get
            # a machine-readable RETRY_AFTER, not a string to parse
            retry_after = getattr(exc, "retry_after_s", None)
            if retry_after is not None:
                try:
                    resp["retry_after"] = float(retry_after)
                except (TypeError, ValueError):
                    pass
        if faults.PLAN is not None:
            hit = faults.PLAN.on_frame(
                "server", str(req.get("method") or ""), peer
            )
            if hit is not None:
                kind, delay = hit
                if kind == "delay":
                    time.sleep(delay)
                elif kind == "drop":
                    return  # response silently never sent
                elif kind == "duplicate":
                    try:
                        _write_frame(conn, resp, wlock)
                        _write_frame(conn, resp, wlock)
                    except OSError:
                        pass
                    return
                elif kind == "truncate":
                    # partial response, then reset: the peer's pending
                    # calls on this connection all fail fast
                    _write_truncated(conn, resp, wlock)
                    for op in (lambda: conn.shutdown(socket.SHUT_RDWR),
                               conn.close):
                        try:
                            op()
                        except OSError:
                            pass
                    return
        try:
            _write_frame(conn, resp, wlock)
        except OSError:
            pass

    def shutdown(self) -> None:
        self._shutdown.set()
        for ls in self._listeners:
            # close() alone does NOT interrupt a thread parked in
            # accept() on Linux — the listening description stays alive
            # and the port keeps accepting.  Wake the acceptor with a
            # throwaway connection first; it sees _shutdown and exits.
            try:
                host, port = ls.getsockname()[:2]
                if host == "0.0.0.0":
                    host = "127.0.0.1"
                elif host == "::":
                    # V6ONLY listener (create_server default): the wake
                    # connection must itself be IPv6
                    host = "::1"
                with socket.create_connection((host, port), timeout=0.5):
                    pass
            except OSError:
                pass
            try:
                ls.close()
            except OSError:
                pass
        # join the acceptors: a thread still inside accept() keeps the
        # listening description (and the PORT) alive past ls.close(), so
        # an immediate restart on the same address would hit EADDRINUSE
        for t in self._threads:
            t.join(timeout=2.0)
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            # SHUT_RDWR first: close() alone neither wakes this server's
            # own reader thread blocked in recv on the fd nor (therefore)
            # sends the FIN that tells peers the server is gone — clients
            # would never see their in-flight calls fail
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass


class RPCClient:
    """Connection to one RPC server: blocking ``call`` and async ``go``.

    The send path is BOUNDED (``send_timeout``): a peer that stops
    reading fills the TCP buffer and ``sendall`` would otherwise block
    forever while holding the write lock — wedging every other caller on
    this client, including the failure detector's probes, before their
    own future timeouts could apply (VERDICT r1 weak #4).  The bound is
    the kernel-level ``SO_SNDTIMEO`` — NOT ``settimeout``, which flips
    the shared fd to non-blocking and would poison the reader thread's
    blocking recv.  A send that trips the bound (or fails at all) tears
    the connection down rather than reusing it, because a partially
    written frame has corrupted the stream; pending callers all fail
    fast and can re-dial.
    """

    def __init__(self, addr: str, timeout: Optional[float] = 10.0,
                 send_timeout: float = 20.0):
        self._addr = addr
        if faults.PLAN is not None:
            faults.PLAN.on_connect(addr)  # may delay or refuse the dial
        self._sock = socket.create_connection(split_addr(addr), timeout=timeout)
        self._sock.settimeout(None)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        if send_timeout:
            sec = int(send_timeout)
            usec = int((send_timeout - sec) * 1e6)
            self._sock.setsockopt(
                socket.SOL_SOCKET, socket.SO_SNDTIMEO,
                struct.pack("ll", sec, usec),
            )
        self._wlock = threading.Lock()
        self._pending: Dict[int, Future] = {}
        self._plock = threading.Lock()
        self._next_id = 0
        self._closed = False
        self._dead: Optional[RPCError] = None  # set by the reader on death
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()

    def _read_loop(self) -> None:
        try:
            while True:
                resp = _read_frame(self._sock)
                if not isinstance(resp, dict):
                    raise RPCError(f"non-object frame: {type(resp).__name__}")
                with self._plock:
                    fut = self._pending.pop(resp.get("id"), None)
                if fut is None:
                    continue
                if resp.get("error"):
                    # a malformed hint must NOT kill the reader thread
                    # (a TypeError here would skip the fail-all
                    # teardown below and strand every pending future):
                    # degrade to a plain RPCError instead
                    try:
                        retry_after = float(resp["retry_after"])
                    except (KeyError, TypeError, ValueError):
                        retry_after = None
                    if retry_after is not None:
                        fut.set_exception(RPCRetryAfter(
                            resp["error"], retry_after
                        ))
                    else:
                        fut.set_exception(RPCError(resp["error"]))
                else:
                    fut.set_result(resp.get("result"))
        except (ConnectionError, OSError, ValueError, RPCError) as exc:
            # same coverage as the server reader (review r4): an
            # oversized/undecodable/non-object response must FAIL the
            # pending futures, not strand them behind a dead reader
            err = exc if self._closed is False else ConnectionError("client closed")
            with self._plock:
                pending, self._pending = self._pending, {}
                # the dead flag and the swap share one critical
                # section: a concurrent go() either registered before
                # (its future is in `pending`, failed below) or
                # registers after (it sees _dead and fails fast) — no
                # window where a future lands in the fresh dict with no
                # reader to resolve it (review r4)
                self._dead = RPCTransportError(str(err))
            for fut in pending.values():
                if not fut.done():
                    fut.set_exception(RPCTransportError(str(err)))
            # and tear the CONNECTION down: on a protocol violation the
            # socket is still healthy, so without this a later go()/
            # call() would send fine and then wait forever on a reader
            # that no longer exists (review r4); closing makes the next
            # send fail fast like the ConnectionError path
            try:
                self._sock.close()
            except OSError:
                pass

    def go(self, method: str, params: Optional[dict] = None) -> Future:
        """Async call; resolves with the result (rpc.Client.Go role)."""
        fut: Future = Future()
        with self._plock:
            if self._dead is not None:
                # a FRESH instance per future: raising a shared
                # exception object from concurrent .result() callers
                # would interleave their __traceback__s (review r4)
                fut.set_exception(RPCTransportError(str(self._dead)))
                return fut
            self._next_id += 1
            rid = self._next_id
            self._pending[rid] = fut
        req = {"id": rid, "method": method, "params": params or {}}
        # round-trip latency per method, observed when the reader (or a
        # teardown path) RESOLVES the future — success and error alike.
        # A frame silently lost on a healthy connection (drop fault, or
        # a peer that just never answers) has no completion to time and
        # leaves no sample here; that outage surfaces in the caller-
        # level histograms instead (powlib.mine_s spans its retries)
        t0 = time.monotonic()
        fut.add_done_callback(
            lambda _f, _m=method, _t0=t0: metrics.observe(
                f"rpc.client.call_s.{_m}", time.monotonic() - _t0
            )
        )
        duplicate = False
        if faults.PLAN is not None:
            hit = faults.PLAN.on_frame("client", method, self._addr)
            if hit is not None:
                kind, delay = hit
                if kind == "delay":
                    time.sleep(delay)
                elif kind == "drop":
                    # silently never sent; the connection stays healthy,
                    # so only the caller's own timeout observes this
                    return fut
                elif kind == "duplicate":
                    duplicate = True
                elif kind == "truncate":
                    # partial frame + teardown: the reader fails every
                    # pending future (this one included) with a
                    # transport error, like a real mid-frame reset
                    _write_truncated(self._sock, req, self._wlock)
                    self.close()
                    return fut
        try:
            _write_frame(self._sock, req, self._wlock)
            if duplicate:
                _write_frame(self._sock, req, self._wlock)
        except OSError as exc:
            with self._plock:
                self._pending.pop(rid, None)
            fut.set_exception(RPCTransportError(str(exc)))
            # a failed sendall may have written a PARTIAL frame (SNDTIMEO
            # surfaces as BlockingIOError mid-write); the stream is
            # unusable — tear it down so the reader fails every pending
            # future and callers re-dial
            self.close()
        return fut

    @property
    def dead(self) -> bool:
        """True once the transport is unusable (reader died or close()
        was called).  False means the connection is healthy as far as
        anyone can tell — a frame lost to a drop fault or an unanswered
        call does NOT flip this; callers deciding whether to re-dial vs
        re-issue on the same connection use exactly that distinction
        (nodes/powlib.py _reconnect)."""
        return self._dead is not None or self._closed

    def call(
        self, method: str, params: Optional[dict] = None, timeout: Optional[float] = None
    ) -> Any:
        """Blocking call (rpc.Client.Call role)."""
        return self.go(method, params).result(timeout=timeout)

    def close(self) -> None:
        self._closed = True
        try:
            # wake the reader thread if it is blocked in recv
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
