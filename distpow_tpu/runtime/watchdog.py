"""Device-hang watchdog: turn a wedged accelerator into a clean worker death.

The tunneled-TPU failure mode observed in practice (BASELINE.md, round-3
measurement provenance) is a device dispatch that never returns: the
in-flight result fetch blocks forever in an uninterruptible C call, and
the worker becomes a zombie — its RPC threads still answer liveness
probes (``Ping``), so the coordinator's ``FailurePolicy: "reassign"``
(nodes/coordinator.py) never triggers, and the Mine task simply never
completes.  The Go reference has no analogue (``md5.Sum`` cannot hang,
worker.go:353), so this subsystem is config-gated and OFF by default
(reference parity).

Mechanism: compute paths that drive the device wrap themselves in
``WATCHDOG.active()`` and call ``WATCHDOG.beat()`` at every host-side
sync point — between launches in the search driver
(parallel/search.py), between compile-and-dispatch steps in boot warmup
(backends._warm_factory).  A daemon monitor thread fires when an
*active* section goes ``timeout`` seconds without a beat.  Python
cannot cancel the hung call, so the default action is ``os._exit``
with a distinctive code: dying visibly is the one move that converts
an undetectable zombie into an RPC failure the coordinator's
reassignment path already handles.  A process supervisor restarting
the worker completes the recovery loop.

Sizing the timeout: it must exceed the worst-case single legitimate
gap between beats — one XLA/Mosaic compile (20-60 s cold; warmup and
serving beat once per compiled program, not once per warmup pass) —
NOT one launch (~0.1-0.2 s).  300 s is a conservative floor; the
config comment on ``WorkerConfig.DeviceHangTimeoutS`` repeats this.

Beats cost two attribute reads and a ``time.monotonic()`` call and are
no-ops while the watchdog is not started, so the instrumented paths pay
nothing in the default configuration.
"""

from __future__ import annotations

import logging
import os
import threading
from contextlib import contextmanager
from time import monotonic
from typing import Callable, Optional

log = logging.getLogger("distpow.watchdog")

# Distinctive exit code so supervisors / tests can tell a watchdog death
# from a crash.  (Avoids the 128+signal range and small shell codes.)
EXIT_CODE = 43

# Grace window for ONE first compile+dispatch of a program (see
# ``DeviceWatchdog.grace``).  Sized to the largest compile measured on
# the tunneled TPU: sha512's fully-unrolled 64-bit limb-emulation
# serving step, observed >22 min server-side (r4 hardware session —
# scripts/probe_sha512_forms.py); every other model compiles in
# 20-60 s.  A device that hangs during a first compile is still
# detected, just after this window.
FIRST_COMPILE_GRACE_S = 1800.0


class DeviceWatchdog:
    """Monitor for device-driving sections that stop making progress.

    One instance (the module-level ``WATCHDOG``) is shared process-wide:
    a worker owns one device, so if any dispatch hangs, every search on
    the device is stuck — a single staleness clock is the right model.
    The corollary (documented limitation): beats from a *live* search
    can mask a hung one in the same process; detection then happens as
    soon as the live search drains.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._active = 0
        self._last_beat = 0.0
        self._timeout = 0.0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._on_hang: Optional[Callable[[float], None]] = None
        self._arm_lock = threading.Lock()  # serializes acquire/release
        self._refs = 0  # acquire/release co-owners
        self._graces: list[float] = []  # active grace windows (multiset)
        self.fired = threading.Event()

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self, timeout_s: float,
              on_hang: Optional[Callable[[float], None]] = None) -> None:
        """Start the monitor.  ``on_hang(stale_seconds)`` overrides the
        default die-by-``os._exit(EXIT_CODE)`` action (tests use this)."""
        if timeout_s <= 0:
            raise ValueError("watchdog timeout must be positive")
        with self._lock:
            if self.running:
                raise RuntimeError("watchdog already running")
            self._timeout = float(timeout_s)
            self._on_hang = on_hang
            self._last_beat = monotonic()
            self._stop.clear()
            self.fired.clear()
            self._thread = threading.Thread(
                target=self._monitor, name="device-watchdog", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        with self._lock:
            self._thread = None
            # _active is deliberately NOT reset: sections still inside
            # active() will run their paired decrements when they
            # unwind; zeroing here would drive the counter negative and
            # permanently blind a re-armed watchdog

    def acquire(self, timeout_s: float) -> None:
        """Refcounted arming for co-owners (one per in-process worker):
        the first acquire starts the monitor, later ones share it (the
        first timeout wins — one device, one staleness clock), and the
        matching ``release`` of the last owner stops it."""
        with self._arm_lock:
            self._refs += 1
            if not self.running:
                self.start(timeout_s)
                log.info("device-hang watchdog armed (timeout %gs)",
                         timeout_s)
            elif self._timeout != timeout_s:
                log.warning(
                    "device-hang watchdog already armed at %gs; ignoring "
                    "requested timeout %gs (one clock per process)",
                    self._timeout, timeout_s,
                )

    def release(self) -> None:
        with self._arm_lock:
            self._refs = max(0, self._refs - 1)
            if self._refs == 0:
                self.stop()

    def beat(self) -> None:
        if self._thread is None:
            return
        # distpow: ok unguarded-shared-write -- lock-free by documented
        # design (class docstring): beat() sits on the per-launch hot
        # path, the store of a monotonic float is atomic under the GIL,
        # and the staleness window tolerates one torn/lost beat
        self._last_beat = monotonic()

    @contextmanager
    def active(self):
        """Mark the enclosing block as device-driving.  Nestable and
        concurrency-safe (a counter, not a flag).

        Counts unconditionally — NOT only while the monitor runs — so a
        section already in flight when a later ``start()``/``acquire()``
        arms the watchdog is covered for the rest of its duration
        (advisor r3: the old early-return left such sections permanently
        invisible).  ``start()`` re-seeds ``_last_beat``, so arming over
        an already-hung section fires one full timeout later; beats stay
        no-ops while stopped, and the per-section lock cost is paid once
        per search, not per beat."""
        with self._lock:
            self._active += 1
            self._last_beat = monotonic()
        try:
            yield
        finally:
            with self._lock:
                self._active -= 1

    @contextmanager
    def grace(self, seconds: float):
        """Widen the no-progress window for ONE known-long operation.

        A single XLA compile cannot beat — it is one uninterruptible
        host call — and the largest graphs (sha512's 64-bit limb
        emulation) have been observed to out-wait the 420 s bench
        timeout on the tunneled backend, converting a healthy device
        into a false ``on_hang`` (BENCH r4 first attempt, 2026-07-31).
        Inside a ``grace(s)`` block the effective timeout is
        ``max(timeout, s)``; a genuinely hung tunnel is still detected,
        just ``s`` seconds later, and only for the annotated operation.
        Nestable and thread-safe: active windows form a multiset and
        the widest CURRENTLY-active one wins, so an inner ``grace(900)``
        stops widening the window the moment it exits (review r4: a
        depth-counter version leaked the inner window into the rest of
        the outer block).  Exit re-seeds the beat clock so the normal
        window restarts cleanly.
        """
        s = float(seconds)
        with self._lock:
            self._graces.append(s)
            self._last_beat = monotonic()
        try:
            yield
        finally:
            with self._lock:
                self._graces.remove(s)
                self._last_beat = monotonic()

    def _monitor(self) -> None:
        poll = min(1.0, self._timeout / 4)
        while not self._stop.wait(poll):
            if self._active <= 0:
                # idle: nothing is driving the device; keep the clock
                # fresh so the first beat of the next section starts a
                # clean window
                # distpow: ok unguarded-shared-write -- monitor-thread
                # refresh of the same GIL-atomic monotonic store as
                # beat(); racing a concurrent beat() only makes the
                # clock fresher, never staler
                self._last_beat = monotonic()
                continue
            # snapshot beat + grace state atomically: reading the beat
            # first and the grace list second races a grace() exit in
            # between (stale computed against the wide window's old
            # beat, limit against the restored narrow one -> false
            # fire on a healthy device, review r4)
            with self._lock:
                stale = monotonic() - self._last_beat
                limit = self._timeout
                if self._graces:
                    limit = max(limit, max(self._graces))
            if stale > limit:
                log.critical(
                    "device watchdog: %d active device section(s) made no "
                    "progress for %.1fs (timeout %.1fs) — the accelerator "
                    "dispatch is presumed hung; exiting so the coordinator "
                    "can reassign this worker's shards",
                    self._active, stale, limit,
                )
                # dump-on-fault: capture the flight-recorder ring and a
                # metrics snapshot BEFORE any exit path — the hang
                # narrative must not depend on someone tailing a log
                # (runtime/telemetry.py; no-op when no dump dir is
                # configured).  Local import: telemetry is imported for
                # the fault path only, so the beat hot path and the
                # stdlib-only importers of this module pay nothing.
                from .telemetry import RECORDER

                RECORDER.record("watchdog.hang", stale_s=round(stale, 3),
                                limit_s=limit, active=self._active)
                RECORDER.dump("device-hang")
                if self._on_hang is not None:
                    # callback first, THEN the observable event: waiters
                    # on ``fired`` may assert on the callback's effects
                    self._on_hang(stale)
                    self.fired.set()
                    return
                self.fired.set()
                # Flush logs before the hard exit (os._exit skips
                # atexit/finally by design: the process state is wedged).
                logging.shutdown()
                os._exit(EXIT_CODE)


WATCHDOG = DeviceWatchdog()
