from . import actions
from .cache import CacheEntry, ResultCache
from .config import (
    ClientConfig,
    CoordinatorConfig,
    TracingServerConfig,
    WorkerConfig,
    read_json_config,
    write_json_config,
)
from . import faults
from . import lockcheck
from .rpc import RPCClient, RPCError, RPCServer, RPCTransportError
from .trace_server import TracingServer
from .tracing import (
    FileSink,
    MemorySink,
    TCPSink,
    Trace,
    Tracer,
    decode_token,
    encode_token,
    make_tracer,
    wire_token,
)

__all__ = [
    "actions", "faults", "lockcheck", "CacheEntry", "ResultCache",
    "ClientConfig", "CoordinatorConfig", "TracingServerConfig", "WorkerConfig",
    "read_json_config", "write_json_config",
    "RPCClient", "RPCError", "RPCServer", "RPCTransportError", "TracingServer",
    "FileSink", "MemorySink", "TCPSink", "Trace", "Tracer",
    "decode_token", "encode_token", "make_tracer", "wire_token",
]
