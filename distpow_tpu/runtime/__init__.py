from . import actions
from .cache import CacheEntry, ResultCache
from .config import (
    ClientConfig,
    CoordinatorConfig,
    TracingServerConfig,
    WorkerConfig,
    read_json_config,
    write_json_config,
)
from .rpc import RPCClient, RPCError, RPCServer
from .trace_server import TracingServer
from .tracing import (
    FileSink,
    MemorySink,
    TCPSink,
    Trace,
    Tracer,
    decode_token,
    encode_token,
    make_tracer,
)

__all__ = [
    "actions", "CacheEntry", "ResultCache",
    "ClientConfig", "CoordinatorConfig", "TracingServerConfig", "WorkerConfig",
    "read_json_config", "write_json_config",
    "RPCClient", "RPCError", "RPCServer", "TracingServer",
    "FileSink", "MemorySink", "TCPSink", "Trace", "Tracer",
    "decode_token", "encode_token", "make_tracer",
]
