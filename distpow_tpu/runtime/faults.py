"""Deterministic network fault-injection plane for the RPC runtime.

The reference system's resilience story (first-result-wins cancellation,
worker reassignment) was only ever chaos-tested with one fault: SIGKILL
a worker process (tests/test_stress.py).  Real networks produce a richer
menagerie — refused connections, delayed/duplicated/truncated/dropped
frames — and production-scale serving (ROADMAP north star) has to ride
all of them out.  This module injects exactly those faults at the two
chokepoints every byte of control-plane traffic passes through
(``runtime/rpc.py``: the client's frame send and the server's response
send), **deterministically**, so a chaos run that finds a bug is a
repro, not an anecdote.

Usage — a plan is a seed plus an ordered rule list::

    {"seed": 1234, "rules": [
      {"kind": "delay",    "method": "WorkerRPCHandler.*", "side": "client",
       "prob": 0.3, "delay_s": 0.05},
      {"kind": "truncate", "method": "CoordRPCHandler.Mine", "calls": "0:2"},
      {"kind": "refuse",   "peer": "*:20001", "max": 1}
    ]}

Installed process-globally via :func:`install` (tests), the
``DISTPOW_FAULTS`` environment variable (inline JSON or a file path),
the per-node ``FaultPlanFile`` config field, or the ``--faults`` CLI
flag.  When no plan is installed the production RPC paths pay exactly
one ``PLAN is None`` branch per frame.

Fault kinds and their injection sites:

* ``refuse``    — dial time (``RPCClient`` connect): the connection is
  refused before any byte moves.  ``method`` is matched against the
  pseudo-method ``"@connect"`` (so the default ``"*"`` matches).
* ``delay``     — sleep ``delay_s`` (or a seeded pick from
  ``delay_range``) before the frame is written; also applies at dial
  time.
* ``truncate``  — write a partial frame, then tear the connection down:
  the peer observes a mid-frame reset and every pending call on the
  connection fails with a transport error.
* ``duplicate`` — write the frame twice.  A duplicated request is
  dispatched twice by the server (exercising handler idempotence); a
  duplicated response is dropped by the client's id-keyed reader.
* ``drop``      — silently never write the frame.  The connection stays
  healthy, so only caller-side timeouts (the coordinator's bounded
  reassign-mode calls, powlib's ``MineAttemptTimeoutS``) can observe it.

Determinism contract: every decision is a pure function of
``(seed, rule_index, k)`` where ``k`` is the index of the call among
those MATCHING that rule (rules are evaluated in order; the first rule
that fires consumes the frame).  The PRNG is a hash, not a shared
stream, so concurrent callers cannot steal each other's draws — the
same seed replays the same fault for the k-th matching call no matter
how threads interleave.  (The *global* interleaving of injections
across different rules is only reproducible when the traffic itself is
sequential, as the determinism tests arrange.)

Observability: every injection increments ``faults.injected.<kind>``
(runtime/metrics.py, shipped by the Stats RPC) and appends a tuple to
``FaultPlan.injected`` for test assertions.  See docs/FAULTS.md.

Wire codecs: the per-frame hooks operate on the ENCODED frame, so every
kind behaves identically on wire v1 (JSON) and wire v2 (binary,
runtime/wire.py) — a truncated binary frame is a mid-frame reset, a
duplicated one re-dispatches, exactly as on JSON (tests/test_wire.py
chaos-on-binary).  The ``rpc.hello`` negotiation exchange itself is NOT
passed through ``on_frame`` — dial-window faults are modeled by the
``@connect`` pseudo-method, and a faulted hello would only ever degrade
to the JSON floor anyway (docs/RPC.md).
"""

from __future__ import annotations

import fnmatch
import hashlib
import json
import logging
import os
import threading
import time
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

from .metrics import REGISTRY as metrics
from .telemetry import RECORDER

log = logging.getLogger("distpow.faults")

KINDS = ("refuse", "delay", "truncate", "duplicate", "drop")

#: pseudo-method rules are matched against at dial time
CONNECT = "@connect"


@dataclass
class FaultRule:
    """One match-and-inject rule; see the module docstring grammar."""

    kind: str
    method: str = "*"          # fnmatch glob over "Service.Method"
    side: str = "*"            # "client" | "server" | "*"
    peer: str = "*"            # fnmatch glob over "host:port"
    prob: float = 1.0          # injection probability per matching call
    calls: object = None       # None | "lo:hi" half-open | [indexes]
    max: Optional[int] = None  # cap on total injections by this rule
    delay_s: float = 0.05      # fixed delay (kind == "delay")
    delay_range: Optional[Sequence[float]] = None  # seeded uniform pick

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; "
                             f"expected one of {KINDS}")
        if self.side not in ("client", "server", "*"):
            raise ValueError(f"unknown side {self.side!r}")
        if not 0.0 <= float(self.prob) <= 1.0:
            raise ValueError(f"prob {self.prob!r} outside [0, 1]")

    def matches(self, side: str, method: str, peer: str) -> bool:
        return (
            (self.side == "*" or self.side == side)
            and fnmatch.fnmatchcase(method, self.method)
            and fnmatch.fnmatchcase(peer or "", self.peer)
        )

    def in_window(self, idx: int) -> bool:
        c = self.calls
        if c is None:
            return True
        if isinstance(c, str):
            lo, _, hi = c.partition(":")
            return int(lo or 0) <= idx and (not hi or idx < int(hi))
        return idx in c


class FaultPlan:
    """A seeded, ordered rule list consulted by the RPC runtime hooks."""

    def __init__(self, seed: int = 0, rules: Sequence = ()):
        self.seed = int(seed)
        self.rules = [
            r if isinstance(r, FaultRule) else FaultRule(**r) for r in rules
        ]
        self._counts = [0] * len(self.rules)  # matching calls seen, per rule
        self._fired = [0] * len(self.rules)   # injections done, per rule
        #: (rule_index, kind, side, method, matching_call_index) per
        #: injection, in injection order — the chaos tests' repro log
        self.injected: List[Tuple[int, str, str, str, int]] = []
        self._lock = threading.Lock()

    @classmethod
    def from_spec(cls, spec) -> "FaultPlan":
        """Build from a dict, inline-JSON string, or JSON file path."""
        if isinstance(spec, str):
            s = spec.strip()
            if s.startswith("{"):
                spec = json.loads(s)
            else:
                with open(s) as fh:
                    spec = json.load(fh)
        return cls(seed=spec.get("seed", 0), rules=spec.get("rules", ()))

    # -- seeded decisions ---------------------------------------------------
    def _unit(self, rule_idx: int, call_idx: int, salt: str = "") -> float:
        """Uniform [0, 1) as a pure function of (seed, rule, call)."""
        h = hashlib.sha256(
            f"{self.seed}:{rule_idx}:{call_idx}:{salt}".encode()
        ).digest()
        return int.from_bytes(h[:8], "big") / 2.0**64

    def _delay_of(self, rule: FaultRule, rule_idx: int, call_idx: int) -> float:
        if rule.delay_range:
            lo, hi = rule.delay_range
            return lo + (hi - lo) * self._unit(rule_idx, call_idx, "delay")
        return rule.delay_s

    def _decide(self, kinds, side: str, method: str,
                peer: str) -> Optional[Tuple[str, float]]:
        with self._lock:
            for ri, rule in enumerate(self.rules):
                if rule.kind not in kinds or not rule.matches(side, method, peer):
                    continue
                idx = self._counts[ri]
                self._counts[ri] += 1
                if not rule.in_window(idx):
                    continue
                if rule.max is not None and self._fired[ri] >= rule.max:
                    continue
                if rule.prob < 1.0 and self._unit(ri, idx) >= rule.prob:
                    continue
                self._fired[ri] += 1
                self.injected.append((ri, rule.kind, side, method, idx))
                metrics.inc(f"faults.injected.{rule.kind}")
                # the flight recorder is the chaos run's evidence trail:
                # a post-mortem dump carries exactly which faults hit
                # which frames, in order (runtime/telemetry.py)
                RECORDER.record("fault.injected", fault=rule.kind,
                                side=side, method=method, peer=peer,
                                rule=ri, call=idx)
                log.info("fault injected: %s %s %s peer=%s (rule %d, call %d)",
                         rule.kind, side, method, peer, ri, idx)
                return rule.kind, self._delay_of(rule, ri, idx)
        return None

    # -- runtime hooks (rpc.py) ---------------------------------------------
    def on_connect(self, peer: str) -> None:
        """Dial-time hook: may sleep (delay) or raise (refuse)."""
        hit = self._decide(("refuse", "delay"), "client", CONNECT, peer)
        if hit is None:
            return
        kind, delay = hit
        if kind == "delay":
            time.sleep(delay)
            return
        raise ConnectionRefusedError(
            f"fault injected: connection to {peer} refused"
        )

    def on_frame(self, side: str, method: str,
                 peer: str) -> Optional[Tuple[str, float]]:
        """Per-frame hook: returns ``(kind, delay)`` or None.  The caller
        (rpc.py) implements the frame-level mechanics for each kind."""
        return self._decide(
            ("delay", "truncate", "duplicate", "drop"), side, method, peer
        )


#: the process-global plan; None (production default) keeps the RPC hot
#: paths to a single branch
PLAN: Optional[FaultPlan] = None


def install(plan: Optional[FaultPlan]) -> Optional[FaultPlan]:
    global PLAN
    PLAN = plan
    if plan is not None:
        log.warning("fault-injection plan installed: seed=%d, %d rules",
                    plan.seed, len(plan.rules))
    return plan


def uninstall() -> None:
    install(None)


def install_from_spec(spec) -> FaultPlan:
    """Install a plan from a dict, inline JSON, or JSON file path."""
    return install(FaultPlan.from_spec(spec))


def _env_install() -> None:
    spec = os.environ.get("DISTPOW_FAULTS")
    if not spec:
        return
    try:
        install_from_spec(spec)
    except Exception as exc:  # a bad plan must not take the process down
        log.error("ignoring unusable DISTPOW_FAULTS plan: %s", exc)


_env_install()
