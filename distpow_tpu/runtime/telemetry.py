"""Flight recorder — a bounded in-memory ring of recent annotated
events, journaled periodically and dumped whole on faults.

The gap this closes (ISSUE 3 / VERDICT r5 weak #2): every outage and
chaos narrative so far rested on hand-kept transcripts, because the
moment something went wrong the only in-process evidence was whatever
happened to be in a log file.  The recorder keeps the last
``capacity`` annotated events (fault injections, watchdog verdicts,
protocol round milestones, reconnects) in memory at all times, and:

* **journals** them periodically as append-only JSONL (one event per
  line, monotonically increasing ``seq``), so a node that dies leaves
  its recent history on disk at at most one flush interval of loss;
* **dumps** everything — ring contents plus a full metrics snapshot —
  to a single JSON file the moment a fault hook fires
  (``runtime/watchdog.py`` on a device hang; chaos harnesses call
  :meth:`FlightRecorder.dump` directly), so the evidence is captured
  by construction, not by whoever was watching the terminal.

One process-global :data:`RECORDER` mirrors the metrics ``REGISTRY``
pattern: in-process multi-node tests share it, which is exactly what
the shared-registry Stats assertions already rely on.  Recording is a
deque append under a lock — cheap enough for every seam that already
pays a metrics increment.  With no journal/dump directory configured
(the production default) the recorder is memory-only and nothing
touches disk.

Configuration: :func:`configure` (nodes call it when their config sets
``TelemetryDir``), or the ``DISTPOW_TELEMETRY_DIR`` environment
variable (mirrors ``DISTPOW_FAULTS``).  docs/METRICS.md documents the
journal and dump formats.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from typing import List, Optional

from .metrics import REGISTRY as metrics

log = logging.getLogger("distpow.telemetry")

DEFAULT_CAPACITY = 2048
DEFAULT_JOURNAL_INTERVAL_S = 5.0
# Journal rotation (ISSUE 14 satellite): the append-only JSONL journal
# grows without bound under soak load — once the live file exceeds the
# byte cap it is rotated to ``<path>.1`` (older segments shift to .2,
# .3, ...) and segments beyond the keep count are deleted, so total
# disk is bounded at ~(keep + 1) x max_bytes while recent history
# stays greppable in order.
DEFAULT_JOURNAL_MAX_BYTES = 8 * 1024 * 1024
DEFAULT_JOURNAL_KEEP = 3


def rotate_if_over(path: str, max_bytes: int, keep: int) -> bool:
    """Size-capped JSONL rotation shared by every append-only spool the
    repo writes (flight-recorder journal here; the time-series spool in
    obs/timeseries.py): once the live file at ``path`` reaches
    ``max_bytes``, shift ``path.(i)`` -> ``path.(i+1)`` (dropping
    segments beyond ``keep``) and the live file to ``path.1``, bounding
    total disk at ~(keep + 1) x max_bytes.  Returns True when a
    rotation happened.  Best-effort: a failed rename costs rotation,
    never the caller's appends.  Callers serialize against their own
    appends (renames are bounded local metadata operations — the
    FileSink discipline)."""
    if max_bytes <= 0:
        return False
    try:
        if os.path.getsize(path) < max_bytes:
            return False
        keep = max(0, int(keep))
        oldest = f"{path}.{keep}"
        if keep == 0:
            os.remove(path)
            return True
        if os.path.exists(oldest):
            os.remove(oldest)
        for i in range(keep - 1, 0, -1):
            seg = f"{path}.{i}"
            if os.path.exists(seg):
                os.replace(seg, f"{path}.{i + 1}")
        os.replace(path, f"{path}.1")
        return True
    except OSError as exc:
        log.warning("journal rotation failed for %s: %s", path, exc)
        return False


def iter_rotated_jsonl(path: str):
    """Yield parsed JSON objects from a rotated spool, oldest segment
    first (``path.N`` ... ``path.1``, then the live file), skipping
    lines that fail to parse (a crash mid-append leaves at most one
    torn tail line per segment)."""
    segments = []
    i = 1
    while os.path.exists(f"{path}.{i}"):
        segments.append(f"{path}.{i}")
        i += 1
    segments.reverse()
    if os.path.exists(path):
        segments.append(path)
    for seg in segments:
        try:
            with open(seg) as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        yield json.loads(line)
                    except ValueError:
                        continue
        except OSError as exc:
            log.warning("spool segment unreadable: %s: %s", seg, exc)


class FlightRecorder:
    """Bounded ring of annotated events with JSONL journaling and
    dump-on-fault snapshots (module docstring)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._events: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._seq = 0
        self._journaled_seq = 0  # highest seq already flushed to JSONL
        self._journal_path: Optional[str] = None
        self._journal_interval = DEFAULT_JOURNAL_INTERVAL_S
        self._journal_max_bytes = DEFAULT_JOURNAL_MAX_BYTES
        self._journal_keep = DEFAULT_JOURNAL_KEEP
        self._journal_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._dump_dir: Optional[str] = None
        self._dump_n = 0  # dump-file uniqueness counter (see dump())

    # -- recording ----------------------------------------------------------
    def record(self, kind: str, /, **fields) -> None:
        """Append one annotated event.  ``kind`` is a dotted tag
        (``fault.injected``, ``watchdog.hang``, ``coord.fanout``);
        ``fields`` must be JSON-able."""
        with self._lock:
            self._seq += 1
            if len(self._events) == self._events.maxlen:
                # ring overwrite: the oldest event is lost — count it so
                # a journal gap is attributable to capacity, not a bug
                metrics.inc("telemetry.dropped_events")
            self._events.append({
                "seq": self._seq,
                "ts": round(time.time(), 6),
                "kind": kind,
                **fields,
            })

    def recent(self, n: Optional[int] = None) -> List[dict]:
        with self._lock:
            evs = list(self._events)
        return evs if n is None else evs[-n:]

    def depth(self) -> int:
        """Current ring occupancy — the ``ring.flightrec_depth`` gauge
        the resource sentinels export (runtime/health.py)."""
        with self._lock:
            return len(self._events)

    # -- configuration ------------------------------------------------------
    def configure(self, journal_path: Optional[str] = None,
                  journal_interval_s: float = DEFAULT_JOURNAL_INTERVAL_S,
                  dump_dir: Optional[str] = None,
                  journal_max_bytes: int = DEFAULT_JOURNAL_MAX_BYTES,
                  journal_keep: int = DEFAULT_JOURNAL_KEEP) -> None:
        """Enable the periodic JSONL journal and/or the dump directory.

        The recorder — and therefore the journal — is PER PROCESS: in
        the production one-process-per-node topology that means per
        node, but an in-process multi-node harness shares one ring, so
        the journal keeps the FIRST configured path (a later node's
        re-path would silently redirect the earlier node's already-
        announced journal mid-write; review PR 3).  The conflict is
        logged loudly instead."""
        if journal_path:
            # create the journal's directory up front: a missing
            # TelemetryDir must not silently cost every flush (the
            # dump path makedirs too, which would otherwise mask this)
            try:
                d = os.path.dirname(journal_path)
                if d:
                    os.makedirs(d, exist_ok=True)
            except OSError as exc:
                log.error("flight-recorder journal dir unusable: %s", exc)
        with self._lock:
            if dump_dir:
                self._dump_dir = dump_dir
            if journal_path:
                if self._journal_path and self._journal_path != journal_path:
                    log.warning(
                        "flight-recorder journal already bound to %s; "
                        "ignoring re-path to %s (one journal per process "
                        "— events of all in-process nodes land in the "
                        "first-configured file)",
                        self._journal_path, journal_path,
                    )
                    journal_path = None
                else:
                    self._journal_path = journal_path
                    self._journal_interval = float(journal_interval_s)
                    self._journal_max_bytes = int(journal_max_bytes)
                    self._journal_keep = max(0, int(journal_keep))
        if journal_path and (self._journal_thread is None
                             or not self._journal_thread.is_alive()):
            self._stop.clear()
            self._journal_thread = threading.Thread(
                target=self._journal_loop, name="flight-recorder-journal",
                daemon=True,
            )
            self._journal_thread.start()

    def stop(self) -> None:
        """Stop the journal thread after one final flush (tests; node
        shutdown leaves the daemon thread to die with the process)."""
        self._stop.set()
        t = self._journal_thread
        if t is not None:
            t.join(timeout=5.0)
            self._journal_thread = None
        self.flush_journal()

    # -- journal ------------------------------------------------------------
    def _journal_loop(self) -> None:
        while not self._stop.wait(self._journal_interval):
            self.flush_journal()

    def flush_journal(self) -> None:
        """Append every not-yet-journaled ring event to the JSONL file.
        Best-effort: a full disk costs journal lines, never protocol
        progress (the TCPSink drop-don't-block discipline).  The
        journaled watermark only advances AFTER a successful write, so
        a transient failure (ENOSPC blip) retries those events on the
        next flush instead of skipping them while they still sit in the
        ring (review PR 3); the write happens under the ring lock —
        a bounded local append, the FileSink discipline — so racing
        explicit flushes cannot duplicate lines."""
        with self._lock:
            path = self._journal_path
            pending = [e for e in self._events
                       if e["seq"] > self._journaled_seq]
            if not path or not pending:
                return
            lines = "".join(json.dumps(e) + "\n" for e in pending)
            try:
                with open(path, "a") as fh:
                    fh.write(lines)
            except OSError as exc:
                log.warning("flight-recorder journal append failed "
                            "(will retry next flush): %s", exc)
                return
            self._journaled_seq = pending[-1]["seq"]
            self._maybe_rotate_locked(path)

    def _maybe_rotate_locked(self, path: str) -> None:
        """Size-capped rotation via the shared :func:`rotate_if_over`.
        Runs under the ring lock right after a successful append so a
        racing flush can neither double-rotate nor append to a
        mid-rotation file."""
        rotate_if_over(path, self._journal_max_bytes, self._journal_keep)

    # -- dump-on-fault ------------------------------------------------------
    def dump(self, reason: str, dump_dir: Optional[str] = None,
             extra: Optional[dict] = None) -> Optional[str]:
        """Write the whole ring plus a metrics snapshot to one JSON
        file; returns its path, or None when no dump directory is
        configured (memory-only mode) or the write fails.  Called by
        the watchdog's hang verdict and chaos harnesses."""
        d = dump_dir or self._dump_dir
        if not d:
            return None
        payload = {
            "reason": reason,
            "ts": round(time.time(), 6),
            "pid": os.getpid(),
            "events": self.recent(),
            "metrics": metrics.snapshot(),
        }
        if extra:
            payload["extra"] = extra
        safe = "".join(c if c.isalnum() or c in "-_" else "-"
                       for c in reason)
        # uniqueness rides a per-process counter, not the wall clock:
        # two same-reason dumps in one millisecond (or a backward clock
        # step) must not truncate earlier fault evidence (review PR 3)
        with self._lock:
            self._dump_n += 1
            n = self._dump_n
        path = os.path.join(
            d, f"flightrec-{safe}-{int(time.time() * 1000)}-{n}.json"
        )
        try:
            os.makedirs(d, exist_ok=True)
            with open(path, "w") as fh:
                json.dump(payload, fh, indent=1)
                fh.write("\n")
        except OSError as exc:
            log.error("flight-recorder dump failed: %s", exc)
            return None
        metrics.inc("telemetry.dumps")
        log.warning("flight recorder dumped %d event(s) to %s (%s)",
                    len(payload["events"]), path, reason)
        return path

    def reset(self) -> None:
        """Testing hook: drop ring contents and journal bookkeeping
        (configuration is kept)."""
        with self._lock:
            self._events.clear()
            self._seq = 0
            self._journaled_seq = 0


RECORDER = FlightRecorder()


def _env_configure() -> None:
    d = os.environ.get("DISTPOW_TELEMETRY_DIR")
    if not d:
        return
    RECORDER.configure(
        journal_path=os.path.join(d, f"telemetry-{os.getpid()}.jsonl"),
        dump_dir=d,
    )


_env_configure()
