"""Runtime lock-order audit — the dynamic twin of the static
``lock-order-inversion`` rule (docs/CONCURRENCY.md, ISSUE 17).

The static rule sees lexical nesting and bounded call summaries; this
module sees what the process actually did.  With ``DISTPOW_LOCK_CHECK=1``
(and an explicit :func:`install`), the ``threading.Lock`` / ``RLock`` /
``Condition`` factories are replaced with wrappers that tag each lock
with its construction site.  Only locks constructed from files under
this repository are instrumented — jax, the stdlib, and third-party
locks pass through untouched, so the audit never perturbs code it
cannot fix.

Every acquisition records, per thread, the set of already-held
instrumented locks; each (held-site → acquired-site) pair becomes an
edge in a global acquisition-order graph, aggregated by construction
site (not lock instance — ten per-key locks made on one line are one
node, matching the static model's ``LockId`` granularity).  Held
durations are accumulated per site as a cheap contention profile.

:func:`check` condenses the observed graph: any strongly-connected
component of two or more sites is an *observed inversion* — two
threads really did take those locks in opposite orders, which is a
latent deadlock even if the run happened not to hang.  The pytest
session fixture (tests/conftest.py) and ``scripts/ci.sh --race-audit``
fail on a non-empty report.

Design notes:

* ``RLock`` re-entry pushes a re-entrant marker and records no edges —
  re-acquiring a lock you hold orders nothing.
* ``Condition.wait`` needs no special casing: the condition delegates
  ``_release_save`` / ``_acquire_restore`` straight to the inner lock
  (via ``__getattr__``), so the bookkeeping stack shows the lock held
  across the wait — exactly the window in which the blocked thread can
  acquire nothing, so no spurious edges are possible.
* The audit's own bookkeeping uses a pre-patch ``threading.Lock`` so it
  never instruments itself.
"""

from __future__ import annotations

import os
import sys
import threading
from dataclasses import dataclass, field
from time import monotonic
from typing import Dict, List, Optional, Set, Tuple

__all__ = [
    "enabled", "install", "uninstall", "reset", "check",
    "format_report", "stats", "Report",
]

ENV_FLAG = "DISTPOW_LOCK_CHECK"

_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_THIS_FILE = os.path.abspath(__file__)

# real factories, captured at import time — the audit's own state uses
# these so instrumentation never recurses into itself
_real_Lock = threading.Lock
_real_RLock = threading.RLock
_real_Condition = threading.Condition

_state_lock = _real_Lock()
# (held_site, acquired_site) -> observation count
_edges: Dict[Tuple[str, str], int] = {}
# site -> [acquisitions, total_held_s, max_held_s]
_held: Dict[str, List[float]] = {}
_tls = threading.local()
_installed = False


def enabled() -> bool:
    return os.environ.get(ENV_FLAG, "") == "1"


def _construction_site() -> Optional[str]:
    """Repo-relative ``path:line`` of the frame that constructed the
    lock, or ``None`` when the construction site is outside this
    repository (→ the lock stays uninstrumented)."""
    f = sys._getframe(1)
    while f is not None:
        fn = f.f_code.co_filename
        if os.path.abspath(fn) != _THIS_FILE:
            break
        f = f.f_back
    if f is None:
        return None
    fn = os.path.abspath(f.f_code.co_filename)
    if not fn.startswith(_ROOT + os.sep):
        return None
    return f"{os.path.relpath(fn, _ROOT)}:{f.f_lineno}"


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


class _LockProxy:
    """Construction-site-tagged wrapper around a real lock.

    Everything not explicitly intercepted delegates to the inner lock,
    which is what lets ``threading.Condition`` drive an RLock-backed
    proxy correctly (``_release_save`` et al. resolve via
    ``__getattr__``)."""

    def __init__(self, inner: object, site: str) -> None:
        self._inner = inner
        self._site = site

    # -- bookkeeping ---------------------------------------------------------
    def _note_acquired(self) -> None:
        st = _stack()
        reentrant = any(e[0] is self for e in st)
        if not reentrant and st:
            held_sites = {e[0]._site for e in st}
            held_sites.discard(self._site)  # same-line sibling locks
            with _state_lock:
                for hs in held_sites:
                    key = (hs, self._site)
                    _edges[key] = _edges.get(key, 0) + 1
        st.append((self, monotonic(), reentrant))

    def _note_released(self) -> None:
        st = _stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i][0] is self:
                _, t0, reentrant = st.pop(i)
                if not reentrant:
                    dt = monotonic() - t0
                    with _state_lock:
                        rec = _held.setdefault(self._site, [0, 0.0, 0.0])
                        rec[0] += 1
                        rec[1] += dt
                        if dt > rec[2]:
                            rec[2] = dt
                return

    # -- lock protocol -------------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._note_acquired()
        return got

    def release(self) -> None:
        self._note_released()
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc: object) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __getattr__(self, name: str) -> object:
        return getattr(self._inner, name)

    def __repr__(self) -> str:
        return f"<lockcheck proxy {self._site} of {self._inner!r}>"


def _wrap(inner: object, site: Optional[str]) -> object:
    return inner if site is None else _LockProxy(inner, site)


def _lock_factory() -> object:
    return _wrap(_real_Lock(), _construction_site())


def _rlock_factory() -> object:
    return _wrap(_real_RLock(), _construction_site())


def _condition_factory(lock: object = None) -> threading.Condition:
    site = _construction_site()
    if lock is None:
        lock = _wrap(_real_RLock(), site)
    elif not isinstance(lock, _LockProxy):
        # caller-supplied foreign lock: tag it with the condition's site
        lock = _wrap(lock, site)
    return _real_Condition(lock)


def install() -> None:
    """Patch the ``threading`` factories.  Idempotent.  Call before the
    code under audit constructs its locks (e.g. at conftest import)."""
    global _installed
    if _installed:
        return
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    threading.Condition = _condition_factory  # type: ignore[misc, assignment]
    _installed = True


def uninstall() -> None:
    global _installed
    if not _installed:
        return
    threading.Lock = _real_Lock
    threading.RLock = _real_RLock
    threading.Condition = _real_Condition  # type: ignore[misc]
    _installed = False


def reset() -> None:
    """Drop all recorded edges and hold stats (not the patch state)."""
    with _state_lock:
        _edges.clear()
        _held.clear()


# -- analysis ----------------------------------------------------------------

@dataclass
class Report:
    """Condensed view of the observed acquisition-order graph."""
    edges: Dict[Tuple[str, str], int] = field(default_factory=dict)
    cycles: List[List[str]] = field(default_factory=list)


def _sccs(graph: Dict[str, Set[str]]) -> List[List[str]]:
    """Iterative Tarjan; returns SCCs with ≥2 nodes (observed cycles)."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on: Set[str] = set()
    stack: List[str] = []
    out: List[List[str]] = []
    counter = [0]

    for root in sorted(graph):
        if root in index:
            continue
        work = [(root, iter(sorted(graph.get(root, ()))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on.add(nxt)
                    work.append((nxt, iter(sorted(graph.get(nxt, ())))))
                    advanced = True
                    break
                if nxt in on:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) >= 2:
                    out.append(sorted(comp))
    return out


def check() -> Report:
    """Snapshot the observed graph and condense it; ``cycles`` is the
    list of observed lock-order inversions (empty == clean run)."""
    with _state_lock:
        edges = dict(_edges)
    graph: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    return Report(edges=edges, cycles=_sccs(graph))


def format_report(report: Report) -> str:
    if not report.cycles:
        return (f"lockcheck: clean — {len(report.edges)} ordered "
                f"site pair(s), no inversions")
    lines = [f"lockcheck: {len(report.cycles)} lock-order inversion(s) "
             f"observed at runtime:"]
    for comp in report.cycles:
        members = set(comp)
        lines.append("  cycle: " + " <-> ".join(comp))
        for (a, b), n in sorted(report.edges.items()):
            if a in members and b in members:
                lines.append(f"    {a} -> {b}  ({n}x)")
    lines.append("  (two threads really took these locks in opposite "
                 "orders — a latent deadlock; fix the ordering or drop "
                 "one nesting level)")
    return "\n".join(lines)


def stats() -> Dict[str, Dict[str, float]]:
    """Per-site hold profile: acquisitions, total and max held seconds."""
    with _state_lock:
        return {site: {"n": rec[0], "total_s": rec[1], "max_s": rec[2]}
                for site, rec in _held.items()}
