"""Binary wire codec v2 for the RPC data plane (docs/RPC.md).

Wire format v1 (runtime/rpc.py since PR 0) is a 4-byte length prefix
plus UTF-8 JSON, and every byte field — nonce, secret, tracing token —
travels as an array of ints (the natural JSON form of the reference's
``[]uint8``).  That wire spends most of a Mine/Found frame on syntax:
repeated key strings, digits-and-commas byte arrays, base64 padding.
This module is the v2 payload encoding the RPC layer negotiates per
connection at dial time (``rpc.hello``): a struct-packed frame header,
interned method/key ids for the protocol's fixed vocabulary, and raw
``bytes`` for the byte fields.  The length-prefix framing, the fault
plane's frame mutations (runtime/faults.py truncate/duplicate/drop
operate on the encoded frame, not its syntax), and the
``rpc.frame.{sent,recv}_bytes`` histograms are codec-agnostic and
unchanged.

Frame payloads (everything after the 4-byte length prefix)::

    request  := 0x01 | varint id | method | value(params)
    response := 0x02 | varint id | u8 flags | [f64 retry_after]
                | [value ring] | value
    flags    := bit0 error (value is the error string)
                bit1 retry_after present (sched/admission.py typed
                     backpressure — the hint is a dedicated header
                     field, exactly like the JSON frame's dedicated
                     ``retry_after`` key)
                bit2 ring present (the cluster plane's NOT_OWNER
                     redirect ships a ring snapshot dict —
                     docs/CLUSTER.md; only pooled coordinators ever
                     set it, so pre-cluster traffic is bit-identical)

    method   := 0x80|idx            interned (METHODS table)
              | 0x00 varint len utf8  anything else

    value    := 0x00                         None
              | 0x01 / 0x02                  False / True
              | 0x03 zigzag-varint           int
              | 0x04 f64 big-endian          float
              | 0x05 varint len utf8         str
              | 0x06 varint len raw          bytes
              | 0x07 varint n value*         list
              | 0x08 varint n (key value)*   dict
    key      := 0x80|idx (KEYS table) | 0x00 varint len utf8

Varints are unsigned LEB128; ints are zigzag-mapped first so small
negatives stay small.  The METHODS/KEYS tables are part of the wire
contract: **append-only** — reordering or removing an entry changes the
meaning of frames already in flight from an older peer.  Golden-vector
tests (tests/test_wire.py) pin the exact bytes of representative frames
in both directions so an accidental table edit fails loudly.

Decoding is defensive: every read is bounds-checked, nesting depth and
varint width are capped, and any violation raises ``ValueError`` — the
same class a corrupt JSON frame raises, so rpc.py's existing
drop-the-connection error handling covers both codecs.
"""

from __future__ import annotations

import struct
from typing import Any, List, Tuple

#: negotiated protocol version carried in the ``rpc.hello`` exchange
WIRE_VERSION = 2

# -- interning tables (append-only; see module docstring) --------------------

METHODS: Tuple[str, ...] = (
    "CoordRPCHandler.Mine",
    "CoordRPCHandler.Result",
    "CoordRPCHandler.Stats",
    "WorkerRPCHandler.Mine",
    "WorkerRPCHandler.Found",
    "WorkerRPCHandler.Cancel",
    "WorkerRPCHandler.Ping",
    "WorkerRPCHandler.Stats",
    # appended for the fleet membership plane (distpow_tpu/fleet/,
    # docs/FLEET.md); table stays append-only
    "Fleet.Register",
    "Fleet.Heartbeat",
    "Fleet.Drain",
    "Fleet.Members",
    # appended for the request-forensics plane (runtime/spans.py,
    # docs/FORENSICS.md): the role-agnostic observability surface;
    # table stays append-only
    "Node.Stats",
    "Node.Spans",
    # appended for the coordinator scale-out plane (distpow_tpu/cluster/,
    # docs/CLUSTER.md): the ring snapshot on demand; table stays
    # append-only
    "Cluster.Ring",
    # appended for the cache replication plane
    # (distpow_tpu/cluster/replication.py, docs/CLUSTER.md
    # "Replication & HA"): write-behind/anti-entropy pushes and the
    # warm shard handoff; table stays append-only
    "Cluster.CacheSync",
    "Cluster.Handoff",
)
_METHOD_IDS = {m: i for i, m in enumerate(METHODS)}

KEYS: Tuple[str, ...] = (
    "nonce",
    "num_trailing_zeros",
    "worker_byte",
    "worker_bits",
    "round",
    "token",
    "secret",
    "codec",
    "worker_tasks",
    # appended for the fleet membership plane (weighted shard ranges on
    # every Mine of a weighted round; lease plumbing on the low-rate
    # Fleet RPCs); table stays append-only
    "tb_lo",
    "tb_count",
    "lease_id",
    "worker_id",
    "capability",
    "ttl_s",
    "heartbeat_s",
    # appended for the request-forensics plane (Node.Spans request and
    # reply vocabulary — runtime/spans.py); table stays append-only
    "trace_id",
    "spans",
    "limit",
    "name",
    "node",
    "ts",
    "dur_s",
    "attrs",
    "seq",
    # appended for the coordinator scale-out plane (distpow_tpu/cluster/,
    # docs/CLUSTER.md): ring snapshots (Cluster.Ring / NOT_OWNER
    # redirects / the extended rpc.hello ack), the Mine reply-to addr a
    # pooled coordinator stamps so shared workers route each Result
    # back to its round's owner, and the no-redirect marker on hedged/
    # failover sends; table stays append-only
    "ring",
    "version",
    "vnodes",
    "members",
    "coord_addr",
    "no_redirect",
    "self",
    # appended for the cache replication plane
    # (distpow_tpu/cluster/replication.py): CacheSync/Handoff entry
    # batches, the anti-entropy digest exchange, and the install
    # accounting replies; table stays append-only
    "entries",
    "digest",
    "installed",
    "stale",
)
_KEY_IDS = {k: i for i, k in enumerate(KEYS)}

FRAME_REQUEST = 0x01
FRAME_RESPONSE = 0x02
FLAG_ERROR = 0x01
FLAG_RETRY_AFTER = 0x02
#: error frame carries a ring snapshot (the cluster plane's NOT_OWNER
#: redirect — docs/CLUSTER.md).  Only a POOLED coordinator ever sets
#: it, so single-coordinator deployments stay byte-identical to every
#: earlier version of this codec; a pre-cluster peer never receives the
#: flag because it never dials a pool.
FLAG_RING = 0x04

_TAG_NONE = 0x00
_TAG_FALSE = 0x01
_TAG_TRUE = 0x02
_TAG_INT = 0x03
_TAG_FLOAT = 0x04
_TAG_STR = 0x05
_TAG_BYTES = 0x06
_TAG_LIST = 0x07
_TAG_DICT = 0x08

_MAX_DEPTH = 32
_MAX_VARINT_BYTES = 10  # 70 bits — covers every counter this repo mints


# -- varints -----------------------------------------------------------------

def _put_varint(out: List[bytes], n: int) -> None:
    if n < 0:
        raise ValueError(f"varint must be non-negative, got {n}")
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(bytes((b | 0x80,)))
        else:
            out.append(bytes((b,)))
            return


def _zigzag(n: int) -> int:
    return (n << 1) if n >= 0 else ((-n) << 1) - 1


def _unzigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


class _Cursor:
    """Bounds-checked reader over one frame's payload."""

    __slots__ = ("data", "pos")

    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def take(self, n: int) -> bytes:
        if n < 0 or self.pos + n > len(self.data):
            raise ValueError("truncated binary frame")
        out = self.data[self.pos:self.pos + n]
        self.pos += n
        return out

    def u8(self) -> int:
        return self.take(1)[0]

    def varint(self) -> int:
        shift = n = 0
        for i in range(_MAX_VARINT_BYTES):
            b = self.u8()
            n |= (b & 0x7F) << shift
            if not b & 0x80:
                return n
            shift += 7
        raise ValueError("varint wider than the protocol allows")

    def done(self) -> bool:
        return self.pos == len(self.data)


# -- value tree --------------------------------------------------------------

def _encode_value(out: List[bytes], v: Any, depth: int = 0) -> None:
    if depth > _MAX_DEPTH:
        raise ValueError("value nesting exceeds the wire depth cap")
    if v is None:
        out.append(bytes((_TAG_NONE,)))
    elif v is True:
        out.append(bytes((_TAG_TRUE,)))
    elif v is False:
        out.append(bytes((_TAG_FALSE,)))
    elif isinstance(v, int):
        out.append(bytes((_TAG_INT,)))
        _put_varint(out, _zigzag(v))
    elif isinstance(v, float):
        out.append(bytes((_TAG_FLOAT,)))
        out.append(struct.pack(">d", v))
    elif isinstance(v, str):
        raw = v.encode()
        out.append(bytes((_TAG_STR,)))
        _put_varint(out, len(raw))
        out.append(raw)
    elif isinstance(v, (bytes, bytearray, memoryview)):
        raw = bytes(v)
        out.append(bytes((_TAG_BYTES,)))
        _put_varint(out, len(raw))
        out.append(raw)
    elif isinstance(v, (list, tuple)):
        out.append(bytes((_TAG_LIST,)))
        _put_varint(out, len(v))
        for item in v:
            _encode_value(out, item, depth + 1)
    elif isinstance(v, dict):
        out.append(bytes((_TAG_DICT,)))
        _put_varint(out, len(v))
        for k, item in v.items():
            if not isinstance(k, str):
                raise ValueError(f"wire dict keys must be str, got {type(k).__name__}")
            idx = _KEY_IDS.get(k)
            if idx is not None:
                out.append(bytes((0x80 | idx,)))
            else:
                raw = k.encode()
                out.append(b"\x00")
                _put_varint(out, len(raw))
                out.append(raw)
            _encode_value(out, item, depth + 1)
    else:
        raise ValueError(f"type {type(v).__name__} is not wire-encodable")


def _decode_value(cur: _Cursor, depth: int = 0) -> Any:
    if depth > _MAX_DEPTH:
        raise ValueError("value nesting exceeds the wire depth cap")
    tag = cur.u8()
    if tag == _TAG_NONE:
        return None
    if tag == _TAG_FALSE:
        return False
    if tag == _TAG_TRUE:
        return True
    if tag == _TAG_INT:
        return _unzigzag(cur.varint())
    if tag == _TAG_FLOAT:
        return struct.unpack(">d", cur.take(8))[0]
    if tag == _TAG_STR:
        return cur.take(cur.varint()).decode()
    if tag == _TAG_BYTES:
        return cur.take(cur.varint())
    if tag == _TAG_LIST:
        return [_decode_value(cur, depth + 1) for _ in range(cur.varint())]
    if tag == _TAG_DICT:
        out = {}
        for _ in range(cur.varint()):
            kb = cur.u8()
            if kb & 0x80:
                idx = kb & 0x7F
                if idx >= len(KEYS):
                    raise ValueError(f"unknown interned key id {idx}")
                k = KEYS[idx]
            elif kb == 0x00:
                k = cur.take(cur.varint()).decode()
            else:
                raise ValueError(f"malformed dict key marker 0x{kb:02x}")
            out[k] = _decode_value(cur, depth + 1)
        return out
    raise ValueError(f"unknown value tag 0x{tag:02x}")


# -- frames ------------------------------------------------------------------

def encode_frame(obj: dict) -> bytes:
    """Encode one request/response dict (the shape rpc.py passes around)
    into a v2 payload.  Requests are recognized by a ``method`` key."""
    out: List[bytes] = []
    rid = int(obj.get("id") or 0)
    if "method" in obj:
        out.append(bytes((FRAME_REQUEST,)))
        _put_varint(out, rid)
        method = obj["method"]
        idx = _METHOD_IDS.get(method)
        if idx is not None:
            out.append(bytes((0x80 | idx,)))
        else:
            raw = method.encode()
            out.append(b"\x00")
            _put_varint(out, len(raw))
            out.append(raw)
        _encode_value(out, obj.get("params") or {})
    else:
        out.append(bytes((FRAME_RESPONSE,)))
        _put_varint(out, rid)
        error = obj.get("error")
        retry_after = obj.get("retry_after")
        ring = obj.get("ring")
        flags = (FLAG_ERROR if error else 0) | \
            (FLAG_RETRY_AFTER if retry_after is not None else 0) | \
            (FLAG_RING if ring is not None else 0)
        out.append(bytes((flags,)))
        if retry_after is not None:
            out.append(struct.pack(">d", float(retry_after)))
        if ring is not None:
            _encode_value(out, ring)
        _encode_value(out, str(error) if error else obj.get("result"))
    return b"".join(out)


def decode_frame(data: bytes) -> dict:
    """Decode one v2 payload back into the dict shape rpc.py expects:
    ``{"id", "method", "params"}`` or ``{"id", "result", "error"[,
    "retry_after"]}``.  Raises ``ValueError`` on any malformation."""
    cur = _Cursor(bytes(data))
    kind = cur.u8()
    rid = cur.varint()
    if kind == FRAME_REQUEST:
        mb = cur.u8()
        if mb & 0x80:
            idx = mb & 0x7F
            if idx >= len(METHODS):
                raise ValueError(f"unknown interned method id {idx}")
            method = METHODS[idx]
        elif mb == 0x00:
            method = cur.take(cur.varint()).decode()
        else:
            raise ValueError(f"malformed method marker 0x{mb:02x}")
        params = _decode_value(cur)
        if not isinstance(params, dict):
            raise ValueError("request params must decode to a dict")
        obj = {"id": rid, "method": method, "params": params}
    elif kind == FRAME_RESPONSE:
        flags = cur.u8()
        if flags & ~(FLAG_ERROR | FLAG_RETRY_AFTER | FLAG_RING):
            raise ValueError(f"unknown response flags 0x{flags:02x}")
        retry_after = None
        if flags & FLAG_RETRY_AFTER:
            retry_after = struct.unpack(">d", cur.take(8))[0]
        ring = None
        if flags & FLAG_RING:
            ring = _decode_value(cur)
            if not isinstance(ring, dict):
                raise ValueError("ring frame field must decode to a dict")
        body = _decode_value(cur)
        if flags & FLAG_ERROR:
            if not isinstance(body, str):
                raise ValueError("error frame body must be a string")
            obj = {"id": rid, "result": None, "error": body}
        else:
            obj = {"id": rid, "result": body, "error": None}
        if retry_after is not None:
            obj["retry_after"] = retry_after
        if ring is not None:
            obj["ring"] = ring
    else:
        raise ValueError(f"unknown frame kind 0x{kind:02x}")
    if not cur.done():
        raise ValueError(
            f"{len(cur.data) - cur.pos} trailing byte(s) after frame body"
        )
    return obj
