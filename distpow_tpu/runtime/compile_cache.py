"""Persistent XLA compile-cache setup — one implementation for every
entry point (review r4: the knob was triplicated across bench/scripts
with drifting thresholds and error handling).

The worker reaches this through ``WorkerConfig.CompilationCacheDir``;
bench.py and the hardware session scripts pass the shared default so a
short tunnel window amortizes compiles across stages AND across the
driver's separate round-end bench run on the same machine.
"""

from __future__ import annotations

import logging
import threading
import warnings

from distpow_tpu.runtime.metrics import REGISTRY

log = logging.getLogger("distpow.compile_cache")

DEFAULT_DIR = "/tmp/xla_cache"
# Cache anything that took >= this many seconds to compile.  Matches the
# worker's threshold so a bench warm-start sees every program a booted
# worker would have persisted.
MIN_COMPILE_SECS = 0.5

# Counter names (REGISTRY): total plus a read/write/keygen breakdown.
# The worker's Stats RPC ships the registry snapshot, so a failing
# cache shows up in ``python -m distpow_tpu.cli.stats`` instead of as
# one stderr line nobody reads (VERDICT r4 item 2: bench7's
# ``UNAVAILABLE`` persistent-cache read error went unnoticed and
# silently cost the run its warm start).
ERRORS_TOTAL = "compile_cache.errors"
ERRORS_READ = "compile_cache.read_errors"
ERRORS_WRITE = "compile_cache.write_errors"
ERRORS_KEYGEN = "compile_cache.keygen_errors"

_install_lock = threading.Lock()
_installed = False


def _classify(message: str) -> str | None:
    """Map a jax cache-failure message to a breakdown counter.

    The upstream shapes (jax._src/compiler.py): read/write failures are
    ``warnings.warn("Error reading|writing persistent compilation cache
    entry for ...")``; cache-key failures are ``logger.error(
    "compile_or_get_cached: unable to generate cache key, ...")``; the
    lru_cache eviction layer warns with its own messages mentioning the
    compilation cache.  The read/write breakdown anchors on jax's
    LITERAL "error reading"/"error writing" phrasings — a looser word
    search substring-matched "read" inside e.g. "thread" and could
    misattribute unrelated cache warnings (advisor r5 low #2); anything
    else cache-related degrades to the total counter, not to silence.
    """
    m = message.lower()
    if "compilation cache" not in m and "cache key" not in m:
        return None
    if "error reading" in m:
        return ERRORS_READ
    if "error writing" in m:
        return ERRORS_WRITE
    if "cache key" in m:
        return ERRORS_KEYGEN
    return ERRORS_TOTAL


def _count(message: str, origin: str) -> bool:
    kind = _classify(message)
    if kind is None:
        return False
    REGISTRY.inc(ERRORS_TOTAL)
    if kind != ERRORS_TOTAL:
        REGISTRY.inc(kind)
    log.warning("persistent compile cache error (%s, counted as %s): %s",
                origin, kind, message[:300])
    return True


class _CacheErrorLogHandler(logging.Handler):
    """Counts jax's logger-path cache failures (keygen errors)."""

    def emit(self, record: logging.LogRecord) -> None:
        if record.levelno >= logging.ERROR:
            try:
                _count(record.getMessage(), "log")
            # distpow: ok silent-except -- this handler runs INSIDE the
            # logging machinery it instruments: raising would recurse, and
            # logging the failure from here would re-enter emit(); silence
            # is the only safe behavior for a counter bug
            except Exception:
                pass


def _install_error_counters() -> None:
    """Intercept both failure channels, once per process.

    * ``warnings.showwarning`` is wrapped (and chained — the original
      still runs, so nothing disappears from stderr) to count the
      read/write entry failures.
    * a handler on the ``jax._src.compiler`` logger counts the
      cache-key failure path.
    """
    global _installed
    with _install_lock:
        if _installed:
            return
        _installed = True

        prev = warnings.showwarning

        def showwarning(message, category, filename, lineno,
                        file=None, line=None):
            try:
                _count(str(message), "warning")
            # distpow: ok silent-except -- runs inside warnings.showwarning:
            # a raise here would break EVERY warning in the process, and the
            # chained prev() below must run regardless; a counter bug costs
            # one count, never the warning itself
            except Exception:
                pass
            prev(message, category, filename, lineno, file, line)

        warnings.showwarning = showwarning
        # Without this, Python's "default" filter action dedupes repeat
        # warnings per (text, category, lineno) — a cache failing the
        # same way on every entry would count as ~1 error total, hiding
        # an ongoing outage behind a one-transient-shaped metric
        # (review r5).  Force every cache-entry failure through the
        # display path (and hence the counter).
        warnings.filterwarnings(
            "always", message=r".*persistent compilation cache.*"
        )
        logging.getLogger("jax._src.compiler").addHandler(
            _CacheErrorLogHandler()
        )


def error_count() -> int:
    """Total persistent-cache errors counted so far (testing/ops hook)."""
    return int(REGISTRY.get(ERRORS_TOTAL))


def enable(cache_dir: str = DEFAULT_DIR,
           min_compile_secs: float = MIN_COMPILE_SECS) -> bool:
    """Point jax's persistent compilation cache at ``cache_dir``.

    Returns True on success; failures are logged (never silent — an
    unwritable directory or renamed config key would otherwise disable
    caching with no trace) and never raised.  Also installs the error
    counters above, so every caller of ``enable`` gets accounting for
    free.
    """
    _install_error_counters()
    try:
        import jax

        prev_dir = getattr(jax.config, "jax_compilation_cache_dir", None)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", min_compile_secs
        )
        if prev_dir != cache_dir:
            # jax initializes its cache object lazily at the FIRST
            # compile attempt and then IGNORES config-dir changes —
            # including the attempt that found no dir configured at
            # all (_initialize_cache sets its once-latch before the
            # empty-path early return).  So a worker enabling a
            # CompilationCacheDir after the process has compiled
            # anything — prior config dir set OR None — would silently
            # get no caching.  reset_cache() returns the latch to the
            # uninitialized state so the next compile binds this dir.
            try:
                from jax._src import compilation_cache as _cc

                _cc.reset_cache()
            except Exception as exc:  # private API: degrade to a log line
                log.warning("could not reset jax cache object after dir "
                            "change %s -> %s: %s", prev_dir, cache_dir, exc)
        return True
    except Exception as exc:
        log.warning("persistent compile cache unavailable (%s): %s",
                    cache_dir, exc)
        return False
