"""Persistent XLA compile-cache setup — one implementation for every
entry point (review r4: the knob was triplicated across bench/scripts
with drifting thresholds and error handling).

The worker reaches this through ``WorkerConfig.CompilationCacheDir``;
bench.py and the hardware session scripts pass the shared default so a
short tunnel window amortizes compiles across stages AND across the
driver's separate round-end bench run on the same machine.
"""

from __future__ import annotations

import logging

log = logging.getLogger("distpow.compile_cache")

DEFAULT_DIR = "/tmp/xla_cache"
# Cache anything that took >= this many seconds to compile.  Matches the
# worker's threshold so a bench warm-start sees every program a booted
# worker would have persisted.
MIN_COMPILE_SECS = 0.5


def enable(cache_dir: str = DEFAULT_DIR,
           min_compile_secs: float = MIN_COMPILE_SECS) -> bool:
    """Point jax's persistent compilation cache at ``cache_dir``.

    Returns True on success; failures are logged (never silent — an
    unwritable directory or renamed config key would otherwise disable
    caching with no trace) and never raised.
    """
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", min_compile_secs
        )
        return True
    except Exception as exc:
        log.warning("persistent compile cache unavailable (%s): %s",
                    cache_dir, exc)
        return False
