"""Process-wide metrics registry (no reference equivalent — the
reference's only observability is its tracing subsystem; SURVEY.md
section 5 "Metrics: no counters").

A deliberately tiny, dependency-free counter/gauge registry.  Every node
process has one ``REGISTRY``; hot paths increment named counters and the
node's ``Stats`` RPC ships a snapshot (see nodes/coordinator.py and
nodes/worker.py; ``python -m distpow_tpu.cli.stats`` prints it).

Counter names in use:

* ``search.hashes``        — candidates evaluated (all backends)
* ``search.launches``      — device dispatches
* ``search.cancelled``     — searches stopped by a cancel check
* ``search.found``         — searches that returned a secret
* ``worker.mine_rpcs`` / ``worker.found_rpcs`` / ``worker.cancel_rpcs``
* ``worker.results_sent``  — messages queued to the forwarder
* ``coord.mine_rpcs`` / ``coord.fanouts`` / ``coord.late_results``
* ``coord.worker_failures`` / ``coord.reassigned_shards``
* ``cache.hit`` / ``cache.miss`` / ``cache.add`` / ``cache.evict``
* ``powlib.retries`` / ``powlib.reconnects`` / ``powlib.degraded``
  — client-side coordinator-outage recovery (nodes/powlib.py)
* ``faults.injected.<kind>`` — fault-injection plane activity
  (runtime/faults.py; kind in refuse/delay/truncate/duplicate/drop)
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Union

Number = Union[int, float]


class Metrics:
    def __init__(self):
        self._counters: Dict[str, Number] = {}
        self._gauges: Dict[str, Number] = {}
        self._lock = threading.Lock()
        self._start = time.time()

    def inc(self, name: str, n: Number = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def gauge(self, name: str, value: Number) -> None:
        with self._lock:
            self._gauges[name] = value

    def get(self, name: str) -> Number:
        with self._lock:
            return self._counters.get(name, self._gauges.get(name, 0))

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "uptime_secs": round(time.time() - self._start, 3),
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
            }

    def reset(self) -> None:
        """Testing hook."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._start = time.time()


REGISTRY = Metrics()
