"""Process-wide metrics registry (no reference equivalent — the
reference's only observability is its tracing subsystem; SURVEY.md
section 5 "Metrics: no counters").

A deliberately tiny, dependency-free counter/gauge/histogram registry.
Every node process has one ``REGISTRY``; hot paths increment named
counters, set gauges, and observe latency/size samples into
log-bucketed histograms; the node's ``Stats`` RPC ships a snapshot (see
nodes/coordinator.py and nodes/worker.py; ``python -m
distpow_tpu.cli.stats`` prints it, ``--prom`` renders Prometheus text
exposition — docs/METRICS.md is the catalog).

Counter names in use (machine-checked: ``KNOWN_COUNTERS`` below is the
declaration distpow-lint's ``metrics-registry`` rule verifies every
``metrics.inc("…")`` call site against — docs/LINT.md):

* ``search.hashes``        — candidates evaluated (all backends)
* ``search.launches``      — device dispatches
* ``search.cancelled``     — searches stopped by a cancel check
* ``search.found``         — searches that returned a secret
* ``search.blocking_syncs`` — result conversions issued WITHOUT
  readiness confirmed (the serial drain's per-launch ``int(res)``;
  the persistent loop's polling drain keeps this flat —
  parallel/search.py, docs/SERVING.md)
* ``search.persistent_steps`` — on-device sub-batches (segments)
  executed inside persistent-loop dispatches (early-exit means this
  can be far below the dispatched segment budget)
* ``worker.mine_rpcs`` / ``worker.found_rpcs`` / ``worker.cancel_rpcs``
* ``worker.results_sent``  — messages queued to the forwarder
* ``worker.forward_retries`` — result deliveries retried after a
  coordinator outage (nodes/worker.py start_forwarder)
* ``coord.mine_rpcs`` / ``coord.fanouts`` / ``coord.late_results``
* ``coord.worker_failures`` / ``coord.reassigned_shards``
* ``coord.stale_results_dropped`` — zombie-round results dropped by the
  Result handler's round tag (nodes/coordinator.py module docstring)
* ``cache.hit`` / ``cache.miss`` / ``cache.add`` / ``cache.evict``
* ``powlib.retries`` / ``powlib.reconnects`` / ``powlib.degraded``
  — client-side coordinator-outage recovery (nodes/powlib.py)
* ``powlib.retry_after`` — server-paced RETRY_AFTER backpressure
  retries (non-counting: they never burn the transport retry budget)
* ``sched.launches`` — batched device dispatches issued by the
  continuous-batching engine (sched/engine.py)
* ``sched.mixed_hash_launches`` — batched launches whose slot set
  spans more than one hash model (per-model sub-batches inside one
  compiled program — sched/engine.py, docs/SERVING.md)
* ``sched.lane_launches.<lane>`` — launch groups served per launch
  lane (``pallas`` / ``mesh`` / ``xla`` — the sched/lanes.py planner;
  a demoted group counts under the lane that actually served it)
* ``sched.admission_rejected`` — Mine requests shed by the
  coordinator's bounded run queue (sched/admission.py)
* ``sched.coalesced_requests`` — duplicate in-flight Mines attached as
  waiters to an existing fan-out round (sched/coalesce.py)
* ``sched.slots_preempted`` — active slots rotated back to the run
  queue by the weighted-fair allocator under oversubscription
* ``sched.fallback_searches`` — searches the packed step could not
  express, served through the wrapped solo backend
* ``sched.loop_failures`` — scheduler device-loop deaths (slots fail
  over to errors, never hangs)
* ``rpc.handler_errors`` — handler exceptions returned to callers in
  the response frame (runtime/rpc.py _dispatch)
* ``rpc.codec.negotiated_v2`` / ``rpc.codec.fallback_v1`` — per-
  connection wire-codec negotiation outcomes (runtime/rpc.py
  ``rpc.hello``; docs/RPC.md): binary v2 agreed vs transparent JSON
  fallback against a v1-only peer
* ``coord.abandoned_resyncs`` — background best-effort Found re-syncs
  to workers abandoned during a round (nodes/coordinator.py
  ``_resync_abandoned`` — off the Mine success path; per-outcome
  detail rides the ``coord.abandoned_resync`` flight-recorder event)
* ``compile_cache.errors`` (+ ``.read_errors`` / ``.write_errors`` /
  ``.keygen_errors``) — persistent XLA cache failures
  (runtime/compile_cache.py)
* ``faults.injected.<kind>`` — fault-injection plane activity
  (runtime/faults.py; kind in refuse/delay/truncate/duplicate/drop)
* ``telemetry.dropped_events`` / ``telemetry.dumps`` — flight-recorder
  ring overwrites and dump-on-fault snapshots (runtime/telemetry.py)
* ``obs.scrapes`` / ``obs.scrape_failures`` — fleet-scraper sweeps
  issued and per-node polls that failed or missed the shared sweep
  deadline (distpow_tpu/obs/scrape.py, docs/SLO.md)
* ``slo.evaluations`` / ``slo.breaches`` — SLO-engine verdict runs and
  verdicts that breached (distpow_tpu/obs/slo.py; every breach also
  records an ``slo.breach`` flight-recorder event)
* ``fleet.joins`` — elastic workers admitted via ``Fleet.Register``
  (re-registrations after a lost lease included;
  distpow_tpu/fleet/membership.py, docs/FLEET.md)
* ``fleet.lease_expiries`` — heartbeat leases retired after missing
  their TTL (the vanished-worker path into orphan reassignment)
* ``fleet.drains`` — leases released through the graceful
  ``Fleet.Drain`` RPC (in-flight rounds completed first)
* ``fleet.hedged_shards`` — straggler shards duplicated onto the
  least-loaded live worker while a round waited on a silent owner
  (nodes/coordinator.py ``_maybe_hedge``)
* ``spans.dropped`` — span-ring overwrites: per-trace forensics
  fetches lose their oldest spans (runtime/spans.py, docs/FORENSICS.md)
* ``forensics.slow_captures`` — Mine rounds auto-captured into the
  flight recorder by the slow-request trigger (threshold or rolling-p99
  exceedance — nodes/coordinator.py, runtime/spans.py)
* ``forensics.fetches`` / ``forensics.fetch_failures`` — fleet-wide
  span sweeps issued and per-node Spans polls that failed or missed
  the shared deadline (distpow_tpu/obs/forensics.py)
* ``cluster.not_owner_redirects`` — misrouted Mines a pooled
  coordinator answered with the typed NOT_OWNER redirect + ring
  snapshot (distpow_tpu/cluster/, docs/CLUSTER.md)
* ``cluster.foreign_mines`` — Mines a pooled coordinator served for a
  key it does NOT own (``no_redirect`` hedged/failover sends — the
  shared worker fleet makes them correct, only cache locality pays)
* ``cluster.ring_serves`` — ``Cluster.Ring`` snapshot requests served
* ``cluster.reroutes`` — powlib mines re-routed to a different shard
  after adopting a NOT_OWNER redirect's ring snapshot
* ``cluster.failovers`` — powlib mines failed over to a ring sibling
  after the owner shard's transport died and its re-dial failed
* ``cluster.sibling_hedges`` — RETRY_AFTER rejections hedged to the
  next ring sibling instead of waiting out the owner's hint
  (non-counting, like every server-paced retry)
* ``repl.pushes`` — dominance-cache entries write-behind-pushed to a
  ring successor (one per entry-destination pair;
  distpow_tpu/cluster/replication.py, docs/CLUSTER.md "Replication")
* ``repl.push_failures`` — entries dropped from the bounded push queue
  or lost to a failed ``Cluster.CacheSync`` (anti-entropy heals both)
* ``repl.installs`` — replica-side installs accepted through the
  dominance order (CacheSync pushes, handoff chunks, anti-entropy
  heals alike)
* ``repl.stale_drops`` — replica-side pushes REJECTED by the dominance
  order (a stale lower-ntz push after a higher-ntz install — proof the
  order held, never a regression)
* ``repl.handoff_keys`` — entries pushed to their new owner during a
  warm shard handoff (``Cluster.Handoff``, before the ring change is
  acked)
* ``repl.antientropy_rounds`` — anti-entropy digest-exchange sweeps
  completed against the ring successors
* ``health.leak_suspects`` — resource gauges the trend detector judged
  monotone-climbing past the noise floor (runtime/health.py; each also
  records a ``health.leak_suspect`` flight-recorder event —
  docs/SOAK.md "Sentinels")
* ``soak.sweeps`` — fleet sweeps the soak harness ingested into the
  time-series store (distpow_tpu/load/soak.py, docs/SOAK.md)
* ``soak.phase_breaches`` — shape phases whose windowed SLO judgment
  breached during a soak (one per failing phase, not per objective)
* ``obs.spool_rotations`` — time-series JSONL spool segments rotated
  out by the size cap (distpow_tpu/obs/timeseries.py; same rotation
  machinery as the flight-recorder journal)

Histogram names in use (same machine check, ``KNOWN_HISTOGRAMS`` /
``KNOWN_HISTOGRAM_PREFIXES`` vs ``observe()``/``time()`` call sites):

* ``coord.mine_s.hit`` / ``coord.mine_s.miss`` — Mine RPC end-to-end
  latency split by dominance-cache outcome (nodes/coordinator.py)
* ``coord.first_result_s``       — fan-out to first worker result
* ``coord.cancel_propagation_s`` — fan-out to last cancellation ACK
* ``worker.solve_s``          — backend search latency for found secrets
* ``worker.solve_s.<model>``  — the same distribution split per hash
  model (family; the per-model SLO objectives and the cluster
  aggregation's per-model breakdown read these — docs/SLO.md)
* ``obs.sweep_s``      — fleet-scraper merge time per sweep
  (distpow_tpu/obs/scrape.py)
* ``fleet.heartbeat_rtt_s`` — worker-observed lease-heartbeat round
  trip (distpow_tpu/fleet/agent.py; the cadence side lives in the
  registry's per-lease EMA and drives the hedge threshold)
* ``cluster.failover_s`` — first owner-shard transport failure to the
  successful reply from another shard: the client-observed cost of
  riding out a coordinator death (nodes/powlib.py, docs/CLUSTER.md)
* ``repl.push_lag_s`` — round completion (queue admit) to the entry
  landing on its last ring successor: the replication window a member
  death can lose (distpow_tpu/cluster/replication.py)
* ``repl.handoff_s`` — wall time of one warm shard handoff (all
  targets, chunked sends, deadline-bounded)
* ``worker.time_to_cancel_s`` — Mine receipt to honored cancellation
* ``search.launch_s``  — time blocked fetching one launch's result
  (the serial driver's FIFO drain; parallel/search.py)
* ``search.poll_s``    — time spent POLLING a launch to readiness in
  the persistent driver's drain (the host stays responsive — cancel
  checks run between polls; docs/SERVING.md)
* ``powlib.mine_s``    — client-observed mine round-trip incl. retries
* ``sched.batch_occupancy`` — real (non-padding) slots per batched
  launch: the continuous-batching win is this distribution's mean
* ``sched.slot_wait_s`` — submit-to-first-dispatch queueing latency of
  a scheduler slot (admission + run-queue wait)
* ``rpc.frame.sent_bytes`` / ``rpc.frame.recv_bytes`` — wire frame sizes
* ``rpc.client.call_s.<Service.Method>``     — per-method round-trip
* ``rpc.server.dispatch_s.<Service.Method>`` — per-method handler time
* ``load.lag_s`` — open-loop generator lag: how far behind its seeded
  Poisson schedule each arrival fired (distpow_tpu/load/loadgen.py; a
  lagging generator silently converts open-loop into closed-loop, so
  the soak verdict judges this distribution — docs/SOAK.md)

Gauge names in use (``KNOWN_GAUGES`` below; lint-gated since the soak
plane made gauges load-bearing — a typo'd sentinel gauge would hide a
leak from the trend detector exactly when it matters):

* ``worker.active_searches`` / ``worker.mine_queue_depth`` /
  ``worker.forward_queue_depth`` — worker occupancy and bounded-queue
  depths (nodes/worker.py)
* ``search.hashes_per_s``  — rolling backend throughput
* ``sched.active_slots`` / ``sched.run_queue_depth`` — continuous-
  batching occupancy and bounded run-queue depth (sched/engine.py)
* ``search.mesh_devices`` — device count of the most recently built
  search mesh (parallel/mesh_search.py make_mesh — the mesh serving
  lanes and backends all pass through it)
* ``fleet.live_workers``   — coordinator-side count of non-draining
  members, static and elastic alike (distpow_tpu/fleet/membership.py)
* ``proc.rss_bytes`` / ``proc.open_fds`` / ``proc.threads`` — per-node
  self-telemetry sampled on every Stats snapshot (runtime/health.py;
  the soak plane's leak sentinels watch these — docs/SOAK.md)
* ``ring.spans_depth`` / ``ring.flightrec_depth`` /
  ``ring.repl_queue_depth`` — occupancy of the bounded rings the repo
  owns (span ring, flight-recorder ring, replication push queue);
  forwarder backlog and sched run queue already ship as the
  ``*_queue_depth`` gauges above
"""

from __future__ import annotations

import math
import threading
import time
from typing import Dict, List, Optional, Tuple, Union

Number = Union[int, float]

# The declared counter registry.  distpow-lint's ``metrics-registry``
# rule parses these literals (AST, no import) and flags any
# ``metrics.inc``/``REGISTRY.inc`` call site whose literal name is not
# declared here — a typo'd counter otherwise splits silently into a
# real-but-frozen counter and a ghost twin nobody reads.  Keep the
# docstring list above and this set in sync (test_lint.py asserts it).
KNOWN_COUNTERS = frozenset({
    "search.hashes", "search.launches", "search.cancelled", "search.found",
    "search.blocking_syncs", "search.persistent_steps",
    "worker.mine_rpcs", "worker.found_rpcs", "worker.cancel_rpcs",
    "worker.results_sent", "worker.forward_retries",
    "coord.mine_rpcs", "coord.fanouts", "coord.late_results",
    "coord.worker_failures", "coord.reassigned_shards",
    "coord.stale_results_dropped",
    "cache.hit", "cache.miss", "cache.add", "cache.evict",
    "powlib.retries", "powlib.reconnects", "powlib.degraded",
    "powlib.retry_after",
    "sched.launches", "sched.admission_rejected",
    "sched.coalesced_requests", "sched.slots_preempted",
    "sched.fallback_searches", "sched.loop_failures",
    "sched.mixed_hash_launches",
    "rpc.handler_errors",
    "rpc.codec.negotiated_v2", "rpc.codec.fallback_v1",
    "coord.abandoned_resyncs",
    "compile_cache.errors", "compile_cache.read_errors",
    "compile_cache.write_errors", "compile_cache.keygen_errors",
    "telemetry.dropped_events", "telemetry.dumps",
    "obs.scrapes", "obs.scrape_failures",
    "slo.evaluations", "slo.breaches",
    "fleet.joins", "fleet.lease_expiries", "fleet.drains",
    "fleet.hedged_shards",
    "spans.dropped",
    "forensics.slow_captures",
    "forensics.fetches", "forensics.fetch_failures",
    "cluster.not_owner_redirects", "cluster.foreign_mines",
    "cluster.ring_serves",
    "cluster.reroutes", "cluster.failovers", "cluster.sibling_hedges",
    "repl.pushes", "repl.push_failures", "repl.installs",
    "repl.stale_drops", "repl.handoff_keys", "repl.antientropy_rounds",
    "health.leak_suspects",
    "soak.sweeps", "soak.phase_breaches",
    "obs.spool_rotations",
})

# Families minted from runtime values (f-string call sites): the
# literal prefix must match one of these.
KNOWN_COUNTER_PREFIXES = frozenset({
    "faults.injected.",
    "search.",  # backends/__init__.py count_exit: search.{cancelled,found}
    "sched.lane_launches.",  # sched/engine.py per-lane launch counters
})

# The declared histogram registry — the same rule checks every
# ``metrics.observe``/``metrics.time`` call site against these.
KNOWN_HISTOGRAMS = frozenset({
    "coord.mine_s.hit", "coord.mine_s.miss",
    "coord.first_result_s", "coord.cancel_propagation_s",
    "worker.solve_s", "worker.time_to_cancel_s",
    "search.launch_s", "search.poll_s",
    "powlib.mine_s",
    "sched.batch_occupancy", "sched.slot_wait_s",
    "rpc.frame.sent_bytes", "rpc.frame.recv_bytes",
    "obs.sweep_s",
    "fleet.heartbeat_rtt_s",
    "cluster.failover_s",
    "repl.push_lag_s", "repl.handoff_s",
    "load.lag_s",
})

# Per-method families (runtime/rpc.py mints one histogram per
# "Service.Method" seen on the wire).
KNOWN_HISTOGRAM_PREFIXES = frozenset({
    "rpc.client.call_s.",
    "rpc.server.dispatch_s.",
    "worker.solve_s.",  # per-hash-model solve latency (nodes/worker.py)
})

# The declared gauge registry — lint-gated like counters since the
# leak sentinels (runtime/health.py) made gauge NAMES load-bearing: a
# typo'd ``metrics.gauge("…")`` would split a climbing resource gauge
# away from the trend detector watching the declared name.
KNOWN_GAUGES = frozenset({
    "worker.active_searches", "worker.mine_queue_depth",
    "worker.forward_queue_depth",
    "search.hashes_per_s", "search.mesh_devices",
    "sched.active_slots", "sched.run_queue_depth",
    "fleet.live_workers",
    "proc.rss_bytes", "proc.open_fds", "proc.threads",
    "ring.spans_depth", "ring.flightrec_depth", "ring.repl_queue_depth",
})

# No gauge families are minted from runtime values today; the empty
# declaration keeps the lint context explicit (and greppable) anyway.
KNOWN_GAUGE_PREFIXES = frozenset()

# Log-bucket geometry: 4 buckets per octave (bounds grow by 2^0.25, so a
# bucket is at most ~19% wide) — fine enough for honest p95/p99
# estimates across the nine decades this registry spans (µs RPC
# dispatches to multi-minute compiles; byte to multi-MB frames) at a
# bounded, value-independent memory cost.
_BUCKETS_PER_OCTAVE = 4
_LOG_GROWTH = math.log(2.0) / _BUCKETS_PER_OCTAVE


class Histogram:
    """Log-bucketed distribution: count/sum/min/max plus percentile
    ESTIMATES (each reported percentile is the upper bound of its
    bucket, so estimates err high by at most one bucket width, ~19%).

    Exemplars (docs/FORENSICS.md): each bucket retains the LAST
    ``(trace_id, value, ts)`` observed with a trace id — the pointer
    from "p99 moved" to the one request that landed there, at a
    bounded (one tuple per touched bucket) memory cost.  Merged
    bucket-wise across nodes (obs/merge.py, freshest wins) and emitted
    as OpenMetrics exemplars by ``stats --prom --openmetrics``.

    Lock discipline: instances carry no lock of their own — the owning
    :class:`Metrics` registry serializes ``observe`` under its single
    registry lock, the same (cheap) critical section a counter
    increment pays.
    """

    __slots__ = ("count", "sum", "min", "max", "_buckets", "_zeros",
                 "_exemplars")

    def __init__(self) -> None:
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._buckets: Dict[int, int] = {}  # log-bucket index -> count
        self._zeros = 0  # non-positive samples (zero-latency clock ticks)
        # log-bucket index (None = zero bucket) -> (trace_id, value, ts)
        self._exemplars: Dict[Optional[int], Tuple[int, float, float]] = {}

    def observe(self, value: Number,
                trace_id: Optional[int] = None) -> None:
        v = float(value)
        self.count += 1
        self.sum += v
        if self.min is None or v < self.min:
            self.min = v
        if self.max is None or v > self.max:
            self.max = v
        if v > 0.0:
            idx = math.floor(math.log(v) / _LOG_GROWTH)
            self._buckets[idx] = self._buckets.get(idx, 0) + 1
        else:
            idx = None
            self._zeros += 1
        if trace_id:
            self._exemplars[idx] = (int(trace_id), v,
                                    round(time.time(), 6))

    @staticmethod
    def bound(idx: int) -> float:
        """Upper bound of log-bucket ``idx``."""
        return math.exp((idx + 1) * _LOG_GROWTH)

    def percentile(self, q: float) -> Optional[float]:
        """Estimated q-quantile (q in [0, 1]); None when empty."""
        if self.count == 0:
            return None
        rank = q * self.count
        cum = self._zeros
        if cum >= rank and self._zeros:
            return 0.0
        for idx in sorted(self._buckets):
            cum += self._buckets[idx]
            if cum >= rank:
                est = self.bound(idx)
                # the true sample lies inside the bucket; clamp the
                # bucket-bound estimate to the observed extremes
                return min(max(est, self.min or est), self.max or est)
        return self.max

    def to_dict(self) -> dict:
        """JSON-able snapshot; ``buckets`` is ``[[upper_bound, count],
        ...]`` in ascending bound order (non-cumulative — the Prometheus
        renderer in cli/stats.py accumulates).  ``exemplars`` rides only
        when some bucket holds one: ``[[upper_bound, trace_id, value,
        ts], ...]`` keyed by the same rounded bounds, so merge and
        rendering pair them with their buckets exactly."""
        buckets: List[Tuple[float, int]] = []
        if self._zeros:
            buckets.append((0.0, self._zeros))
        buckets.extend(
            (round(self.bound(i), 9), self._buckets[i])
            for i in sorted(self._buckets)
        )
        out = {
            "count": self.count,
            "sum": round(self.sum, 9),
            "min": self.min,
            "max": self.max,
            "p50": self.percentile(0.50),
            "p95": self.percentile(0.95),
            "p99": self.percentile(0.99),
            "buckets": [[b, c] for b, c in buckets],
        }
        if self._exemplars:
            out["exemplars"] = [
                [0.0 if i is None else round(self.bound(i), 9),
                 tid, v, ts]
                for i, (tid, v, ts) in sorted(
                    self._exemplars.items(),
                    key=lambda kv: (float("-inf") if kv[0] is None
                                    else kv[0]))
            ]
        return out


class _Timer:
    """Context manager returned by :meth:`Metrics.time` — observes the
    block's wall-clock duration (seconds) into the named histogram."""

    __slots__ = ("_metrics", "_name", "_t0")

    def __init__(self, metrics: "Metrics", name: str):
        self._metrics = metrics
        self._name = name
        self._t0 = 0.0

    def __enter__(self) -> "_Timer":
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc) -> None:
        self._metrics.observe(self._name, time.monotonic() - self._t0)


class Metrics:
    def __init__(self) -> None:
        self._counters: Dict[str, Number] = {}
        self._gauges: Dict[str, Number] = {}
        self._hists: Dict[str, Histogram] = {}
        self._lock = threading.Lock()
        self._start = time.monotonic()
        # exemplar capture switch (docs/FORENSICS.md): call sites pass
        # trace ids unconditionally; flipping this off drops them at
        # the registry so bench.py --forensics-overhead can measure
        # exemplars-on vs -off without touching the instrumented seams
        self.exemplars_enabled = True

    def inc(self, name: str, n: Number = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def gauge(self, name: str, value: Number) -> None:
        with self._lock:
            self._gauges[name] = value

    def observe(self, name: str, value: Number,
                trace_id: Optional[int] = None) -> None:
        """Add one sample to the named histogram (created on first
        touch, like counters — distpow-lint polices the names).
        ``trace_id`` (when the call site has a request in scope)
        retains the sample as its bucket's exemplar."""
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram()
            h.observe(value,
                      trace_id if self.exemplars_enabled else None)

    def time(self, name: str) -> _Timer:
        """``with metrics.time("x.y"): ...`` observes the block's
        duration in seconds into histogram ``x.y``."""
        return _Timer(self, name)

    def get(self, name: str) -> Number:
        with self._lock:
            return self._counters.get(name, self._gauges.get(name, 0))

    def get_histogram(self, name: str) -> Optional[dict]:
        with self._lock:
            h = self._hists.get(name)
            return h.to_dict() if h is not None else None

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "uptime_secs": round(time.monotonic() - self._start, 3),
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {
                    name: h.to_dict() for name, h in self._hists.items()
                },
            }

    def reset(self) -> None:
        """Testing hook."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()
            self._start = time.monotonic()


REGISTRY = Metrics()
