"""Process-wide metrics registry (no reference equivalent — the
reference's only observability is its tracing subsystem; SURVEY.md
section 5 "Metrics: no counters").

A deliberately tiny, dependency-free counter/gauge registry.  Every node
process has one ``REGISTRY``; hot paths increment named counters and the
node's ``Stats`` RPC ships a snapshot (see nodes/coordinator.py and
nodes/worker.py; ``python -m distpow_tpu.cli.stats`` prints it).

Counter names in use (machine-checked: ``KNOWN_COUNTERS`` below is the
declaration distpow-lint's ``metrics-registry`` rule verifies every
``metrics.inc("…")`` call site against — docs/LINT.md):

* ``search.hashes``        — candidates evaluated (all backends)
* ``search.launches``      — device dispatches
* ``search.cancelled``     — searches stopped by a cancel check
* ``search.found``         — searches that returned a secret
* ``worker.mine_rpcs`` / ``worker.found_rpcs`` / ``worker.cancel_rpcs``
* ``worker.results_sent``  — messages queued to the forwarder
* ``worker.forward_retries`` — result deliveries retried after a
  coordinator outage (nodes/worker.py start_forwarder)
* ``coord.mine_rpcs`` / ``coord.fanouts`` / ``coord.late_results``
* ``coord.worker_failures`` / ``coord.reassigned_shards``
* ``coord.stale_results_dropped`` — zombie-round results dropped by the
  Result handler's round tag (nodes/coordinator.py module docstring)
* ``cache.hit`` / ``cache.miss`` / ``cache.add`` / ``cache.evict``
* ``powlib.retries`` / ``powlib.reconnects`` / ``powlib.degraded``
  — client-side coordinator-outage recovery (nodes/powlib.py)
* ``rpc.handler_errors`` — handler exceptions returned to callers in
  the response frame (runtime/rpc.py _dispatch)
* ``compile_cache.errors`` (+ ``.read_errors`` / ``.write_errors`` /
  ``.keygen_errors``) — persistent XLA cache failures
  (runtime/compile_cache.py)
* ``faults.injected.<kind>`` — fault-injection plane activity
  (runtime/faults.py; kind in refuse/delay/truncate/duplicate/drop)
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Union

Number = Union[int, float]

# The declared counter registry.  distpow-lint's ``metrics-registry``
# rule parses these two literals (AST, no import) and flags any
# ``metrics.inc``/``REGISTRY.inc`` call site whose literal name is not
# declared here — a typo'd counter otherwise splits silently into a
# real-but-frozen counter and a ghost twin nobody reads.  Keep the
# docstring list above and this set in sync (test_lint.py asserts it).
KNOWN_COUNTERS = frozenset({
    "search.hashes", "search.launches", "search.cancelled", "search.found",
    "worker.mine_rpcs", "worker.found_rpcs", "worker.cancel_rpcs",
    "worker.results_sent", "worker.forward_retries",
    "coord.mine_rpcs", "coord.fanouts", "coord.late_results",
    "coord.worker_failures", "coord.reassigned_shards",
    "coord.stale_results_dropped",
    "cache.hit", "cache.miss", "cache.add", "cache.evict",
    "powlib.retries", "powlib.reconnects", "powlib.degraded",
    "rpc.handler_errors",
    "compile_cache.errors", "compile_cache.read_errors",
    "compile_cache.write_errors", "compile_cache.keygen_errors",
})

# Families minted from runtime values (f-string call sites): the
# literal prefix must match one of these.
KNOWN_COUNTER_PREFIXES = frozenset({
    "faults.injected.",
    "search.",  # backends/__init__.py count_exit: search.{cancelled,found}
})


class Metrics:
    def __init__(self) -> None:
        self._counters: Dict[str, Number] = {}
        self._gauges: Dict[str, Number] = {}
        self._lock = threading.Lock()
        self._start = time.time()

    def inc(self, name: str, n: Number = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def gauge(self, name: str, value: Number) -> None:
        with self._lock:
            self._gauges[name] = value

    def get(self, name: str) -> Number:
        with self._lock:
            return self._counters.get(name, self._gauges.get(name, 0))

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "uptime_secs": round(time.time() - self._start, 3),
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
            }

    def reset(self) -> None:
        """Testing hook."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._start = time.time()


REGISTRY = Metrics()
