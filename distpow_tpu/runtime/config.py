"""Per-node JSON configuration (SURVEY.md section 2 component 14).

Field names match the reference's config JSON exactly
(config/client_config.json, config/coordinator_config.json,
config/worker_config.json, config/tracing_server_config.json via
``ReadJSONConfig``, config.go:8-18) so existing reference config files
load unchanged.  TPU-specific extensions are additive with defaults:

* ``WorkerConfig.Backend``   — miner backend: ``jax`` (single device,
  default), ``jax-mesh`` (shard_map over all local devices), ``pallas``
  / ``pallas-mesh`` (the hand-written TPU kernels), ``python``
  (hashlib loop, the CPU-parity baseline), ``native`` (C++ miner), or
  ``auto`` (resolve from the hardware at boot — the kernels on TPU,
  mesh when multi-device; backends/__init__.py ``get_backend``).
* ``WorkerConfig.HashModel`` — any registry model
  (models/registry.py): ``md5`` (reference parity, default),
  ``sha256`` (north-star variant), ``sha1``, ``ripemd160``,
  ``sha512``, ``sha384``, ``sha3_256``, ``blake2b_256``, or
  ``sha256d`` (double SHA-256, Bitcoin's PoW digest).
* ``WorkerConfig.BatchSize`` — candidates per device launch.

Unknown JSON fields are ignored (forward compatibility); missing fields
take dataclass defaults.
"""

from __future__ import annotations

import base64
import dataclasses
import json
from dataclasses import dataclass, field
from typing import List, Optional, Type, TypeVar

T = TypeVar("T")


def _decode_secret(v) -> bytes:
    """Reference configs store TracerSecret as a base64-ish JSON string
    (Go unmarshals ``string`` -> ``[]byte`` via base64); accept str, list
    of ints, or empty."""
    if v is None or v == "":
        return b""
    if isinstance(v, str):
        try:
            return base64.b64decode(v)
        except ValueError:  # binascii.Error — not base64: raw-string secret
            return v.encode()
    return bytes(v)


@dataclass
class ClientConfig:
    ClientID: str = "client1"
    CoordAddr: str = ""
    TracerServerAddr: str = ""
    TracerSecret: bytes = b""
    ChCapacity: int = 10  # client.go:9
    # --- TPU-native extensions -------------------------------------------
    # Coordinator-outage resilience (nodes/powlib.py): a transport-level
    # Mine failure is retried with jittered exponential backoff and a
    # shared coordinator re-dial.  Each failed attempt consumes one unit
    # of MineRetries; a successful re-dial restores the full budget; an
    # exhausted budget delivers a terminal "degraded: ..." error result.
    MineRetries: int = 4
    MineBackoffS: float = 0.2
    MineBackoffMaxS: float = 2.0
    # Per-attempt bound on waiting for the Mine response.  0 = wait
    # forever (the default — a legitimate mine can run arbitrarily long,
    # so only chaos/ops configs that must detect silently-dropped frames
    # should set this).
    MineAttemptTimeoutS: float = 0.0
    # --- coordinator pool (distpow_tpu/cluster/, docs/CLUSTER.md) --------
    # Client-facing addresses of the WHOLE coordinator pool, in shard
    # order — the ring seeds.  Non-empty with >= 2 entries flips powlib
    # into cluster mode: consistent-hash owner routing, hedged sibling
    # retry on RETRY_AFTER, ring-guided failover.  Empty (default)
    # keeps the single-coordinator behavior byte-identical.
    CoordAddrs: List[str] = field(default_factory=list)
    # Deterministic fault-injection plan (runtime/faults.py); empty = no
    # injection.  Also reachable via $DISTPOW_FAULTS and --faults.
    FaultPlanFile: str = ""


@dataclass
class CoordinatorConfig:
    ClientAPIListenAddr: str = ""
    WorkerAPIListenAddr: str = ""
    Workers: List[str] = field(default_factory=list)
    TracerServerAddr: str = ""
    TracerSecret: bytes = b""
    # --- TPU-native extensions -------------------------------------------
    # Checkpoint/resume: JSONL journal for the dominance result cache; a
    # restarted coordinator resumes warm (the reference starts cold,
    # coordinator.go:105-108).  Empty = in-memory only.
    CacheFile: str = ""
    # Failure handling for worker RPC errors mid-protocol:
    #   "error"    — reference parity: the Mine RPC fails on any worker
    #                error, no retry (coordinator.go:196-198, 227-229).
    #   "reassign" — failure recovery: dead workers are detected (failed
    #                calls + liveness probes while waiting) and their
    #                search-space shard is reassigned to a live worker;
    #                the ack ledger drops expectations from the dead.
    FailurePolicy: str = "error"
    # Probe cadence (seconds) while blocked on worker results in
    # "reassign" mode.
    FailureProbeSecs: float = 1.0
    # Deterministic fault-injection plan (runtime/faults.py); empty = no
    # injection.  Also reachable via $DISTPOW_FAULTS and --faults.
    FaultPlanFile: str = ""
    # Flight-recorder directory (runtime/telemetry.py): periodic
    # append-only JSONL journal of recent annotated events plus
    # dump-on-fault snapshots land here.  Empty = memory-only ring.
    # Also reachable via $DISTPOW_TELEMETRY_DIR.
    TelemetryDir: str = ""
    # --- scheduler plane (distpow_tpu/sched/, docs/SCHEDULER.md) ---------
    # Admission control: maximum concurrently fanned-out miss rounds.
    # A Mine arriving beyond the bound is rejected with a typed
    # RETRY_AFTER reply (sched/admission.py) that powlib's backoff
    # machinery consumes as a server-paced, non-counting retry.
    # 0 = unbounded (reference-parity default).
    SchedMaxInflight: int = 0
    # Retry-after hint (seconds) carried by admission rejections.
    SchedRetryAfterS: float = 0.5
    # In-flight coalescing of identical (nonce, ntz) Mine requests into
    # one fan-out round with a multi-waiter reply (sched/coalesce.py).
    # On by default: it is a scheduling upgrade of the documented
    # per-key-mutex duplicate fix with identical trace shapes.
    SchedCoalesce: bool = True
    # --- elastic fleet (distpow_tpu/fleet/, docs/FLEET.md) ---------------
    # Lease TTL for Fleet.Register members: a worker whose heartbeats
    # stop for this long is retired from membership and its shards ride
    # the existing orphan-reassignment path.  Static config workers are
    # permanent leases and never expire.
    FleetLeaseTTLS: float = 10.0
    # Straggler hedging: while a round waits for its first result, a
    # shard whose heartbeat-lease owner has been silent for longer than
    # FleetHedgeMultiple x the fleet's median heartbeat interval gets a
    # duplicate Mine on the least-loaded live worker (first result
    # wins).  Only heartbeat leases can trip it, so static fleets are
    # unaffected.
    FleetHedge: bool = True
    FleetHedgeMultiple: float = 3.0
    # Bound on how long one Fleet.Drain call may wait for the leaving
    # worker's in-flight rounds to finish before releasing the lease
    # anyway.
    FleetDrainTimeoutS: float = 20.0
    # --- request forensics (runtime/spans.py, docs/FORENSICS.md) ---------
    # Slow-request auto-capture: a completed Mine miss slower than this
    # fixed budget (seconds) captures its span tree into the flight
    # recorder.  0 = arm the fixed-threshold trigger off.
    ForensicsSlowS: float = 0.0
    # Rolling-p99 exceedance arm: a miss slower than this multiple of
    # the rolling p99 over recent misses is captured even when the
    # fixed budget is generous.  0 = off.  Both arms off (the default)
    # disables the trigger entirely.
    ForensicsSlowP99X: float = 0.0
    # --- coordinator pool (distpow_tpu/cluster/, docs/CLUSTER.md) --------
    # Client-facing addresses of the whole pool in shard order (this
    # coordinator's own entry included) — the consistent-hash ring is a
    # pure function of this list, so every member and every client
    # computes the identical ring.  Empty (default) = single
    # coordinator, byte-identical to every earlier version.
    ClusterPeers: List[str] = field(default_factory=list)
    # This coordinator's index into ClusterPeers (its ring member id is
    # "c<index>").  Required (>= 0) when ClusterPeers is set.
    ClusterSelf: int = -1
    # --- cache replication / HA (cluster/replication.py) -----------------
    # Ring successors each dominance-cache entry is write-behind
    # replicated to (docs/CLUSTER.md "Replication & HA").  0 disables
    # the write-behind pushes and anti-entropy (warm handoff on ring
    # change still runs — it is an ownership-move, not a replica,
    # concern).  Only meaningful when ClusterPeers is set; single
    # coordinators never replicate.
    ClusterCacheReplicas: int = 1
    # Bound on the write-behind push queue: replication stays off the
    # Mine critical path, so a slower-than-traffic successor overflows
    # the queue and the overflow is DROPPED (counted in
    # repl.push_failures; anti-entropy heals it later).
    ClusterReplQueueDepth: int = 1024
    # Anti-entropy cadence (seconds): each sweep exchanges per-ring-
    # range digests with the successors and pushes only diverged
    # ranges.  0 = off.
    ClusterAntiEntropyS: float = 5.0
    # Bound on one warm shard handoff (seconds): a frozen recipient
    # costs at most this before the ring change proceeds without it
    # (anti-entropy backfills what the deadline cut off).
    ClusterHandoffDeadlineS: float = 5.0


@dataclass
class WorkerConfig:
    WorkerID: str = "worker1"
    ListenAddr: str = ""
    CoordAddr: str = ""
    TracerServerAddr: str = ""
    TracerSecret: bytes = b""
    # --- TPU-native extensions -------------------------------------------
    Backend: str = "jax"
    HashModel: str = "md5"
    BatchSize: int = 1 << 20
    MeshDevices: int = 0  # 0 = all local devices (jax-mesh backend)
    # Candidates one device dispatch should cover (sub-batches of
    # BatchSize run in an on-device loop).  Dispatch+result-fetch costs a
    # host<->device round trip, so this bounds both the amortization of
    # that cost and the cancellation latency (one launch).  0 = framework
    # default: 2^30 scaled down by the model's measured cost so one
    # launch is ~0.1-0.25 s of device work for EVERY hash model
    # (parallel/search.py scaled_launch_candidates).
    MaxLaunchCandidates: int = 0
    # Pre-compile the layout-keyed search programs for these nonce byte
    # lengths at boot (background thread), so the first Mine RPC is pure
    # dispatch.  The compiled programs are nonce-content-, difficulty- and
    # partition-independent (ops/search_step.py dynamic regime); only the
    # nonce *length* and chunk width key the compile.  Empty list = no
    # warmup.
    WarmupNonceLens: List[int] = field(default_factory=lambda: [2, 4])
    WarmupWidths: List[int] = field(default_factory=lambda: [0, 1, 2, 3, 4])
    # Checkpoint/resume: JSONL journal for the worker's dominance cache
    # (the reference's worker cache is memory-only, worker.go:98-101).
    # Empty = in-memory only.
    CacheFile: str = ""
    # Persistent XLA compilation cache directory: warmup compiles
    # (~10-12s for the full width set on TPU) are paid once per machine
    # instead of once per boot.  Empty = no persistent cache.
    CompilationCacheDir: str = ""
    # Device-hang watchdog (runtime/watchdog.py): if a device-driving
    # section (search launch/drain, a warmup compile) makes no progress
    # for this many seconds, the worker exits with a distinctive code
    # (EXIT_CODE 43) so the coordinator's FailurePolicy="reassign" can
    # redirect its shards — a hung accelerator dispatch otherwise leaves
    # a zombie that still answers liveness probes.  Must exceed the
    # worst-case single compile (20-60s cold), not one launch; 300 is a
    # conservative floor.  0 = disabled (reference parity).
    DeviceHangTimeoutS: float = 0.0
    # Multi-host mesh: when JaxCoordinator is set,
    # jax.distributed.initialize runs before the backend is built, so a
    # jax-mesh worker's shard_map spans every chip of a multi-host slice
    # (collectives over ICI within a host, DCN across).  The --jax-*
    # worker CLI flags override these.
    JaxCoordinator: str = ""
    JaxNumProcesses: int = 1
    JaxProcessId: int = 0
    # Serving loop for the single-device XLA backend (docs/SERVING.md):
    # "persistent" (default) drives the multi-segment on-device search
    # loop with a polling drain — the host never blocks inside a
    # per-launch result fetch; "serial" keeps the pre-persistent
    # launch/fetch/relaunch loop (the bench.py --serving-loop baseline
    # and the escape hatch).
    SearchLoop: str = "persistent"
    # Dev-only: run the pallas/pallas-mesh kernels in interpret mode so
    # kernel-backed workers can serve off-TPU (CI, the CPU mesh demo).
    # Orders of magnitude slower than the XLA step on CPU — never set in
    # production.
    PallasInterpret: bool = False
    # Deterministic fault-injection plan (runtime/faults.py); empty = no
    # injection.  Also reachable via $DISTPOW_FAULTS and --faults.
    FaultPlanFile: str = ""
    # Flight-recorder directory (runtime/telemetry.py): periodic
    # append-only JSONL journal of recent annotated events plus
    # dump-on-fault snapshots land here.  Empty = memory-only ring.
    # Also reachable via $DISTPOW_TELEMETRY_DIR.
    TelemetryDir: str = ""
    # --- scheduler plane (distpow_tpu/sched/, docs/SCHEDULER.md) ---------
    # "batching" multiplexes concurrent Mine searches onto shared
    # batched device launches through the continuous-batching engine
    # (sched/engine.py slot table over the ops/search_step.py batch
    # axis); "off" keeps one-launch-per-request reference behavior.
    # Searches the packed step cannot express (non-power-of-two
    # partitions, unsatisfiable difficulties) fall back to Backend.
    Scheduler: str = "off"
    # Slot-table width: maximum searches packed into one device launch
    # (also the preemption bound — requests beyond it wait in the run
    # queue under deterministic weighted-fair rotation).
    SchedMaxSlots: int = 8
    # Extra hash models the batching scheduler admits to its packed
    # step BEYOND HashModel: slots of different models then share one
    # mixed-hash launch (per-model sub-batches inside one compiled
    # program — docs/SERVING.md).  A Mine carrying a "hash_model" param
    # outside this set (or an XLA-serving-impractical model) routes to
    # the solo path instead.  Empty = HashModel only (pre-PR-6
    # behavior: any other hash forfeits batching).
    SchedHashModels: List[str] = field(default_factory=list)
    # Launch-lane override for the batching scheduler (sched/lanes.py):
    # "auto" ranks by hardware capability (pallas on TPU, mesh on any
    # multi-device host, xla otherwise); "pallas"/"mesh"/"xla" pins that
    # lane first (a pinned lane that fails to compile still demotes to
    # xla — the override is a ranking, not a correctness gate).
    SchedLane: str = "auto"
    # --- elastic fleet (distpow_tpu/fleet/, docs/FLEET.md) ---------------
    # Join the coordinator's fleet via Fleet.Register instead of (not in
    # addition to) being a static entry in the coordinator's Workers
    # list.  Off by default: static config workers must not
    # double-register.
    FleetRegister: bool = False
    # Heartbeat cadence in seconds; 0 = use the coordinator's hint from
    # the Register reply (lease TTL / 3).
    FleetHeartbeatS: float = 0.0
    # Budget for the boot-time MH/s self-calibration the capability
    # advertisement carries; 0 = skip (advertise unknown, which keeps
    # the fleet on the reference equal split).
    FleetCalibrationS: float = 0.2
    # Explicit advertised MH/s override (> 0 skips calibration):
    # deterministic weights for tests and benches, or an operator who
    # knows the hardware better than a 200 ms sample does.
    FleetMHS: float = 0.0
    # Bound on the graceful-drain wait at shutdown (mirrors the
    # coordinator-side FleetDrainTimeoutS).
    FleetDrainTimeoutS: float = 20.0


@dataclass
class TracingServerConfig:
    ServerBind: str = ""
    Secret: bytes = b""
    OutputFile: str = "trace_output.log"
    ShivizOutputFile: str = "shiviz_output.log"


def from_dict(cls: Type[T], data: dict) -> T:
    known = {f.name: f for f in dataclasses.fields(cls)}
    kwargs = {}
    for k, v in data.items():
        f = known.get(k)
        if f is None:
            continue
        if f.type in ("bytes",) or k in ("TracerSecret", "Secret"):
            v = _decode_secret(v)
        kwargs[k] = v
    return cls(**kwargs)


def read_json_config(path: str, cls: Type[T]) -> T:
    """ReadJSONConfig equivalent (config.go:8-18)."""
    with open(path) as f:
        return from_dict(cls, json.load(f))


def write_json_config(path: str, cfg) -> None:
    data = {}
    for f in dataclasses.fields(cfg):
        v = getattr(cfg, f.name)
        if isinstance(v, bytes):
            v = base64.b64encode(v).decode()
        data[f.name] = v
    with open(path, "w") as fp:
        json.dump(data, fp, indent="\t")
        fp.write("\n")
