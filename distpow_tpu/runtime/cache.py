"""Nonce-keyed dominance result cache with trace emission.

One implementation serving both the coordinator and worker roles.  The
reference duplicates this logic verbatim in both nodes
(coordinator.go:390-473 vs worker.go:423-506); per SURVEY.md section 7
item 2 we deliberately de-duplicate — semantics are identical:

* key: the raw nonce bytes (coordinator.go:479-481, worker.go:512-514);
  one entry per nonce.
* ``get`` hits iff the entry's difficulty >= the requested difficulty
  (coordinator.go:403); every lookup records ``CacheHit`` (with the stored
  secret) or ``CacheMiss``.
* ``add`` installs when no entry exists; replaces when the new entry has
  strictly more trailing zeros (coordinator.go:436) or equal zeros and a
  lexicographically greater secret (``bytes.Compare > 0``,
  coordinator.go:454) — the "dominance" order that keeps all replicas
  convergent regardless of result arrival order.  Replacement records
  ``CacheRemove`` then ``CacheAdd``; a dominated insert records nothing.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, Optional

from .actions import CacheAdd, CacheHit, CacheMiss, CacheRemove
from .tracing import Trace


@dataclass
class CacheEntry:
    num_trailing_zeros: int
    secret: bytes


class ResultCache:
    def __init__(self):
        self._entries: Dict[bytes, CacheEntry] = {}
        self._lock = threading.Lock()

    def get(
        self, nonce: bytes, num_trailing_zeros: int, trace: Optional[Trace]
    ) -> Optional[bytes]:
        nonce = bytes(nonce)
        with self._lock:
            entry = self._entries.get(nonce)
            if entry is not None and entry.num_trailing_zeros >= num_trailing_zeros:
                if trace:
                    trace.record_action(
                        CacheHit(
                            nonce=nonce,
                            num_trailing_zeros=num_trailing_zeros,
                            secret=entry.secret,
                        )
                    )
                return entry.secret
            if trace:
                trace.record_action(
                    CacheMiss(nonce=nonce, num_trailing_zeros=num_trailing_zeros)
                )
            return None

    def add(
        self,
        nonce: bytes,
        num_trailing_zeros: int,
        secret: bytes,
        trace: Optional[Trace],
    ) -> bool:
        """Install/replace per the dominance order; True if the cache changed."""
        nonce, secret = bytes(nonce), bytes(secret)
        with self._lock:
            entry = self._entries.get(nonce)
            if entry is None:
                self._entries[nonce] = CacheEntry(num_trailing_zeros, secret)
                if trace:
                    trace.record_action(
                        CacheAdd(
                            nonce=nonce,
                            num_trailing_zeros=num_trailing_zeros,
                            secret=secret,
                        )
                    )
                return True
            dominates = num_trailing_zeros > entry.num_trailing_zeros or (
                num_trailing_zeros == entry.num_trailing_zeros
                and secret > entry.secret
            )
            if not dominates:
                return False
            if trace:
                trace.record_action(
                    CacheRemove(
                        nonce=nonce,
                        num_trailing_zeros=entry.num_trailing_zeros,
                        secret=entry.secret,
                    )
                )
                trace.record_action(
                    CacheAdd(
                        nonce=nonce,
                        num_trailing_zeros=num_trailing_zeros,
                        secret=secret,
                    )
                )
            self._entries[nonce] = CacheEntry(num_trailing_zeros, secret)
            return True

    def peek(self, nonce: bytes) -> Optional[CacheEntry]:
        """Inspect without tracing (tests/diagnostics)."""
        with self._lock:
            return self._entries.get(bytes(nonce))

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)
