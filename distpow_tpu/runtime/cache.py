"""Nonce-keyed dominance result cache with trace emission.

One implementation serving both the coordinator and worker roles.  The
reference duplicates this logic verbatim in both nodes
(coordinator.go:390-473 vs worker.go:423-506); per SURVEY.md section 7
item 2 we deliberately de-duplicate — semantics are identical:

* key: the raw nonce bytes (coordinator.go:479-481, worker.go:512-514);
  one entry per nonce.
* ``get`` hits iff the entry's difficulty >= the requested difficulty
  (coordinator.go:403); every lookup records ``CacheHit`` (with the stored
  secret) or ``CacheMiss``.
* ``add`` installs when no entry exists; replaces when the new entry has
  strictly more trailing zeros (coordinator.go:436) or equal zeros and a
  lexicographically greater secret (``bytes.Compare > 0``,
  coordinator.go:454) — the "dominance" order that keeps all replicas
  convergent regardless of result arrival order.  Replacement records
  ``CacheRemove`` then ``CacheAdd``; a dominated insert records nothing.

Checkpoint/resume (a capability the reference lacks — its caches are
in-memory only and a restarted node starts cold, coordinator.go:105-108,
worker.go:98-101): pass ``persist_path`` and every accepted add is
appended to a JSONL journal; on construction the journal is replayed
through the same dominance order, so a restarted node resumes with the
converged cache state.  Replay tolerates a truncated final line (torn
write on crash) and compacts the journal when it has accumulated
superseded entries.  Compaction is crash-consistent: the replacement
journal is written to a temp file, fsynced, atomically renamed over
the original, and the directory entry is fsynced — a crash at ANY
point mid-compaction leaves either the complete old journal or the
complete new one, never a truncated mix (tests/test_runtime.py kills
compaction mid-write and asserts full replay).  This journal plus the
restart-epoch file is the coordinator pool's per-member durability
story (docs/CLUSTER.md "Replication & HA"): a restarted member replays
its journal, then anti-entropy backfills what it missed while dead.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from dataclasses import dataclass
from typing import Dict, Optional

from .actions import CacheAdd, CacheHit, CacheMiss, CacheRemove
from .metrics import REGISTRY as metrics
from .tracing import Trace

log = logging.getLogger("distpow.cache")


@dataclass
class CacheEntry:
    num_trailing_zeros: int
    secret: bytes


class ResultCache:
    def __init__(self, persist_path: Optional[str] = None):
        self._entries: Dict[bytes, CacheEntry] = {}
        self._lock = threading.Lock()
        self._journal = None
        self._replaying = False
        if persist_path:
            # journal replay must not count as protocol cache traffic —
            # a restart would otherwise report thousands of cache.add at
            # uptime ~0
            self._replaying = True
            try:
                lines, torn = self._replay(persist_path)
            finally:
                self._replaying = False
            if torn or lines > 2 * len(self._entries):
                # a torn tail MUST be rewritten before appending: a new
                # record appended after a partial line would merge into
                # one corrupt line and poison the next replay
                self._compact(persist_path)
            self._journal = open(persist_path, "a", encoding="ascii")

    # -- persistence -------------------------------------------------------
    def _replay(self, path: str):
        """Load journal lines through the dominance order; returns
        (lines_seen, torn) for the compaction decision."""
        if not os.path.exists(path):
            return 0, False
        lines, torn = 0, False
        with open(path, "r", encoding="ascii") as fh:
            for raw in fh:
                raw = raw.strip()
                if not raw:
                    continue
                lines += 1
                try:
                    rec = json.loads(raw)
                    self.add(
                        bytes.fromhex(rec["nonce"]),
                        int(rec["ntz"]),
                        bytes.fromhex(rec["secret"]),
                        trace=None,
                    )
                except (ValueError, KeyError, TypeError) as exc:
                    # torn tail write from a crash mid-append: stop here
                    log.warning("cache journal %s: stopping replay at "
                                "corrupt line %d (%s)", path, lines, exc)
                    torn = True
                    break
        log.info("cache journal %s: %d entries resumed from %d lines",
                 path, len(self._entries), lines)
        return lines, torn

    def _compact(self, path: str) -> None:
        """Rewrite the journal to the converged entry set, crash-
        consistently (module docstring): temp file + fsync + atomic
        rename + directory fsync.  A crash mid-write leaves the
        original journal untouched; a crash after the rename leaves
        the complete replacement — no interleaving can shorten the
        next replay."""
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="ascii") as fh:
            for nonce, e in self._entries.items():
                fh.write(json.dumps({
                    "nonce": nonce.hex(),
                    "ntz": e.num_trailing_zeros,
                    "secret": e.secret.hex(),
                }) + "\n")
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
        try:
            # the rename itself must reach disk, or a crash can resurrect
            # the superseded journal the replay decision was made against
            dfd = os.open(os.path.dirname(os.path.abspath(path)) or ".",
                          os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:  # platforms without directory fsync: best effort
            pass

    def _append(self, nonce: bytes, ntz: int, secret: bytes) -> None:
        if self._journal is None:
            return
        self._journal.write(json.dumps({
            "nonce": nonce.hex(), "ntz": ntz, "secret": secret.hex(),
        }) + "\n")
        self._journal.flush()

    def close(self) -> None:
        with self._lock:
            if self._journal is not None:
                self._journal.close()
                self._journal = None

    def get(
        self, nonce: bytes, num_trailing_zeros: int, trace: Optional[Trace]
    ) -> Optional[bytes]:
        nonce = bytes(nonce)
        with self._lock:
            entry = self._entries.get(nonce)
            if entry is not None and entry.num_trailing_zeros >= num_trailing_zeros:
                metrics.inc("cache.hit")
                if trace:
                    # distpow: ok no-blocking-under-lock -- trace emission
                    # order must match cache state order (the reference
                    # records from inside its cache mutex,
                    # coordinator.go:403); emitting after release lets a
                    # concurrent add interleave a contradictory event
                    trace.record_action(
                        CacheHit(
                            nonce=nonce,
                            num_trailing_zeros=num_trailing_zeros,
                            secret=entry.secret,
                        )
                    )
                return entry.secret
            metrics.inc("cache.miss")
            if trace:
                # distpow: ok no-blocking-under-lock -- same mutex-order
                # invariant as the hit path above
                trace.record_action(
                    CacheMiss(nonce=nonce, num_trailing_zeros=num_trailing_zeros)
                )
            return None

    def add(
        self,
        nonce: bytes,
        num_trailing_zeros: int,
        secret: bytes,
        trace: Optional[Trace],
    ) -> bool:
        """Install/replace per the dominance order; True if the cache changed."""
        nonce, secret = bytes(nonce), bytes(secret)
        with self._lock:
            entry = self._entries.get(nonce)
            if entry is None:
                if not self._replaying:
                    metrics.inc("cache.add")
                self._entries[nonce] = CacheEntry(num_trailing_zeros, secret)
                self._append(nonce, num_trailing_zeros, secret)
                if trace:
                    # distpow: ok no-blocking-under-lock -- CacheAdd must
                    # be emitted in cache-mutation order (reference emits
                    # inside the cache mutex, coordinator.go:436)
                    trace.record_action(
                        CacheAdd(
                            nonce=nonce,
                            num_trailing_zeros=num_trailing_zeros,
                            secret=secret,
                        )
                    )
                return True
            dominates = num_trailing_zeros > entry.num_trailing_zeros or (
                num_trailing_zeros == entry.num_trailing_zeros
                and secret > entry.secret
            )
            if not dominates:
                return False
            if trace:
                # one tracer-lock critical section: the Remove/Add pair is
                # adjacent in the reference trace (emitted from inside the
                # cache mutex, coordinator.go:436-454) and trace_check.py
                # asserts that adjacency — per-action locking would let a
                # concurrent handler interleave an event between them
                # distpow: ok no-blocking-under-lock -- the adjacency
                # invariant above requires emitting under the cache mutex
                trace.record_actions(
                    CacheRemove(
                        nonce=nonce,
                        num_trailing_zeros=entry.num_trailing_zeros,
                        secret=entry.secret,
                    ),
                    CacheAdd(
                        nonce=nonce,
                        num_trailing_zeros=num_trailing_zeros,
                        secret=secret,
                    ),
                )
            if not self._replaying:
                metrics.inc("cache.evict")
                metrics.inc("cache.add")
            self._entries[nonce] = CacheEntry(num_trailing_zeros, secret)
            self._append(nonce, num_trailing_zeros, secret)
            return True

    def peek(self, nonce: bytes) -> Optional[CacheEntry]:
        """Inspect without tracing (tests/diagnostics)."""
        with self._lock:
            return self._entries.get(bytes(nonce))

    def entries_snapshot(self):
        """Point-in-time ``[(nonce, ntz, secret), ...]`` copy — the
        replication plane's iteration surface (cluster/replication.py:
        handoff range computation, anti-entropy digests).  A snapshot,
        not a live view: the caller walks it outside the cache lock, so
        a concurrent add during a handoff costs at most one entry the
        anti-entropy loop heals later."""
        with self._lock:
            return [(n, e.num_trailing_zeros, e.secret)
                    for n, e in self._entries.items()]

    def satisfies(self, nonce: bytes, num_trailing_zeros: int) -> Optional[bytes]:
        """Unmetered, untraced dominance lookup for hot polling paths
        (the miner's between-batch cancel check) — ``get`` would swamp the
        cache.hit/cache.miss counters with polling noise and is reserved
        for protocol cache traffic."""
        with self._lock:
            entry = self._entries.get(bytes(nonce))
            if entry is not None and entry.num_trailing_zeros >= num_trailing_zeros:
                return entry.secret
            return None

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)
