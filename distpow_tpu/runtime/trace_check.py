"""Trace-log validator — the protocol's ordering oracle.

The reference system's de-facto acceptance test is its distributed trace
(SURVEY.md section 4): every protocol step records a typed action into a
causally-ordered log, and grading inspects the ordering invariants.  This
module makes that inspection executable: it parses the tracing server's
human log (``trace_output.log``) and ShiViz log and reports violations of
the invariants the reference protocol guarantees:

Per trace, per node (file order within one node's events is that node's
program order — each tracer ships events over one FIFO connection):

* client   — ``PowlibMiningBegin`` -> ``PowlibMine`` -> ... ->
  ``PowlibSuccess`` -> ``PowlibMiningComplete`` (powlib.go:106-176).
* coordinator — starts with ``CoordinatorMine``; then either
  ``CacheHit`` -> ``CoordinatorSuccess`` (the hit fast path,
  coordinator.go:150-166) or ``CacheMiss`` -> one
  ``CoordinatorWorkerMine`` per shard -> ... -> ``CoordinatorSuccess``
  last (coordinator.go:139-298); every ``CacheRemove`` is immediately
  followed by a ``CacheAdd`` for the same nonce (coordinator.go:436-454).
* worker   — per (identity, worker_byte): ``WorkerMine`` first; at most
  one ``WorkerResult``; ``WorkerCancel`` present and strictly after any
  ``WorkerResult`` — the finding worker blocks on its cancel channel so
  ``WorkerCancel`` is always its last action for the task
  (worker.go:357-396).

ShiViz log: per-host vector-clock components must increment by exactly 1
on each of that host's events, and no component may ever decrease —
violations mean the happens-before graph is corrupt.

Coordinator-pool traces (docs/CLUSTER.md): when a trace shows MULTIPLE
coordinator identities (client failover after a shard death re-issues
the same trace's mine at a sibling), the per-coordinator Success
requirement relaxes to at-least-one-member and per-shard WorkerResult
counts are bounded by the member count — the dead member's truncated
round fragment is evidence of the chaos, not a protocol bug.  Traces
with one coordinator identity keep the strict reference oracle
unchanged.

Usage: ``python -m distpow_tpu.cli.trace_check trace_output.log
[shiviz_output.log]`` — exits non-zero and prints each violation.
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass
from typing import Dict, List, Optional

ACTION_RE = re.compile(
    r"^\[(?P<id>[^\]]+)\] TraceID=(?P<tid>\d+) (?P<action>[A-Za-z]\w*)"
    r"(?: (?P<body>.*))?$"
)
TOKEN_RE = re.compile(
    r"^\[(?P<id>[^\]]+)\] (?P<kind>generate_token|receive_token)"
    r" TraceID=(?P<tid>\d+)$"
)

CLIENT_ACTIONS = {
    "PowlibMiningBegin", "PowlibMine", "PowlibMineWithToken",
    "PowlibSuccess", "PowlibMiningComplete",
}
COORD_ACTIONS = {
    "CoordinatorMine", "CoordinatorWorkerMine", "CoordinatorWorkerResult",
    "CoordinatorWorkerCancel", "CoordinatorSuccess",
}
WORKER_ACTIONS = {"WorkerMine", "WorkerResult", "WorkerCancel"}
CACHE_ACTIONS = {"CacheAdd", "CacheRemove", "CacheHit", "CacheMiss"}


@dataclass
class Event:
    line_no: int
    identity: str
    trace_id: int
    action: str
    body: dict


def _parse_body(raw: Optional[str]) -> dict:
    """Parse ``k=v, k=v`` bodies; values are best-effort literals."""
    body: dict = {}
    if not raw:
        return body
    # values may contain ", " inside list literals; split on ", " only at
    # top nesting level
    parts, depth, cur = [], 0, ""
    for ch in raw:
        if ch in "[{(":
            depth += 1
        elif ch in "]})":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append(cur)
            cur = ""
        else:
            cur += ch
    if cur.strip():
        parts.append(cur)
    for part in parts:
        k, _, v = part.strip().partition("=")
        v = v.strip()
        try:
            body[k] = json.loads(v)
        except (ValueError, json.JSONDecodeError):
            body[k] = v
    return body


def parse_trace_log(path: str) -> List[Event]:
    events: List[Event] = []
    with open(path) as fh:
        for i, line in enumerate(fh, 1):
            line = line.rstrip("\n")
            if not line or TOKEN_RE.match(line):
                continue
            m = ACTION_RE.match(line)
            if m is None:
                continue
            events.append(Event(
                line_no=i,
                identity=m.group("id"),
                trace_id=int(m.group("tid")),
                action=m.group("action"),
                body=_parse_body(m.group("body")),
            ))
    return events


def _check_client(trace_id: int, seq: List[Event], out: List[str]) -> None:
    names = [e.action for e in seq if e.action in CLIENT_ACTIONS]
    if not names:
        return
    if names[0] != "PowlibMiningBegin":
        out.append(f"trace {trace_id}: client sequence starts with "
                   f"{names[0]}, expected PowlibMiningBegin")
    want_after_begin = {"PowlibMine", "PowlibMineWithToken"}
    if len(names) > 1 and names[1] not in want_after_begin:
        out.append(f"trace {trace_id}: PowlibMiningBegin followed by "
                   f"{names[1]}, expected PowlibMine")
    if "PowlibMiningComplete" in names:
        if names[-1] != "PowlibMiningComplete":
            out.append(f"trace {trace_id}: PowlibMiningComplete is not the "
                       f"client's final action")
        if "PowlibSuccess" in names and (
            names.index("PowlibSuccess")
            > names.index("PowlibMiningComplete")
        ):
            out.append(f"trace {trace_id}: PowlibSuccess after "
                       f"PowlibMiningComplete")


def _check_coordinator(trace_id: int, seq: List[Event], out: List[str],
                       require_success: bool = True) -> None:
    names = [e.action for e in seq]
    coord = [n for n in names if n in COORD_ACTIONS or n in CACHE_ACTIONS]
    if not coord:
        return
    if coord[0] != "CoordinatorMine":
        out.append(f"trace {trace_id}: coordinator sequence starts with "
                   f"{coord[0]}, expected CoordinatorMine")
    if require_success and "CoordinatorSuccess" not in coord:
        out.append(f"trace {trace_id}: no CoordinatorSuccess")
    if "CacheHit" in coord and "CoordinatorWorkerMine" in coord:
        # a hit before any fan-out means the fan-out should not exist for
        # the SAME request; both can appear when the trace covers a
        # miss-then-dominated-repeat — only flag hit-THEN-mine order
        if coord.index("CacheHit") < coord.index("CoordinatorWorkerMine"):
            out.append(f"trace {trace_id}: fan-out after CacheHit")
    if "CoordinatorWorkerMine" in coord and "CacheMiss" in coord:
        if coord.index("CacheMiss") > coord.index("CoordinatorWorkerMine"):
            out.append(f"trace {trace_id}: fan-out before CacheMiss")
    # CacheRemove must be immediately followed by CacheAdd (same node)
    for i, e in enumerate(seq):
        if e.action == "CacheRemove":
            nxt = seq[i + 1] if i + 1 < len(seq) else None
            if nxt is None or nxt.action != "CacheAdd":
                out.append(
                    f"trace {trace_id}: CacheRemove (line {e.line_no}) not "
                    f"immediately followed by CacheAdd"
                )


def _check_worker(trace_id: int, identity: str, seq: List[Event],
                  out: List[str], max_results: int = 1) -> None:
    per_byte: Dict[object, List[Event]] = {}
    for e in seq:
        if e.action in WORKER_ACTIONS:
            per_byte.setdefault(e.body.get("WorkerByte"), []).append(e)
    for byte, evs in per_byte.items():
        names = [e.action for e in evs]
        if names and names[0] != "WorkerMine" and "WorkerMine" in names:
            out.append(f"trace {trace_id}: {identity} shard {byte}: "
                       f"{names[0]} before WorkerMine")
        if names.count("WorkerResult") > max_results:
            out.append(f"trace {trace_id}: {identity} shard {byte}: "
                       f"multiple WorkerResult")
        if "WorkerResult" in names:
            if "WorkerCancel" not in names:
                out.append(f"trace {trace_id}: {identity} shard {byte}: "
                           f"WorkerResult without a following WorkerCancel")
            elif names.index("WorkerCancel") < names.index("WorkerResult"):
                out.append(f"trace {trace_id}: {identity} shard {byte}: "
                           f"WorkerCancel before WorkerResult")
        if "WorkerCancel" in names and names[-1] != "WorkerCancel":
            out.append(f"trace {trace_id}: {identity} shard {byte}: "
                       f"WorkerCancel is not the final worker action")


def check_trace_log(path: str) -> List[str]:
    """Validate ordering invariants; returns a list of violations."""
    events = parse_trace_log(path)
    out: List[str] = []
    by_trace: Dict[int, List[Event]] = {}
    for e in events:
        by_trace.setdefault(e.trace_id, []).append(e)
    for trace_id, evs in sorted(by_trace.items()):
        by_node: Dict[str, List[Event]] = {}
        for e in evs:
            by_node.setdefault(e.identity, []).append(e)
        # coordinator-POOL traces (docs/CLUSTER.md): a client failover
        # can legitimately leave one round per pool member in ONE trace
        # — the member that died mid-round contributes a truncated
        # fragment (CoordinatorMine, fan-out, no Success) and each
        # fan-out may earn a shard one more WorkerResult.  The relaxed
        # invariants — Success on at least ONE member, per-shard
        # results bounded by the member count — apply ONLY when the
        # trace shows multiple coordinator identities; single-
        # coordinator traces keep the strict reference oracle.
        coord_ids = [i for i, seq in by_node.items()
                     if {e.action for e in seq} & COORD_ACTIONS]
        pool = len(coord_ids) > 1
        if pool and not any(
            "CoordinatorSuccess" in [e.action for e in by_node[i]]
            for i in coord_ids
        ):
            out.append(f"trace {trace_id}: no CoordinatorSuccess on any "
                       f"of the {len(coord_ids)} pool members")
        for identity, seq in by_node.items():
            kinds = {e.action for e in seq}
            if kinds & CLIENT_ACTIONS:
                _check_client(trace_id, seq, out)
            if kinds & COORD_ACTIONS:
                _check_coordinator(trace_id, seq, out,
                                   require_success=not pool)
            if kinds & WORKER_ACTIONS:
                _check_worker(trace_id, identity, seq, out,
                              max_results=max(1, len(coord_ids)))
    return out


def check_shiviz_log(path: str) -> List[str]:
    """Validate the vector-clock log: per-host components increment by 1
    on own events and never decrease anywhere."""
    out: List[str] = []
    last_seen: Dict[str, Dict[str, int]] = {}
    own: Dict[str, int] = {}
    with open(path) as fh:
        lines = fh.read().splitlines()
    i = 0
    # skip the parser-regex header (first non-empty lines up to a blank)
    while i < len(lines) and lines[i].strip():
        i += 1
    while i < len(lines):
        line = lines[i]
        i += 1
        if not line.strip():
            continue
        host, _, vc_raw = line.partition(" ")
        if not vc_raw.startswith("{"):
            continue
        try:
            vc = {k: int(v) for k, v in json.loads(vc_raw).items()}
        except (ValueError, json.JSONDecodeError):
            out.append(f"line {i}: unparsable vector clock")
            continue
        i += 1  # the description line
        mine = vc.get(host, 0)
        prev_own = own.get(host, 0)
        if mine == 1 and prev_own > 1:
            # identity restart: a fresh process reusing the name starts a
            # new epoch (the server appends across runs) — reset baseline
            last_seen.pop(host, None)
        elif mine != prev_own + 1:
            out.append(
                f"line {i - 1}: {host} clock component jumped "
                f"{prev_own} -> {mine} (expected +1)"
            )
        own[host] = mine
        prev = last_seen.get(host, {})
        for h, v in prev.items():
            if vc.get(h, 0) < v and h != host:
                out.append(
                    f"line {i - 1}: {host} clock component for {h} "
                    f"decreased {v} -> {vc.get(h, 0)}"
                )
        last_seen[host] = {**prev, **vc}
    return out
