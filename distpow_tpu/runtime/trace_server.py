"""Standalone tracing server (SURVEY.md section 2 component 13).

Collects trace events from every node's tracer over TCP and writes two
logs, mirroring the role of the DistributedClocks tracing server the
reference boots in cmd/tracing-server/main.go:10-17 with the output files
configured in config/tracing_server_config.json:4-5:

* ``OutputFile`` — human-readable, one line per event:
  ``[identity] TraceID=… Action field=value, …``
* ``ShivizOutputFile`` — ShiViz-compatible vector-clock log.  Parser
  regex (header written at the top of the file):
  ``(?<host>\\S*) (?<clock>{.*})\\n(?<event>.*)``

Wire protocol: framed JSON (4-byte big-endian length prefix), first frame
per connection is a hello carrying the shared secret (tracing.TCPSink);
connections with a wrong secret are dropped, mirroring the reference
tracer's shared-secret authentication (worker.go:117-121).
"""

from __future__ import annotations

import base64
import json
import socket
import threading
from typing import Optional

from .config import TracingServerConfig
from .rpc import _read_frame, split_bind_addr  # same framing as the RPC layer
from .tracing import format_trace_line

SHIVIZ_HEADER = "(?<host>\\S*) (?<clock>{.*})\\n(?<event>.*)\n\n"


def govector_vc_string(vc: dict) -> str:
    """Byte-compatible rendering of GoVector's ``vclock.ReturnVCString()``.

    The published GoVector clock-line shape (the format every
    DistributedClocks ShiViz log uses, and what the reference's tracing
    server emits through govec): ids sorted lexicographically,
    ``"id":count`` pairs joined by ``", "`` inside braces —
    ``{"alpha":2, "beta":1}``.  Still valid JSON, so every consumer
    (runtime/trace_check.py check_shiviz_log, ShiViz itself) parses it
    unchanged; emitting it byte-identically means a clock line from this
    server and one from a GoVector log diff clean
    (tests/test_trace_parity.py golden-shape case, VERDICT r3 item 6).

    Ids are JSON-escaped: for every id without quotes/backslashes —
    every real config — the bytes match GoVector exactly (which
    interpolates ids raw via fmt.Sprintf and would itself emit a broken
    line for such ids); for pathological ids we stay parseable instead
    of corrupting the log.
    """
    return "{" + ", ".join(
        f"{json.dumps(k)}:{int(vc[k])}" for k in sorted(vc)) + "}"


class TracingServer:
    """TCP trace collector writing human + ShiViz logs."""

    def __init__(self, config: TracingServerConfig):
        self.config = config
        self._listener: Optional[socket.socket] = None
        self._lock = threading.Lock()
        self._out = open(config.OutputFile, "a", buffering=1)
        self._shiviz = open(config.ShivizOutputFile, "a", buffering=1)
        if self._shiviz.tell() == 0:
            self._shiviz.write(SHIVIZ_HEADER)
        self._shutdown = threading.Event()

    # -- lifecycle ---------------------------------------------------------
    def open(self) -> str:
        host, port = split_bind_addr(self.config.ServerBind)
        self._listener = socket.create_server((host, port))
        bound = self._listener.getsockname()
        return f"{host or '127.0.0.1'}:{bound[1]}"

    def accept_forever(self) -> None:
        assert self._listener is not None, "open() first"
        while not self._shutdown.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            # distpow: ok unbounded-thread-spawn -- thread-per-node
            # connection like the RPC server's accept loop: the tracing
            # peers are the cluster's nodes, a small bounded set
            threading.Thread(
                target=self._conn_loop, args=(conn,), daemon=True
            ).start()

    def accept_in_background(self) -> None:
        threading.Thread(target=self.accept_forever, daemon=True).start()

    def close(self) -> None:
        self._shutdown.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        with self._lock:
            self._out.close()
            self._shiviz.close()

    # -- internals ---------------------------------------------------------
    def _conn_loop(self, conn: socket.socket) -> None:
        try:
            hello = _read_frame(conn)
            if hello.get("type") != "hello":
                return
            secret = base64.b64decode(hello.get("secret", ""))
            if secret != bytes(self.config.Secret):
                return  # drop unauthenticated tracers
            while True:
                self._handle_event(_read_frame(conn))
        except (ConnectionError, OSError, json.JSONDecodeError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _handle_event(self, event: dict) -> None:
        with self._lock:
            if self._out.closed:
                return
            self._out.write(format_trace_line(event) + "\n")
            vc = govector_vc_string(event.get("vc", {}))
            if event["type"] == "action":
                desc = f"{event['action']} {json.dumps(event['body'])}"
            else:
                desc = f"{event['type']} TraceID={event.get('trace_id')}"
            self._shiviz.write(f"{event['identity']} {vc}\n{desc}\n")
