"""Trace action records — the observable protocol vocabulary.

The reference's distributed tracing is its correctness oracle (SURVEY.md
section 4): every protocol state transition records a typed action into a
causally-ordered trace.  These dataclasses mirror the reference's action
structs one-to-one so trace parity can be checked field by field:

* powlib actions:      powlib/powlib.go:13-39
* coordinator actions: coordinator.go:32-60
* worker actions:      worker.go:25-50
* cache actions:       cache.go:3-24

``nonce``/``secret`` are byte sequences, ``num_trailing_zeros`` the nibble
difficulty, ``worker_byte`` the worker's partition index.

Field-name parity (VERDICT r2 item 3): Python attributes stay snake_case
(idiomatic), but ``to_fields()`` — the dict that reaches every trace log —
emits the Go structs' exported CamelCase names (``Nonce``,
``NumTrailingZeros``, ``WorkerByte``, ``Secret``) in declaration order, so
a recorded action line is field-for-field the shape the reference's
structs serialize to.  Byte slices are emitted as integer lists (Go's
``%v`` rendering of ``[]uint8``); note that Go's ``encoding/json`` would
base64 a ``[]byte`` — untestable here either way (no Go toolchain, the
DistributedClocks library is not vendored), so the readable form wins.
"""

from __future__ import annotations

from dataclasses import dataclass, fields
from typing import Dict, Tuple, Type


def _b(x) -> Tuple[int, ...]:
    return tuple(x) if x is not None else None


def _go_name(snake: str) -> str:
    """snake_case attribute -> the Go struct's exported CamelCase field."""
    return "".join(part.capitalize() for part in snake.split("_"))


@dataclass(frozen=True)
class Action:
    """Base trace action; ``name`` is the record type in logs."""

    @property
    def name(self) -> str:
        return type(self).__name__

    def to_fields(self) -> Dict:
        d = {}
        for f in fields(self):
            v = getattr(self, f.name)
            if isinstance(v, (bytes, bytearray)):
                v = list(v)
            d[_go_name(f.name)] = v
        return d


# --- powlib (client library) actions, powlib/powlib.go:13-39 ---------------

@dataclass(frozen=True)
class PowlibMiningBegin(Action):
    nonce: bytes
    num_trailing_zeros: int


@dataclass(frozen=True)
class PowlibMine(Action):
    nonce: bytes
    num_trailing_zeros: int


@dataclass(frozen=True)
class PowlibSuccess(Action):
    nonce: bytes
    num_trailing_zeros: int
    secret: bytes


@dataclass(frozen=True)
class PowlibMiningComplete(Action):
    nonce: bytes
    num_trailing_zeros: int
    secret: bytes


# --- coordinator actions, coordinator.go:32-60 ------------------------------

@dataclass(frozen=True)
class CoordinatorMine(Action):
    nonce: bytes
    num_trailing_zeros: int


@dataclass(frozen=True)
class CoordinatorWorkerMine(Action):
    nonce: bytes
    num_trailing_zeros: int
    worker_byte: int


@dataclass(frozen=True)
class CoordinatorWorkerResult(Action):
    nonce: bytes
    num_trailing_zeros: int
    worker_byte: int
    secret: bytes


@dataclass(frozen=True)
class CoordinatorWorkerCancel(Action):
    nonce: bytes
    num_trailing_zeros: int
    worker_byte: int


@dataclass(frozen=True)
class CoordinatorSuccess(Action):
    nonce: bytes
    num_trailing_zeros: int
    secret: bytes


# --- worker actions, worker.go:25-50 ----------------------------------------

@dataclass(frozen=True)
class WorkerMine(Action):
    nonce: bytes
    num_trailing_zeros: int
    worker_byte: int


@dataclass(frozen=True)
class WorkerResult(Action):
    nonce: bytes
    num_trailing_zeros: int
    worker_byte: int
    secret: bytes


@dataclass(frozen=True)
class WorkerCancel(Action):
    nonce: bytes
    num_trailing_zeros: int
    worker_byte: int


# --- cache actions, cache.go:3-24 -------------------------------------------

@dataclass(frozen=True)
class CacheAdd(Action):
    nonce: bytes
    num_trailing_zeros: int
    secret: bytes


@dataclass(frozen=True)
class CacheRemove(Action):
    nonce: bytes
    num_trailing_zeros: int
    secret: bytes


@dataclass(frozen=True)
class CacheHit(Action):
    nonce: bytes
    num_trailing_zeros: int
    secret: bytes


@dataclass(frozen=True)
class CacheMiss(Action):
    nonce: bytes
    num_trailing_zeros: int


ACTION_TYPES: Dict[str, Type[Action]] = {
    cls.__name__: cls
    for cls in (
        PowlibMiningBegin, PowlibMine, PowlibSuccess, PowlibMiningComplete,
        CoordinatorMine, CoordinatorWorkerMine, CoordinatorWorkerResult,
        CoordinatorWorkerCancel, CoordinatorSuccess,
        WorkerMine, WorkerResult, WorkerCancel,
        CacheAdd, CacheRemove, CacheHit, CacheMiss,
    )
}
