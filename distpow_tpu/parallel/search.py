"""Batched search drivers (single-device and factory-pluggable).

Replaces the reference worker's ``miner`` goroutine hot loop
(worker.go:258-401).  Differences dictated by the accelerator model
(SURVEY.md section 7 "hard parts"):

* The reference enumerates one candidate at a time and polls its cancel
  channel every iteration (worker.go:320-345).  A TPU kernel is
  uninterruptible, so the driver dispatches fixed-size batches and checks
  ``cancel_check`` between dispatches — cancellation latency is bounded by
  one batch.
* The chunk counter grows by appending carry bytes (worker.go:234-244),
  changing the message length.  The driver therefore runs one fused-step
  specialization per chunk *width* (0, 1, 2, ... bytes); within a width the
  space is a dense integer range and the kernel maps flat indices to
  candidates arithmetically.  Widths above 4 bytes (beyond uint32 lanes)
  fix the high chunk bytes per launch segment.
* Dispatches are pipelined (depth 2 by default) so the host prepares launch
  N+1 while the device crunches launch N; results are drained FIFO, which
  preserves reference enumeration order for the returned first match.

Batch-boundary note: a width-``w`` launch whose chunk range overruns
``256**w`` hashes candidates whose ``w``-byte little-endian chunk encoding
has a zero top byte.  Those are not in the reference's canonical
enumeration (its encodings are minimal) but they are perfectly valid
secrets under the puzzle contract — any solving secret is acceptable
(coordinator.go:202 takes whichever result arrives first) — so the driver
accepts them rather than paying a tail recompile per width.  The launch
multiplier widens the possible overrun to up to one full launch
(``launch_steps * chunks_per_step`` chunks past the segment end), but a
wrapped candidate can only win when no canonical candidate in the same
launch solves (canonical flat indices sort first), and every result is
re-verified host-side with hashlib before being returned.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Optional, Sequence, Tuple

from ..models import puzzle
from ..models.registry import HashModel, get_hash_model
from ..ops.search_step import (
    SENTINEL,
    cached_persistent_step,
    cached_search_step,
)
from ..runtime.metrics import REGISTRY as metrics
from ..runtime.spans import SPANS
from ..runtime.watchdog import FIRST_COMPILE_GRACE_S, WATCHDOG

DEFAULT_BATCH = 1 << 20
DEFAULT_PIPELINE_DEPTH = 2
# Candidates one dispatch should cover.  Every launch costs one
# host<->device round trip to fetch its first-hit index (tens of ms over a
# remote-tunnel TPU), so a dispatch must carry enough work to amortize it;
# steps run `launch_steps` sub-batches in an on-device fori_loop, keeping
# materialized buffers at the (much smaller) batch size.
DEFAULT_LAUNCH_CANDIDATES = 1 << 30


def scaled_launch_candidates(cost_ops: int, reference_ops: int = 584) -> int:
    """Per-dispatch candidate budget scaled by measured model cost.

    ``DEFAULT_LAUNCH_CANDIDATES`` (2^30) is tuned for md5: ~0.1-0.2 s
    of device work per launch at the measured ~10 GH/s, which bounds
    both cancellation latency (cancel_check runs between launches) and
    solve-time granularity (a hit surfaces when its launch drains).
    The slower hashes at the same budget stretch one launch to 2-4 s
    (measured: sha512/sha384/sha3 e2e solves quantized to ~2 s steps,
    docs/artifacts/r4c/e2e_models.json) — scaling by
    ``HashModel.cost_ops`` keeps launch wall-clock roughly
    model-independent.  The 2^24 floor preserves dispatch
    amortization; an explicitly configured ``MaxLaunchCandidates``
    bypasses this entirely.
    """
    return max(1 << 24,
               (DEFAULT_LAUNCH_CANDIDATES * reference_ops)
               // max(cost_ops, reference_ops))


def launch_steps_for(
    vw: int,
    sub_chunks: int,
    tbc: int,
    max_launch: int = DEFAULT_LAUNCH_CANDIDATES,
) -> int:
    """Launch multiplier for one width segment.

    Pure function of (width, sub-batch candidate count, budget) — boot
    warmup (backends._warm_layouts) and serving both call it, which is
    what keeps the warmed compile keys identical to the served ones.
    Everything is computed from ``sub_chunks * tbc`` (== effective_batch
    for every power-of-two partition) and the width's CANONICAL 256-
    thread-byte candidate volume, never from the partition's own chunk
    count — the resulting k is identical across partitions, so it is safe
    inside compile keys.  The segment cap bounds overscan on small widths
    (a sub-256 partition may overscan its segment by at most 256/tbc)."""
    if vw == 0 or sub_chunks < 1:
        return 1
    sub_cand = sub_chunks * tbc
    seg_chunks = (1 << 32) if vw >= 4 else 256 ** vw - 256 ** (vw - 1)
    k_seg = -(-(seg_chunks * 256) // sub_cand)
    k_rtt = max_launch // sub_cand
    return max(1, min(k_rtt, k_seg))


def effective_batch(batch_size: int) -> int:
    """Requested batch size normalized to a partition-independent value.

    The serving batch must be a pure function of the configured size —
    NOT of the request's thread-byte count — so that the layout-keyed
    programs warmed at boot (tbc=256) are byte-for-byte the programs
    every power-of-two partition dispatches.  Rounding down to a
    multiple of 256 makes ``chunks * tbc == effective_batch`` exact for
    every pow2 tbc <= 256."""
    return max(256, batch_size - batch_size % 256)

# A step factory maps (variable_width, extra_const_chunk, target_chunks,
# launch_steps) to (step_fn, chunks_per_step) where step_fn(chunk0)->uint32
# evaluates chunks_per_step * tb_count candidates starting at chunk0 and
# returns the flat index (chunk-major, thread-byte-minor, i.e. reference
# enumeration order, worker.go:318-319) of the first hit, or SENTINEL.
# ``launch_steps`` asks for that many target_chunks-sized sub-batches per
# dispatch; a factory may serve fewer — the driver always trusts the
# returned chunks_per_step.
StepFactory = Callable[[int, bytes, int, int], Tuple[Callable, int]]


@dataclass
class SearchResult:
    secret: bytes
    thread_byte: int
    chunk: bytes
    hashes_tried: int


class _RateMeter:
    """Process-wide live-throughput meter behind ``search.hashes_per_s``.

    One shared meter, not per-search state: concurrent searches all
    drain the same device, so the meaningful rate is candidates drained
    per wall-clock interval ACROSS searches (per-search EMAs writing one
    gauge would interleave garbage).  EMA over drain-to-drain windows
    smooths tunnel jitter; when the last active search exits the gauge
    drops to 0 — a stale full-throughput reading on an idle worker is
    the stuck-gauge class this plane polices elsewhere (review PR 3).
    """

    def __init__(self) -> None:
        import threading

        self._lock = threading.Lock()
        self._active = 0
        self._last_t: Optional[float] = None
        self._ema: Optional[float] = None

    def enter(self) -> None:
        with self._lock:
            self._active += 1

    def exit(self) -> None:
        with self._lock:
            self._active -= 1
            if self._active <= 0:
                self._last_t = self._ema = None
                metrics.gauge("search.hashes_per_s", 0)

    def note(self, n_cand: int) -> None:
        now = time.monotonic()
        with self._lock:
            prev, self._last_t = self._last_t, now
            if prev is None or now <= prev:
                return
            inst = n_cand / (now - prev)
            self._ema = inst if self._ema is None else \
                0.7 * self._ema + 0.3 * inst
            metrics.gauge("search.hashes_per_s", round(self._ema, 3))


_RATE_METER = _RateMeter()


# canonical home is the jax-free partition module (advisor r3: the
# native backend validates runs without importing the JAX compute path);
# re-exported here because the driver and both device backends import it
# from this module.
from .partition import contiguous_bounds  # noqa: E402,F401


def assemble_secret(
    chunk0: int, f: int, vw: int, extra: bytes, tb_lo: int, tbc: int
) -> Tuple[bytes, int]:
    """Host-side inverse of a launch's flat index: ``(secret, tb)``.

    One home for the candidate reconstruction both drivers share — the
    solo FIFO drain below and the continuous-batching scheduler's
    per-slot drain (sched/engine.py).  The width mask reproduces the
    launch-overrun aliasing documented in the module docstring: an
    overshot chunk int wraps into a zero-top-byte encoding, which is a
    valid (verified) secret even though it is off the canonical
    enumeration.
    """
    chunk_int = (chunk0 + f // tbc) & 0xFFFFFFFF
    tb = tb_lo + f % tbc
    chunk_bytes = (
        (chunk_int & (256 ** vw - 1)).to_bytes(vw, "little") if vw else b""
    ) + extra
    return bytes([tb]) + chunk_bytes, tb


def width_segments(width: int):
    """Yield (variable_width, chunk_lo, chunk_hi, extra_const_chunk) for one
    chunk width.  For width <= 4 the whole width is one dense uint32 range;
    beyond that the high bytes are fixed per segment."""
    if width == 0:
        yield 0, 0, 1, b""
        return
    if width <= 4:
        yield width, 256 ** (width - 1), 256 ** width, b""
        return
    hi_w = width - 4
    for hi in range(256 ** (hi_w - 1), 256 ** hi_w):
        yield 4, 0, 1 << 32, hi.to_bytes(hi_w, "little")


def _unsatisfiable_wait(model: HashModel, difficulty: int, cancel_check,
                        max_hashes) -> None:
    """Shared unsatisfiable-difficulty gate (both drivers).

    Unsatisfiable: the digest only has max_difficulty nibbles.  The
    reference would brute-force forever (worker.go:246-256 never
    reaches the threshold); we busy-wait on the cancel/budget gates
    instead of burning the device.  With NEITHER gate supplied the
    wait could never end — a trap for bare library callers (the
    worker always passes a cancel_check), so that combination
    raises instead (VERDICT r3 weak #4 / item 7).
    """
    if cancel_check is None and max_hashes is None:
        raise ValueError(
            f"difficulty {difficulty} exceeds {model.name}'s "
            f"{model.max_difficulty} digest nibbles (unsatisfiable) "
            f"and no cancel_check/max_hashes gate was supplied; the "
            f"search could never return"
        )
    # (no watchdog involvement: this loop never touches the device,
    # and beating here could mask a genuinely hung concurrent search
    # on the shared staleness clock)
    while True:
        if cancel_check is not None and cancel_check():
            return None
        if max_hashes is not None:
            return None
        time.sleep(0.01)


def default_step_factory(
    nonce: bytes,
    difficulty: int,
    tb_lo: int,
    tb_count: int,
    model: HashModel,
) -> StepFactory:
    """Single-device factory over the fused XLA search step."""

    def factory(vw: int, extra: bytes, target_chunks: int, launch_steps: int = 1):
        chunks = max(1, target_chunks) if vw else 1
        k = launch_steps if vw else 1
        step = cached_search_step(
            bytes(nonce), vw, difficulty, tb_lo, tb_count,
            chunks, model.name, extra, k,
        )
        return step, chunks * k

    return factory


def search(
    nonce: bytes,
    difficulty: int,
    thread_bytes: Sequence[int],
    *,
    model: Optional[HashModel] = None,
    batch_size: int = DEFAULT_BATCH,
    pipeline_depth: int = DEFAULT_PIPELINE_DEPTH,
    cancel_check: Optional[Callable[[], bool]] = None,
    max_hashes: Optional[int] = None,
    max_width: int = 8,
    step_factory: Optional[StepFactory] = None,
    launch_candidates: Optional[int] = None,
) -> Optional[SearchResult]:
    """Find the first (reference-enumeration-order) solving secret.

    Returns None if cancelled or ``max_hashes`` exhausted.  ``step_factory``
    overrides the launch builder — the mesh driver (parallel/mesh_search.py)
    and the Pallas kernel path (ops/md5_pallas.py) plug in here.
    ``launch_candidates`` defaults to the model's cost-scaled budget
    (``scaled_launch_candidates``) so a direct library caller gets the
    same ~0.1-0.25 s launch granularity a backend would.
    """
    model = model or get_hash_model("md5")
    if launch_candidates is None:
        launch_candidates = scaled_launch_candidates(model.cost_ops)
    nonce = bytes(nonce)
    tb_lo, tbc = contiguous_bounds(thread_bytes)
    if difficulty > model.max_difficulty:
        return _unsatisfiable_wait(model, difficulty, cancel_check,
                                   max_hashes)
    factory = step_factory or default_step_factory(
        nonce, difficulty, tb_lo, tbc, model
    )
    target_chunks = max(1, effective_batch(batch_size) // tbc)

    hashes = 0
    # FIFO of in-flight launches: (result, chunk0, var_width, extra, n_cand)
    inflight: deque = deque()

    def drain_one() -> Optional[SearchResult]:
        nonlocal hashes
        WATCHDOG.beat()  # about to block on a device result fetch
        res, chunk0, vw, extra, n_cand = inflight.popleft()
        hashes += n_cand
        metrics.inc("search.hashes", n_cand)
        # the sanctioned host sync: time blocked on the launch's result
        # fetch — the per-launch latency distribution (pipelined, so a
        # busy pipeline shows near-zero waits; a dry one shows the full
        # device+tunnel round trip).  Counted as a blocking sync: the
        # conversion is issued without readiness confirmed, which is
        # exactly what the persistent driver's polling drain avoids
        # (bench.py --serving-loop measures the two against each other)
        metrics.inc("search.blocking_syncs")
        fetch_ts = time.time()
        fetch_t0 = time.monotonic()
        f = int(res)
        fetch_s = time.monotonic() - fetch_t0
        metrics.observe("search.launch_s", fetch_s)
        if SPANS.enabled:
            # per-dispatch forensics segment: the trace id rides the
            # miner thread's binding (nodes/worker.py SPANS.bind), so a
            # request's launches line up under it on the stitched
            # timeline (docs/FORENSICS.md)
            SPANS.record("search.launch", fetch_ts, fetch_s,
                         n_cand=n_cand)
        _RATE_METER.note(n_cand)
        if f == SENTINEL:
            return None
        secret, tb = assemble_secret(chunk0, f, vw, extra, tb_lo, tbc)
        chunk_bytes = secret[1:]
        if not puzzle.check_secret(nonce, secret, difficulty, model.name):
            raise RuntimeError(
                f"kernel returned non-solving candidate tb={tb} "
                f"chunk={chunk_bytes.hex()} (kernel/oracle divergence)"
            )
        return SearchResult(
            secret=secret, thread_byte=tb, chunk=chunk_bytes, hashes_tried=hashes
        )

    def drain_all() -> Optional[SearchResult]:
        while inflight:
            found = drain_one()
            if found is not None:
                return found
        return None

    def flush_inflight_counts() -> None:
        """Account launches still in flight at an early exit WITHOUT
        draining them (the device completes them either way; fetching
        would add a round trip per launch).  Keeps search.hashes equal
        to dispatched work on every exit path — found, cancelled, or
        budget — while SearchResult.hashes_tried remains the DRAINED
        count (the enumeration-position bound at the find)."""
        nonlocal hashes
        while inflight:
            *_, n = inflight.popleft()
            hashes += n
            metrics.inc("search.hashes", n)

    # The active() window covers every dispatch and drain: if the device
    # hangs mid-search, beats stop and the watchdog (if the worker
    # enabled it — WorkerConfig.DeviceHangTimeoutS) converts the zombie
    # into a visible process death (runtime/watchdog.py).  The rate
    # meter brackets the same window: its refcount zeroes the
    # hashes_per_s gauge when the LAST concurrent search exits, on
    # every exit path (found / cancelled / budget / error).
    _RATE_METER.enter()
    try:
        with WATCHDOG.active():
            for width in range(0, max_width + 1):
                for vw, lo, hi, extra in width_segments(width):
                    WATCHDOG.beat()  # factory may compile (bounded gap)
                    k = launch_steps_for(vw, target_chunks, tbc,
                                         launch_candidates)
                    step, chunks_per_step = factory(vw, extra,
                                                    target_chunks, k)
                    chunk0 = lo
                    while chunk0 < hi:
                        # A launch's compiled span can overshoot the
                        # segment end (the chunk count is a compile-time
                        # shape; the tail launch is not re-compiled
                        # smaller).  Overshot chunk ints alias back into
                        # already-covered candidates via the width mask —
                        # harmless for first-hit order (an aliased hit
                        # implies an equal in-launch or already-scanned
                        # hit) — but they are NOT searched work: count
                        # only the in-segment candidates, or hashes_tried
                        # / search.hashes inflate by orders of magnitude
                        # on small partitions and max_hashes budgets
                        # misfire (found by the round-4 differential
                        # fuzz: a [240,241] partition reported 16.7M
                        # hashes for a 4.8k-candidate solve).
                        n_cand = min(chunks_per_step, hi - chunk0) * tbc
                        WATCHDOG.beat()
                        if cancel_check is not None and cancel_check():
                            flush_inflight_counts()
                            metrics.inc("search.cancelled")
                            return None
                        if max_hashes is not None and hashes >= max_hashes:
                            found = drain_all()
                            # drain_all stops at the first hit: launches
                            # still in flight behind it must be counted
                            # (search.hashes == dispatched work on every
                            # exit path, flush_inflight_counts)
                            flush_inflight_counts()
                            if found is not None:
                                metrics.inc("search.found")
                            return found
                        if chunk0 == lo:
                            # the segment's FIRST launch pays the compile
                            # when the layout cache is cold (an unwarmed
                            # width or model): one uninterruptible gap
                            # that can far exceed the hang timeout for
                            # the biggest graphs (sha512 unrolled:
                            # >22 min observed on the tunnel) — widen the
                            # window for just this launch so an armed
                            # watchdog does not kill a healthy worker
                            # mid-compile
                            with WATCHDOG.grace(FIRST_COMPILE_GRACE_S):
                                res = step(chunk0 & 0xFFFFFFFF)
                        else:
                            res = step(chunk0 & 0xFFFFFFFF)
                        metrics.inc("search.launches")
                        inflight.append((res, chunk0, vw, extra, n_cand))
                        chunk0 += chunks_per_step
                        if len(inflight) >= pipeline_depth:
                            found = drain_one()
                            if found is not None:
                                flush_inflight_counts()
                                metrics.inc("search.found")
                                return found
                    found = drain_all()
                    if found is not None:
                        flush_inflight_counts()
                        metrics.inc("search.found")
                        return found
        return None
    finally:
        _RATE_METER.exit()


# Host-side poll cadence while a launch result is not yet ready.  Short
# enough that drain latency adds negligibly to a launch's wall-clock
# (launches are 0.1-0.25 s of device work by budget), long enough that
# polling is not a busy spin over the tunnel.
DEFAULT_POLL_INTERVAL_S = 0.001


class StopFlag:
    """Host-writable device stop flag for the persistent loop
    (docs/SERVING.md flag protocol).

    The flag is a one-element uint32 device buffer passed to every
    persistent dispatch; the on-device while_loop reads it in its loop
    condition, so a dispatch carrying a set flag exits after one
    condition check instead of burning its full segment budget.  The
    host "writes" it by replacing the buffer (``set()`` updates the
    operand the NEXT dispatches bind — JAX buffers are immutable, so
    already-enqueued dispatches still run their remaining segments).
    Two call sites exercise the SET form today: backend warmup, which
    compiles the persistent programs against a set flag so compilation
    costs near-zero device work, and any dispatch a driver issues
    after observing a cancel — the solo driver never issues one (it
    stops dispatching the moment it observes the cancel), so there the
    flag is the invariant guard, not the cancel mechanism: cancel
    latency is bounded by stop-on-observe plus the ≤ ``pipeline_depth``
    already-in-flight dispatches running out in the background (each
    still early-exits on its own hit).  The buffer is created lazily
    and reused across dispatches, so the steady-state cost is zero
    transfers.
    """

    __slots__ = ("_operand", "_set")

    def __init__(self) -> None:
        self._operand = None
        self._set = False

    def set(self) -> None:
        self._set = True
        self._operand = None  # rebuilt hot with the new value

    def is_set(self) -> bool:
        return self._set

    def operand(self):
        if self._operand is None:
            import jax.numpy as jnp

            self._operand = jnp.uint32(1 if self._set else 0)
        return self._operand


def persistent_search(
    nonce: bytes,
    difficulty: int,
    thread_bytes: Sequence[int],
    *,
    model: Optional[HashModel] = None,
    batch_size: int = DEFAULT_BATCH,
    pipeline_depth: int = DEFAULT_PIPELINE_DEPTH,
    cancel_check: Optional[Callable[[], bool]] = None,
    max_hashes: Optional[int] = None,
    max_width: int = 8,
    launch_candidates: Optional[int] = None,
    poll_interval_s: float = DEFAULT_POLL_INTERVAL_S,
    step_builder: Optional[Callable] = None,
) -> Optional[SearchResult]:
    """Persistent-loop twin of :func:`search` — same contract, same
    first-hit enumeration order, byte-identical results (the golden
    parity suite, tests/test_serving_loop.py, asserts it).

    Three differences from the relaunch loop, all on the host side of
    the dispatch boundary (docs/SERVING.md):

    * each dispatch is a multi-segment on-device loop
      (``cached_persistent_step``) that early-exits on the first hit,
      so the per-dispatch candidate budget no longer trades hit
      latency against round-trip amortization;
    * the drain POLLS the in-flight head's readiness
      (``jax.Array.is_ready`` — a cheap flag query, not a result
      fetch) and only converts once ready, so the host never blocks
      inside a result fetch (``search.blocking_syncs`` stays flat;
      the waiting time is observable as ``search.poll_s``);
    * cancellation stops issuing dispatches the moment it is observed
      (and flips the :class:`StopFlag` future dispatches would carry —
      see its docstring for what actually exercises the set form):
      the host returns immediately, and the abandoned device work is
      bounded at the in-flight window (≤ ``pipeline_depth`` dispatches
      running out their segment budget in the background) without
      shrinking launches.

    ``step_builder`` is the launch-lane hook (sched/lanes.py
    ``persistent_step_builder``): called per width segment as
    ``step_builder(vw, extra, target_chunks, k)`` it may return
    ``(step, chunks_each, chunks_per_step)`` — a drop-in for the
    default single-device persistent step with the identical
    ``(chunk0, stop) -> uint32[2]`` contract and first-hit order over
    the same global candidate span — or None to keep the default for
    that width.  The mesh lane serves every dispatch across all local
    devices this way; enumeration order (and so results) stays
    byte-identical.
    """
    model = model or get_hash_model("md5")
    if launch_candidates is None:
        launch_candidates = scaled_launch_candidates(model.cost_ops)
    nonce = bytes(nonce)
    tb_lo, tbc = contiguous_bounds(thread_bytes)
    if difficulty > model.max_difficulty:
        return _unsatisfiable_wait(model, difficulty, cancel_check,
                                   max_hashes)
    target_chunks = max(1, effective_batch(batch_size) // tbc)
    stop = StopFlag()

    hashes = 0
    # FIFO of in-flight dispatches:
    # (res, chunk0, vw, extra, seg_chunks, chunks_each, is_pair)
    # where seg_chunks is the dispatch's IN-SEGMENT chunk span (the
    # overscan clip the serial driver documents at its n_cand line) and
    # chunks_each the chunk count of one on-device segment.
    inflight: deque = deque()

    def _fetch_pair(res):
        # the conversion site: only ever entered with res.is_ready()
        # confirmed, so this does not serialize the pipeline
        f = int(res[0])
        segs = int(res[1])
        return f, segs

    def drain_one() -> Tuple[Optional[SearchResult], bool]:
        """Poll the head to readiness, then convert.  Returns
        ``(found, cancelled)`` — polling honors cancel_check, so a
        cancel arriving mid-wait stops the search without blocking on
        the device."""
        nonlocal hashes
        res, chunk0, vw, extra, seg_chunks, chunks_each, is_pair = \
            inflight.popleft()
        poll_ts = time.time()
        poll_t0 = time.monotonic()
        waited = False
        # deliberately NO WATCHDOG.beat() inside the poll wait: a hung
        # device leaves is_ready() false forever, and beating here
        # would mask exactly the staleness the hang watchdog exists to
        # convert into a visible death — the poll wait accumulates
        # staleness like the serial driver's blocking fetch does
        while not res.is_ready():
            waited = True
            if cancel_check is not None and cancel_check():
                stop.set()
                # account the popped head like the rest of the flush:
                # dispatched work the device completes either way
                hashes += seg_chunks * tbc
                metrics.inc("search.hashes", seg_chunks * tbc)
                return None, True
            time.sleep(poll_interval_s)
        if waited:
            poll_s = time.monotonic() - poll_t0
            metrics.observe("search.poll_s", poll_s)
            if SPANS.enabled:
                # the persistent twin of the serial driver's
                # search.launch span (same thread-bound trace id)
                SPANS.record("search.poll", poll_ts, poll_s)
        if is_pair:
            f, segs = _fetch_pair(res)
            metrics.inc("search.persistent_steps", segs)
            n_cand = min(segs * chunks_each, seg_chunks) * tbc
        else:
            # width-0 probe: single 256-candidate launch, polled to
            # readiness above like every other dispatch — the
            # conversion cannot block
            f = int(res)
            n_cand = seg_chunks * tbc
        hashes += n_cand
        metrics.inc("search.hashes", n_cand)
        _RATE_METER.note(n_cand)
        if f == SENTINEL:
            return None, False
        secret, tb = assemble_secret(chunk0, f, vw, extra, tb_lo, tbc)
        if not puzzle.check_secret(nonce, secret, difficulty, model.name):
            raise RuntimeError(
                f"kernel returned non-solving candidate tb={tb} "
                f"chunk={secret[1:].hex()} (kernel/oracle divergence)"
            )
        return SearchResult(
            secret=secret, thread_byte=tb, chunk=secret[1:],
            hashes_tried=hashes,
        ), False

    def flush_inflight_counts() -> None:
        # same accounting contract as the serial driver: dispatched
        # work counts on every exit path without paying a fetch per
        # launch (launches carrying a set stop flag exit early on
        # device, so this is an upper bound there — documented in
        # docs/SERVING.md)
        nonlocal hashes
        while inflight:
            _res, _c0, _vw, _ex, seg_chunks, _ce, _p = inflight.popleft()
            hashes += seg_chunks * tbc
            metrics.inc("search.hashes", seg_chunks * tbc)

    def drain_all() -> Tuple[Optional[SearchResult], bool]:
        while inflight:
            found, cancelled = drain_one()
            if found is not None or cancelled:
                return found, cancelled
        return None, False

    _RATE_METER.enter()
    try:
        with WATCHDOG.active():
            for width in range(0, max_width + 1):
                for vw, lo, hi, extra in width_segments(width):
                    WATCHDOG.beat()  # step build may compile below
                    k = launch_steps_for(vw, target_chunks, tbc,
                                         launch_candidates)
                    if vw == 0:
                        step0 = cached_search_step(
                            nonce, 0, difficulty, tb_lo, tbc, 1,
                            model.name, extra, 1,
                        )
                        step, chunks_per_step, chunks_each = \
                            None, 1, 1
                    else:
                        plan = (step_builder(vw, extra, target_chunks, k)
                                if step_builder is not None else None)
                        if plan is not None:
                            step, chunks_each, chunks_per_step = plan
                        else:
                            step = cached_persistent_step(
                                nonce, vw, difficulty, tb_lo, tbc,
                                target_chunks, model.name, extra, k,
                            )
                            chunks_each = target_chunks
                            chunks_per_step = target_chunks * k
                    chunk0 = lo
                    first_launch = True
                    while chunk0 < hi:
                        seg_chunks = min(chunks_per_step, hi - chunk0)
                        WATCHDOG.beat()
                        if cancel_check is not None and cancel_check():
                            stop.set()
                            flush_inflight_counts()
                            metrics.inc("search.cancelled")
                            return None
                        if max_hashes is not None and hashes >= max_hashes:
                            found, cancelled = drain_all()
                            # drain_all stops at the first hit/cancel:
                            # dispatches still in flight behind it must
                            # count like every other exit path
                            flush_inflight_counts()
                            if cancelled:
                                metrics.inc("search.cancelled")
                                return None
                            if found is not None:
                                metrics.inc("search.found")
                            return found
                        c = chunk0 & 0xFFFFFFFF
                        if first_launch:
                            first_launch = False
                            # first dispatch of a segment may compile
                            # (same grace rationale as the serial
                            # driver's cold-layout launch)
                            with WATCHDOG.grace(FIRST_COMPILE_GRACE_S):
                                res = step0(c) if vw == 0 else \
                                    step(c, stop.operand())
                        else:
                            res = step0(c) if vw == 0 else \
                                step(c, stop.operand())
                        metrics.inc("search.launches")
                        inflight.append((res, chunk0, vw, extra,
                                         seg_chunks, chunks_each,
                                         vw != 0))
                        chunk0 += chunks_per_step
                        if len(inflight) >= pipeline_depth:
                            found, cancelled = drain_one()
                            if cancelled:
                                flush_inflight_counts()
                                metrics.inc("search.cancelled")
                                return None
                            if found is not None:
                                flush_inflight_counts()
                                metrics.inc("search.found")
                                return found
                    found, cancelled = drain_all()
                    if cancelled:
                        flush_inflight_counts()
                        metrics.inc("search.cancelled")
                        return None
                    if found is not None:
                        flush_inflight_counts()
                        metrics.inc("search.found")
                        return found
        return None
    finally:
        _RATE_METER.exit()
