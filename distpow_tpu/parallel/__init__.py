"""Parallel search: partition algebra (jax-free) + device search drivers.

``search``/``mesh_search`` import jax, so they are exposed lazily via
module ``__getattr__`` (PEP 562) — jax-free consumers (the native C++
backend, runtime, CLI parsers) can use the partition algebra without
pulling the JAX compute path into their import graph (advisor r3; same
pattern as models/__init__.py).
"""

from .partition import (  # noqa: F401
    contiguous_bounds,
    remainder_bits,
    split_thread_bytes,
    thread_bytes,
    worker_bits,
)

_LAZY = {
    "SearchResult": "search",
    "search": "search",
    "make_mesh": "mesh_search",
    "search_mesh": "mesh_search",
}

__all__ = [
    "contiguous_bounds", "remainder_bits", "split_thread_bytes",
    "thread_bytes", "worker_bits",
    "SearchResult", "search", "make_mesh", "search_mesh",
]


def __getattr__(name):
    if name in _LAZY:
        import importlib

        mod = importlib.import_module(f".{_LAZY[name]}", __name__)
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
