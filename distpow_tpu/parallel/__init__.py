from .partition import remainder_bits, split_thread_bytes, thread_bytes, worker_bits
from .search import SearchResult, search
from .mesh_search import make_mesh, search_mesh

__all__ = [
    "remainder_bits", "split_thread_bytes", "thread_bytes", "worker_bits",
    "SearchResult", "search", "make_mesh", "search_mesh",
]
