"""Parallel search: partition algebra (jax-free) + device search drivers.

``search``/``mesh_search`` import jax, so they are exposed lazily —
jax-free consumers (the native C++ backend, runtime, CLI parsers) can
use the partition algebra without pulling the JAX compute path into
their import graph (advisor r3; same rationale as models/__init__.py).

Laziness is implemented with *properties on the module's class*, not
PEP 562 ``__getattr__``: the public name ``search`` (the function,
README surface) collides with the ``parallel.search`` submodule, and
whenever anything imports the submodule first (``backends/__init__``
does ``from ..parallel.search import ...``), the import system writes
the MODULE into this package's ``__dict__`` — after which a module
``__getattr__`` never fires and ``from distpow_tpu.parallel import
search`` silently hands callers the module instead of the function
(caught by the round-4 verify drive).  A property is a data descriptor
on the type, so it wins over the instance ``__dict__`` regardless of
import order.
"""

import sys
import types

from .partition import (  # noqa: F401
    contiguous_bounds,
    remainder_bits,
    split_thread_bytes,
    thread_bytes,
    worker_bits,
)

__all__ = [
    "contiguous_bounds", "remainder_bits", "split_thread_bytes",
    "thread_bytes", "worker_bits",
    "SearchResult", "search", "persistent_search", "make_mesh",
    "search_mesh",
]


def _lazy(submodule: str, name: str) -> property:
    """Property pair: reads resolve ``name`` from ``submodule`` (the
    getter wins over instance ``__dict__`` by descriptor protocol);
    writes land in ``__dict__`` so the import system's own
    ``parallel.search = <module>`` setattr succeeds silently instead of
    raising ImportWarning on ``import distpow_tpu.parallel.search``
    (review r4).  Caveat (documented trap, no in-repo user):
    ``import distpow_tpu.parallel.search as s`` binds the FUNCTION —
    use ``from distpow_tpu.parallel.search import X`` for module
    internals, as the whole repo already does."""

    def _get(self):
        import importlib

        mod = importlib.import_module(f".{submodule}", __name__)
        return getattr(mod, name)

    def _set(self, value):
        self.__dict__[name] = value

    return property(_get, _set)


class _ParallelModule(types.ModuleType):
    SearchResult = _lazy("search", "SearchResult")
    search = _lazy("search", "search")
    persistent_search = _lazy("search", "persistent_search")
    make_mesh = _lazy("mesh_search", "make_mesh")
    search_mesh = _lazy("mesh_search", "search_mesh")


sys.modules[__name__].__class__ = _ParallelModule
